"""Pallas TPU kernels: grouped IVF-PQ scans over COMPACT codes.

The recon-cache kernel (:mod:`raft_tpu.ops.pq_group_scan_pallas`) streams
2 bytes/dim/row of bf16 reconstructions from HBM.  The reference instead
scans the bit-packed PQ codes against a shared-memory LUT
(``compute_similarity_kernel``, ivf_pq_search.cuh:611) — ~pq_dim
bytes/row.  This module is the TPU analogue, two kernels:

- **code scan** (:func:`grouped_code_scan`): each program DMAs its list's
  *packed codes* — an (Wi, cap) int32 block with candidates on the LANE
  axis (``Wi = ceil(pq_dim*pq_bits/32)`` words; the naive (cap, Wi)
  layout lane-pads Wi to 128 and forfeits the traffic win) — and the
  full (pq_dim, pq_len, book) codebook table, which is a few hundred KB
  and VMEM-resident for the whole grid.  Mosaic has no row gather, so
  per subspace the LUT lookup becomes a **transposed one-hot MXU
  contraction**: ``onehotT (book, cap) = (iota == code_j)``, then
  ``reconT_j = cbT_j (pq_len, book) @ onehotT`` decodes the whole
  subspace column block in one matmul.  Decoding to ``reconT (rot, cap)``
  and running ONE shared distance GEMM costs ~book/pq_len times fewer
  MACs than contracting a per-query LUT against the one-hots
  (pq_dim·G·book·cap vs pq_dim·pq_len·book·cap + G·rot·cap).  The bf16
  codebook cast makes the decoded values bit-identical to the bf16 recon
  cache, so distances match the recon kernel's.
- **int8 recon scan** (:func:`grouped_recon8_scan`): the second traffic
  lever — the recon cache quantized to int8 with a per-list scale
  (1 byte/dim/row); the kernel dequantizes in-register
  (``d = ||sub||² + rsq8 − 2·scale·(sub·q8)``).

Both reuse the recon kernel's one-hot query gather and top-kt
extraction; an opt-in **packed-key extraction** (:func:`_extract_topk_packed`)
halves the cross-lane reduces per pass by packing (distance bits | column)
into one int32 key — valid for L2 (d ≥ 0 makes the f32 bit pattern
order-isomorphic to int order); value truncation is ≤ ceil(log2 cap)
mantissa bits (~2⁻¹³ relative at cap 1024), far under PQ quantization
noise, and the exact-refine pass recomputes distances anyway.

Codes must not straddle int32 words for the in-register unpack to be one
shift+mask: gated to ``32 % pq_bits == 0`` → pq_bits ∈ {4, 8} (the
reference's default and its half-width option).  Other widths fall back
to the recon / XLA LUT paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.neighbors.grouped import GROUP
from raft_tpu.ops import vmem_budget as vb
from raft_tpu.ops.pq_group_scan_pallas import (_KT_MAX, _KT_UNROLL,
                                               _extract_topk,
                                               _fused_step,
                                               _gather_queries,
                                               _gather_queries_masked,
                                               _scratch_shapes,
                                               _unpack_admission)
from raft_tpu.ops.pq_group_scan_pallas import _ACC_WORST  # noqa: F401 (re-export)

_VMEM_BUDGET = 10 << 20
# merge-side budget of the fused codes kernel: accumulator + staging
# ring + merge transients, charged NEXT TO the streaming budget above
# (raised from the round-7 2 MiB accumulator cap — the windowed merge
# spends staging VMEM to buy back per-step merge passes)
_FUSED_MERGE_BUDGET = 4 << 20


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def code_lane_words(pq_dim: int, pq_bits: int) -> int:
    """int32 words per row in the lane-major packed-code layout."""
    return -(-(-(-pq_dim * pq_bits // 8)) // 4)


@jax.jit
def pack_code_lanes(list_codes: jax.Array) -> jax.Array:
    """(n_lists, cap, W) uint8 packed codes -> (n_lists, Wi, cap) int32.

    Byte k of a row lands in word ``k // 4`` at bit ``8*(k % 4)`` —
    LSB-first, so the bit stream is unchanged and subspace j still
    starts at bit ``j*pq_bits``.  Candidates move to the LANE axis: the
    (cap, Wi) orientation would lane-pad Wi (16 words at bench shape) to
    128 — an 8x HBM blowup that would erase the codes path's entire
    traffic advantage.
    """
    L, cap, W = list_codes.shape
    Wi = -(-W // 4)
    b = jnp.pad(list_codes, ((0, 0), (0, 0), (0, Wi * 4 - W)))
    b = b.astype(jnp.int32).reshape(L, cap, Wi, 4)
    shifts = (8 * jnp.arange(4, dtype=jnp.int32))[None, None, None, :]
    words = jnp.sum(jax.lax.shift_left(b, shifts), axis=-1)
    return jnp.transpose(words, (0, 2, 1))


def pack_row_lanes(codes: jax.Array) -> jax.Array:
    """(n, W) uint8 packed code rows -> (n, Wi) int32 lane words — the
    row-wise twin of :func:`pack_code_lanes`, used by the extend fast
    path to scatter-append into the lane-major cache without re-packing
    the whole index."""
    n, W = codes.shape
    Wi = -(-W // 4)
    b = jnp.pad(codes, ((0, 0), (0, Wi * 4 - W)))
    b = b.astype(jnp.int32).reshape(n, Wi, 4)
    shifts = (8 * jnp.arange(4, dtype=jnp.int32))[None, None, :]
    return jnp.sum(jax.lax.shift_left(b, shifts), axis=-1)


def _decode_reconT(codes_ref, cb_ref, pq_dim, pq_bits, rot_pad, cap):
    """In-register decode of one list's codes to (rot_pad, cap) bf16 —
    the transposed recon block.  Python-unrolled over subspaces: the
    word/shift offsets are static, and each step is one VPU shift+mask
    plus one (pq_len, book) x (book, cap) MXU matmul.  The bf16 cast of
    the codebook reproduces the bf16 recon cache bit-for-bit."""
    mask = (1 << pq_bits) - 1
    book = cb_ref.shape[2]
    pq_len = cb_ref.shape[1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (book, cap), 0)
    parts = []
    for j in range(pq_dim):
        bitpos = j * pq_bits
        w, sh = bitpos // 32, bitpos % 32
        word = codes_ref[0, w:w + 1, :]                  # (1, cap) int32
        # arithmetic >> then & mask == logical shift (sh + pq_bits <= 32)
        cj = (word >> sh) & mask if sh else word & mask
        onehotT = (rows == cj).astype(jnp.bfloat16)      # (book, cap)
        cbT_j = cb_ref[j].astype(jnp.bfloat16)           # (pq_len, book)
        rT = jax.lax.dot_general(cbT_j, onehotT,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        parts.append(rT.astype(jnp.bfloat16))            # (pq_len, cap)
    rot = pq_dim * pq_len
    if rot_pad > rot:
        parts.append(jnp.zeros((rot_pad - rot, cap), jnp.bfloat16))
    return jnp.concatenate(parts, axis=0)                # (rot_pad, cap)


def _extract_topk_packed(d, ids_row, vals_ref, ids_out_ref, vscratch,
                         pscratch, kt, cap_bits, adm=None):
    """Packed-key top-kt: ONE cross-lane reduce per selection pass.

    L2 distances are >= 0, so their f32 bit patterns order like ints;
    ``key = (bits(d) & ~col_mask) | col`` makes each pass a single int
    min-reduce with a built-in lowest-column tie-break (vs the standard
    extraction's max + argmin + id reduces).  Values lose the low
    ``cap_bits`` mantissa bits; columns decode exactly, and the
    column -> global-id mapping runs once per selected slot after the
    selection loop.  Sentinel/exhausted slots surface as INT32_MAX keys
    and are emitted as +inf values (the shared caller contract)."""
    cap = d.shape[1]
    col_mask = (1 << cap_bits) - 1
    inf_bits = jnp.int32(0x7F800000)
    int_max = jnp.int32(2**31 - 1)
    col = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    invalid = (ids_row < 0)[None, :]
    if adm is not None:
        # admission folds through the same INT32_MAX key sentinel as a
        # tombstone — rejected before any selection pass
        invalid = invalid | (adm == 0)
    bits = jax.lax.bitcast_convert_type(d, jnp.int32)
    key = jnp.where(invalid, int_max, (bits & ~col_mask) | col)
    ids_f = ids_row.astype(jnp.float32)

    picked = []
    for _ in range(kt):
        m = jnp.min(key, axis=1)                         # (G,) int32
        key = jnp.where(key == m[:, None], int_max, key)
        picked.append(m)
    for j, m in enumerate(picked):
        vj = jax.lax.bitcast_convert_type(m & ~col_mask, jnp.float32)
        vj = jnp.where(m >= inf_bits, jnp.inf, vj)
        sel = col == (m & col_mask)[:, None]
        gid = jnp.max(jnp.where(sel, ids_f[None, :], -jnp.inf), axis=1)
        vscratch[:, j] = vj
        pscratch[:, j] = gid.astype(jnp.int32)
    vals_ref[0] = vscratch[:, :]
    ids_out_ref[0] = pscratch[:, :]


def _extract(d, ids_ref, vals_ref, ids_out_ref, vscratch, pscratch, kt,
             packed, cap_bits, adm=None):
    ids_row = ids_ref[0, 0]                              # (cap,) int32
    if packed:
        _extract_topk_packed(d, ids_row, vals_ref, ids_out_ref, vscratch,
                             pscratch, kt, cap_bits, adm=adm)
    else:
        _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch,
                      pscratch, kt, adm=adm)


def _kernel_codes(gl_ref, slot_ref, qrot_ref, cf_ref, codes_ref, cb_ref,
                  rsq_ref, ids_ref, *rest, kt, n_probes, P, pq_dim,
                  pq_bits, packed, cap_bits, has_adm=False):
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    vals_ref, ids_out_ref, vscratch, pscratch = rest
    qv = _gather_queries(slot_ref, qrot_ref, n_probes, P)
    sub = qv - cf_ref[0, 0][None, :]                     # (G, rot_pad) f32
    sub_sq = jnp.sum(sub * sub, axis=1)                  # (G,)
    cap = codes_ref.shape[2]
    reconT = _decode_reconT(codes_ref, cb_ref, pq_dim, pq_bits,
                            qrot_ref.shape[1], cap)      # (rot_pad, cap)
    ip = jax.lax.dot_general(sub.astype(jnp.bfloat16), reconT,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = sub_sq[:, None] + rsq_ref[0, 0][None, :] - 2.0 * ip
    d = jnp.maximum(d, 0.0)
    adm = _unpack_admission(adm_ref, cap) if has_adm else None
    _extract(d, ids_ref, vals_ref, ids_out_ref, vscratch, pscratch, kt,
             packed, cap_bits, adm=adm)


def _kernel_recon8(gl_ref, slot_ref, qrot_ref, cf_ref, data_ref, scale_ref,
                   rsq_ref, ids_ref, *rest, kt, n_probes, P, packed,
                   cap_bits, has_adm=False):
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    vals_ref, ids_out_ref, vscratch, pscratch = rest
    qv = _gather_queries(slot_ref, qrot_ref, n_probes, P)
    sub = qv - cf_ref[0, 0][None, :]                     # (G, rot_pad) f32
    sub_sq = jnp.sum(sub * sub, axis=1)                  # (G,)
    data = data_ref[0].astype(jnp.bfloat16)              # (cap, rot_pad)
    scale = scale_ref[0, 0, 0]                           # f32 scalar
    ip = jax.lax.dot_general(sub.astype(jnp.bfloat16), data,
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = sub_sq[:, None] + rsq_ref[0, 0][None, :] - 2.0 * scale * ip
    d = jnp.maximum(d, 0.0)
    adm = _unpack_admission(adm_ref, d.shape[1]) if has_adm else None
    _extract(d, ids_ref, vals_ref, ids_out_ref, vscratch, pscratch, kt,
             packed, cap_bits, adm=adm)


def _kernel_codes_fused(gl_ref, slot_ref, qrot_ref, cf_ref, codes_ref,
                        cb_ref, rsq_ref, ids_ref, *rest, kt, k, n_probes,
                        P, pq_dim, pq_bits, n_groups, merge_window,
                        has_adm=False):
    """Fused compact-code scan: the ``_kernel_codes`` decode + distance
    block feeding the in-kernel per-query accumulator
    (pq_group_scan_pallas._fused_step — per-step merge at W=1, staged
    ring + windowed merge at W>1) instead of per-pair output rows —
    candidates never reach HBM; the final (k, nq_pad) answers flush
    once on the last grid step."""
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    vals_ref, ids_out_ref, acc_v, acc_i, *stg = rest
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _init():
        acc_v[:] = jnp.full(acc_v.shape, _ACC_WORST, jnp.float32)
        acc_i[:] = jnp.full(acc_i.shape, -1.0, jnp.float32)
        if merge_window > 1:
            stg[0][:] = jnp.full(stg[0].shape, _ACC_WORST, jnp.float32)
            stg[1][:] = jnp.full(stg[1].shape, -1.0, jnp.float32)

    qv, oh = _gather_queries_masked(slot_ref, qrot_ref, n_probes, P)
    sub = qv - cf_ref[0, 0][None, :]                     # (G, rot_pad) f32
    sub_sq = jnp.sum(sub * sub, axis=1)                  # (G,)
    cap = codes_ref.shape[2]
    reconT = _decode_reconT(codes_ref, cb_ref, pq_dim, pq_bits,
                            qrot_ref.shape[1], cap)      # (rot_pad, cap)
    ip = jax.lax.dot_general(sub.astype(jnp.bfloat16), reconT,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = sub_sq[:, None] + rsq_ref[0, 0][None, :] - 2.0 * ip
    d = jnp.maximum(d, 0.0)
    adm = _unpack_admission(adm_ref, cap) if has_adm else None
    _fused_step(g, oh, d, ids_ref[0, 0], acc_v, acc_i, stg, kt=kt,
                merge_window=merge_window, n_groups=n_groups, adm=adm)

    @pl.when(g == n_groups - 1)
    def _flush():
        vals_ref[:] = acc_v[:]
        ids_out_ref[:] = acc_i[:].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("kt", "k", "n_probes",
                                             "pq_bits", "interpret",
                                             "merge_window"))
def grouped_code_scan_fused(group_list, slot_pairs, qrot, centers_f32,
                            codes_lanes, codebooks, rsq, list_indices, kt,
                            k, n_probes, pq_bits, interpret=False,
                            merge_window=1, adm_words=None):
    """Fused compact-code scan with IN-KERNEL per-query top-k.

    Inputs as :func:`grouped_code_scan`; output contract as
    ``pq_group_scan_pallas.grouped_l2_scan_fused`` — the batch's final
    ``(vals (k, nq_pad) f32, ids (k, nq_pad) int32)``, ascending per
    column, exhausted ranks at the finite ``_ACC_WORST`` sentinel.
    ``adm_words`` (n_groups, GROUP, ceil(cap/32)) int32 streams packed
    per-(slot, candidate) admission bits (filtered search).
    """
    n_groups = group_list.shape[0]
    nq, rot = qrot.shape
    _, _, cap = codes_lanes.shape
    pq_dim, book, pq_len = codebooks.shape
    Wi = codes_lanes.shape[1]
    P = nq * n_probes
    rot_pad = _round_up(rot, 128)

    nq_pad = _round_up(nq + 1, 128)
    qrot_pad = jnp.zeros((nq_pad, rot_pad), jnp.float32)
    qrot_pad = qrot_pad.at[:nq, :rot].set(qrot.astype(jnp.float32))
    cf_pad = _pad_lanes(centers_f32, rot_pad)
    cbT = jnp.swapaxes(codebooks.astype(jnp.float32), 1, 2)

    has_adm = adm_words is not None
    in_specs = [
        pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
        pl.BlockSpec((nq_pad, rot_pad), lambda g, gl: (0, 0)),
        pl.BlockSpec((1, 1, rot_pad), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, Wi, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((pq_dim, pq_len, book), lambda g, gl: (0, 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
    ]
    inputs = [group_list, slot_pairs[:, None, :], qrot_pad,
              cf_pad[:, None, :], codes_lanes, cbT, rsq[:, None, :],
              list_indices[:, None, :]]
    if has_adm:
        wc = adm_words.shape[2]
        in_specs.append(pl.BlockSpec((1, GROUP, wc),
                                     lambda g, gl: (g, 0, 0)))
        inputs.append(adm_words)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((k, nq_pad), lambda g, gl: (0, 0)),
            pl.BlockSpec((k, nq_pad), lambda g, gl: (0, 0)),
        ],
        scratch_shapes=vb.fused_scan_scratch(k, kt, merge_window, nq_pad),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel_codes_fused, kt=kt, k=k,
                          n_probes=n_probes, P=P, pq_dim=pq_dim,
                          pq_bits=pq_bits, n_groups=n_groups,
                          merge_window=merge_window, has_adm=has_adm),
        out_shape=[
            jax.ShapeDtypeStruct((k, nq_pad), jnp.float32),
            jax.ShapeDtypeStruct((k, nq_pad), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return vals, gids


def _pad_lanes(x, width):
    """Zero-pad the trailing (lane) axis of a 2-D array to ``width``."""
    if x.shape[-1] == width:
        return x.astype(jnp.float32)
    return jnp.pad(x.astype(jnp.float32),
                   ((0, 0), (0, width - x.shape[-1])))


def _cap_bits(cap: int) -> int:
    return max((cap - 1).bit_length(), 1)


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "pq_bits",
                                             "packed", "interpret"))
def grouped_code_scan(group_list, slot_pairs, qrot, centers_f32,
                      codes_lanes, codebooks, rsq, list_indices, kt,
                      n_probes, pq_bits, packed=False, interpret=False,
                      adm_words=None):
    """Fused grouped scan over packed PQ codes + local top-kt.

    Same contract as ``pq_group_scan_pallas.grouped_l2_scan`` with the
    bf16 recon cache replaced by ``codes_lanes`` (n_lists, Wi, cap) int32
    (:func:`pack_code_lanes`) + ``codebooks`` (pq_dim, book, pq_len);
    ``rsq`` (n_lists, cap) f32 row norms of the bf16 reconstructions.
    rot_dim need not be 128-aligned: queries/centers are lane-padded here
    and the decoded block pads with zero rows (the deep conf's rot=96).
    """
    n_groups = group_list.shape[0]
    nq, rot = qrot.shape
    _, _, cap = codes_lanes.shape
    pq_dim, book, pq_len = codebooks.shape
    Wi = codes_lanes.shape[1]
    P = nq * n_probes
    rot_pad = _round_up(rot, 128)

    nq_pad = _round_up(nq + 1, 128)
    qrot_pad = jnp.zeros((nq_pad, rot_pad), jnp.float32)
    qrot_pad = qrot_pad.at[:nq, :rot].set(qrot.astype(jnp.float32))
    cf_pad = _pad_lanes(centers_f32, rot_pad)
    # (pq_dim, pq_len, book): books on lanes — the (.., book, pq_len)
    # orientation would lane-pad pq_len (2 at bench shape) to 128
    cbT = jnp.swapaxes(codebooks.astype(jnp.float32), 1, 2)

    has_adm = adm_words is not None
    in_specs = [
        pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
        pl.BlockSpec((nq_pad, rot_pad), lambda g, gl: (0, 0)),
        pl.BlockSpec((1, 1, rot_pad), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, Wi, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((pq_dim, pq_len, book), lambda g, gl: (0, 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
    ]
    inputs = [group_list, slot_pairs[:, None, :], qrot_pad,
              cf_pad[:, None, :], codes_lanes, cbT, rsq[:, None, :],
              list_indices[:, None, :]]
    if has_adm:
        wc = adm_words.shape[2]
        in_specs.append(pl.BlockSpec((1, GROUP, wc),
                                     lambda g, gl: (g, 0, 0)))
        inputs.append(adm_words)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=_scratch_shapes(kt),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel_codes, kt=kt, n_probes=n_probes, P=P,
                          pq_dim=pq_dim, pq_bits=pq_bits, packed=packed,
                          cap_bits=_cap_bits(cap), has_adm=has_adm),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return vals, gids


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "packed",
                                             "interpret"))
def grouped_recon8_scan(group_list, slot_pairs, qrot, centers_f32,
                        recon_i8, scales, rsq8, list_indices, kt, n_probes,
                        packed=False, interpret=False, adm_words=None):
    """Fused grouped scan over the int8-quantized recon cache.

    ``recon_i8`` (n_lists, cap, rot_pad) int8 with lanes already
    128-padded (see ivf_pq._with_recon8), ``scales`` (n_lists,) f32
    per-list dequant scales, ``rsq8`` (n_lists, cap) f32 row norms of
    the DEQUANTIZED rows (so distances are consistent with the in-kernel
    dequant).  Same output contract as ``grouped_l2_scan``.
    """
    n_groups = group_list.shape[0]
    nq, rot = qrot.shape
    _, cap, rot_pad = recon_i8.shape
    P = nq * n_probes

    nq_pad = _round_up(nq + 1, 128)
    qrot_pad = jnp.zeros((nq_pad, rot_pad), jnp.float32)
    qrot_pad = qrot_pad.at[:nq, :rot].set(qrot.astype(jnp.float32))
    cf_pad = _pad_lanes(centers_f32, rot_pad)

    has_adm = adm_words is not None
    in_specs = [
        pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
        pl.BlockSpec((nq_pad, rot_pad), lambda g, gl: (0, 0)),
        pl.BlockSpec((1, 1, rot_pad), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, cap, rot_pad), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, 1), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
    ]
    inputs = [group_list, slot_pairs[:, None, :], qrot_pad,
              cf_pad[:, None, :], recon_i8,
              scales.astype(jnp.float32)[:, None, None],
              rsq8[:, None, :], list_indices[:, None, :]]
    if has_adm:
        wc = adm_words.shape[2]
        in_specs.append(pl.BlockSpec((1, GROUP, wc),
                                     lambda g, gl: (g, 0, 0)))
        inputs.append(adm_words)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=_scratch_shapes(kt),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel_recon8, kt=kt, n_probes=n_probes, P=P,
                          packed=packed, cap_bits=_cap_bits(cap),
                          has_adm=has_adm),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return vals, gids


def _extract_ok(kt: int, packed: bool) -> bool:
    # the packed variant is unrolled-only; the generic path also serves
    # the fori_loop regime up to _KT_MAX
    return 0 < kt <= (_KT_UNROLL if packed else _KT_MAX)


def supported_codes(metric_is_l2: bool, per_subspace: bool, cap: int,
                    rot: int, kt: int, nq: int, pq_dim: int, pq_bits: int,
                    packed: bool = False) -> bool:
    """Shapes/configs the code-scan kernel handles.

    pq_bits must divide 32 (in-register unpack is one static shift+mask
    per subspace), codebooks must be PER_SUBSPACE (a per-cluster table
    would re-DMA book*rot per group), and the summed VMEM footprint —
    query table + one-hot, packed-code block, codebook table, decoded
    reconT block, distances + extraction temps — stays under budget.
    Candidate-id f32-exactness is data-dependent and checked by the
    caller (grouped.ids_f32_exact), as for the recon kernel."""
    if not (metric_is_l2 and per_subspace and pq_bits in (4, 8)):
        return False
    book = 1 << pq_bits
    pq_len = rot // pq_dim if pq_dim and rot % pq_dim == 0 else 0
    if not pq_len:
        return False
    rot_pad = _round_up(rot, 128)
    nq_pad = _round_up(nq + 1, 128)
    Wi = code_lane_words(pq_dim, pq_bits)
    vmem = (2 * nq_pad * rot_pad * 4            # query table + one-hot
            + _round_up(Wi, 8) * cap * 4        # packed-code block
            + pq_dim * _round_up(pq_len, 8) * _round_up(book, 128) * 4
            + 2 * rot_pad * cap * 2             # reconT + concat temp
            + _round_up(book, 8) * cap * 2      # one-hot transient
            + 2 * GROUP * cap * 4)              # distances + extraction
    return (cap % 16 == 0 and GROUP % 16 == 0 and _extract_ok(kt, packed)
            and nq <= 6144 and vmem <= _VMEM_BUDGET)


def fused_codes_merge_window(cap: int, rot: int, kt: int, k: int, nq: int,
                             pq_dim: int, pq_bits: int,
                             requested: int = 0) -> int:
    """Host-static merge window for the fused codes scan (0 = no window
    fits).  The streaming side (codes + codebook + decode transients)
    is budgeted by :func:`supported_codes`; the merge side —
    accumulator + staging ring + merge transients — gets its own
    ``_FUSED_MERGE_BUDGET`` next to it, so ``base_bytes`` is 0 here."""
    del cap, rot, pq_dim, pq_bits    # streaming side budgeted separately
    nq_pad = _round_up(nq + 1, 128)
    return vb.select_merge_window(
        requested, kt=kt, k=k, nq_pad=nq_pad, group=GROUP, base_bytes=0,
        budget=_FUSED_MERGE_BUDGET, w_min=1 if k <= _KT_UNROLL else 2)


def supported_fused_codes(metric_is_l2: bool, per_subspace: bool, cap: int,
                          rot: int, kt: int, k: int, nq: int, pq_dim: int,
                          pq_bits: int, merge_window: int = 0) -> bool:
    """Shapes the FUSED code-scan kernel handles: the static
    :func:`supported_codes` preconditions (generic extraction — the
    packed-key variant has no fused twin) plus the merge side —
    (k, nq_pad) accumulator pair, staging ring, merge transients —
    within ``_FUSED_MERGE_BUDGET`` for some window W
    (:func:`fused_codes_merge_window`); kt stays unrolled while k
    extends to ``vmem_budget.FUSED_K_MAX`` through the windowed
    merge."""
    if not supported_codes(metric_is_l2, per_subspace, cap, rot, kt, nq,
                           pq_dim, pq_bits, packed=False):
        return False
    return (0 < kt <= _KT_UNROLL and 0 < k <= vb.FUSED_K_MAX
            and fused_codes_merge_window(cap, rot, kt, k, nq, pq_dim,
                                         pq_bits, merge_window) > 0)


def fused_codes_reject_reason(metric_is_l2: bool, per_subspace: bool,
                              cap: int, rot: int, kt: int, k: int, nq: int,
                              pq_dim: int, pq_bits: int,
                              merge_window: int = 0) -> str:
    """Reason code for a fused-codes gate miss ('' when supported):
    'dtype' (metric / codebook layout / pq_bits), 'k-too-large' (k/kt
    bounds), 'bucket-too-wide' (batch, alignment, or VMEM)."""
    if not (metric_is_l2 and per_subspace and pq_bits in (4, 8)
            and pq_dim and rot % pq_dim == 0):
        return "dtype"
    if not (0 < kt <= _KT_UNROLL and 0 < k <= vb.FUSED_K_MAX):
        return "k-too-large"
    if supported_fused_codes(metric_is_l2, per_subspace, cap, rot, kt, k,
                             nq, pq_dim, pq_bits, merge_window):
        return ""
    return "bucket-too-wide"


def supported_recon8(metric_is_l2: bool, cap: int, rot: int, kt: int,
                     nq: int, packed: bool = False) -> bool:
    """Shapes the int8 recon kernel handles: int8 tiles are (32, 128), so
    cap must be 32-aligned (the list allocator's _LIST_ALIGN guarantees
    it); rot is lane-padded internally."""
    rot_pad = _round_up(rot, 128)
    nq_pad = _round_up(nq + 1, 128)
    vmem = (2 * nq_pad * rot_pad * 4
            + cap * rot_pad * 1                 # int8 data block
            + cap * rot_pad * 2                 # bf16 dequant transient
            + 2 * GROUP * cap * 4)
    return (metric_is_l2 and cap % 32 == 0 and GROUP % 16 == 0
            and _extract_ok(kt, packed) and nq <= 6144
            and vmem <= _VMEM_BUDGET)
