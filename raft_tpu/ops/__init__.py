"""Hand-written Pallas TPU kernels.

The analogue of the reference's bespoke-CUDA-kernel layer (the ``.cu``
instantiation units of cpp/src and the custom kernels under detail/ —
SURVEY.md §2.10): ops XLA cannot fuse or schedule optimally get explicit
VMEM-resident Pallas implementations here.  Each kernel ships with an
interpreter-mode test (CPU) and an on-chip parity check against its XLA
formulation.
"""

from raft_tpu.ops.fused_l2_nn_pallas import fused_l2_nn_pallas  # noqa: F401

__all__ = ["fused_l2_nn_pallas"]
