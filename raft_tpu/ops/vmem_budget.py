"""Shared VMEM-budget model for the fused Pallas kernels' windowed merge.

The three fused kernels (:mod:`raft_tpu.ops.pq_group_scan_pallas`,
:mod:`raft_tpu.ops.pq_code_scan_pallas`,
:mod:`raft_tpu.ops.cagra_hop_pallas`) amortize their per-step top-k merge
through a VMEM **staging ring**: each grid step appends its kt candidates
into a (kt*W, nq_pad) scratch pair with a cheap one-hot scatter +
sentinel fill, and only every W-th step (and at flush) pays the full
merge into the (k, nq_pad) accumulator.  ``W`` is host-static: it is
chosen here, from shapes only, by one budget model all three kernels
share — staging + accumulator + merge working set must fit the kernel's
existing VMEM budget next to its streaming blocks.  graftlint's
mask-seam pass requires the fused kernels to size their scratch through
:func:`fused_scan_scratch` / :func:`hop_scratch` so the scratch a kernel
allocates and the bytes this model charges cannot drift apart.

Selection is monotone: the amortized per-step merge cost
``k * (k + kt*W) / W`` column passes strictly decreases in W while the
staging write stays O(kt), so ``auto`` picks the LARGEST W that fits,
capped at :data:`MERGE_WINDOW_MAX` (past which the staged rows' own
merge passes dominate and the VMEM spent stops buying wall-clock).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

# requested merge_window sentinel: pick the largest window that fits
MERGE_WINDOW_AUTO = 0
# staging rings larger than this stop paying: the merge over k + kt*W
# staged rows grows linearly in W while the amortization factor 1/W
# saturates
MERGE_WINDOW_MAX = 8
# the windowed merge's fori_loop accumulator store lifts the unrolled
# k <= 64 merge bound up to the radix-select regime
FUSED_K_MAX = 256


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def merge_window_request(value) -> int:
    """Normalize the public ``merge_window`` knob ("auto" | int) to the
    integer request the selectors take: 0 = auto, n >= 1 = upper bound.
    Every caller (ivf_pq / cagra SearchParams, distributed.ann, AOT
    exports) parses the knob through here so the accepted spellings
    cannot drift."""
    if value is None or value == "auto":
        return MERGE_WINDOW_AUTO
    w = int(value)
    if w < 0:
        raise ValueError(
            f"merge_window must be 'auto' or an int >= 0, got {value!r}")
    return w


def nq_padded(nq: int) -> int:
    """Lane-padded query-table height shared by the fused scan kernels
    (one sentinel row for empty slots, then 128-lane alignment)."""
    return round_up(nq + 1, 128)


def accumulator_bytes(k: int, nq_pad: int) -> int:
    """The (k, nq_pad) f32 value/id accumulator pair."""
    return 2 * k * nq_pad * 4


def staging_bytes(kt: int, merge_window: int, nq_pad: int) -> int:
    """The (kt*W, nq_pad) f32 staging-ring pair; W <= 1 stages nothing
    (the per-step merge never materializes a window)."""
    if merge_window <= 1:
        return 0
    return 2 * kt * merge_window * nq_pad * 4


def merge_temps_bytes(k: int, kt: int, merge_window: int, nq_pad: int,
                      group: int) -> int:
    """Transient working set of one merge.

    W <= 1 is the per-step merge: one-hot gather/write-back temps at
    GROUP width, 4 (k+kt, GROUP) f32 arrays (values + ids, in + out).
    W > 1 merges at FULL column width: the concatenated
    (k + kt*W, nq_pad) value/id pair the selection passes sweep.
    """
    if merge_window <= 1:
        return 4 * (k + kt) * group * 4
    return 2 * (k + kt * merge_window) * nq_pad * 4


def select_merge_window(requested: int, *, kt: int, k: int, nq_pad: int,
                        group: int, base_bytes: int, budget: int,
                        w_min: int = 1,
                        w_max: int = MERGE_WINDOW_MAX) -> int:
    """Host-static merge-window choice for a fused scan shape.

    ``base_bytes`` is the kernel's non-merge VMEM floor (query table,
    streamed data block, distance block, ...); the merge side —
    accumulator + staging ring + merge transients — must fit in
    ``budget - base_bytes``.  ``requested`` is the user knob:
    :data:`MERGE_WINDOW_AUTO` (0) picks the largest fitting W; a
    positive W is honored as an upper bound (clamped down to what
    fits).  ``w_min`` > 1 expresses shapes the per-step merge cannot
    serve (k past the unrolled regime needs the windowed fori_loop
    merge).  Returns the chosen W, or 0 when NO window fits — callers
    treat 0 as "fused unsupported at this shape".
    """
    if requested < 0 or kt <= 0 or k <= 0:
        return 0

    def fits(w: int) -> bool:
        total = (base_bytes + accumulator_bytes(k, nq_pad)
                 + staging_bytes(kt, w, nq_pad)
                 + merge_temps_bytes(k, kt, w, nq_pad, group))
        return total <= budget

    hi = w_max if requested == MERGE_WINDOW_AUTO else min(requested, w_max)
    for w in range(hi, w_min - 1, -1):
        if fits(w):
            return w
    return 0


def fused_scan_scratch(k: int, kt: int, merge_window: int, nq_pad: int):
    """Scratch list for the fused scan kernels: the (k, nq_pad)
    accumulator pair, plus the (kt*W, nq_pad) staging-ring pair when a
    window is in play.  The fused kernels MUST allocate through this
    helper (graftlint-enforced) so scratch and the budget model agree."""
    scratch = [pltpu.VMEM((k, nq_pad), jnp.float32),
               pltpu.VMEM((k, nq_pad), jnp.float32)]
    if merge_window > 1:
        scratch += [pltpu.VMEM((kt * merge_window, nq_pad), jnp.float32),
                    pltpu.VMEM((kt * merge_window, nq_pad), jnp.float32)]
    return scratch


# ---------------------------------------------------------------------------
# fused CAGRA hop
# ---------------------------------------------------------------------------
#
# The hop kernel's "window" is within-hop: the walk needs the fully
# merged sorted buffer before every parent selection, so work cannot be
# deferred ACROSS hops.  W > 1 selects the staged variant — candidates
# are extracted into a sorted staging block (min(itopk, wd) rows) and
# merged with the buffer by one in-kernel bitonic pass, replacing the
# itopk min-extraction rounds over all itopk+wd rows that gated the
# legacy kernel at itopk <= 32.


def hop_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def hop_stage_rows(itopk: int, wd: int) -> int:
    """Rows of the staged-extraction block (top-t of the hop's
    candidates; t beyond min(itopk, wd) can never survive the merge)."""
    return min(itopk, wd)


def hop_merge_rows(itopk: int, wd: int) -> int:
    """Height of the bitonic compare-exchange network: buffer + staged
    block, padded to a power of two."""
    return hop_pow2(itopk + hop_stage_rows(itopk, wd))


def hop_bytes(itopk: int, wd: int, pdim: int, merge_window: int,
              lanes: int) -> int:
    """VMEM model of one fused hop, legacy (W <= 1) or staged (W > 1)."""
    base = (wd * pdim * lanes * 4        # neighbor lanes
            + (pdim + 1) * lanes * 4     # qpT + q_sq
            + 2 * wd * lanes * 4         # nb_sq / nb_id
            + 9 * itopk * lanes * 4)     # buffer triple, in + out
    if merge_window <= 1:
        return base + 4 * (itopk + wd) * lanes * 4   # merge working set
    rows = hop_merge_rows(itopk, wd)
    return (base
            + 2 * hop_stage_rows(itopk, wd) * lanes * 4   # staging block
            + 6 * rows * lanes * 4)      # bitonic working set (d/i/v x2)


def select_hop_window(requested: int, *, itopk: int, wd: int, pdim: int,
                      lanes: int, budget: int, itopk_legacy_max: int,
                      itopk_staged_max: int) -> int:
    """Merge-window choice for the fused hop: 1 = legacy in-pass merge,
    2 = staged extraction + bitonic merge (there is no deeper ring —
    the walk consumes the merged buffer every hop).  ``auto`` keeps the
    proven legacy kernel where it is allowed (itopk within the legacy
    gate) and selects the staged variant for larger itopk; an explicit
    W > 1 forces staging.  Returns 0 when neither variant fits."""
    if requested < 0 or itopk <= 0:
        return 0
    want_staged = (requested > 1
                   or (requested == MERGE_WINDOW_AUTO
                       and itopk > itopk_legacy_max))
    if want_staged:
        if (itopk <= itopk_staged_max
                and hop_bytes(itopk, wd, pdim, 2, lanes) <= budget):
            return 2
        if requested > 1:
            return 0
    if (itopk <= itopk_legacy_max
            and hop_bytes(itopk, wd, pdim, 1, lanes) <= budget):
        return 1
    return 0


def hop_scratch(itopk: int, wd: int, merge_window: int, lanes: int):
    """Scratch list for the fused hop kernel: the staged variant's
    (t, lanes) extraction block pair (distances / ids — staged
    candidates are never visited, so no flag plane).  Sized here
    (graftlint-enforced) for the same reason as
    :func:`fused_scan_scratch`; the legacy variant stages nothing."""
    if merge_window <= 1:
        return []
    t = hop_stage_rows(itopk, wd)
    return [pltpu.VMEM((t, lanes), jnp.float32) for _ in range(2)]
