"""Pallas TPU kernel: fused grouped PQ-reconstruction scan + local top-k.

The ``compute_similarity_kernel`` analogue (reference:
neighbors/detail/ivf_pq_search.cuh:611) for the grouped search layout
(:mod:`raft_tpu.neighbors.grouped`): one program per pair-group computes
the group's (GROUP, cap) quantized L2 distances on the MXU and extracts
each row's top-kt **in VMEM**, so the distance matrix never reaches HBM.

Structure per program ``g``:

- the scalar-prefetched ``group_list`` drives the BlockSpec index maps —
  the list's bf16 reconstructions, squared norms, and candidate ids are
  DMA'd directly by list id (the TPU equivalent of the reference
  assigning one CTA per (list, query-group));
- the group's rotated queries are gathered from the VMEM-resident
  ``qrot`` table (it is only nq x rot ~ a few MB) by a **one-hot MXU
  matmul** — Mosaic has no native row-gather, and the XLA-side gather
  this replaces measured ~120 ms/batch at bench shapes versus a few ms
  of MXU time for the one-hot contraction;
- residuals against the list center, the distance GEMM
  ``d = ||sub||^2 + ||recon||^2 - 2 sub.recon``, and kt passes of
  max / where-iota argmin / mask extract the top-kt per row — all in
  VMEM;
- selected positions map to **global candidate ids** by a second one-hot
  contraction against the list's id row (ids < 2^24 are exact in f32),
  so the XLA side needs no post-hoc id gather.

Outputs are per-pair (values, global ids); callers scatter them into the
(P, kt) buffers by pair slot.  Rows with fewer than kt finite candidates
emit +inf values; callers map those to the -1 id sentinel (valid L2
distances are finite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.neighbors.grouped import GROUP
from raft_tpu.ops import vmem_budget as vb

# extraction switches from unrolled static-lane passes to a fori_loop
# with transposed scratch above this kt (see _extract_topk)
_KT_UNROLL = 64
_KT_MAX = 128

# Finite "worst distance" sentinel of the fused accumulator.  The
# accumulator is read and written through one-hot f32 contractions, and
# IEEE 0 * inf = nan would leak a +inf sentinel into every gathered row
# — so the fused kernels keep exhausted slots at a large FINITE value
# and the epilogue maps values past _ACC_WORST/2 to the public
# +inf / id -1 contract.
_ACC_WORST = 3.0e38


def _scratch_shapes(kt):
    if kt <= _KT_UNROLL:
        shape = (GROUP, kt)
    else:
        shape = (-(-kt // 8) * 8, GROUP)
    return [pltpu.VMEM(shape, jnp.float32), pltpu.VMEM(shape, jnp.int32)]


def _gather_queries(slot_ref, q_ref, n_probes, P):
    """One-hot MXU row gather of the group's queries from the
    VMEM-resident table.  f32 one-hot x f32 table is EXACT (one product
    per output) — a bf16 table would round |q| before any center
    subtraction, which can exceed the residual magnitude on
    well-clustered data.  Sentinel slots gather the zero row."""
    nq_pad = q_ref.shape[0]
    slot = slot_ref[0, 0]                              # (G,) int32 pair ids
    qid = jnp.where(slot < P, slot // n_probes, nq_pad - 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (GROUP, nq_pad), 1)
    onehot = (cols == qid[:, None]).astype(jnp.float32)
    return jax.lax.dot_general(onehot, q_ref[:],
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)  # (G, d)


def _unpack_admission(adm_ref, cap):
    """In-kernel unpack of the packed per-(slot, candidate) admission
    words — (1, GROUP, Wc) int32, bit b of word w admitting candidate
    ``32*w + b`` (the layout :func:`raft_tpu.filters.bitset.pack_mask`
    writes, built per group by ``group_admission_words``) — to a
    (GROUP, cap) 0/1 block.  One shift/mask per word: admission costs
    ~1 bit of VMEM streaming per candidate."""
    aw = adm_ref[0]                                    # (GROUP, Wc) int32
    shifts = jax.lax.broadcasted_iota(jnp.int32, aw.shape + (32,), 2)
    bits = (aw[:, :, None] >> shifts) & 1
    return bits.reshape(aw.shape[0], -1)[:, :cap]


def _kernel(gl_ref, slot_ref, qrot_ref, cf_ref, data_ref, rsq_ref, ids_ref,
            *rest, kt, n_probes, P, has_adm=False):
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    vals_ref, ids_out_ref, vscratch, pscratch = rest
    qv = _gather_queries(slot_ref, qrot_ref, n_probes, P)
    sub = qv - cf_ref[0, 0][None, :]                   # (G, rot) f32
    sub_sq = jnp.sum(sub * sub, axis=1)                # (G,)
    data = data_ref[0]                                 # (cap, rot) bf16
    ip = jax.lax.dot_general(sub.astype(jnp.bfloat16), data,
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = sub_sq[:, None] + rsq_ref[0, 0][None, :] - 2.0 * ip
    d = jnp.maximum(d, 0.0)
    ids_row = ids_ref[0, 0]                            # (cap,) int32
    adm = _unpack_admission(adm_ref, d.shape[1]) if has_adm else None
    _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch, pscratch,
                  kt, adm=adm)


def _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch, pscratch,
                  kt, adm=None):
    """Shared in-VMEM top-kt extraction + position -> global-id mapping.

    kt passes of max / where-iota argmin / mask over the (G, cap) block;
    the id map is a masked reduce against the list's id row per pass
    (a single (G*kt, cap) one-hot matmul would cost ~5 MB of VMEM).

    kt <= _KT_UNROLL: unrolled passes writing static scratch lanes (the
    proven hot path).  Larger kt (radix-select regime, k to 128+ —
    reference select_radix.cuh): a ``fori_loop`` with dynamic SUBLANE
    stores into (kt, G)-transposed scratch — dynamic stores on the lane
    dim are Mosaic-hostile, on the sublane dim they are cheap — then one
    in-VMEM transpose on the way out."""
    invalid = (ids_row < 0)[None, :]
    if adm is not None:
        # per-(slot, candidate) admission bit: a rejected candidate
        # folds exactly like a tombstone — excluded before any
        # selection pass, through the same finite-sentinel seam
        invalid = invalid | (adm == 0)
    neg = jnp.where(invalid, -jnp.inf, -d)             # select-min as max

    cap = neg.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, neg.shape, 1)
    ids_f = ids_row.astype(jnp.float32)                # exact below 2^24

    def step(neg):
        m = jnp.max(neg, axis=1)                       # (G,)
        # where-iota argmax (ties -> lowest column, stable like sort)
        p = jnp.min(jnp.where(neg == m[:, None], col, cap), axis=1)
        p = jnp.minimum(p, cap - 1)                    # all -inf row guard
        sel = col == p[:, None]
        gid = jnp.max(jnp.where(sel, ids_f[None, :], -jnp.inf), axis=1)
        return m, sel, gid

    if kt <= _KT_UNROLL:
        for j in range(kt):
            m, sel, gid = step(neg)
            vscratch[:, j] = -m
            pscratch[:, j] = gid.astype(jnp.int32)
            neg = jnp.where(sel, -jnp.inf, neg)
        vals_ref[0] = vscratch[:, :]
        ids_out_ref[0] = pscratch[:, :]
    else:
        def body(j, neg):
            m, sel, gid = step(neg)
            vscratch[pl.ds(j, 1), :] = (-m)[None, :]
            pscratch[pl.ds(j, 1), :] = gid.astype(jnp.int32)[None, :]
            return jnp.where(sel, -jnp.inf, neg)

        jax.lax.fori_loop(0, kt, body, neg, unroll=False)
        vals_ref[0] = vscratch[:kt, :].T
        ids_out_ref[0] = pscratch[:kt, :].T


# ---------------------------------------------------------------------------
# fused in-kernel top-k: candidates never touch HBM
# ---------------------------------------------------------------------------
#
# The non-fused kernels emit (n_groups, GROUP, kt) per-pair winners that
# the XLA side scatters into (P, kt) buffers and reduces with a final
# select — at bench shapes that round-trip plus the select is the
# dominant remaining cost (PERFORMANCE.md round 6: ~3.3 us per kept
# candidate).  The fused variants exploit the TPU grid's SEQUENTIAL
# execution: a (k, nq_pad) per-query accumulator lives in VMEM scratch
# across ALL grid steps, each group's local top-kt is merged into its
# queries' rows in-kernel, and only the final (k, nq_pad) answer is
# written to HBM on the last step.  No scatter, no final select — the
# extraction stage disappears from the profile.
#
# The accumulator is addressed by query id through the SAME one-hot
# matrix the query gather builds (rows are gathered by
# ``onehot @ acc`` and written back as ``acc*(1-cover) + onehotT @
# merged``).  Every slot of a group holds a DISTINCT query (a group is
# one list; a query probes each list at most once), so the write-back
# touches each row through exactly one one-hot lane — the update is
# EXACT in f32, and candidate ids ride along as exact-below-2^24 f32
# lanes just like the id mapping of the non-fused extraction.


def _gather_queries_masked(slot_ref, q_ref, n_probes, P):
    """Query gather that also returns the validity-masked one-hot used
    to address the fused accumulator.  Sentinel slots have an all-zero
    one-hot row: they gather the zero query row AND are excluded from
    the accumulator write-back (their merged columns are discarded)."""
    nq_pad = q_ref.shape[0]
    slot = slot_ref[0, 0]                              # (G,) int32 pair ids
    valid = slot < P
    qid = jnp.where(valid, slot // n_probes, 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (GROUP, nq_pad), 1)
    oh = ((cols == qid[:, None]) & valid[:, None]).astype(jnp.float32)
    qv = jax.lax.dot_general(oh, q_ref[:], (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return qv, oh


def _topk_rows(d, ids_row, kt, adm=None):
    """Local top-kt of a (G, cap) distance block as sublane-stacked
    (kt, G) value/id rows — the fused twin of :func:`_extract_topk`
    (same max / where-iota argmin / masked-id-reduce passes), except
    results stay in registers for the in-kernel merge and exhausted
    slots carry the finite ``_ACC_WORST`` instead of +inf.  ``adm``
    folds per-(slot, candidate) admission bits through the same seam
    BEFORE any value reaches the staging ring or the accumulator's
    one-hot products (only finite sentinels ever meet a product)."""
    invalid = (ids_row < 0)[None, :]
    if adm is not None:
        invalid = invalid | (adm == 0)
    neg = jnp.where(invalid, -jnp.inf, -d)
    cap = neg.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, neg.shape, 1)
    ids_f = ids_row.astype(jnp.float32)                # exact below 2^24
    vs, gs = [], []
    for _ in range(kt):
        m = jnp.max(neg, axis=1)                       # (G,)
        p = jnp.min(jnp.where(neg == m[:, None], col, cap), axis=1)
        p = jnp.minimum(p, cap - 1)                    # all -inf row guard
        sel = col == p[:, None]
        gid = jnp.max(jnp.where(sel, ids_f[None, :], -jnp.inf), axis=1)
        v = jnp.where(jnp.isinf(m), _ACC_WORST, -m)
        vs.append(v[None, :])
        gs.append(gid[None, :])
        neg = jnp.where(sel, -jnp.inf, neg)
    return jnp.concatenate(vs, 0), jnp.concatenate(gs, 0)   # (kt, G)


def _merge_topk(cat_v, cat_i, k):
    """k selection passes over sublane-stacked (rows, G) candidates:
    merge of the accumulator's sorted k rows with a group's local kt
    rows.  Cross-SUBLANE reduces (rows <= k + kt, tiny) — the lane axis
    stays the 128 pair slots."""
    rows_n = cat_v.shape[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, cat_v.shape, 0)
    out_v, out_i = [], []
    for _ in range(k):
        m = jnp.min(cat_v, axis=0)                     # (G,)
        p = jnp.min(jnp.where(cat_v == m[None, :], rows, rows_n), axis=0)
        p = jnp.minimum(p, rows_n - 1)
        sel = rows == p[None, :]
        gi = jnp.max(jnp.where(sel, cat_i, -jnp.inf), axis=0)
        out_v.append(m[None, :])
        out_i.append(gi[None, :])
        cat_v = jnp.where(sel, _ACC_WORST, cat_v)
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)  # (k, G)


def _fused_accumulate(oh, d, ids_row, acc_v, acc_i, kt, adm=None):
    """Merge one group's (G, cap) distances into the per-query
    accumulator: local top-kt, gather the slots' accumulator rows via
    the one-hot, merge sorted k+kt candidates per slot, write back.
    The one-hot write-back is exact (each real row is covered by at
    most one slot; sentinel slots have all-zero one-hot rows)."""
    k = acc_v.shape[0]
    new_v, new_i = _topk_rows(d, ids_row, kt, adm=adm)  # (kt, G)
    old_v = jax.lax.dot_general(acc_v[:], oh, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
    old_i = jax.lax.dot_general(acc_i[:], oh, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
    mer_v, mer_i = _merge_topk(jnp.concatenate([old_v, new_v], 0),
                               jnp.concatenate([old_i, new_i], 0), k)
    cover = jnp.sum(oh, axis=0)                        # (nq_pad,) 0/1
    keep = (1.0 - cover)[None, :]
    acc_v[:] = acc_v[:] * keep + jax.lax.dot_general(
        mer_v, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_i[:] = acc_i[:] * keep + jax.lax.dot_general(
        mer_i, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _merge_cols(acc_v, acc_i, stg_v, stg_i, k):
    """Windowed merge: fold the staged (kt*W, nq_pad) ring into the
    sorted (k, nq_pad) accumulator at FULL column width — no one-hot
    gather or write-back, every query column merges in place.  Same
    selection rule as :func:`_merge_topk` (min, lowest-row tie-break,
    masked-id reduce, winner re-masked to the finite sentinel), with
    rows ordered [accumulator | ring in arrival order] so tie retention
    matches the per-step merge bit-for-bit.  Columns whose staged rows
    are all sentinels reproduce the accumulator exactly (it is sorted
    and its rows precede the ring's), so partially-filled windows and
    all-sentinel tails are free.

    k past the unrolled regime runs as a ``fori_loop`` with dynamic
    SUBLANE stores into the accumulator — the concatenated working set
    is materialized before the loop, so the in-place row writes never
    feed back into the selection carry."""
    cat_v = jnp.concatenate([acc_v[:], stg_v[:]], axis=0)
    cat_i = jnp.concatenate([acc_i[:], stg_i[:]], axis=0)
    rows_n = cat_v.shape[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, cat_v.shape, 0)

    def step(cat_v):
        m = jnp.min(cat_v, axis=0)                     # (nq_pad,)
        p = jnp.min(jnp.where(cat_v == m[None, :], rows, rows_n), axis=0)
        p = jnp.minimum(p, rows_n - 1)
        sel = rows == p[None, :]
        gi = jnp.max(jnp.where(sel, cat_i, -jnp.inf), axis=0)
        return m, sel, gi

    if k <= _KT_UNROLL:
        out_v, out_i = [], []
        for _ in range(k):
            m, sel, gi = step(cat_v)
            out_v.append(m[None, :])
            out_i.append(gi[None, :])
            cat_v = jnp.where(sel, _ACC_WORST, cat_v)
        acc_v[:] = jnp.concatenate(out_v, 0)
        acc_i[:] = jnp.concatenate(out_i, 0)
    else:
        def body(j, cat_v):
            m, sel, gi = step(cat_v)
            acc_v[pl.ds(j, 1), :] = m[None, :]
            acc_i[pl.ds(j, 1), :] = gi[None, :]
            return jnp.where(sel, _ACC_WORST, cat_v)

        jax.lax.fori_loop(0, k, body, cat_v, unroll=False)


def _fused_step(g, oh, d, ids_row, acc_v, acc_i, stg, *, kt,
                merge_window, n_groups, adm=None):
    """One grid step of the fused accumulator, windowed.

    W <= 1 is the original per-step path (:func:`_fused_accumulate` —
    gather + merge + write-back every step).  W > 1 stages instead:
    the step's local top-kt lands in the ring slot ``g % W`` by ONE
    one-hot scatter per operand — uncovered columns take the
    ``_ACC_WORST`` / id -1 sentinel fill (``dot + _ACC_WORST*(1-cover)``
    is exact: covered columns add 0, uncovered columns add to 0) — and
    only every W-th step (and the flush step) pays
    :func:`_merge_cols`.  The ring resets to sentinels after each
    merge so stale slots of a partial final window merge as no-ops.
    """
    if merge_window <= 1:
        _fused_accumulate(oh, d, ids_row, acc_v, acc_i, kt, adm=adm)
        return
    stg_v, stg_i = stg
    new_v, new_i = _topk_rows(d, ids_row, kt, adm=adm)  # (kt, G), finite
    cover = jnp.sum(oh, axis=0)                        # (nq_pad,) 0/1
    fill = (1.0 - cover)[None, :]
    row0 = (g % merge_window) * kt
    stg_v[pl.ds(row0, kt), :] = jax.lax.dot_general(
        new_v, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + _ACC_WORST * fill
    stg_i[pl.ds(row0, kt), :] = jax.lax.dot_general(
        new_i, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) - fill

    @pl.when(((g + 1) % merge_window == 0) | (g == n_groups - 1))
    def _merge():
        _merge_cols(acc_v, acc_i, stg_v, stg_i, acc_v.shape[0])
        stg_v[:] = jnp.full(stg_v.shape, _ACC_WORST, jnp.float32)
        stg_i[:] = jnp.full(stg_i.shape, -1.0, jnp.float32)


def _kernel_fused(gl_ref, slot_ref, qrot_ref, cf_ref, data_ref, rsq_ref,
                  ids_ref, *rest, kt, k, n_probes, P, n_groups,
                  merge_window, has_adm=False):
    """Fused recon scan: the non-fused ``_kernel`` distance block plus
    the in-kernel accumulator merge (windowed through the staging ring
    when merge_window > 1); outputs are the FINAL per-query (k, nq_pad)
    answers, flushed once on the last grid step."""
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    vals_ref, ids_out_ref, acc_v, acc_i, *stg = rest
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _init():
        acc_v[:] = jnp.full(acc_v.shape, _ACC_WORST, jnp.float32)
        acc_i[:] = jnp.full(acc_i.shape, -1.0, jnp.float32)
        if merge_window > 1:
            stg[0][:] = jnp.full(stg[0].shape, _ACC_WORST, jnp.float32)
            stg[1][:] = jnp.full(stg[1].shape, -1.0, jnp.float32)

    qv, oh = _gather_queries_masked(slot_ref, qrot_ref, n_probes, P)
    sub = qv - cf_ref[0, 0][None, :]                   # (G, rot) f32
    sub_sq = jnp.sum(sub * sub, axis=1)                # (G,)
    data = data_ref[0]                                 # (cap, rot) bf16
    ip = jax.lax.dot_general(sub.astype(jnp.bfloat16), data,
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = sub_sq[:, None] + rsq_ref[0, 0][None, :] - 2.0 * ip
    d = jnp.maximum(d, 0.0)
    adm = _unpack_admission(adm_ref, d.shape[1]) if has_adm else None
    _fused_step(g, oh, d, ids_ref[0, 0], acc_v, acc_i, stg, kt=kt,
                merge_window=merge_window, n_groups=n_groups, adm=adm)

    @pl.when(g == n_groups - 1)
    def _flush():
        vals_ref[:] = acc_v[:]
        ids_out_ref[:] = acc_i[:].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("kt", "k", "n_probes",
                                             "interpret", "merge_window"))
def grouped_l2_scan_fused(group_list, slot_pairs, qrot, centers_f32,
                          list_recon, rec_sq, list_indices, kt, k, n_probes,
                          interpret=False, merge_window=1, adm_words=None):
    """Fused grouped recon scan with IN-KERNEL per-query top-k.

    Inputs as :func:`grouped_l2_scan`; instead of per-pair winners the
    kernel returns the batch's FINAL per-query answers —
    ``(vals (k, nq_pad) f32, ids (k, nq_pad) int32)`` sorted ascending
    per column, query q in column q.  Exhausted ranks carry values at
    the finite ``_ACC_WORST`` sentinel (callers map values past
    ``_ACC_WORST/2`` to +inf / id -1).  ``kt`` bounds the per-(query,
    probe) keep-set exactly like the non-fused path: each group
    contributes at most its local top-kt per pair before the merge, so
    results match the scatter+select reference at matched kt.

    ``merge_window`` W amortizes the accumulator merge: steps stage
    their top-kt in a (kt*W, nq_pad) VMEM ring and the merge runs every
    W-th step — bit-identical to W=1 (the merge is order-insensitive
    under the finite sentinel; ring order preserves tie retention).
    Pick W with :func:`fused_merge_window`; k > 64 requires W >= 2.

    ``adm_words`` (n_groups, GROUP, ceil(cap/32)) int32 streams packed
    per-(slot, candidate) admission bits (filtered search): rejected
    candidates fold to the finite sentinel before the windowed merge.
    """
    n_groups = group_list.shape[0]
    nq, rot = qrot.shape
    _, cap, _ = list_recon.shape
    P = nq * n_probes

    nq_pad = -(-(nq + 1) // 128) * 128
    qrot_pad = jnp.zeros((nq_pad, rot), jnp.float32)
    qrot_pad = qrot_pad.at[:nq].set(qrot.astype(jnp.float32))

    has_adm = adm_words is not None
    in_specs = [
        pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
        pl.BlockSpec((nq_pad, rot), lambda g, gl: (0, 0)),
        pl.BlockSpec((1, 1, rot), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, cap, rot), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
    ]
    inputs = [group_list, slot_pairs[:, None, :], qrot_pad,
              centers_f32[:, None, :], list_recon, rec_sq[:, None, :],
              list_indices[:, None, :]]
    if has_adm:
        wc = adm_words.shape[2]
        in_specs.append(pl.BlockSpec((1, GROUP, wc),
                                     lambda g, gl: (g, 0, 0)))
        inputs.append(adm_words)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((k, nq_pad), lambda g, gl: (0, 0)),
            pl.BlockSpec((k, nq_pad), lambda g, gl: (0, 0)),
        ],
        scratch_shapes=vb.fused_scan_scratch(k, kt, merge_window, nq_pad),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel_fused, kt=kt, k=k, n_probes=n_probes,
                          P=P, n_groups=n_groups,
                          merge_window=merge_window, has_adm=has_adm),
        out_shape=[
            jax.ShapeDtypeStruct((k, nq_pad), jnp.float32),
            jax.ShapeDtypeStruct((k, nq_pad), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return vals, gids


def _fused_base_bytes(cap: int, rot: int, nq_pad: int,
                      data_elem_bytes: int) -> int:
    return (2 * nq_pad * rot * 4              # query table + one-hot
            + cap * rot * data_elem_bytes     # per-list data block
            + 2 * GROUP * cap * 4)            # distances + local passes


def _fused_static_ok(metric_is_l2: bool, cap: int, rot: int, kt: int,
                     k: int, nq: int) -> bool:
    return (metric_is_l2 and rot % 128 == 0 and cap % 16 == 0
            and GROUP % 16 == 0 and 0 < kt <= _KT_UNROLL
            and 0 < k <= vb.FUSED_K_MAX and nq <= 6144)


def fused_merge_window(cap: int, rot: int, kt: int, k: int, nq: int,
                       data_elem_bytes: int = 2, requested: int = 0) -> int:
    """Host-static merge window for the fused recon scan at this shape
    (0 = no window fits -> fused unsupported).  ``requested`` 0 is auto
    (largest fitting W); k past the unrolled per-step merge needs the
    windowed path, so W >= 2 is forced there."""
    nq_pad = vb.nq_padded(nq)
    return vb.select_merge_window(
        requested, kt=kt, k=k, nq_pad=nq_pad, group=GROUP,
        base_bytes=_fused_base_bytes(cap, rot, nq_pad, data_elem_bytes),
        budget=10 << 20, w_min=1 if k <= _KT_UNROLL else 2)


def supported_fused(metric_is_l2: bool, cap: int, rot: int, kt: int,
                    k: int, nq: int, data_elem_bytes: int = 2,
                    merge_window: int = 0) -> bool:
    """Shapes the fused recon kernel handles.  Beyond :func:`supported`:
    the (k, nq_pad) accumulator pair and the staging ring join the VMEM
    budget (:mod:`raft_tpu.ops.vmem_budget`); kt stays in the unrolled
    regime while k extends to ``FUSED_K_MAX`` through the windowed
    merge (some W must fit — check :func:`fused_merge_window`)."""
    return (_fused_static_ok(metric_is_l2, cap, rot, kt, k, nq)
            and fused_merge_window(cap, rot, kt, k, nq, data_elem_bytes,
                                   merge_window) > 0)


def fused_reject_reason(metric_is_l2: bool, cap: int, rot: int, kt: int,
                        k: int, nq: int, data_elem_bytes: int = 2,
                        merge_window: int = 0) -> str:
    """Reason code for a fused-recon gate miss ('' when supported):
    'dtype' (metric), 'k-too-large' (k/kt bounds), 'bucket-too-wide'
    (batch, layout, or VMEM — no merge window fits).  Drives the
    ``fused_fallback`` counter attrs and flight events."""
    if not metric_is_l2:
        return "dtype"
    if not (0 < kt <= _KT_UNROLL and 0 < k <= vb.FUSED_K_MAX):
        return "k-too-large"
    if not (rot % 128 == 0 and cap % 16 == 0 and GROUP % 16 == 0
            and nq <= 6144):
        return "bucket-too-wide"
    if fused_merge_window(cap, rot, kt, k, nq, data_elem_bytes,
                          merge_window) <= 0:
        return "bucket-too-wide"
    return ""


def _kernel_flat(gl_ref, slot_ref, q_ref, data_ref, dsq_ref, ids_ref,
                 *rest, kt, n_probes, P, has_adm=False):
    """IVF-Flat variant: exact fp32 distances over raw list vectors
    (d = ||q||^2 + ||x||^2 - 2 q.x), same gather/extraction structure."""
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    vals_ref, ids_out_ref, vscratch, pscratch = rest
    qv = _gather_queries(slot_ref, q_ref, n_probes, P)
    q_sq = jnp.sum(qv * qv, axis=1)                    # (G,)
    data = data_ref[0]                                 # (cap, d) f32
    ip = jax.lax.dot_general(qv, data, (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    d = jnp.maximum(q_sq[:, None] + dsq_ref[0, 0][None, :] - 2.0 * ip, 0.0)
    ids_row = ids_ref[0, 0]                            # (cap,) int32
    adm = _unpack_admission(adm_ref, d.shape[1]) if has_adm else None
    _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch, pscratch,
                  kt, adm=adm)


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "interpret"))
def grouped_l2_scan(group_list, slot_pairs, qrot, centers_f32, list_recon,
                    rec_sq, list_indices, kt, n_probes, interpret=False,
                    adm_words=None):
    """Fused query-gather + distance + local top-kt over all pair groups.

    ``group_list`` (n_groups,) int32; ``slot_pairs`` (n_groups, GROUP)
    int32 pair ids with P = nq * n_probes as the empty sentinel;
    ``qrot`` (nq, rot) f32 rotated queries; ``centers_f32`` (n_lists, rot)
    f32; ``list_recon`` (n_lists, cap, rot) bf16; ``rec_sq`` (n_lists,
    cap) f32; ``list_indices`` (n_lists, cap) int32.  Returns
    ``(vals (n_groups, GROUP, kt) f32, ids ... int32)`` sorted ascending
    (L2); exhausted rows carry +inf values (callers map them to -1 ids).

    ``adm_words`` (n_groups, GROUP, ceil(cap/32)) int32, optional:
    packed per-(slot, candidate) admission bits in list-slot order
    (:func:`raft_tpu.filters.bitset.group_admission_words`); rejected
    candidates fold like tombstones before extraction.
    """
    n_groups = group_list.shape[0]
    nq, rot = qrot.shape
    _, cap, _ = list_recon.shape
    P = nq * n_probes

    # pad the query table to a lane-friendly height; the sentinel row
    # (all zeros, index nq_pad-1) is what empty slots gather
    nq_pad = -(-(nq + 1) // 128) * 128
    qrot_pad = jnp.zeros((nq_pad, rot), jnp.float32)
    qrot_pad = qrot_pad.at[:nq].set(qrot.astype(jnp.float32))

    has_adm = adm_words is not None
    in_specs = [
        pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
        pl.BlockSpec((nq_pad, rot), lambda g, gl: (0, 0)),
        pl.BlockSpec((1, 1, rot), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, cap, rot), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
    ]
    inputs = [group_list, slot_pairs[:, None, :], qrot_pad,
              centers_f32[:, None, :], list_recon, rec_sq[:, None, :],
              list_indices[:, None, :]]
    if has_adm:
        wc = adm_words.shape[2]
        in_specs.append(pl.BlockSpec((1, GROUP, wc),
                                     lambda g, gl: (g, 0, 0)))
        inputs.append(adm_words)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=_scratch_shapes(kt),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel, kt=kt, n_probes=n_probes, P=P,
                          has_adm=has_adm),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return vals, gids


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "interpret"))
def grouped_flat_l2_scan(group_list, slot_pairs, queries_f32, list_data,
                         d_sq, list_indices, kt, n_probes, interpret=False,
                         adm_words=None):
    """IVF-Flat fused scan: exact fp32 distances over raw list vectors.
    Same contract as :func:`grouped_l2_scan` with ``queries_f32``
    (nq, dim) raw queries, ``list_data`` (n_lists, cap, dim) fp32 and
    ``d_sq`` (n_lists, cap) fp32 row norms."""
    n_groups = group_list.shape[0]
    nq, dim = queries_f32.shape
    _, cap, _ = list_data.shape
    P = nq * n_probes

    nq_pad = -(-(nq + 1) // 128) * 128
    q_pad = jnp.zeros((nq_pad, dim), jnp.float32)
    q_pad = q_pad.at[:nq].set(queries_f32.astype(jnp.float32))

    has_adm = adm_words is not None
    in_specs = [
        pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
        pl.BlockSpec((nq_pad, dim), lambda g, gl: (0, 0)),
        pl.BlockSpec((1, cap, dim), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
    ]
    inputs = [group_list, slot_pairs[:, None, :], q_pad,
              list_data.astype(jnp.float32), d_sq[:, None, :],
              list_indices[:, None, :]]
    if has_adm:
        wc = adm_words.shape[2]
        in_specs.append(pl.BlockSpec((1, GROUP, wc),
                                     lambda g, gl: (g, 0, 0)))
        inputs.append(adm_words)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=_scratch_shapes(kt),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel_flat, kt=kt, n_probes=n_probes, P=P,
                          has_adm=has_adm),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return vals, gids


def supported(metric_is_l2: bool, cap: int, rot: int, kt: int,
              nq: int, data_elem_bytes: int = 2) -> bool:
    """Shapes the kernel handles; callers fall back to the XLA scan
    otherwise.  Lane dims must be 128-aligned (rot) or tile-aligned
    (cap); kt is bounded to keep the extraction loop sane; the
    query table, its per-program one-hot, the per-list data block, and
    the (GROUP, cap) distance block all live in VMEM, so their summed
    footprint is bounded (the one-hot gather cost also grows with nq —
    larger batches should be split by the caller anyway).

    Candidate-id f32-exactness (|id| < 2^24, required by the one-hot id
    contraction) is data-dependent and checked by the caller on the
    index's actual ids (:func:`raft_tpu.neighbors.grouped.ids_f32_exact`)
    — user-supplied ids from ``extend(new_indices=...)`` can exceed any
    row-count proxy."""
    nq_pad = -(-(nq + 1) // 128) * 128
    vmem = (2 * nq_pad * rot * 4              # query table + one-hot
            + cap * rot * data_elem_bytes     # per-list data block
            + 2 * GROUP * cap * 4)            # distances + extraction temps
    return (metric_is_l2 and rot % 128 == 0 and cap % 16 == 0
            and GROUP % 16 == 0 and 0 < kt <= _KT_MAX
            and nq <= 6144 and vmem <= (10 << 20))
