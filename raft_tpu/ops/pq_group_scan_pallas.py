"""Pallas TPU kernel: fused grouped PQ-reconstruction scan + local top-k.

The ``compute_similarity_kernel`` analogue (reference:
neighbors/detail/ivf_pq_search.cuh:611) for the grouped search layout
(:mod:`raft_tpu.neighbors.grouped`): one program per pair-group computes
the group's (GROUP, cap) quantized L2 distances on the MXU and extracts
each row's top-kt **in VMEM**, so the distance matrix never reaches HBM.

Structure per program ``g``:

- the scalar-prefetched ``group_list`` drives the BlockSpec index maps —
  the list's bf16 reconstructions, squared norms, and candidate ids are
  DMA'd directly by list id (the TPU equivalent of the reference
  assigning one CTA per (list, query-group));
- the group's rotated queries are gathered from the VMEM-resident
  ``qrot`` table (it is only nq x rot ~ a few MB) by a **one-hot MXU
  matmul** — Mosaic has no native row-gather, and the XLA-side gather
  this replaces measured ~120 ms/batch at bench shapes versus a few ms
  of MXU time for the one-hot contraction;
- residuals against the list center, the distance GEMM
  ``d = ||sub||^2 + ||recon||^2 - 2 sub.recon``, and kt passes of
  max / where-iota argmin / mask extract the top-kt per row — all in
  VMEM;
- selected positions map to **global candidate ids** by a second one-hot
  contraction against the list's id row (ids < 2^24 are exact in f32),
  so the XLA side needs no post-hoc id gather.

Outputs are per-pair (values, global ids); callers scatter them into the
(P, kt) buffers by pair slot.  Rows with fewer than kt finite candidates
emit +inf values; callers map those to the -1 id sentinel (valid L2
distances are finite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.neighbors.grouped import GROUP

# extraction switches from unrolled static-lane passes to a fori_loop
# with transposed scratch above this kt (see _extract_topk)
_KT_UNROLL = 64
_KT_MAX = 128


def _scratch_shapes(kt):
    if kt <= _KT_UNROLL:
        shape = (GROUP, kt)
    else:
        shape = (-(-kt // 8) * 8, GROUP)
    return [pltpu.VMEM(shape, jnp.float32), pltpu.VMEM(shape, jnp.int32)]


def _gather_queries(slot_ref, q_ref, n_probes, P):
    """One-hot MXU row gather of the group's queries from the
    VMEM-resident table.  f32 one-hot x f32 table is EXACT (one product
    per output) — a bf16 table would round |q| before any center
    subtraction, which can exceed the residual magnitude on
    well-clustered data.  Sentinel slots gather the zero row."""
    nq_pad = q_ref.shape[0]
    slot = slot_ref[0, 0]                              # (G,) int32 pair ids
    qid = jnp.where(slot < P, slot // n_probes, nq_pad - 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (GROUP, nq_pad), 1)
    onehot = (cols == qid[:, None]).astype(jnp.float32)
    return jax.lax.dot_general(onehot, q_ref[:],
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)  # (G, d)


def _kernel(gl_ref, slot_ref, qrot_ref, cf_ref, data_ref, rsq_ref, ids_ref,
            vals_ref, ids_out_ref, vscratch, pscratch, *, kt, n_probes, P):
    qv = _gather_queries(slot_ref, qrot_ref, n_probes, P)
    sub = qv - cf_ref[0, 0][None, :]                   # (G, rot) f32
    sub_sq = jnp.sum(sub * sub, axis=1)                # (G,)
    data = data_ref[0]                                 # (cap, rot) bf16
    ip = jax.lax.dot_general(sub.astype(jnp.bfloat16), data,
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = sub_sq[:, None] + rsq_ref[0, 0][None, :] - 2.0 * ip
    d = jnp.maximum(d, 0.0)
    ids_row = ids_ref[0, 0]                            # (cap,) int32
    _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch, pscratch,
                  kt)


def _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch, pscratch,
                  kt):
    """Shared in-VMEM top-kt extraction + position -> global-id mapping.

    kt passes of max / where-iota argmin / mask over the (G, cap) block;
    the id map is a masked reduce against the list's id row per pass
    (a single (G*kt, cap) one-hot matmul would cost ~5 MB of VMEM).

    kt <= _KT_UNROLL: unrolled passes writing static scratch lanes (the
    proven hot path).  Larger kt (radix-select regime, k to 128+ —
    reference select_radix.cuh): a ``fori_loop`` with dynamic SUBLANE
    stores into (kt, G)-transposed scratch — dynamic stores on the lane
    dim are Mosaic-hostile, on the sublane dim they are cheap — then one
    in-VMEM transpose on the way out."""
    invalid = (ids_row < 0)[None, :]
    neg = jnp.where(invalid, -jnp.inf, -d)             # select-min as max

    cap = neg.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, neg.shape, 1)
    ids_f = ids_row.astype(jnp.float32)                # exact below 2^24

    def step(neg):
        m = jnp.max(neg, axis=1)                       # (G,)
        # where-iota argmax (ties -> lowest column, stable like sort)
        p = jnp.min(jnp.where(neg == m[:, None], col, cap), axis=1)
        p = jnp.minimum(p, cap - 1)                    # all -inf row guard
        sel = col == p[:, None]
        gid = jnp.max(jnp.where(sel, ids_f[None, :], -jnp.inf), axis=1)
        return m, sel, gid

    if kt <= _KT_UNROLL:
        for j in range(kt):
            m, sel, gid = step(neg)
            vscratch[:, j] = -m
            pscratch[:, j] = gid.astype(jnp.int32)
            neg = jnp.where(sel, -jnp.inf, neg)
        vals_ref[0] = vscratch[:, :]
        ids_out_ref[0] = pscratch[:, :]
    else:
        def body(j, neg):
            m, sel, gid = step(neg)
            vscratch[pl.ds(j, 1), :] = (-m)[None, :]
            pscratch[pl.ds(j, 1), :] = gid.astype(jnp.int32)[None, :]
            return jnp.where(sel, -jnp.inf, neg)

        jax.lax.fori_loop(0, kt, body, neg, unroll=False)
        vals_ref[0] = vscratch[:kt, :].T
        ids_out_ref[0] = pscratch[:kt, :].T


def _kernel_flat(gl_ref, slot_ref, q_ref, data_ref, dsq_ref, ids_ref,
                 vals_ref, ids_out_ref, vscratch, pscratch, *, kt,
                 n_probes, P):
    """IVF-Flat variant: exact fp32 distances over raw list vectors
    (d = ||q||^2 + ||x||^2 - 2 q.x), same gather/extraction structure."""
    qv = _gather_queries(slot_ref, q_ref, n_probes, P)
    q_sq = jnp.sum(qv * qv, axis=1)                    # (G,)
    data = data_ref[0]                                 # (cap, d) f32
    ip = jax.lax.dot_general(qv, data, (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    d = jnp.maximum(q_sq[:, None] + dsq_ref[0, 0][None, :] - 2.0 * ip, 0.0)
    ids_row = ids_ref[0, 0]                            # (cap,) int32
    _extract_topk(d, ids_row, vals_ref, ids_out_ref, vscratch, pscratch,
                  kt)


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "interpret"))
def grouped_l2_scan(group_list, slot_pairs, qrot, centers_f32, list_recon,
                    rec_sq, list_indices, kt, n_probes, interpret=False):
    """Fused query-gather + distance + local top-kt over all pair groups.

    ``group_list`` (n_groups,) int32; ``slot_pairs`` (n_groups, GROUP)
    int32 pair ids with P = nq * n_probes as the empty sentinel;
    ``qrot`` (nq, rot) f32 rotated queries; ``centers_f32`` (n_lists, rot)
    f32; ``list_recon`` (n_lists, cap, rot) bf16; ``rec_sq`` (n_lists,
    cap) f32; ``list_indices`` (n_lists, cap) int32.  Returns
    ``(vals (n_groups, GROUP, kt) f32, ids ... int32)`` sorted ascending
    (L2); exhausted rows carry +inf values (callers map them to -1 ids).
    """
    n_groups = group_list.shape[0]
    nq, rot = qrot.shape
    _, cap, _ = list_recon.shape
    P = nq * n_probes

    # pad the query table to a lane-friendly height; the sentinel row
    # (all zeros, index nq_pad-1) is what empty slots gather
    nq_pad = -(-(nq + 1) // 128) * 128
    qrot_pad = jnp.zeros((nq_pad, rot), jnp.float32)
    qrot_pad = qrot_pad.at[:nq].set(qrot.astype(jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((nq_pad, rot), lambda g, gl: (0, 0)),
            pl.BlockSpec((1, 1, rot), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, cap, rot), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=_scratch_shapes(kt),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel, kt=kt, n_probes=n_probes, P=P),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(group_list, slot_pairs[:, None, :], qrot_pad,
      centers_f32[:, None, :], list_recon, rec_sq[:, None, :],
      list_indices[:, None, :])
    return vals, gids


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "interpret"))
def grouped_flat_l2_scan(group_list, slot_pairs, queries_f32, list_data,
                         d_sq, list_indices, kt, n_probes, interpret=False):
    """IVF-Flat fused scan: exact fp32 distances over raw list vectors.
    Same contract as :func:`grouped_l2_scan` with ``queries_f32``
    (nq, dim) raw queries, ``list_data`` (n_lists, cap, dim) fp32 and
    ``d_sq`` (n_lists, cap) fp32 row norms."""
    n_groups = group_list.shape[0]
    nq, dim = queries_f32.shape
    _, cap, _ = list_data.shape
    P = nq * n_probes

    nq_pad = -(-(nq + 1) // 128) * 128
    q_pad = jnp.zeros((nq_pad, dim), jnp.float32)
    q_pad = q_pad.at[:nq].set(queries_f32.astype(jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((nq_pad, dim), lambda g, gl: (0, 0)),
            pl.BlockSpec((1, cap, dim), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=_scratch_shapes(kt),
    )
    vals, gids = pl.pallas_call(
        functools.partial(_kernel_flat, kt=kt, n_probes=n_probes, P=P),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(group_list, slot_pairs[:, None, :], q_pad,
      list_data.astype(jnp.float32), d_sq[:, None, :],
      list_indices[:, None, :])
    return vals, gids


def supported(metric_is_l2: bool, cap: int, rot: int, kt: int,
              nq: int, data_elem_bytes: int = 2) -> bool:
    """Shapes the kernel handles; callers fall back to the XLA scan
    otherwise.  Lane dims must be 128-aligned (rot) or tile-aligned
    (cap); kt is bounded to keep the extraction loop sane; the
    query table, its per-program one-hot, the per-list data block, and
    the (GROUP, cap) distance block all live in VMEM, so their summed
    footprint is bounded (the one-hot gather cost also grows with nq —
    larger batches should be split by the caller anyway).

    Candidate-id f32-exactness (|id| < 2^24, required by the one-hot id
    contraction) is data-dependent and checked by the caller on the
    index's actual ids (:func:`raft_tpu.neighbors.grouped.ids_f32_exact`)
    — user-supplied ids from ``extend(new_indices=...)`` can exceed any
    row-count proxy."""
    nq_pad = -(-(nq + 1) // 128) * 128
    vmem = (2 * nq_pad * rot * 4              # query table + one-hot
            + cap * rot * data_elem_bytes     # per-list data block
            + 2 * GROUP * cap * 4)            # distances + extraction temps
    return (metric_is_l2 and rot % 128 == 0 and cap % 16 == 0
            and GROUP % 16 == 0 and 0 < kt <= _KT_MAX
            and nq <= 6144 and vmem <= (10 << 20))
