"""Pallas TPU kernel: fused grouped PQ-reconstruction scan + local top-k.

The ``compute_similarity_kernel`` analogue (reference:
neighbors/detail/ivf_pq_search.cuh:611) for the grouped search layout
(:mod:`raft_tpu.neighbors.grouped`): one program per pair-group computes
the group's (GROUP, cap) quantized L2 distances on the MXU and extracts
each row's top-kt **in VMEM**, so the distance matrix never reaches HBM.

Structure per program ``g``:

- the scalar-prefetched ``group_list`` drives the BlockSpec index maps —
  the list's bf16 reconstructions, squared norms, and slot-validity ids
  are DMA'd directly by list id (the TPU equivalent of the reference
  assigning one CTA per (list, query-group));
- the group's query-residual tile (precomputed outside: ``q_rot - center``
  in fp32, cast bf16) hits the MXU against the list tile:
  ``d = ||sub||^2 + ||recon||^2 - 2 sub.recon``;
- top-kt per row by iterative max-extraction (kt passes of
  max / where-iota argmin / mask over the VMEM-resident (GROUP, cap)
  block) — the XLA path's separate sort pass and its HBM round-trip of
  the distances are folded away.

Returns per-pair values and *positions* (column within the list); callers
map positions to candidate ids with a broadcasting ``take_along_axis``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.neighbors.grouped import GROUP


def _kernel(gl_ref, sub_ref, subsq_ref, data_ref, rsq_ref, ids_ref,
            vals_ref, pos_ref, vscratch, pscratch, *, kt):
    sub = sub_ref[0]                                   # (G, rot) bf16
    data = data_ref[0]                                 # (cap, rot) bf16
    ip = jax.lax.dot_general(sub, data, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    # the 1-length middle axis keeps 2-D operands in valid TPU block
    # shapes (see grouped_l2_scan's reshapes)
    d = subsq_ref[0, 0][:, None] + rsq_ref[0, 0][None, :] - 2.0 * ip
    d = jnp.maximum(d, 0.0)
    invalid = (ids_ref[0, 0] < 0)[None, :]             # (1, cap)
    neg = jnp.where(invalid, -jnp.inf, -d)             # select-min as max

    cap = neg.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, neg.shape, 1)
    for j in range(kt):
        m = jnp.max(neg, axis=1)                       # (G,)
        # where-iota argmax (ties -> lowest column, stable like sort)
        p = jnp.min(jnp.where(neg == m[:, None], col, cap), axis=1)
        p = jnp.minimum(p, cap - 1)                    # all -inf row guard
        vscratch[:, j] = -m
        pscratch[:, j] = p
        neg = jnp.where(col == p[:, None], -jnp.inf, neg)
    vals_ref[0] = vscratch[:, :]
    pos_ref[0] = pscratch[:, :]


@functools.partial(jax.jit, static_argnames=("kt", "interpret"))
def grouped_l2_scan(group_list, sub, sub_sq, list_recon, rec_sq,
                    list_indices, kt, interpret=False):
    """Fused distance + local top-kt over all pair groups.

    ``group_list`` (n_groups,) int32; ``sub`` (n_groups, GROUP, rot) bf16;
    ``sub_sq`` (n_groups, GROUP) f32; ``list_recon`` (n_lists, cap, rot)
    bf16; ``rec_sq`` (n_lists, cap) f32; ``list_indices`` (n_lists, cap)
    int32.  Returns ``(vals (n_groups, GROUP, kt) f32, pos ... int32)``
    sorted ascending (L2).  Invalid slots carry +inf.
    """
    n_groups = group_list.shape[0]
    _, cap, rot = list_recon.shape

    # 2-D operands get a singleton middle axis: TPU block shapes must have
    # their last two dims tile-aligned or equal to the array dims, which
    # (1, len) blocks of a 2-D array violate
    sub_sq3 = sub_sq[:, None, :]
    rec_sq3 = rec_sq[:, None, :]
    ids3 = list_indices[:, None, :]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, GROUP, rot), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, cap, rot), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
            pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((GROUP, kt), jnp.float32),
            pltpu.VMEM((GROUP, kt), jnp.int32),
        ],
    )
    vals, pos = pl.pallas_call(
        functools.partial(_kernel, kt=kt),
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(group_list, sub, sub_sq3, list_recon, rec_sq3, ids3)
    return vals, pos


def supported(metric_is_l2: bool, cap: int, rot: int, kt: int) -> bool:
    """Shapes the kernel handles; callers fall back to the XLA scan
    otherwise.  Lane dim must be a full 128 multiple and the sublane dim a
    bf16 tile multiple; kt is bounded to keep the extraction loop sane."""
    return (metric_is_l2 and rot % 128 == 0 and cap % 16 == 0
            and GROUP % 16 == 0 and 0 < kt <= 64)
