"""Matmul precision policy.

The reference's distance/linalg stack computes in true fp32 (cuBLAS SGEMM /
CUTLASS fp32-accumulate — linalg/detail/gemm.hpp).  On TPU the MXU natively
multiplies bf16 and ``Precision.DEFAULT`` rounds fp32 inputs to bf16 — fast but
~1e-2 absolute error, which breaks RAFT-parity numerics.  ``HIGHEST`` runs the
6-pass fp32 emulation.

Policy: raft_tpu defaults to ``highest`` so results match the reference;
benchmarks and recall-tolerant paths (ANN search) can globally or locally opt
into faster modes.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional, Union

import jax

_NAMES = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
    "bfloat16": jax.lax.Precision.DEFAULT,
    "float32": jax.lax.Precision.HIGHEST,
}

_current = jax.lax.Precision.HIGHEST


def set_matmul_precision(name: Union[str, jax.lax.Precision]) -> None:
    """Set the global matmul precision for raft_tpu primitives."""
    global _current
    _current = _NAMES[name] if isinstance(name, str) else name


def get_matmul_precision() -> jax.lax.Precision:
    return _current


@contextlib.contextmanager
def matmul_precision(name: Union[str, jax.lax.Precision]) -> Iterator[None]:
    """Scoped override (host-side; applies to ops traced inside the block)."""
    global _current
    prev = _current
    set_matmul_precision(name)
    try:
        yield
    finally:
        _current = prev
