"""Spectral graph partitioning + modularity maximization.

Reference: cpp/include/raft/spectral/partition.cuh:52 (``partition``),
detail/partition.hpp:29-55 (Laplacian -> smallest eigenvectors -> whiten ->
k-means), partition.cuh ``analyzePartition``;
spectral/modularity_maximization.cuh:47 (``modularity_maximization``,
largest eigenvectors of the modularity matrix), :73 (``analyzeModularity``);
policy objects spectral/eigen_solvers.cuh (``eigen_solver_config_t`` /
``lanczos_solver_t``) and spectral/cluster_solvers.cuh
(``cluster_solver_config_t`` / ``kmeans_solver_t``).

TPU design: both operators stay *matrix-free* — the Laplacian is the
(off-diagonal CSR, diagonal) pair from ``sparse.linalg.laplacian`` and the
modularity matrix is a rank-one-corrected adjacency spmv, so the Lanczos
solver only ever sees a matvec closure (one spmv + one (m, n) panel matmul
per step — MXU-friendly, no n x n materialization).  The eigen/cluster
solver *policy objects* of the reference are kept verbatim so downstream
callers can swap solvers.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.cluster.kmeans_types import KMeansParams
from raft_tpu.core.error import expects
from raft_tpu.sparse.formats import CooMatrix, coo_to_csr
from raft_tpu.sparse.linalg import laplacian, laplacian_spmv, spmv
from raft_tpu.sparse.solver import eigsh_largest, eigsh_smallest


# ---------------------------------------------------------------------------
# Solver policy objects (reference: eigen_solvers.cuh / cluster_solvers.cuh)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EigenSolverConfig:
    """Reference: spectral/eigen_solvers.cuh ``eigen_solver_config_t``."""

    n_eig_vecs: int
    max_iter: int = 100
    restart_iter: int = 0          # 0 == auto ncv
    tol: float = 1e-4
    reorthogonalize: bool = True   # always on in this implementation
    seed: int = 1234567


class LanczosSolver:
    """Reference: spectral/eigen_solvers.cuh ``lanczos_solver_t``.

    Wraps the thick-restart Lanczos in ``sparse.solver`` behind the
    reference's policy interface.
    """

    def __init__(self, config: EigenSolverConfig):
        self._config = config

    @property
    def config(self) -> EigenSolverConfig:
        return self._config

    def solve_smallest_eigenvectors(
        self, res, matvec: Callable[[jax.Array], jax.Array], n: int
    ) -> Tuple[jax.Array, jax.Array]:
        c = self._config
        return eigsh_smallest(
            res, None, c.n_eig_vecs, matvec=matvec, n=n,
            ncv=c.restart_iter or 0, max_restarts=c.max_iter, tol=c.tol,
            seed=c.seed)

    def solve_largest_eigenvectors(
        self, res, matvec: Callable[[jax.Array], jax.Array], n: int
    ) -> Tuple[jax.Array, jax.Array]:
        c = self._config
        return eigsh_largest(
            res, None, c.n_eig_vecs, matvec=matvec, n=n,
            ncv=c.restart_iter or 0, max_restarts=c.max_iter, tol=c.tol,
            seed=c.seed)


@dataclasses.dataclass
class ClusterSolverConfig:
    """Reference: spectral/cluster_solvers.cuh ``cluster_solver_config_t``."""

    n_clusters: int
    max_iter: int = 100
    tol: float = 1e-4
    seed: int = 123456


class KMeansSolver:
    """Reference: spectral/cluster_solvers.cuh ``kmeans_solver_t``."""

    def __init__(self, config: ClusterSolverConfig):
        self._config = config

    @property
    def config(self) -> ClusterSolverConfig:
        return self._config

    def solve(self, res, embedding: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """k-means on the (n, n_eig_vecs) spectral embedding.
        Returns (labels, residual)."""
        from raft_tpu.cluster import kmeans
        c = self._config
        params = KMeansParams(n_clusters=c.n_clusters, max_iter=c.max_iter,
                              tol=c.tol, seed=c.seed, n_init=3)
        labels, _, inertia, _ = kmeans.fit_predict(res, params, embedding)
        return labels, inertia


# ---------------------------------------------------------------------------
# Embedding helpers
# ---------------------------------------------------------------------------

def _whiten(vecs: jax.Array) -> jax.Array:
    """Reference: detail/spectral_util.cuh ``transform_eigen_matrix`` —
    mean-center and unit-variance each eigenvector column before k-means."""
    mu = jnp.mean(vecs, axis=0, keepdims=True)
    sd = jnp.std(vecs, axis=0, keepdims=True)
    return (vecs - mu) / jnp.maximum(sd, 1e-12)


def _scale_obs(vecs: jax.Array) -> jax.Array:
    """Reference: detail/spectral_util.cuh ``scale_obs`` — row-normalize
    observations (used by modularity maximization)."""
    nrm = jnp.linalg.norm(vecs, axis=1, keepdims=True)
    return vecs / jnp.maximum(nrm, 1e-12)


def fit_embedding(res, adj: CooMatrix, n_components: int, *,
                  normalized: bool = False, max_iter: int = 100,
                  tol: float = 1e-4, seed: int = 1234567) -> jax.Array:
    """Smallest-eigenvector Laplacian embedding (n, n_components).

    Reference: sparse/linalg/spectral.cuh ``fit_embedding`` (the sparse
    spectral-embedding entry point used by cuML TSNE/UMAP).
    """
    n = adj.shape[0]
    off, diag = laplacian(adj, normalized=normalized)
    mv = lambda x: laplacian_spmv(off, diag, x)  # noqa: E731
    _, vecs = eigsh_smallest(res, None, n_components, matvec=mv, n=n,
                             max_restarts=max_iter, tol=tol, seed=seed)
    return vecs


# ---------------------------------------------------------------------------
# Partition (min-balanced-cut flavor)
# ---------------------------------------------------------------------------

def partition(
    res,
    adj: CooMatrix,
    eigen_solver: LanczosSolver,
    cluster_solver: KMeansSolver,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Spectral min-cost partition of a weighted undirected graph.

    Pipeline (reference detail/partition.hpp:29-55): Laplacian L = D - A ->
    smallest ``n_eig_vecs`` eigenpairs -> whiten eigenvectors -> k-means.
    Returns ``(clusters (n,), eig_vals (k,), eig_vecs (n, k), residual)``.
    """
    expects(adj.shape[0] == adj.shape[1], "partition: adjacency must be square")
    n = adj.shape[0]
    off, diag = laplacian(adj, normalized=False)
    mv = lambda x: laplacian_spmv(off, diag, x)  # noqa: E731
    eig_vals, eig_vecs = eigen_solver.solve_smallest_eigenvectors(res, mv, n)
    emb = _whiten(eig_vecs)
    clusters, residual = cluster_solver.solve(res, emb)
    return clusters, eig_vals, eig_vecs, residual


def analyze_partition(
    res, adj: CooMatrix, n_clusters: int, clusters: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Edge cut + balanced-cut cost of a partition.

    Reference: spectral/partition.cuh ``analyzePartition`` /
    detail/partition.hpp:120-180 — per cluster i with indicator x_i,
    ``partEdgesCut = x_i^T L x_i`` (the cut weight between cluster i and the
    rest), ``cost = sum_i partEdgesCut_i / |C_i|``, ``edgeCut = sum_i / 2``.
    Vectorized: one one-hot (n, k) matmul against the Laplacian instead of
    the reference's per-cluster indicator loop.
    """
    n = adj.shape[0]
    off, diag = laplacian(adj, normalized=False)
    onehot = jax.nn.one_hot(clusters, n_clusters, dtype=jnp.float32)  # (n, k)
    # L @ onehot column-by-column via the (off-diag, diag) operator
    lx = jax.vmap(lambda col: laplacian_spmv(off, diag, col),
                  in_axes=1, out_axes=1)(onehot)
    part_cut = jnp.sum(onehot * lx, axis=0)               # (k,) x^T L x
    sizes = jnp.sum(onehot, axis=0)
    cost = jnp.sum(jnp.where(sizes > 0, part_cut / jnp.maximum(sizes, 1), 0))
    edge_cut = jnp.sum(part_cut) / 2.0
    return edge_cut, cost


# ---------------------------------------------------------------------------
# Modularity maximization
# ---------------------------------------------------------------------------

def _modularity_matvec(adj_csr, degree: jax.Array, total_w: jax.Array):
    """B x = A x - (d . x / sum_w) d — the rank-one-corrected spmv of the
    reference's ``modularity_matrix_t`` (spectral/matrix_wrappers.hpp)."""
    def mv(x):
        return spmv(adj_csr, x) - (jnp.dot(degree, x) / total_w) * degree
    return mv


def _modularity_operator(adj: CooMatrix):
    """Shared setup for modularity clustering and scoring: degree vector
    (sentinel padding rows masked), total weight, and the B-matvec closure.
    Returns ``(mv, total_w)``."""
    n = adj.shape[0]
    csr = coo_to_csr(adj)
    d = jax.ops.segment_sum(
        jnp.where(adj.rows < n, adj.vals.astype(jnp.float32), 0),
        jnp.minimum(adj.rows, n - 1).astype(jnp.int32), num_segments=n)
    total_w = jnp.maximum(jnp.sum(d), 1e-30)
    return _modularity_matvec(csr, d, total_w), total_w


def modularity_maximization(
    res,
    adj: CooMatrix,
    eigen_solver: LanczosSolver,
    cluster_solver: KMeansSolver,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Spectral modularity clustering.

    Reference: spectral/modularity_maximization.cuh:47 — largest
    eigenvectors of the modularity matrix B = A - d d^T / (2m), whiten,
    row-scale (``scale_obs``), then k-means.
    Returns ``(clusters, eig_vals, eig_vecs, residual)``.
    """
    n = adj.shape[0]
    mv, _ = _modularity_operator(adj)
    eig_vals, eig_vecs = eigen_solver.solve_largest_eigenvectors(res, mv, n)
    emb = _scale_obs(_whiten(eig_vecs))
    clusters, residual = cluster_solver.solve(res, emb)
    return clusters, eig_vals, eig_vecs, residual


def analyze_modularity(
    res, adj: CooMatrix, n_clusters: int, clusters: jax.Array
) -> jax.Array:
    """Modularity Q of a clustering.

    Reference: spectral/modularity_maximization.cuh:73
    ``analyzeModularity`` — Q = (1/2m) sum_i x_i^T B x_i over cluster
    indicators x_i.
    """
    mv, total_w = _modularity_operator(adj)
    onehot = jax.nn.one_hot(clusters, n_clusters, dtype=jnp.float32)
    bx = jax.vmap(mv, in_axes=1, out_axes=1)(onehot)
    return jnp.sum(onehot * bx) / total_w
