"""Spectral graph partitioning (reference: cpp/include/raft/spectral/)."""

from raft_tpu.spectral.partition import (
    ClusterSolverConfig,
    EigenSolverConfig,
    KMeansSolver,
    LanczosSolver,
    analyze_modularity,
    analyze_partition,
    fit_embedding,
    modularity_maximization,
    partition,
)

__all__ = [
    "ClusterSolverConfig",
    "EigenSolverConfig",
    "KMeansSolver",
    "LanczosSolver",
    "analyze_modularity",
    "analyze_partition",
    "fit_embedding",
    "modularity_maximization",
    "partition",
]
