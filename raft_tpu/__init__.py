"""raft_tpu — a TPU-native framework with the capabilities of RAPIDS RAFT.

Built from scratch on JAX/XLA/Pallas/pjit. The reference (RAPIDS RAFT, CUDA) is a
library of accelerated primitives for data science / ML: dense & sparse linear
algebra, pairwise distances, nearest-neighbor search (brute-force, IVF-Flat,
IVF-PQ, CAGRA), clustering, statistics, random generation, solvers, and a
multi-node communicator fabric.  raft_tpu reproduces that capability surface
idiomatically for TPU:

- compute primitives are pure functions over ``jax.Array`` (XLA fuses them);
- bespoke kernels (fused L2 1-NN, PQ-LUT scan, large-k select) are Pallas;
- the reference's ``raft::resources`` handle (cpp/include/raft/core/resources.hpp)
  becomes :class:`raft_tpu.core.Resources` carrying devices, mesh, PRNG state and
  comms;
- the reference's NCCL/UCX ``comms_t`` (cpp/include/raft/core/comms.hpp) becomes
  a comms abstraction over XLA collectives on a ``jax.sharding.Mesh`` (ICI/DCN).
"""

from raft_tpu import config  # noqa: F401
from raft_tpu import observability  # noqa: F401
from raft_tpu import integrity  # noqa: F401
from raft_tpu.core import (  # noqa: F401
    Resources,
    DeviceResources,
    RaftError,
    expects,
)
from raft_tpu.core.outputs import auto_convert_output  # noqa: F401

__version__ = "0.2.0"
