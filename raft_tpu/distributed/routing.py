"""Load-aware replica routing for the routed (``by_list``) path.

PR 17's replicated placement made replica choice *data*: the routed
dispatch reads a pair of host-side ``(n_lists,)`` tables (owner, slot)
and swapping them is a zero-recompile update.  Until now only failover
and hedging ever swapped them — healthy traffic always paid replica
rank 0, so ``replication_factor=r`` cost ``r×`` memory and returned
nothing on the healthy path.  This module is the policy that makes the
replicas pay rent:

**Replica-rank selection as data.**  :meth:`RoutingPolicy.plan` builds
effective routing tables for one query batch by walking the lists in
descending expected-probe-weight order and assigning each list to the
live owner (across all ``r`` ranks) with the lowest *load score*,
accumulating the assigned weight as it goes — greedy LPT over the
replica ranks.  The per-shard load score is::

    score[s] = ewma_rows[s] * (1 + w_q * queue_depth + w_p * p99_ms)
               + w_pen * load_penalty[s]

where ``ewma_rows`` is an EWMA of the probe rows this policy planned
onto each shard (in-flight work), ``queue_depth`` / ``p99_ms`` come
from the windowed serving telemetry (the PR 5/11 instruments
``serving.queue_depth`` and ``serving.latency.exec``), and
``load_penalty`` is the health tracker's per-shard overload demotion
(:meth:`~raft_tpu.distributed.health.HealthTracker.note_overload` —
score demotion, never binary up/down).  At full probe any live
assignment is **bit-identical** to rank 0: the k-bounded merge's
exactness argument is per *list* (a global top-k candidate is in the
local top-k of whichever shard serves its list), and replica copies
are identical rows.

**Probe-frequency accumulation without host syncs.**  The routed
dispatch hands every batch's per-list probe histogram (computed
in-graph from the replicated coarse routing — identical on all shards)
to :meth:`observe_probes`, which only *retains the lazy device array*.
Nothing is materialized on the dispatch path; :meth:`refresh` — called
from maintenance cadence (rebalancer tick, bench calibration), never
from a hot dispatch — folds the pending arrays into a rotating window
of per-list probe counts.  :meth:`expected_probe_load` exposes the
decayed window as a normalized per-list probe rate: the heat that
:func:`raft_tpu.serving.rebalancer.rebalance_routed` feeds into the
LPT recompute (balance by *expected probe load*, not just live rows)
and that :meth:`plan` uses to weight its greedy assignment.

**Per-bucket replica groups.**  :meth:`spread_bucket` is the
bucket→replica-group map the serving executor consults per
``(bucket, k)``: hot buckets (small batch, high QPS) route
data-parallel across all ``r`` ranks; memory-bound large-batch buckets
pin ``by_list`` at the primary.  The selection happens when the
executor builds its warmed fn table, so the AOT/executable cache key
is untouched.

Every score mutation lands in ONE method
(:meth:`RoutingPolicy._fold_load_scores`) that routes overload
evidence through the health tracker — the seam graftlint's
``health-transition`` rule 3 enforces (no ad-hoc load-score writes
outside the tracker/publish discipline).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.core.error import expects


@dataclasses.dataclass
class RoutingConfig:
    """Knobs for the load score and the probe-heat window.  The weights
    convert the telemetry terms into the score's row units (see the
    module docstring formula); defaults are deliberately mild — with no
    telemetry and no penalties the policy degenerates to pure greedy
    LPT over the replica ranks, which is already the throughput win."""

    #: EWMA factor folding each plan's per-shard assigned rows into the
    #: in-flight estimate (higher = reacts faster, flaps easier)
    ewma_alpha: float = 0.3
    #: score multiplier per queued row (``serving.queue_depth`` gauge)
    queue_depth_weight: float = 0.0005
    #: score multiplier per millisecond of windowed exec p99
    p99_weight: float = 0.01
    #: rows added to the score per unit of tracker load penalty
    penalty_rows: float = 1024.0
    #: shards whose EWMA rows exceed this multiple of the mean report
    #: overload evidence to the health tracker
    overload_factor: float = 2.0
    #: probe-heat rotating window: number of refresh slots retained
    window_slots: int = 8
    #: per-slot decay when summing the window (newest slot weight 1.0)
    window_decay: float = 0.7
    #: max un-refreshed device histograms retained (oldest dropped —
    #: bounds device memory if maintenance stalls; no host sync either
    #: way)
    max_pending: int = 64
    #: bucket→replica-group map: buckets at/below this row count are
    #: "hot" and spread across all replica ranks; larger (memory-bound)
    #: buckets pin at the primary
    hot_bucket_rows: int = 64

    def validate(self) -> "RoutingConfig":
        expects(0.0 < self.ewma_alpha <= 1.0,
                "routing: ewma_alpha must be in (0, 1]")
        expects(self.window_slots >= 1,
                "routing: window_slots must be >= 1")
        expects(0.0 < self.window_decay <= 1.0,
                "routing: window_decay must be in (0, 1]")
        expects(self.max_pending >= 1,
                "routing: max_pending must be >= 1")
        expects(self.overload_factor >= 1.0,
                "routing: overload_factor must be >= 1")
        expects(self.hot_bucket_rows >= 0,
                "routing: hot_bucket_rows must be >= 0")
        return self


class RoutingPolicy:
    """Load-aware replica-rank selection + probe-heat accumulation.

    Thread-safe: plans run on the search path (under the executor's
    dispatch), observations arrive from the same path, refresh/heat
    reads come from maintenance threads.  All state is host-side numpy
    behind one lock — the device program never sees the policy, only
    the tables it emits (replica choice is data, not shape)."""

    def __init__(self, n_shards: int,
                 config: Optional[RoutingConfig] = None, *,
                 tracker=None) -> None:
        expects(n_shards >= 1, "routing: n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.config = (config or RoutingConfig()).validate()
        self.tracker = tracker
        self._lock = threading.Lock()
        #: EWMA of planned per-shard probe rows (the in-flight term).
        #: Annotated = declaration: mutations happen ONLY in
        #: _fold_load_scores (graftlint health-transition rule 3)
        self._load_score_rows: np.ndarray = np.zeros(
            self.n_shards, np.float64)
        #: per-list live row counts (host; fed at build/swap) — the
        #: rows half of the expected-work weight
        self._list_rows: Optional[np.ndarray] = None
        #: lazy device histograms awaiting refresh (never materialized
        #: on the dispatch path)
        self._pending: List = []
        #: rotating window of refreshed per-list probe counts (host)
        self._window: List[np.ndarray] = []
        #: summary of the last plan (the ``distributed.replica_choice``
        #: event payload)
        self._last_choice: Dict[str, object] = {}

    # ---- per-bucket replica groups --------------------------------------

    def spread_bucket(self, bucket: int) -> bool:
        """The bucket→replica-group map: True when ``bucket`` should
        route data-parallel across all replica ranks (hot, small-batch,
        QPS-bound); False pins ``by_list`` at the primary (memory-bound
        large batch — spreading it only doubles its working set)."""
        return int(bucket) <= self.config.hot_bucket_rows

    # ---- probe-frequency window (dispatch: lazy; refresh: host) ---------

    def observe_probes(self, hist) -> None:
        """Retain one batch's per-list probe histogram.  ``hist`` is a
        device array straight off the routed dispatch — appending keeps
        the reference WITHOUT materializing it (the no-host-sync
        contract of the steady-state path; :meth:`refresh` pays the
        readback later, off the dispatch path)."""
        with self._lock:
            self._pending.append(hist)
            if len(self._pending) > self.config.max_pending:
                self._pending.pop(0)

    def refresh(self) -> int:
        """Materialize the pending histograms into one rotating-window
        slot; returns the number of batches folded.  Maintenance-path
        only (rebalancer tick / bench calibration) — this is the single
        place probe counters touch the host."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return 0
        total: Optional[np.ndarray] = None
        for h in pending:
            a = np.asarray(h, np.float64)
            total = a if total is None else total + a
        with self._lock:
            self._window.append(total)
            while len(self._window) > self.config.window_slots:
                self._window.pop(0)
        return len(pending)

    def expected_probe_load(self) -> Optional[np.ndarray]:
        """Decayed per-list probe rate from the window, normalized to
        sum 1 — the measured heat the rebalancer's LPT recompute and
        :meth:`plan` weight by.  None before the first refresh."""
        with self._lock:
            window = list(self._window)
        if not window:
            return None
        decay = self.config.window_decay
        acc = np.zeros_like(window[-1])
        w = 1.0
        for slot in reversed(window):
            acc = acc + w * slot
            w *= decay
        s = float(acc.sum())
        if s <= 0.0:
            return None
        return acc / s

    def note_list_rows(self, rows) -> None:
        """Install the per-list *per-probe scan cost* (host numpy;
        from the placement build / swap).  The plan weight for list
        ``g`` is ``probe_rate[g] * rows[g]``.  For the routed padded
        scans every probe touches the full ``(cap,)`` slot row
        regardless of live rows, so callers on that path (the serving
        executor, ``rebalance_routed``) feed the slab capacity —
        uniform, which reduces the weight to pure measured heat; a
        cost model that does scale with live rows (e.g. a future
        compacted scan) can feed those instead."""
        rows = np.asarray(rows, np.float64).reshape(-1)
        with self._lock:
            self._list_rows = rows

    # ---- the load score -------------------------------------------------

    def shard_scores(self) -> np.ndarray:
        """The per-shard load score (row units) — the formula in the
        module docstring.  Telemetry terms read the windowed registry
        instruments only while collection is enabled; with observability
        off they contribute nothing (the EWMA term alone still spreads
        load)."""
        qd = 0.0
        p99 = 0.0
        from raft_tpu import observability as obs
        if obs.enabled():
            reg = obs.registry()
            qd = float(reg.gauge("serving.queue_depth").value)
            hist = reg.histogram("serving.latency.exec").windowed_dict()
            p99 = float(hist.get("p99") or 0.0) * 1e3  # s -> ms
        pressure = (1.0 + self.config.queue_depth_weight * qd
                    + self.config.p99_weight * p99)
        with self._lock:
            rows = self._load_score_rows.copy()
        scores = rows * pressure
        if self.tracker is not None:
            pen = getattr(self.tracker, "load_penalties", None)
            if pen is not None:
                scores = scores + self.config.penalty_rows * np.asarray(
                    pen(), np.float64)
        return scores

    def _fold_load_scores(self, planned_rows: np.ndarray) -> None:
        # THE load-score mutation site: every plan folds its per-shard
        # assigned rows into the EWMA here, and overload evidence goes
        # out through the health tracker — never an ad-hoc table write
        # (graftlint health-transition rule 3)
        a = self.config.ewma_alpha
        overloaded: List[Tuple[int, float]] = []
        with self._lock:
            self._load_score_rows = ((1.0 - a) * self._load_score_rows
                                     + a * planned_rows)
            mean = float(self._load_score_rows.mean())
            if mean > 0.0:
                bar = self.config.overload_factor * mean
                for s in range(self.n_shards):
                    if self._load_score_rows[s] > bar:
                        overloaded.append(
                            (s, float(self._load_score_rows[s] / mean)))
        if self.tracker is not None:
            for s, ratio in overloaded:
                self.tracker.note_overload(s, ratio)

    # ---- the plan -------------------------------------------------------

    def _list_weights(self, n_lists: int) -> np.ndarray:
        heat = self.expected_probe_load()
        with self._lock:
            rows = self._list_rows
        if heat is None or heat.shape[0] != n_lists:
            heat = np.full(n_lists, 1.0 / n_lists)
        if rows is None or rows.shape[0] != n_lists:
            rows = np.ones(n_lists)
        return heat * rows

    def plan(self, placement, down: Sequence[int] = ()
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Effective ``(owner, slot)`` routing tables for one batch.

        Greedy LPT over the replica ranks: lists in descending expected
        probe weight, each assigned to the live owner with the smallest
        accumulated score.  Shards in ``down`` are excluded; a list all
        of whose owners are down keeps its rank-0 primary — the same
        contract as :meth:`Placement.healthy_routing`, so the search
        path's residual/covered bookkeeping composes unchanged.  A
        hedged straggler's lists therefore re-issue to the
        *least-loaded* covering replica, not blindly the lowest rank.

        Both returned arrays are host numpy shaped exactly like the
        primary tables: swapping them into the dispatch is a data
        change only (zero recompiles)."""
        owners, slots = placement.rank_tables()
        r, n_lists = owners.shape
        expects(placement.n_shards == self.n_shards,
                f"routing: policy sized for {self.n_shards} shards, "
                f"placement has {placement.n_shards}")
        eff_owner = placement.owner.copy()
        eff_slot = placement.local_slot.copy()
        downset = {int(s) for s in down}
        expects(all(0 <= s < self.n_shards for s in downset),
                f"routing: down shard ids {sorted(downset)} out of range "
                f"for {self.n_shards} shards")
        weights = self._list_weights(n_lists)
        scores = self.shard_scores()
        assigned = scores.copy()
        planned = np.zeros(self.n_shards, np.float64)
        if r > 1:
            order = np.argsort(-weights, kind="stable")
            for g in order:
                cand = [j for j in range(r)
                        if int(owners[j, g]) not in downset]
                if not cand:
                    continue  # uncovered: keep the rank-0 primary —
                    # the degraded-masking path owns it
                j = min(cand, key=lambda jj: assigned[int(owners[jj, g])])
                s = int(owners[j, g])
                eff_owner[g] = s
                eff_slot[g] = int(slots[j, g])
                assigned[s] += weights[g]
                planned[s] += weights[g]
        else:
            np.add.at(planned, eff_owner, weights)
        # row-normalize the fold so the EWMA term is in actual row
        # units when list rows are known, probe-share units otherwise
        self._fold_load_scores(planned)
        # anti-co-location makes the rank of each choice unambiguous
        per_rank = [int(np.sum(eff_owner == owners[j]))
                    for j in range(r)]
        with self._lock:
            self._last_choice = {
                "scores": [round(float(v), 3) for v in scores],
                "per_rank_lists": per_rank,
                "per_shard_lists": np.bincount(
                    eff_owner, minlength=self.n_shards).tolist(),
                "down": sorted(downset),
            }
        return eff_owner, eff_slot

    def ack_plan(self, placement, down: Sequence[int] = (), *,
                 lists: Optional[Sequence[int]] = None
                 ) -> Dict[int, List[int]]:
        """Write-side companion of :meth:`plan` (round 19, distributed
        ingest): for each global list, the ORDERED live owner shards the
        write path appends to and gates the ack on.

        Every live owner still receives the record (replication is not
        optional); the ORDER decides which owner is the list's *ack
        leader* (first entry — classified as the ``ingest.dist.append``
        site; the rest are ``ingest.dist.replicate``) and, under a
        partial quorum ``w < r``, which owners' durability the ack
        prefers to wait on.  Ordering is replica-rank order re-ranked by
        the live load score (least-loaded first, shard id as the tie
        break), so a write-heavy shard sheds ack-leadership the same way
        the read plan sheds probes.  Shards in ``down`` are excluded
        entirely — a FAILED shard has no write eligibility; a list with
        an empty entry has lost ALL its replicas and the caller must
        refuse the write with a typed ``Unavailable``.

        ``lists`` restricts the plan to the touched lists (the write
        batch's routed home lists) — the per-write cost is then
        O(touched x r), never O(n_lists)."""
        owners, _ = placement.rank_tables()
        r, n_lists = owners.shape
        expects(placement.n_shards == self.n_shards,
                f"routing: policy sized for {self.n_shards} shards, "
                f"placement has {placement.n_shards}")
        downset = {int(s) for s in down}
        scores = self.shard_scores()
        targets = (range(n_lists) if lists is None
                   else [int(g) for g in lists])
        out: Dict[int, List[int]] = {}
        for g in targets:
            live = [int(owners[j, g]) for j in range(r)
                    if int(owners[j, g]) not in downset]
            live.sort(key=lambda s: (float(scores[s]), s))
            out[int(g)] = live
        return out

    def choice_summary(self) -> Dict[str, object]:
        """The last plan's decision record — chosen per-rank/per-shard
        list counts plus the scores they were chosen against (the
        ``distributed.replica_choice`` event payload)."""
        with self._lock:
            return dict(self._last_choice)

    def stats(self) -> Dict[str, object]:
        """Point-in-time policy snapshot for ops/bench."""
        with self._lock:
            return {
                "ewma_rows": self._load_score_rows.tolist(),
                "pending_batches": len(self._pending),
                "window_slots": len(self._window),
                "last_choice": dict(self._last_choice),
            }
