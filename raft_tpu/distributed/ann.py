"""Multi-device (MNMG) IVF-PQ: sharded build + search-with-merge.

The reference ships the seam, not the algorithm: row-sharded ANN with
per-part search and a top-k merge (``knn_merge_parts``,
neighbors/brute_force.cuh:80; the ANN bench's ``multigpu`` option,
docs/source/cuda_ann_benchmarks.md:163; CAGRA's explicit multi-GPU chunking,
detail/cagra/graph_core.cuh:333-369).  raft_tpu provides the full algorithm:

- **build**: rows are split across the mesh axis; each shard trains its own
  local IVF-PQ index over its rows (ids pre-offset to global), and the local
  indexes are stacked leaf-wise into one device-sharded pytree — shard i's
  leaves live on device i (``P(axis)`` on the stacked axis).
- **search**: one ``shard_map`` — every device searches its local shard with
  the single-chip kernel (queries replicated), then an ``all_gather`` of the
  (q, k) candidates (tiny payload over ICI) and a replicated merge-select.

This is the same shard → local select_k → all_gather → merge shape as
:mod:`raft_tpu.distributed.knn`, applied to the compressed index.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.compat import shard_map
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.resilience import faults
from raft_tpu.resilience import retry as _retry

P = jax.sharding.PartitionSpec


def _entry(site, fn, retry_policy, deadline):
    """Run an entry point under retry/deadline with a host-side fault
    site checked per attempt (jit caching never skips it, unlike the
    trace-time ``comms.*`` sites)."""
    def attempt():
        faults.maybe_fail(site)
        return fn()
    return _retry.retry_call(attempt, site=site, policy=retry_policy,
                             deadline=deadline)


def _degraded_set(n_shards: int, failed_shards: Sequence[int]
                  ) -> Tuple[int, ...]:
    """Union of caller-flagged shards and the active fault plan's
    ``fail_shards``, clipped to range and sorted (a static jit key)."""
    flagged = {int(s) for s in failed_shards if 0 <= int(s) < n_shards}
    return tuple(sorted(flagged | set(faults.failed_shards(n_shards))))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DistributedIndex:
    """Leaf-stacked local IVF-PQ indexes: every leaf carries a leading
    mesh-axis dimension (n_dev, ...) sharded one shard per device."""

    centers: jax.Array        # (n_dev, n_lists, rot_dim)
    codebooks: jax.Array
    list_codes: jax.Array     # (n_dev, n_lists, cap, pq_dim)
    list_indices: jax.Array   # (n_dev, n_lists, cap) — GLOBAL ids
    list_sizes: jax.Array
    rotation: jax.Array       # (n_dev, dim, rot_dim)
    list_recon: jax.Array     # (n_dev, n_lists, cap, rot_dim) bf16
    metric: int = DistanceType.L2Expanded
    size: int = 0
    # per-shard recall canaries (tuple of integrity.CanarySet / None) —
    # host-side metadata, NOT a pytree leaf, so jax transforms drop it;
    # build / health_check carry it explicitly
    shard_canaries: Optional[tuple] = None

    @property
    def n_shards(self) -> int:
        return self.centers.shape[0]

    def tree_flatten(self):
        return ((self.centers, self.codebooks, self.list_codes,
                 self.list_indices, self.list_sizes, self.rotation,
                 self.list_recon), (self.metric, self.size))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], size=aux[1])


def build(handle, params: ivf_pq.IndexParams, dataset, *,
          retry_policy: Optional[_retry.RetryPolicy] = None,
          deadline: Optional[_retry.Deadline] = None) -> DistributedIndex:
    """Shard rows over the handle's mesh and build one local index per
    shard (ids globally offset).  ``params.n_lists`` is per shard.

    PER_SUBSPACE builds run as ONE two-phase ``shard_map`` — every
    shard's k-means, codebook training and encoding execute SPMD across
    the mesh simultaneously, with a single tiny host sync (the global
    max list size) between encoding and list packing.  The round-3
    host loop built shards one after another — 8x the build latency on
    a v5e-8 for no reason (VERDICT r3).  Other codebook kinds and
    mesocluster-scale n_lists fall back to the sequential per-shard
    loop.

    Transient faults at entry (site ``distributed.ann.build``) are
    retried under ``retry_policy`` / ``deadline``.
    """
    return _entry("distributed.ann.build",
                  lambda: _build_impl(handle, params, dataset),
                  retry_policy, deadline)


def _build_impl(handle, params: ivf_pq.IndexParams,
                dataset) -> DistributedIndex:
    with named_range("distributed::ivf_pq_build"):
        expects(handle.comms_initialized(),
                "distributed.ann.build: handle has no comms (use "
                "CommsSession.worker_handle())")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n, n_dev, per, devs = _shard_layout(
            handle, dataset)
        expects(params.cache_reconstructions,
                "distributed.ann: the sharded search kernel runs the "
                "reconstruction path; cache_reconstructions must be True")

        from raft_tpu.cluster import kmeans_balanced as kb

        if (params.codebook_kind == ivf_pq.CodebookKind.PER_SUBSPACE
                and params.n_lists < kb._MESO_THRESHOLD
                and params.n_lists <= per
                and params.add_data_on_build
                # canaries need per-shard exact ground truth, which only
                # the sequential per-shard build computes
                and params.canary_queries == 0):
            return _build_spmd(handle, params, dataset, mesh, axis, n,
                               n_dev, per)

        locals_ = []
        for s in range(n_dev):
            shard = dataset[s * per:(s + 1) * per]
            idx = ivf_pq.build(handle, params, shard)
            # globalize ids: local slot ids are 0..per-1 over the shard
            idx.list_indices = jnp.where(
                idx.list_indices >= 0, idx.list_indices + s * per, -1)
            locals_.append(idx)

        cap = max(ix.capacity for ix in locals_)

        def pad_cap(a, fill):
            return jnp.pad(a, ((0, 0), (0, cap - a.shape[1]))
                           + ((0, 0),) * (a.ndim - 2),
                           constant_values=fill)

        per_shard_leaves = [
            (ix.centers, ix.codebooks, pad_cap(ix.list_codes, 0),
             pad_cap(ix.list_indices, -1), ix.list_sizes, ix.rotation,
             pad_cap(ix.list_recon, 0))
            for ix in locals_]

        placed = _stack_leaves(per_shard_leaves, mesh, axis, devs)
        out = DistributedIndex.tree_unflatten(
            (params.metric, n), tuple(placed))
        out.shard_canaries = _collect_canaries(locals_, per,
                                               offset_ids=True)
        return out


def _stack_leaves(per_shard_leaves, mesh, axis, devs):
    """Assemble (n_dev, ...) stacked leaves from per-device shards —
    never materializing the full stack on one device, whose HBM the
    full index may not fit (the regime MNMG sharding exists for)."""
    n_dev = len(per_shard_leaves)
    placed = []
    for li in range(len(per_shard_leaves[0])):
        shards = [jax.device_put(per_shard_leaves[s][li][None],
                                 devs[s]) for s in range(n_dev)]
        shape = (n_dev,) + per_shard_leaves[0][li].shape
        sharding = jax.sharding.NamedSharding(
            mesh, P(axis, *([None] * (len(shape) - 1))))
        placed.append(jax.make_array_from_single_device_arrays(
            shape, sharding, shards))
    return placed


def _build_spmd(handle, params: ivf_pq.IndexParams, dataset, mesh, axis,
                n, n_dev, per) -> DistributedIndex:
    """Two-phase SPMD build (see :func:`build`).

    Phase A (per shard, no collectives): coarse balanced k-means,
    per-subspace codebooks, encode + bit-pack, per-list counts.
    Host: one (n_dev, n_lists) readback picks the global static list
    capacity.  Phase B: pack lists + decode the bf16 recon cache.
    """
    from raft_tpu.cluster import kmeans_balanced as kb
    from raft_tpu.neighbors.ivf_flat import _LIST_ALIGN, _pack_lists

    dim = dataset.shape[1]
    pq_dim = params.pq_dim or max(dim // 4, 1)
    rot_dim = ivf_pq._round_up(dim, pq_dim)
    rotation = ivf_pq._make_rotation(
        dim, rot_dim, params.force_random_rotation or rot_dim != dim,
        seed=7)
    n_train = min(per, max(params.n_lists,
                           int(per * params.kmeans_trainset_fraction)))
    n_lists = params.n_lists
    book = 1 << params.pq_bits
    base_key = handle.next_key()

    def spec(ndim):
        return P(axis, *([None] * (ndim - 1)))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(axis), P()),
        out_specs=(spec(3), spec(4), spec(3), spec(2), spec(2)),
        check_vma=False)
    def phase_a(shard, rot):
        s = jax.lax.axis_index(axis)
        k1, k2 = jax.random.split(jax.random.fold_in(base_key, s))
        xf = shard.astype(jnp.float32) @ rot
        stride_t = max(per // n_train, 1)
        train = xf[::stride_t][:n_train]
        stride_c = max(n_train // n_lists, 1)
        c0 = train[::stride_c][:n_lists]
        centers, labels_t = kb._balanced_loop(
            train, c0, k1, n_lists, params.kmeans_n_iters, params.metric)
        resid_t = ivf_pq._subspace_split(train - centers[labels_t], pq_dim)
        books = ivf_pq._train_books_per_subspace(
            jnp.transpose(resid_t, (1, 0, 2)), jax.random.split(k2, pq_dim),
            book, params.kmeans_n_iters)
        labels, _ = kb._assign(xf, centers, params.metric)
        resid = ivf_pq._subspace_split(xf - centers[labels], pq_dim)
        codes = ivf_pq._pack_codes(
            ivf_pq._encode(books, resid, params.codebook_kind, labels),
            params.pq_bits)
        sizes = jax.ops.segment_sum(jnp.ones(per, jnp.int32), labels,
                                    num_segments=n_lists)
        return (centers[None], books[None], codes[None], labels[None],
                sizes[None])

    centers_a, books_a, codes_a, labels_a, sizes_a = phase_a(
        dataset, rotation)

    # the ONE host sync: global static list capacity
    capacity = ivf_pq._round_up(
        max(int(jnp.max(sizes_a)), _LIST_ALIGN), _LIST_ALIGN)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec(3), spec(4), spec(3), spec(2)),
        out_specs=(spec(4), spec(3), spec(2), spec(4)),
        check_vma=False)
    def phase_b(centers, books, codes, labels):
        s = jax.lax.axis_index(axis)
        gids = (s * per + jnp.arange(per)).astype(jnp.int32)
        lc, li, sz = _pack_lists(codes[0], labels[0], gids, n_lists,
                                 capacity)
        recon = ivf_pq._decode_lists(centers[0], books[0], lc,
                                     params.codebook_kind, pq_dim,
                                     params.pq_bits)
        return lc[None], li[None], sz[None], recon[None]

    list_codes, list_indices, list_sizes, list_recon = phase_b(
        centers_a, books_a, codes_a, labels_a)

    rot_stack = jax.device_put(
        jnp.broadcast_to(rotation[None], (n_dev,) + rotation.shape),
        jax.sharding.NamedSharding(mesh, P(axis, None, None)))
    return DistributedIndex.tree_unflatten(
        (params.metric, n),
        (centers_a, books_a, list_codes, list_indices, list_sizes,
         rot_stack, list_recon))


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric",
                                             "axis_name", "mesh", "failed"))
def _dist_search(index_leaves, queries, k, n_probes, metric, axis_name,
                 mesh, failed=()):
    # only the leaves the recon search kernel consumes are threaded through
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in index_leaves)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(specs, P()), out_specs=(P(), P()),
                       check_vma=False)
    def run(leaves, q):
        centers, list_indices, rotation, list_recon = leaves
        ld, li = ivf_pq._search_impl_recon(
            centers[0], list_recon[0], list_indices[0], rotation[0], q,
            k, n_probes, metric)
        select_min = metric != DistanceType.InnerProduct
        if failed:
            # degraded mode: a failed shard contributes only sentinel
            # candidates, so the replicated merge ranks every live
            # shard's hits first and pads the tail with id -1.  `failed`
            # is a static jit key — the no-fault compiled path is
            # byte-identical to before this feature existed.
            s = jax.lax.axis_index(axis_name)
            bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
            sentinel = jnp.inf if select_min else -jnp.inf
            ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
            li = jnp.where(bad, jnp.full_like(li, -1), li)
        all_d = jax.lax.all_gather(ld, axis_name)   # (n_dev, q, k)
        all_i = jax.lax.all_gather(li, axis_name)
        nq = q.shape[0]
        return select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)

    return run(index_leaves, queries)


def search(handle, params: ivf_pq.SearchParams, index: DistributedIndex,
           queries, k: int, *,
           failed_shards: Sequence[int] = (),
           return_status: bool = False,
           retry_policy: Optional[_retry.RetryPolicy] = None,
           deadline: Optional[_retry.Deadline] = None):
    """Sharded search + merge; returns replicated (distances, global ids)
    of shape (q, k).

    Degraded mode: shards listed in ``failed_shards`` (or flagged by the
    active fault plan's ``fail_shards``) are masked out of the merge —
    the query still answers with the live shards' top-k, the tail padded
    with ``(inf, -1)`` when fewer than ``k`` live candidates exist.
    With ``return_status=True`` a third output is appended: an
    ``(n_shards,)`` int8 vector, 1 = healthy / 0 = failed-and-skipped.
    Transient faults at entry (site ``distributed.ann.search``) are
    retried under ``retry_policy`` / ``deadline``.

    ``params.scan_mode`` threading: the shard-local scan runs *inside*
    ``shard_map``, where the grouped Pallas kernels (including the fused
    in-kernel top-k) cannot dispatch — their group construction is
    batch-data-dependent and host-driven.  Every mode therefore lowers
    to the traceable probe-order recon scan here; results are identical
    in ranking semantics.  An explicit ``scan_mode="fused"`` request is
    accepted but ticks the ``ivf_pq.search.fused_fallback`` counter so
    operators can see the sharded path did not hit the fused kernel.
    """
    with named_range("distributed::ivf_pq_search"):
        expects(handle.comms_initialized(),
                "distributed.ann.search: handle has no comms")
        mode = getattr(params, "scan_mode", "auto")
        expects(mode in ivf_pq._SCAN_MODES,
                f"distributed.ann.search: unknown scan_mode {mode!r}")
        if mode == "fused":
            from raft_tpu import observability as obs
            if obs.enabled():
                obs.registry().counter(
                    "ivf_pq.search.fused_fallback").inc()
        comms = handle.get_comms()
        queries = ensure_array(queries, "queries")
        n_probes = min(params.n_probes, index.centers.shape[1])
        leaves = (index.centers, index.list_indices, index.rotation,
                  index.list_recon)
        failed = _degraded_set(index.n_shards, failed_shards)
        d, i = _entry(
            "distributed.ann.search",
            lambda: _dist_search(leaves, queries, int(k), n_probes,
                                 index.metric, comms.axis_name,
                                 handle.mesh, failed=failed),
            retry_policy, deadline)
        if not return_status:
            return d, i
        status = np.ones(index.n_shards, np.int8)
        status[list(failed)] = 0
        return d, i, jnp.asarray(status)


def delete(handle, index: DistributedIndex, ids, *,
           retry_policy: Optional[_retry.RetryPolicy] = None,
           deadline: Optional[_retry.Deadline] = None) -> DistributedIndex:
    """Tombstone delete over the sharded index (ids are GLOBAL).

    One sharding-preserving elementwise rewrite of the stacked
    ``list_indices`` leaf — matching slots flip to the tombstone
    encoding (see :mod:`raft_tpu.neighbors.mutate`), which the
    shard-local recon scan already masks (it keeps ``>= 0`` slots only).
    Every other leaf is shared with the parent; the returned snapshot is
    generation-bumped.  Transient faults at entry (site
    ``distributed.ann.delete``) are retried under ``retry_policy`` /
    ``deadline``."""
    return _entry("distributed.ann.delete",
                  lambda: _delete_impl(index, ids), retry_policy, deadline)


def _delete_impl(index: DistributedIndex, ids) -> DistributedIndex:
    with named_range("distributed::ivf_pq_delete"):
        ids = ensure_array(ids, "ids")
        expects(ids.ndim == 1, "distributed.ann.delete: 1-D ids required")
        new_li, _ = _mutate.tombstone(index.list_indices, ids)
        leaves, aux = index.tree_flatten()
        leaves = list(leaves)
        leaves[3] = new_li
        out = DistributedIndex.tree_unflatten(aux, tuple(leaves))
        out.shard_canaries = index.shard_canaries
        _mutate.next_generation(index, out)
        return out


# ---------------------------------------------------------------------------
# IVF-Flat (same shard -> local search -> all_gather -> merge seam)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DistributedFlatIndex:
    """Leaf-stacked local IVF-Flat indexes (one shard per device)."""

    centers: jax.Array        # (n_dev, n_lists, dim)
    list_data: jax.Array      # (n_dev, n_lists, cap, dim)
    list_indices: jax.Array   # (n_dev, n_lists, cap) — GLOBAL ids
    list_sizes: jax.Array
    metric: int = DistanceType.L2Expanded
    size: int = 0
    # per-shard recall canaries — host-side, not a pytree leaf
    shard_canaries: Optional[tuple] = None

    @property
    def n_shards(self) -> int:
        return self.centers.shape[0]

    def tree_flatten(self):
        return ((self.centers, self.list_data, self.list_indices,
                 self.list_sizes), (self.metric, self.size))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], size=aux[1])


def _shard_layout(handle, dataset):
    comms = handle.get_comms()
    mesh = handle.mesh
    axis = comms.axis_name
    expects(mesh.devices.ndim == 1,
            "distributed.ann: a 1-D mesh is required (reshape 2D grids "
            "to the data axis for index sharding)")
    n = dataset.shape[0]
    n_dev = mesh.shape[axis]
    expects(n % n_dev == 0,
            f"distributed.ann: n ({n}) must divide evenly over "
            f"{n_dev} devices (pad the input)")
    return comms, mesh, axis, n, n_dev, n // n_dev, mesh.devices.ravel()


def build_flat(handle, params, dataset, *,
               retry_policy: Optional[_retry.RetryPolicy] = None,
               deadline: Optional[_retry.Deadline] = None
               ) -> DistributedFlatIndex:
    """Shard rows over the mesh and build one local IVF-Flat index per
    shard, ids globally offset (the ANN bench ``multigpu`` seam,
    docs/source/cuda_ann_benchmarks.md:163, for raft_ivf_flat)."""
    return _entry("distributed.ann.build_flat",
                  lambda: _build_flat_impl(handle, params, dataset),
                  retry_policy, deadline)


def _build_flat_impl(handle, params, dataset) -> DistributedFlatIndex:
    from raft_tpu.neighbors import ivf_flat

    with named_range("distributed::ivf_flat_build"):
        expects(handle.comms_initialized(),
                "distributed.ann.build_flat: handle has no comms")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n, n_dev, per, devs = _shard_layout(
            handle, dataset)

        locals_ = []
        for s in range(n_dev):
            idx = ivf_flat.build(handle, params, dataset[s * per:(s + 1) * per])
            idx.list_indices = jnp.where(
                idx.list_indices >= 0, idx.list_indices + s * per, -1)
            locals_.append(idx)
        cap = max(ix.capacity for ix in locals_)

        def pad_cap(a, fill):
            return jnp.pad(a, ((0, 0), (0, cap - a.shape[1]))
                           + ((0, 0),) * (a.ndim - 2),
                           constant_values=fill)

        leaves = [(ix.centers, pad_cap(ix.list_data, 0),
                   pad_cap(ix.list_indices, -1), ix.list_sizes)
                  for ix in locals_]
        placed = _stack_leaves(leaves, mesh, axis, devs)
        out = DistributedFlatIndex.tree_unflatten(
            (params.metric, n), tuple(placed))
        out.shard_canaries = _collect_canaries(locals_, per,
                                               offset_ids=True)
        return out


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric",
                                             "axis_name", "mesh", "failed"))
def _dist_search_flat(leaves, queries, k, n_probes, metric, axis_name,
                      mesh, failed=()):
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in leaves)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(specs, P()), out_specs=(P(), P()),
                       check_vma=False)
    def run(lv, q):
        from raft_tpu.neighbors import ivf_flat
        centers, list_data, list_indices, _ = lv
        ld, li = ivf_flat._search_impl(centers[0], list_data[0],
                                       list_indices[0], q, k, n_probes,
                                       metric)
        select_min = metric != DistanceType.InnerProduct
        if failed:
            s = jax.lax.axis_index(axis_name)
            bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
            sentinel = jnp.inf if select_min else -jnp.inf
            ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
            li = jnp.where(bad, jnp.full_like(li, -1), li)
        all_d = jax.lax.all_gather(ld, axis_name)
        all_i = jax.lax.all_gather(li, axis_name)
        nq = q.shape[0]
        return select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)

    return run(leaves, queries)


def search_flat(handle, params, index: DistributedFlatIndex, queries,
                k: int, *,
                failed_shards: Sequence[int] = (),
                return_status: bool = False,
                retry_policy: Optional[_retry.RetryPolicy] = None,
                deadline: Optional[_retry.Deadline] = None):
    """Sharded IVF-Flat search + merge; replicated (distances, ids).
    Same degraded-mode / retry contract as :func:`search`."""
    with named_range("distributed::ivf_flat_search"):
        expects(handle.comms_initialized(),
                "distributed.ann.search_flat: handle has no comms")
        comms = handle.get_comms()
        queries = ensure_array(queries, "queries")
        n_probes = min(params.n_probes, index.centers.shape[1])
        leaves = (index.centers, index.list_data, index.list_indices,
                  index.list_sizes)
        failed = _degraded_set(index.n_shards, failed_shards)
        d, i = _entry(
            "distributed.ann.search_flat",
            lambda: _dist_search_flat(leaves, queries, int(k), n_probes,
                                      index.metric, comms.axis_name,
                                      handle.mesh, failed=failed),
            retry_policy, deadline)
        if not return_status:
            return d, i
        status = np.ones(index.n_shards, np.int8)
        status[list(failed)] = 0
        return d, i, jnp.asarray(status)


# ---------------------------------------------------------------------------
# CAGRA (reference's explicit multi-GPU seam: per-GPU graph chunks +
# merged search, detail/cagra/graph_core.cuh:333-369)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DistributedCagraIndex:
    """Per-shard CAGRA graphs + packed walk tables, leaf-stacked.  Ids
    inside each shard's graph/table are LOCAL (0..per-1); search maps
    them to global ids with the shard offset.  ``use_walk=False`` (walk
    fidelity calibration failed, or the per-shard table exceeds the
    byte gate — the same routes single-device ``cagra.search`` takes)
    stores (1, 1)-placeholder walk leaves and searches via the exact
    direct walk over ``graph``."""

    dataset: jax.Array        # (n_dev, per, dim)
    graph: jax.Array          # (n_dev, per, deg)
    table: jax.Array          # (n_dev, per, W) int16 packed neighborhoods
    proj: jax.Array           # (n_dev, dim, pdim)
    entry_proj: jax.Array     # (n_dev, S, pdim) bf16
    entry_sq: jax.Array       # (n_dev, S)
    entry_ids: jax.Array      # (n_dev, S) int32 LOCAL
    metric: int = DistanceType.L2Expanded
    size: int = 0
    use_walk: bool = True
    # per-shard recall canaries — host-side, not a pytree leaf; CAGRA
    # shard ids stay LOCAL, so these carry local ground-truth ids
    shard_canaries: Optional[tuple] = None

    @property
    def n_shards(self) -> int:
        return self.dataset.shape[0]

    def tree_flatten(self):
        return ((self.dataset, self.graph, self.table, self.proj,
                 self.entry_proj, self.entry_sq, self.entry_ids),
                (self.metric, self.size, self.use_walk))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], size=aux[1], use_walk=aux[2])


def build_cagra(handle, params, dataset, *,
                retry_policy: Optional[_retry.RetryPolicy] = None,
                deadline: Optional[_retry.Deadline] = None
                ) -> DistributedCagraIndex:
    """Shard rows over the mesh and build one local CAGRA graph + packed
    walk table per shard (reference: graph_core.cuh:333-369 builds the
    kNN graph in per-GPU chunks; here each shard also serves its own
    walk).  A single projection dim (calibrated on shard 0) is forced on
    every shard so the packed tables stack; when calibration fails
    (pdim 0) or the per-shard table exceeds the byte gate, the index
    falls back to the exact direct walk — the same two routes
    single-device ``cagra.search`` takes."""
    return _entry("distributed.ann.build_cagra",
                  lambda: _build_cagra_impl(handle, params, dataset),
                  retry_policy, deadline)


def _build_cagra_impl(handle, params, dataset) -> DistributedCagraIndex:
    from raft_tpu.neighbors import cagra

    with named_range("distributed::cagra_build"):
        expects(handle.comms_initialized(),
                "distributed.ann.build_cagra: handle has no comms")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n, n_dev, per, devs = _shard_layout(
            handle, dataset)

        locals_, shard_idxs, pdim, use_walk = [], [], None, True
        for s in range(n_dev):
            idx = cagra.build(handle, params, dataset[s * per:(s + 1) * per])
            shard_idxs.append(idx)
            if pdim is None:
                pdim = cagra._auto_pdim(idx)
                use_walk = (pdim > 0 and cagra._table_bytes(
                    per, idx.graph_degree, pdim, False)
                    <= cagra._WALK_TABLE_MAX_BYTES)
            if use_walk:
                cache = cagra._walk_cache(handle, idx, pdim, 4096)
                walk_leaves = (cache.table, cache.proj, cache.entry_proj,
                               cache.entry_sq, cache.entry_ids)
            else:
                walk_leaves = (jnp.zeros((1, 1), jnp.int16),
                               jnp.zeros((1, 1), jnp.float32),
                               jnp.zeros((1, 1), jnp.bfloat16),
                               jnp.zeros((1,), jnp.float32),
                               jnp.zeros((1,), jnp.int32))
            locals_.append((idx.dataset, idx.graph) + walk_leaves)
        placed = _stack_leaves(locals_, mesh, axis, devs)
        out = DistributedCagraIndex.tree_unflatten(
            (params.metric, n, use_walk), tuple(placed))
        # CAGRA shard ids are local: ground truth needs no offset
        out.shard_canaries = _collect_canaries(shard_idxs, per,
                                               offset_ids=False)
        return out


@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric", "rerank",
    "deg", "axis_name", "mesh", "use_walk", "n_samplings"))
def _dist_search_cagra(leaves, queries, seed_key, k, itopk, search_width,
                       max_iterations, metric, rerank, deg, axis_name,
                       mesh, use_walk, n_samplings=1):
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in leaves)
    select_min = metric != DistanceType.InnerProduct

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(specs, P(), P()), out_specs=(P(), P()),
                       check_vma=False)
    def run(lv, q, skey):
        from raft_tpu.neighbors import cagra
        ds, graph, table, proj, ep, esq, eids = lv
        per = ds.shape[1]
        s = jax.lax.axis_index(axis_name)
        if use_walk:
            d, i = cagra._search_impl_walk(
                ds[0], table[0], ep[0], esq[0], eids[0], proj[0], q, k,
                itopk, search_width, max_iterations, metric, rerank, deg)
        else:
            # same seed-count formula as single-device cagra.search
            n_seeds = max(itopk,
                          min(per, max(n_samplings * 4 * itopk, 128)))
            seed_ids = jax.random.randint(
                jax.random.fold_in(skey, s), (q.shape[0], n_seeds), 0,
                per, dtype=jnp.int32)
            d, i = cagra._search_impl(ds[0], graph[0], q, seed_ids, k,
                                      itopk, search_width,
                                      max_iterations, metric)
        i = jnp.where(i >= 0, i + s * per, -1)
        all_d = jax.lax.all_gather(d, axis_name)
        all_i = jax.lax.all_gather(i, axis_name)
        nq = q.shape[0]
        return select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)

    return run(leaves, queries, seed_key)


def search_cagra(handle, params, index: DistributedCagraIndex, queries,
                 k: int, *,
                 retry_policy: Optional[_retry.RetryPolicy] = None,
                 deadline: Optional[_retry.Deadline] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Sharded CAGRA walk + merge; replicated (distances, global ids).
    Transient faults at entry (site ``distributed.ann.search_cagra``)
    are retried — the seed key is drawn once, so a retried query
    answers identically."""
    with named_range("distributed::cagra_search"):
        expects(handle.comms_initialized(),
                "distributed.ann.search_cagra: handle has no comms")
        comms = handle.get_comms()
        queries = ensure_array(queries, "queries")
        itopk = max(params.itopk_size, k)
        max_iter = params.max_iterations or (
            10 + itopk // max(params.search_width, 1))
        rerank = min(itopk, params.rerank_topk or max(32, 2 * k))
        rerank = max(rerank, k)
        deg = index.graph.shape[2]
        leaves = (index.dataset, index.graph, index.table, index.proj,
                  index.entry_proj, index.entry_sq, index.entry_ids)
        seed_key = handle.next_key()
        return _entry(
            "distributed.ann.search_cagra",
            lambda: _dist_search_cagra(
                leaves, queries, seed_key, int(k), itopk,
                params.search_width, max_iter, index.metric, rerank, deg,
                comms.axis_name, handle.mesh, index.use_walk,
                n_samplings=max(params.num_random_samplings, 1)),
            retry_policy, deadline)


# ---------------------------------------------------------------------------
# per-shard recall-canary health checks (raft_tpu.integrity)
# ---------------------------------------------------------------------------

def _collect_canaries(shard_indexes, per, *, offset_ids):
    """Gather per-shard CanarySets off the local indexes.  ``offset_ids``
    globalizes the stored ground-truth ids to match the stacked leaves'
    id space (IVF shards store GLOBAL ids; CAGRA shards stay local)."""
    cans = [getattr(ix, "canaries", None) for ix in shard_indexes]
    if all(c is None for c in cans):
        return None
    out = []
    for s, cs in enumerate(cans):
        if cs is not None and offset_ids and s > 0:
            cs = dataclasses.replace(cs, gt_ids=cs.gt_ids + s * per)
        out.append(cs)
    return tuple(out)


def _local_index(index, s):
    """Reassemble shard ``s`` as a single-device index (a leaf slice —
    the stacked layout is exactly the local index layout plus a leading
    shard axis)."""
    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq
    if isinstance(index, DistributedIndex):
        return ivf_pq.Index(
            centers=index.centers[s], codebooks=index.codebooks[s],
            list_codes=index.list_codes[s],
            list_indices=index.list_indices[s],
            list_sizes=index.list_sizes[s], rotation=index.rotation[s],
            metric=index.metric, list_recon=index.list_recon[s])
    if isinstance(index, DistributedFlatIndex):
        return ivf_flat.Index(
            centers=index.centers[s], list_data=index.list_data[s],
            list_indices=index.list_indices[s],
            list_sizes=index.list_sizes[s], metric=index.metric)
    if isinstance(index, DistributedCagraIndex):
        return cagra.Index(dataset=index.dataset[s], graph=index.graph[s],
                           metric=index.metric)
    raise TypeError(
        f"distributed.ann.health_check: unsupported index type "
        f"{type(index).__name__}")


def health_check(handle, index, *, raise_on_fail: bool = True):
    """Re-search every shard's stored recall canaries and compare against
    the stored floor (see :func:`raft_tpu.integrity.health_check`).

    Returns a list with one :class:`~raft_tpu.integrity.CanaryReport`
    (or ``None``) per shard, or ``None`` when the index carries no
    canaries.  With ``raise_on_fail`` (default) the first failing shard
    raises :class:`~raft_tpu.integrity.IntegrityError` — the error names
    the shard in its message."""
    from raft_tpu.integrity import IntegrityError
    from raft_tpu.integrity import canary as _canary
    cans = getattr(index, "shard_canaries", None)
    if cans is None:
        return None
    reports = []
    for s, cs in enumerate(cans):
        if cs is None:
            reports.append(None)
            continue
        local = _local_index(index, s)
        local.canaries = cs
        try:
            reports.append(_canary.health_check(
                handle, local, raise_on_fail=raise_on_fail))
        except IntegrityError as e:
            raise IntegrityError(f"shard {s}: {e}",
                                 invariant=e.invariant,
                                 coord=(s,) + tuple(e.coord or ())) from e
    return reports
