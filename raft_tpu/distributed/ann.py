"""Multi-device (MNMG) IVF-PQ: sharded build + search-with-merge.

The reference ships the seam, not the algorithm: row-sharded ANN with
per-part search and a top-k merge (``knn_merge_parts``,
neighbors/brute_force.cuh:80; the ANN bench's ``multigpu`` option,
docs/source/cuda_ann_benchmarks.md:163; CAGRA's explicit multi-GPU chunking,
detail/cagra/graph_core.cuh:333-369).  raft_tpu provides the full algorithm:

- **build**: rows are split across the mesh axis; each shard trains its own
  local IVF-PQ index over its rows (ids pre-offset to global), and the local
  indexes are stacked leaf-wise into one device-sharded pytree — shard i's
  leaves live on device i (``P(axis)`` on the stacked axis).
- **search**: one ``shard_map`` — every device searches its local shard with
  the single-chip kernel (queries replicated), then an ``all_gather`` of the
  (q, k) candidates (tiny payload over ICI) and a replicated merge-select.

This is the same shard → local select_k → all_gather → merge shape as
:mod:`raft_tpu.distributed.knn`, applied to the compressed index.

Two placements coexist (round 8):

- ``placement="by_row"`` (the original data-parallel mode above): every
  shard scans its whole local index for every query — per-chip scan work
  is constant in the chip count.
- ``placement="by_list"`` (index-parallel, :class:`RoutedIndex`): ONE
  global coarse quantizer, replicated on every chip, with the IVF lists
  partitioned across shards balanced by live list size
  (:func:`compute_placement`).  Search *routes* each query's ``n_probes``
  probe set: a shard scans only the probed lists it owns (unowned probes
  lower to an always-empty dummy list slot — the same ``id < 0`` /
  worst-distance padded-row path tombstones ride, zero kernel changes),
  then the k-bounded candidate exchange — per-shard local top-k,
  fixed-size ``all_gather`` of (q, k) pairs, replicated
  ``grouped.finalize_topk`` merge — replaces the full-index gather.
  Per-chip candidate work drops by ~``n_shards`` at identical results:
  any global top-k candidate is in its owning shard's local top-k, so
  the routed search is exactly the single-index search.

Scan formulations under ``shard_map`` (round 10): group construction is
now fully traceable at a static capacity
(:func:`raft_tpu.neighbors.grouped.group_capacity`), so the grouped and
fused scans lower under ``shard_map`` for both placements —
``scan_mode="fused"`` runs the same formulation ladder the single-index
search picks (fused Pallas kernels on TPU, the XLA grouped twin
elsewhere) instead of the pre-round-10 blanket lowering to the
probe-order recon scan.  :func:`_resolve_scan_mode` is the host-side
resolution table; :data:`SHARD_OK_FALLBACK` now marks only the genuinely
unsupported combinations (e.g. ``recon8`` — no stacked int8 cache — or
code modes on an index without PQ metadata).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core.compat import shard_map
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.filters import bitset as _fbits
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors import grouped
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.observability import flight as _flight
from raft_tpu.observability import trace as _rtrace
from raft_tpu.ops import vmem_budget as vb
from raft_tpu.resilience import faults
from raft_tpu.resilience import retry as _retry

P = jax.sharding.PartitionSpec

# per-shard status codes (the ``return_status=True`` vector).  OK_FALLBACK
# marks a LIVE shard whose requested ``scan_mode`` has no distributed
# formulation and was lowered to the probe-order recon scan — since
# round 10 the exception, not the rule (fused/grouped scans lower under
# ``shard_map`` at the static group capacity; results are correct either
# way, only the formulation differs).  REPLICA_SERVED marks a shard that
# did not answer (failed, or hedged around as a straggler) but whose
# owned lists were scanned by healthy replicas — results are COMPLETE,
# the code is routing telemetry, not a degradation signal.
SHARD_FAILED = 0
SHARD_OK = 1
SHARD_OK_FALLBACK = 2
SHARD_REPLICA_SERVED = 3


def _entry(site, fn, retry_policy, deadline):
    """Run an entry point under retry/deadline with a host-side fault
    site checked per attempt (jit caching never skips it, unlike the
    trace-time ``comms.*`` sites)."""
    def attempt():
        faults.maybe_fail(site)
        return fn()
    return _retry.retry_call(attempt, site=site, policy=retry_policy,
                             deadline=deadline)


def _degraded_set(n_shards: int, failed_shards: Sequence[int]
                  ) -> Tuple[int, ...]:
    """Union of caller-flagged shards and the active fault plan's
    ``fail_shards``, clipped to range and sorted (a static jit key)."""
    flagged = {int(s) for s in failed_shards if 0 <= int(s) < n_shards}
    return tuple(sorted(flagged | set(faults.failed_shards(n_shards))))


def _status_vector(n_shards: int, failed: Tuple[int, ...],
                   lowered: bool,
                   replica_served: Tuple[int, ...] = ()) -> jax.Array:
    """(n_shards,) int8 per-shard status: failed shards report
    :data:`SHARD_FAILED`; shards whose owned lists replicas covered
    (failover or a hedged read) report :data:`SHARD_REPLICA_SERVED`;
    live shards report :data:`SHARD_OK_FALLBACK` when the requested scan
    mode was lowered, else :data:`SHARD_OK`."""
    status = np.full(n_shards,
                     SHARD_OK_FALLBACK if lowered else SHARD_OK, np.int8)
    status[list(failed)] = SHARD_FAILED
    status[list(replica_served)] = SHARD_REPLICA_SERVED
    return jnp.asarray(status)


@dataclasses.dataclass(frozen=True)
class _ScanResolution:
    """Host-side static resolution of the shard-local scan formulation.

    ``form`` is one of ``probe_recon`` (probe-order recon scan — the
    pre-round-10 universal formulation), ``grouped_recon`` (XLA grouped
    scan at static capacity — the same twin the single-index fused
    ladder lands on off-TPU), ``fused_recon`` / ``fused_codes`` (the
    Pallas fused kernels, TPU only) or ``lut`` (the traceable LUT
    formulation, data-parallel only).  ``lowered`` marks a genuine
    fallback (status :data:`SHARD_OK_FALLBACK`); ``n_groups`` is the
    static group capacity for the grouped forms; ``exact`` False arms
    the in-graph overflow count (calibrated capacity only);
    ``use_pallas`` gates the non-fused Pallas group kernel inside
    ``grouped_recon``."""

    form: str
    lowered: bool
    n_groups: int = 0
    exact: bool = True
    kt: int = 0
    use_pallas: bool = False
    # fused merge window W (ops.vmem_budget), resolved host-statically
    # alongside the form so the jitted dispatch carries it as a static
    # argument; 0 for the non-fused forms
    merge_window: int = 0


def _note_lowered(mode: str) -> None:
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter("distributed.ann.scan_mode_lowered").inc()
        if mode == "fused":
            obs.registry().counter("ivf_pq.search.fused_fallback").inc()
    rec = _rtrace.current()
    _flight.record_event("distributed.scan_mode_lowered",
                         trace_id=rec.trace_id if rec else None,
                         requested=mode)


def _note_fused_fallback(reason: str = "backend") -> None:
    """Fused requested but the Pallas kernel gates failed: the XLA
    grouped twin runs instead (same ladder as single-index; NOT a
    distributed lowering, so the status vector stays SHARD_OK).
    ``reason`` carries the same codes as the single-index path
    (ivf_pq._search_checked.note_fused_fallback): kernel reject codes
    ("dtype" / "k-too-large" / "bucket-too-wide" / "itopk-gate") or
    "backend" for off-TPU / non-f32-id misses."""
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter("ivf_pq.search.fused_fallback").inc()
        obs.registry().counter(
            f"ivf_pq.search.fused_fallback.reason.{reason}").inc()
    rec = _rtrace.current()
    _flight.record_event("ivf_pq.fused_fallback", reason=reason,
                         trace_id=rec.trace_id if rec else None)


def _resolve_scan_mode(params, index, nq: int, n_probes: int,
                       k: int) -> _ScanResolution:
    """Resolve ``params.scan_mode`` to the distributed formulation that
    runs inside ``shard_map`` — the support matrix docs/api.md
    ("Distributed search") documents.  Everything here is host-static
    (shapes, flags, calibrated estimate), so the jitted dispatch below
    carries the decision as static arguments and the request path does
    no device sync."""
    mode = getattr(params, "scan_mode", "auto")
    expects(mode in ivf_pq._SCAN_MODES,
            f"distributed.ann.search: unknown scan_mode {mode!r}")
    on_tpu = jax.default_backend() == "tpu"
    kt_req = int(getattr(params, "per_probe_topk", 0) or 0)
    routed = isinstance(index, RoutedIndex)
    want_fused = mode == "fused" or (mode == "auto" and on_tpu)

    if routed:
        if mode in ("lut", "codes", "recon8"):
            # routed shards carry no raw packed codes and no int8 recon
            # cache — the documented FALLBACK exception
            _note_lowered(mode)
            return _ScanResolution("probe_recon", lowered=True)
        if not want_fused:
            return _ScanResolution("probe_recon", lowered=False)
        slots = index.local_centers.shape[1]
        cap = index.capacity
        rot = index.rotation.shape[1]
        kt = min(kt_req or k, cap)
        n_groups, exact = grouped.group_capacity(
            nq, n_probes, slots, est=getattr(index, "group_est", 0.0))
        metric_l2 = index.metric in ivf_pq._L2_METRICS
        mw_req = vb.merge_window_request(
            getattr(params, "merge_window", "auto"))
        if on_tpu:
            from raft_tpu.ops import pq_code_scan_pallas as pcs
            from raft_tpu.ops import pq_group_scan_pallas as pqp
            ids_ok = grouped.ids_f32_exact(index, index.list_indices)
            if (index.list_code_lanes is not None
                    and index.list_code_rsq is not None
                    and index.codebooks is not None and index.pq_bits
                    and ids_ok and metric_l2
                    and pcs.supported_fused_codes(
                        True, True, cap, rot, kt, k, nq,
                        index.codebooks.shape[0], index.pq_bits,
                        merge_window=mw_req)):
                # the 72 B/row headline: per-shard fused code scan
                return _ScanResolution(
                    "fused_codes", lowered=False, n_groups=n_groups,
                    exact=exact, kt=kt,
                    merge_window=pcs.fused_codes_merge_window(
                        cap, rot, kt, k, nq, index.codebooks.shape[0],
                        index.pq_bits, requested=mw_req))
            if ids_ok and pqp.supported_fused(metric_l2, cap, rot, kt,
                                              k, nq,
                                              merge_window=mw_req):
                return _ScanResolution(
                    "fused_recon", lowered=False, n_groups=n_groups,
                    exact=exact, kt=kt,
                    merge_window=pqp.fused_merge_window(
                        cap, rot, kt, k, nq, requested=mw_req))
            if mode == "fused":
                _note_fused_fallback(
                    (pqp.fused_reject_reason(metric_l2, cap, rot, kt, k,
                                             nq, merge_window=mw_req)
                     or "bucket-too-wide") if ids_ok else "backend")
            return _ScanResolution("grouped_recon", lowered=False,
                                   n_groups=n_groups, exact=exact, kt=kt,
                                   use_pallas=ids_ok)
        if mode == "fused":
            _note_fused_fallback("backend")
        return _ScanResolution("grouped_recon", lowered=False,
                               n_groups=n_groups, exact=exact, kt=kt)

    # data-parallel (by_row): per-shard local index, worst-bound
    # capacity only (exact regime — no overflow machinery in the jit)
    n_lists_local = index.centers.shape[1]
    cap = index.list_recon.shape[2]
    rot = index.rotation.shape[2]
    kt = min(kt_req or k, cap)
    if mode in ("lut", "codes"):
        if getattr(index, "pq_bits", 0):
            # the traceable LUT twin computes the same quantized
            # distance the codes kernel streams; on TPU a codes request
            # is still a formulation downgrade (no lane-packed leaves in
            # the stacked pytree), so report the lowering there
            lowered = mode == "codes" and on_tpu
            if lowered:
                _note_lowered(mode)
            return _ScanResolution("lut", lowered=lowered, kt=kt)
        _note_lowered(mode)  # legacy stacked pytree without PQ metadata
        return _ScanResolution("probe_recon", lowered=True)
    if mode == "recon8":
        _note_lowered(mode)  # no stacked int8 recon cache
        return _ScanResolution("probe_recon", lowered=True)
    if not want_fused:
        return _ScanResolution("probe_recon", lowered=False)
    n_groups, _ = grouped.group_capacity(nq, n_probes, n_lists_local)
    mw_req = vb.merge_window_request(
        getattr(params, "merge_window", "auto"))
    if on_tpu:
        from raft_tpu.ops import pq_group_scan_pallas as pqp
        metric_l2 = index.metric in ivf_pq._L2_METRICS
        ids_ok = grouped.ids_f32_exact(index, index.list_indices)
        if ids_ok and pqp.supported_fused(metric_l2, cap, rot, kt, k, nq,
                                          merge_window=mw_req):
            return _ScanResolution(
                "fused_recon", lowered=False, n_groups=n_groups, kt=kt,
                merge_window=pqp.fused_merge_window(cap, rot, kt, k, nq,
                                                    requested=mw_req))
        if mode == "fused":
            _note_fused_fallback(
                (pqp.fused_reject_reason(metric_l2, cap, rot, kt, k, nq,
                                         merge_window=mw_req)
                 or "bucket-too-wide") if ids_ok else "backend")
        return _ScanResolution("grouped_recon", lowered=False,
                               n_groups=n_groups, kt=kt, use_pallas=ids_ok)
    if mode == "fused":
        _note_fused_fallback("backend")
    return _ScanResolution("grouped_recon", lowered=False,
                           n_groups=n_groups, kt=kt)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DistributedIndex:
    """Leaf-stacked local IVF-PQ indexes: every leaf carries a leading
    mesh-axis dimension (n_dev, ...) sharded one shard per device."""

    centers: jax.Array        # (n_dev, n_lists, rot_dim)
    codebooks: jax.Array
    list_codes: jax.Array     # (n_dev, n_lists, cap, pq_dim)
    list_indices: jax.Array   # (n_dev, n_lists, cap) — GLOBAL ids
    list_sizes: jax.Array
    rotation: jax.Array       # (n_dev, dim, rot_dim)
    list_recon: jax.Array     # (n_dev, n_lists, cap, rot_dim) bf16
    metric: int = DistanceType.L2Expanded
    size: int = 0
    # static PQ metadata (round 10): lets the sharded search run the
    # traceable LUT formulation for codes/lut scan modes instead of
    # lowering to probe-order recon.  Zero on legacy stacked pytrees,
    # which keep the pre-round-10 fallback.
    pq_bits: int = 0
    codebook_kind: int = 0
    # per-shard recall canaries (tuple of integrity.CanarySet / None) —
    # host-side metadata, NOT a pytree leaf, so jax transforms drop it;
    # build / health_check carry it explicitly
    shard_canaries: Optional[tuple] = None

    @property
    def n_shards(self) -> int:
        return self.centers.shape[0]

    def tree_flatten(self):
        return ((self.centers, self.codebooks, self.list_codes,
                 self.list_indices, self.list_sizes, self.rotation,
                 self.list_recon),
                (self.metric, self.size, self.pq_bits, self.codebook_kind))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        # aux may be the legacy (metric, size) pair — callers that
        # round-trip flatten/unflatten through stored aux keep working
        return cls(*leaves, metric=aux[0], size=aux[1],
                   pq_bits=aux[2] if len(aux) > 2 else 0,
                   codebook_kind=aux[3] if len(aux) > 3 else 0)


def build(handle, params: ivf_pq.IndexParams, dataset, *,
          placement: str = "by_row", replication_factor: int = 1,
          retry_policy: Optional[_retry.RetryPolicy] = None,
          deadline: Optional[_retry.Deadline] = None):
    """Build a sharded IVF-PQ index over the handle's mesh.

    ``placement="by_row"`` (default): rows are split across shards and
    each shard trains its own local index (ids globally offset);
    ``params.n_lists`` is per shard.  PER_SUBSPACE builds run as ONE
    two-phase ``shard_map`` — every shard's k-means, codebook training
    and encoding execute SPMD across the mesh simultaneously, with a
    single tiny host sync (the global max list size) between encoding
    and list packing.  The round-3 host loop built shards one after
    another — 8x the build latency on a v5e-8 for no reason (VERDICT
    r3).  Other codebook kinds and mesocluster-scale n_lists fall back
    to the sequential per-shard loop.

    ``placement="by_list"``: ONE global index is trained (so
    ``params.n_lists`` is GLOBAL) and its lists are partitioned across
    shards balanced by list size — returns a :class:`RoutedIndex` whose
    search routes probes to owning shards (see module docstring).
    ``replication_factor=r > 1`` (by_list only) places ``r`` copies of
    every list on distinct shards for recall-preserving shard failover
    (see :func:`compute_placement`).

    Transient faults at entry (site ``distributed.ann.build``) are
    retried under ``retry_policy`` / ``deadline``.
    """
    expects(placement in ("by_row", "by_list"),
            f"distributed.ann.build: placement must be 'by_row' or "
            f"'by_list', got {placement!r}")
    expects(replication_factor == 1 or placement == "by_list",
            "distributed.ann.build: replication_factor > 1 requires "
            "placement='by_list' (by_row is already fully replicated "
            "per shard's rows)")
    if placement == "by_list":
        return _entry("distributed.ann.build",
                      lambda: _build_by_list(
                          handle, params, dataset,
                          replication_factor=replication_factor),
                      retry_policy, deadline)
    return _entry("distributed.ann.build",
                  lambda: _build_impl(handle, params, dataset),
                  retry_policy, deadline)


def _build_impl(handle, params: ivf_pq.IndexParams,
                dataset) -> DistributedIndex:
    with named_range("distributed::ivf_pq_build"):
        expects(handle.comms_initialized(),
                "distributed.ann.build: handle has no comms (use "
                "CommsSession.worker_handle())")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n, n_dev, per, devs = _shard_layout(
            handle, dataset)
        expects(params.cache_reconstructions,
                "distributed.ann: the sharded search kernel runs the "
                "reconstruction path; cache_reconstructions must be True")

        from raft_tpu.cluster import kmeans_balanced as kb

        if (params.codebook_kind == ivf_pq.CodebookKind.PER_SUBSPACE
                and params.n_lists < kb._MESO_THRESHOLD
                and params.n_lists <= per
                and params.add_data_on_build
                # canaries need per-shard exact ground truth, which only
                # the sequential per-shard build computes
                and params.canary_queries == 0):
            return _build_spmd(handle, params, dataset, mesh, axis, n,
                               n_dev, per)

        locals_ = []
        for s in range(n_dev):
            shard = dataset[s * per:(s + 1) * per]
            idx = ivf_pq.build(handle, params, shard)
            # globalize ids: local slot ids are 0..per-1 over the shard
            idx.list_indices = jnp.where(
                idx.list_indices >= 0, idx.list_indices + s * per, -1)
            locals_.append(idx)

        cap = max(ix.capacity for ix in locals_)

        def pad_cap(a, fill):
            return jnp.pad(a, ((0, 0), (0, cap - a.shape[1]))
                           + ((0, 0),) * (a.ndim - 2),
                           constant_values=fill)

        per_shard_leaves = [
            (ix.centers, ix.codebooks, pad_cap(ix.list_codes, 0),
             pad_cap(ix.list_indices, -1), ix.list_sizes, ix.rotation,
             pad_cap(ix.list_recon, 0))
            for ix in locals_]

        placed = _stack_leaves(per_shard_leaves, mesh, axis, devs)
        out = DistributedIndex.tree_unflatten(
            (params.metric, n, int(locals_[0].pq_bits),
             int(locals_[0].codebook_kind)), tuple(placed))
        out.shard_canaries = _collect_canaries(locals_, per,
                                               offset_ids=True)
        return out


def _stack_leaves(per_shard_leaves, mesh, axis, devs):
    """Assemble (n_dev, ...) stacked leaves from per-device shards —
    never materializing the full stack on one device, whose HBM the
    full index may not fit (the regime MNMG sharding exists for)."""
    n_dev = len(per_shard_leaves)
    placed = []
    for li in range(len(per_shard_leaves[0])):
        shards = [jax.device_put(per_shard_leaves[s][li][None],
                                 devs[s]) for s in range(n_dev)]
        shape = (n_dev,) + per_shard_leaves[0][li].shape
        # graftlint: disable=recompile-hazard -- len() is the static
        sharding = jax.sharding.NamedSharding(  # leaf rank at build time
            mesh, P(axis, *([None] * (len(shape) - 1))))
        placed.append(jax.make_array_from_single_device_arrays(
            shape, sharding, shards))
    return placed


def _build_spmd(handle, params: ivf_pq.IndexParams, dataset, mesh, axis,
                n, n_dev, per) -> DistributedIndex:
    """Two-phase SPMD build (see :func:`build`).

    Phase A (per shard, no collectives): coarse balanced k-means,
    per-subspace codebooks, encode + bit-pack, per-list counts.
    Host: one (n_dev, n_lists) readback picks the global static list
    capacity.  Phase B: pack lists + decode the bf16 recon cache.
    """
    from raft_tpu.cluster import kmeans_balanced as kb
    from raft_tpu.neighbors.ivf_flat import _LIST_ALIGN, _pack_lists

    dim = dataset.shape[1]
    pq_dim = params.pq_dim or max(dim // 4, 1)
    rot_dim = ivf_pq._round_up(dim, pq_dim)
    rotation = ivf_pq._make_rotation(
        dim, rot_dim, params.force_random_rotation or rot_dim != dim,
        seed=7)
    n_train = min(per, max(params.n_lists,
                           int(per * params.kmeans_trainset_fraction)))
    n_lists = params.n_lists
    book = 1 << params.pq_bits
    base_key = handle.next_key()

    def spec(ndim):
        return P(axis, *([None] * (ndim - 1)))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(axis), P()),
        out_specs=(spec(3), spec(4), spec(3), spec(2), spec(2)),
        check_vma=False)
    def phase_a(shard, rot):
        s = jax.lax.axis_index(axis)
        k1, k2 = jax.random.split(jax.random.fold_in(base_key, s))
        xf = shard.astype(jnp.float32) @ rot
        stride_t = max(per // n_train, 1)
        train = xf[::stride_t][:n_train]
        stride_c = max(n_train // n_lists, 1)
        c0 = train[::stride_c][:n_lists]
        centers, labels_t = kb._balanced_loop(
            train, c0, k1, n_lists, params.kmeans_n_iters, params.metric)
        resid_t = ivf_pq._subspace_split(train - centers[labels_t], pq_dim)
        books = ivf_pq._train_books_per_subspace(
            jnp.transpose(resid_t, (1, 0, 2)), jax.random.split(k2, pq_dim),
            book, params.kmeans_n_iters)
        labels, _ = kb._assign(xf, centers, params.metric)
        resid = ivf_pq._subspace_split(xf - centers[labels], pq_dim)
        codes = ivf_pq._pack_codes(
            ivf_pq._encode(books, resid, params.codebook_kind, labels),
            params.pq_bits)
        sizes = jax.ops.segment_sum(jnp.ones(per, jnp.int32), labels,
                                    num_segments=n_lists)
        return (centers[None], books[None], codes[None], labels[None],
                sizes[None])

    centers_a, books_a, codes_a, labels_a, sizes_a = phase_a(
        dataset, rotation)

    # the ONE host sync: global static list capacity
    capacity = ivf_pq._round_up(
        max(int(jnp.max(sizes_a)), _LIST_ALIGN), _LIST_ALIGN)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec(3), spec(4), spec(3), spec(2)),
        out_specs=(spec(4), spec(3), spec(2), spec(4)),
        check_vma=False)
    def phase_b(centers, books, codes, labels):
        s = jax.lax.axis_index(axis)
        gids = (s * per + jnp.arange(per)).astype(jnp.int32)
        lc, li, sz = _pack_lists(codes[0], labels[0], gids, n_lists,
                                 capacity)
        recon = ivf_pq._decode_lists(centers[0], books[0], lc,
                                     params.codebook_kind, pq_dim,
                                     params.pq_bits)
        return lc[None], li[None], sz[None], recon[None]

    list_codes, list_indices, list_sizes, list_recon = phase_b(
        centers_a, books_a, codes_a, labels_a)

    rot_stack = jax.device_put(
        jnp.broadcast_to(rotation[None], (n_dev,) + rotation.shape),
        jax.sharding.NamedSharding(mesh, P(axis, None, None)))
    return DistributedIndex.tree_unflatten(
        (params.metric, n, int(params.pq_bits),
         int(params.codebook_kind)),
        (centers_a, books_a, list_codes, list_indices, list_sizes,
         rot_stack, list_recon))


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric",
                                             "axis_name", "mesh", "failed"))
def _dist_search(index_leaves, queries, k, n_probes, metric, axis_name,
                 mesh, failed=(), filter_words=None):
    # only the leaves the recon search kernel consumes are threaded through
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in index_leaves)
    # filtered search (round 20): the bitset addresses GLOBAL row ids —
    # exactly what every shard's list_indices store — so one replicated
    # (q, n_words) buffer serves all shards unsliced.  Presence is part
    # of the trace signature: the unfiltered graph is unchanged.
    has_f = filter_words is not None
    in_specs = (specs, P()) + ((P(),) if has_f else ())
    out_specs = (P(), P()) + ((P(),) if has_f else ())

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    def run(leaves, q, *rest):
        centers, list_indices, rotation, list_recon = leaves
        ld, li = ivf_pq._search_impl_recon(
            centers[0], list_recon[0], list_indices[0], rotation[0], q,
            k, n_probes, metric,
            filter_words=rest[0] if has_f else None)
        select_min = metric != DistanceType.InnerProduct
        if failed:
            # degraded mode: a failed shard contributes only sentinel
            # candidates, so the replicated merge ranks every live
            # shard's hits first and pads the tail with id -1.  `failed`
            # is a static jit key — the no-fault compiled path is
            # byte-identical to before this feature existed.
            s = jax.lax.axis_index(axis_name)
            bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
            sentinel = jnp.inf if select_min else -jnp.inf
            ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
            li = jnp.where(bad, jnp.full_like(li, -1), li)
        all_d = jax.lax.all_gather(ld, axis_name)   # (n_dev, q, k)
        all_i = jax.lax.all_gather(li, axis_name)
        nq = q.shape[0]
        md, mi = select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)
        if has_f:
            # per-shard admitted-row counter: candidates this shard
            # contributed to the exchange after the admission fold
            # (starved slots are already id -1)
            admitted = jax.lax.all_gather(
                jnp.sum((li >= 0).astype(jnp.int32)), axis_name)
            return md, mi, admitted
        return md, mi

    args = (index_leaves, queries) + ((filter_words,) if has_f else ())
    return run(*args)


def _recon_sq_stack(index: DistributedIndex) -> jax.Array:
    """Stacked (n_dev, n_lists, cap) recon row norms, computed once and
    cached on the index object (the stacked pytree has no recon_sq leaf;
    the grouped scan's distance decomposition needs it)."""
    rsq = getattr(index, "_list_recon_sq_stack", None)
    if rsq is None:
        rsq = ivf_pq._recon_sq(index.list_recon)
        object.__setattr__(index, "_list_recon_sq_stack", rsq)
    return rsq


def _merge_gathered(ld, li, q, k, metric, axis_name, failed):
    """Shared shard_map epilogue: degraded-shard masking, the k-bounded
    all_gather, and the replicated merge-select (see :func:`_dist_search`
    for the exactness argument)."""
    select_min = metric != DistanceType.InnerProduct
    if failed:
        s = jax.lax.axis_index(axis_name)
        bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
        sentinel = jnp.inf if select_min else -jnp.inf
        ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
        li = jnp.where(bad, jnp.full_like(li, -1), li)
    all_d = jax.lax.all_gather(ld, axis_name)   # (n_dev, q, k)
    all_i = jax.lax.all_gather(li, axis_name)
    nq = q.shape[0]
    # sqrt=False: the shard-local epilogue already applied it for the
    # sqrt metrics, and the merge is monotone
    return grouped.finalize_topk(
        jnp.transpose(all_d, (1, 0, 2)), jnp.transpose(all_i, (1, 0, 2)),
        nq, k, select_min, False, select_k)


@functools.partial(jax.jit, static_argnames=(
    "k", "kt", "n_probes", "metric", "axis_name", "mesh", "n_groups",
    "form", "use_pallas", "merge_window", "failed"))
def _dist_search_grouped(index_leaves, queries, k, kt, n_probes, metric,
                         axis_name, mesh, n_groups, form,
                         use_pallas=False, merge_window=1, failed=(),
                         filter_words=None):
    """Data-parallel grouped/fused scan under ``shard_map`` (round 10):
    every shard runs the SAME formulation ladder the single-index search
    picks, at the worst-case static group capacity — the capacity is a
    pure function of (nq, n_probes, n_lists), so overflow is impossible
    and this jitted function carries no overflow plumbing at all."""
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in index_leaves)
    has_f = filter_words is not None
    in_specs = (specs, P()) + ((P(),) if has_f else ())
    out_specs = (P(), P()) + ((P(),) if has_f else ())

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    def run(leaves, q, *rest):
        centers, list_recon, list_recon_sq, list_indices, rotation = leaves
        fw = rest[0] if has_f else None
        probes = ivf_pq._select_clusters(centers[0], rotation[0], q,
                                         n_probes, metric)
        cap, rot = list_recon.shape[2], list_recon.shape[3]
        if form == "fused_recon":
            ld, li = ivf_pq._search_impl_fused_recon_grouped(
                centers[0], list_recon[0], list_recon_sq[0],
                list_indices[0], rotation[0], q, probes, k, kt, metric,
                n_groups, merge_window=merge_window, filter_words=fw)
        else:
            G = grouped.GROUP
            block = grouped.block_size(n_groups, G * cap * 8,
                                       cap * rot * 2, G * rot * 4)
            ld, li = ivf_pq._search_impl_recon_grouped(
                centers[0], list_recon[0], list_recon_sq[0],
                list_indices[0], rotation[0], q, probes, k, metric,
                n_groups, block, use_pallas=use_pallas, kt=kt,
                filter_words=fw)
        md, mi = _merge_gathered(ld, li, q, k, metric, axis_name, failed)
        if has_f:
            admitted = jax.lax.all_gather(
                jnp.sum((li >= 0).astype(jnp.int32)), axis_name)
            return md, mi, admitted
        return md, mi

    args = (index_leaves, queries) + ((filter_words,) if has_f else ())
    return run(*args)


@functools.partial(jax.jit, static_argnames=(
    "k", "n_probes", "metric", "codebook_kind", "lut_dtype", "pq_bits",
    "axis_name", "mesh", "failed"))
def _dist_search_lut(index_leaves, queries, k, n_probes, metric,
                     codebook_kind, lut_dtype, pq_bits, axis_name, mesh,
                     failed=(), filter_words=None):
    """Data-parallel LUT scan under ``shard_map``: the traceable LUT
    formulation computes the same quantized distance the codes kernel
    streams, so a ``codes``/``lut`` request answers with code-domain
    distances instead of lowering to the recon scan."""
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in index_leaves)
    has_f = filter_words is not None
    in_specs = (specs, P()) + ((P(),) if has_f else ())
    out_specs = (P(), P()) + ((P(),) if has_f else ())

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    def run(leaves, q, *rest):
        centers, codebooks, list_codes, list_indices, rotation = leaves
        ld, li = ivf_pq._search_impl(
            centers[0], codebooks[0], list_codes[0], list_indices[0],
            rotation[0], q, k, n_probes, metric, codebook_kind,
            lut_dtype, pq_bits=pq_bits,
            filter_words=rest[0] if has_f else None)
        md, mi = _merge_gathered(ld, li, q, k, metric, axis_name, failed)
        if has_f:
            admitted = jax.lax.all_gather(
                jnp.sum((li >= 0).astype(jnp.int32)), axis_name)
            return md, mi, admitted
        return md, mi

    args = (index_leaves, queries) + ((filter_words,) if has_f else ())
    return run(*args)


def ground_truth_params(index, params=None) -> ivf_pq.SearchParams:
    """The ground-truth operating point for a sharded index — every
    coarse list probed (per shard for the stacked placement, globally
    for ``by_list``), exact coarse ranking, no per-probe candidate
    truncation.  The shadow-replay quality monitor
    (:mod:`raft_tpu.serving.shadow`) searches at this point through the
    SAME placement map as live traffic to estimate live recall.

    ``scan_mode`` is pinned to ``"lut"`` (not ``"auto"``): the fused
    ladder's VMEM gates can refuse at full-probe shapes, and the
    resulting ``ivf_pq.search.fused_fallback`` ticks would pollute the
    drift detector's steady-state-fallback check with the monitor's own
    traffic."""
    routed = isinstance(index, RoutedIndex)
    n_lists = int(index.n_lists if routed else index.centers.shape[1])
    base = params if params is not None else ivf_pq.SearchParams()
    return dataclasses.replace(base, n_probes=n_lists, scan_mode="lut",
                               per_probe_topk=0, exact_coarse=True,
                               use_reconstruction=None)


def search(handle, params: ivf_pq.SearchParams, index, queries, k: int, *,
           failed_shards: Sequence[int] = (),
           return_status: bool = False,
           return_stats: bool = False,
           retry_policy: Optional[_retry.RetryPolicy] = None,
           deadline: Optional[_retry.Deadline] = None,
           health=None,
           shard_deadline_s: Optional[float] = None,
           hedge: bool = True,
           routing=None,
           filter=None):
    """Sharded search + merge; returns replicated (distances, global ids)
    of shape (q, k).  Accepts both placements: a
    :class:`DistributedIndex` (data-parallel full-shard scan) or a
    :class:`RoutedIndex` (routed-probe scan over owned lists only).

    Degraded mode: shards listed in ``failed_shards`` (or flagged by the
    active fault plan's ``fail_shards``) are masked out of the merge —
    the query still answers with the live shards' top-k, the tail padded
    with ``(inf, -1)`` when fewer than ``k`` live candidates exist.
    Under ``by_list`` a lost shard drops only its *owned* lists — recall
    degrades by roughly the failed shard's probed share instead of a
    full replica vanishing.  With ``return_status=True`` a status output
    is appended: an ``(n_shards,)`` int8 vector of
    :data:`SHARD_FAILED` / :data:`SHARD_OK` / :data:`SHARD_OK_FALLBACK`
    (live, but the requested ``scan_mode`` was lowered — see below).
    With ``return_stats=True`` a host-side dict is appended (after the
    status vector when both are requested) with the per-shard
    ``scanned_rows`` counter, the fixed candidate-exchange
    ``gather_shape``, and the effective ``scan_mode`` — the observability
    surface the placement-balance tripwire asserts on.
    Transient faults at entry (site ``distributed.ann.search``) are
    retried under ``retry_policy`` / ``deadline``.

    ``params.scan_mode`` threading (round 10): group construction is
    traceable at the static capacity
    :func:`raft_tpu.neighbors.grouped.group_capacity`, so the grouped
    and fused scans lower under ``shard_map`` for both placements —
    ``scan_mode="fused"`` (and ``"auto"`` on TPU) runs the same
    formulation ladder the single-index search picks: the fused Pallas
    kernels where the shape/VMEM gates pass, the XLA grouped twin
    elsewhere (a missed kernel gate ticks ``ivf_pq.search.fused_fallback``
    but is NOT a distributed lowering — the status stays
    :data:`SHARD_OK`).  Data-parallel ``codes``/``lut`` requests run the
    traceable LUT formulation (same quantized distance) when the stacked
    pytree carries PQ metadata.  Only the genuinely unsupported
    combinations lower to the probe-order recon scan — ``recon8`` (no
    stacked int8 cache), code modes on a routed index without the code
    leaves or on a legacy stacked pytree — and those report
    :data:`SHARD_OK_FALLBACK` plus the
    ``distributed.ann.scan_mode_lowered`` counter, exactly as before.

    Routed fused dispatch is sync-free: an uncalibrated index runs at
    the exact-safe worst-case capacity (zero host reads); a calibrated
    index (``group_est`` from
    :func:`raft_tpu.neighbors.ivf_pq.calibrate_group_capacity`, carried
    through :func:`shard_by_list`) dispatches at the tightened capacity
    and the per-shard true group counts ride the candidate all_gather —
    only a batch whose probe skew exceeds the calibrated bound pays the
    one host read plus an exact re-dispatch at the worst bound, counted
    by ``ivf_pq.search.group_overflow``.

    Replication (the routed path only, ``replication_factor > 1``): a
    down shard's lists fail over to their replicas *before* dispatch —
    host-side, the effective routing tables swap each affected list to
    its lowest-rank live owner, so the device program sees the same
    shapes (replica choice is data, not shape: zero recompiles) and the
    merge pulls the lost lists from shards that scan the identical rows,
    keeping full-probe results **bit-identical** to the healthy run.
    Fully-covered shards report :data:`SHARD_REPLICA_SERVED`; only
    shards with uncovered lists stay :data:`SHARD_FAILED` (the
    ``distributed.degraded_search`` event fires for those alone, and the
    residual set is the only thing passed as a static jit arg — a fully
    covered failover reuses the warmed healthy executable).

    ``health`` (a :class:`raft_tpu.distributed.health.HealthTracker`)
    contributes its FAILED shards to the down set and receives straggle
    / deadline-overrun signals.  ``shard_deadline_s`` (satellite of the
    straggler model: a float budget or a :class:`resilience.Deadline`)
    bounds the wait on any one shard — an overrun emits a
    ``distributed.shard_timeout`` flight event, notes a timeout with the
    tracker, and (with replicas available and ``hedge=True``) converts
    the unbounded wait into a **hedged read**: the straggler's probe
    subset is re-issued to a replica and the first answer taken — exact,
    because both scan identical lists.  A hedged shard's injected delay
    is not paid beyond the deadline; with no covering replica the shard
    is un-hedged and waited for in full (slow beats dropped).

    ``routing`` (a :class:`raft_tpu.distributed.routing.RoutingPolicy`)
    turns the replicas into a throughput lever on the HEALTHY path:
    every batch's effective tables come from
    :meth:`~raft_tpu.distributed.routing.RoutingPolicy.plan` — greedy
    least-loaded replica-rank selection over the per-shard load scores
    — instead of the fixed rank-0 primaries, and a hedge re-issues to
    the least-loaded covering replica rather than the lowest rank.
    Exactness is unchanged (any live assignment is bit-identical at
    full probe: the k-bounded merge argument is per list, and replica
    copies are identical rows), the tables stay data-not-shape (zero
    recompiles), and each decision lands a
    ``distributed.replica_choice`` flight event.  The routed dispatch
    also hands the policy each batch's in-graph per-list probe
    histogram (``observe_probes`` — a lazy device array, no host sync)
    for probe-frequency-aware rebalancing.

    ``filter`` (round 20): a :class:`raft_tpu.filters.SampleFilter` (or
    a ``(q, n_rows)`` bool mask) over GLOBAL row ids.  The packed
    ``(q, n_words)`` bitset is broadcast replicated alongside the
    queries — shards consume it unsliced because their ``list_indices``
    store global ids, so the admission fold commutes with both
    placements, replica failover, and hedging (replica copies scan
    identical rows).  Filtered full-probe results are bit-identical to
    a post-hoc-filtered exact scan; starved slots pad with ``(inf,
    -1)``.  The filter is data, not shape: varying filters reuse the
    warmed executable, and presence/absence is a separate trace.  Each
    shard's admitted-candidate count rides the existing gather — with
    ``return_stats=True`` the stats dict gains ``admitted_rows``, and
    the lazy per-shard vector is annotated on the ambient trace as
    ``distributed.admitted_rows``.
    """
    with named_range("distributed::ivf_pq_search"):
        expects(handle.comms_initialized(),
                "distributed.ann.search: handle has no comms")
        comms = handle.get_comms()
        queries = ensure_array(queries, "queries")
        # lifecycle-boundary kill site: a shard killed here is seen by
        # THIS search's failed-set computation (killed during routing)
        faults.maybe_fail("distributed.route")
        failed = _degraded_set(index.n_shards, failed_shards)
        if health is not None:
            failed = tuple(sorted(
                set(failed) | set(health.failed_shards())))
        nq = int(queries.shape[0])
        k = int(k)
        fw = _fbits.query_filter_words(filter, nq, "distributed.ann.search")
        routed = isinstance(index, RoutedIndex)
        rec = _rtrace.current()
        rf = (index.placement.replication_factor
              if routed and index.placement is not None else 1)
        if isinstance(shard_deadline_s, _retry.Deadline):
            shard_deadline_s = shard_deadline_s.remaining()
        expects(shard_deadline_s is None or shard_deadline_s > 0,
                "distributed.ann.search: shard_deadline_s must be > 0")
        # per-shard straggler injection (host-side, before dispatch):
        # the SPMD merge completes when the slowest shard answers.
        # Probe the scripted schedule WITHOUT sleeping first — the
        # straggler detector — so hedging can collapse a flagged
        # shard's wait before it is paid.
        delays = faults.straggler_delays(index.n_shards)
        flagged = tuple(s for s, dly in enumerate(delays) if dly > 0.0)
        if delays:
            _flight.record_event("distributed.straggler",
                                 trace_id=rec.trace_id if rec else None,
                                 delays_s=list(delays),
                                 n_shards=index.n_shards)
        timeouts = ()
        if flagged and shard_deadline_s is not None:
            timeouts = tuple(s for s in flagged
                             if delays[s] > shard_deadline_s)
            for s in timeouts:
                _flight.record_event("distributed.shard_timeout",
                                     trace_id=rec.trace_id if rec else None,
                                     shard=s, delay_s=delays[s],
                                     deadline_s=shard_deadline_s)
                if health is not None:
                    health.note_timeout(s)
        if health is not None:
            for s in flagged:
                health.note_straggle(s)
        # -- replica failover + hedging (host-side, data not shape) ----
        hedge_cand = set()
        if hedge and routed and rf > 1:
            hedge_cand = set(flagged) - set(failed)
            if health is not None:
                hedge_cand |= set(health.suspect_shards()) - set(failed)
        hedged: Tuple[int, ...] = ()
        residual = failed
        replica_served: Tuple[int, ...] = ()
        eff = None  # (eff_owner, eff_slot) host numpy, or None
        # load-aware policy: plan() honors the same keep-primary-when-
        # uncovered contract as healthy_routing, so the residual /
        # covered bookkeeping below composes with either table source
        use_policy = routing is not None and routed and rf > 1

        def _route_tables(d):
            if use_policy:
                return routing.plan(index.placement, down=d)
            return index.placement.healthy_routing(d)

        if routed and rf > 1 and (failed or hedge_cand or use_policy):
            down = set(failed) | hedge_cand
            eo, es = _route_tables(tuple(sorted(down)))
            still = down & set(np.unique(eo).tolist())
            # a hedge candidate whose lists have no live replica is
            # UN-hedged: the shard is alive, just slow — wait for it
            # rather than drop its lists
            unhedged = hedge_cand & still
            hedged = tuple(sorted(hedge_cand - unhedged))
            down = set(failed) | set(hedged)
            if unhedged and down:
                eo, es = _route_tables(tuple(sorted(down)))
            if down:
                still = down & set(np.unique(eo).tolist())
                residual = tuple(sorted(set(failed) & still))
                replica_served = tuple(sorted(down - still))
                eff = (eo, es)
            elif use_policy:
                # pure load spreading: nothing down, every list served
                # by its least-loaded live rank
                eff = (eo, es)
            if use_policy:
                reason = ("failover" if failed
                          else "hedge" if hedged else "load_spread")
                choice = routing.choice_summary()
                _flight.record_event(
                    "distributed.replica_choice",
                    trace_id=rec.trace_id if rec else None,
                    reason=reason,
                    scores=choice.get("scores"),
                    per_rank_lists=choice.get("per_rank_lists"),
                    per_shard_lists=choice.get("per_shard_lists"))
                from raft_tpu import observability as obs
                if obs.enabled():
                    obs.registry().counter(
                        "distributed.replica_choice").inc()
            if failed and set(failed) - set(residual):
                _flight.record_event(
                    "distributed.replica_failover",
                    trace_id=rec.trace_id if rec else None,
                    failed=list(failed), residual=list(residual),
                    covered=sorted(set(failed) - set(residual)))
            for s in hedged:
                _flight.record_event("distributed.hedged_read",
                                     trace_id=rec.trace_id if rec else None,
                                     shard=s, delay_s=delays[s]
                                     if s < len(delays) else 0.0)
            if hedged:
                from raft_tpu import observability as obs
                if obs.enabled():
                    obs.registry().counter(
                        "distributed.hedged_reads").inc(len(hedged))
        # pay the straggler wait: a hedged shard's wait collapses to the
        # deadline (the replica answered instead); everyone else is
        # waited for in full.  The sleep stays in the resilience layer.
        wait = 0.0
        hedged_set = set(hedged)
        for s, dly in enumerate(delays):
            if dly <= 0.0:
                continue
            if s in hedged_set:
                dly = min(dly, shard_deadline_s or 0.0)
            wait = max(wait, dly)
        faults.pause(wait)
        n_probes = min(params.n_probes,
                       index.n_lists if routed else index.centers.shape[1])
        r = _resolve_scan_mode(params, index, nq, n_probes, k)
        # per-request tracing: annotate the ambient recorder (pushed by
        # the serving batcher around its executor call) with the host-
        # static facts of this dispatch.  Everything attached here is
        # already on the host — NO new device->host syncs; the scanned-
        # rows counter below rides along as a lazy device array that only
        # flight.dump() materializes.
        if rec is not None:
            rec.annotate("distributed.scan_mode",
                         {"probe_recon": "recon"}.get(r.form, r.form))
            rec.annotate("distributed.n_probes", int(n_probes))
            # same host values _status_vector encodes, without the
            # device round-trip
            status = np.full(index.n_shards,
                             SHARD_OK_FALLBACK if r.lowered else SHARD_OK,
                             np.int8)
            status[list(residual)] = SHARD_FAILED
            status[list(replica_served)] = SHARD_REPLICA_SERVED
            rec.annotate("distributed.shard_status", status.tolist())
        if residual:
            # only shards with genuinely UNCOVERED lists degrade the
            # answer; a fully covered failover is telemetry, not
            # degradation
            _flight.record_event("distributed.degraded_search",
                                 trace_id=rec.trace_id if rec else None,
                                 failed=list(residual),
                                 n_shards=index.n_shards)
        scanned = None
        phist = None  # per-list probe histogram (routed; lazy device)
        admitted = None  # per-shard admitted-candidate counts (filtered)
        # lifecycle-boundary kill site: a shard killed here (mid-scan)
        # keeps this search's pre-kill routing — its in-flight answer
        # completes — and the NEXT search routes around it
        faults.maybe_fail("distributed.scan")
        if routed:
            if r.form == "probe_recon":
                sharded = (index.local_centers, index.list_recon,
                           index.list_recon_sq, index.list_indices)
                replicated = (index.coarse_centers, index.rotation,
                              index.owner, index.local_slot)
                if eff is not None:
                    # effective routing tables: same shape as the
                    # healthy tables (replica choice is data, not
                    # shape — no recompile), swapped in host-side
                    replicated = replicated[:2] + (
                        _replicate(jnp.asarray(eff[0]), handle.mesh),
                        _replicate(jnp.asarray(eff[1]), handle.mesh))
                out = _entry(
                    "distributed.ann.search",
                    lambda: _dist_search_routed(
                        sharded, replicated, queries, k, n_probes,
                        index.metric, comms.axis_name, handle.mesh,
                        failed=residual, filter_words=fw),
                    retry_policy, deadline)
                if fw is not None:
                    d, i, scanned, phist, admitted = out
                else:
                    d, i, scanned, phist = out
            else:
                sharded, replicated = _routed_leaves(index, r.form)
                if eff is not None:
                    replicated = replicated[:2] + (
                        _replicate(jnp.asarray(eff[0]), handle.mesh),
                        _replicate(jnp.asarray(eff[1]), handle.mesh),
                    ) + replicated[4:]

                def dispatch(ng):
                    out = _dist_search_routed_grouped(
                        sharded, replicated, queries, k, r.kt, n_probes,
                        index.metric, comms.axis_name, handle.mesh, ng,
                        r.form, pq_bits=int(index.pq_bits),
                        use_pallas=r.use_pallas,
                        merge_window=r.merge_window, failed=residual,
                        filter_words=fw)
                    return out if fw is not None else out + (None,)

                d, i, scanned, needed, phist, admitted = _entry(
                    "distributed.ann.search",
                    lambda: dispatch(r.n_groups), retry_policy, deadline)
                if not r.exact:
                    # calibrated-capacity regime: the ONE deliberate host
                    # read of the routed path, AFTER the dispatch so it
                    # overlaps the scan; almost every batch passes and
                    # pays nothing further
                    # graftlint: disable=host-sync -- overflow re-dispatch gate, not steady-state dispatch
                    if int(jnp.max(needed)) > r.n_groups:
                        from raft_tpu import observability as obs
                        if obs.enabled():
                            obs.registry().counter(
                                "ivf_pq.search.group_overflow").inc()
                        worst, _ = grouped.group_capacity(
                            nq, n_probes, index.local_centers.shape[1])
                        _flight.record_event(
                            "ivf_pq.group_overflow",
                            trace_id=rec.trace_id if rec else None,
                            calibrated_groups=r.n_groups, worst=worst)
                        (d, i, scanned, needed, phist,
                         admitted) = dispatch(worst)
        elif r.form == "probe_recon":
            leaves = (index.centers, index.list_indices, index.rotation,
                      index.list_recon)
            out = _entry(
                "distributed.ann.search",
                lambda: _dist_search(leaves, queries, k, n_probes,
                                     index.metric, comms.axis_name,
                                     handle.mesh, failed=residual,
                                     filter_words=fw),
                retry_policy, deadline)
            (d, i, admitted) = out if fw is not None else out + (None,)
        elif r.form == "lut":
            leaves = (index.centers, index.codebooks, index.list_codes,
                      index.list_indices, index.rotation)
            lut_dtype = jnp.dtype(
                getattr(params, "lut_dtype", jnp.float32)).name
            out = _entry(
                "distributed.ann.search",
                lambda: _dist_search_lut(
                    leaves, queries, k, n_probes, index.metric,
                    index.codebook_kind, lut_dtype,
                    int(index.pq_bits), comms.axis_name, handle.mesh,
                    failed=residual, filter_words=fw),
                retry_policy, deadline)
            (d, i, admitted) = out if fw is not None else out + (None,)
        else:
            leaves = (index.centers, index.list_recon,
                      _recon_sq_stack(index), index.list_indices,
                      index.rotation)
            out = _entry(
                "distributed.ann.search",
                lambda: _dist_search_grouped(
                    leaves, queries, k, r.kt, n_probes, index.metric,
                    comms.axis_name, handle.mesh, r.n_groups, r.form,
                    use_pallas=r.use_pallas,
                    merge_window=r.merge_window, failed=residual,
                    filter_words=fw),
                retry_policy, deadline)
            (d, i, admitted) = out if fw is not None else out + (None,)
        # lifecycle-boundary kill site: post-dispatch, pre-merge-return
        # — a kill here lands after the candidate gather, next search
        # sees the shard down
        faults.maybe_fail("distributed.gather")
        if rec is not None and scanned is not None:
            # lazy attachment: `scanned` is a device array; annotate()
            # stores the reference without fetching it (no host sync on
            # the dispatch path — flight.dump() materializes it later)
            rec.annotate("distributed.scanned_rows", scanned)
        if fw is not None:
            from raft_tpu import observability as obs
            if obs.enabled():
                obs.registry().counter(
                    "distributed.ann.search.filtered").inc()
            if rec is not None and admitted is not None:
                # lazy, like scanned_rows: per-shard admitted-candidate
                # counts ride the existing candidate gather
                rec.annotate("distributed.admitted_rows", admitted)
        if routing is not None and phist is not None:
            # the probe-frequency counters: the policy retains the lazy
            # device histogram; materialization happens only in its
            # maintenance-path refresh() — steady state stays sync-free
            routing.observe_probes(phist)
        out = [d, i]
        if return_status:
            out.append(_status_vector(index.n_shards, residual,
                                      r.lowered, replica_served))
        if return_stats:
            if scanned is None:
                # data-parallel: every live shard scans its whole local
                # index for every probe — n_probes lists of cap rows
                cap = index.list_recon.shape[2]
                per = np.full(index.n_shards, nq * n_probes * cap,
                              np.int64)
                per[list(residual)] = 0
            else:
                # graftlint: disable=host-sync -- opt-in stats readback (return_stats=True), not the serving dispatch
                per = np.asarray(scanned, np.int64)
            gather = (index.n_shards, nq, k)
            stats = {"scanned_rows": per, "gather_shape": gather,
                     "scan_mode": {"probe_recon": "recon"}.get(
                         r.form, r.form),
                     "n_probes": int(n_probes)}
            if admitted is not None:
                # graftlint: disable=host-sync -- opt-in stats readback (return_stats=True), not the serving dispatch
                stats["admitted_rows"] = np.asarray(admitted, np.int64)
            out.append(stats)
        return tuple(out) if len(out) > 2 else (d, i)


def delete(handle, index: DistributedIndex, ids, *,
           retry_policy: Optional[_retry.RetryPolicy] = None,
           deadline: Optional[_retry.Deadline] = None) -> DistributedIndex:
    """Tombstone delete over the sharded index (ids are GLOBAL).

    One sharding-preserving elementwise rewrite of the stacked
    ``list_indices`` leaf — matching slots flip to the tombstone
    encoding (see :mod:`raft_tpu.neighbors.mutate`), which the
    shard-local recon scan already masks (it keeps ``>= 0`` slots only).
    Every other leaf is shared with the parent; the returned snapshot is
    generation-bumped.  Transient faults at entry (site
    ``distributed.ann.delete``) are retried under ``retry_policy`` /
    ``deadline``."""
    return _entry("distributed.ann.delete",
                  lambda: _delete_impl(index, ids), retry_policy, deadline)


def _delete_impl(index, ids):
    with named_range("distributed::ivf_pq_delete"):
        ids = ensure_array(ids, "ids")
        expects(ids.ndim == 1, "distributed.ann.delete: 1-D ids required")
        new_li, _ = _mutate.tombstone(index.list_indices, ids)
        if isinstance(index, RoutedIndex):
            # sharding-preserving elementwise rewrite of the stacked
            # (n_dev, L+1, cap) leaf; placement and canaries carry over
            out = dataclasses.replace(index, list_indices=new_li)
            _mutate.next_generation(index, out)
            return out
        leaves, aux = index.tree_flatten()
        leaves = list(leaves)
        leaves[3] = new_li
        out = DistributedIndex.tree_unflatten(aux, tuple(leaves))
        out.shard_canaries = index.shard_canaries
        _mutate.next_generation(index, out)
        return out


# ---------------------------------------------------------------------------
# Index-parallel sharding (placement="by_list"): routed probes + matched
# candidate gather
# ---------------------------------------------------------------------------

# v2 (round 17): trailing replication block — ``replication_factor``
# plus, when > 1, the per-rank (r, n_lists) owner/slot tables.  v1
# streams read fine and land unreplicated (r=1).
_PLACEMENT_VERSION = 2
_PLACEMENT_MIN_READ_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Placement:
    """List → shard ownership map for ``placement="by_list"`` indexes.

    ``owner[g]`` is the shard owning global IVF list ``g`` (the
    *primary* — replica rank 0); ``local_slot[g]`` is that list's slot
    in the owner's stacked local leaves.  ``n_local`` is the per-shard
    slot count *excluding* the dummy slot (every shard's slot
    ``n_local`` is an always-empty list that unowned probes lower to).
    ``generation`` counts placement recomputations — it keys the
    serving tier's executable cache alongside the index mutation
    generation.

    Replication (round 17): with ``replication_factor=r > 1`` every
    list is owned by ``r`` DISTINCT shards — the primary at rank 0 plus
    ``r-1`` replicas, each rank independently LPT-balanced.  ``owners``
    / ``slots`` are the full ``(r, n_lists)`` rank tables (row 0 equals
    ``owner`` / ``local_slot``); a shard's local leaves hold the union
    of the lists it owns at ANY rank, so failover to a replica is a
    pure routing-table change — replica choice is data, not shape."""

    owner: np.ndarray       # (n_lists,) int32 — rank-0 owners
    local_slot: np.ndarray  # (n_lists,) int32 — rank-0 slots
    n_shards: int
    n_local: int
    generation: int = 0
    replication_factor: int = 1
    owners: Optional[np.ndarray] = None  # (r, n_lists) int32, r > 1 only
    slots: Optional[np.ndarray] = None   # (r, n_lists) int32, r > 1 only

    @property
    def n_lists(self) -> int:
        return int(self.owner.shape[0])

    def rank_tables(self) -> Tuple[np.ndarray, np.ndarray]:
        """The ``(r, n_lists)`` per-rank (owners, slots) tables —
        ``(1, n_lists)`` views of the primary arrays when r=1."""
        if self.owners is None:
            return self.owner[None, :], self.local_slot[None, :]
        return self.owners, self.slots

    def shard_lists(self, s: int,
                    rank: Optional[int] = None) -> np.ndarray:
        """Global list ids materialized on shard ``s``, in local-slot
        order.  Default: the union over every replica rank (the lists
        whose copies live in ``s``'s local leaves — what
        ``_place_lists`` stacks); ``rank=j`` restricts to the lists
        ``s`` owns at that rank (``rank=0`` is the primary set)."""
        owners, slots = self.rank_tables()
        if rank is not None:
            owned = np.nonzero(owners[rank] == s)[0]
            return owned[np.argsort(slots[rank][owned], kind="stable")]
        ranks, lists = np.nonzero(owners == s)
        order = np.argsort(slots[ranks, lists], kind="stable")
        return lists[order]

    def healthy_routing(self, down: Sequence[int]
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """Effective (owner, slot) routing tables with every list served
        by its LOWEST-rank owner not in ``down`` — the failover /
        hedging tables.  A list all of whose owners are down keeps its
        rank-0 primary (the degraded-masking path handles it); both
        arrays are host-side numpy, shaped exactly like ``owner`` /
        ``local_slot``, so swapping them into the routed dispatch is a
        data change only (zero recompiles)."""
        owners, slots = self.rank_tables()
        eff_owner = self.owner.copy()
        eff_slot = self.local_slot.copy()
        downset = {int(s) for s in down}
        if not downset or owners.shape[0] == 1:
            return eff_owner, eff_slot
        hit = np.nonzero(np.isin(self.owner, list(downset)))[0]
        for g in hit:
            for r in range(owners.shape[0]):
                if int(owners[r, g]) not in downset:
                    eff_owner[g] = owners[r, g]
                    eff_slot[g] = slots[r, g]
                    break
        return eff_owner, eff_slot


def compute_placement(list_sizes, n_shards: int, *, generation: int = 0,
                      replication_factor: int = 1) -> Placement:
    """Balanced list partition: LPT greedy — lists sorted by (live) size
    descending, each assigned to the least-loaded shard (ties broken by
    fewest lists, then lowest shard id, so the result is deterministic
    and slot counts stay even under uniform sizes).  LPT is a 4/3
    approximation to the optimal makespan, which bounds the worst
    shard's scan work — the property the placement-balance tripwire
    (``(probed_rows / n_shards) * 1.5``) rides on.

    ``replication_factor=r > 1`` runs the SAME greedy once per replica
    rank with an anti-co-location constraint: rank ``j`` skips the
    shards already owning the list at ranks ``< j``, so a list's ``r``
    copies always land on distinct shards and any ``r-1`` shard
    failures leave every list with a healthy owner.  Each rank is
    LPT-balanced against its own load vector; local slots draw from one
    shared per-shard counter, so a shard's leaves hold the union of its
    per-rank owned sets at consecutive slots (memory cost ~``r``×)."""
    sizes = np.asarray(list_sizes, np.int64).reshape(-1)
    n_lists = sizes.shape[0]
    r = int(replication_factor)
    expects(n_shards >= 1, "compute_placement: n_shards must be >= 1")
    expects(n_lists >= n_shards,
            f"compute_placement: need n_lists ({n_lists}) >= n_shards "
            f"({n_shards}) to give every shard at least one list")
    expects(1 <= r <= n_shards,
            f"compute_placement: replication_factor ({r}) must be in "
            f"[1, n_shards={n_shards}] — replicas of a list are never "
            f"co-located, so each list needs {r} distinct shards")
    owners = np.zeros((r, n_lists), np.int32)
    slots = np.zeros((r, n_lists), np.int32)
    load = np.zeros((r, n_shards), np.int64)
    per_rank_count = np.zeros((r, n_shards), np.int64)
    count = np.zeros(n_shards, np.int64)  # shared slot counter
    # stable argsort on -sizes: equal-size lists keep ascending id order
    order = np.argsort(-sizes, kind="stable")
    for rank in range(r):
        for g in order:
            taken = owners[:rank, g]
            for s in np.lexsort((per_rank_count[rank], load[rank])):
                if s not in taken:
                    break
            s = int(s)
            owners[rank, g] = s
            slots[rank, g] = count[s]
            load[rank, s] += int(sizes[g])
            per_rank_count[rank, s] += 1
            count[s] += 1
    return Placement(owner=owners[0], local_slot=slots[0],
                     n_shards=int(n_shards), n_local=int(count.max()),
                     generation=int(generation),
                     replication_factor=r,
                     owners=owners if r > 1 else None,
                     slots=slots if r > 1 else None)


def placement_to_stream(res, stream, placement: Placement) -> None:
    """CRC32-enveloped dump of the placement map (rides inside the
    routed index envelope; also usable standalone)."""
    with ser.enveloped_writer(stream) as body:
        ser.serialize_scalar(res, body, np.int32(_PLACEMENT_VERSION))
        ser.serialize_scalar(res, body, np.int32(placement.n_shards))
        ser.serialize_scalar(res, body, np.int32(placement.n_local))
        ser.serialize_scalar(res, body, np.int64(placement.generation))
        ser.serialize_mdspan(res, body, placement.owner)
        ser.serialize_mdspan(res, body, placement.local_slot)
        # v2 replication block: factor always, rank tables only when
        # replicated (r=1 round-trips to the v1-equivalent shape)
        ser.serialize_scalar(
            res, body, np.int32(placement.replication_factor))
        if placement.replication_factor > 1:
            ser.serialize_mdspan(res, body, placement.owners)
            ser.serialize_mdspan(res, body, placement.slots)


def placement_from_stream(res, stream) -> Placement:
    body = ser.open_envelope(stream)
    version = int(ser.deserialize_scalar(res, body))
    if not (_PLACEMENT_MIN_READ_VERSION <= version
            <= _PLACEMENT_VERSION):
        raise ValueError(
            f"placement serialization version mismatch: got {version}, "
            f"expected {_PLACEMENT_MIN_READ_VERSION}.."
            f"{_PLACEMENT_VERSION}")
    n_shards = int(ser.deserialize_scalar(res, body))
    n_local = int(ser.deserialize_scalar(res, body))
    generation = int(ser.deserialize_scalar(res, body))
    owner = np.asarray(ser.deserialize_mdspan(res, body), np.int32)
    local_slot = np.asarray(ser.deserialize_mdspan(res, body), np.int32)
    replication_factor = 1
    owners = slots = None
    if version >= 2:
        replication_factor = int(ser.deserialize_scalar(res, body))
        if replication_factor > 1:
            owners = np.asarray(
                ser.deserialize_mdspan(res, body), np.int32)
            slots = np.asarray(
                ser.deserialize_mdspan(res, body), np.int32)
    return Placement(owner=owner, local_slot=local_slot,
                     n_shards=n_shards, n_local=n_local,
                     generation=generation,
                     replication_factor=replication_factor,
                     owners=owners, slots=slots)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RoutedIndex:
    """Index-parallel (``placement="by_list"``) IVF-PQ: one global
    coarse quantizer + rotation replicated on every chip, the IVF lists
    partitioned across shards.  Shard ``s``'s local leaves hold its
    owned lists at slots ``0..n_owned-1`` plus a terminal dummy slot
    (all ids ``-1``) that unowned probes lower to — the scan kernel's
    existing padded-row mask makes those probes contribute nothing, so
    routing needs zero kernel changes."""

    coarse_centers: jax.Array  # (n_lists, rot_dim) — replicated
    rotation: jax.Array        # (dim, rot_dim) — replicated
    owner: jax.Array           # (n_lists,) int32 — replicated
    local_slot: jax.Array      # (n_lists,) int32 — replicated
    local_centers: jax.Array   # (n_dev, L+1, rot_dim) — sharded
    list_recon: jax.Array      # (n_dev, L+1, cap, rot_dim) bf16 — sharded
    list_recon_sq: jax.Array   # (n_dev, L+1, cap) — sharded
    list_indices: jax.Array    # (n_dev, L+1, cap) — sharded
    list_sizes: jax.Array      # (n_dev, L+1) — sharded
    # optional lane-major code leaves (round 10): carried when the base
    # index was codes-mode eligible, so the routed fused scan streams
    # 4*ceil(W/4)+8 B/row instead of the 2*rot+8 recon rows.  None on
    # indexes sharded before round 10 (and after a v1 deserialize).
    codebooks: Optional[jax.Array] = None        # replicated
    list_code_lanes: Optional[jax.Array] = None  # (n_dev, L+1, Wi, cap)
    list_code_rsq: Optional[jax.Array] = None    # (n_dev, L+1, cap)
    metric: int = DistanceType.L2Expanded
    size: int = 0
    pq_bits: int = 0
    # calibrated group-capacity estimate (see ivf_pq.group_est); static
    # aux so jit keys change when a recalibration tightens the capacity
    group_est: float = 0.0
    # host-side metadata, NOT pytree leaves (transforms drop them; the
    # host wrappers carry them explicitly, like shard_canaries above)
    placement: Optional[Placement] = None
    canaries: Optional[object] = None

    @property
    def n_shards(self) -> int:
        return self.local_centers.shape[0]

    @property
    def n_lists(self) -> int:
        return self.coarse_centers.shape[0]

    @property
    def capacity(self) -> int:
        return self.list_indices.shape[2]

    @property
    def dim(self) -> int:
        return self.rotation.shape[0]

    def tree_flatten(self):
        # the optional code leaves are pytree children too (None is an
        # empty subtree, so pre-round-10 indexes flatten identically)
        return ((self.coarse_centers, self.rotation, self.owner,
                 self.local_slot, self.local_centers, self.list_recon,
                 self.list_recon_sq, self.list_indices, self.list_sizes,
                 self.codebooks, self.list_code_lanes,
                 self.list_code_rsq),
                (self.metric, self.size, self.pq_bits, self.group_est))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], size=aux[1],
                   pq_bits=aux[2] if len(aux) > 2 else 0,
                   group_est=aux[3] if len(aux) > 3 else 0.0)


def _mesh_layout(handle):
    """Mesh geometry without the by_row row-divisibility constraint
    (by_list shards lists, not rows)."""
    comms = handle.get_comms()
    mesh = handle.mesh
    axis = comms.axis_name
    expects(mesh.devices.ndim == 1,
            "distributed.ann: a 1-D mesh is required (reshape 2D grids "
            "to the data axis for index sharding)")
    return comms, mesh, axis, mesh.shape[axis], mesh.devices.ravel()


def _replicate(arr, mesh):
    return jax.device_put(arr, jax.sharding.NamedSharding(
        mesh, P(*([None] * jnp.ndim(arr)))))


def _place_lists(handle, global_leaves, rotation, placement: Placement,
                 metric, size, code_leaves=None, pq_bits=0,
                 group_est=0.0) -> RoutedIndex:
    """Assemble a :class:`RoutedIndex` from global per-list arrays
    (centers, recon, recon_sq, indices, sizes) under ``placement``.
    ``code_leaves`` optionally carries (codebooks, list_code_lanes,
    list_code_rsq) — the lane-major compact-code cache the routed fused
    scan streams; the lanes/rsq shard like the recon leaves (axis 0 is
    the global list id), the codebooks replicate."""
    centers, recon, rsq, li, sizes = global_leaves
    comms, mesh, axis, n_dev, devs = _mesh_layout(handle)
    expects(placement.n_shards == n_dev,
            f"distributed.ann: placement maps {placement.n_shards} "
            f"shards but the mesh has {n_dev} devices")
    slots = placement.n_local + 1  # terminal dummy slot

    per_shard = []
    for s in range(n_dev):
        owned = jnp.asarray(placement.shard_lists(s), jnp.int32)

        def pad(a, fill, owned=owned):
            sel = jnp.take(a, owned, axis=0)
            width = ((0, slots - sel.shape[0]),) + ((0, 0),) * (a.ndim - 1)
            return jnp.pad(sel, width, constant_values=fill)

        leaves_s = (pad(centers, 0), pad(recon, 0), pad(rsq, 0),
                    pad(li, -1), pad(sizes, 0))
        if code_leaves is not None:
            leaves_s += (pad(code_leaves[1], 0), pad(code_leaves[2], 0))
        per_shard.append(leaves_s)
    placed = _stack_leaves(per_shard, mesh, axis, devs)
    return RoutedIndex(
        coarse_centers=_replicate(centers, mesh),
        rotation=_replicate(rotation, mesh),
        owner=_replicate(jnp.asarray(placement.owner), mesh),
        local_slot=_replicate(jnp.asarray(placement.local_slot), mesh),
        local_centers=placed[0], list_recon=placed[1],
        list_recon_sq=placed[2], list_indices=placed[3],
        list_sizes=placed[4],
        codebooks=(_replicate(code_leaves[0], mesh)
                   if code_leaves is not None else None),
        list_code_lanes=placed[5] if code_leaves is not None else None,
        list_code_rsq=placed[6] if code_leaves is not None else None,
        metric=metric, size=size, pq_bits=int(pq_bits),
        group_est=float(group_est), placement=placement)


def shard_by_list(handle, index, *,
                  placement: Optional[Placement] = None,
                  replication_factor: int = 1) -> RoutedIndex:
    """Partition a single-chip IVF-PQ index's lists across the mesh.

    The index must carry the reconstruction cache (the shard-local scan
    is the recon formulation).  ``placement`` defaults to an LPT balance
    over *live* list sizes (tombstones excluded — dead rows cost scan
    work but a rebalance pass compacts them away, so balancing on live
    rows keeps the placement stable across compactions).

    ``replication_factor=r > 1`` materializes ``r`` copies of every
    list on distinct shards (see :func:`compute_placement`): each shard's
    stacked leaves hold the union of its per-rank owned sets, healthy
    routing serves every list from its primary, and a failed shard's
    lists fail over to replicas with results bit-identical to the
    healthy run (ignored when an explicit ``placement`` is passed — the
    placement carries its own factor)."""
    with named_range("distributed::shard_by_list"):
        expects(handle.comms_initialized(),
                "distributed.ann.shard_by_list: handle has no comms")
        expects(getattr(index, "list_recon", None) is not None,
                "distributed.ann.shard_by_list: index must carry the "
                "reconstruction cache (build with "
                "cache_reconstructions=True)")
        comms, mesh, axis, n_dev, devs = _mesh_layout(handle)
        if placement is None:
            live = _mutate.live_sizes(index.list_indices)
            placement = compute_placement(
                np.asarray(live), n_dev,
                replication_factor=replication_factor)
        rsq = index.list_recon_sq
        if rsq is None:
            rsq = ivf_pq._recon_sq(index.list_recon)
        size = int(jnp.sum(index.list_sizes))
        # carry the compact-code cache when the base index is eligible
        # (the routed fused scan streams the lane-major codes at
        # 4*ceil(W/4)+8 B/row instead of the 2*rot+8 recon rows)
        code_leaves = None
        pq_bits = 0
        if ivf_pq._codes_mode_eligible(index):
            if (index.list_code_lanes is None
                    or index.list_code_rsq is None):
                index = ivf_pq._with_code_lanes(index)
            code_leaves = (index.codebooks, index.list_code_lanes,
                           index.list_code_rsq)
            pq_bits = int(index.pq_bits)
        out = _place_lists(
            handle, (index.centers, index.list_recon, rsq,
                     index.list_indices, index.list_sizes),
            index.rotation, placement, index.metric, size,
            code_leaves=code_leaves, pq_bits=pq_bits,
            group_est=float(getattr(index, "group_est", 0.0)))
        out.canaries = getattr(index, "canaries", None)
        out.generation = _mutate.generation(index)
        # precompute the fused kernels' id-exactness verdict now (one
        # tiny host sync at shard time) so search dispatch never syncs
        grouped.ids_f32_exact(out, out.list_indices)
        return out


def _build_by_list(handle, params: ivf_pq.IndexParams, dataset,
                   replication_factor: int = 1) -> RoutedIndex:
    with named_range("distributed::ivf_pq_build_by_list"):
        expects(handle.comms_initialized(),
                "distributed.ann.build: handle has no comms (use "
                "CommsSession.worker_handle())")
        expects(params.cache_reconstructions,
                "distributed.ann: the routed search kernel runs the "
                "reconstruction path; cache_reconstructions must be True")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n_dev, devs = _mesh_layout(handle)
        expects(params.n_lists >= n_dev,
                f"distributed.ann: by_list needs n_lists "
                f"({params.n_lists}, GLOBAL in this mode) >= the "
                f"{n_dev}-device mesh")
        # ONE global quantizer/codebook train — the coarse structure is
        # tiny and replicated; only the lists are partitioned
        base = ivf_pq.build(handle, params, dataset)
        return shard_by_list(handle, base,
                             replication_factor=replication_factor)


def _gather_global(index: RoutedIndex):
    """Reassemble the global per-list arrays from the stacked shards
    (admin path: rebalance / serialization — one cross-device gather of
    each leaf, never on the serving path)."""
    own = jnp.asarray(np.asarray(index.owner), jnp.int32)
    slot = jnp.asarray(np.asarray(index.local_slot), jnp.int32)
    centers = index.local_centers[own, slot]
    recon = index.list_recon[own, slot]
    rsq = index.list_recon_sq[own, slot]
    li = index.list_indices[own, slot]
    sizes = index.list_sizes[own, slot]
    code_leaves = None
    if index.list_code_lanes is not None:
        code_leaves = (index.codebooks, index.list_code_lanes[own, slot],
                       index.list_code_rsq[own, slot])
    return centers, recon, rsq, li, sizes, code_leaves


def route_vectors(index: RoutedIndex, vectors) -> np.ndarray:
    """The distributed WRITE path's list router (round 19): the global
    IVF list each row lands in, ranked by the SAME replicated coarse
    quantizer the probe path uses — a row's home list is its top probe
    (``n_probes=1``), so a written row is found by exactly the probes
    that would scan it after a fold.  One jitted call keyed by the
    write-batch shape; :func:`raft_tpu.core.aot.warm_write_router`
    pre-traces the serving batch shapes so the first write after a
    deploy or failover is compile-free."""
    vecs = jnp.asarray(vectors, jnp.float32)
    expects(vecs.ndim == 2 and vecs.shape[1] == index.dim,
            f"distributed.ann.route_vectors: vectors must be "
            f"(n, {index.dim}), got {tuple(vecs.shape)}")
    probes = ivf_pq._select_clusters(index.coarse_centers, index.rotation,
                                     vecs, 1, DistanceType(index.metric))
    return np.asarray(probes).reshape(-1)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric",
                                             "axis_name", "mesh", "failed"))
def _dist_search_routed(sharded, replicated, queries, k, n_probes, metric,
                        axis_name, mesh, failed=(), filter_words=None):
    sspecs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                   for leaf in sharded)
    rspecs = tuple(P() for _ in replicated)
    # the bitset addresses GLOBAL ids — the routed list_indices store
    # exactly those, so one replicated buffer serves every shard and the
    # replica-failover table swaps compose unchanged (replica copies are
    # identical rows, so the admission fold commutes with routing)
    has_f = filter_words is not None
    in_specs = (sspecs, rspecs, P()) + ((P(),) if has_f else ())
    out_specs = (P(),) * (5 if has_f else 4)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    def run(sl, rl, q, *rest):
        local_centers, list_recon, list_recon_sq, list_indices = sl
        coarse, rot, owner, local_slot = rl
        s = jax.lax.axis_index(axis_name)
        cap = list_recon.shape[2]
        # replicated coarse routing: every shard ranks the SAME probe
        # set deterministically, so ownership tests need no exchange
        probes = ivf_pq._select_clusters(coarse, rot, q, n_probes, metric)
        # per-list probe histogram for the routing policy's heat window:
        # built from the REPLICATED probe set (identical on every
        # shard), so it replicates for free — and it stays a lazy
        # device array until a maintenance-path refresh reads it
        hist = jnp.zeros((owner.shape[0],), jnp.int32).at[
            probes.reshape(-1)].add(1)
        owned = owner[probes] == s                       # (q, n_probes)
        dummy = local_centers.shape[1] - 1               # static slot L
        local_probes = jnp.where(owned, local_slot[probes],
                                 dummy).astype(jnp.int32)
        # unowned probes point at the dummy slot: all-(-1) ids lower to
        # the worst-distance padded-row path inside the scan — the same
        # mask tombstones ride, so this is the existing kernel untouched
        ld, li = ivf_pq._search_impl_recon(
            local_centers[0], list_recon[0], list_indices[0], rot, q,
            k, n_probes, metric, probes=local_probes,
            list_recon_sq=list_recon_sq[0],
            filter_words=rest[0] if has_f else None)
        select_min = metric != DistanceType.InnerProduct
        scanned = (jnp.sum(owned.astype(jnp.int32)) * cap).astype(
            jnp.int32)
        if failed:
            bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
            sentinel = jnp.inf if select_min else -jnp.inf
            ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
            li = jnp.where(bad, jnp.full_like(li, -1), li)
            scanned = jnp.where(bad, 0, scanned)
        # the k-bounded candidate exchange: exactly (q, k) pairs per
        # shard regardless of index size — the payload the data-parallel
        # path also ships, but here each pair was mined from 1/n_shards
        # of the probed rows
        all_d = jax.lax.all_gather(ld, axis_name)        # (n_dev, q, k)
        all_i = jax.lax.all_gather(li, axis_name)
        all_scanned = jax.lax.all_gather(scanned, axis_name)  # (n_dev,)
        nq = q.shape[0]
        # hierarchical exactness: a global top-k candidate is in its
        # owning shard's local top-k, so the replicated merge over the
        # (n_dev * k)-wide survivors equals the single-index search.
        # sqrt=False: the shard-local epilogue already applied it for
        # the sqrt metrics, and the merge is monotone
        md, mi = grouped.finalize_topk(
            jnp.transpose(all_d, (1, 0, 2)),
            jnp.transpose(all_i, (1, 0, 2)),
            nq, k, select_min, False, select_k)
        if has_f:
            admitted = jax.lax.all_gather(
                jnp.sum((li >= 0).astype(jnp.int32)), axis_name)
            return md, mi, all_scanned, hist, admitted
        return md, mi, all_scanned, hist

    args = (sharded, replicated, queries) + (
        (filter_words,) if has_f else ())
    return run(*args)


def _routed_leaves(index: "RoutedIndex", form: str):
    """(sharded, replicated) leaf tuples for the routed grouped dispatch.
    ``fused_codes`` threads the lane-major code cache where the recon
    forms thread the bf16 reconstructions — the kernels share positional
    structure (data, row-norms), so ONE jitted dispatcher serves both."""
    if form == "fused_codes":
        sharded = (index.local_centers, index.list_code_lanes,
                   index.list_code_rsq, index.list_indices)
        replicated = (index.coarse_centers, index.rotation, index.owner,
                      index.local_slot, index.codebooks)
    else:
        sharded = (index.local_centers, index.list_recon,
                   index.list_recon_sq, index.list_indices)
        replicated = (index.coarse_centers, index.rotation, index.owner,
                      index.local_slot)
    return sharded, replicated


@functools.partial(jax.jit, static_argnames=(
    "k", "kt", "n_probes", "metric", "axis_name", "mesh", "n_groups",
    "form", "pq_bits", "use_pallas", "merge_window", "failed"))
def _dist_search_routed_grouped(sharded, replicated, queries, k, kt,
                                n_probes, metric, axis_name, mesh,
                                n_groups, form, pq_bits=0,
                                use_pallas=False, merge_window=1,
                                failed=(), filter_words=None):
    """Routed (by_list) grouped/fused scan under ``shard_map``
    (round 10): the tentpole dispatch.  Replicated coarse routing picks
    the probe set, ownership maps it to local slots, and the shard scans
    its owned probes with the grouped formulation at the static capacity
    ``n_groups`` — the fused code scan streams 4*ceil(W/4)+8 B/row where
    the probe-order recon scan streamed 2*rot+8 (264 -> 72 at the bench
    shape).  Alongside the k-bounded candidate exchange, each shard
    all_gathers its true required group count so the HOST can check the
    calibrated capacity without a second collective; the check itself
    (and the rare exact re-dispatch) lives in :func:`search`, keeping
    this function sync-free."""
    sspecs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                   for leaf in sharded)
    rspecs = tuple(P() for _ in replicated)
    has_f = filter_words is not None
    in_specs = (sspecs, rspecs, P()) + ((P(),) if has_f else ())
    out_specs = (P(),) * (6 if has_f else 5)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    def run(sl, rl, q, *rest):
        local_centers, data, rownorm, list_indices = sl
        fw = rest[0] if has_f else None
        coarse, rot, owner, local_slot = rl[:4]
        s = jax.lax.axis_index(axis_name)
        slots = local_centers.shape[1]
        cap = list_indices.shape[2]
        probes = ivf_pq._select_clusters(coarse, rot, q, n_probes, metric)
        # replicated per-list probe histogram (identical on every shard
        # — the probe set is) for the routing policy's heat window; a
        # lazy device array until a maintenance-path refresh
        hist = jnp.zeros((owner.shape[0],), jnp.int32).at[
            probes.reshape(-1)].add(1)
        owned = owner[probes] == s                       # (q, n_probes)
        # unowned probes map to the OUT-OF-RANGE sentinel slot id
        # (== slots), NOT the dummy slot: build_groups drops sentinel
        # probes from the pair groups entirely, so they cost no group
        # slots.  Mapping them to the dummy slot (the probe-order path's
        # trick) would funnel ~(1 - 1/n_shards) of all pairs into ONE
        # list and blow any calibrated capacity.
        local_probes = jnp.where(owned, local_slot[probes],
                                 slots).astype(jnp.int32)
        if form == "fused_codes":
            ld, li = ivf_pq._search_impl_fused_codes_grouped(
                local_centers[0], rl[4], data[0], rownorm[0],
                list_indices[0], rot, q, local_probes, k, kt, metric,
                n_groups, pq_bits, merge_window=merge_window,
                filter_words=fw)
        elif form == "fused_recon":
            ld, li = ivf_pq._search_impl_fused_recon_grouped(
                local_centers[0], data[0], rownorm[0], list_indices[0],
                rot, q, local_probes, k, kt, metric, n_groups,
                merge_window=merge_window, filter_words=fw)
        else:
            rot_dim = data.shape[3]
            G = grouped.GROUP
            block = grouped.block_size(n_groups, G * cap * 8,
                                       cap * rot_dim * 2, G * rot_dim * 4)
            ld, li = ivf_pq._search_impl_recon_grouped(
                local_centers[0], data[0], rownorm[0], list_indices[0],
                rot, q, local_probes, k, metric, n_groups, block,
                use_pallas=use_pallas, kt=kt, filter_words=fw)
        select_min = metric != DistanceType.InnerProduct
        scanned = (jnp.sum(owned.astype(jnp.int32)) * cap).astype(
            jnp.int32)
        # the shard's TRUE group requirement — the in-graph overflow
        # count the calibrated-capacity regime is checked against
        needed = grouped.num_groups(local_probes, slots)
        if failed:
            bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
            sentinel = jnp.inf if select_min else -jnp.inf
            ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
            li = jnp.where(bad, jnp.full_like(li, -1), li)
            scanned = jnp.where(bad, 0, scanned)
            needed = jnp.where(bad, 0, needed)
        all_d = jax.lax.all_gather(ld, axis_name)        # (n_dev, q, k)
        all_i = jax.lax.all_gather(li, axis_name)
        all_scanned = jax.lax.all_gather(scanned, axis_name)  # (n_dev,)
        all_needed = jax.lax.all_gather(needed, axis_name)    # (n_dev,)
        nq = q.shape[0]
        md, mi = grouped.finalize_topk(
            jnp.transpose(all_d, (1, 0, 2)),
            jnp.transpose(all_i, (1, 0, 2)),
            nq, k, select_min, False, select_k)
        if has_f:
            admitted = jax.lax.all_gather(
                jnp.sum((li >= 0).astype(jnp.int32)), axis_name)
            return md, mi, all_scanned, all_needed, hist, admitted
        return md, mi, all_scanned, all_needed, hist

    args = (sharded, replicated, queries) + (
        (filter_words,) if has_f else ())
    return run(*args)


def rebalance_placement(handle, index: RoutedIndex, *,
                        placement: Optional[Placement] = None
                        ) -> RoutedIndex:
    """Recompute the list partition from *live* row counts and re-shard.

    The swap is a single global generation bump — the barrier the
    serving tier needs: the new pytree is assembled completely (every
    shard's leaves) before anything is published, and
    ``Executor.swap_index`` installs it with one atomic reference swap
    after warming, so no reader ever sees shard ``a`` at placement ``g``
    and shard ``b`` at ``g+1``.  The placement generation advances with
    it, invalidating placement-keyed cache entries."""
    with named_range("distributed::rebalance_placement"):
        expects(index.placement is not None,
                "distributed.ann.rebalance_placement: index carries no "
                "placement map")
        centers, recon, rsq, li, sizes, code_leaves = _gather_global(index)
        if placement is None:
            live = jnp.sum(li >= 0, axis=1).astype(jnp.int32)
            placement = compute_placement(
                np.asarray(live), index.n_shards,
                generation=index.placement.generation + 1,
                replication_factor=index.placement.replication_factor)
        out = _place_lists(handle, (centers, recon, rsq, li, sizes),
                           index.rotation, placement, index.metric,
                           index.size, code_leaves=code_leaves,
                           pq_bits=index.pq_bits,
                           group_est=index.group_est)
        out.canaries = index.canaries
        _mutate.next_generation(index, out)
        return out


# v2 (round 10): trailing (has_codes, pq_bits, group_est) block and,
# when has_codes, the lane-major compact-code cache (codebooks, lanes,
# row norms) — v1 streams read fine and land uncalibrated/recon-only.
# v3 (round 17): the embedded placement envelope may be placement-v2
# (replicated rank tables); the routed body layout is unchanged, the
# bump marks the back-compat read window.  v1/v2 streams still read
# (and land r=1); v2 READERS cannot open a replicated v3 stream — the
# version check fails loudly instead of mis-parsing the rank tables.
_ROUTED_SERIALIZATION_VERSION = 3
_ROUTED_MIN_READ_VERSION = 1


def serialize_routed(res, stream, index: RoutedIndex) -> None:
    """CRC32-enveloped dump of a routed index: the placement map rides
    in the envelope next to the global per-list arrays (reassembled from
    the shards), so a reload lands lists on the same owners.  The bf16
    recon cache is stored as uint16 views (the npy format carries no
    bfloat16 descr — same trick :mod:`raft_tpu.core.aot` uses)."""
    expects(index.placement is not None,
            "distributed.ann.serialize_routed: index carries no "
            "placement map")
    centers, recon, rsq, li, sizes, code_leaves = _gather_global(index)
    with ser.enveloped_writer(stream) as body:
        ser.serialize_scalar(
            res, body, np.int32(_ROUTED_SERIALIZATION_VERSION))
        ser.serialize_scalar(res, body, np.int32(index.metric))
        ser.serialize_scalar(res, body, np.int64(index.size))
        ser.serialize_scalar(
            res, body, np.int64(_mutate.generation(index)))
        placement_to_stream(res, body, index.placement)
        ser.serialize_mdspan(res, body, centers)
        ser.serialize_mdspan(
            res, body, np.asarray(jax.device_get(recon)).view(np.uint16))
        ser.serialize_mdspan(res, body, rsq)
        ser.serialize_mdspan(res, body, li)
        ser.serialize_mdspan(res, body, sizes)
        ser.serialize_mdspan(res, body, index.rotation)
        ser.serialize_scalar(
            res, body, np.int32(1 if code_leaves is not None else 0))
        ser.serialize_scalar(res, body, np.int32(index.pq_bits))
        ser.serialize_scalar(res, body, np.float64(index.group_est))
        if code_leaves is not None:
            books, lanes, crsq = code_leaves
            ser.serialize_mdspan(res, body, books)
            ser.serialize_mdspan(res, body, lanes)
            ser.serialize_mdspan(res, body, crsq)
        from raft_tpu.integrity import canary as _canary
        _canary.to_stream(res, body, index.canaries)


def deserialize_routed(handle, stream) -> RoutedIndex:
    """Reload a routed index onto the handle's mesh under its stored
    placement (the mesh must match the stored shard count).  v1 streams
    (pre round 10) load recon-only and uncalibrated — always correct,
    just without the fused code scan and tightened capacity."""
    body = ser.open_envelope(stream)
    version = int(ser.deserialize_scalar(handle, body))
    if not (_ROUTED_MIN_READ_VERSION <= version
            <= _ROUTED_SERIALIZATION_VERSION):
        raise ValueError(
            f"routed serialization version mismatch: got {version}, "
            f"expected {_ROUTED_MIN_READ_VERSION}.."
            f"{_ROUTED_SERIALIZATION_VERSION}")
    metric = int(ser.deserialize_scalar(handle, body))
    size = int(ser.deserialize_scalar(handle, body))
    generation = int(ser.deserialize_scalar(handle, body))
    placement = placement_from_stream(handle, body)
    centers = jnp.asarray(ser.deserialize_mdspan(handle, body))
    recon = jnp.asarray(
        ser.deserialize_mdspan(handle, body).view(jnp.bfloat16))
    rsq = jnp.asarray(ser.deserialize_mdspan(handle, body))
    li = jnp.asarray(ser.deserialize_mdspan(handle, body))
    sizes = jnp.asarray(ser.deserialize_mdspan(handle, body))
    rotation = jnp.asarray(ser.deserialize_mdspan(handle, body))
    code_leaves = None
    pq_bits = 0
    group_est = 0.0
    if version >= 2:
        has_codes = int(ser.deserialize_scalar(handle, body))
        pq_bits = int(ser.deserialize_scalar(handle, body))
        group_est = float(ser.deserialize_scalar(handle, body))
        if has_codes:
            books = jnp.asarray(ser.deserialize_mdspan(handle, body))
            lanes = jnp.asarray(ser.deserialize_mdspan(handle, body))
            crsq = jnp.asarray(ser.deserialize_mdspan(handle, body))
            code_leaves = (books, lanes, crsq)
    from raft_tpu.integrity import canary as _canary
    canaries = _canary.from_stream(handle, body)
    out = _place_lists(handle, (centers, recon, rsq, li, sizes),
                       rotation, placement, metric, size,
                       code_leaves=code_leaves, pq_bits=pq_bits,
                       group_est=group_est)
    out.canaries = canaries
    out.generation = generation
    return out


# ---------------------------------------------------------------------------
# IVF-Flat (same shard -> local search -> all_gather -> merge seam)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DistributedFlatIndex:
    """Leaf-stacked local IVF-Flat indexes (one shard per device)."""

    centers: jax.Array        # (n_dev, n_lists, dim)
    list_data: jax.Array      # (n_dev, n_lists, cap, dim)
    list_indices: jax.Array   # (n_dev, n_lists, cap) — GLOBAL ids
    list_sizes: jax.Array
    metric: int = DistanceType.L2Expanded
    size: int = 0
    # per-shard recall canaries — host-side, not a pytree leaf
    shard_canaries: Optional[tuple] = None

    @property
    def n_shards(self) -> int:
        return self.centers.shape[0]

    def tree_flatten(self):
        return ((self.centers, self.list_data, self.list_indices,
                 self.list_sizes), (self.metric, self.size))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], size=aux[1])


def _shard_layout(handle, dataset):
    comms = handle.get_comms()
    mesh = handle.mesh
    axis = comms.axis_name
    expects(mesh.devices.ndim == 1,
            "distributed.ann: a 1-D mesh is required (reshape 2D grids "
            "to the data axis for index sharding)")
    n = dataset.shape[0]
    n_dev = mesh.shape[axis]
    expects(n % n_dev == 0,
            f"distributed.ann: n ({n}) must divide evenly over "
            f"{n_dev} devices (pad the input)")
    return comms, mesh, axis, n, n_dev, n // n_dev, mesh.devices.ravel()


def build_flat(handle, params, dataset, *,
               retry_policy: Optional[_retry.RetryPolicy] = None,
               deadline: Optional[_retry.Deadline] = None
               ) -> DistributedFlatIndex:
    """Shard rows over the mesh and build one local IVF-Flat index per
    shard, ids globally offset (the ANN bench ``multigpu`` seam,
    docs/source/cuda_ann_benchmarks.md:163, for raft_ivf_flat)."""
    return _entry("distributed.ann.build_flat",
                  lambda: _build_flat_impl(handle, params, dataset),
                  retry_policy, deadline)


def _build_flat_impl(handle, params, dataset) -> DistributedFlatIndex:
    from raft_tpu.neighbors import ivf_flat

    with named_range("distributed::ivf_flat_build"):
        expects(handle.comms_initialized(),
                "distributed.ann.build_flat: handle has no comms")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n, n_dev, per, devs = _shard_layout(
            handle, dataset)

        locals_ = []
        for s in range(n_dev):
            idx = ivf_flat.build(handle, params, dataset[s * per:(s + 1) * per])
            idx.list_indices = jnp.where(
                idx.list_indices >= 0, idx.list_indices + s * per, -1)
            locals_.append(idx)
        cap = max(ix.capacity for ix in locals_)

        def pad_cap(a, fill):
            return jnp.pad(a, ((0, 0), (0, cap - a.shape[1]))
                           + ((0, 0),) * (a.ndim - 2),
                           constant_values=fill)

        leaves = [(ix.centers, pad_cap(ix.list_data, 0),
                   pad_cap(ix.list_indices, -1), ix.list_sizes)
                  for ix in locals_]
        placed = _stack_leaves(leaves, mesh, axis, devs)
        out = DistributedFlatIndex.tree_unflatten(
            (params.metric, n), tuple(placed))
        out.shard_canaries = _collect_canaries(locals_, per,
                                               offset_ids=True)
        return out


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric",
                                             "axis_name", "mesh", "failed"))
def _dist_search_flat(leaves, queries, k, n_probes, metric, axis_name,
                      mesh, failed=()):
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in leaves)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(specs, P()), out_specs=(P(), P()),
                       check_vma=False)
    def run(lv, q):
        from raft_tpu.neighbors import ivf_flat
        centers, list_data, list_indices, _ = lv
        ld, li = ivf_flat._search_impl(centers[0], list_data[0],
                                       list_indices[0], q, k, n_probes,
                                       metric)
        select_min = metric != DistanceType.InnerProduct
        if failed:
            s = jax.lax.axis_index(axis_name)
            bad = jnp.any(jnp.asarray(failed, jnp.int32) == s)
            sentinel = jnp.inf if select_min else -jnp.inf
            ld = jnp.where(bad, jnp.full_like(ld, sentinel), ld)
            li = jnp.where(bad, jnp.full_like(li, -1), li)
        all_d = jax.lax.all_gather(ld, axis_name)
        all_i = jax.lax.all_gather(li, axis_name)
        nq = q.shape[0]
        return select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)

    return run(leaves, queries)


def search_flat(handle, params, index: DistributedFlatIndex, queries,
                k: int, *,
                failed_shards: Sequence[int] = (),
                return_status: bool = False,
                retry_policy: Optional[_retry.RetryPolicy] = None,
                deadline: Optional[_retry.Deadline] = None):
    """Sharded IVF-Flat search + merge; replicated (distances, ids).
    Same degraded-mode / retry contract as :func:`search`."""
    with named_range("distributed::ivf_flat_search"):
        expects(handle.comms_initialized(),
                "distributed.ann.search_flat: handle has no comms")
        comms = handle.get_comms()
        queries = ensure_array(queries, "queries")
        n_probes = min(params.n_probes, index.centers.shape[1])
        leaves = (index.centers, index.list_data, index.list_indices,
                  index.list_sizes)
        failed = _degraded_set(index.n_shards, failed_shards)
        # same straggler seam as search(): host-side pause, exact merge
        stragglers = faults.straggler_pause(index.n_shards)
        if stragglers:
            _flight.record_event("distributed.straggler",
                                 delays_s=list(stragglers),
                                 n_shards=index.n_shards)
        d, i = _entry(
            "distributed.ann.search_flat",
            lambda: _dist_search_flat(leaves, queries, int(k), n_probes,
                                      index.metric, comms.axis_name,
                                      handle.mesh, failed=failed),
            retry_policy, deadline)
        if not return_status:
            return d, i
        status = np.ones(index.n_shards, np.int8)
        status[list(failed)] = 0
        return d, i, jnp.asarray(status)


# ---------------------------------------------------------------------------
# CAGRA (reference's explicit multi-GPU seam: per-GPU graph chunks +
# merged search, detail/cagra/graph_core.cuh:333-369)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DistributedCagraIndex:
    """Per-shard CAGRA graphs + packed walk tables, leaf-stacked.  Ids
    inside each shard's graph/table are LOCAL (0..per-1); search maps
    them to global ids with the shard offset.  ``use_walk=False`` (walk
    fidelity calibration failed, or the per-shard table exceeds the
    byte gate — the same routes single-device ``cagra.search`` takes)
    stores (1, 1)-placeholder walk leaves and searches via the exact
    direct walk over ``graph``."""

    dataset: jax.Array        # (n_dev, per, dim)
    graph: jax.Array          # (n_dev, per, deg)
    table: jax.Array          # (n_dev, per, W) int16 packed neighborhoods
    proj: jax.Array           # (n_dev, dim, pdim)
    entry_proj: jax.Array     # (n_dev, S, pdim) bf16
    entry_sq: jax.Array       # (n_dev, S)
    entry_ids: jax.Array      # (n_dev, S) int32 LOCAL
    metric: int = DistanceType.L2Expanded
    size: int = 0
    use_walk: bool = True
    # per-shard recall canaries — host-side, not a pytree leaf; CAGRA
    # shard ids stay LOCAL, so these carry local ground-truth ids
    shard_canaries: Optional[tuple] = None

    @property
    def n_shards(self) -> int:
        return self.dataset.shape[0]

    def tree_flatten(self):
        return ((self.dataset, self.graph, self.table, self.proj,
                 self.entry_proj, self.entry_sq, self.entry_ids),
                (self.metric, self.size, self.use_walk))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], size=aux[1], use_walk=aux[2])


def build_cagra(handle, params, dataset, *,
                retry_policy: Optional[_retry.RetryPolicy] = None,
                deadline: Optional[_retry.Deadline] = None
                ) -> DistributedCagraIndex:
    """Shard rows over the mesh and build one local CAGRA graph + packed
    walk table per shard (reference: graph_core.cuh:333-369 builds the
    kNN graph in per-GPU chunks; here each shard also serves its own
    walk).  A single projection dim (calibrated on shard 0) is forced on
    every shard so the packed tables stack; when calibration fails
    (pdim 0) or the per-shard table exceeds the byte gate, the index
    falls back to the exact direct walk — the same two routes
    single-device ``cagra.search`` takes."""
    return _entry("distributed.ann.build_cagra",
                  lambda: _build_cagra_impl(handle, params, dataset),
                  retry_policy, deadline)


def _build_cagra_impl(handle, params, dataset) -> DistributedCagraIndex:
    from raft_tpu.neighbors import cagra

    with named_range("distributed::cagra_build"):
        expects(handle.comms_initialized(),
                "distributed.ann.build_cagra: handle has no comms")
        dataset = ensure_array(dataset, "dataset")
        comms, mesh, axis, n, n_dev, per, devs = _shard_layout(
            handle, dataset)

        locals_, shard_idxs, pdim, use_walk = [], [], None, True
        for s in range(n_dev):
            idx = cagra.build(handle, params, dataset[s * per:(s + 1) * per])
            shard_idxs.append(idx)
            if pdim is None:
                pdim = cagra._auto_pdim(idx)
                use_walk = (pdim > 0 and cagra._table_bytes(
                    per, idx.graph_degree, pdim, False)
                    <= cagra._WALK_TABLE_MAX_BYTES)
            if use_walk:
                cache = cagra._walk_cache(handle, idx, pdim, 4096)
                walk_leaves = (cache.table, cache.proj, cache.entry_proj,
                               cache.entry_sq, cache.entry_ids)
            else:
                walk_leaves = (jnp.zeros((1, 1), jnp.int16),
                               jnp.zeros((1, 1), jnp.float32),
                               jnp.zeros((1, 1), jnp.bfloat16),
                               jnp.zeros((1,), jnp.float32),
                               jnp.zeros((1,), jnp.int32))
            locals_.append((idx.dataset, idx.graph) + walk_leaves)
        placed = _stack_leaves(locals_, mesh, axis, devs)
        out = DistributedCagraIndex.tree_unflatten(
            (params.metric, n, use_walk), tuple(placed))
        # CAGRA shard ids are local: ground truth needs no offset
        out.shard_canaries = _collect_canaries(shard_idxs, per,
                                               offset_ids=False)
        return out


@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric", "rerank",
    "deg", "axis_name", "mesh", "use_walk", "n_samplings"))
def _dist_search_cagra(leaves, queries, seed_key, k, itopk, search_width,
                       max_iterations, metric, rerank, deg, axis_name,
                       mesh, use_walk, n_samplings=1):
    specs = tuple(P(axis_name, *([None] * (leaf.ndim - 1)))
                  for leaf in leaves)
    select_min = metric != DistanceType.InnerProduct

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(specs, P(), P()), out_specs=(P(), P()),
                       check_vma=False)
    def run(lv, q, skey):
        from raft_tpu.neighbors import cagra
        ds, graph, table, proj, ep, esq, eids = lv
        per = ds.shape[1]
        s = jax.lax.axis_index(axis_name)
        if use_walk:
            d, i = cagra._search_impl_walk(
                ds[0], table[0], ep[0], esq[0], eids[0], proj[0], q, k,
                itopk, search_width, max_iterations, metric, rerank, deg)
        else:
            # same seed-count formula as single-device cagra.search
            n_seeds = max(itopk,
                          min(per, max(n_samplings * 4 * itopk, 128)))
            seed_ids = jax.random.randint(
                jax.random.fold_in(skey, s), (q.shape[0], n_seeds), 0,
                per, dtype=jnp.int32)
            d, i = cagra._search_impl(ds[0], graph[0], q, seed_ids, k,
                                      itopk, search_width,
                                      max_iterations, metric)
        i = jnp.where(i >= 0, i + s * per, -1)
        all_d = jax.lax.all_gather(d, axis_name)
        all_i = jax.lax.all_gather(i, axis_name)
        nq = q.shape[0]
        return select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)

    return run(leaves, queries, seed_key)


def search_cagra(handle, params, index: DistributedCagraIndex, queries,
                 k: int, *,
                 retry_policy: Optional[_retry.RetryPolicy] = None,
                 deadline: Optional[_retry.Deadline] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Sharded CAGRA walk + merge; replicated (distances, global ids).
    Transient faults at entry (site ``distributed.ann.search_cagra``)
    are retried — the seed key is drawn once, so a retried query
    answers identically."""
    with named_range("distributed::cagra_search"):
        expects(handle.comms_initialized(),
                "distributed.ann.search_cagra: handle has no comms")
        comms = handle.get_comms()
        queries = ensure_array(queries, "queries")
        itopk = max(params.itopk_size, k)
        max_iter = params.max_iterations or (
            10 + itopk // max(params.search_width, 1))
        rerank = min(itopk, params.rerank_topk or max(32, 2 * k))
        rerank = max(rerank, k)
        deg = index.graph.shape[2]
        leaves = (index.dataset, index.graph, index.table, index.proj,
                  index.entry_proj, index.entry_sq, index.entry_ids)
        seed_key = handle.next_key()
        return _entry(
            "distributed.ann.search_cagra",
            lambda: _dist_search_cagra(
                leaves, queries, seed_key, int(k), itopk,
                params.search_width, max_iter, index.metric, rerank, deg,
                comms.axis_name, handle.mesh, index.use_walk,
                n_samplings=max(params.num_random_samplings, 1)),
            retry_policy, deadline)


# ---------------------------------------------------------------------------
# per-shard recall-canary health checks (raft_tpu.integrity)
# ---------------------------------------------------------------------------

def _collect_canaries(shard_indexes, per, *, offset_ids):
    """Gather per-shard CanarySets off the local indexes.  ``offset_ids``
    globalizes the stored ground-truth ids to match the stacked leaves'
    id space (IVF shards store GLOBAL ids; CAGRA shards stay local)."""
    cans = [getattr(ix, "canaries", None) for ix in shard_indexes]
    if all(c is None for c in cans):
        return None
    out = []
    for s, cs in enumerate(cans):
        if cs is not None and offset_ids and s > 0:
            cs = dataclasses.replace(cs, gt_ids=cs.gt_ids + s * per)
        out.append(cs)
    return tuple(out)


def _local_index(index, s):
    """Reassemble shard ``s`` as a single-device index (a leaf slice —
    the stacked layout is exactly the local index layout plus a leading
    shard axis)."""
    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq
    if isinstance(index, DistributedIndex):
        out = ivf_pq.Index(
            centers=index.centers[s], codebooks=index.codebooks[s],
            list_codes=index.list_codes[s],
            list_indices=index.list_indices[s],
            list_sizes=index.list_sizes[s], rotation=index.rotation[s],
            metric=index.metric, list_recon=index.list_recon[s])
    elif isinstance(index, DistributedFlatIndex):
        out = ivf_flat.Index(
            centers=index.centers[s], list_data=index.list_data[s],
            list_indices=index.list_indices[s],
            list_sizes=index.list_sizes[s], metric=index.metric)
    elif isinstance(index, DistributedCagraIndex):
        out = cagra.Index(dataset=index.dataset[s], graph=index.graph[s],
                          metric=index.metric)
    else:
        raise TypeError(
            f"distributed.ann.health_check: unsupported index type "
            f"{type(index).__name__}")
    # the local view serves the parent's data snapshot: carry its
    # generation so generation-keyed executable caches stay distinct
    out.generation = _mutate.generation(index)
    return out


def health_check(handle, index, *, raise_on_fail: bool = True,
                 health=None):
    """Re-search every shard's stored recall canaries and compare against
    the stored floor (see :func:`raft_tpu.integrity.health_check`).

    Returns a list with one :class:`~raft_tpu.integrity.CanaryReport`
    (or ``None``) per shard, or ``None`` when the index carries no
    canaries.  With ``raise_on_fail`` (default) the first failing shard
    raises :class:`~raft_tpu.integrity.IntegrityError` — the error names
    the shard in its message.

    ``health`` (a :class:`raft_tpu.distributed.health.HealthTracker`)
    consumes the verdicts: a failing shard's canary notes a canary
    failure (ticking ``integrity.canary_failure`` with the shard id),
    a passing shard notes OK — repeated failures drive the shard
    through SUSPECT into FAILED, repeated passes clear SUSPECT back to
    HEALTHY.  On the routed path the global canary set cannot localize
    the failure; its verdict is attributed to every shard not already
    HEALTHY (the suspects are the plausible culprits), or to all shards
    when none is suspect."""
    from raft_tpu.integrity import IntegrityError
    from raft_tpu.integrity import canary as _canary

    def _note(shard, passed):
        if health is None:
            return
        if passed:
            health.note_ok(shard)
        else:
            health.note_canary_failure(shard)

    if isinstance(index, RoutedIndex):
        # routed indexes carry ONE global canary set (the quantizer is
        # global); the routed search is globally exact, so the standard
        # single-index health check applies — it dispatches the search
        # through this module (canary._search_canaries)
        if index.canaries is None:
            return None
        try:
            report = _canary.health_check(handle, index,
                                          raise_on_fail=raise_on_fail)
        except IntegrityError:
            for s in _blame_shards(index.n_shards, health):
                _note(s, False)
            raise
        passed = report is None or report.ok
        targets = (range(index.n_shards) if passed
                   else _blame_shards(index.n_shards, health))
        for s in targets:
            _note(s, passed)
        return [report]
    cans = getattr(index, "shard_canaries", None)
    if cans is None:
        return None
    reports = []
    for s, cs in enumerate(cans):
        if cs is None:
            reports.append(None)
            continue
        local = _local_index(index, s)
        local.canaries = cs
        try:
            report = _canary.health_check(
                handle, local, raise_on_fail=raise_on_fail)
        except IntegrityError as e:
            _note(s, False)
            raise IntegrityError(f"shard {s}: {e}",
                                 invariant=e.invariant,
                                 coord=(s,) + tuple(e.coord or ())) from e
        _note(s, report is None or report.ok)
        reports.append(report)
    return reports


def _blame_shards(n_shards: int, health) -> Tuple[int, ...]:
    """Shards a non-localizable (global-canary) failure is attributed
    to: the tracker's non-HEALTHY shards when any exist — the plausible
    culprits — else every shard."""
    if health is not None:
        suspects = tuple(s for s in range(n_shards)
                         if health.state(s) != "HEALTHY")
        if suspects:
            return suspects
    return tuple(range(n_shards))
