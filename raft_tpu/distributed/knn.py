"""Multi-device (MNMG) brute-force kNN.

The reference's scale-out seam: shard the database row-wise, per-shard exact
kNN, then merge with per-part id translations
(``knn_merge_parts``, neighbors/brute_force.cuh:80 — SURVEY.md §5
"long-context analogue": shard → local select_k → allgather → merge-select).

TPU design: one shard_map — each device scans only its database shard
(queries replicated), local top-k, ``all_gather`` of the (k)-sized
candidates (tiny payload over ICI), merged top-k computed replicated.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.compat import shard_map
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import select_k
from raft_tpu.utils.precision import get_matmul_precision

P = jax.sharding.PartitionSpec


@functools.partial(jax.jit, static_argnames=("k", "metric", "axis_name",
                                             "mesh"))
def _dist_knn(db, queries, k, metric, axis_name, mesh):
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name, None), P()),
                       out_specs=(P(), P()),
                       check_vma=False)
    def run(db_shard, q):
        n_local = db_shard.shape[0]
        qf = q.astype(jnp.float32)
        dbf = db_shard.astype(jnp.float32)
        ip = jax.lax.dot_general(qf, dbf, (((1,), (1,)), ((), ())),
                                 precision=get_matmul_precision(),
                                 preferred_element_type=jnp.float32)
        if metric == DistanceType.InnerProduct:
            d = ip
            select_min = False
        else:
            qsq = jnp.sum(qf * qf, axis=1)
            dsq = jnp.sum(dbf * dbf, axis=1)
            d = jnp.maximum(qsq[:, None] + dsq[None, :] - 2.0 * ip, 0.0)
            select_min = True
        kk = min(k, n_local)
        ld, li = select_k(d, kk, select_min=select_min)
        # translate to global ids (knn_merge_parts' translations)
        li = li + jax.lax.axis_index(axis_name) * n_local
        all_d = jax.lax.all_gather(ld, axis_name)   # (n_dev, q, kk)
        all_i = jax.lax.all_gather(li, axis_name)
        nq = q.shape[0]
        md, mi = select_k(
            jnp.transpose(all_d, (1, 0, 2)).reshape(nq, -1), k,
            in_idx=jnp.transpose(all_i, (1, 0, 2)).reshape(nq, -1),
            select_min=select_min)
        if metric in (DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded):
            md = jnp.sqrt(jnp.maximum(md, 0.0))
        return md, mi

    return run(db, queries)


def knn(
    handle,
    database,
    queries,
    k: int,
    *,
    metric: int = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """Sharded exact kNN over the handle's mesh; returns replicated
    (distances, global indices) of shape (q, k)."""
    with named_range("distributed::knn"):
        expects(handle.comms_initialized(),
                "distributed.knn: handle has no comms (use "
                "CommsSession.worker_handle())")
        comms = handle.get_comms()
        mesh = handle.mesh
        database = ensure_array(database, "database")
        queries = ensure_array(queries, "queries")
        n = database.shape[0]
        n_dev = mesh.shape[comms.axis_name]
        expects(n % n_dev == 0,
                f"distributed.knn: n ({n}) must divide evenly over "
                f"{n_dev} devices (pad the input)")
        expects(k <= n // n_dev,
                "distributed.knn: k must be <= rows per shard")
        database = jax.device_put(
            database,
            jax.sharding.NamedSharding(mesh, P(comms.axis_name, None)))
        return _dist_knn(database, queries, k, metric, comms.axis_name, mesh)
