"""Multi-device (MNMG) k-means.

The reference ships no distributed *algorithms* — it ships the comms fabric
and pylibraft exposes the per-partition building blocks
(``compute_new_centroids``, kmeans.pyx:54) that cuML's Dask k-means drives
with a centroid allreduce per iteration (SURVEY.md §3.3).  BASELINE.md
config 5 requires the loop itself, so raft_tpu ships it natively.

TPU design: the whole Lloyd loop runs inside ONE jitted shard_map over the
session mesh — per-shard assignment (fused L2 1-NN) and partial sums, a
``comms.allreduce`` (psum over ICI) for sums/counts/shift, and the
convergence test replicated on every shard.  One compilation, zero
per-iteration host round-trips, collectives ride ICI — this is the pattern
the reference approximates with NCCL allreduce per Dask task.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster.kmeans import init_plus_plus
from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
from raft_tpu.comms.comms import Comms, op_t
from raft_tpu.core.compat import shard_map
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.fused_l2_nn import fused_l2_nn

P = jax.sharding.PartitionSpec


@functools.partial(jax.jit,
                   static_argnames=("n_clusters", "max_iter", "axis_name",
                                    "mesh"))
def _dist_lloyd(X, centroids0, tol, n_clusters, max_iter, axis_name, mesh):
    comms = Comms(axis_name=axis_name)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name, None), P()),
                       out_specs=(P(), P(), P()),
                       check_vma=False)
    def run(x_shard, c0):
        def cond(carry):
            _, it, shift = carry
            return jnp.logical_and(it < max_iter, shift >= tol)

        def body(carry):
            c, it, _ = carry
            d, labels = fused_l2_nn(x_shard, c)
            part_sums = jax.ops.segment_sum(
                x_shard.astype(jnp.float32), labels,
                num_segments=n_clusters)
            part_counts = jax.ops.segment_sum(
                jnp.ones(x_shard.shape[0], jnp.float32), labels,
                num_segments=n_clusters)
            # the MNMG allreduce step (cuML dask-kmeans pattern, SURVEY §3.3)
            sums = comms.allreduce(part_sums, op_t.SUM)
            counts = comms.allreduce(part_counts, op_t.SUM)
            new_c = jnp.where((counts > 0)[:, None],
                              sums / jnp.maximum(counts, 1.0)[:, None], c)
            shift = jnp.sum((new_c - c) ** 2)
            return new_c, it + 1, shift

        c, n_iter, _ = jax.lax.while_loop(
            cond, body, (c0.astype(jnp.float32), jnp.int32(0),
                         jnp.float32(jnp.inf)))
        d, labels = fused_l2_nn(x_shard, c)
        inertia = comms.allreduce(jnp.sum(d), op_t.SUM)
        return c, inertia, n_iter

    return run(X, centroids0)


def fit(
    handle,
    params: KMeansParams,
    X,
    *,
    centroids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Distributed k-means fit over the handle's mesh.

    ``handle`` must carry comms (see :class:`raft_tpu.comms.CommsSession`);
    ``X`` is (n, d) — resharded row-wise over the mesh axis if not already.
    Returns (centroids, inertia, n_iter), replicated.
    """
    with named_range("distributed::kmeans::fit"):
        expects(handle.comms_initialized(),
                "distributed.kmeans.fit: handle has no comms (use "
                "CommsSession.worker_handle())")
        comms = handle.get_comms()
        mesh = handle.mesh
        X = ensure_array(X, "X")
        n = X.shape[0]
        n_dev = mesh.shape[comms.axis_name]
        expects(n % n_dev == 0,
                f"distributed.kmeans.fit: n ({n}) must divide evenly over "
                f"{n_dev} devices (pad the input)")
        X = jax.device_put(
            X, jax.sharding.NamedSharding(mesh, P(comms.axis_name, None)))

        if params.init == InitMethod.Array:
            expects(centroids is not None,
                    "InitMethod.Array requires centroids")
            c0 = jnp.asarray(centroids)
        else:
            # init on a subsample (replicated); ++ on the full set would
            # need the distributed variant — subsampling matches the
            # reference's trainset-fraction approach for big-n builds
            take = min(n, max(params.n_clusters * 64, 16384))
            c0 = init_plus_plus(handle, X[:take], params.n_clusters,
                                key=jax.random.key(params.seed))
        return _dist_lloyd(X, c0, jnp.float32(params.tol),
                           params.n_clusters, params.max_iter,
                           comms.axis_name, mesh)


def predict(handle, params: KMeansParams, X, centroids) -> jax.Array:
    """Distributed predict: per-shard nearest centroid (labels gathered)."""
    comms = handle.get_comms()
    mesh = handle.mesh
    X = ensure_array(X, "X")
    X = jax.device_put(
        X, jax.sharding.NamedSharding(mesh, P(comms.axis_name, None)))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(comms.axis_name, None), P()),
                       out_specs=P(comms.axis_name),
                       check_vma=False)
    def run(x_shard, c):
        _, labels = fused_l2_nn(x_shard, c)
        return labels

    return jax.jit(run)(X, jnp.asarray(centroids))
