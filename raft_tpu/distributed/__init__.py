"""Distributed (MNMG) algorithms over the comms fabric.

The reference ships only the fabric (SURVEY.md §2.9: "there are no
distributed algorithms in RAFT itself" — cuML/cuGraph build them on top);
the BASELINE configs require the algorithms too, so raft_tpu ships
reference-quality MNMG k-means and kNN natively.
"""

from raft_tpu.distributed import ann  # noqa: F401
from raft_tpu.distributed import health  # noqa: F401
from raft_tpu.distributed import kmeans  # noqa: F401
from raft_tpu.distributed import routing  # noqa: F401
from raft_tpu.distributed import knn  # noqa: F401
