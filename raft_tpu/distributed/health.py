"""Shard health lifecycle for the routed distributed index.

The routed path (PR 8) answers a dead shard by masking its owned lists
out of the merge, and PR 17's replicated placement answers it by
failing the lists over to replicas — but nothing *decided* a shard was
dead: ``failed_shards`` was purely caller-scripted.  This module is the
decision loop — a per-shard state machine::

    HEALTHY --strikes--> SUSPECT --strikes--> FAILED
       ^                    |                    |
       |<---consecutive OKs-+     begin_catch_up v
       |                                    CATCHING_UP
       +<----- readmit (canary-gated swap) ------+

driven by three evidence streams the search path already produces:
per-shard deadline overruns (``distributed.shard_timeout``), straggler
flags from the fault plan's injected schedule, and failed
``health_check`` canaries.  Flapping is pinned the same two ways as the
PR 12 brownout controller: **hysteresis** (strikes escalate one state at
a time; clearing SUSPECT takes ``ok_to_clear`` *consecutive* passes) and
**dwell time** (``dwell_s`` must elapse in a state before the next
transition in either direction).  Every transition lands a
``distributed.health.*`` flight event (always-on recorder) plus the
same-named counter — the paired-signal contract graftlint's
``health-transition`` rule enforces.

Readmission is anti-entropy catch-up: :func:`catch_up` rebuilds the
recovering shard's leaves from the live replicas (a generation-delta
replay — the stacked pytree's healthy copies ARE the authoritative
state, the same way the WAL fold is) and publishes under **one**
placement-generation bump; :func:`readmit` canary-gates the caught-up
index and installs it through ``server.swap_index`` — the warmed atomic
barrier — so routing resumes with zero steady-state recompiles (the
routing tables are host-side numpy; replica choice is data, not shape).

The tracker is deliberately NOT in the device path: all state is plain
Python under one lock, clocks are injected (tests drive dwell
synthetically), and the search path reads it with two tuple calls.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu.core.error import expects
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.observability import flight as _flight
from raft_tpu.resilience import faults

#: shard lifecycle states (strings, not an enum: they appear verbatim in
#: flight-event attrs, stats dicts and test assertions)
HEALTHY = "HEALTHY"
SUSPECT = "SUSPECT"
FAILED = "FAILED"
CATCHING_UP = "CATCHING_UP"


def _emit(event: str, **attrs) -> None:
    """The paired transition signal: one always-on flight event plus the
    same-named counter (gated, like every counter).  Transition sites
    call this with LITERAL event names so the observability registry
    self-registers ``distributed.health.*``."""
    _flight.record_event(event, **attrs)
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter(event).inc()


@dataclasses.dataclass
class HealthConfig:
    """State-machine knobs.  Hysteresis is structural (strikes escalate
    one state per threshold; clearing takes consecutive passes) and
    ``dwell_s`` pins residency — together they absorb a flapping shard
    (see ``FaultPlan.flap_shard``) instead of thrashing the placement.
    """

    #: evidence strikes at/above which a HEALTHY shard turns SUSPECT
    #: (one deadline overrun or canary failure counts this many — hard
    #: evidence suspects immediately; a straggle flag counts one)
    suspect_after: int = 2
    #: further strikes (counted from SUSPECT entry) at/above which a
    #: SUSPECT shard is declared FAILED and leaves the routing
    fail_after: int = 3
    #: consecutive OK verdicts clearing SUSPECT back to HEALTHY
    ok_to_clear: int = 2
    #: minimum residency in a state before the next transition in
    #: either direction (0 = transitions are immediate)
    dwell_s: float = 0.0

    def validate(self) -> "HealthConfig":
        expects(self.suspect_after >= 1,
                "health: suspect_after must be >= 1")
        expects(self.fail_after >= 1, "health: fail_after must be >= 1")
        expects(self.ok_to_clear >= 1, "health: ok_to_clear must be >= 1")
        expects(self.dwell_s >= 0.0, "health: dwell_s must be >= 0")
        return self


class HealthTracker:
    """Per-shard lifecycle state machine.  Thread-safe (evidence arrives
    from the search path, canary loops and ops threads); the clock is
    injected so tests drive dwell deterministically."""

    def __init__(self, n_shards: int,
                 config: Optional[HealthConfig] = None, *,
                 clock: Callable[[], float] = time.monotonic) -> None:
        expects(n_shards >= 1, "health: n_shards must be >= 1")
        self.config = (config or HealthConfig()).validate()
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        self._state: List[str] = [HEALTHY] * n_shards
        self._strikes: List[int] = [0] * n_shards
        self._oks: List[int] = [0] * n_shards
        self._since: List[float] = [now] * n_shards
        self._transitions: Dict[str, int] = {}
        # per-shard load demotion (note_overload): a continuous score
        # penalty the routing policy reads back — NOT a lifecycle state
        self._load_penalty: List[float] = [0.0] * n_shards

    # -- evidence ----------------------------------------------------------
    def note_straggle(self, shard: int) -> None:
        """Soft evidence: the straggler detector flagged ``shard`` this
        window (one strike)."""
        self._strike(shard, "straggle", weight=1)

    def note_timeout(self, shard: int) -> None:
        """Hard evidence: ``shard`` overran its per-shard search
        deadline — enough strikes to suspect a healthy shard at once."""
        self._strike(shard, "timeout", weight=self.config.suspect_after)

    def note_canary_failure(self, shard: int) -> None:
        """Hard evidence: a recall canary attributed to ``shard``
        failed.  Ticks ``integrity.canary_failure`` with the shard id
        (the satellite the bare per-shard verdicts never had a consumer
        for) and strikes like a timeout."""
        _flight.record_event("integrity.canary_failure", shard=int(shard))
        from raft_tpu import observability as obs
        if obs.enabled():
            obs.registry().counter("integrity.canary_failure").inc()
        self._strike(shard, "canary", weight=self.config.suspect_after)

    def note_write_error(self, shard: int) -> None:
        """Hard evidence from the distributed write path (round 19):
        ``shard`` failed to make an appended WAL record durable (fsync
        error).  A shard that cannot persist writes cannot count toward
        a write quorum, so this strikes like a timeout — enough to
        suspect a healthy shard at once; repeated errors fail it and
        the ack planner re-plans quorums onto the surviving replicas."""
        self._strike(shard, "write", weight=self.config.suspect_after)

    def note_overload(self, shard: int, load: float) -> None:
        """Soft evidence from the routing policy: ``shard``'s planned
        probe load runs at ``load``× the mesh mean.  Folds the excess
        into the shard's *load penalty* — a continuous score demotion
        :meth:`load_penalties` exposes back to the routing policy — and
        escalates at most to SUSPECT (so replicas absorb its traffic
        and stragglers from it are hedged).  Overload is **not**
        failure: a load-SUSPECT shard never advances to FAILED from
        this signal and never enters :meth:`failed_shards`, so the
        status vector keeps reporting it live."""
        s = int(shard)
        load = float(load)
        event = None
        strikes = 0
        with self._lock:
            if self._state[s] in (FAILED, CATCHING_UP):
                return  # already out of the routing
            # EWMA of the overload excess, clamped at zero: transient
            # spikes decay instead of latching
            self._load_penalty[s] = max(
                0.0, 0.7 * self._load_penalty[s] + 0.3 * (load - 1.0))
            now = self._clock()
            if (self._state[s] == HEALTHY
                    and now - self._since[s] >= self.config.dwell_s):
                self._oks[s] = 0
                self._strikes[s] += 1
                strikes = self._strikes[s]
                if strikes >= self.config.suspect_after:
                    self._state[s] = SUSPECT
                    self._since[s] = now
                    self._strikes[s] = 0
                    event = "distributed.health.suspect"
                    self._transitions[event] = \
                        self._transitions.get(event, 0) + 1
            # a SUSPECT shard stays SUSPECT: load evidence accrues no
            # strikes toward FAILED — only timeouts/canaries/straggles
            # (genuine failure evidence) may take it further down
        if event:
            _emit(event, shard=s, cause="load", strikes=strikes)

    def note_ok(self, shard: int) -> None:
        """A passing verdict (canary OK / answered in budget): resets
        the strike run; ``ok_to_clear`` consecutive OKs clear SUSPECT
        back to HEALTHY (after dwell)."""
        s = int(shard)
        recovered = False
        with self._lock:
            # an OK verdict also decays the load demotion — pressure
            # that stopped showing up stops costing score
            self._load_penalty[s] *= 0.7
            if self._state[s] == SUSPECT:
                self._oks[s] += 1
                now = self._clock()
                if (self._oks[s] >= self.config.ok_to_clear
                        and now - self._since[s] >= self.config.dwell_s):
                    self._state[s] = HEALTHY
                    self._since[s] = now
                    self._strikes[s] = 0
                    self._oks[s] = 0
                    self._transitions["distributed.health.recovered"] = \
                        self._transitions.get(
                            "distributed.health.recovered", 0) + 1
                    recovered = True
            elif self._state[s] == HEALTHY:
                self._strikes[s] = 0
        if recovered:
            _emit("distributed.health.recovered", shard=s)

    def _strike(self, shard: int, cause: str, *, weight: int) -> None:
        s = int(shard)
        event = None
        strikes = 0
        with self._lock:
            if self._state[s] in (FAILED, CATCHING_UP):
                return  # already out of the routing; nothing to escalate
            self._oks[s] = 0
            self._strikes[s] += weight
            strikes = self._strikes[s]
            now = self._clock()
            if now - self._since[s] < self.config.dwell_s:
                return  # dwell pins the state; strikes keep accruing
            if (self._state[s] == HEALTHY
                    and strikes >= self.config.suspect_after):
                self._state[s] = SUSPECT
                self._since[s] = now
                self._strikes[s] = 0
                event = "distributed.health.suspect"
            elif (self._state[s] == SUSPECT
                    and strikes >= self.config.fail_after):
                self._state[s] = FAILED
                self._since[s] = now
                self._strikes[s] = 0
                event = "distributed.health.failed"
            if event:
                self._transitions[event] = \
                    self._transitions.get(event, 0) + 1
        if event == "distributed.health.suspect":
            _emit("distributed.health.suspect", shard=s, cause=cause,
                  strikes=strikes)
        elif event == "distributed.health.failed":
            _emit("distributed.health.failed", shard=s, cause=cause,
                  strikes=strikes)

    # -- readmission lifecycle ---------------------------------------------
    def begin_catch_up(self, shard: int, **attrs) -> None:
        """FAILED -> CATCHING_UP: the shard starts replaying what it
        missed.  It stays OUT of the routing (``failed_shards`` keeps
        reporting it) until :meth:`readmit`."""
        s = int(shard)
        with self._lock:
            expects(self._state[s] == FAILED,
                    f"health: shard {s} is {self._state[s]}, only a "
                    f"FAILED shard can begin catch-up")
            self._state[s] = CATCHING_UP
            self._since[s] = self._clock()
            self._transitions["distributed.health.catch_up"] = \
                self._transitions.get("distributed.health.catch_up", 0) + 1
        _emit("distributed.health.catch_up", shard=s, **attrs)

    def readmit(self, shard: int) -> None:
        """CATCHING_UP -> HEALTHY: the canary gate passed and the new
        placement generation is published; routing resumes."""
        s = int(shard)
        with self._lock:
            expects(self._state[s] == CATCHING_UP,
                    f"health: shard {s} is {self._state[s]}, only a "
                    f"CATCHING_UP shard can be readmitted")
            self._state[s] = HEALTHY
            self._since[s] = self._clock()
            self._strikes[s] = 0
            self._oks[s] = 0
            self._transitions["distributed.health.readmitted"] = \
                self._transitions.get(
                    "distributed.health.readmitted", 0) + 1
        _emit("distributed.health.readmitted", shard=s)

    def block_readmit(self, shard: int, reason: str = "canary") -> None:
        """CATCHING_UP -> FAILED: the readmission canary gate failed;
        the shard stays out of the routing."""
        s = int(shard)
        with self._lock:
            expects(self._state[s] == CATCHING_UP,
                    f"health: shard {s} is {self._state[s]}, only a "
                    f"CATCHING_UP readmission can be blocked")
            self._state[s] = FAILED
            self._since[s] = self._clock()
            self._transitions["distributed.health.readmit_blocked"] = \
                self._transitions.get(
                    "distributed.health.readmit_blocked", 0) + 1
        _emit("distributed.health.readmit_blocked", shard=s,
              reason=reason)

    # -- views -------------------------------------------------------------
    def state(self, shard: int) -> str:
        with self._lock:
            return self._state[int(shard)]

    def states(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._state)

    def failed_shards(self) -> Tuple[int, ...]:
        """Shards the routing must treat as down: FAILED plus
        CATCHING_UP (a catching-up shard holds a stale generation — it
        must not serve until readmitted)."""
        with self._lock:
            return tuple(s for s, st in enumerate(self._state)
                         if st in (FAILED, CATCHING_UP))

    def suspect_shards(self) -> Tuple[int, ...]:
        """Shards under suspicion: still routed, but hedged when
        replicas exist."""
        with self._lock:
            return tuple(s for s, st in enumerate(self._state)
                         if st == SUSPECT)

    def load_penalties(self) -> Tuple[float, ...]:
        """Per-shard overload demotion (EWMA of the excess-over-mean
        from :meth:`note_overload`) — the continuous score term the
        routing policy adds, instead of a binary up/down verdict."""
        with self._lock:
            return tuple(self._load_penalty)

    def stats(self) -> Dict[str, object]:
        """Snapshot for ops/bench: per-shard state + strike run and the
        cumulative transition counts."""
        with self._lock:
            return {"states": tuple(self._state),
                    "strikes": tuple(self._strikes),
                    "load_penalties": tuple(self._load_penalty),
                    "transitions": dict(self._transitions)}


# ---------------------------------------------------------------------------
# anti-entropy readmission


def catch_up(handle, index, shard: int, *,
             tracker: Optional[HealthTracker] = None,
             stale=None, ingest=None):
    """Anti-entropy catch-up for recovering ``shard``: rebuild its
    leaves from the live index (whose replicas hold every list the
    shard owned — the generation-delta replay source, the same
    authoritative-copy argument the WAL fold makes) and assemble the
    result under **one** placement-generation bump.  Returns the
    caught-up :class:`~raft_tpu.distributed.ann.RoutedIndex` — NOT yet
    published: route it through :func:`readmit` so the canary gate and
    the ``swap_index`` barrier stay in front of live traffic.

    ``stale`` (the index snapshot the shard went down holding, when the
    caller retained one) only feeds the ``generation_delta`` attribute
    on the ``distributed.health.catch_up`` event — how far behind the
    shard was.

    ``ingest`` (a :class:`raft_tpu.serving.dist_ingest.RoutedIngest`,
    round 19) adds the WAL **delta phase**: before the leaves are
    re-placed, the recovering shard's per-shard WAL + memtable are
    rebuilt by replaying the records it missed from the live replicas'
    logs (``RoutedIngest.catch_up_shard`` — site
    ``ingest.dist.catch_up``), so the readmitted shard's delta tier is
    whole, not just its folded leaves."""
    from raft_tpu.distributed import ann
    expects(index.placement is not None,
            "health.catch_up: index carries no placement map")
    faults.maybe_fail("distributed.catch_up")
    delta = _mutate.generation(index) - (
        _mutate.generation(stale) if stale is not None else
        _mutate.generation(index))
    if tracker is not None:
        tracker.begin_catch_up(shard, generation_delta=delta)
    if ingest is not None:
        # the WAL delta phase runs while the shard is CATCHING_UP (out
        # of the routing), BEFORE the placement re-bump: live replicas'
        # logs are the authoritative record of every acked write the
        # shard missed
        ingest.catch_up_shard(shard)
    placement = dataclasses.replace(
        index.placement, generation=index.placement.generation + 1)
    # one generation bump: rebalance_placement gathers the live global
    # arrays (replicas are authoritative for the dead shard's lists),
    # re-places under the bumped placement and stamps the next index
    # generation — the identical publish discipline every mutation uses
    return ann.rebalance_placement(handle, index, placement=placement)


def readmit(handle, server, index, shard: int, *,
            tracker: Optional[HealthTracker] = None) -> bool:
    """Canary-gated readmission: health-check the caught-up ``index``;
    on pass, publish it through ``server.swap_index`` (the warmed atomic
    barrier — zero steady-state recompiles) and move the tracker
    CATCHING_UP -> HEALTHY.  On canary failure the shard goes back to
    FAILED (``distributed.health.readmit_blocked``) and nothing is
    published.  Returns True when routing resumed."""
    from raft_tpu.distributed import ann
    faults.maybe_fail("distributed.swap")
    reports = ann.health_check(handle, index, raise_on_fail=False,
                               health=None)
    ok = all(r is None or r.ok for r in (reports or []))
    if not ok:
        if tracker is not None:
            tracker.block_readmit(shard, reason="canary")
        else:
            _emit("distributed.health.readmit_blocked", shard=int(shard),
                  reason="canary")
        return False
    server.swap_index(index)
    if tracker is not None:
        tracker.readmit(shard)
    else:
        _emit("distributed.health.readmitted", shard=int(shard))
    return True
