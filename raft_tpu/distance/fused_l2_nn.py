"""Fused L2 distance + 1-nearest-neighbor argmin.

Reference: raft/distance/fused_l2_nn.cuh:100 ``fusedL2NN`` / :205
``fusedL2NNMinReduce`` — the k-means / IVF hot kernel: for each row of x, the
index and distance of its nearest row in y, computed WITHOUT materialising the
(m, n) distance matrix.

TPU design: scan over database tiles.  Each step does one (m, tile_n) gemm on
the MXU plus a running (min, argmin) epilogue on the VPU; XLA keeps the tile
resident and fuses the epilogue, so HBM traffic is O(m*k + n*k + m) — the same
property the CUDA kernel's register-tile epilogue buys.  Peak memory is
m * tile_n.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output

_TILE_N = 2048


@auto_convert_output
def fused_l2_nn(
    x: jax.Array,
    y: jax.Array,
    *,
    sqrt: bool = False,
    tile_n: int = _TILE_N,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of x (m, k): (min L2 distance, argmin index) over rows of y (n, k).

    Reference contract: fused_l2_nn.cuh:100 (out as KeyValuePair<idx, dist>);
    we return the pair as two arrays (dists (m,), idx (m,) int32).
    """
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "fused_l2_nn: (m,k),(n,k) inputs required")
    m, k = x.shape
    n = y.shape[0]
    tile_n = min(tile_n, n)
    # bound the (m, tile_n) working tile: at m=1M, tile_n=2048 it is 8 GB
    # fp32 — chunk the x side so the transient stays ~1 GB
    tile_m = 131_072
    if m > tile_m:
        outs = [fused_l2_nn.__wrapped__(x[s:s + tile_m], y, sqrt=sqrt,
                                        tile_n=tile_n)
                for s in range(0, m, tile_m)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]))
    n_tiles = -(-n // tile_n)
    padded = n_tiles * tile_n

    xf = x.astype(jnp.float32)
    yf = jnp.pad(y.astype(jnp.float32), ((0, padded - n), (0, 0)))
    x_sq = jnp.sum(xf * xf, axis=1)
    y_sq = jnp.sum(yf * yf, axis=1)
    y_tiles = yf.reshape(n_tiles, tile_n, k)
    ysq_tiles = y_sq.reshape(n_tiles, tile_n)

    init = (jnp.full((m,), jnp.inf, jnp.float32),
            jnp.zeros((m,), jnp.int32))

    def step(carry, tile):
        best_d, best_i = carry
        yt, ysq, t = tile
        # (m, tile_n) distances for this tile: ||x||^2 + ||y||^2 - 2 x.y
        ip = jax.lax.dot_general(xf, yt, (((1,), (1,)), ((), ())),
                                 precision=get_matmul_precision(),
                                 preferred_element_type=jnp.float32)
        d = x_sq[:, None] + ysq[None, :] - 2.0 * ip
        # mask padding
        valid = (t * tile_n + jnp.arange(tile_n)) < n
        d = jnp.where(valid[None, :], jnp.maximum(d, 0.0), jnp.inf)
        tile_best = jnp.min(d, axis=1)
        tile_arg = jnp.argmin(d, axis=1).astype(jnp.int32) + t * tile_n
        upd = tile_best < best_d
        return (jnp.where(upd, tile_best, best_d),
                jnp.where(upd, tile_arg, best_i)), None

    (best_d, best_i), _ = jax.lax.scan(
        step, init, (y_tiles, ysq_tiles, jnp.arange(n_tiles)))
    if sqrt:
        best_d = jnp.sqrt(best_d)
    return best_d, best_i


@auto_convert_output
def fused_l2_nn_min_reduce(x: jax.Array, y: jax.Array, *,
                           sqrt: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Alias matching fused_l2_nn.cuh:205 ``fusedL2NNMinReduce``."""
    return fused_l2_nn(x, y, sqrt=sqrt)
