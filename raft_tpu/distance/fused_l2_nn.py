"""Fused L2 distance + 1-nearest-neighbor argmin.

Reference: raft/distance/fused_l2_nn.cuh:100 ``fusedL2NN`` / :205
``fusedL2NNMinReduce`` — the k-means / IVF hot kernel: for each row of x, the
index and distance of its nearest row in y, computed WITHOUT materialising the
(m, n) distance matrix.

TPU design: scan over database tiles.  Each step does one (m, tile_n) gemm on
the MXU plus a running (min, argmin) epilogue on the VPU; XLA keeps the tile
resident and fuses the epilogue, so HBM traffic is O(m*k + n*k + m) — the same
property the CUDA kernel's register-tile epilogue buys.  Peak memory is
m * tile_n.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output, raw

_TILE_N = 2048


@auto_convert_output
def fused_l2_nn(
    x: jax.Array,
    y: jax.Array,
    *,
    sqrt: bool = False,
    tile_n: int = _TILE_N,
    use_pallas: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of x (m, k): (min L2 distance, argmin index) over rows of y (n, k).

    Reference contract: fused_l2_nn.cuh:100 (out as KeyValuePair<idx, dist>);
    we return the pair as two arrays (dists (m,), idx (m,) int32).

    ``use_pallas=True`` runs the hand-written Pallas kernel
    (:mod:`raft_tpu.ops.fused_l2_nn_pallas`) — measured at parity with this
    XLA formulation on a v5e chip (both HBM-bound at the k-means shape);
    it exists as the foundation for fused epilogues XLA cannot express.
    """
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "fused_l2_nn: (m,k),(n,k) inputs required")
    if use_pallas:
        from raft_tpu.ops.fused_l2_nn_pallas import fused_l2_nn_pallas
        # Mosaic needs a real TPU backend; elsewhere run the interpreter so
        # the dispatch stays testable on CPU
        interpret = jax.default_backend() not in ("tpu", "axon")
        return fused_l2_nn_pallas(x, y, sqrt=sqrt, interpret=interpret)
    m, k = x.shape
    n = y.shape[0]
    tile_n = min(tile_n, n)
    if not isinstance(x, jax.core.Tracer) and not isinstance(
            y, jax.core.Tracer):
        # eager call: route through jit — op-by-op dispatch of the tile
        # scan costs ~27x on a remote-attached TPU.  The precision policy
        # is part of the jit key (a global read inside a cached trace
        # would go stale under matmul_precision()).
        return _fused_l2_nn_jit(x, y, sqrt=sqrt, tile_n=tile_n,
                                precision=get_matmul_precision())
    return _impl(x, y, sqrt=sqrt, tile_n=tile_n)


def _impl(x, y, *, sqrt, tile_n, precision=None):
    m, k = x.shape
    n = y.shape[0]
    # bound the (m, tile_n) working tile: at m=1M, tile_n=2048 it is 8 GB
    # fp32 — chunk the x side so the transient stays ~1 GB
    tile_m = 131_072
    if m > tile_m:
        outs = [_impl(x[s:s + tile_m], y, sqrt=sqrt, tile_n=tile_n,
                      precision=precision)
                for s in range(0, m, tile_m)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]))
    n_tiles = -(-n // tile_n)
    padded = n_tiles * tile_n

    xf = x.astype(jnp.float32)
    yf = jnp.pad(y.astype(jnp.float32), ((0, padded - n), (0, 0)))
    x_sq = jnp.sum(xf * xf, axis=1)
    y_sq = jnp.sum(yf * yf, axis=1)
    y_tiles = yf.reshape(n_tiles, tile_n, k)
    ysq_tiles = y_sq.reshape(n_tiles, tile_n)

    init = (jnp.full((m,), jnp.inf, jnp.float32),
            jnp.zeros((m,), jnp.int32))

    def step(carry, tile):
        best_d, best_i = carry
        yt, ysq, t = tile
        # (m, tile_n) distances for this tile: ||x||^2 + ||y||^2 - 2 x.y
        ip = jax.lax.dot_general(xf, yt, (((1,), (1,)), ((), ())),
                                 precision=precision or get_matmul_precision(),
                                 preferred_element_type=jnp.float32)
        d = x_sq[:, None] + ysq[None, :] - 2.0 * ip
        # mask padding
        valid = (t * tile_n + jnp.arange(tile_n)) < n
        d = jnp.where(valid[None, :], jnp.maximum(d, 0.0), jnp.inf)
        tile_best = jnp.min(d, axis=1)
        tile_arg = jnp.argmin(d, axis=1).astype(jnp.int32) + t * tile_n
        upd = tile_best < best_d
        return (jnp.where(upd, tile_best, best_d),
                jnp.where(upd, tile_arg, best_i)), None

    (best_d, best_i), _ = jax.lax.scan(
        step, init, (y_tiles, ysq_tiles, jnp.arange(n_tiles)))
    if sqrt:
        best_d = jnp.sqrt(best_d)
    return best_d, best_i


_fused_l2_nn_jit = jax.jit(_impl,
                           static_argnames=("sqrt", "tile_n", "precision"))


@auto_convert_output
def fused_l2_nn_min_reduce(x: jax.Array, y: jax.Array, *,
                           sqrt: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Alias matching fused_l2_nn.cuh:205 ``fusedL2NNMinReduce``."""
    return raw(fused_l2_nn)(x, y, sqrt=sqrt)
