"""Gram / kernel matrices (SVM support).

Reference: raft/distance/kernels.cuh + detail/kernels/ — polynomial, tanh and
RBF kernels over dense inputs, each a GEMM plus epilogue.  Pure MXU work on
TPU.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from raft_tpu.distance.pairwise import _inner, _l2_expanded


class KernelType(enum.IntEnum):
    """Reference: detail/kernels/kernel_factory KernelType."""

    LINEAR = 0
    POLYNOMIAL = 1
    RBF = 2
    TANH = 3


@dataclasses.dataclass
class KernelParams:
    """Reference: kernels.cuh ``KernelParams``."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def gram_matrix(x: jax.Array, y: jax.Array,
                params: KernelParams = KernelParams()) -> jax.Array:
    """K(x, y) per params (reference: kernels.cuh GramMatrix::evaluate)."""
    if params.kernel == KernelType.LINEAR:
        return _inner(x, y)
    if params.kernel == KernelType.POLYNOMIAL:
        return jnp.power(params.gamma * _inner(x, y) + params.coef0,
                         params.degree)
    if params.kernel == KernelType.TANH:
        return jnp.tanh(params.gamma * _inner(x, y) + params.coef0)
    if params.kernel == KernelType.RBF:
        return jnp.exp(-params.gamma * _l2_expanded(x, y))
    raise ValueError(f"unknown kernel {params.kernel}")
