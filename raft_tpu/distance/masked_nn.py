"""Masked nearest-neighbor.

Reference: raft/distance/masked_nn.cuh — fused L2 1-NN where an adjacency mask
restricts which (row, group) pairs participate (used by connect_components in
single-linkage).  The reference compresses the mask to bits
(detail/compress_to_bits.cuh); on TPU a dense bool mask folded into the
distance epilogue is the fused form — XLA keeps it in the matmul consumer.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


def masked_l2_nn(
    x: jax.Array,
    y: jax.Array,
    adj: jax.Array,
    group_idxs: jax.Array,
    *,
    sqrt: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """For each row i of x: nearest row j of y with adj[i, group(j)] true.

    ``adj`` is (m, n_groups) bool; ``group_idxs`` is (n_groups,) *end offsets*
    of each contiguous group of y rows (reference: masked_nn.cuh group_idxs
    convention).  Returns (dists (m,), idx (m,)); masked-out rows yield inf/0.
    """
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "masked_l2_nn: (m,k),(n,k) required")
    m, n = x.shape[0], y.shape[0]
    n_groups = adj.shape[1]
    expects(group_idxs.shape[0] == n_groups, "group_idxs vs adj mismatch")

    # group id of every y row from end-offsets: group[j] = #ends <= j
    j = jnp.arange(n)
    group_of_y = jnp.sum(j[:, None] >= group_idxs[None, :], axis=1)

    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    from raft_tpu.utils.precision import get_matmul_precision
    ip = jax.lax.dot_general(xf, yf, (((1,), (1,)), ((), ())),
                             precision=get_matmul_precision(),
                             preferred_element_type=jnp.float32)
    d = (jnp.sum(xf * xf, axis=1)[:, None]
         + jnp.sum(yf * yf, axis=1)[None, :] - 2.0 * ip)
    d = jnp.maximum(d, 0.0)
    mask = jnp.take(adj, group_of_y, axis=1)  # (m, n)
    d = jnp.where(mask, d, jnp.inf)
    best = jnp.min(d, axis=1)
    arg = jnp.argmin(d, axis=1).astype(jnp.int32)
    if sqrt:
        best = jnp.sqrt(best)
    return best, arg
