"""Pairwise distances.

Reference: cpp/include/raft/distance/ (SURVEY.md §2.2) — 20 metrics
(distance_types.hpp:23-70), a tiled GEMM-like pairwise kernel with CUTLASS
dispatch, and the fused L2 + 1-NN argmin kernel (fused_l2_nn.cuh:100) that
powers k-means and IVF builds.

TPU-first design: the whole CUDA dispatch tree collapses into two paths —
(1) "expanded" metrics whose inner term is an inner product ride
``lax.dot_general`` on the MXU with an elementwise epilogue XLA fuses;
(2) genuinely elementwise metrics (L1, Linf, Canberra, ...) run through a
row-tiled broadcast engine that bounds memory at tile_m x n x k.
``fused_l2_nn`` keeps the reference's contract (1-NN without materialising the
n x m matrix) as a scan over database tiles with a running (min, argmin).
"""

from raft_tpu.distance.types import DistanceType  # noqa: F401
from raft_tpu.distance.pairwise import (  # noqa: F401
    pairwise_distance,
    distance,
)
from raft_tpu.distance.fused_l2_nn import (  # noqa: F401
    fused_l2_nn,
    fused_l2_nn_min_reduce,
)
from raft_tpu.distance.masked_nn import masked_l2_nn  # noqa: F401
from raft_tpu.distance.kernels import (  # noqa: F401
    KernelParams,
    KernelType,
    gram_matrix,
)
