"""Distance metric enumeration.

Reference: raft/distance/distance_types.hpp:23-70 — names and numeric values
kept identical so serialized indexes and Python callers interop.
"""

from __future__ import annotations

import enum


class DistanceType(enum.IntEnum):
    """Mirror of ``raft::distance::DistanceType`` (distance_types.hpp:26-68)."""

    L2Expanded = 0
    L2SqrtExpanded = 1
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7
    Canberra = 8
    LpUnexpanded = 9
    CorrelationExpanded = 10
    JaccardExpanded = 11
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19


# pylibraft-style metric-name aliases (python/pylibraft/pylibraft/distance/pairwise_distance.pyx)
METRIC_NAMES = {
    "sqeuclidean": DistanceType.L2Expanded,
    "euclidean": DistanceType.L2SqrtExpanded,
    "l2": DistanceType.L2SqrtExpanded,
    "cosine": DistanceType.CosineExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "inner_product": DistanceType.InnerProduct,
    "chebyshev": DistanceType.Linf,
    "linf": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "minkowski": DistanceType.LpUnexpanded,
    "lp": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "russellrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
}


def resolve_metric(metric) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    if isinstance(metric, int):
        return DistanceType(metric)
    name = str(metric).lower()
    if name in METRIC_NAMES:
        return METRIC_NAMES[name]
    raise ValueError(f"unknown metric {metric!r}")
