"""Pairwise distance computation — all 20 reference metrics.

Reference: raft/distance/distance.cuh:70,241,398,441 (public API + runtime
metric dispatch), detail/distance.cuh:90-560 (per-metric impls built from
distance-op functors), detail/pairwise_matrix/ (tiled kernel + CUTLASS
dispatch).

TPU mapping (replaces the whole SM-arch dispatch tree):

- **MXU path** — metrics whose pairwise term decomposes into an inner product
  (L2 expanded, cosine, correlation, inner-product, Hellinger, KL,
  Jaccard/Dice/RusselRao on nonneg data): one ``gemm`` in fp32 accumulation +
  an elementwise epilogue XLA fuses into the matmul's output.
- **VPU path** — metrics needing |x-y|-style elementwise terms (L1, Linf,
  Canberra, Lp, BrayCurtis, JensenShannon, Hamming, L2 unexpanded): a
  row-tiled broadcast (tile_m, n, k) reduced over k, scanned over row tiles so
  peak memory stays bounded (the Contractions_NT tiling analogue).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.core.outputs import auto_convert_output, raw

# Row-tile size for the VPU (broadcast) path; bounds peak memory at
# _TILE_M * n * k elements.
_TILE_M = 128


def _acc_t(*arrays) -> jnp.dtype:
    """Accumulation dtype: >=fp32, f64 preserved (reference instantiates both
    float and double kernels)."""
    t = arrays[0].dtype
    for a in arrays[1:]:
        t = jnp.promote_types(t, a.dtype)
    return jnp.promote_types(t, jnp.float32)


def _inner(x: jax.Array, y: jax.Array) -> jax.Array:
    """x @ y.T with >=fp32 accumulation (MXU)."""
    from raft_tpu.utils.precision import get_matmul_precision
    return jax.lax.dot_general(
        x, y, (((1,), (1,)), ((), ())),
        precision=get_matmul_precision(),
        preferred_element_type=_acc_t(x, y))


def _sq_norms(x: jax.Array) -> jax.Array:
    return jnp.sum(x.astype(_acc_t(x)) ** 2, axis=1)


def _l2_expanded(x, y):
    xx = _sq_norms(x)[:, None]
    yy = _sq_norms(y)[None, :]
    d = xx + yy - 2.0 * _inner(x, y)
    return jnp.maximum(d, 0.0)


def _cosine(x, y):
    xn = jnp.sqrt(_sq_norms(x))[:, None]
    yn = jnp.sqrt(_sq_norms(y))[None, :]
    denom = jnp.maximum(xn * yn, 1e-30)
    return 1.0 - _inner(x, y) / denom


def _correlation(x, y):
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return _cosine(xc, yc)


def _hellinger(x, y):
    # reference (distance_ops/hellinger.cuh): d = sqrt(1 - sum sqrt(x_i y_i))
    ip = _inner(jnp.sqrt(jnp.maximum(x, 0.0)), jnp.sqrt(jnp.maximum(y, 0.0)))
    return jnp.sqrt(jnp.maximum(1.0 - ip, 0.0))


def _kl_divergence(x, y):
    # sum_i x_i * log(x_i / y_i) = sum x log x - x . log y  (matmul form).
    # y_i == 0 contributes zero to the cross term, matching the reference
    # (detail/distance_ops/kl_divergence.cuh:66 zeroes log(y) at y==0 rather
    # than clamping it).
    acc = _acc_t(x, y)
    xf = x.astype(acc)
    yf = y.astype(acc)
    x_log_x = jnp.sum(jnp.where(xf > 0, xf * jnp.log(jnp.maximum(xf, 1e-30)), 0.0),
                      axis=1)
    log_y = jnp.where(yf > 0, jnp.log(jnp.maximum(yf, 1e-30)), 0.0)
    cross = _inner(jnp.where(xf > 0, xf, 0.0), log_y)
    return x_log_x[:, None] - cross


def _jaccard(x, y):
    # boolean-presence semantics on nonneg data (reference: distance_ops/jaccard-like
    # path in detail/distance.cuh): 1 - |x&y| / (|x| + |y| - |x&y|)
    xb = (x > 0).astype(jnp.float32)
    yb = (y > 0).astype(jnp.float32)
    inter = _inner(xb, yb)
    union = jnp.sum(xb, axis=1)[:, None] + jnp.sum(yb, axis=1)[None, :] - inter
    return 1.0 - inter / jnp.maximum(union, 1.0)


def _dice(x, y):
    xb = (x > 0).astype(jnp.float32)
    yb = (y > 0).astype(jnp.float32)
    inter = _inner(xb, yb)
    tot = jnp.sum(xb, axis=1)[:, None] + jnp.sum(yb, axis=1)[None, :]
    return 1.0 - 2.0 * inter / jnp.maximum(tot, 1.0)


def _russelrao(x, y):
    k = x.shape[1]
    xb = (x > 0).astype(jnp.float32)
    yb = (y > 0).astype(jnp.float32)
    inter = _inner(xb, yb)
    return (k - inter) / k


def _haversine(x, y):
    # 2-feature lat/lon in radians (reference: distance_ops/haversine.cuh)
    expects(x.shape[1] == 2, "haversine requires 2 features (lat, lon)")
    lat1, lon1 = x[:, 0][:, None], x[:, 1][:, None]
    lat2, lon2 = y[:, 0][None, :], y[:, 1][None, :]
    sdlat = jnp.sin((lat2 - lat1) * 0.5)
    sdlon = jnp.sin((lon2 - lon1) * 0.5)
    a = sdlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sdlon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


# -- VPU (tiled broadcast) path ---------------------------------------------

def _tiled(elem_reduce, x: jax.Array, y: jax.Array) -> jax.Array:
    """Scan row tiles of x against all of y; elem_reduce maps
    (tile_m, 1, k), (1, n, k) -> (tile_m, n)."""
    m = x.shape[0]
    acc = _acc_t(x, y)
    n_tiles = -(-m // _TILE_M)
    padded = n_tiles * _TILE_M
    xp = jnp.pad(x, ((0, padded - m), (0, 0)))
    xt = xp.reshape(n_tiles, _TILE_M, x.shape[1]).astype(acc)
    yf = y.astype(acc)

    def one_tile(x_tile):
        return elem_reduce(x_tile[:, None, :], yf[None, :, :])

    out = jax.lax.map(one_tile, xt)
    return out.reshape(padded, y.shape[0])[:m]


def _l1_reduce(xt, yt):
    return jnp.sum(jnp.abs(xt - yt), axis=-1)


def _linf_reduce(xt, yt):
    return jnp.max(jnp.abs(xt - yt), axis=-1)


def _canberra_reduce(xt, yt):
    num = jnp.abs(xt - yt)
    den = jnp.abs(xt) + jnp.abs(yt)
    return jnp.sum(jnp.where(den > 0, num / den, 0.0), axis=-1)


def _braycurtis_reduce(xt, yt):
    num = jnp.sum(jnp.abs(xt - yt), axis=-1)
    den = jnp.sum(jnp.abs(xt + yt), axis=-1)
    return jnp.where(den > 0, num / den, 0.0)


def _jensen_shannon_reduce(xt, yt):
    m = 0.5 * (xt + yt)
    def kl_term(p):
        return jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)
                                            / jnp.maximum(m, 1e-30)), 0.0)
    js = 0.5 * jnp.sum(kl_term(xt) + kl_term(yt), axis=-1)
    return jnp.sqrt(jnp.maximum(js, 0.0))


def _hamming_reduce(xt, yt):
    return jnp.mean((xt != yt).astype(jnp.float32), axis=-1)


def _l2_unexp_reduce(xt, yt):
    d = xt - yt
    return jnp.sum(d * d, axis=-1)


def _minkowski_reduce(p):
    def f(xt, yt):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(xt - yt), p), axis=-1),
                         1.0 / p)
    return f


@auto_convert_output
def pairwise_distance(
    x,
    y,
    metric=DistanceType.L2Unexpanded,
    *,
    metric_arg: float = 2.0,
) -> jax.Array:
    """All-pairs distance matrix (m, n) between rows of x (m, k) and y (n, k).

    Reference: raft/distance/distance.cuh:441 ``pairwise_distance`` (runtime
    metric dispatch at :398).  ``metric`` accepts a :class:`DistanceType` or a
    pylibraft-style name string; ``metric_arg`` is the Minkowski p.
    """
    x = ensure_array(x, "x")
    y = ensure_array(y, "y")
    expects(x.ndim == 2 and y.ndim == 2, "pairwise_distance: rank-2 inputs")
    expects(x.shape[1] == y.shape[1],
            f"feature dims differ: {x.shape[1]} vs {y.shape[1]}")
    m = resolve_metric(metric)
    out_t = jnp.promote_types(x.dtype, jnp.float32)

    if m == DistanceType.L2Expanded:
        out = _l2_expanded(x, y)
    elif m == DistanceType.L2SqrtExpanded:
        out = jnp.sqrt(_l2_expanded(x, y))
    elif m == DistanceType.L2Unexpanded:
        out = _tiled(_l2_unexp_reduce, x, y)
    elif m == DistanceType.L2SqrtUnexpanded:
        out = jnp.sqrt(_tiled(_l2_unexp_reduce, x, y))
    elif m == DistanceType.CosineExpanded:
        out = _cosine(x, y)
    elif m == DistanceType.CorrelationExpanded:
        out = _correlation(x, y)
    elif m == DistanceType.InnerProduct:
        out = _inner(x, y)
    elif m == DistanceType.L1:
        out = _tiled(_l1_reduce, x, y)
    elif m == DistanceType.Linf:
        out = _tiled(_linf_reduce, x, y)
    elif m == DistanceType.Canberra:
        out = _tiled(_canberra_reduce, x, y)
    elif m == DistanceType.LpUnexpanded:
        out = _tiled(_minkowski_reduce(metric_arg), x, y)
    elif m == DistanceType.HellingerExpanded:
        out = _hellinger(x, y)
    elif m == DistanceType.KLDivergence:
        out = _kl_divergence(x, y)
    elif m == DistanceType.JaccardExpanded:
        out = _jaccard(x, y)
    elif m == DistanceType.DiceExpanded:
        out = _dice(x, y)
    elif m == DistanceType.RusselRaoExpanded:
        out = _russelrao(x, y)
    elif m == DistanceType.Haversine:
        out = _haversine(x, y)
    elif m == DistanceType.BrayCurtis:
        out = _tiled(_braycurtis_reduce, x, y)
    elif m == DistanceType.JensenShannon:
        out = _tiled(_jensen_shannon_reduce, x, y)
    elif m == DistanceType.HammingUnexpanded:
        out = _tiled(_hamming_reduce, x, y)
    else:
        raise ValueError(f"unhandled metric {m}")
    return out.astype(out_t)


@auto_convert_output
def distance(x, y, metric=DistanceType.L2Unexpanded, *,
             metric_arg: float = 2.0) -> jax.Array:
    """Compile-time-metric flavor (reference: distance.cuh:70 ``distance<T>``);
    identical here since XLA specializes per trace."""
    return raw(pairwise_distance)(x, y, metric, metric_arg=metric_arg)
