"""numpy-format array serialization, hardened.

Counterpart of the reference's mdspan (de)serializer that writes the numpy
``.npy`` wire format to iostreams (cpp/include/raft/core/serialize.hpp:34-124,
core/detail/mdspan_numpy_serializer.hpp).  Index serializers
(:mod:`raft_tpu.neighbors`) compose these with a version header exactly like
neighbors/detail/ivf_pq_serialize.cuh.

We use :func:`numpy.lib.format.write_array` which emits the identical format
(the reference hand-rolls the same header), plus scalar helpers.

Hardening (PR 2, resilience):

- every reader detects **short reads** (EOF mid-record) and raises
  :class:`CorruptIndexError` with byte offsets instead of the opaque
  ``np.frombuffer`` failure a truncated stream used to produce;
- index serializers wrap their whole payload in a **versioned envelope**
  (magic ``RTIE``, format version, payload length, CRC32 — the analogue
  of the reference's kSerializationVersion header, plus the integrity
  check it lacks): a torn or bit-flipped index file raises
  :class:`CorruptIndexError`, never loads as garbage arrays.  The CRC
  is computed only at save/load; search paths never touch it.
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import BinaryIO

import jax
import numpy as np
from numpy.lib import format as npy_format

from raft_tpu.resilience import faults as _faults

_SCALAR_MAGIC = b"RTSC"

_ENVELOPE_MAGIC = b"RTIE"
_ENVELOPE_VERSION = 1
# magic | u16 envelope version | u64 payload bytes | u32 crc32(payload)
_ENVELOPE_HEADER = struct.Struct("<4sHQI")


class CorruptIndexError(ValueError):
    """A serialized index/checkpoint stream is truncated or corrupted
    (bad magic, short read, CRC mismatch).  Subclasses ``ValueError`` so
    pre-hardening callers that caught ValueError keep working."""


def _tell(stream: BinaryIO) -> int:
    try:
        return stream.tell()
    except (OSError, AttributeError):
        return -1


def _offset(off: int) -> str:
    return f"at byte offset {off}" if off >= 0 else "at unknown offset"


def _read_exact(stream: BinaryIO, n: int, what: str) -> bytes:
    """Read exactly ``n`` bytes or raise :class:`CorruptIndexError`
    naming the record and offsets (short-read detection)."""
    off = _tell(stream)
    data = stream.read(n)
    if data is None or len(data) != n:
        got = 0 if data is None else len(data)
        raise CorruptIndexError(
            f"corrupt stream: short read of {what} {_offset(off)} "
            f"(wanted {n} bytes, got {got})")
    return data


def serialize_mdspan(res, stream: BinaryIO, arr) -> None:
    """Write an array in ``.npy`` format (reference: serialize.hpp:34-67)."""
    _faults.maybe_fail("serialize.write")
    np_arr = np.asarray(jax.device_get(arr) if isinstance(arr, jax.Array) else arr)
    npy_format.write_array(stream, np_arr, allow_pickle=False)


def deserialize_mdspan(res, stream: BinaryIO) -> np.ndarray:
    """Read an array in ``.npy`` format (reference: serialize.hpp:81-124).

    Truncated headers or data regions raise :class:`CorruptIndexError`
    with the record's start offset."""
    _faults.maybe_fail("serialize.read")
    off = _tell(stream)
    try:
        return npy_format.read_array(stream, allow_pickle=False)
    except (ValueError, OSError, EOFError, struct.error) as e:
        raise CorruptIndexError(
            f"corrupt stream: bad/truncated array record starting "
            f"{_offset(off)}: {e}") from e


def serialize_scalar(res, stream: BinaryIO, value) -> None:
    """Write one scalar with a dtype tag (reference: serialize_scalar)."""
    _faults.maybe_fail("serialize.write")
    arr = np.asarray(value)
    dt = arr.dtype.str.encode()
    stream.write(_SCALAR_MAGIC)
    stream.write(struct.pack("<B", len(dt)))
    stream.write(dt)
    stream.write(arr.tobytes())


def deserialize_scalar(res, stream: BinaryIO):
    _faults.maybe_fail("serialize.read")
    off = _tell(stream)
    magic = _read_exact(stream, 4, "scalar magic")
    if magic != _SCALAR_MAGIC:
        raise CorruptIndexError(
            f"corrupt scalar stream: bad magic {magic!r} {_offset(off)}")
    (n,) = struct.unpack("<B", _read_exact(stream, 1, "scalar dtype length"))
    try:
        dtype = np.dtype(_read_exact(stream, n, "scalar dtype tag").decode())
    except (TypeError, ValueError, UnicodeDecodeError) as e:
        raise CorruptIndexError(
            f"corrupt scalar stream: bad dtype tag {_offset(off)}: "
            f"{e}") from e
    payload = _read_exact(stream, dtype.itemsize,
                          f"scalar payload ({dtype.str})")
    return np.frombuffer(payload, dtype=dtype)[0]


# ---------------------------------------------------------------------------
# versioned integrity envelope (index serializers + build checkpoints)
# ---------------------------------------------------------------------------

def write_envelope(stream: BinaryIO, payload: bytes) -> None:
    """Wrap ``payload`` with magic + format version + length + CRC32."""
    _faults.maybe_fail("serialize.write")
    stream.write(_ENVELOPE_HEADER.pack(_ENVELOPE_MAGIC, _ENVELOPE_VERSION,
                                       len(payload),
                                       zlib.crc32(payload) & 0xFFFFFFFF))
    stream.write(payload)


def read_envelope(stream: BinaryIO) -> bytes:
    """Read and verify an envelope; returns the payload bytes.

    Bad magic / unknown version / short payload / CRC mismatch all raise
    :class:`CorruptIndexError` — a corrupted index is *rejected*, never
    silently loaded as wrong arrays."""
    _faults.maybe_fail("serialize.read")
    off = _tell(stream)
    header = _read_exact(stream, _ENVELOPE_HEADER.size, "envelope header")
    magic, version, length, crc = _ENVELOPE_HEADER.unpack(header)
    if magic != _ENVELOPE_MAGIC:
        raise CorruptIndexError(
            f"corrupt stream: bad envelope magic {magic!r} {_offset(off)} "
            "(not a raft_tpu index/checkpoint, or written by a "
            "pre-envelope version)")
    if version != _ENVELOPE_VERSION:
        raise CorruptIndexError(
            f"unsupported envelope version {version} {_offset(off)} "
            f"(expected {_ENVELOPE_VERSION})")
    payload = _read_exact(stream, length, f"envelope payload ({length} B)")
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    if actual != crc:
        raise CorruptIndexError(
            f"corrupt stream: payload CRC mismatch {_offset(off)} "
            f"(stored {crc:#010x}, computed {actual:#010x})")
    return payload


class enveloped_writer:
    """``with enveloped_writer(stream) as body:`` — serialize records into
    ``body``; one CRC-sealed envelope is emitted on clean exit."""

    def __init__(self, stream: BinaryIO) -> None:
        self._stream = stream
        self._body = io.BytesIO()

    def __enter__(self) -> BinaryIO:
        return self._body

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            write_envelope(self._stream, self._body.getvalue())


def open_envelope(stream: BinaryIO) -> BinaryIO:
    """Verify the envelope at ``stream`` and return the payload as a
    readable buffer for record-level deserializers."""
    return io.BytesIO(read_envelope(stream))
