"""numpy-format array serialization.

Counterpart of the reference's mdspan (de)serializer that writes the numpy
``.npy`` wire format to iostreams (cpp/include/raft/core/serialize.hpp:34-124,
core/detail/mdspan_numpy_serializer.hpp).  Index serializers
(:mod:`raft_tpu.neighbors`) compose these with a version header exactly like
neighbors/detail/ivf_pq_serialize.cuh.

We use :func:`numpy.lib.format.write_array` which emits the identical format
(the reference hand-rolls the same header), plus scalar helpers.
"""

from __future__ import annotations

import struct
from typing import BinaryIO

import jax
import numpy as np
from numpy.lib import format as npy_format

_SCALAR_MAGIC = b"RTSC"


def serialize_mdspan(res, stream: BinaryIO, arr) -> None:
    """Write an array in ``.npy`` format (reference: serialize.hpp:34-67)."""
    np_arr = np.asarray(jax.device_get(arr) if isinstance(arr, jax.Array) else arr)
    npy_format.write_array(stream, np_arr, allow_pickle=False)


def deserialize_mdspan(res, stream: BinaryIO) -> np.ndarray:
    """Read an array in ``.npy`` format (reference: serialize.hpp:81-124)."""
    return npy_format.read_array(stream, allow_pickle=False)


def serialize_scalar(res, stream: BinaryIO, value) -> None:
    """Write one scalar with a dtype tag (reference: serialize_scalar)."""
    arr = np.asarray(value)
    dt = arr.dtype.str.encode()
    stream.write(_SCALAR_MAGIC)
    stream.write(struct.pack("<B", len(dt)))
    stream.write(dt)
    stream.write(arr.tobytes())


def deserialize_scalar(res, stream: BinaryIO):
    magic = stream.read(4)
    if magic != _SCALAR_MAGIC:
        raise ValueError("corrupt scalar stream (bad magic)")
    (n,) = struct.unpack("<B", stream.read(1))
    dtype = np.dtype(stream.read(n).decode())
    return np.frombuffer(stream.read(dtype.itemsize), dtype=dtype)[0]
