"""Cooperative cancellation.

Counterpart of the reference's ``raft::interruptible``
(cpp/include/raft/core/interruptible.hpp:66-130): a per-thread token that other
CPU threads can ``cancel()``, causing the target thread's next
``interruptible::synchronize`` (a stream-sync point) to raise.

The TPU analogue: JAX dispatch is async and device work is not abortable
mid-kernel (same as CUDA kernels), so the cancellation points are the host-side
sync points — :func:`synchronize` here.  Long-running host loops (index build
batching, k-means iterations) call :func:`synchronize` or :func:`yield_no_wait`
each iteration, making them cancellable from another thread, mirroring how the
reference threads cancellation through stream syncs.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax


class InterruptedException(RuntimeError):
    """Raised at a sync point after cancel() (reference: raft::interruptible::interrupted_exception)."""


class interruptible:
    _tokens: Dict[int, "interruptible"] = {}
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._cancelled = threading.Event()

    # -- token registry (reference: get_token / get_token(thread_id)) --------
    @classmethod
    def get_token(cls, thread_id: Optional[int] = None) -> "interruptible":
        # Tokens persist for the process lifetime: a token may legitimately be
        # created for a thread that has not started yet (the reference's
        # cross-thread pattern), so liveness-based pruning would lose pending
        # cancellations.  The registry is bounded by the number of distinct
        # thread ids; a thread that consumed an interruption clears its own
        # flag (yield_no_wait), so id reuse never inherits a stale cancel
        # after the flag was observed.
        tid = thread_id if thread_id is not None else threading.get_ident()
        with cls._lock:
            tok = cls._tokens.get(tid)
            if tok is None:
                tok = interruptible()
                cls._tokens[tid] = tok
            return tok

    @classmethod
    def release_token(cls, thread_id: Optional[int] = None) -> None:
        """Drop a thread's token (call when a worker thread retires)."""
        tid = thread_id if thread_id is not None else threading.get_ident()
        with cls._lock:
            cls._tokens.pop(tid, None)

    def cancel(self) -> None:
        """Flag the owning thread for interruption (reference: :cancel)."""
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    # -- sync points ---------------------------------------------------------
    @classmethod
    def yield_no_wait(cls) -> None:
        """Check the current thread's token without blocking (reference: yield_no_wait)."""
        tok = cls.get_token()
        if tok._cancelled.is_set():
            tok._cancelled.clear()
            raise InterruptedException("raft_tpu: thread interrupted")

    @classmethod
    def synchronize(cls, *arrays: jax.Array) -> None:
        """Block on device work, raising if cancelled (reference: :synchronize :78).

        With arrays given, blocks until those are ready; otherwise drains all
        dispatched work.  Also a named fault-injection site
        (``interruptible.synchronize``) so preemption mid-build is scriptable
        in tests (resilience/faults.py).
        """
        cls.yield_no_wait()
        # lazy import: core must stay importable without the resilience
        # package initialized (faults itself imports core.error)
        from raft_tpu.resilience import faults
        faults.maybe_fail("interruptible.synchronize")
        if arrays:
            for a in arrays:
                a.block_until_ready()
        else:
            jax.effects_barrier()
        cls.yield_no_wait()
