"""JAX version compatibility shims.

One module owns every version probe so algorithm code stays on the
modern spelling.  Currently: ``jax.shard_map`` graduated from
``jax.experimental.shard_map`` (and renamed ``check_rep`` →
``check_vma``) — older runtimes get the experimental one adapted.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=False):
        if f is None:
            return lambda fn: jax.shard_map(fn, mesh=mesh,
                                            in_specs=in_specs,
                                            out_specs=out_specs,
                                            check_vma=check_vma)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:  # pre-graduation releases
    from jax.experimental.shard_map import shard_map as _experimental

    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=False):
        if f is None:
            return lambda fn: _experimental(fn, mesh=mesh,
                                            in_specs=in_specs,
                                            out_specs=out_specs,
                                            check_rep=check_vma)
        return _experimental(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_vma)


if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # pre-graduation releases keep it under jax.experimental
    from jax.experimental import enable_x64  # noqa: F401


if hasattr(jax.lax, "axis_size"):
    def axis_size(axis_name):
        """Static extent of a mesh axis inside a traced context."""
        return jax.lax.axis_size(axis_name)
else:
    def axis_size(axis_name):
        """Static extent of a mesh axis inside a traced context (older
        releases expose it as the axis frame itself)."""
        from jax.core import axis_frame
        return axis_frame(axis_name)
