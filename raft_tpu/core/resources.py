"""Resource handle.

TPU-native counterpart of the reference's ``raft::resources`` registry
(cpp/include/raft/core/resources.hpp:46,90,109) and ``raft::device_resources``
(cpp/include/raft/core/device_resources.hpp:60).

The reference carries all expensive, device-bound state — CUDA stream(s),
cuBLAS/cuSOLVER handles, communicator, workspace allocator — in a type-erased
map of lazily-created resources keyed by ``resource_type``
(core/resource/resource_types.hpp:29-45).  Copying a ``resources`` shares the
*factories*, and each resource is instantiated on first access.

On TPU the analogous expensive state is:

- the set of :class:`jax.Device` s and the :class:`jax.sharding.Mesh` laid over
  them (the stream-pool / sub-communicator analogue);
- the PRNG key chain (the reference threads an ``rng_state`` separately; here
  it lives in the handle so algorithms can draw keys deterministically);
- the communicator (:mod:`raft_tpu.comms`) bound to a mesh axis;
- donated workspace buffers (the RMM workspace-resource analogue) — on TPU,
  XLA owns allocation, so the workspace slot records a *byte budget* used by
  batching heuristics instead of an allocator.

Compute primitives in raft_tpu are pure functions (jit-friendly); the handle is
passed to stateful entry points (index build, random generation, distributed
algorithms) exactly where the reference passes ``raft::resources const&``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np

from raft_tpu.core.error import expects


class resource_type:
    """Well-known resource slots (reference: core/resource/resource_types.hpp:29-45).

    CUDA-specific slots (CUBLAS_HANDLE, CUDA_STREAM_VIEW, ...) have no TPU
    meaning; their roles map onto the slots below.
    """

    DEVICE = "device"              # reference: DEVICE_ID
    DEVICES = "devices"            # reference: CUDA_STREAM_POOL (parallel lanes)
    MESH = "mesh"                  # reference: none; TPU-native device grid
    COMMUNICATOR = "communicator"  # reference: COMMUNICATOR
    SUB_COMMUNICATOR = "sub_communicator"  # reference: SUB_COMMUNICATOR
    RNG = "rng"                    # PRNG key chain
    WORKSPACE = "workspace"        # reference: WORKSPACE_RESOURCE (byte budget here)
    DEVICE_PROPERTIES = "device_properties"


class Resources:
    """Type-erased registry of lazily-created resources.

    Reference: ``class resources`` (core/resources.hpp:46); factories are
    registered with :meth:`add_resource_factory` (:90) and instantiated on the
    first :meth:`get_resource` (:109).  Copies share factories; instantiated
    resources are created per-copy, mirroring the reference semantics.
    """

    def __init__(self, other: Optional["Resources"] = None) -> None:
        self._factories: Dict[str, Callable[[], Any]] = (
            dict(other._factories) if other is not None else {}
        )
        self._resources: Dict[str, Any] = {}

    def add_resource_factory(self, rtype: str, factory: Callable[[], Any]) -> None:
        self._factories[rtype] = factory
        self._resources.pop(rtype, None)

    def has_resource_factory(self, rtype: str) -> bool:
        return rtype in self._factories

    def get_resource(self, rtype: str) -> Any:
        if rtype not in self._resources:
            expects(rtype in self._factories,
                    f"no factory registered for resource '{rtype}'")
            self._resources[rtype] = self._factories[rtype]()
        return self._resources[rtype]


def _default_device() -> jax.Device:
    return jax.devices()[0]


class DeviceResources(Resources):
    """Accelerator-flavored handle (reference: device_resources.hpp:60-232).

    Parameters
    ----------
    device:
        Primary device; defaults to ``jax.devices()[0]``.
    devices:
        Device set for multi-device work; defaults to ``[device]``.
    mesh:
        Optional :class:`jax.sharding.Mesh` for sharded execution; lazily built
        as a 1-D ``("data",)`` mesh over ``devices`` when first requested.
    seed:
        Seed for the handle's PRNG chain.
    workspace_bytes:
        Byte budget batching heuristics may assume resident at once
        (reference: WORKSPACE_RESOURCE / rmm limiting adaptor,
        core/resource/device_memory_resource.hpp:41-73).
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: int = 0,
        workspace_bytes: int = 1 << 30,
    ) -> None:
        super().__init__()
        self.add_resource_factory(
            resource_type.DEVICE,
            (lambda: device) if device is not None else _default_device,
        )
        self.add_resource_factory(
            resource_type.DEVICES,
            (lambda: list(devices)) if devices is not None
            else (lambda: [self.get_resource(resource_type.DEVICE)]),
        )
        if mesh is not None:
            self.add_resource_factory(resource_type.MESH, lambda: mesh)
        else:
            self.add_resource_factory(resource_type.MESH, self._make_default_mesh)
        self.add_resource_factory(resource_type.RNG, lambda: _RngChain(seed))
        self.add_resource_factory(resource_type.WORKSPACE, lambda: workspace_bytes)

    def _make_default_mesh(self) -> jax.sharding.Mesh:
        devs = np.asarray(self.get_resource(resource_type.DEVICES))
        return jax.sharding.Mesh(devs, ("data",))

    # -- accessors mirroring device_resources.hpp ---------------------------
    @property
    def device(self) -> jax.Device:
        """Primary device (reference: get_device_id)."""
        return self.get_resource(resource_type.DEVICE)

    @property
    def devices(self) -> Sequence[jax.Device]:
        return self.get_resource(resource_type.DEVICES)

    @property
    def mesh(self) -> jax.sharding.Mesh:
        return self.get_resource(resource_type.MESH)

    @property
    def workspace_bytes(self) -> int:
        return self.get_resource(resource_type.WORKSPACE)

    # -- PRNG ---------------------------------------------------------------
    def next_key(self) -> jax.Array:
        """Draw the next PRNG key from the handle's deterministic chain."""
        return self.get_resource(resource_type.RNG).next_key()

    # -- comms (reference: device_resources.hpp get_comms :209) -------------
    def set_comms(self, comms: Any) -> None:
        """Inject a communicator (reference: comms/std_comms.hpp inject pattern)."""
        self.add_resource_factory(resource_type.COMMUNICATOR, lambda: comms)

    def get_comms(self) -> Any:
        return self.get_resource(resource_type.COMMUNICATOR)

    def comms_initialized(self) -> bool:
        return self.has_resource_factory(resource_type.COMMUNICATOR)

    def set_sub_comms(self, key: str, comms: Any) -> None:
        """Register a sub-communicator by key (reference: sub_comms.hpp)."""
        subs = self._resources.setdefault(resource_type.SUB_COMMUNICATOR, {})
        subs[key] = comms

    def get_sub_comms(self, key: str) -> Any:
        subs = self._resources.get(resource_type.SUB_COMMUNICATOR, {})
        expects(key in subs, f"no sub-communicator '{key}'")
        return subs[key]

    def sync(self) -> None:
        """Block until enqueued device work completes.

        Reference: ``device_resources::sync_stream`` (:164).  JAX dispatch is
        async; this is the barrier tests/benchmarks use.
        """
        jax.effects_barrier()


class _RngChain:
    """Deterministic PRNG key chain (reference analogue: rng_state's
    seed+subsequence, random/rng_state.hpp:28-52 — jax keys are already
    counter-based, so a fold_in chain is the native fit)."""

    def __init__(self, seed: int) -> None:
        self._key = jax.random.key(seed)
        self._count = 0

    def next_key(self) -> jax.Array:
        self._count += 1
        return jax.random.fold_in(self._key, self._count)
