"""Logging.

Counterpart of the reference's spdlog-backed singleton logger with a callback
sink so host applications can capture log records
(cpp/include/raft/core/logger.hpp:36,118-180; core/detail/callback_sink.hpp).

Implemented over :mod:`logging` with the same surface: settable level/pattern,
an optional callback sink, and ``RAFT_LOG_*``-style helpers.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

OFF = logging.CRITICAL + 10
CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARN = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
TRACE = logging.DEBUG - 5

logging.addLevelName(TRACE, "TRACE")

_DEFAULT_PATTERN = "[%(levelname)s] [%(asctime)s] %(message)s"


class _CallbackHandler(logging.Handler):
    """Routes records to a user callback (reference: callback_sink.hpp)."""

    def __init__(self, callback: Callable[[int, str], None],
                 flush: Optional[Callable[[], None]] = None):
        super().__init__()
        self._callback = callback
        self._flush = flush

    def emit(self, record: logging.LogRecord) -> None:
        self._callback(record.levelno, self.format(record))

    def flush(self) -> None:
        if self._flush is not None:
            self._flush()


class Logger:
    """Singleton logger (reference: ``raft::logger``, core/logger.hpp:118)."""

    _instance: Optional["Logger"] = None

    def __init__(self) -> None:
        self._logger = logging.getLogger("raft_tpu")
        self._logger.propagate = False
        self._stream = logging.StreamHandler(sys.stderr)
        self._formatter = logging.Formatter(_DEFAULT_PATTERN)
        self._stream.setFormatter(self._formatter)
        self._logger.addHandler(self._stream)
        self._logger.setLevel(INFO)
        self._callback_handler: Optional[_CallbackHandler] = None

    @classmethod
    def get(cls) -> "Logger":
        if cls._instance is None:
            cls._instance = Logger()
        return cls._instance

    def set_level(self, level: int) -> None:
        self._logger.setLevel(level)

    def get_level(self) -> int:
        return self._logger.level

    def should_log_for(self, level: int) -> bool:
        return self._logger.isEnabledFor(level)

    def set_pattern(self, pattern: str) -> None:
        self._formatter = logging.Formatter(pattern)
        for h in self._logger.handlers:
            h.setFormatter(self._formatter)

    def set_callback(self, callback: Optional[Callable[[int, str], None]],
                     flush: Optional[Callable[[], None]] = None) -> None:
        """Install (or clear) a callback sink replacing stderr output."""
        if self._callback_handler is not None:
            self._logger.removeHandler(self._callback_handler)
            self._callback_handler = None
            if self._stream not in self._logger.handlers:
                self._logger.addHandler(self._stream)
        if callback is not None:
            self._logger.removeHandler(self._stream)
            self._callback_handler = _CallbackHandler(callback, flush)
            self._callback_handler.setFormatter(self._formatter)
            self._logger.addHandler(self._callback_handler)

    def log(self, level: int, msg: str, *args) -> None:
        self._logger.log(level, msg, *args)

    def flush(self) -> None:
        for h in self._logger.handlers:
            h.flush()


def log_trace(msg: str, *args) -> None:
    Logger.get().log(TRACE, msg, *args)


def log_debug(msg: str, *args) -> None:
    Logger.get().log(DEBUG, msg, *args)


def log_info(msg: str, *args) -> None:
    Logger.get().log(INFO, msg, *args)


def log_warn(msg: str, *args) -> None:
    Logger.get().log(WARN, msg, *args)


def log_error(msg: str, *args) -> None:
    Logger.get().log(ERROR, msg, *args)
