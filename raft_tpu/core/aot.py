"""AOT export of compiled entry points — the instantiation-layer analogue.

Reference: cpp/src's 139 precompiled template instantiation units +
pylibraft's prebuilt wheels give RAFT a compile-free deployment story.
The TPU-native equivalent is **StableHLO export**: trace + lower a
jitted entry point once, serialize the portable artifact
(`jax.export`), and reload it in a process that never imports the
algorithm's Python (or pays its trace time).  Artifacts are
version-stable across jax releases per the StableHLO compatibility
guarantees and are compiled (not re-traced) on load.

Usage::

    from raft_tpu.core import aot
    blob = aot.export_fn(fn, example_args)         # bytes
    g = aot.load_fn(blob)                          # callable
    out = g(*args)                                 # same shapes/dtypes

`save_search_fn` / `load_search_fn` wrap the ANN flagship: a
searchable IVF-PQ index becomes one self-contained artifact (index
arrays + exported search program) — the deployment shape of the
reference's serialized index + prebuilt kernels.
"""

from __future__ import annotations

import io
import threading
import weakref
from typing import BinaryIO, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import export as jax_export

from raft_tpu.core.error import expects

_MAGIC = b"RAFT_TPU_AOT1"


def export_fn(fn: Callable, example_args: Sequence) -> bytes:
    """Lower + serialize ``jit(fn)`` for the example args' shapes/dtypes.

    ``fn`` must be jit-compatible; the artifact is specialized to the
    example shapes (the reference's instantiation grid is likewise
    shape-specialized — one unit per (T, IdxT, dims...) combination).
    """
    shapes = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
        if not hasattr(a, "shape") else jax.ShapeDtypeStruct(a.shape, a.dtype),
        tuple(example_args))
    exp = jax_export.export(jax.jit(fn))(*shapes)
    return bytes(exp.serialize())


def load_fn(blob: bytes) -> Callable:
    """Deserialize an exported entry point into a callable."""
    exp = jax_export.deserialize(blob)

    def call(*args):
        return exp.call(*args)

    return call


def save_search_fn(stream: BinaryIO, fn: Callable, arrays: Sequence,
                   example_queries) -> None:
    """One-file deployment artifact: captured arrays + exported program.

    ``fn(arrays..., *runtime) -> (distances, indices)``; ``arrays`` are
    baked into the artifact (host numpy).  ``example_queries`` is the
    runtime input — a single queries example, or a tuple of runtime
    inputs (e.g. ``(queries, filter_words)`` for a filtered export); the
    loaded callable takes them positionally.
    """
    import jax.numpy as jnp

    runtime = (example_queries if isinstance(example_queries, tuple)
               else (example_queries,))
    blob = export_fn(fn, tuple(arrays) + runtime)
    # non-executable container on purpose: npz for the arrays + a
    # length-prefixed raw program blob (a pickle payload would execute
    # arbitrary code when loading an untrusted artifact).  bf16 has no
    # numpy representation; it rides as a uint16 view + dtype manifest.
    stream.write(_MAGIC)
    stream.write(len(blob).to_bytes(8, "little"))
    stream.write(blob)
    metas, store = [], {}
    for i, a in enumerate(arrays):
        a = jnp.asarray(a)
        if a.dtype == jnp.bfloat16:
            store[f"a{i}"] = np.asarray(
                jax.lax.bitcast_convert_type(a, jnp.uint16))
            metas.append("bfloat16")
        else:
            store[f"a{i}"] = np.asarray(a)
            metas.append("native")
    store["dtypes"] = np.asarray(metas)
    np.savez(stream, **store)


def load_search_fn(stream: BinaryIO) -> Callable:
    """Load a :func:`save_search_fn` artifact; returns ``g(queries)``."""
    magic = stream.read(len(_MAGIC))
    expects(magic == _MAGIC, "aot: not a raft_tpu AOT artifact")
    blob_len = int.from_bytes(stream.read(8), "little")
    call = load_fn(stream.read(blob_len))
    import jax.numpy as jnp

    with np.load(stream, allow_pickle=False) as payload:
        metas = [str(s) for s in payload["dtypes"]]
        arrays = []
        for i, meta in enumerate(metas):
            a = jnp.asarray(payload[f"a{i}"])
            if meta == "bfloat16":
                a = jax.lax.bitcast_convert_type(a, jnp.bfloat16)
            arrays.append(a)

    def g(*runtime):
        return call(*arrays, *runtime)

    return g


# ---------------------------------------------------------------------------
# executable cache — bucket-shaped warm executors for the serving layer
# ---------------------------------------------------------------------------

class ExecutableCache:
    """Process cache of loaded search executables, keyed per bucket shape.

    The serving layer pre-warms one executable per *bucket* — the same
    index exported at several batch sizes (1, 2, 4, ... max_batch).  The
    cache key therefore includes EVERY shape the export was specialized
    to: ``(kind, index identity, batch, k, n_probes, extra...)``.  Keying
    on the index alone (the obvious first cut) collides the buckets —
    every bucket would get the executable of whichever batch size warmed
    first, and steady-state traffic at the other sizes would re-trace.

    Index identity is ``id(index)`` *validated through a weakref*: a hit
    whose stored referent is no longer the keyed object (the id was
    recycled after a gc) is treated as a miss and re-exported, so a dead
    index can never serve another index's executables.

    Loaded callables dispatch through jax's primitive cache keyed on the
    (stable) exported-program identity and argument avals: the serving
    warmup calls each bucket's executable once, after which steady-state
    traffic at any warmed bucket shape triggers zero recompiles.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, Tuple[weakref.ref, Callable]] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, kind: str, res, index, *, batch: int, k: int,
            n_probes: int = 0, scan_mode: Optional[str] = None,
            rung: int = 0, **export_kwargs) -> Callable:
        """The warmed ``g(queries) -> (distances, indices)`` for one
        bucket, exporting + loading on first use.

        ``kind`` is one of ``"ivf_pq" | "ivf_flat" | "brute_force" |
        "cagra"``; ``batch`` is the bucket's (padded) query count and is
        part of the cache key.  ``rung`` is the serving degradation-
        ladder position (brownout, PR 12): it joins the cache key — like
        ``scan_mode`` — but is NOT forwarded to the exporter, so two
        rungs that happen to share search parameters still get distinct
        warmed entries and a brownout transition can never alias a
        colder rung onto a warm one.  Extra keyword arguments are
        forwarded to the exporter (and keyed on, sorted by name).
        """
        extra = tuple(sorted(export_kwargs.items()))
        # generation rides in the key alongside the id()+weakref identity
        # check: a mutated index is a NEW object (delete/extend/compact
        # return fresh snapshots), but keying the generation explicitly
        # keeps a recycled id() from ever pairing a stale executable with
        # a newer generation, and makes swap-time invalidation exact.
        # by_list indexes additionally key their PLACEMENT generation: a
        # rebalance that moves lists between shards invalidates every
        # per-shard executable even if no row was mutated
        placement_gen = int(getattr(getattr(index, "placement", None),
                                    "generation", 0) or 0)
        key = (kind, id(index), int(getattr(index, "generation", 0) or 0),
               placement_gen, int(batch), int(k), int(n_probes),
               scan_mode, int(rung), extra)
        from raft_tpu import observability as obs
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None and hit[0]() is index:
                if obs.enabled():
                    obs.registry().counter("aot.cache.hits").inc()
                return hit[1]
        if obs.enabled():
            obs.registry().counter("aot.cache.misses").inc()
        # always-on flight event: a miss outside warmup/swap means an
        # export+compile on the serving path — exactly the "why did p99
        # spike" answer a flight dump should contain
        from raft_tpu.observability import flight as _flight
        _flight.record_event("aot.cache_miss", kind=kind, batch=int(batch),
                             k=int(k), n_probes=int(n_probes),
                             scan_mode=scan_mode)
        g = self._export_load(kind, res, index, batch=batch, k=k,
                              n_probes=n_probes, scan_mode=scan_mode,
                              **export_kwargs)
        with self._lock:
            self._entries[key] = (weakref.ref(index), g)
        return g

    def _export_load(self, kind: str, res, index, *, batch: int, k: int,
                     n_probes: int, scan_mode: Optional[str],
                     **export_kwargs) -> Callable:
        if kind == "ivf_pq":
            buf = export_ivf_pq_search(
                res, index, n_probes=n_probes, k=k, batch=batch,
                scan_mode=scan_mode or "recon", **export_kwargs)
        elif kind == "ivf_pq_routed":
            # per-shard routed program; `shard` (and, for the fused scan,
            # `group_capacity`) arrive via export_kwargs and are part of
            # the cache key like every other export specialization
            buf = export_ivf_pq_routed_search(
                res, index, n_probes=n_probes, k=k, batch=batch,
                scan_mode=scan_mode or "recon", **export_kwargs)
        elif kind == "ivf_flat":
            buf = export_ivf_flat_search(res, index, n_probes=n_probes,
                                         k=k, batch=batch, **export_kwargs)
        elif kind == "brute_force":
            buf = export_brute_force_knn(res, index, k=k, batch=batch,
                                         **export_kwargs)
        elif kind == "cagra":
            buf = export_cagra_search(res, index, k=k, batch=batch,
                                      **export_kwargs)
        else:
            expects(False, f"aot: unknown executable kind {kind!r}")
        # NOT wrapped in an outer jit: an exported call dispatches through
        # the primitive cache keyed on (exported identity, avals) — warm
        # once, then zero recompiles — while jit(g) would re-lower the
        # program with the index arrays embedded as constants (a second
        # compile AND a second copy of the index in device memory)
        return load_search_fn(buf)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_EXECUTABLES = ExecutableCache()


def executables() -> ExecutableCache:
    """The process-global executable cache (serving warms into this)."""
    return _EXECUTABLES


def export_ivf_pq_search(res, index, n_probes: int, k: int, batch: int,
                         *, scan_mode: str = "recon",
                         group_capacity: int = 0,
                         merge_window=0,
                         n_filter_words: int = 0) -> io.BytesIO:
    """Export the flagship IVF-PQ search at fixed (batch, k, n_probes)
    into a self-contained artifact (reference analogue: serialized index
    + the prebuilt search instantiation).

    ``scan_mode`` picks which index representation rides in the
    artifact:

    - ``"recon"`` bakes the bf16 reconstruction cache and exports the
      recon scan (2 bytes/dim/row in the artifact — the fastest live
      formulation, also the largest file).
    - ``"fused"`` bakes the recon cache and exports the GROUPED scan at
      the static group capacity ``group_capacity`` (0 derives the
      exact-safe worst bound from (batch, n_probes, n_lists) — group
      construction is fully traceable at a static capacity since
      round 10, so the list-centric formulation exports like any other).
      The Pallas in-kernel top-k variants remain runtime dispatch paths;
      the exported XLA twin computes identical quantized distances.
      Falls back to the LUT export below when the index carries no recon
      cache.
    - ``"codes"`` / ``"lut"`` bake only the bit-packed PQ codes +
      codebooks and export the portable LUT formulation over them
      (~pq_bits/8 bytes per subspace per row — the compact deployment
      shape); it computes the same quantized distances as the codes
      kernel, so an artifact warmed under either mode answers
      identically while carrying its own distinct
      :class:`ExecutableCache` key component.

    ``merge_window`` ("auto" | int, see
    :data:`raft_tpu.neighbors.ivf_pq.SearchParams.merge_window`) windows
    the baked grouped scan's staged scatter (the XLA twin of the fused
    kernels' staging ring) and keys the artifact in
    :class:`ExecutableCache` — serving pre-warms one executable per
    (bucket, k, merge_window) point, so the live Pallas dispatch and the
    exported twin share a cache dimension.  Ignored by the non-grouped
    exports, where there is no staged scatter to window.
    """
    from raft_tpu.neighbors import grouped, ivf_pq
    from raft_tpu.ops import vmem_budget as vb

    merge_window = vb.merge_window_request(merge_window)

    expects(scan_mode in ("recon", "codes", "lut", "fused"),
            "aot: scan_mode must be 'recon', 'codes', 'lut' or 'fused'")
    metric = index.metric
    # n_filter_words > 0 adds a second runtime input: a (batch, n_words)
    # int32 packed admission bitset (raft_tpu.filters.bitset), threaded
    # through the scan's admission seam.  Filters are data, not shape —
    # one filtered artifact serves every predicate at this bucket
    # (all-ones words = unfiltered).
    nfw = int(n_filter_words)

    if scan_mode == "fused" and index.list_recon is None:
        scan_mode = "lut"
    if scan_mode in ("recon", "fused"):
        expects(index.list_recon is not None,
                "aot: index must carry the reconstruction cache")
        if index.list_recon_sq is None:
            index.list_recon_sq = ivf_pq._recon_sq(index.list_recon)

        if scan_mode == "fused":
            n_groups = int(group_capacity) or grouped.group_capacity(
                batch, n_probes, index.n_lists)[0]
            cap = int(index.capacity)
            rot = int(index.rot_dim)
            G = grouped.GROUP
            block = grouped.block_size(n_groups, G * cap * 8,
                                       cap * rot * 2, G * rot * 4)

            def fn(centers, list_recon, list_recon_sq, list_indices,
                   rotation, queries, *rt):
                probes = ivf_pq._select_clusters(centers, rotation,
                                                 queries, n_probes,
                                                 metric)
                return ivf_pq._search_impl_recon_grouped(
                    centers, list_recon, list_recon_sq, list_indices,
                    rotation, queries, probes, k, metric, n_groups,
                    block, merge_window=merge_window,
                    filter_words=rt[0] if nfw else None)
        else:
            def fn(centers, list_recon, list_recon_sq, list_indices,
                   rotation, queries, *rt):
                # the precomputed norms ride in the artifact — without
                # them the exported program would recompute a full pass
                # over the recon cache per batch (they are runtime
                # inputs, not constants)
                return ivf_pq._search_impl_recon(
                    centers, list_recon, list_indices, rotation, queries,
                    k=k, n_probes=n_probes, metric=metric,
                    list_recon_sq=list_recon_sq,
                    filter_words=rt[0] if nfw else None)

        arrays = (index.centers, index.list_recon, index.list_recon_sq,
                  index.list_indices, index.rotation)
    else:
        codebook_kind = index.codebook_kind
        pq_bits = index.pq_bits

        def fn(centers, codebooks, list_codes, list_indices, rotation,
               queries, *rt):
            return ivf_pq._search_impl(
                centers, codebooks, list_codes, list_indices, rotation,
                queries, k=k, n_probes=n_probes, metric=metric,
                codebook_kind=codebook_kind, lut_dtype=jax.numpy.float32,
                pq_bits=pq_bits, filter_words=rt[0] if nfw else None)

        arrays = (index.centers, index.codebooks, index.list_codes,
                  index.list_indices, index.rotation)

    example_q = jax.ShapeDtypeStruct((batch, index.dim),
                                     index.centers.dtype)
    runtime = ((example_q, jax.ShapeDtypeStruct((batch, nfw), np.int32))
               if nfw else example_q)
    buf = io.BytesIO()
    save_search_fn(buf, fn, arrays, runtime)
    buf.seek(0)
    return buf


def export_ivf_pq_routed_search(res, index, shard: int, n_probes: int,
                                k: int, batch: int, *,
                                scan_mode: str = "recon",
                                group_capacity: int = 0,
                                merge_window=0,
                                replica_rank: int = 0,
                                n_filter_words: int = 0) -> io.BytesIO:
    """Export ONE shard's routed (``placement="by_list"``) search
    program at fixed (batch, k, n_probes): replicated coarse routing +
    ownership mask + the shard-local scan over the owned lists +
    shard-local top-k.  The artifact is the per-chip deployment unit of
    an index-parallel mesh — each chip loads its own shard's program,
    and the k-bounded candidate exchange/merge stays in the (tiny)
    runtime layer.  Merging every shard's exported outputs with
    ``grouped.finalize_topk`` reproduces the live
    :func:`raft_tpu.distributed.ann.search` answer exactly (the
    hierarchical-top-k argument; asserted in tests).

    ``scan_mode="recon"`` (default) bakes the probe-order recon scan.
    ``scan_mode="fused"`` bakes the grouped scan at the static group
    capacity ``group_capacity`` (0 derives the exact-safe worst bound
    from (batch, n_probes, slots) — see
    :func:`raft_tpu.neighbors.grouped.group_capacity`); group
    construction is fully traceable at a static capacity (round 10), so
    the export carries zero host syncs and the serving tier's bucket
    pre-warm covers fused routed executables like any other shape.

    ``shard_map`` itself is not exportable — this bakes the shard's
    leaves plus the replicated routing arrays (coarse centers, rotation,
    owner, local_slot) into a single-device program instead.

    ``merge_window`` windows the fused export's staged scatter exactly
    as in :func:`export_ivf_pq_search` (and keys the artifact the same
    way).

    ``replica_rank`` (a replicated placement only) bakes replica rank
    ``j``'s routing tables instead of the primaries': the exported
    program answers for the lists this shard owns *at that rank* — the
    artifact a deployment loads to serve a failed primary's share.  The
    shard's local leaves already hold every rank's owned lists (the slot
    layout is the union), so only the two routing arrays differ; the
    rank is part of the executable-cache key."""
    from raft_tpu.neighbors import grouped, ivf_pq
    from raft_tpu.ops import vmem_budget as vb

    merge_window = vb.merge_window_request(merge_window)

    expects(getattr(index, "placement", None) is not None,
            "aot: export_ivf_pq_routed_search needs a RoutedIndex "
            "(placement='by_list')")
    expects(0 <= shard < index.n_shards,
            f"aot: shard {shard} out of range for {index.n_shards}")
    expects(scan_mode in ("recon", "fused"),
            f"aot: export_ivf_pq_routed_search supports scan_mode "
            f"'recon' or 'fused', got {scan_mode!r}")
    expects(0 <= replica_rank < index.placement.replication_factor,
            f"aot: replica_rank {replica_rank} out of range for "
            f"replication_factor "
            f"{index.placement.replication_factor}")
    metric = index.metric
    slots = int(index.local_centers.shape[1])
    dummy = slots - 1
    # filtered routed export: the SAME (batch, n_words) bitset every
    # shard receives (filters address global row ids, so the broadcast
    # needs no per-shard slicing)
    nfw = int(n_filter_words)

    if scan_mode == "fused":
        n_groups = int(group_capacity) or grouped.group_capacity(
            batch, n_probes, slots)[0]
        cap = int(index.capacity)
        rot = int(index.rotation.shape[1])
        G = grouped.GROUP
        block = grouped.block_size(n_groups, G * cap * 8,
                                   cap * rot * 2, G * rot * 4)

        def fn(coarse, rotation, owner, local_slot, local_centers,
               list_recon, list_recon_sq, list_indices, queries, *rt):
            probes = ivf_pq._select_clusters(coarse, rotation, queries,
                                             n_probes, metric)
            owned = owner[probes] == shard
            # out-of-range sentinel (== slots): build_groups drops the
            # unowned pairs entirely (see _dist_search_routed_grouped)
            local_probes = jax.numpy.where(
                owned, local_slot[probes],
                slots).astype(jax.numpy.int32)
            return ivf_pq._search_impl_recon_grouped(
                local_centers, list_recon, list_recon_sq, list_indices,
                rotation, queries, local_probes, k, metric, n_groups,
                block, merge_window=merge_window,
                filter_words=rt[0] if nfw else None)
    else:
        def fn(coarse, rotation, owner, local_slot, local_centers,
               list_recon, list_recon_sq, list_indices, queries, *rt):
            probes = ivf_pq._select_clusters(coarse, rotation, queries,
                                             n_probes, metric)
            owned = owner[probes] == shard
            local_probes = jax.numpy.where(owned, local_slot[probes],
                                           dummy).astype(jax.numpy.int32)
            return ivf_pq._search_impl_recon(
                local_centers, list_recon, list_indices, rotation,
                queries, k=k, n_probes=n_probes, metric=metric,
                probes=local_probes, list_recon_sq=list_recon_sq,
                filter_words=rt[0] if nfw else None)

    if replica_rank > 0:
        rank_owner, rank_slot = index.placement.rank_tables()
        route = (rank_owner[replica_rank], rank_slot[replica_rank])
    else:
        route = (index.owner, index.local_slot)
    arrays = tuple(jax.device_get(a) for a in (
        index.coarse_centers, index.rotation) + route + (
        index.local_centers[shard],
        index.list_recon[shard], index.list_recon_sq[shard],
        index.list_indices[shard]))
    example_q = jax.ShapeDtypeStruct((batch, index.dim),
                                     index.coarse_centers.dtype)
    runtime = ((example_q, jax.ShapeDtypeStruct((batch, nfw), np.int32))
               if nfw else example_q)
    buf = io.BytesIO()
    save_search_fn(buf, fn, arrays, runtime)
    buf.seek(0)
    return buf


def warm_write_router(index, batches: Sequence[int]) -> int:
    """Pre-trace the distributed WRITE router (round 19) at the serving
    write-batch shapes: one jitted ``_select_clusters`` call per batch
    size with ``n_probes=1`` against the replicated coarse quantizer —
    exactly what :func:`raft_tpu.distributed.ann.route_vectors` runs per
    upsert/delete.  Called from the routed ingest tier's ``prewarm`` so
    the first write after a deploy (or the first re-routed write after a
    failover) hits a warm executable; routing tables are data, so
    placement changes never invalidate these traces.  Returns the number
    of shapes warmed."""
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import ivf_pq

    warmed = 0
    for b in sorted({int(b) for b in batches if int(b) > 0}):
        zeros = jax.numpy.zeros((b, index.dim),
                                index.coarse_centers.dtype)
        out = ivf_pq._select_clusters(index.coarse_centers,
                                      index.rotation, zeros, 1,
                                      DistanceType(index.metric))
        jax.block_until_ready(out)
        warmed += 1
    return warmed


def export_ivf_flat_search(res, index, n_probes: int, k: int,
                           batch: int, *,
                           n_filter_words: int = 0) -> io.BytesIO:
    """Export the IVF-Flat search at fixed (batch, k, n_probes): raw
    list vectors + exported scan program in one artifact (reference
    analogue: the per-(T, IdxT, veclen) interleaved-scan instantiations
    in cpp/src/neighbors/ivfflat_*).  ``n_filter_words`` > 0 adds the
    packed admission bitset as a second runtime input (see
    :func:`export_ivf_pq_search`)."""
    from raft_tpu.neighbors import ivf_flat

    metric = index.metric
    nfw = int(n_filter_words)

    def fn(centers, list_data, list_indices, queries, *rt):
        return ivf_flat._search_impl(centers, list_data, list_indices,
                                     queries, k=k, n_probes=n_probes,
                                     metric=metric,
                                     filter_words=rt[0] if nfw else None)

    example_q = jax.ShapeDtypeStruct((batch, index.dim),
                                     index.centers.dtype)
    runtime = ((example_q, jax.ShapeDtypeStruct((batch, nfw), np.int32))
               if nfw else example_q)
    buf = io.BytesIO()
    save_search_fn(buf, fn, (index.centers, index.list_data,
                             index.list_indices), runtime)
    buf.seek(0)
    return buf


def export_brute_force_knn(res, database, k: int, batch: int, *,
                           metric=None, metric_arg: float = 2.0,
                           n_filter_words: int = 0) -> io.BytesIO:
    """Export exact brute-force kNN over a fixed database at (batch, k):
    the database rides in the artifact, queries stay the runtime input
    (reference analogue: the brute_force_knn instantiation units).
    ``n_filter_words`` > 0 adds the packed admission bitset as a second
    runtime input (see :func:`export_ivf_pq_search`)."""
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import brute_force

    if metric is None:
        metric = DistanceType.L2Unexpanded
    database = jax.numpy.asarray(database)
    tile = min(brute_force._TILE_N, database.shape[0])
    nfw = int(n_filter_words)

    def fn(db, queries, *rt):
        if nfw:
            return brute_force._knn_impl(
                db, queries, k, metric, metric_arg, tile,
                filter_words=rt[0],
                id_offset=jax.numpy.int32(0))
        return brute_force._knn_impl(db, queries, k, metric, metric_arg,
                                     tile)

    example_q = jax.ShapeDtypeStruct((batch, database.shape[1]),
                                     database.dtype)
    runtime = ((example_q, jax.ShapeDtypeStruct((batch, nfw), np.int32))
               if nfw else example_q)
    buf = io.BytesIO()
    save_search_fn(buf, fn, (database,), runtime)
    buf.seek(0)
    return buf


def export_cagra_search(res, index, k: int, batch: int, *,
                        itopk: int = 64, search_width: int = 1,
                        max_iterations: int = 0,
                        walk_pdim: int = 0,
                        n_filter_words: int = 0) -> io.BytesIO:
    """Export the CAGRA packed-neighborhood walk at fixed (batch, k,
    itopk, search_width) into a self-contained artifact: walk table +
    entry set + exported walk program (reference analogue: serialized
    CAGRA index + the per-dtype prebuilt search units in
    cpp/src/neighbors/).

    The packed table and projection are calibrated/built here (the same
    lazy path the first live search takes) and baked into the artifact;
    fails when the fidelity calibration rejects every projection (the
    regime where the live search falls back to the exact direct walk —
    that path has data-dependent random seeds and is not exported).
    """
    from raft_tpu.neighbors import cagra

    itopk = max(itopk, k)
    pdim = walk_pdim or cagra._auto_pdim(index)
    expects(pdim > 0,
            "aot: walk fidelity calibration failed — no packed walk to "
            "export (the live fallback, the exact direct walk, is not "
            "exportable)")
    # same format ladder the live search uses (bf16, else the quantized
    # deep-scale format) — the exporter must cover every index the live
    # packed walk serves
    fmt = cagra._search_table_format(index, pdim)
    expects(fmt is not None,
            "aot: no packed walk table format fits the size gate")
    pdim, quant = fmt
    cache = cagra._walk_cache(res, index, pdim, max(4096, itopk),
                              quant=quant)
    max_iter = max_iterations or (10 + itopk // max(search_width, 1))
    rerank = max(min(itopk, max(32, 2 * k)), k)
    metric = index.metric
    deg = index.graph_degree
    nfw = int(n_filter_words)

    if quant:
        def fn(dataset, table, entry_proj, entry_sq, entry_ids, proj,
               scales, queries, *rt):
            return cagra._search_impl_walk(
                dataset, table, entry_proj, entry_sq, entry_ids, proj,
                queries, k, itopk, search_width, max_iter, metric,
                rerank, deg, quant=True, scales=scales,
                filter_words=rt[0] if nfw else None)

        arrays = (index.dataset, cache.table, cache.entry_proj,
                  cache.entry_sq, cache.entry_ids, cache.proj,
                  cache.scales)
    else:
        def fn(dataset, table, entry_proj, entry_sq, entry_ids, proj,
               queries, *rt):
            return cagra._search_impl_walk(
                dataset, table, entry_proj, entry_sq, entry_ids, proj,
                queries, k, itopk, search_width, max_iter, metric,
                rerank, deg, filter_words=rt[0] if nfw else None)

        arrays = (index.dataset, cache.table, cache.entry_proj,
                  cache.entry_sq, cache.entry_ids, cache.proj)

    example_q = jax.ShapeDtypeStruct((batch, index.dim),
                                     index.dataset.dtype)
    runtime = ((example_q, jax.ShapeDtypeStruct((batch, nfw), np.int32))
               if nfw else example_q)
    buf = io.BytesIO()
    save_search_fn(buf, fn, arrays, runtime)
    buf.seek(0)
    return buf
