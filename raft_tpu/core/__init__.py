"""Core layer: resources handle, array contracts, error/logging/tracing,
serialization, cooperative cancellation.

Reference: cpp/include/raft/core/ (see SURVEY.md §2.1).
"""

from raft_tpu.core.error import RaftError, LogicError, expects, fail  # noqa: F401
from raft_tpu.core.resources import (  # noqa: F401
    Resources,
    DeviceResources,
    resource_type,
)
from raft_tpu.core.mdarray import (  # noqa: F401
    ensure_array,
    check_matrix,
    check_vector,
    check_rank,
    check_same_shape,
    check_same_dtype,
    make_device_matrix,
    make_device_vector,
    make_device_scalar,
    row_major,
    col_major,
)
from raft_tpu.core.serialize import (  # noqa: F401
    CorruptIndexError,
    serialize_mdspan,
    deserialize_mdspan,
    serialize_scalar,
    deserialize_scalar,
)
from raft_tpu.core.interruptible import interruptible, InterruptedException  # noqa: F401
from raft_tpu.core import logger, tracing  # noqa: F401
