"""Error handling.

TPU-native counterpart of the reference's exception machinery
(cpp/include/raft/core/error.hpp: ``raft::exception``, ``raft::logic_error``,
``RAFT_EXPECTS`` :168, ``RAFT_FAIL`` :184).  Python exceptions already carry
backtraces, so the value here is the validation idiom: every public entry point
validates its inputs with :func:`expects` so shape/dtype contract violations
fail eagerly at trace time rather than deep inside XLA.
"""

from __future__ import annotations


class RaftError(RuntimeError):
    """Base exception for raft_tpu (reference: ``raft::exception``, error.hpp:67)."""


class LogicError(RaftError):
    """Invalid arguments / broken invariants (reference: ``raft::logic_error``, error.hpp:96)."""


def expects(cond: bool, msg: str = "precondition violated") -> None:
    """Validate a precondition; raise :class:`LogicError` on failure.

    Reference: ``RAFT_EXPECTS(cond, fmt, ...)`` (core/error.hpp:168).
    """
    if not cond:
        raise LogicError(msg)


def fail(msg: str) -> None:
    """Unconditionally raise (reference: ``RAFT_FAIL``, core/error.hpp:184)."""
    raise LogicError(msg)
