"""Array contracts.

The reference builds its entire API on ``mdspan``/``mdarray`` — non-owning
multi-dim views with compile-time layout and host/device accessor tags
(cpp/include/raft/core/device_mdspan.hpp:39,161,256; device_mdarray.hpp:47-172;
mdarray.hpp).  On TPU, ``jax.Array`` already *is* an owning, device-placed,
layout-carrying multi-dim array, and XLA picks physical layouts — so a vendored
mdspan would be pure ceremony.

What survives is the *contract*: every public function states and checks the
rank/shape/dtype relationships of its arguments up front (the role
``RAFT_EXPECTS`` + typed mdspan signatures play in the reference).  This module
provides those checkers plus the ``make_*`` factories mirroring the reference
naming so ported call sites read the same.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects

ArrayLike = Union[jax.Array, np.ndarray]

# Layout tags for API parity (reference: layout_c_contiguous / layout_f_contiguous).
# XLA controls physical layout; these document logical index order only.
row_major = "row_major"
col_major = "col_major"


def ensure_array(x: ArrayLike, name: str = "array") -> jax.Array:
    """Ingest any array-like (numpy, dlpack-capable, jax) as a jax.Array.

    Plays the role of pylibraft's ``cai_wrapper``/``ai_wrapper`` zero-copy
    ingestion (python/pylibraft/pylibraft/common/cai_wrapper.py:21).
    """
    if isinstance(x, jax.Array):
        return x
    if hasattr(x, "__dlpack__") and not isinstance(x, np.ndarray):
        return jnp.from_dlpack(x)
    return jnp.asarray(x)


def check_rank(x: jax.Array, rank: int, name: str = "array") -> None:
    expects(x.ndim == rank, f"{name}: expected rank {rank}, got {x.ndim}")


def check_matrix(x: ArrayLike, name: str = "matrix",
                 dtype: Optional[jnp.dtype] = None,
                 rows: Optional[int] = None,
                 cols: Optional[int] = None) -> jax.Array:
    """Validate a rank-2 array (reference: device_matrix_view contract)."""
    x = ensure_array(x, name)
    check_rank(x, 2, name)
    if dtype is not None:
        expects(x.dtype == jnp.dtype(dtype),
                f"{name}: expected dtype {jnp.dtype(dtype)}, got {x.dtype}")
    if rows is not None:
        expects(x.shape[0] == rows, f"{name}: expected {rows} rows, got {x.shape[0]}")
    if cols is not None:
        expects(x.shape[1] == cols, f"{name}: expected {cols} cols, got {x.shape[1]}")
    return x


def check_vector(x: ArrayLike, name: str = "vector",
                 dtype: Optional[jnp.dtype] = None,
                 size: Optional[int] = None) -> jax.Array:
    """Validate a rank-1 array (reference: device_vector_view contract)."""
    x = ensure_array(x, name)
    check_rank(x, 1, name)
    if dtype is not None:
        expects(x.dtype == jnp.dtype(dtype),
                f"{name}: expected dtype {jnp.dtype(dtype)}, got {x.dtype}")
    if size is not None:
        expects(x.shape[0] == size, f"{name}: expected size {size}, got {x.shape[0]}")
    return x


def check_same_shape(a: jax.Array, b: jax.Array,
                     names: Tuple[str, str] = ("a", "b")) -> None:
    expects(a.shape == b.shape,
            f"{names[0]} shape {a.shape} != {names[1]} shape {b.shape}")


def check_same_dtype(*arrays: jax.Array) -> None:
    dts = {a.dtype for a in arrays}
    expects(len(dts) == 1, f"dtype mismatch: {sorted(map(str, dts))}")


# -- factories mirroring reference naming (device_mdarray.hpp:134-172) -------

def make_device_matrix(res, n_rows: int, n_cols: int,
                       dtype=jnp.float32) -> jax.Array:
    """Zero-initialised (n_rows, n_cols) array on the handle's device."""
    dev = res.device if res is not None else None
    arr = jnp.zeros((n_rows, n_cols), dtype=dtype)
    return jax.device_put(arr, dev) if dev is not None else arr


def make_device_vector(res, n: int, dtype=jnp.float32) -> jax.Array:
    dev = res.device if res is not None else None
    arr = jnp.zeros((n,), dtype=dtype)
    return jax.device_put(arr, dev) if dev is not None else arr


def make_device_scalar(res, value, dtype=jnp.float32) -> jax.Array:
    dev = res.device if res is not None else None
    arr = jnp.asarray(value, dtype=dtype)
    return jax.device_put(arr, dev) if dev is not None else arr
