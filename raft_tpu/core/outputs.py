"""Output auto-conversion.

Parity with ``pylibraft.common.outputs`` (`/root/reference/python/pylibraft/
pylibraft/common/outputs.py:29-46` — torch/cupy converters, ``:75`` —
``auto_convert_output``).  The reference converts ``device_ndarray`` returns
to the globally configured ``__cuda_array_interface__`` type; raft_tpu
converts ``jax.Array`` returns to the type configured in
:mod:`raft_tpu.config` — numpy, torch (dlpack zero-copy when the buffer is
host-visible, host copy otherwise), or a user callable.

Tuples, lists, and NamedTuples of arrays are converted element-wise with
their container type preserved (the reference handles tuple/list,
outputs.py:84-90; NamedTuple support is new because raft_tpu's index/search
APIs return typed tuples).
"""

from __future__ import annotations

import functools
import warnings

import jax

import raft_tpu.config


def _import_warn(lib: str) -> None:
    warnings.warn(f"{lib} is not available and output cannot be converted. "
                  "Returning original output instead.")


def convert_to_torch(arr: jax.Array):
    """jax.Array -> torch.Tensor (outputs.py:29 ``convert_to_torch``)."""
    try:
        import torch
    except ImportError:
        _import_warn("PyTorch")
        return arr
    try:
        return torch.from_dlpack(arr)     # zero-copy when host-visible
    except Exception:
        import numpy as np
        # copy: np.asarray over a jax buffer is read-only, and torch
        # aliasing read-only memory is undefined behavior on write
        return torch.as_tensor(np.array(arr))


def convert_to_numpy(arr: jax.Array):
    import numpy as np
    return np.asarray(arr)


def convert_output(arr: jax.Array):
    """Apply the configured conversion to one array
    (``convert_to_cai_type`` analogue, outputs.py:52-64)."""
    output_as = raft_tpu.config.output_as_
    if callable(output_as):
        return output_as(arr)
    if output_as == "jax":
        return arr
    if output_as == "numpy":
        return convert_to_numpy(arr)
    if output_as == "torch":
        return convert_to_torch(arr)
    raise ValueError(f"No valid type conversion found for {output_as!r}")


def _convert_value(value):
    if isinstance(value, jax.core.Tracer):
        # decorated primitives (select_k, pairwise_distance, ...) are also
        # called *inside* jitted compositions; converting a tracer would
        # crash the trace. Pass it through — the outermost decorated,
        # un-jitted entry point performs the conversion.
        return value
    if isinstance(value, jax.Array):
        return convert_output(value)
    if isinstance(value, tuple):
        converted = [_convert_value(v) for v in value]
        if hasattr(value, "_fields"):     # NamedTuple: rebuild by fields
            return type(value)(*converted)
        return tuple(converted)
    if isinstance(value, list):
        return [_convert_value(v) for v in value]
    return value


def auto_convert_output(f):
    """Decorator converting ``jax.Array`` returns (or containers of them)
    to the configured output type (outputs.py:75 ``auto_convert_output``)."""

    @functools.wraps(f)
    def wrapper(*args, **kwargs):
        return _convert_value(f(*args, **kwargs))

    return wrapper


def raw(f):
    """The undecorated implementation of an auto-converted public function.

    Internal library composition must stay in ``jax.Array`` land regardless
    of the user's configured output type — a decorated primitive called from
    un-jitted library code would otherwise hand numpy/torch values to jax
    ops (``.at[]``, ``lax.top_k``) mid-pipeline.
    """
    return getattr(f, "__wrapped__", f)
