"""Tracing / profiling annotations.

Counterpart of the reference's NVTX ranges (cpp/include/raft/core/nvtx.hpp:48-76):
RAII ``common::nvtx::range<domain>`` plus ``push_range``/``pop_range``, used at
every algorithm entry point.  On TPU the profiler is ``jax.profiler`` and the
annotation primitive is ``jax.named_scope`` / ``jax.profiler.TraceAnnotation``;
we expose the same surface.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator, List

import jax


class domain:
    """Annotation domains (reference: core/nvtx.hpp ``domain::app`` / ``domain::raft``)."""

    app = "app"
    raft = "raft_tpu"


class _RangeStack(threading.local):
    """Per-thread stack — jax.named_scope is thread-local, so imperative
    push/pop must be too (the reference's nvtx ranges are per-thread)."""

    def __init__(self) -> None:
        self.items: List[Any] = []


_range_stack = _RangeStack()


@contextlib.contextmanager
def range(name: str, *fmt_args: Any, domain: str = domain.raft) -> Iterator[None]:
    """RAII-style trace range (reference: ``common::nvtx::range``, core/nvtx.hpp:76).

    Inside a traced/jitted computation this adds a named scope to the HLO (so
    the op shows up grouped in the TPU profiler); outside it also emits a
    ``jax.profiler`` trace annotation visible in host traces.
    """
    if fmt_args:
        name = name % fmt_args
    label = f"{domain}:{name}"
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield


def push_range(name: str, *fmt_args: Any) -> None:
    """Imperative begin-range (reference: core/nvtx.hpp ``push_range``)."""
    cm = range(name, *fmt_args)
    cm.__enter__()
    _range_stack.items.append(cm)


def pop_range() -> None:
    """Imperative end-range (reference: core/nvtx.hpp ``pop_range``)."""
    if _range_stack.items:
        cm = _range_stack.items.pop()
        cm.__exit__(None, None, None)
