"""Single-linkage agglomerative clustering.

Reference: raft/cluster/single_linkage.cuh:112 — pipeline (SURVEY.md §2.7):
``detail/connectivities.cuh`` (kNN-graph connectivity), ``detail/mst.cuh:194``
(Boruvka MST + ``connect_components`` fix-up for disconnected kNN graphs),
``detail/agglomerative.cuh`` (dendrogram build + cluster-cut labeling —
union-find ON HOST in the reference too).

TPU design: graph + MST run on device (sparse.knn_graph / sparse.mst); the
final dendrogram labeling is the same O(n α(n)) host union-find the reference
uses — it is inherently sequential and tiny.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.sparse.formats import CooMatrix
from raft_tpu.sparse.linalg import symmetrize
from raft_tpu.sparse.neighbors import connect_components, knn_graph
from raft_tpu.sparse.solver import mst
from raft_tpu.core.outputs import raw


class LinkageDistance:
    """Reference: single_linkage.cuh ``LinkageDistance`` enum."""

    PAIRWISE = 0
    KNN_GRAPH = 1


@dataclasses.dataclass
class SingleLinkageOutput:
    """Reference: single_linkage.cuh ``linkage_output``."""

    labels: np.ndarray          # (n,)
    dendrogram: np.ndarray      # (n-1, 2) merged children
    distances: np.ndarray       # (n-1,) merge heights
    n_clusters: int


def _host_union_find_labels(src, dst, w, n, n_clusters
                            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sort MST edges by weight, union in order, stop at n_clusters
    components (reference: detail/agglomerative.cuh build_dendrogram_host +
    extract_flattened_clusters).  Runs the native C++ union-find when the
    compiled library is available (raft_tpu.native); this pure-Python body
    is the fallback and the reference implementation for its tests."""
    from raft_tpu import native
    out = native.build_dendrogram(src, dst, w, n, n_clusters)
    if out is not None:
        return out
    order = np.argsort(w, kind="stable")
    parent = np.arange(n)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    dendrogram, heights = [], []
    merges_needed = n - n_clusters
    for e in order:
        if len(dendrogram) >= merges_needed:
            break
        a, b = find(int(src[e])), find(int(dst[e]))
        if a == b:
            continue
        parent[max(a, b)] = min(a, b)
        dendrogram.append((int(src[e]), int(dst[e])))
        heights.append(float(w[e]))
    roots = np.asarray([find(i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return (labels.astype(np.int32),
            np.asarray(dendrogram, np.int32).reshape(-1, 2),
            np.asarray(heights, np.float32))


def single_linkage(
    res,
    X,
    *,
    n_clusters: int,
    metric: int = DistanceType.L2SqrtExpanded,
    linkage: int = LinkageDistance.KNN_GRAPH,
    c: int = 15,
) -> SingleLinkageOutput:
    """Single-linkage clustering (reference: single_linkage.cuh:112; ``c``
    controls kNN-graph degree like the reference's ``c`` neighborhood knob).
    """
    with named_range("single_linkage"):
        X = ensure_array(X, "X")
        n = X.shape[0]
        expects(2 <= n_clusters <= n,
                "single_linkage: need 2 <= n_clusters <= n")

        if linkage == LinkageDistance.KNN_GRAPH:
            k = min(max(c, 2), n - 1)
            graph = knn_graph(res, X, k, metric=metric)
        else:
            # PAIRWISE: full dense distances as a (dense->coo) graph — the
            # reference's pairwise connectivity path
            from raft_tpu.distance.pairwise import pairwise_distance
            from raft_tpu.sparse.formats import dense_to_coo
            d = raw(pairwise_distance)(X, X, metric)
            d = d.at[jnp.arange(n), jnp.arange(n)].set(0.0)
            graph = dense_to_coo(d)

        src, dst, w, color = mst(res, graph)
        src_h = np.asarray(src)
        dst_h = np.asarray(dst)
        w_h = np.asarray(w)
        valid = src_h >= 0
        src_h, dst_h, w_h = src_h[valid], dst_h[valid], w_h[valid]

        # fix-up for disconnected kNN graphs (reference: mst.cuh:194
        # connect_components loop)
        colors = np.asarray(color)
        guard = 0
        while len(np.unique(colors)) > 1 and guard < 32:
            cc_src, cc_dst, cc_d = connect_components(
                res, X, jnp.asarray(colors),
                metric=DistanceType.L2Expanded)
            cs, cd, cw = (np.asarray(cc_src), np.asarray(cc_dst),
                          np.asarray(cc_d))
            ok = cs >= 0
            src_h = np.concatenate([src_h, cs[ok]])
            dst_h = np.concatenate([dst_h, cd[ok]])
            w_h = np.concatenate([w_h, np.sqrt(np.maximum(cw[ok], 0))
                                  if metric in (DistanceType.L2SqrtExpanded,
                                                DistanceType.L2SqrtUnexpanded)
                                  else cw[ok]])
            # recompute components on host union-find over current edges
            from raft_tpu import native
            cc = native.connected_components(src_h, dst_h, n)
            if cc is not None:
                colors = cc[0]
            else:
                parent = np.arange(n)

                def find(x):
                    while parent[x] != x:
                        parent[x] = parent[parent[x]]
                        x = parent[x]
                    return x

                for a, b in zip(src_h, dst_h):
                    ra, rb = find(int(a)), find(int(b))
                    if ra != rb:
                        parent[max(ra, rb)] = min(ra, rb)
                colors = np.asarray([find(i) for i in range(n)])
            guard += 1

        labels, dendrogram, heights = _host_union_find_labels(
            src_h, dst_h, w_h, n, n_clusters)
        return SingleLinkageOutput(labels=labels, dendrogram=dendrogram,
                                   distances=heights,
                                   n_clusters=int(labels.max()) + 1)
