"""Balanced hierarchical k-means — the ANN coarse quantizer.

Reference: raft/cluster/kmeans_balanced.cuh:75 ``fit``, :133 ``predict``, :198
``fit_predict``; helpers ``build_clusters`` :257 and
``calc_centers_and_sizes`` :336; impl cluster/detail/kmeans_balanced.cuh
(mesocluster split/balance loop, minibatched predict, L2Expanded or
InnerProduct metric only).

The reference's goal is not the k-means optimum but *roughly balanced* cluster
sizes, because the clusters become IVF inverted lists whose occupancy drives
search cost.  Its mechanism is an iterative loop with a center-adjustment step
that re-seeds under-populated clusters from the data.  TPU design: one jitted
``lax.fori_loop`` — assignment via the fused-L2-1NN scan (MXU), centroid
update via ``segment_sum``, then a balancing step that re-seeds every cluster
whose size falls below ``avg/ratio`` to a data point drawn with probability
proportional to its distance-to-centroid (a k-means++-style re-seed, playing
the role of the reference's ``adjust_centers``).  All shapes static; no host
round-trips inside the loop.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster.kmeans_types import KMeansBalancedParams
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.integrity import boundary as _boundary
from raft_tpu import observability as obs
from raft_tpu.distance.fused_l2_nn import fused_l2_nn
from raft_tpu.distance.types import DistanceType
from raft_tpu.core.outputs import raw
from raft_tpu.utils.precision import get_matmul_precision

# Clusters smaller than avg_size / _BALANCE_RATIO get re-seeded each round
# (reference: detail/kmeans_balanced.cuh adjust_centers threshold).
_BALANCE_RATIO = 8.0


def _assign(X: jax.Array, centroids: jax.Array, metric: int
            ) -> Tuple[jax.Array, jax.Array]:
    """(labels, distances).  L2 path is the fused scan; InnerProduct is a
    plain argmax over the gram matrix (reference predicts in minibatches)."""
    if metric == DistanceType.InnerProduct:
        ip = jax.lax.dot_general(
            X.astype(jnp.float32), centroids.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            precision=get_matmul_precision(),
            preferred_element_type=jnp.float32)
        return jnp.argmax(ip, axis=1).astype(jnp.int32), -jnp.max(ip, axis=1)
    return tuple(reversed(raw(fused_l2_nn)(X, centroids)))


def calc_centers_and_sizes(
    X: jax.Array,
    labels: jax.Array,
    n_clusters: int,
    *,
    old_centroids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-cluster mean + population (reference: kmeans_balanced.cuh:336)."""
    acc = jnp.promote_types(X.dtype, jnp.float32)
    sums = jax.ops.segment_sum(X.astype(acc), labels,
                               num_segments=n_clusters)
    sizes = jax.ops.segment_sum(jnp.ones(X.shape[0], acc), labels,
                                num_segments=n_clusters)
    centers = sums / jnp.maximum(sizes, 1.0)[:, None]
    if old_centroids is not None:
        centers = jnp.where((sizes > 0)[:, None], centers,
                            old_centroids.astype(acc))
    return centers.astype(jnp.float32), sizes.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n_clusters", "n_iters",
                                             "metric", "use_fused",
                                             "fused_interpret"))
def _balanced_loop(X, centroids0, key, n_clusters, n_iters, metric,
                   use_fused=0, fused_interpret=False):
    """``use_fused`` (TPU, L2): assignment + per-cluster sums + per-row
    min distance come from ONE Pallas pass per iteration
    (:mod:`raft_tpu.ops.kmeans_update_pallas`) — this loop is the inner
    engine of every IVF coarse build, where the XLA formulation was
    ~2 s/iteration at 1M x 4000 lists."""
    xf = X.astype(jnp.float32)
    n = xf.shape[0]
    if use_fused:
        from raft_tpu.ops.kmeans_update_pallas import fused_assign_update

        ones = jnp.ones((n,), jnp.float32)
        x_sq = jnp.sum(xf * xf, axis=1)     # loop-invariant

    def body(it, carry):
        centroids, key = carry
        if use_fused:
            sums, counts, dmin = fused_assign_update(
                xf, ones, centroids, tile=use_fused,
                interpret=fused_interpret)
            centers = (sums / jnp.maximum(counts, 1.0)[:, None])
            centers = jnp.where((counts > 0)[:, None], centers,
                                centroids.astype(jnp.float32))
            sizes = counts.astype(jnp.int32)
            dists = jnp.maximum(x_sq + dmin, 0.0)
        else:
            labels, dists = _assign(xf, centroids, metric)
            centers, sizes = calc_centers_and_sizes(
                xf, labels, n_clusters, old_centroids=centroids)
        # balancing: re-seed under-populated clusters from far-away points
        # (the adjust_centers analogue, detail/kmeans_balanced.cuh)
        avg = jnp.float32(n) / n_clusters
        small = sizes.astype(jnp.float32) < (avg / _BALANCE_RATIO)
        key, kc = jax.random.split(key)
        # one candidate point per cluster, drawn ∝ assignment distance via
        # inverse-CDF (cumsum + searchsorted).  NOT the gumbel-matrix trick:
        # an (n_clusters, n) gumbel draw per iteration is O(K·n) randomness —
        # at IVF scale (K~4k, n~500k) that is gigabytes per Lloyd step and
        # dominated the whole IVF-PQ build.
        w = jnp.maximum(dists - jnp.min(dists), 0.0) + 1e-6
        cdf = jnp.cumsum(w)
        u = jax.random.uniform(kc, (n_clusters,)) * cdf[-1]
        cand = jnp.clip(jnp.searchsorted(cdf, u), 0, n - 1)
        centers = jnp.where(small[:, None], xf[cand], centers)
        if metric == DistanceType.InnerProduct:
            # spherical k-means: keep centroids on the unit sphere
            norms = jnp.linalg.norm(centers, axis=1, keepdims=True)
            centers = centers / jnp.maximum(norms, 1e-12)
        return centers, key

    centroids, _ = jax.lax.fori_loop(0, n_iters, body, (centroids0, key))
    labels, _ = _assign(xf, centroids, metric)
    return centroids, labels


# Above this cluster count fit() switches to the two-level mesocluster
# build (reference: detail/kmeans_balanced.cuh build_hierarchical — the
# mesocluster split/balance loop that makes n_lists=16384+ tractable).
_MESO_THRESHOLD = 8192


@functools.partial(jax.jit, static_argnames=("n_meso", "per"))
def _meso_partition_sample(meso_labels, key, n_meso, per):
    """Fixed-size member samples per mesocluster WITHOUT an
    (n_meso, n) membership matrix: one argsort groups rows into
    contiguous label segments; each mesocluster takes ``per`` rows from
    its segment, cycling when it has fewer members.  Returns
    (n_meso, per) row indices."""
    n = meso_labels.shape[0]
    order = jnp.argsort(meso_labels)
    sorted_lab = meso_labels[order]
    starts = jnp.searchsorted(sorted_lab, jnp.arange(n_meso))
    ends = jnp.searchsorted(sorted_lab, jnp.arange(n_meso),
                            side="right")
    counts = jnp.maximum(ends - starts, 1)
    # random offsets decorrelate which members are sampled run-to-run
    off = jax.random.randint(key, (n_meso,), 0, n)
    j = (jnp.arange(per)[None, :] + off[:, None]) % counts[:, None]
    return order[jnp.clip(starts[:, None] + j, 0, n - 1)]


def _fused_ok(n, dim, k, metric) -> int:
    """Host-side choice: the data tile for the fused Pallas
    assignment+update kernel (TPU, L2, shapes it handles), 0 = use the
    XLA path."""
    from raft_tpu.ops import kmeans_update_pallas as kup

    if metric != DistanceType.L2Expanded:
        return 0
    return kup.fused_tile(n, dim, k)


def _fit_hierarchical(xf, n_clusters, key, n_iters, metric):
    """Two-level balanced build (the build_hierarchical analogue).

    1. ~sqrt(K) mesoclusters via the standard balanced loop (full data
       — the (n, n_meso) assignment is cheap);
    2. per-mesocluster fine clusters trained on fixed-size member
       samples, ``vmap``-ed across mesoclusters (static shapes: ragged
       member lists are sampled-with-cycling, not materialized);
    3. a short full-K balanced refinement from the stacked fine
       centers (the reference's fine-tuning passes), which also
       re-seeds any cluster left under-populated by the hierarchy.

    Per-iteration assignment cost falls from O(n*K) to
    O(n*sqrt(K)) + O(per*K) — the difference between minutes and
    seconds at K=16384, n=1M.
    """
    n, dim = xf.shape
    n_meso = max(2, min(int(round(float(np.sqrt(n_clusters)))),
                        n_clusters // 2))
    k_base = n_clusters // n_meso
    rem = n_clusters % n_meso
    k_max = k_base + (1 if rem else 0)

    k1, k2, k3, k4 = jax.random.split(key, 4)
    stride = max(n // n_meso, 1)
    c0 = xf[::stride][:n_meso]
    if c0.shape[0] < n_meso:
        c0 = jnp.pad(c0, ((0, n_meso - c0.shape[0]), (0, 0)), mode="edge")
    meso_centers, meso_labels = _balanced_loop(
        xf, c0, k1, n_meso, n_iters, metric,
        use_fused=_fused_ok(n, dim, n_meso, metric))

    per = min(n, max(2048, 32 * k_max))
    idx = _meso_partition_sample(meso_labels, k2, n_meso, per)
    subsets = xf[idx]                                # (n_meso, per, dim)

    sub_stride = max(per // k_max, 1)

    def one(sub, k):
        c0f = sub[::sub_stride][:k_max]
        c0f = jnp.pad(c0f, ((0, k_max - c0f.shape[0]), (0, 0)),
                      mode="edge")
        centers, _ = _balanced_loop(sub, c0f, k, k_max, n_iters, metric)
        return centers

    fine = jax.vmap(one)(subsets, jax.random.split(k3, n_meso))

    # keep exactly n_clusters centers: meso m contributes
    # k_base (+1 for the first `rem`) of its k_max trained centers
    quota = k_base + (jnp.arange(n_meso) < rem).astype(jnp.int32)
    valid = jnp.arange(k_max)[None, :] < quota[:, None]
    flat = fine.reshape(-1, dim)
    order = jnp.argsort(~valid.ravel(), stable=True)
    centers0 = flat[order[:n_clusters]]

    refine_iters = max(2, n_iters // 5)
    centers, _ = _balanced_loop(
        xf, centers0, k4, n_clusters, refine_iters, metric,
        use_fused=_fused_ok(n, dim, n_clusters, metric))
    return centers


def fit(
    res,
    params: KMeansBalancedParams,
    X,
    n_clusters: int,
    *,
    key: Optional[jax.Array] = None,
    hierarchical: Optional[bool] = None,
) -> jax.Array:
    """Train balanced centroids; returns (n_clusters, dim) float32.

    Reference: cluster/kmeans_balanced.cuh:75.  ``hierarchical`` forces
    (True) or disables (False) the two-level mesocluster build; None
    auto-selects it for n_clusters >= _MESO_THRESHOLD (the reference's
    build_hierarchical path, detail/kmeans_balanced.cuh).
    """
    with named_range("kmeans_balanced::fit"), \
            obs.stage("kmeans_balanced.fit") as st:
        X = ensure_array(X, "X")
        X, _ = _boundary.check_matrix(X, "X", site="kmeans_balanced.fit",
                                      allow_empty=False)
        n, _ = X.shape
        expects(n_clusters <= n, "kmeans_balanced.fit: n_clusters > n_samples")
        expects(params.metric in (DistanceType.L2Expanded,
                                  DistanceType.InnerProduct),
                "kmeans_balanced supports L2Expanded / InnerProduct only "
                "(as the reference does)")
        if key is None:
            key = res.next_key()
        if hierarchical is None:
            hierarchical = n_clusters >= _MESO_THRESHOLD
        if hierarchical and n_clusters >= 4:
            centroids = _fit_hierarchical(X.astype(jnp.float32), n_clusters,
                                          key, params.n_iters, params.metric)
            st.fence(centroids)
            return centroids
        # evenly-strided init over the (caller-shuffled) trainset — the
        # reference seeds from strided trainset rows.
        stride = max(n // n_clusters, 1)
        c0 = X[::stride][:n_clusters].astype(jnp.float32)
        if c0.shape[0] < n_clusters:
            c0 = jnp.pad(c0, ((0, n_clusters - c0.shape[0]), (0, 0)),
                         mode="edge")
        if params.metric == DistanceType.InnerProduct:
            c0 = c0 / jnp.maximum(jnp.linalg.norm(c0, axis=1, keepdims=True),
                                  1e-12)
        centroids, _ = _balanced_loop(
            X, c0, key, n_clusters, params.n_iters, params.metric,
            use_fused=_fused_ok(n, X.shape[1], n_clusters, params.metric))
        st.fence(centroids)
        return centroids


def predict(res, params: KMeansBalancedParams, X, centroids) -> jax.Array:
    """Nearest-centroid labels (reference: kmeans_balanced.cuh:133)."""
    X = ensure_array(X, "X")
    X, _ = _boundary.check_matrix(X, "X", site="kmeans_balanced.predict")
    labels, _ = _assign(X.astype(jnp.float32),
                        ensure_array(centroids, "centroids"), params.metric)
    return labels


def fit_predict(res, params: KMeansBalancedParams, X, n_clusters: int,
                *, key: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, jax.Array]:
    """Reference: cluster/kmeans_balanced.cuh:198."""
    centroids = fit(res, params, X, n_clusters, key=key)
    return centroids, predict(res, params, X, centroids)


def build_clusters(
    res,
    params: KMeansBalancedParams,
    X,
    n_clusters: int,
    *,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Train + assign + sizes in one call (reference: kmeans_balanced.cuh:257
    ``helpers::build_clusters`` — the IVF-PQ codebook trainer entry).
    Returns (centroids, labels, sizes)."""
    centroids, labels = fit_predict(res, params, X, n_clusters, key=key)
    _, sizes = calc_centers_and_sizes(ensure_array(X, "X").astype(jnp.float32),
                                      labels, n_clusters)
    return centroids, labels, sizes
