"""k-means parameter structs.

Reference: raft/cluster/kmeans_types.hpp (``KMeansParams``) and
raft/cluster/kmeans_balanced_types.hpp (``kmeans_balanced_params``).
Plain dataclasses, mirroring the reference's POD param-struct idiom
(SURVEY.md §5 config system level 2).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from raft_tpu.distance.types import DistanceType


class InitMethod:
    """Reference: kmeans_types.hpp ``InitMethod`` enum."""

    KMeansPlusPlus = 0
    Random = 1
    Array = 2


@dataclasses.dataclass
class KMeansParams:
    """Reference: cluster/kmeans_types.hpp ``KMeansParams``.

    Attributes mirror the reference fields; ``batch_samples``/``batch_centroids``
    bound the per-step working set exactly as the reference's memory-constrained
    batching does.
    """

    n_clusters: int = 8
    init: int = InitMethod.KMeansPlusPlus
    max_iter: int = 300
    tol: float = 1e-4
    verbosity: int = 0
    seed: int = 0
    metric: int = DistanceType.L2Expanded
    n_init: int = 1
    oversampling_factor: float = 2.0
    batch_samples: int = 1 << 15
    batch_centroids: int = 0  # 0 == use all
    inertia_check: bool = False


@dataclasses.dataclass
class KMeansBalancedParams:
    """Reference: cluster/kmeans_balanced_types.hpp ``kmeans_balanced_params``.

    ``metric`` must be L2Expanded or InnerProduct (the reference supports only
    these for the balanced variant — detail/kmeans_balanced.cuh).
    """

    n_iters: int = 20
    metric: int = DistanceType.L2Expanded
