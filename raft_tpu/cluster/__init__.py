"""Clustering algorithms.

Reference: cpp/include/raft/cluster/ (SURVEY.md §2.7) — Lloyd k-means with
k-means++ init (cluster/kmeans.cuh), balanced hierarchical k-means used as the
ANN coarse quantizer (cluster/kmeans_balanced.cuh), and single-linkage
agglomerative clustering (cluster/single_linkage.cuh).
"""

from raft_tpu.cluster import kmeans  # noqa: F401
from raft_tpu.cluster import kmeans_balanced  # noqa: F401
from raft_tpu.cluster.kmeans_types import (  # noqa: F401
    InitMethod,
    KMeansParams,
    KMeansBalancedParams,
)
