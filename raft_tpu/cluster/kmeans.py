"""k-means clustering (Lloyd's algorithm, k-means++ init).

Reference: raft/cluster/kmeans.cuh:87 ``fit``, :151 ``predict``, :214
``fit_predict``, :243 ``transform``, plus the publicly exposed building blocks
``sample_centroids`` :339, ``update_centroids`` :392,
``min_cluster_and_distance`` :495, ``shuffle_and_gather`` :530; internals in
cluster/detail/kmeans.cuh (``initRandom`` :62, ``kmeansPlusPlus`` :88,
``update_centroids`` :285, ``kmeans_fit_main`` :359).

TPU design notes:

- The Lloyd loop is a single ``lax.while_loop`` jitted end-to-end — assignment
  (fused L2 1-NN, the reference's hot ``minClusterAndDistanceCompute`` path),
  centroid update (``segment_sum``) and the convergence check all stay on
  device; no per-iteration host sync (the reference syncs each iter).
- k-means++ follows the reference's n_trials candidate scheme
  (detail/kmeans.cuh:88): each round draws ``n_trials`` candidates with
  probability proportional to the current min-distance-squared (Gumbel top-k
  trick) and keeps the candidate with the lowest resulting cost.
- Empty clusters keep their previous centroid (the reference's
  update_centroids divides by max(count, 1) and copies the old center back —
  detail/kmeans.cuh:285).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.integrity import boundary as _boundary
from raft_tpu import observability as obs
from raft_tpu.distance.fused_l2_nn import fused_l2_nn
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output, raw


# ---------------------------------------------------------------------------
# building blocks (public in the reference: kmeans.cuh:339-616)
# ---------------------------------------------------------------------------

def min_cluster_and_distance(
    X: jax.Array,
    centroids: jax.Array,
    *,
    metric: int = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """Per-sample (label, distance) to the nearest centroid.

    Reference: kmeans.cuh:495 ``min_cluster_and_distance`` (KeyValuePair out),
    backed by fusedL2NN for L2 (detail/kmeans.cuh:432).  Returns
    ``(labels int32 (n,), distances (n,))``; distances are squared-L2 for the
    L2 metrics, raw metric values otherwise.
    """
    if metric in (DistanceType.L2Expanded, DistanceType.L2Unexpanded):
        d, i = raw(fused_l2_nn)(X, centroids)
        return i, d
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        d, i = raw(fused_l2_nn)(X, centroids, sqrt=True)
        return i, d
    dmat = raw(pairwise_distance)(X, centroids, metric)
    return jnp.argmin(dmat, axis=1).astype(jnp.int32), jnp.min(dmat, axis=1)


def update_centroids(
    X: jax.Array,
    labels: jax.Array,
    n_clusters: int,
    *,
    sample_weight: Optional[jax.Array] = None,
    old_centroids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Weighted per-cluster mean; empty clusters keep ``old_centroids``.

    Reference: kmeans.cuh:392 / detail/kmeans.cuh:285 (reduce_rows_by_key +
    weighted mean + empty-cluster copy-back).  Returns (centroids, counts).
    """
    w = (jnp.ones(X.shape[0], X.dtype) if sample_weight is None
         else sample_weight.astype(X.dtype))
    acc = jnp.promote_types(X.dtype, jnp.float32)
    sums = jax.ops.segment_sum((X.astype(acc) * w[:, None].astype(acc)),
                               labels, num_segments=n_clusters)
    counts = jax.ops.segment_sum(w.astype(acc), labels,
                                 num_segments=n_clusters)
    means = sums / jnp.maximum(counts, 1.0)[:, None]
    if old_centroids is not None:
        means = jnp.where((counts > 0)[:, None], means,
                          old_centroids.astype(acc))
    return means.astype(X.dtype), counts


def sample_centroids(res, X: jax.Array, n_to_sample: int,
                     *, key: Optional[jax.Array] = None) -> jax.Array:
    """Uniformly sample rows as centroids (reference: kmeans.cuh:339)."""
    if key is None:
        key = res.next_key()
    n = X.shape[0]
    expects(n_to_sample <= n, "sample_centroids: more samples than rows")
    idx = jax.random.choice(key, n, (n_to_sample,), replace=False)
    return X[idx]


def shuffle_and_gather(res, X: jax.Array, n_to_gather: int,
                       *, key: Optional[jax.Array] = None) -> jax.Array:
    """Random subset of rows via permutation (reference: kmeans.cuh:530)."""
    if key is None:
        key = res.next_key()
    perm = jax.random.permutation(key, X.shape[0])
    return X[perm[:n_to_gather]]


def cluster_cost(X: jax.Array, centroids: jax.Array,
                 *, metric: int = DistanceType.L2Expanded) -> jax.Array:
    """Total cost (inertia) of an assignment.

    Reference: raft_runtime/cluster/kmeans.hpp:79 ``cluster_cost``.
    """
    _, d = min_cluster_and_distance(X, centroids, metric=metric)
    return jnp.sum(d)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_plus_plus(
    res,
    X: jax.Array,
    n_clusters: int,
    *,
    key: Optional[jax.Array] = None,
    n_trials: int = 0,
) -> jax.Array:
    """k-means++ with n_trials candidate sampling per round.

    Reference: detail/kmeans.cuh:88 ``kmeansPlusPlus`` (candidate sampling,
    cost evaluated via fusedL2NN, best candidate kept);
    raft_runtime/cluster/kmeans.hpp:69 ``init_plus_plus``.
    """
    X = ensure_array(X, "X")
    n, dim = X.shape
    expects(n_clusters <= n, "init_plus_plus: n_clusters > n_samples")
    if key is None:
        key = res.next_key()
    if n_trials <= 0:
        n_trials = 2 + int(jnp.ceil(jnp.log(jnp.asarray(float(n_clusters)))))

    xf = X.astype(jnp.float32)
    x_sq = jnp.sum(xf * xf, axis=1)

    def sq_dists_to(points):  # (t, d) -> (t, n)
        ip = jax.lax.dot_general(points, xf, (((1,), (1,)), ((), ())),
                                 precision=get_matmul_precision(),
                                 preferred_element_type=jnp.float32)
        p_sq = jnp.sum(points * points, axis=1)
        return jnp.maximum(p_sq[:, None] + x_sq[None, :] - 2.0 * ip, 0.0)

    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centroids0 = jnp.zeros((n_clusters, dim), jnp.float32)
    centroids0 = centroids0.at[0].set(xf[first])
    min_d0 = sq_dists_to(xf[first][None, :])[0]

    def round_body(i, carry):
        centroids, min_d, key = carry
        key, kc = jax.random.split(key)
        # Gumbel top-n_trials == sampling n_trials candidates w/o replacement
        # with prob ∝ min_d (the D^2 weighting of k-means++).
        logits = jnp.where(min_d > 0, jnp.log(jnp.maximum(min_d, 1e-30)),
                           -jnp.inf)
        g = jax.random.gumbel(kc, (n,))
        _, cand = jax.lax.top_k(logits + g, n_trials)
        cand_d = sq_dists_to(xf[cand])              # (n_trials, n)
        new_min = jnp.minimum(cand_d, min_d[None, :])
        costs = jnp.sum(new_min, axis=1)
        best = jnp.argmin(costs)
        centroids = centroids.at[i].set(xf[cand[best]])
        return centroids, new_min[best], key

    centroids, _, _ = jax.lax.fori_loop(
        1, n_clusters, round_body, (centroids0, min_d0, key))
    return centroids.astype(X.dtype)


def init_random(res, X: jax.Array, n_clusters: int,
                *, key: Optional[jax.Array] = None) -> jax.Array:
    """Random-row init (reference: detail/kmeans.cuh:62 ``initRandom``)."""
    return sample_centroids(res, X, n_clusters, key=key)


# ---------------------------------------------------------------------------
# fit / predict
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_clusters", "max_iter",
                                             "metric", "use_fused"))
def _lloyd(X, centroids0, sample_weight, tol, n_clusters, max_iter, metric,
           use_fused=0):
    """Jitted Lloyd loop (reference: detail/kmeans.cuh:359 kmeans_fit_main).

    Converges on centroid shift: sum ||c_new - c_old||^2 < tol (the reference
    checks sqrdNorm of the centroid delta against tol each iteration).

    ``use_fused`` (TPU, L2 metrics): one Pallas pass per iteration fuses
    assignment and the weighted per-cluster sums — labels and distances
    never leave VMEM (:mod:`raft_tpu.ops.kmeans_update_pallas`; the
    round-3 loop was segment-sum/epilogue-bound, PERFORMANCE.md).
    """

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift >= tol)

    def body(carry):
        centroids, it, _ = carry
        if use_fused:
            from raft_tpu.ops.kmeans_update_pallas import fused_assign_update

            sums, counts, _ = fused_assign_update(X, sample_weight,
                                                  centroids,
                                                  tile=use_fused)
            means = sums / jnp.maximum(counts, 1.0)[:, None]
            new_c = jnp.where((counts > 0)[:, None], means,
                              centroids.astype(jnp.float32)).astype(X.dtype)
        else:
            labels, _ = min_cluster_and_distance(X, centroids, metric=metric)
            new_c, _ = update_centroids(X, labels, n_clusters,
                                        sample_weight=sample_weight,
                                        old_centroids=centroids)
        shift = jnp.sum((new_c.astype(jnp.float32)
                         - centroids.astype(jnp.float32)) ** 2)
        return new_c, it + 1, shift

    init = (centroids0, jnp.int32(0), jnp.float32(jnp.inf))
    centroids, n_iter, _ = jax.lax.while_loop(cond, body, init)
    # final assignment cost for the returned centroids
    labels, dists = min_cluster_and_distance(X, centroids, metric=metric)
    inertia = jnp.sum(dists * sample_weight)
    return centroids, inertia, n_iter, labels


@auto_convert_output
def fit(
    res,
    params: KMeansParams,
    X,
    sample_weight: Optional[jax.Array] = None,
    centroids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fit k-means; returns ``(centroids, inertia, n_iter)``.

    Reference: cluster/kmeans.cuh:87 ``kmeans::fit`` (centroids may carry the
    init when ``params.init == InitMethod.Array``).  ``n_init`` restarts keep
    the lowest-inertia run, as in the reference/sklearn convention.
    """
    with named_range("kmeans::fit"):
        X = ensure_array(X, "X")
        expects(X.ndim == 2, "kmeans.fit: 2-D X required")
        X, _ = _boundary.check_matrix(X, "X", site="kmeans.fit",
                                      allow_empty=False)
        expects(params.n_clusters <= X.shape[0],
                "kmeans.fit: n_clusters > n_samples")
        w = (jnp.ones(X.shape[0], jnp.float32) if sample_weight is None
             else jnp.asarray(sample_weight, jnp.float32))

        from raft_tpu.ops import kmeans_update_pallas as kup

        l2_metrics = (DistanceType.L2Expanded, DistanceType.L2Unexpanded,
                      DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded)
        # sqrt variants share the fused path: sqrt is monotone, so the
        # in-kernel argmin is identical; inertia is computed after the
        # loop with the caller's metric either way.  use_fused carries
        # the chosen data tile (0 = XLA path).
        use_fused = (kup.fused_tile(X.shape[0], X.shape[1],
                                    params.n_clusters)
                     if params.metric in l2_metrics else 0)

        best = None
        # Array init is deterministic — restarts would be bit-identical.
        n_init = 1 if params.init == InitMethod.Array else max(1, params.n_init)
        # the Lloyd loop is one fused while_loop, so per-iteration timing is
        # not observable; the stage records the whole fit and the iteration
        # count comes from the loop carry afterwards
        with obs.stage("kmeans.fit") as st:
            for restart in range(n_init):
                key = jax.random.fold_in(jax.random.key(params.seed), restart)
                if params.init == InitMethod.Array:
                    expects(centroids is not None,
                            "InitMethod.Array requires centroids")
                    c0 = jnp.asarray(centroids, X.dtype)
                elif params.init == InitMethod.Random:
                    c0 = init_random(res, X, params.n_clusters, key=key)
                else:
                    c0 = init_plus_plus(res, X, params.n_clusters, key=key)
                c, inertia, n_iter, _ = _lloyd(
                    X, c0, w, jnp.float32(params.tol), params.n_clusters,
                    params.max_iter, params.metric, use_fused=use_fused)
                if best is None or float(inertia) < float(best[1]):
                    best = (c, inertia, n_iter)
            st.fence(best[0])
        if obs.enabled():
            obs.registry().counter("kmeans.iterations").inc(int(best[2]))
            obs.registry().counter("kmeans.restarts").inc(n_init)
        return best


@auto_convert_output
def predict(
    res,
    params: KMeansParams,
    X,
    centroids,
    *,
    sample_weight: Optional[jax.Array] = None,
    normalize_weight: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Assign samples to centroids; returns ``(labels, inertia)``.

    Reference: cluster/kmeans.cuh:151.
    """
    X = ensure_array(X, "X")
    X, _ = _boundary.check_matrix(X, "X", site="kmeans.predict")
    centroids = ensure_array(centroids, "centroids")
    labels, dists = min_cluster_and_distance(X, centroids,
                                             metric=params.metric)
    w = (jnp.ones(X.shape[0], jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    return labels, jnp.sum(dists * w)


@auto_convert_output
def fit_predict(res, params: KMeansParams, X,
                sample_weight: Optional[jax.Array] = None,
                centroids: Optional[jax.Array] = None):
    """Reference: cluster/kmeans.cuh:214.  Returns (labels, centroids, inertia, n_iter)."""
    centroids, inertia, n_iter = raw(fit)(res, params, X, sample_weight,
                                          centroids)
    labels, inertia = raw(predict)(res, params, X, centroids,
                                   sample_weight=sample_weight)
    return labels, centroids, inertia, n_iter


@auto_convert_output
def transform(res, params: KMeansParams, X, centroids) -> jax.Array:
    """Distance from every sample to every centroid (reference: kmeans.cuh:243)."""
    X, _ = _boundary.check_matrix(ensure_array(X, "X"), "X",
                                  site="kmeans.transform")
    return raw(pairwise_distance)(X, ensure_array(centroids, "centroids"),
                                  params.metric)


def find_k(
    res,
    X,
    *,
    k_max: int = 20,
    k_min: int = 2,
    max_iter: int = 100,
    tol: float = 1e-3,
) -> Tuple[int, jax.Array, jax.Array]:
    """Auto-find k by binary search on inertia elbow.

    Reference: detail/kmeans_auto_find_k.cuh (``find_k``) — evaluates fit
    quality across k via a bisection on the cost curve.  Returns
    ``(best_k, centroids, inertia)``.
    """
    X = ensure_array(X, "X")

    def fit_k(k):
        p = KMeansParams(n_clusters=k, max_iter=max_iter, tol=tol)
        c, inertia, _ = raw(fit)(res, p, X)
        return c, float(inertia)

    # Coarse scan then local refine — the reference bisects the elbow of the
    # cost-vs-k curve; a small scan is equivalent at these k ranges.
    ks, results = [], {}
    k = k_min
    while k <= k_max:
        ks.append(k)
        results[k] = fit_k(k)
        k = max(k + 1, int(k * 1.5))
    # pick the elbow: largest second difference of cost
    if len(ks) >= 3:
        costs = [results[k][1] for k in ks]
        curv = [costs[i - 1] - 2 * costs[i] + costs[i + 1]
                for i in range(1, len(ks) - 1)]
        best_k = ks[1 + int(jnp.argmax(jnp.asarray(curv)))]
    else:
        best_k = min(ks, key=lambda k: results[k][1])
    c, inertia = results[best_k]
    return best_k, c, jnp.asarray(inertia)
