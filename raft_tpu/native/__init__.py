"""Native (C++) host-side runtime components, loaded via ctypes.

The analogue of the reference's compiled host layer: the pieces that are
inherently sequential host work (union-find dendrogram labeling,
agglomerative/detail/agglomerative.cuh's ``build_dendrogram_host``) run as
C++ with a plain C ABI.  The shared library is compiled on first use with
the system toolchain (g++); every entry point has a pure-Python fallback so
the package works without a compiler.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = pathlib.Path(__file__).parent
_SO = _HERE / "libagglomerative.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    src = _HERE / "agglomerative.cpp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", str(src), "-o", str(_SO)],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RAFT_TPU_DISABLE_NATIVE"):
            return None
        if not _SO.exists() and not _compile():
            return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError:
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.raft_tpu_build_dendrogram.restype = ctypes.c_int64
        lib.raft_tpu_build_dendrogram.argtypes = [
            i32p, i32p, f32p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, i32p, i32p, f32p]
        lib.raft_tpu_connected_components.restype = ctypes.c_int64
        lib.raft_tpu_connected_components.argtypes = [
            i32p, i32p, ctypes.c_int64, ctypes.c_int64, i32p]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native library is loaded (or compilable)."""
    return _load() is not None


def _as_i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def build_dendrogram(src, dst, w, n: int, n_clusters: int
                     ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]]:
    """Native union-find dendrogram (reference:
    detail/agglomerative.cuh ``build_dendrogram_host``).  Returns
    (labels (n,), dendrogram (merges, 2), heights (merges,)) or None when
    the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    src = _as_i32(src)
    dst = _as_i32(dst)
    w = np.ascontiguousarray(w, dtype=np.float32)
    n_edges = src.shape[0]
    max_merges = max(n - n_clusters, 0)
    labels = np.empty(n, np.int32)
    dendro = np.empty(2 * max_merges, np.int32)
    heights = np.empty(max_merges, np.float32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    merges = lib.raft_tpu_build_dendrogram(
        src.ctypes.data_as(i32p), dst.ctypes.data_as(i32p),
        w.ctypes.data_as(f32p), n_edges, n, n_clusters,
        labels.ctypes.data_as(i32p), dendro.ctypes.data_as(i32p),
        heights.ctypes.data_as(f32p))
    return (labels, dendro[:2 * merges].reshape(-1, 2),
            heights[:merges])


def connected_components(src, dst, n: int
                         ) -> Optional[Tuple[np.ndarray, int]]:
    """Native connected components over an edge list; returns
    (labels (n,), n_components) or None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    src = _as_i32(src)
    dst = _as_i32(dst)
    labels = np.empty(n, np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    n_comp = lib.raft_tpu_connected_components(
        src.ctypes.data_as(i32p), dst.ctypes.data_as(i32p),
        src.shape[0], n, labels.ctypes.data_as(i32p))
    return labels, int(n_comp)
