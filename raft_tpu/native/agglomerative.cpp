// Host-side agglomerative primitives (C ABI, loaded via ctypes).
//
// The reference keeps exactly this work on the host in C++ as well:
// cpp/include/raft/cluster/detail/agglomerative.cuh —
// build_dendrogram_host (union-find over weight-sorted MST edges) and the
// flattened-cluster extraction.  It is inherently sequential (inverse-
// Ackermann union-find), so the TPU plays no part; a native implementation
// removes the Python interpreter from the only host-side hot loop in the
// library (~30x over the numpy/Python fallback at 1M edges).
//
// Build: g++ -O3 -shared -fPIC agglomerative.cpp -o libagglomerative.so
// (driven by raft_tpu/native/__init__.py on first import).

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <vector>

namespace {

struct UnionFind {
  std::vector<int64_t> parent;
  explicit UnionFind(int64_t n) : parent(n) {
    std::iota(parent.begin(), parent.end(), int64_t{0});
  }
  int64_t find(int64_t x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];  // path halving
      x = parent[x];
    }
    return x;
  }
  // returns false if already joined
  bool unite(int64_t a, int64_t b) {
    a = find(a);
    b = find(b);
    if (a == b) return false;
    if (a < b) parent[b] = a; else parent[a] = b;  // min-root convention
    return true;
  }
};

void compact_labels(UnionFind& uf, int64_t n, int32_t* labels_out) {
  // map roots -> dense 0..k-1 ids, first-seen order by node id (matches
  // np.unique(..., return_inverse=True) on sorted roots because the root
  // is always the minimum node of its component)
  std::vector<int32_t> root_label(n, -1);
  int32_t next = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t r = uf.find(i);
    if (root_label[r] < 0) root_label[r] = next++;
    labels_out[i] = root_label[r];
  }
}

}  // namespace

extern "C" {

// Union weight-sorted edges until n_clusters components remain.
// Outputs: labels (n), dendrogram (2 * max_merges), heights (max_merges)
// where max_merges = n - n_clusters.  Returns the number of merges done.
int64_t raft_tpu_build_dendrogram(const int32_t* src, const int32_t* dst,
                                  const float* w, int64_t n_edges,
                                  int64_t n, int64_t n_clusters,
                                  int32_t* labels_out,
                                  int32_t* dendrogram_out,
                                  float* heights_out) {
  std::vector<int64_t> order(n_edges);
  std::iota(order.begin(), order.end(), int64_t{0});
  std::stable_sort(order.begin(), order.end(),
                   [w](int64_t a, int64_t b) { return w[a] < w[b]; });

  UnionFind uf(n);
  const int64_t max_merges = n - n_clusters;
  int64_t merges = 0;
  for (int64_t e = 0; e < n_edges && merges < max_merges; ++e) {
    const int64_t i = order[e];
    if (src[i] < 0 || dst[i] < 0) continue;
    if (!uf.unite(src[i], dst[i])) continue;
    dendrogram_out[2 * merges] = src[i];
    dendrogram_out[2 * merges + 1] = dst[i];
    heights_out[merges] = w[i];
    ++merges;
  }
  compact_labels(uf, n, labels_out);
  return merges;
}

// Connected-component labels over an edge list (the fix-up loop's host
// union-find).  Returns the number of components.
int64_t raft_tpu_connected_components(const int32_t* src, const int32_t* dst,
                                      int64_t n_edges, int64_t n,
                                      int32_t* labels_out) {
  UnionFind uf(n);
  int64_t components = n;
  for (int64_t e = 0; e < n_edges; ++e) {
    if (src[e] < 0 || dst[e] < 0) continue;
    if (uf.unite(src[e], dst[e])) --components;
  }
  compact_labels(uf, n, labels_out);
  return components;
}

}  // extern "C"
