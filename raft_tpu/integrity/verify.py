"""Tiered index invariant verification.

``verify(index, level=)`` walks every invariant an index type promises,
raising :class:`IntegrityError` naming the first violation and its
coordinates.  Levels nest (each includes the previous):

``structural``
    Shape/dtype consistency of every field and derived cache, list sizes
    vs. slot validity, ids in-range and unique, CAGRA adjacency validity
    — including that the PR 3 derived caches (packed code lanes, int8
    recon) decode back to the bf16 recon cache, the bug class the extend
    fast path can introduce.
``statistical``
    No non-finite centroids / codebooks / data, per-list norm sanity,
    rotation orthonormality.
``full``
    The recall canary (:func:`integrity.health_check`) — requires the
    index to carry canaries and a ``res`` to search with.

Verification is host-side by design (it pulls arrays with numpy): it is
an admin/offline operation, never on the serving path.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.integrity.errors import IntegrityError

_LEVELS = ("structural", "statistical", "full")


def _fail(invariant: str, msg: str, coord=None):
    if obs.enabled():
        obs.registry().counter("integrity.verify.failures").inc()
    raise IntegrityError(msg, invariant=invariant, coord=coord)


def _check(ok: bool, invariant: str, msg: str, coord=None) -> None:
    if not ok:
        _fail(invariant, msg, coord)


def _first_bad(mask: np.ndarray):
    """Coordinates of the first True entry of a violation mask."""
    idx = np.argwhere(mask)
    return tuple(int(v) for v in idx[0]) if idx.size else None


# ---------------------------------------------------------------------------
# shared IVF list-layout invariants
# ---------------------------------------------------------------------------

def _verify_ivf_lists(kind: str, list_indices: np.ndarray,
                      list_sizes: np.ndarray, capacity: int) -> None:
    n_lists = list_sizes.shape[0]
    _check(list_indices.shape == (n_lists, capacity),
           f"{kind}.list_indices.shape",
           f"list_indices shape {list_indices.shape} != "
           f"{(n_lists, capacity)}")
    _check(list_indices.dtype == np.int32, f"{kind}.list_indices.dtype",
           f"list_indices dtype {list_indices.dtype} != int32")
    _check(list_sizes.dtype == np.int32, f"{kind}.list_sizes.dtype",
           f"list_sizes dtype {list_sizes.dtype} != int32")

    bad = (list_sizes < 0) | (list_sizes > capacity)
    if bad.any():
        li = int(np.argmax(bad))
        _fail(f"{kind}.list_sizes.range",
              f"list {li} has size {int(list_sizes[li])} outside "
              f"[0, {capacity}]", coord=(li,))

    # slot occupancy must match the size vector exactly: each list's
    # first `size` slots hold a live id (>= 0) or a tombstone (<= -2,
    # see neighbors/mutate), the padding holds -1.  A tombstone outside
    # the occupied prefix, or a -1 inside it, is corruption.
    slot = np.arange(capacity)[None, :]
    should_be_valid = slot < list_sizes[:, None]
    valid = list_indices >= 0
    tomb = list_indices <= -2
    mism = (valid | tomb) != should_be_valid
    if mism.any():
        li, sl = _first_bad(mism)
        state = ("valid id" if valid[li, sl]
                 else "tombstone" if tomb[li, sl]
                 else "empty slot (-1)")
        want = int(list_sizes[li])
        _fail(f"{kind}.list_sizes.slots",
              f"list {li} slot {sl} holds a {state} but list size is "
              f"{want} — sizes and slot occupancy disagree (stale size "
              f"after extend/delete?)", coord=(li, sl))

    # uniqueness is enforced among LIVE ids only: a tombstone sharing an
    # id with a live slot is the legitimate delete -> re-insert pattern
    # (the rebalancer's recluster step tombstones a row and re-extends it
    # under the same id), and stale tombstones carry no search-visible
    # state — they are garbage pending compaction, not invariants
    ids = list_indices[valid]
    if ids.size:
        uniq, counts = np.unique(ids, return_counts=True)
        if (counts > 1).any():
            dup = int(uniq[np.argmax(counts > 1)])
            li, sl = _first_bad(list_indices == dup)
            _fail(f"{kind}.ids.unique",
                  f"live source id {dup} appears {int(counts.max())} "
                  f"times (first at list {li} slot {sl})",
                  coord=(li, sl))


def _verify_ids_in_range(kind: str, list_indices: np.ndarray,
                         n_rows: int) -> None:
    """Default id-space convention: source ids are ``0..n_rows-1`` with
    ``n_rows = sum(list_sizes)`` (what ``build(add_data_on_build=True)``
    produces).  Indexes extended with a custom sparse id space pass their
    true universe size via ``verify(..., n_rows=)`` — in particular
    after delete + compact, which makes the live id space sparse while
    shrinking ``sum(list_sizes)``."""
    # decoded view: tombstones (<= -2, neighbors/mutate) map back to the
    # original source id so deleted rows stay range-checked too
    dec = np.where(list_indices <= -2,
                   -list_indices.astype(np.int64) - 2,
                   list_indices.astype(np.int64))
    occupied = (list_indices >= 0) | (list_indices <= -2)
    too_big = occupied & (dec >= n_rows)
    if too_big.any():
        li, sl = _first_bad(too_big)
        _fail(f"{kind}.ids.range",
              f"source id {int(dec[li, sl])} at list {li} slot "
              f"{sl} is >= the index's {n_rows} rows", coord=(li, sl))


def _verify_namespaces(kind: str, live_ids: np.ndarray,
                       namespaces) -> None:
    """Tenant-namespace invariants (round 20): the declared id ranges
    must be pairwise disjoint, and every live id must fall inside some
    declared tenant's range — otherwise a filtered search could leak a
    row to the wrong tenant (or to nobody).  ``namespaces`` is a
    :class:`raft_tpu.filters.TenantFilter`; violations raise the typed
    error naming the violating (tenant, id)."""
    spans = sorted((int(lo), int(hi), t)
                   for t, (lo, hi) in namespaces.ranges.items())
    for (lo, hi, t) in spans:
        if not (0 <= lo <= hi):
            _fail("namespace.range",
                  f"tenant {t!r} declares an invalid id range "
                  f"[{lo}, {hi})", coord=(t, lo))
    for (lo0, hi0, t0), (lo1, hi1, t1) in zip(spans, spans[1:]):
        if hi0 > lo1:
            _fail("namespace.disjoint",
                  f"tenant ranges overlap: {t0!r} [{lo0},{hi0}) and "
                  f"{t1!r} [{lo1},{hi1}) — an id in the overlap would "
                  f"serve two tenants", coord=(t0, t1))
    live = np.unique(live_ids[live_ids >= 0].astype(np.int64))
    if live.size == 0:
        return
    los = np.asarray([s[0] for s in spans], np.int64)
    his = np.asarray([s[1] for s in spans], np.int64)
    j = np.searchsorted(los, live, side="right") - 1
    inside = (j >= 0) & (live < his[np.clip(j, 0, len(his) - 1)])
    if not inside.all():
        i = int(live[int(np.argmin(inside))])
        _fail("namespace.coverage",
              f"{kind}: live id {i} falls outside every declared tenant "
              f"namespace — it is unreachable under tenant filtering",
              coord=(namespaces.owner_of(i), i))


def _verify_finite(kind: str, name: str, arr: np.ndarray) -> None:
    fin = np.isfinite(arr)
    if not fin.all():
        coord = _first_bad(~fin)
        _fail(f"{kind}.{name}.finite",
              f"{name} has a non-finite value at {coord}", coord=coord)


# ---------------------------------------------------------------------------
# per-index-type verifiers
# ---------------------------------------------------------------------------

def _verify_ivf_flat(index, level: str, n_rows=None) -> None:
    from raft_tpu.neighbors import ivf_flat  # noqa: F401 (type anchor)

    centers = np.asarray(index.centers)
    sizes = np.asarray(index.list_sizes)
    lidx = np.asarray(index.list_indices)
    kind = "ivf_flat"

    _check(index.list_data.ndim == 3 and
           index.list_data.shape[:2] == (index.n_lists, index.capacity),
           f"{kind}.list_data.shape",
           f"list_data shape {index.list_data.shape} inconsistent with "
           f"{index.n_lists} lists x capacity {index.capacity}")
    _check(centers.shape == (index.n_lists, index.dim),
           f"{kind}.centers.shape",
           f"centers shape {centers.shape} != "
           f"{(index.n_lists, index.dim)}")
    _verify_ivf_lists(kind, lidx, sizes, index.capacity)
    _verify_ids_in_range(kind, lidx,
                         int(sizes.sum()) if n_rows is None else n_rows)

    if index.list_data_sq is not None:
        _check(index.list_data_sq.shape == (index.n_lists, index.capacity),
               f"{kind}.list_data_sq.shape",
               f"list_data_sq shape {index.list_data_sq.shape} != "
               f"{(index.n_lists, index.capacity)}")
        want = np.asarray(jnp.sum(
            jnp.asarray(index.list_data).astype(jnp.float32) ** 2,
            axis=-1))
        got = np.asarray(index.list_data_sq)
        valid = lidx >= 0
        stale = valid & ~np.isclose(got, want, rtol=1e-4, atol=1e-3)
        if stale.any():
            coord = _first_bad(stale)
            _fail(f"{kind}.list_data_sq.stale",
                  f"cached norm at {coord} is {got[coord]:.6g}, "
                  f"recompute gives {want[coord]:.6g} — stale derived "
                  f"cache", coord=coord)

    if level in ("statistical", "full"):
        _verify_finite(kind, "centers", centers)
        data = np.asarray(index.list_data, np.float32)
        valid = lidx >= 0
        row_fin = np.isfinite(data).all(axis=-1)
        bad = valid & ~row_fin
        if bad.any():
            coord = _first_bad(bad)
            _fail(f"{kind}.list_data.finite",
                  f"stored vector at list {coord[0]} slot {coord[1]} has "
                  f"non-finite values", coord=coord)


def _verify_ivf_pq(index, level: str, n_rows=None) -> None:
    from raft_tpu.neighbors import ivf_pq

    kind = "ivf_pq"
    centers = np.asarray(index.centers)
    sizes = np.asarray(index.list_sizes)
    lidx = np.asarray(index.list_indices)
    L, cap = index.n_lists, index.capacity

    _check(index.rot_dim % index.pq_dim == 0, f"{kind}.rot_dim.divisible",
           f"rot_dim {index.rot_dim} not divisible by pq_dim "
           f"{index.pq_dim}")
    _check(index.pq_len == index.rot_dim // index.pq_dim,
           f"{kind}.codebooks.pq_len",
           f"codebook sub-dim {index.pq_len} != rot_dim/pq_dim "
           f"{index.rot_dim // index.pq_dim}")
    want_w = ivf_pq.packed_code_width(index.pq_dim, index.pq_bits)
    _check(index.code_width == want_w, f"{kind}.list_codes.width",
           f"packed code width {index.code_width} != "
           f"ceil(pq_dim*pq_bits/8) = {want_w}")
    _check(index.list_codes.dtype == jnp.uint8, f"{kind}.list_codes.dtype",
           f"list_codes dtype {index.list_codes.dtype} != uint8")
    book = (index.pq_dim
            if index.codebook_kind == ivf_pq.CodebookKind.PER_SUBSPACE
            else L)
    _check(index.codebooks.shape ==
           (book, index.pq_book_size, index.pq_len),
           f"{kind}.codebooks.shape",
           f"codebooks shape {index.codebooks.shape} != "
           f"{(book, index.pq_book_size, index.pq_len)}")
    _check(index.rotation.shape == (index.dim, index.rot_dim),
           f"{kind}.rotation.shape",
           f"rotation shape {index.rotation.shape} != "
           f"{(index.dim, index.rot_dim)}")
    _verify_ivf_lists(kind, lidx, sizes, cap)
    _verify_ids_in_range(kind, lidx,
                         int(sizes.sum()) if n_rows is None else n_rows)

    valid = lidx >= 0
    recon_ref = None     # lazily recomputed bf16 recon (codes are truth)

    def _recon_recompute():
        nonlocal recon_ref
        if recon_ref is None:
            recon_ref = np.asarray(ivf_pq._decode_lists(
                index.centers, index.codebooks, index.list_codes,
                index.codebook_kind, index.pq_dim, index.pq_bits),
                np.float32)
        return recon_ref

    if index.list_recon is not None:
        _check(index.list_recon.shape == (L, cap, index.rot_dim),
               f"{kind}.list_recon.shape",
               f"list_recon shape {index.list_recon.shape} != "
               f"{(L, cap, index.rot_dim)}")
        got = np.asarray(index.list_recon, np.float32)
        stale = valid[:, :, None] & (got != _recon_recompute())
        if stale.any():
            coord = _first_bad(stale)
            _fail(f"{kind}.list_recon.stale",
                  f"recon cache at list {coord[0]} slot {coord[1]} dim "
                  f"{coord[2]} does not decode from the stored codes — "
                  f"stale derived cache", coord=coord)
        if index.list_recon_sq is not None:
            got_sq = np.asarray(index.list_recon_sq)
            want_sq = (_recon_recompute().astype(np.float32) ** 2
                       ).sum(axis=-1)
            stale = valid & ~np.isclose(got_sq, want_sq, rtol=1e-3,
                                        atol=1e-3)
            if stale.any():
                coord = _first_bad(stale)
                _fail(f"{kind}.list_recon_sq.stale",
                      f"cached recon norm at {coord} is "
                      f"{got_sq[coord]:.6g}, recompute gives "
                      f"{want_sq[coord]:.6g}", coord=coord)

    if index.list_code_lanes is not None:
        from raft_tpu.ops import pq_code_scan_pallas as pcs
        want_lanes = np.asarray(pcs.pack_code_lanes(index.list_codes))
        got_lanes = np.asarray(index.list_code_lanes)
        _check(got_lanes.shape == want_lanes.shape,
               f"{kind}.list_code_lanes.shape",
               f"code-lane cache shape {got_lanes.shape} != "
               f"{want_lanes.shape}")
        stale = (got_lanes != want_lanes) & valid[:, None, :]
        if stale.any():
            coord = _first_bad(stale)
            _fail(f"{kind}.list_code_lanes.stale",
                  f"packed code lane at list {coord[0]} word {coord[1]} "
                  f"slot {coord[2]} does not repack from the stored "
                  f"codes", coord=coord)

    if index.list_recon_i8 is not None:
        rot_pad = -(-index.rot_dim // 128) * 128
        qi, scale, rsq8 = ivf_pq._quantize_recon(
            jnp.asarray(_recon_recompute(), jnp.bfloat16), rot_pad)
        got_i8 = np.asarray(index.list_recon_i8)
        _check(got_i8.shape == qi.shape, f"{kind}.list_recon_i8.shape",
               f"int8 recon shape {got_i8.shape} != {qi.shape}")
        stale = (got_i8 != np.asarray(qi)) & valid[:, :, None]
        if stale.any():
            coord = _first_bad(stale)
            _fail(f"{kind}.list_recon_i8.stale",
                  f"int8 recon at list {coord[0]} slot {coord[1]} lane "
                  f"{coord[2]} does not re-quantize from the stored "
                  f"codes — stale derived cache (extend without "
                  f"re-quantization?)", coord=coord)
        if index.list_recon_scale is not None:
            got_s = np.asarray(index.list_recon_scale)
            badl = ~np.isclose(got_s, np.asarray(scale), rtol=1e-5)
            if badl.any():
                li = int(np.argmax(badl))
                _fail(f"{kind}.list_recon_scale.stale",
                      f"int8 scale of list {li} is {got_s[li]:.6g}, "
                      f"recompute gives {float(scale[li]):.6g}",
                      coord=(li,))

    if level in ("statistical", "full"):
        _verify_finite(kind, "centers", centers)
        _verify_finite(kind, "codebooks", np.asarray(index.codebooks,
                                                     np.float32))
        _verify_finite(kind, "rotation", np.asarray(index.rotation))
        rot = np.asarray(index.rotation, np.float64)
        gram = rot.T @ rot
        if not np.allclose(gram, np.eye(rot.shape[1]), atol=1e-3):
            _fail(f"{kind}.rotation.orthonormal",
                  "rotation columns are not orthonormal "
                  f"(max |R^T R - I| = "
                  f"{np.abs(gram - np.eye(rot.shape[1])).max():.3g})")
        if index.list_recon_sq is not None:
            sq = np.asarray(index.list_recon_sq)
            bad = valid & (~np.isfinite(sq) | (sq < 0))
            if bad.any():
                coord = _first_bad(bad)
                _fail(f"{kind}.list_recon_sq.sane",
                      f"recon norm at {coord} is {sq[coord]!r} "
                      f"(negative or non-finite)", coord=coord)


def _verify_cagra(index, level: str) -> None:
    kind = "cagra"
    n = index.size
    graph = np.asarray(index.graph)

    _check(graph.ndim == 2 and graph.shape[0] == n, f"{kind}.graph.shape",
           f"graph shape {graph.shape} inconsistent with {n} dataset "
           f"rows")
    _check(graph.dtype == np.int32, f"{kind}.graph.dtype",
           f"graph dtype {graph.dtype} != int32")
    _check(1 <= index.graph_degree <= max(n - 1, 1),
           f"{kind}.graph.degree",
           f"graph degree {index.graph_degree} invalid for {n} nodes")

    oob = (graph < 0) | (graph >= n)
    if oob.any():
        coord = _first_bad(oob)
        _fail(f"{kind}.graph.range",
              f"edge {coord} points at node {int(graph[coord])}, outside "
              f"[0, {n})", coord=coord)
    self_loop = graph == np.arange(n, dtype=graph.dtype)[:, None]
    if self_loop.any():
        coord = _first_bad(self_loop)
        _fail(f"{kind}.graph.self_loop",
              f"node {coord[0]} lists itself as neighbor (edge slot "
              f"{coord[1]})", coord=coord)

    if level in ("statistical", "full"):
        data = np.asarray(index.dataset, np.float32)
        row_fin = np.isfinite(data).all(axis=-1)
        if not row_fin.all():
            r = int(np.argmin(row_fin))
            _fail(f"{kind}.dataset.finite",
                  f"dataset row {r} has non-finite values", coord=(r,))


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def verify(index, level: str = "structural", *, res=None,
           n_rows=None, namespaces=None) -> None:
    """Verify every invariant of ``index`` at the given level; raises
    :class:`IntegrityError` naming the first violation.  ``level="full"``
    additionally runs the recall canary and therefore requires ``res``
    and a canary-carrying index (see ``integrity.canary``).

    ``n_rows`` overrides the id-space bound for the source-id range
    check; the default assumes the build convention (ids are exactly
    ``0..sum(list_sizes)-1``).  Pass the true universe size for indexes
    extended with custom ids.

    ``namespaces`` (round 20): a :class:`raft_tpu.filters.TenantFilter`
    declaring the tenant id ranges the index serves under — checked for
    pairwise disjointness and full coverage of every live id (invariants
    ``namespace.disjoint`` / ``namespace.coverage``, coord = the
    violating (tenant, id))."""
    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq

    if level not in _LEVELS:
        raise ValueError(f"verify: unknown level {level!r}; expected one "
                         f"of {_LEVELS}")
    if obs.enabled():
        obs.registry().counter("integrity.verify.calls").inc()
    with obs.stage("verify"):
        if isinstance(index, ivf_flat.Index):
            _verify_ivf_flat(index, level, n_rows)
            if namespaces is not None:
                _verify_namespaces("ivf_flat",
                                   np.asarray(index.list_indices),
                                   namespaces)
        elif isinstance(index, ivf_pq.Index):
            _verify_ivf_pq(index, level, n_rows)
            if namespaces is not None:
                _verify_namespaces("ivf_pq",
                                   np.asarray(index.list_indices),
                                   namespaces)
        elif isinstance(index, cagra.Index):
            _verify_cagra(index, level)
            if namespaces is not None:
                # cagra ids are implicit dataset row positions
                _verify_namespaces(
                    "cagra", np.arange(index.size, dtype=np.int64),
                    namespaces)
        else:
            raise TypeError(
                f"verify: unsupported index type {type(index).__name__}")
        if level == "full":
            from raft_tpu.integrity import canary as _canary
            if getattr(index, "canaries", None) is None:
                _fail("canary.missing",
                      "level='full' requires a canary-carrying index "
                      "(build with canaries=...)")
            if res is None:
                raise ValueError(
                    "verify: level='full' needs res= to search with")
            _canary.health_check(res, index, raise_on_fail=True)
