"""Recall canaries — build-time sentinel queries with exact ground truth.

The self-test pattern of ``comms/self_test.py`` applied to ANN indexes:
``build()`` samples a handful of dataset rows as sentinel queries,
computes their *exact* neighbors while the dataset is still in hand, and
stores both inside the index (CRC-protected by a nested RTIE envelope in
the serialized stream).  :func:`health_check` re-searches the sentinels
and compares recall against the stored floor — run automatically after
``load()``, ``extend()`` and checkpoint ``resume=True``, so an index
whose invariants were silently violated is detected *before* it serves
traffic, not by a dashboard dip hours later.

Canary recall is a one-sided detector: corruption can only lower it, but
rows added by ``extend()`` can legitimately displace stored ground truth
too, so the floor should be conservative (default 0.5 of a build-time
recall that is typically > 0.9).
"""

from __future__ import annotations

import dataclasses
import io
from typing import Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core import serialize as ser
from raft_tpu.integrity.errors import IntegrityError

# build-time defaults: enough sentinels for a stable recall estimate,
# few enough that the stored block and the health-check search are noise
DEFAULT_QUERIES = 32
DEFAULT_K = 10
DEFAULT_FLOOR = 0.5


@dataclasses.dataclass
class CanarySet:
    """Sentinel queries + exact ground truth + acceptance floor."""

    queries: np.ndarray       # (c, dim) float32
    gt_ids: np.ndarray        # (c, k) int32, exact neighbors at build
    floor: float              # health_check fails below this recall
    build_recall: float = -1.0   # measured right after build (reporting)

    @property
    def k(self) -> int:
        return self.gt_ids.shape[1]

    @property
    def n_queries(self) -> int:
        return self.queries.shape[0]


@dataclasses.dataclass
class CanaryReport:
    """health_check outcome (returned, and raised-from on failure)."""

    recall: float
    floor: float
    n_queries: int
    k: int

    @property
    def ok(self) -> bool:
        return self.recall >= self.floor


def make(res, dataset, *, metric: int, n_queries: int = DEFAULT_QUERIES,
         k: int = DEFAULT_K, floor: float = DEFAULT_FLOOR) -> CanarySet:
    """Sample sentinel queries from ``dataset`` and compute exact ground
    truth (one brute-force pass) while the raw rows are still available.
    Ground-truth ids are dataset row positions — the default source ids
    ``build()`` assigns."""
    from raft_tpu.core.outputs import raw
    from raft_tpu.neighbors import brute_force

    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    c = min(n_queries, n)
    k = min(k, n)
    # strided row sample: deterministic (reproducible builds burn no key
    # stream) and distinct since (c-1)*stride < n
    stride = max(1, n // c)
    queries = dataset[np.arange(c) * stride]
    _, gt = raw(brute_force.knn)(res, dataset, queries, k, metric=metric)
    return CanarySet(queries=np.asarray(queries, np.float32),
                     gt_ids=np.asarray(gt, np.int32), floor=float(floor))


def _search_canaries(res, index, cs: CanarySet, filter=None) -> np.ndarray:
    """Re-search the sentinels on ``index``; returns (c, k) found ids."""
    from raft_tpu.core.outputs import raw
    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq

    q = jnp.asarray(cs.queries)
    if isinstance(index, ivf_flat.Index):
        p = ivf_flat.SearchParams(n_probes=min(32, index.n_lists))
        _, ids = raw(ivf_flat.search)(res, p, index, q, cs.k, filter=filter)
    elif isinstance(index, ivf_pq.Index):
        p = ivf_pq.SearchParams(n_probes=min(32, index.n_lists))
        _, ids = raw(ivf_pq.search)(res, p, index, q, cs.k, filter=filter)
    elif isinstance(index, cagra.Index):
        _, ids = raw(cagra.search)(res, cagra.SearchParams(), index, q,
                                   cs.k, filter=filter)
    elif type(index).__name__ == "RoutedIndex":
        # by_list distributed index (lazy import: integrity must not pull
        # the comms fabric in); ``res`` is the worker handle here — the
        # routed health check passes it through
        from raft_tpu.distributed import ann as _dann
        p = ivf_pq.SearchParams(n_probes=min(32, index.n_lists))
        _, ids = _dann.search(res, p, index, q, cs.k, filter=filter)
    else:
        raise TypeError(
            f"health_check: unsupported index type {type(index).__name__}")
    return np.asarray(ids)


def measure(res, index, cs: CanarySet, *, filter=None) -> float:
    """Canary recall of ``index`` against the stored ground truth.

    Deleted rows (tombstones in the IVF ``list_indices``, or a graph
    index's ``deleted_ids`` mask) are excluded from both the per-query
    ground-truth sets and the denominator: a delete legitimately removes
    stored neighbors, and counting them as misses would fail the floor
    for a perfectly healthy index.  An index whose every ground-truth id
    was deleted measures 1.0 (nothing left to find).

    ``filter`` (round 20, the filtered variant): a
    :class:`~raft_tpu.filters.SampleFilter` applied to BOTH sides — the
    sentinel search runs under the filter, and inadmissible ids leave
    the ground-truth sets and the denominator, exactly like tombstones.
    Measures that the admission seam preserves recall over the admitted
    set rather than penalizing the filter itself."""
    from raft_tpu.neighbors import mutate as _mutate

    found = _search_canaries(res, index, cs, filter=filter)
    dropped = _mutate.deleted_ids(index)
    admitted = None
    if filter is not None:
        from raft_tpu.filters import bitset as _fb
        mask = np.asarray(_fb.unpack_words(jnp.asarray(filter.words),
                                           filter.n_rows)) != 0
        if mask.shape[0] == 1:
            mask = np.broadcast_to(mask, (cs.n_queries, mask.shape[1]))
        admitted = mask
    hits = total = 0
    for row, (f, t) in enumerate(zip(found, cs.gt_ids)):
        gt = set(t.tolist()) - dropped if dropped else set(t.tolist())
        if admitted is not None:
            adm = admitted[row]
            cov = adm.shape[0]
            gt = {i for i in gt if i < cov and adm[i]}
        total += len(gt)
        hits += len(set(f.tolist()) & gt)
    return hits / total if total else 1.0


def health_check(res, index, *, raise_on_fail: bool = True
                 ) -> Optional[CanaryReport]:
    """Re-search the index's stored sentinels and compare recall to the
    floor.  Returns the report (``None`` when the index carries no
    canaries); raises :class:`IntegrityError` on a floor violation unless
    ``raise_on_fail=False``."""
    cs = getattr(index, "canaries", None)
    if cs is None:
        return None
    with obs.stage("integrity.health_check"):
        rec = measure(res, index, cs)
    if obs.enabled():
        obs.registry().counter("integrity.canary.checks").inc()
    report = CanaryReport(recall=rec, floor=cs.floor,
                          n_queries=cs.n_queries, k=cs.k)
    if not report.ok:
        if obs.enabled():
            obs.registry().counter("integrity.canary.failures").inc()
        # always-on flight event: a canary failure usually precedes a
        # rollback / serving error — the post-mortem timeline needs it
        from raft_tpu.observability import flight as _flight
        _flight.record_event("integrity.canary_failure",
                             recall=rec, floor=cs.floor,
                             n_queries=cs.n_queries, k=cs.k)
        if raise_on_fail:
            raise IntegrityError(
                f"canary recall {rec:.3f} below floor {cs.floor:.3f} "
                f"({cs.n_queries} sentinels, k={cs.k}; build-time recall "
                f"was {cs.build_recall:.3f})",
                invariant="canary.recall_floor")
    return report


def floor_of(index) -> Optional[float]:
    """The index's stored canary acceptance floor, or None for a
    canary-less index.  The live quality monitor
    (:mod:`raft_tpu.serving.shadow`) reuses it as the default degraded
    threshold for shadow-replay recall — build-time and live quality
    share ONE contract, declared once at build."""
    cs = getattr(index, "canaries", None)
    if cs is None:
        return None
    return float(cs.floor)


def auto_check(res, index, *, site: str) -> None:
    """The post-``load()`` / ``extend()`` / ``resume`` hook: a no-op for
    canary-less indexes, an :class:`IntegrityError` for a failing one."""
    cs = getattr(index, "canaries", None)
    if cs is None:
        return
    if obs.enabled():
        obs.registry().counter(f"integrity.canary.auto.{site}").inc()
    health_check(res, index, raise_on_fail=True)


# ---------------------------------------------------------------------------
# serialization: a nested RTIE envelope inside the index stream, so the
# canary block has its own CRC and a corrupt block fails fast on load
# ---------------------------------------------------------------------------

def to_stream(res, stream, cs: Optional[CanarySet]) -> None:
    ser.serialize_scalar(res, stream, np.int32(0 if cs is None else 1))
    if cs is None:
        return
    body = io.BytesIO()
    with ser.enveloped_writer(body) as env:
        ser.serialize_scalar(res, env, np.float64(cs.floor))
        ser.serialize_scalar(res, env, np.float64(cs.build_recall))
        ser.serialize_mdspan(res, env, cs.queries)
        ser.serialize_mdspan(res, env, cs.gt_ids)
    ser.serialize_mdspan(res, stream,
                         np.frombuffer(body.getvalue(), np.uint8))


def from_stream(res, stream) -> Optional[CanarySet]:
    present = int(ser.deserialize_scalar(res, stream))
    if not present:
        return None
    blob = np.asarray(ser.deserialize_mdspan(res, stream), np.uint8)
    env = ser.open_envelope(io.BytesIO(blob.tobytes()))
    floor = float(ser.deserialize_scalar(res, env))
    build_recall = float(ser.deserialize_scalar(res, env))
    queries = np.asarray(ser.deserialize_mdspan(res, env), np.float32)
    gt_ids = np.asarray(ser.deserialize_mdspan(res, env), np.int32)
    return CanarySet(queries=queries, gt_ids=gt_ids, floor=floor,
                     build_recall=build_recall)
