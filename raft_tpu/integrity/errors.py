"""Typed integrity failures.

The serving-stack counterpart of the reference's ``RAFT_EXPECTS`` /
``RAFT_FAIL`` macros (core/error.py): where ``LogicError`` means "the
caller misused the API", :class:`IntegrityError` means "the index (or an
input) is in a semantically invalid state" — every instance names the
first violated invariant and, when one exists, the index coordinate
where it was observed, so a monitoring stack can aggregate failures by
invariant without parsing prose.
"""

from __future__ import annotations

from typing import Optional, Tuple

from raft_tpu.core.error import RaftError


class IntegrityError(RaftError):
    """An index invariant (or canary recall floor) is violated.

    Attributes
    ----------
    invariant : str
        Dotted name of the first violated invariant, e.g.
        ``"ivf.list_sizes.range"`` or ``"canary.recall_floor"``.
    coord : tuple or None
        Index coordinates of the first violation (e.g. ``(list, slot)``
        for an IVF slot, ``(row, col)`` for a CAGRA edge), when the
        invariant is localized.
    """

    def __init__(self, message: str, *, invariant: str = "unknown",
                 coord: Optional[Tuple[int, ...]] = None):
        super().__init__(message)
        self.invariant = invariant
        self.coord = coord


class ValidationError(IntegrityError, ValueError):
    """A public entry point rejected its input under policy ``raise``
    (non-finite rows, malformed shapes).  Also a ``ValueError`` so
    callers that predate the integrity layer keep catching it."""
