"""Boundary validation — input hardening at every public entry point.

The TPU analogue of the input checking the reference does in its C++ API
layer (``RAFT_EXPECTS`` guards on every public header): *validate at the
boundary*, so garbage inputs (NaN/Inf rows, malformed shapes) are
reported where they enter instead of flowing through jitted kernels and
coming out as wrong-but-plausible neighbors.

Behavior is governed by :func:`raft_tpu.config.get_validation_policy`:

``raise``
    One fused ``isfinite`` reduction over the input plus a host sync; a
    non-finite row raises :class:`~raft_tpu.integrity.errors.ValidationError`
    naming the first bad row.  The default (serving-safe).
``mask``
    Jit-compatible, sync-free: non-finite rows are replaced by zeros
    in-graph and the per-row validity vector is returned so callers flag
    the corresponding *outputs* (search marks masked rows with id -1 /
    worst distance) — one bad row cannot poison the batch.
``off``
    Every function here returns immediately — zero added work, the
    jitted path is identical to an unvalidated call.

Counters: ``integrity.boundary.checks`` / ``.raised`` / ``.masked_rows``
(the masked-row count syncs only when observability collection is on).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import config
from raft_tpu import observability as obs
from raft_tpu.integrity.errors import ValidationError


def _is_floating(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def guard_nonfinite(x, *, site: str, policy: Optional[str] = None,
                    host: bool = False
                    ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Policy-driven non-finite guard over the rows of ``x``.

    Returns ``(x, ok_rows)`` where ``ok_rows`` is a per-row bool vector
    under policy ``mask`` (callers use it to flag outputs) and ``None``
    otherwise.  Non-floating inputs pass through untouched.

    ``host=True`` runs the identical policy in numpy and returns host
    arrays — for callers validating request-shaped data whose sizes are
    unbounded (the serving submit path), where a per-shape device
    compile would break the zero-recompile contract.
    """
    p = policy if policy is not None else config.get_validation_policy()
    if p == "off":
        return x, None
    if host:
        return _guard_nonfinite_host(x, site=site, policy=p)
    x = jnp.asarray(x)
    if not _is_floating(x):
        return x, None
    if p == "raise" and isinstance(x, jax.core.Tracer):
        # inside an outer jit/vmap there is no host to sync to; 'raise'
        # degrades to a no-op there ('mask' stays fully in-graph)
        return x, None
    if obs.enabled():
        obs.registry().counter("integrity.boundary.checks").inc()
    reduce_axes = tuple(range(1, x.ndim))
    ok = jnp.all(jnp.isfinite(x.astype(jnp.float32)), axis=reduce_axes)
    if p == "raise":
        if not bool(jnp.all(ok)):       # the policy's one host sync
            bad = int(jnp.argmin(ok))
            if obs.enabled():
                obs.registry().counter("integrity.boundary.raised").inc()
            raise ValidationError(
                f"{site}: non-finite values in input row {bad} "
                f"(policy 'raise'; use config.validation_policy('mask') "
                f"to flag rows instead, or 'off' for trusted inputs)",
                invariant="boundary.nonfinite", coord=(bad,))
        return x, None
    # mask: in-graph replacement, no host sync on the result path
    shape_ok = ok.reshape(ok.shape + (1,) * (x.ndim - 1))
    clean = jnp.where(shape_ok, x, jnp.zeros((), x.dtype))
    if obs.enabled():
        obs.registry().counter("integrity.boundary.masked_rows").inc(
            int(jnp.sum(~ok)))
    return clean, ok


def _guard_nonfinite_host(x, *, site: str, policy: str
                          ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Numpy twin of the device guard — same policy semantics, same
    counters, zero device work (and therefore zero compiles)."""
    x = np.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):  # dtype-level, no transfer
        return x, None
    if obs.enabled():
        obs.registry().counter("integrity.boundary.checks").inc()
    reduce_axes = tuple(range(1, x.ndim))
    ok = np.all(np.isfinite(x.astype(np.float32)), axis=reduce_axes)
    if policy == "raise":
        if not bool(np.all(ok)):
            bad = int(np.argmin(ok))
            if obs.enabled():
                obs.registry().counter("integrity.boundary.raised").inc()
            raise ValidationError(
                f"{site}: non-finite values in input row {bad} "
                f"(policy 'raise'; use config.validation_policy('mask') "
                f"to flag rows instead, or 'off' for trusted inputs)",
                invariant="boundary.nonfinite", coord=(bad,))
        return x, None
    shape_ok = ok.reshape(ok.shape + (1,) * (x.ndim - 1))
    clean = np.where(shape_ok, x, np.zeros((), x.dtype))
    if obs.enabled():
        obs.registry().counter("integrity.boundary.masked_rows").inc(
            int(np.sum(~ok)))
    return clean, ok


def check_matrix(x, name: str, *, site: str, dim: Optional[int] = None,
                 allow_empty: bool = True, policy: Optional[str] = None,
                 host: bool = False
                 ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Structural + non-finite validation for a 2-D input.

    Host-side O(1) shape checks (always under ``raise``/``mask``; skipped
    entirely under ``off``), then :func:`guard_nonfinite`.  Returns
    ``(x, ok_rows)`` as :func:`guard_nonfinite` does.
    """
    p = policy if policy is not None else config.get_validation_policy()
    if p == "off":
        return x, None
    xs = np.shape(x) if not hasattr(x, "shape") else x.shape
    if len(xs) != 2:
        raise ValidationError(
            f"{site}: {name} must be 2-D, got shape {tuple(xs)}",
            invariant="boundary.rank")
    if dim is not None and xs[1] != dim:
        raise ValidationError(
            f"{site}: {name} has {xs[1]} columns, expected {dim}",
            invariant="boundary.dim")
    if not allow_empty and xs[0] == 0:
        raise ValidationError(
            f"{site}: {name} has no rows",
            invariant="boundary.empty")
    return guard_nonfinite(x, site=site, policy=p, host=host)


def mask_search_outputs(distances: jax.Array, indices: jax.Array,
                        ok_rows: Optional[jax.Array], *,
                        select_min: bool = True
                        ) -> Tuple[jax.Array, jax.Array]:
    """Flag masked query rows in search outputs: id -1 and the worst
    distance for the metric (sync-free; composes with the in-graph
    masking of :func:`guard_nonfinite`)."""
    if ok_rows is None:
        return distances, indices
    worst = jnp.inf if select_min else -jnp.inf
    bad = ~ok_rows[:, None]
    return (jnp.where(bad, jnp.asarray(worst, distances.dtype), distances),
            jnp.where(bad, jnp.asarray(-1, indices.dtype), indices))
