"""raft_tpu.integrity — index integrity verification and input hardening.

Three layers, mirroring a serving stack's defense in depth:

1. :func:`verify` — tiered invariant checks (``structural`` /
   ``statistical`` / ``full``) over IVF-Flat, IVF-PQ and CAGRA indexes,
   raising a typed :class:`IntegrityError` that names the first violated
   invariant and its index coordinates.
2. Boundary validation (:mod:`~raft_tpu.integrity.boundary`) — a
   jit-compatible ``check_matrix`` / ``guard_nonfinite`` layer applied at
   every public build/search/extend/cluster entry point, governed by
   ``config.set_validation_policy("raise" | "mask" | "off")``.
3. Recall canaries (:mod:`~raft_tpu.integrity.canary`) — build-time
   sentinel queries with exact ground truth stored inside the index;
   :func:`health_check` re-searches them after ``load()`` / ``extend()``
   / checkpoint resume and fails fast when recall drops below the stored
   floor.

Counters land under ``integrity.*`` in the observability registry; the
verifier runs under a ``verify`` stage label.
"""

from raft_tpu.integrity import boundary  # noqa: F401
from raft_tpu.integrity import canary  # noqa: F401
from raft_tpu.integrity.boundary import (  # noqa: F401
    check_matrix,
    guard_nonfinite,
    mask_search_outputs,
)
from raft_tpu.integrity.canary import (  # noqa: F401
    CanaryReport,
    CanarySet,
    health_check,
)
from raft_tpu.integrity.errors import (  # noqa: F401
    IntegrityError,
    ValidationError,
)
from raft_tpu.integrity.verify import verify  # noqa: F401
