"""Flight recorder — always-on ring buffer of recent traces + anomalies.

The post-hoc half of the observability story: when a request is shed, a
shard degrades, or serving raises, the aggregate counters say *how often*
but not *what was happening*.  The flight recorder keeps the last N
completed request traces (when tracing is enabled) and **every anomaly
event** (always — anomalies are rare, so recording them is never gated on
collection) in a fixed-size ring, and :func:`dump` emits a Chrome
trace-event-format JSON artifact (load it in ``chrome://tracing`` /
Perfetto) for exactly this post-mortem.

Lock-free: the ring is a preallocated slot list; writers claim a slot with
``next(itertools.count())`` (atomic under the GIL) and store a single
reference — no lock, no allocation beyond the record itself, safe from any
thread including jax host callbacks.  Readers snapshot racily, which is
fine: a torn read can only miss or double-see a record mid-overwrite,
never observe a half-written one.

Anomaly event names are registry-style dotted literals and are policed by
graftlint's registry-consistency pass (a typo'd event name fails lint, not
silently records nothing).  The catalogue lives in docs/api.md.

Auto-dump: set ``RAFT_TPU_FLIGHT_DUMP=<path>`` and the serving path writes
the dump there when a batch dispatch raises (see batcher.py); CI uploads
it as a failure artifact.
"""

from __future__ import annotations

import itertools
import json
import os
from typing import Any, Dict, List, Optional

from raft_tpu.observability import trace as _trace

DEFAULT_CAPACITY = 512

#: hard ceiling on the ring size — the ring is a preallocated slot list,
#: so an absurd capacity is an allocation bug, not a tuning choice
MAX_CAPACITY = 1 << 20

#: env var overriding the process-global recorder's ring capacity
CAPACITY_ENV = "RAFT_TPU_FLIGHT_CAPACITY"

_EVENT = 0
_TRACE = 1


def _materialize(value: Any) -> Any:
    """Make one attribute JSON-safe, fetching lazy device values *here*,
    off the hot path (dump time is the only place a traced device array is
    brought to host)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, (list, tuple)):
        return [_materialize(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _materialize(v) for k, v in value.items()}
    if hasattr(value, "tolist"):          # np / jax arrays (host fetch ok here)
        try:
            return value.tolist()
        except Exception:
            return repr(value)
    return repr(value)


class FlightRecorder:
    """Fixed-capacity ring of ``(kind, seq, payload)`` records."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if not 0 < capacity <= MAX_CAPACITY:
            raise ValueError(
                f"flight recorder capacity must be in [1, {MAX_CAPACITY}], "
                f"got {capacity}")
        self.capacity = int(capacity)
        self._slots: List[Optional[tuple]] = [None] * self.capacity
        self._seq = itertools.count()

    # -- writers (hot path: one next() + one list store, no lock) ----------

    def record_event(self, name: str, *, trace_id: Optional[int] = None,
                     **attrs: Any) -> None:
        """Record one anomaly event.  Always on — call sites do NOT gate
        this on ``obs.enabled()``; anomalies are rare by construction."""
        seq = next(self._seq)
        self._slots[seq % self.capacity] = (
            _EVENT, seq, _trace.now(), name, trace_id, attrs or None)

    def record_trace(self, rec: _trace.SpanRecorder) -> None:
        """Record one completed request trace (caller closes it first)."""
        seq = next(self._seq)
        self._slots[seq % self.capacity] = (_TRACE, seq, rec)

    # -- readers (racy snapshot; see module docstring) ---------------------

    def _records(self) -> List[tuple]:
        return sorted((r for r in list(self._slots) if r is not None),
                      key=lambda r: r[1])

    def events(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        """Anomaly events in the ring, oldest first, optionally filtered
        by exact event name."""
        out = []
        for r in self._records():
            if r[0] != _EVENT:
                continue
            if name is not None and r[3] != name:
                continue
            out.append({"name": r[3], "t": r[2], "trace_id": r[4],
                        "attrs": r[5] or {}})
        return out

    def traces(self) -> List[_trace.SpanRecorder]:
        """Completed request traces in the ring, oldest first."""
        return [r[2] for r in self._records() if r[0] == _TRACE]

    def clear(self) -> None:
        # rebind, don't mutate: a racing writer lands in the old list
        self._slots = [None] * self.capacity

    # -- dump --------------------------------------------------------------

    def dump(self, path: Optional[str] = None, *,
             reason: Optional[str] = None) -> str:
        """Serialize the ring to Chrome trace-event JSON; optionally also
        write it to ``path``.  Returns the JSON string.

        Each request trace becomes a row (``tid`` = trace id) of complete
        ("X") events — the root span plus children; each anomaly is an
        instant ("i") event.  Timestamps are the monotonic trace clock in
        microseconds, so rows are mutually comparable within one process.
        """
        pid = os.getpid()
        ev: List[Dict[str, Any]] = []
        for r in self._records():
            if r[0] == _EVENT:
                _, _seq, t, name, trace_id, attrs = r
                ev.append({
                    "name": name, "ph": "i", "s": "g",
                    "ts": t * 1e6, "pid": pid, "tid": trace_id or 0,
                    "args": _materialize(attrs or {}),
                })
            else:
                rec = r[2]
                t1 = rec.t1 if rec.t1 is not None else _trace.now()
                ev.append({
                    "name": rec.name, "ph": "X",
                    "ts": rec.t0 * 1e6, "dur": (t1 - rec.t0) * 1e6,
                    "pid": pid, "tid": rec.trace_id,
                    "args": _materialize({"trace_id": rec.trace_id,
                                          **rec.attrs}),
                })
                for s in rec.spans:
                    ev.append({
                        "name": s.name, "ph": "X",
                        "ts": s.t0 * 1e6, "dur": s.duration * 1e6,
                        "pid": pid, "tid": rec.trace_id,
                        "args": _materialize(s.attrs or {}),
                    })
        doc = {"traceEvents": ev, "displayTimeUnit": "ms",
               "otherData": {"generator": "raft_tpu.observability.flight",
                             **({"reason": reason} if reason else {})}}
        text = json.dumps(doc)
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text


# ---------------------------------------------------------------------------
# process-global recorder + module-level conveniences


def _env_capacity() -> int:
    """Ring capacity for the process-global recorder:
    ``$RAFT_TPU_FLIGHT_CAPACITY`` when set and valid, else the default.
    Unparseable / out-of-bounds values fall back (with a warning) rather
    than raise — a bad env var must not make ``import raft_tpu`` fail."""
    raw = os.environ.get(CAPACITY_ENV)
    if not raw:
        return DEFAULT_CAPACITY
    try:
        cap = int(raw)
        if not 0 < cap <= MAX_CAPACITY:
            raise ValueError(raw)
    except ValueError:
        import warnings
        warnings.warn(
            f"ignoring {CAPACITY_ENV}={raw!r}: expected an integer in "
            f"[1, {MAX_CAPACITY}]; using {DEFAULT_CAPACITY}",
            RuntimeWarning, stacklevel=2)
        return DEFAULT_CAPACITY
    return cap


_RECORDER = FlightRecorder(_env_capacity())

#: env var naming the auto-dump destination (CI sets it; see test.yml).
#: A FILE path is overwritten in place (the original contract); a
#: DIRECTORY (existing, or a trailing separator) rotates
#: ``flight-NNNNNN.json`` dumps, keeping the newest ``DUMP_KEEP_ENV``
#: (default 5) — repeated failures no longer clobber the first, usually
#: most interesting, dump.
DUMP_ENV = "RAFT_TPU_FLIGHT_DUMP"

#: env var bounding how many rotated dumps a directory destination keeps
DUMP_KEEP_ENV = "RAFT_TPU_FLIGHT_DUMP_KEEP"
DEFAULT_DUMP_KEEP = 5


def recorder() -> FlightRecorder:
    return _RECORDER


def record_event(name: str, *, trace_id: Optional[int] = None,
                 **attrs: Any) -> None:
    _RECORDER.record_event(name, trace_id=trace_id, **attrs)


def record_trace(rec: _trace.SpanRecorder) -> None:
    _RECORDER.record_trace(rec)


def events(name: Optional[str] = None) -> List[Dict[str, Any]]:
    return _RECORDER.events(name)


def traces() -> List[_trace.SpanRecorder]:
    return _RECORDER.traces()


def clear() -> None:
    _RECORDER.clear()


def dump(path: Optional[str] = None, *, reason: Optional[str] = None) -> str:
    return _RECORDER.dump(path, reason=reason)


def maybe_auto_dump(reason: str) -> Optional[str]:
    """Write the flight dump to ``$RAFT_TPU_FLIGHT_DUMP`` if set (the
    serving path calls this when a dispatch raises; pytest's failure hook
    and bench.py call it on serving failures).  Returns the path written,
    or None when the env var is unset or the write itself fails (never
    raises — the recorder must not mask the original error)."""
    path = os.environ.get(DUMP_ENV)
    if not path:
        return None
    try:
        if os.path.isdir(path) or path.endswith(os.sep):
            return _rotated_dump(path, reason)
        _RECORDER.dump(path, reason=reason)
        return path
    except OSError:
        return None


def _dump_seq(name: str) -> Optional[int]:
    if not (name.startswith("flight-") and name.endswith(".json")):
        return None
    seq = name[len("flight-"):-len(".json")]
    return int(seq) if seq.isdigit() else None


def _rotated_dump(d: str, reason: str) -> str:
    """Directory-mode auto-dump: write ``flight-NNNNNN.json`` with the
    next sequence number (no clock — deterministic, collision-free
    within a process tree sharing the directory via the max scan) and
    prune the oldest beyond the keep bound."""
    os.makedirs(d, exist_ok=True)
    seqs = sorted(s for s in (_dump_seq(n) for n in os.listdir(d))
                  if s is not None)
    path = os.path.join(d, f"flight-{(seqs[-1] + 1 if seqs else 0):06d}.json")
    _RECORDER.dump(path, reason=reason)
    try:
        keep = max(1, int(os.environ.get(DUMP_KEEP_ENV,
                                         DEFAULT_DUMP_KEEP)))
    except ValueError:
        keep = DEFAULT_DUMP_KEEP
    stale = sorted(s for s in (_dump_seq(n) for n in os.listdir(d))
                   if s is not None)[:-keep]
    for s in stale:
        try:
            os.remove(os.path.join(d, f"flight-{s:06d}.json"))
        except OSError:
            pass          # a concurrent prune already took it
    return path
