"""Per-request distributed tracing for the serving path.

The registry (registry.py) aggregates; a *trace* follows **one request**
through the online pipeline: ``Server.submit`` mints a trace id and a
:class:`SpanRecorder`, the batcher attaches queue / batch-cut / exec /
result-slice spans, and ``distributed.ann.search`` annotates the recorder
with the per-shard status vector and scanned-rows counters it already
computed — attributes ride along, **no new device->host syncs** (the PR 10
host-sync graftlint rule holds with tracing enabled; device values are
attached lazily and only materialized by ``flight.dump()``).

Span timestamps use ``time.monotonic`` — the same clock the serving path
already uses for enqueue times and deadlines, so spans can be built
*retroactively* from timestamps the batcher records anyway (no extra clock
reads on the hot path beyond the ones serving already takes).

Tracing has its own gate, independent of metrics collection
(:func:`enable_tracing` / :func:`disable_tracing`): the CI serving-smoke
overhead comparison runs metrics-on in both arms and toggles only tracing.
When tracing is off, ``Server.submit`` mints nothing and every hook here is
a single module-flag check.

Cross-thread propagation: the batcher executes a *batch* on its dispatch
thread while requests originate on caller threads, so the ambient recorder
is a per-thread stack (:func:`push_active` / :func:`pop_active` /
:func:`current`) — the batcher pushes a batch-level recorder around the
executor call and adopts its spans/attributes into every live request's
trace afterwards.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import contextlib

#: process-global monotonic trace-id source (ids are unique per process;
#: the pid in flight dumps disambiguates across processes)
_TRACE_IDS = itertools.count(1)

_TRACING = False


def tracing() -> bool:
    """Whether per-request tracing is on (off by default)."""
    return _TRACING


def enable_tracing() -> None:
    global _TRACING
    _TRACING = True


def disable_tracing() -> None:
    global _TRACING
    _TRACING = False


@contextlib.contextmanager
def tracing_scope() -> Iterator[None]:
    """Enable tracing for the body, restoring the previous state after."""
    prev = _TRACING
    enable_tracing()
    try:
        yield
    finally:
        if not prev:
            disable_tracing()


def now() -> float:
    """The trace clock (``time.monotonic`` — matches serving timestamps)."""
    return time.monotonic()


class Span:
    """One closed phase of a request: ``[t0, t1)`` under a registry-style
    dotted name, plus free-form attributes.  Immutable once recorded, so a
    batch-shared span can be adopted by many request traces."""

    __slots__ = ("name", "t0", "t1", "attrs")

    def __init__(self, name: str, t0: float, t1: float,
                 attrs: Optional[Dict[str, Any]] = None) -> None:
        self.name = name
        self.t0 = float(t0)
        self.t1 = float(t1)
        self.attrs = attrs

    @property
    def duration(self) -> float:
        return self.t1 - self.t0

    def __repr__(self) -> str:  # debugging / dump readability
        return (f"Span({self.name!r}, dur={self.duration * 1e3:.3f}ms"
                + (f", attrs={self.attrs}" if self.attrs else "") + ")")


class SpanRecorder:
    """A request's trace under construction: the root span (``name``,
    opened at construction) plus child spans recorded retroactively from
    timestamps via :meth:`span`, and root-level attributes via
    :meth:`annotate`.

    Not locked: a recorder is only ever mutated by the thread that holds it
    (caller thread during submit, dispatch thread afterwards) — the handoff
    happens through the admission queue, which is the synchronization
    point.
    """

    __slots__ = ("trace_id", "name", "t0", "t1", "spans", "attrs")

    def __init__(self, name: str, trace_id: Optional[int] = None,
                 t0: Optional[float] = None) -> None:
        self.trace_id = next(_TRACE_IDS) if trace_id is None else trace_id
        self.name = name
        self.t0 = now() if t0 is None else float(t0)
        self.t1: Optional[float] = None
        self.spans: List[Span] = []
        self.attrs: Dict[str, Any] = {}

    def span(self, name: str, t0: float, t1: float, **attrs: Any) -> Span:
        """Record a closed child span from timestamps already taken."""
        s = Span(name, t0, t1, attrs or None)
        self.spans.append(s)
        return s

    def adopt(self, other: "SpanRecorder") -> None:
        """Merge a batch-level recorder's spans and attributes into this
        request's trace (spans are immutable — shared, not copied)."""
        self.spans.extend(other.spans)
        self.attrs.update(other.attrs)

    def annotate(self, key: str, value: Any) -> None:
        """Attach a root-span attribute.  Values may be lazy (e.g. an
        un-fetched device array): nothing here forces them to host — only
        ``flight.dump()`` materializes attributes, off the hot path."""
        self.attrs[key] = value

    def close(self, t1: Optional[float] = None) -> "SpanRecorder":
        self.t1 = now() if t1 is None else float(t1)
        return self

    @property
    def duration(self) -> float:
        return (self.t1 if self.t1 is not None else now()) - self.t0


def start_request(name: str = "serving.request") -> SpanRecorder:
    """Mint a new trace (fresh id, root span opened now)."""
    return SpanRecorder(name)


# ---------------------------------------------------------------------------
# ambient recorder: per-thread stack

_tls = threading.local()


def _stack() -> List[SpanRecorder]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def push_active(rec: SpanRecorder) -> None:
    _stack().append(rec)


def pop_active() -> Optional[SpanRecorder]:
    st = _stack()
    return st.pop() if st else None


def current() -> Optional[SpanRecorder]:
    """The innermost active recorder on this thread (None when tracing is
    off or nothing is active) — library code annotates through this without
    threading a handle through every signature."""
    if not _TRACING:
        return None
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


@contextlib.contextmanager
def activating(rec: Optional[SpanRecorder]) -> Iterator[None]:
    """Make ``rec`` the ambient recorder for the body (no-op on None)."""
    if rec is None:
        yield
        return
    push_active(rec)
    try:
        yield
    finally:
        pop_active()


def annotate_current(key: str, value: Any) -> None:
    """Annotate the ambient recorder, if any (one flag check when off)."""
    rec = current()
    if rec is not None:
        rec.annotate(key, value)


def stage_hook(name: str, seconds: float) -> None:
    """Called by ``stage()`` on exit: mirror the stage timing as a span on
    the ambient recorder, so ``stage()`` timers nest inside request traces
    under the same labels.  One flag check when tracing is off."""
    rec = current()
    if rec is not None:
        t1 = now()
        rec.span(name, t1 - seconds, t1)
