"""raft_tpu.observability — stage-level metrics, tracing, and exporters.

The aggregation layer on top of ``core/tracing`` (the NVTX-range analogue):
a process-global :class:`MetricsRegistry` of counters / gauges / timers, a
:func:`stage` context manager that times algorithm phases under the same
labels the TPU profiler sees, XLA compile-event tracking, per-build
:func:`build_report` breakdowns, and JSON / Prometheus exporters.

Contract: collection is **off by default**.  While off, instrumented library
code performs no timing and — the part that matters for QPS — **no
``block_until_ready`` fences**; ``stage`` yields a shared no-op handle.
Turn it on with :func:`enable` or scoped via ``with collecting(): ...``.

Quick tour::

    from raft_tpu import observability as obs

    with obs.collecting():
        index = cagra.build(res, params, dataset)
    print(obs.build_report(index)["stages"])   # per-stage seconds
    print(obs.to_prometheus())                 # scrape-ready text
"""

from raft_tpu.observability.registry import (
    Counter,
    DEFAULT_HISTOGRAM_BOUNDS,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
    WINDOW_INTERVAL_S,
    WINDOW_SLOTS,
    collecting,
    disable,
    enable,
    enabled,
    registry,
    reset,
    snapshot,
)
from raft_tpu.observability.stage import fence, stage
from raft_tpu.observability.export import to_json, to_prometheus
from raft_tpu.observability.report import BuildReport, build_report, build_scope
from raft_tpu.observability import flight
from raft_tpu.observability import quality
from raft_tpu.observability.quality import (
    DriftDetector,
    DriftFinding,
    DriftThresholds,
    OperatingPointLog,
    OpPoint,
    RecallEstimate,
    RecallEstimator,
    calibrator_table,
    read_operating_points,
    wilson_interval,
)
from raft_tpu.observability import trace
from raft_tpu.observability.trace import (
    Span,
    SpanRecorder,
    disable_tracing,
    enable_tracing,
    start_request,
    tracing,
    tracing_scope,
)

__all__ = [
    "Counter",
    "DEFAULT_HISTOGRAM_BOUNDS",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Timer",
    "WINDOW_INTERVAL_S",
    "WINDOW_SLOTS",
    "BuildReport",
    "DriftDetector",
    "DriftFinding",
    "DriftThresholds",
    "OperatingPointLog",
    "OpPoint",
    "RecallEstimate",
    "RecallEstimator",
    "Span",
    "SpanRecorder",
    "build_report",
    "build_scope",
    "calibrator_table",
    "collecting",
    "disable",
    "disable_tracing",
    "enable",
    "enable_tracing",
    "enabled",
    "fence",
    "flight",
    "quality",
    "read_operating_points",
    "registry",
    "reset",
    "snapshot",
    "stage",
    "start_request",
    "to_json",
    "to_prometheus",
    "trace",
    "tracing",
    "tracing_scope",
    "wilson_interval",
]
