"""Snapshot exporters: JSON and Prometheus text exposition format.

Both operate on the plain-dict snapshots produced by
``MetricsRegistry.snapshot()`` (or the global :func:`raft_tpu.observability.snapshot`),
so exports are consistent point-in-time views and never hold registry locks
during serialization.
"""

from __future__ import annotations

import json
import re
from typing import Dict, Optional

from raft_tpu.observability.registry import snapshot as _global_snapshot

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def to_json(snapshot: Optional[Dict] = None, *, indent: Optional[int] = None) -> str:
    """Serialize a snapshot (default: the global registry's) to JSON."""
    if snapshot is None:
        snapshot = _global_snapshot()
    return json.dumps(snapshot, indent=indent, sort_keys=True)


def _prom_name(name: str, prefix: str) -> str:
    """``cagra.build.scan`` -> ``raft_tpu_cagra_build_scan`` (Prometheus
    metric names admit only ``[a-zA-Z0-9_:]``)."""
    base = _PROM_NAME_RE.sub("_", name)
    return f"{prefix}_{base}" if prefix else base


def to_prometheus(snapshot: Optional[Dict] = None, *,
                  prefix: str = "raft_tpu") -> str:
    """Serialize a snapshot to the Prometheus text exposition format.

    Counters/gauges map directly; each timer ``t`` becomes five series:
    ``<t>_seconds_count|_total|_min|_max|_last``.
    """
    if snapshot is None:
        snapshot = _global_snapshot()
    lines = []
    for name, value in sorted(snapshot.get("counters", {}).items()):
        pname = _prom_name(name, prefix) + "_total"
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {value}")
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    for name, t in sorted(snapshot.get("timers", {}).items()):
        pname = _prom_name(name, prefix) + "_seconds"
        lines.append(f"# TYPE {pname} summary")
        lines.append(f"{pname}_count {t['count']}")
        lines.append(f"{pname}_total {t['total_s']}")
        lines.append(f"{pname}_min {t['min_s']}")
        lines.append(f"{pname}_max {t['max_s']}")
        lines.append(f"{pname}_last {t['last_s']}")
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} histogram")
        cum = 0
        for le, c in zip(h["bounds"], h["counts"]):
            cum += c
            lines.append(f'{pname}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{pname}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{pname}_sum {h['sum']}")
        lines.append(f"{pname}_count {h['count']}")
        # pre-computed quantile gauges: native histograms carry no
        # quantiles, but p50/p95/p99 are the numbers dashboards want
        for q in ("p50", "p95", "p99"):
            lines.append(f"{pname}_{q} {h[q]}")
    # windowed telemetry (PR 11): recent-interval counts and quantiles as
    # gauges — they rise AND fall with load, unlike the lifetime series
    win = snapshot.get("window") or {}
    for name, value in sorted(win.get("counters", {}).items()):
        pname = _prom_name(name, prefix) + "_window"
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    for name, h in sorted(win.get("histograms", {}).items()):
        pname = _prom_name(name, prefix) + "_window"
        for field in ("count", "sum", "max", "p50", "p95", "p99"):
            lines.append(f"# TYPE {pname}_{field} gauge")
            lines.append(f"{pname}_{field} {h[field]}")
    return "\n".join(lines) + "\n"
