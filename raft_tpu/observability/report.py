"""Per-build stage breakdown, attached to index objects.

``build_scope(...)`` wraps an index build; on exit it diffs global-registry
snapshots and keeps every metric that *changed* during the scope — the
build's own stage timers plus anything they pulled in (``kmeans_balanced.fit``,
``xla.compiles``, comms counters).  The resulting dict is attached to the
returned index (``object.__setattr__``, the same lazy-attach pattern the
index caches use) and retrieved with :func:`build_report`.

Stage timers are hierarchical by *name* only (``cagra.build.scan`` runs
inside ``cagra.build``): nested stage totals overlap their parents, so the
breakdown is attribution, not a partition.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

from raft_tpu.observability.registry import (
    enabled as _enabled,
    snapshot as _global_snapshot,
)

_ATTR = "_raft_tpu_build_report"


class BuildReport:
    """Mutable handle yielded by :func:`build_scope`; finalized on exit."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.total_s = 0.0
        self.stages: Dict[str, Dict[str, float]] = {}
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, float] = {}

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "total_s": self.total_s,
            "stages": dict(self.stages),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
        }

    def attach(self, index: Any) -> Any:
        """Attach this report to ``index`` (works on frozen dataclasses) and
        return it.  The handle itself is stored — ``build_scope`` finalizes
        it on exit, so attaching inside or outside the scope both work;
        :func:`build_report` renders the dict at read time.  No-op handle
        when collection was disabled."""
        object.__setattr__(index, _ATTR, self)
        return index

    def _finalize(self, before: Dict, after: Dict, total_s: float) -> None:
        self.total_s = total_s
        b_t, a_t = before.get("timers", {}), after.get("timers", {})
        for name, t in a_t.items():
            prev = b_t.get(name)
            if prev is not None and prev["count"] == t["count"]:
                continue  # untouched during the scope
            delta = dict(t)
            if prev is not None:
                delta["count"] = t["count"] - prev["count"]
                delta["total_s"] = t["total_s"] - prev["total_s"]
                # min/max/last are not diffable; keep the scope-end values
            self.stages[name] = delta
        b_c, a_c = before.get("counters", {}), after.get("counters", {})
        for name, v in a_c.items():
            d = v - b_c.get(name, 0)
            if d:
                self.counters[name] = d
        b_g, a_g = before.get("gauges", {}), after.get("gauges", {})
        for name, v in a_g.items():
            if name not in b_g or b_g[name] != v:
                self.gauges[name] = v


class _NoopReport(BuildReport):
    """Disabled-path handle: ``attach`` leaves the index untouched."""

    def attach(self, index: Any) -> Any:
        return index


@contextlib.contextmanager
def build_scope(name: str) -> Iterator[BuildReport]:
    """Collect the stage breakdown of one build.

    Usage (inside ``cagra.build`` etc.)::

        with build_scope("cagra.build") as rep:
            index = ...
        return rep.attach(index)

    Disabled collection yields a no-op report; the build runs untouched."""
    if not _enabled():
        yield _NoopReport(name)
        return
    rep = BuildReport(name)
    before = _global_snapshot()
    t0 = time.perf_counter()
    try:
        yield rep
    finally:
        rep._finalize(before, _global_snapshot(), time.perf_counter() - t0)


def build_report(index: Any) -> Optional[Dict[str, Any]]:
    """The stage breakdown recorded while ``index`` was built (a plain dict:
    ``{name, total_s, stages, counters, gauges}``), or None if the build ran
    with collection disabled."""
    rep = getattr(index, _ATTR, None)
    return rep.as_dict() if rep is not None else None
