"""``stage(name)`` — the timing primitive, composed with tracing.range.

A stage is one phase of an algorithm (``"cagra.build.scan"``,
``"ivf_pq.search.coarse"``).  Entering a stage while collection is enabled

  * opens the existing :func:`raft_tpu.core.tracing.range` under the **same
    label**, so the TPU profiler timeline and the metrics registry agree on
    stage names, and
  * starts a wall clock whose reading is recorded into
    ``registry().timer(name)`` on exit.

JAX dispatch is async, so a wall clock alone would measure enqueue time; the
yielded handle exposes :meth:`_StageHandle.fence` for the caller to block on
the stage's outputs before the clock stops.  **When collection is disabled
(the default) the context manager yields a no-op singleton: no named scope,
no clock, and — critically — ``fence`` does nothing, so instrumented hot
paths keep their async dispatch.**  That contract is load-bearing for search
QPS and is pinned by tests/test_observability.py.

Also here: the ``jax.monitoring`` listener that surfaces XLA compile events
(``/jax/core/compile/*``) as registry metrics, making recompile storms
visible as the ``xla.compiles`` counter.
"""

from __future__ import annotations

import time
from typing import Any, Iterator, Optional

import contextlib

import jax

from raft_tpu.core.tracing import range as _trace_range
from raft_tpu.observability import trace as _request_trace
from raft_tpu.observability.registry import (
    MetricsRegistry,
    enabled as _enabled,
    registry as _registry,
)

# Indirection so tests can observe (or forbid) fencing: the disabled-path
# test monkeypatches this and asserts it is never called.
_block_until_ready = jax.block_until_ready


def fence(*values: Any) -> None:
    """Block until every non-tracer jax array in ``values`` is ready.

    Safe to call from inside ``jit`` tracing: tracers are skipped (a traced
    stage then times tracing, not execution — which is what a trace-time
    caller gets anyway)."""
    for leaf in jax.tree_util.tree_leaves(values):
        if isinstance(leaf, jax.core.Tracer):
            continue
        if isinstance(leaf, jax.Array):
            _block_until_ready(leaf)


class _StageHandle:
    """Yielded by an *enabled* stage; carries the fence."""

    __slots__ = ("name",)

    def __init__(self, name: str) -> None:
        self.name = name

    def fence(self, *values: Any) -> None:
        fence(*values)


class _NoopHandle:
    """Yielded when collection is disabled — every method is free."""

    __slots__ = ()
    name = ""

    def fence(self, *values: Any) -> None:  # noqa: ARG002 - deliberate no-op
        return None


_NOOP = _NoopHandle()


@contextlib.contextmanager
def stage(name: str,
          registry: Optional[MetricsRegistry] = None) -> Iterator[Any]:
    """Time one algorithm phase under ``name`` (see module docstring).

    Usage::

        with stage("cagra.build.scan") as s:
            knn = run_the_scan(...)
            s.fence(knn)          # no-op when collection is off

    The final fence is the caller's responsibility — without it the timer
    records dispatch time only (still useful for host-loop stages)."""
    if not _enabled():
        yield _NOOP
        return
    reg = registry if registry is not None else _registry()
    with _trace_range(name):
        t0 = time.perf_counter()
        try:
            yield _StageHandle(name)
        finally:
            dt = time.perf_counter() - t0
            reg.timer(name).record(dt)
            # mirror onto the ambient request trace (one flag check when
            # per-request tracing is off) so stage timers nest inside
            # request spans under the same labels
            _request_trace.stage_hook(name, dt)


# ---------------------------------------------------------------------------
# XLA compile-event tracking (jax.monitoring)

_COMPILE_PREFIX = "/jax/core/compile/"
# the event marking one actual backend (XLA) compilation; fires once per
# cache-missing jit specialization — its count is the recompile-storm signal
_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"

_listener_installed = False


def _on_event_duration(name: str, secs: float, **kwargs: Any) -> None:
    # listener stays registered for the life of the process (jax.monitoring
    # has no public unregister), so gate on the collection flag instead
    if not _enabled() or not name.startswith(_COMPILE_PREFIX):
        return
    reg = _registry()
    reg.timer("xla." + name[len(_COMPILE_PREFIX):]).record(secs)
    if name == _BACKEND_COMPILE:
        reg.counter("xla.compiles").inc()


def _install_compile_listener() -> None:
    """Idempotently register the compile-event listener (called by enable())."""
    global _listener_installed
    if _listener_installed:
        return
    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
    _listener_installed = True
