"""Live quality observability: recall estimation, drift, op-point log.

The serving stack measures *latency* deeply (per-request tracing,
windowed telemetry, brownout steering) but says nothing about the
recall actually delivered to live traffic.  This module holds the
math and persistence for closing that loop; the sampling/replay
machinery that feeds it lives in :mod:`raft_tpu.serving.shadow`.

Three pieces:

:class:`RecallEstimator`
    Windowed (hits, total) accumulators keyed by ``(tenant, k)`` fed by
    shadow replays — each sampled query row contributes ``hits`` =
    |served top-k ∩ ground-truth top-k| out of ``total`` ground-truth
    neighbors.  :meth:`RecallEstimator.estimate` pools a window into a
    live recall estimate with a **Wilson score interval**: every
    (served, ground-truth) pair is a Bernoulli trial, so the interval
    is exact in the same way a canary floor is — a lower bound that
    only real quality loss (or too few samples) can push down.

:class:`DriftDetector`
    Calibrated-vs-measured checks, run once per window OFF the serving
    path (host syncs are fine here).  The catalogue:

    - ``group_est`` — the calibrated grouped-scan capacity estimate
      (:func:`raft_tpu.neighbors.ivf_pq.calibrate_group_capacity`)
      against the touched-list fraction measured on the window's
      sampled queries.  A measured fraction past the calibration margin
      means the overflow re-dispatch fallback is no longer rare.
    - ``scan_skew`` — mean probed rows per query against the
      uniform-list cost model (``live_rows * n_probes / n_lists``).
      Hot lists growing past the threshold ratio mean the latency
      model (and any planner fitted on it) is stale.
    - ``fused_fallback`` — windowed ``ivf_pq.search.fused_fallback``
      count; a warmed steady state should never fall back, so any
      window activity names its reason mix.
    - ``memtable_dead`` — tombstoned fraction of the delta tier; past
      the threshold, every probe is paying dead-row scan work that a
      fold would reclaim.

    Each finding ticks ``serving.quality.drift`` (plus a per-kind
    counter) and records a ``serving.quality.drift`` flight event —
    always-on, like every anomaly event.

:class:`OperatingPointLog`
    Persistent JSONL log of ``(knobs, generation, measured)`` records —
    one per quality window — with RTIE-enveloped rotation: the active
    file is plain append-only JSONL (tail-able, torn-tail tolerant);
    when it exceeds ``max_bytes`` it is sealed into a CRC-protected
    ``<path>.NNNNNN.rtie`` segment (atomic rename) and the oldest
    segments beyond ``keep`` are pruned.  :func:`read_operating_points`
    parses segments + active file back into :class:`OpPoint` records,
    and :func:`calibrator_table` groups them by knob tuple — exactly
    the fitted-surface input the ROADMAP item 3 SLO planner consumes.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.observability import flight as _flight
# the package __init__ rebinds its ``registry`` attribute to the accessor
# function, so pull the gate/accessor pair straight from the submodule
from raft_tpu.observability.registry import enabled as _enabled
from raft_tpu.observability.registry import registry as _registry

#: the window clock — module-level and monkeypatchable, same contract as
#: ``registry._now`` (tests inject a fake clock)
_now = time.monotonic

#: default two-sided confidence level: z for 95%
DEFAULT_Z = 1.96


# ---------------------------------------------------------------------------
# Wilson interval
# ---------------------------------------------------------------------------


def wilson_interval(hits: float, total: float, z: float = DEFAULT_Z
                    ) -> Tuple[float, float]:
    """Wilson score interval for a binomial proportion ``hits/total``.

    Preferred over the normal approximation because shadow windows are
    small (tens of rows) and live recall sits near 1.0 — exactly the
    regime where the Wald interval collapses to a zero-width lie.  An
    empty window returns the vacuous ``(0, 1)``.
    """
    if total <= 0:
        return 0.0, 1.0
    n = float(total)
    p = min(1.0, max(0.0, hits / n))
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2.0 * n)) / denom
    half = (z / denom) * math.sqrt(p * (1.0 - p) / n + z2 / (4.0 * n * n))
    return max(0.0, center - half), min(1.0, center + half)


@dataclasses.dataclass
class RecallEstimate:
    """One pooled window estimate: ``recall`` = hits/total with the
    Wilson ``(lo, hi)`` bound, over ``rows`` sampled query rows."""

    recall: float
    lo: float
    hi: float
    hits: int
    total: int
    rows: int
    window_s: float

    def as_dict(self) -> Dict[str, Any]:
        return {"recall": self.recall, "lo": self.lo, "hi": self.hi,
                "hits": self.hits, "total": self.total, "rows": self.rows,
                "window_s": self.window_s}


class RecallEstimator:
    """Windowed recall accumulators keyed by ``(tenant, k)``.

    Thread-safe but never on the serving hot path: only the shadow
    replay thread records, and readers (flush / stats / tests) take the
    same short lock.  Samples age out of a rolling ``window_s`` horizon
    on every record/read — no background maintenance."""

    def __init__(self, window_s: float = 60.0, z: float = DEFAULT_Z) -> None:
        self.window_s = float(window_s)
        self.z = float(z)
        self._lock = threading.Lock()
        # (tenant, k) -> deque of (t, rows, hits, total)
        self._samples: Dict[Tuple[str, int], deque] = {}

    def record(self, tenant: str, k: int, hits: int, total: int,
               rows: int = 1) -> None:
        t = _now()
        with self._lock:
            dq = self._samples.get((tenant, k))
            if dq is None:
                dq = self._samples[(tenant, k)] = deque()
            dq.append((t, int(rows), int(hits), int(total)))
            self._prune(dq, t)

    def _prune(self, dq: deque, t: float) -> None:
        horizon = t - self.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def _pool(self, keys) -> Tuple[int, int, int]:
        t = _now()
        rows = hits = total = 0
        for key in keys:
            dq = self._samples.get(key)
            if dq is None:
                continue
            self._prune(dq, t)
            for _, r, h, tot in dq:
                rows += r
                hits += h
                total += tot
        return rows, hits, total

    def estimate(self, tenant: Optional[str] = None,
                 k: Optional[int] = None) -> Optional[RecallEstimate]:
        """Pooled estimate over the window — all keys, one tenant's
        keys, or one exact ``(tenant, k)``.  None when no sample in the
        window matches."""
        with self._lock:
            keys = [key for key in self._samples
                    if (tenant is None or key[0] == tenant)
                    and (k is None or key[1] == k)]
            rows, hits, total = self._pool(keys)
        if total <= 0:
            return None
        lo, hi = wilson_interval(hits, total, self.z)
        return RecallEstimate(recall=hits / total, lo=lo, hi=hi,
                              hits=hits, total=total, rows=rows,
                              window_s=self.window_s)

    def estimates(self) -> Dict[Tuple[str, int], RecallEstimate]:
        """Per-(tenant, k) window estimates, empty keys dropped."""
        with self._lock:
            keys = list(self._samples)
        out = {}
        for key in keys:
            est = self.estimate(tenant=key[0], k=key[1])
            if est is not None:
                out[key] = est
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DriftThresholds:
    """Flag bounds for the calibrated-vs-measured checks.  Defaults err
    toward quiet: a finding should mean "recalibrate / fold now", not
    background noise."""

    # measured touched-list fraction beyond group_est * margin means the
    # calibrated capacity no longer covers real batches (1.25 is the
    # safety margin grouped.group_capacity already applies)
    group_est_margin: float = 1.25
    # measured probed rows per query vs the uniform-list model
    scan_skew_ratio: float = 2.0
    # windowed fused-fallback count tolerated in steady state
    fused_fallback_max: int = 0
    # tombstoned fraction of the delta tier / main index
    dead_fraction_max: float = 0.3


@dataclasses.dataclass
class DriftFinding:
    """One calibrated-vs-measured violation."""

    kind: str            # group_est | scan_skew | fused_fallback | memtable_dead
    calibrated: float    # the modeled / stored value
    measured: float
    threshold: float
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "calibrated": self.calibrated,
                "measured": self.measured, "threshold": self.threshold,
                **self.detail}


def measure_probe_stats(index, queries, n_probes: int
                        ) -> Optional[Dict[str, float]]:
    """Coarse-rank ``queries`` against ``index`` and measure what the
    calibration layer models: the touched-list fraction (group_est's
    quantity) and the mean probed rows per query (the scan-traffic cost
    model's quantity).  Runs the same ``_select_clusters`` ranking the
    search path uses — host syncs included, so call this OFF the serving
    path only (the shadow thread's window flush).  Returns None for
    indexes without the IVF coarse structure."""
    centers = getattr(index, "centers", None)
    rotation = getattr(index, "rotation", None)
    list_sizes = getattr(index, "list_sizes", None)
    if centers is None or rotation is None or queries is None:
        return None
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_pq as _pq

    n_lists = int(centers.shape[0])
    n_probes = max(1, min(int(n_probes), n_lists))
    queries = np.asarray(queries, np.float32)
    probes = np.asarray(_pq._select_clusters(
        centers, rotation, jnp.asarray(queries), n_probes,
        getattr(index, "metric", None)))
    flat = probes.reshape(-1)
    flat = flat[(flat >= 0) & (flat < n_lists)]
    pairs = int(queries.shape[0]) * n_probes
    touched = int(np.unique(flat).size)
    out = {"touched_fraction": touched / max(min(n_lists, pairs), 1),
           "touched_lists": float(touched),
           "n_probes": float(n_probes), "n_lists": float(n_lists)}
    if list_sizes is not None:
        sizes = np.asarray(list_sizes, np.int64)
        probed = sizes[probes.reshape(probes.shape[0], -1)]
        out["probed_rows_per_query"] = float(probed.sum(axis=1).mean())
        out["live_rows"] = float(sizes.sum())
    return out


class DriftDetector:
    """Run the calibrated-vs-measured catalogue once per quality window.

    Every check degrades to "skip" when its signal is unavailable (no
    calibration stored, metrics collection off, no delta tier) — a
    detector must never invent drift out of missing data."""

    def __init__(self, thresholds: Optional[DriftThresholds] = None
                 ) -> None:
        self.thresholds = thresholds or DriftThresholds()

    # -- individual checks --------------------------------------------------

    def check_group_est(self, index, probe_stats: Optional[Dict[str, float]]
                        ) -> Optional[DriftFinding]:
        est = float(getattr(index, "group_est", 0.0) or 0.0)
        if est <= 0.0 or not probe_stats:
            return None          # uncalibrated dispatch is always correct
        measured = probe_stats["touched_fraction"]
        bound = est * self.thresholds.group_est_margin
        if measured <= bound:
            return None
        return DriftFinding(
            kind="group_est", calibrated=est, measured=measured,
            threshold=bound,
            detail={"touched_lists": probe_stats["touched_lists"],
                    "n_probes": probe_stats["n_probes"]})

    def check_scan_skew(self, index, probe_stats: Optional[Dict[str, float]]
                        ) -> Optional[DriftFinding]:
        if not probe_stats or "probed_rows_per_query" not in probe_stats:
            return None
        live = probe_stats.get("live_rows", 0.0)
        n_lists = probe_stats["n_lists"]
        if live <= 0 or n_lists <= 0:
            return None
        modeled = live * probe_stats["n_probes"] / n_lists
        measured = probe_stats["probed_rows_per_query"]
        if modeled <= 0 or measured <= self.thresholds.scan_skew_ratio * modeled:
            return None
        return DriftFinding(
            kind="scan_skew", calibrated=modeled, measured=measured,
            threshold=self.thresholds.scan_skew_ratio * modeled,
            detail={"live_rows": live})

    def check_fused_fallback(self) -> Optional[DriftFinding]:
        if not _enabled():
            return None
        reg = _registry()
        fallbacks = reg.counter("ivf_pq.search.fused_fallback").windowed()
        if fallbacks <= self.thresholds.fused_fallback_max:
            return None
        prefix = "ivf_pq.search.fused_fallback.reason."
        reasons = {}
        for name, c in reg.snapshot().get("counters", {}).items():
            if name.startswith(prefix):
                w = reg.counter(name).windowed()
                if w:
                    reasons[name[len(prefix):]] = w
        return DriftFinding(
            kind="fused_fallback", calibrated=0.0, measured=float(fallbacks),
            threshold=float(self.thresholds.fused_fallback_max),
            detail={"reasons": reasons})

    def check_memtable_dead(self, memtable) -> Optional[DriftFinding]:
        if memtable is None:
            return None
        live = int(memtable.live_rows)
        dead = int(memtable.n_tombstones)
        total = live + dead
        if total == 0:
            return None
        frac = dead / total
        if frac <= self.thresholds.dead_fraction_max:
            return None
        return DriftFinding(
            kind="memtable_dead", calibrated=0.0, measured=frac,
            threshold=self.thresholds.dead_fraction_max,
            detail={"live_rows": live, "tombstones": dead})

    # -- the window pass ----------------------------------------------------

    def check(self, *, index=None, queries=None, n_probes: Optional[int] = None,
              memtable=None, probe_stats: Optional[Dict[str, float]] = None
              ) -> List[DriftFinding]:
        """One pass over the catalogue; emits metrics + flight events for
        every finding and returns them.  ``probe_stats`` short-circuits
        the measurement when the caller already ran
        :func:`measure_probe_stats` this window (the shadow flush shares
        one measurement between drift and the op-point log)."""
        if (probe_stats is None and index is not None
                and queries is not None and n_probes is not None):
            probe_stats = measure_probe_stats(index, queries, n_probes)
        findings = [f for f in (
            self.check_group_est(index, probe_stats)
            if index is not None else None,
            self.check_scan_skew(index, probe_stats)
            if index is not None else None,
            self.check_fused_fallback(),
            self.check_memtable_dead(memtable),
        ) if f is not None]
        for f in findings:
            self._emit(f)
        return findings

    @staticmethod
    def _emit(f: DriftFinding) -> None:
        if _enabled():
            reg = _registry()
            reg.counter("serving.quality.drift").inc()
            reg.counter(f"serving.quality.drift.{f.kind}").inc()
        # always-on anomaly event: drift is rare and exactly what the
        # post-mortem / recalibration runbook needs to see with values
        _flight.record_event("serving.quality.drift", **f.as_dict())


# ---------------------------------------------------------------------------
# the operating-point log
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OpPoint:
    """One logged operating point: the knobs a window served at, the
    index generation, and what was measured there.

    ``knobs`` keys (the serving executor's closed-shape coordinates):
    ``kind / scan_mode / n_probes / kt / merge_window / bucket / rung /
    k / filtered`` (``filtered`` — round 20 — marks a filter-configured
    executor: recall under admission predicates is a different operating
    regime than unfiltered recall, so the calibrator must not mix the
    two).  ``measured`` keys: the recall estimate (``recall / lo / hi /
    hits / total / rows``), window latency quantiles (``p50 / p95 /
    p99`` seconds), and whatever scan-traffic numbers were available
    (``scan_rows``).  The calibrator treats both as open dicts."""

    t: float
    generation: int
    knobs: Dict[str, Any]
    measured: Dict[str, Any]
    tenant: str = "*"

    def as_dict(self) -> Dict[str, Any]:
        return {"t": self.t, "generation": self.generation,
                "tenant": self.tenant, "knobs": self.knobs,
                "measured": self.measured}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OpPoint":
        return cls(t=float(d.get("t", 0.0)),
                   generation=int(d.get("generation", 0)),
                   tenant=str(d.get("tenant", "*")),
                   knobs=dict(d.get("knobs", {})),
                   measured=dict(d.get("measured", {})))


_SEGMENT_SUFFIX = ".rtie"


def _segment_paths(path: str) -> List[str]:
    """Sealed segments for ``path``, oldest first."""
    d, base = os.path.split(os.path.abspath(path))
    if not os.path.isdir(d):
        return []
    out = []
    for name in os.listdir(d):
        if not (name.startswith(base + ".")
                and name.endswith(_SEGMENT_SUFFIX)):
            continue
        seq = name[len(base) + 1:-len(_SEGMENT_SUFFIX)]
        if seq.isdigit():
            out.append((int(seq), os.path.join(d, name)))
    return [p for _, p in sorted(out)]


class OperatingPointLog:
    """Append-only JSONL operating-point log with RTIE-sealed rotation.

    The ACTIVE file is plain JSONL — one :meth:`append` is one
    ``json.dumps`` line on a line-buffered handle, so a crash can tear
    at most the final line (the reader drops a torn tail, the same
    tolerance the WAL gives its own tail).  When the active file grows
    past ``max_bytes`` it is sealed: the raw JSONL bytes are wrapped in
    one RTIE envelope (magic/version/length/CRC32 — the index
    serialization's framing) and atomically renamed to
    ``<path>.NNNNNN.rtie``; segments beyond ``keep`` are pruned oldest
    first.  Sealed history is CRC-verified on read — a flipped bit in
    the planner's training data is rejected, not fitted."""

    def __init__(self, path: str, *, max_bytes: int = 1 << 20,
                 keep: int = 8) -> None:
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.path, "a", buffering=1)
        self._size = self._f.tell()

    def append(self, op: OpPoint) -> None:
        line = json.dumps(op.as_dict(), sort_keys=True) + "\n"
        with self._lock:
            self._f.write(line)
            self._size += len(line)
            if self._size >= self.max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        """Seal the active file into the next RTIE segment (caller holds
        the lock)."""
        from raft_tpu.core import serialize as ser
        from raft_tpu.resilience.checkpoint import atomic_write

        self._f.close()
        with open(self.path, "rb") as f:
            payload = f.read()
        segments = _segment_paths(self.path)
        seq = 0
        if segments:
            tail = os.path.basename(segments[-1])
            base = os.path.basename(self.path)
            seq = int(tail[len(base) + 1:-len(_SEGMENT_SUFFIX)]) + 1
        import io as _io

        buf = _io.BytesIO()
        ser.write_envelope(buf, payload)
        atomic_write(f"{self.path}.{seq:06d}{_SEGMENT_SUFFIX}",
                     buf.getvalue())
        for stale in _segment_paths(self.path)[:-self.keep]:
            try:
                os.remove(stale)
            except OSError:
                pass
        self._f = open(self.path, "w", buffering=1)
        self._size = 0

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.close()

    def __enter__(self) -> "OperatingPointLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_operating_points(path: str) -> List[OpPoint]:
    """Parse an operating-point log (sealed segments oldest-first, then
    the active JSONL) back into :class:`OpPoint` records — the
    calibrator's input shape.

    Sealed segments are CRC-verified (:class:`CorruptIndexError` on
    damage — history the planner fits on must be intact); the active
    file tolerates exactly one torn FINAL line (the crash window of a
    line-buffered append)."""
    from raft_tpu.core import serialize as ser
    from raft_tpu.core.serialize import CorruptIndexError

    chunks: List[Tuple[str, bytes]] = []
    for seg in _segment_paths(path):
        with open(seg, "rb") as f:
            chunks.append((seg, ser.read_envelope(f)))
    if os.path.exists(path):
        with open(path, "rb") as f:
            chunks.append((path, f.read()))
    out: List[OpPoint] = []
    for src, data in chunks:
        lines = data.decode("utf-8", errors="replace").splitlines()
        sealed = src != path
        for j, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(OpPoint.from_dict(json.loads(line)))
            except (ValueError, TypeError) as e:
                if not sealed and j == len(lines) - 1:
                    continue          # torn final line of the active file
                raise CorruptIndexError(
                    f"corrupt operating-point log {src!r} line {j + 1}: "
                    f"{e}") from e
    return out


def calibrator_table(points: List[OpPoint]
                     ) -> Dict[Tuple[Tuple[str, Any], ...],
                               Dict[str, Any]]:
    """Group logged points by knob tuple and aggregate the measured
    surface — the ``knobs -> measured`` table a planner fits.

    Keys are sorted ``(knob, value)`` tuples (hashable, stable across
    runs); values carry the per-point measured dicts plus pooled
    recall (hits/total re-pooled, NOT averaged — windows have unequal
    sample counts) and mean latency quantiles."""
    table: Dict[Tuple[Tuple[str, Any], ...], Dict[str, Any]] = {}
    for p in points:
        key = tuple(sorted(p.knobs.items(),
                           key=lambda kv: kv[0]))
        row = table.setdefault(key, {"points": [], "hits": 0, "total": 0})
        row["points"].append(p.measured)
        row["hits"] += int(p.measured.get("hits", 0) or 0)
        row["total"] += int(p.measured.get("total", 0) or 0)
    for row in table.values():
        total = row["total"]
        row["recall"] = (row["hits"] / total) if total else None
        if total:
            row["recall_lo"], row["recall_hi"] = wilson_interval(
                row["hits"], total)
        for q in ("p50", "p95", "p99"):
            vals = [m[q] for m in row["points"]
                    if isinstance(m.get(q), (int, float))]
            row[q] = (sum(vals) / len(vals)) if vals else None
    return table
