"""Metrics registry — counters, gauges, timers.

The aggregation layer the reference lacks an exact analogue for: NVTX ranges
(core/nvtx.hpp) annotate but never aggregate, so raft's bench harness
re-derives stage costs from profiler dumps.  Here the registry *is* the
aggregate: ``stage(...)`` (see stage.py) records wall time per label, library
code bumps counters (comms bytes, kmeans iterations, XLA compiles), and the
exporters (export.py) serialize a snapshot to JSON / Prometheus text.

Collection is **off by default** and globally gated: when disabled, the
instrumentation in the library degenerates to a handful of predicate checks
(no timing, no device fences, no named scopes beyond the ones that already
existed).  Enable with :func:`enable` / the :func:`collecting` context
manager.

Thread-safety: metric mutation is guarded by a per-registry lock — stages can
close on worker threads (e.g. host callbacks, jax.monitoring listeners).

Windowed telemetry (PR 11): counters and histograms additionally maintain a
rotating ring of fixed-interval slots, so ``snapshot()["window"]`` exposes
counts and p50/p95/p99 over roughly the last ``interval * slots`` seconds
instead of process lifetime.  Rotation is lazy (on record — no background
thread): each slot remembers the absolute interval index ("epoch") it was
last written in and is zeroed when reused, so an idle metric simply ages out
of the window.  This is the surface the SLO planner (ROADMAP item 3) and
load-aware routing (item 4) consume.
"""

from __future__ import annotations

import bisect
import contextlib
import math
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# Window defaults: 12 slots x 5 s = ~60 s of recent history.  Kept cheap:
# one int division + one ring-slot update per record.
WINDOW_INTERVAL_S = 5.0
WINDOW_SLOTS = 12

# Injectable clock (tests monkeypatch this to step time deterministically).
# monotonic matches the serving path's enqueue/deadline clock.
_now = time.monotonic


class Counter:
    """Monotonic counter (e.g. ``comms.allreduce.calls``, ``xla.compiles``).

    Alongside the lifetime total, ``inc`` maintains the rotating window ring
    (see module docstring); :meth:`windowed` reads the recent-interval count.
    Call sites are collection-gated — when ``enabled()`` is False nothing
    calls :meth:`inc`, so a disabled counter costs nothing (pinned by
    tests/test_tracing.py::TestDisabledPathCost).
    """

    __slots__ = ("name", "_value", "_lock", "_win_interval", "_win_slots",
                 "_win_epoch", "_win_counts")

    def __init__(self, name: str, lock: threading.RLock,
                 window: Tuple[float, int] = (WINDOW_INTERVAL_S,
                                              WINDOW_SLOTS)) -> None:
        self.name = name
        self._value = 0
        self._lock = lock
        self._win_interval = float(window[0])
        self._win_slots = int(window[1])
        self._win_epoch = [-1] * self._win_slots
        self._win_counts = [0] * self._win_slots

    def inc(self, n: int = 1) -> None:
        epoch = int(_now() / self._win_interval)
        idx = epoch % self._win_slots
        with self._lock:
            self._value += n
            if self._win_epoch[idx] != epoch:
                self._win_epoch[idx] = epoch
                self._win_counts[idx] = 0
            self._win_counts[idx] += n

    @property
    def value(self) -> int:
        return self._value

    def windowed(self) -> int:
        """Count over the last ``interval * slots`` seconds (approximate:
        includes the currently-filling slot, drops whole expired slots)."""
        epoch = int(_now() / self._win_interval)
        lo = epoch - self._win_slots + 1
        with self._lock:
            return sum(c for e, c in zip(self._win_epoch, self._win_counts)
                       if lo <= e <= epoch)


class Gauge:
    """Last-write-wins scalar (e.g. ``cagra.build.pdim``)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock) -> None:
        self.name = name
        self._value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Timer:
    """Duration accumulator: count / total / min / max / last, in seconds."""

    __slots__ = ("name", "count", "total", "min", "max", "last", "_lock")

    def __init__(self, name: str, lock: threading.RLock) -> None:
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0
        self.last = 0.0
        self._lock = lock

    def record(self, seconds: float) -> None:
        seconds = float(seconds)
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)
            self.last = seconds

    def as_dict(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "total_s": self.total,
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
            "last_s": self.last,
        }


# Default histogram buckets: log-spaced (factor 2) from 1 µs to ~67 s —
# wide enough for both per-query serving latencies and build stages.  27
# finite upper bounds + one overflow bucket; fixed at construction so
# ``observe`` is one bisect + one increment under the registry lock.
DEFAULT_HISTOGRAM_BOUNDS: Tuple[float, ...] = tuple(
    1e-6 * 2.0 ** i for i in range(27))


def _quantile_of(counts: Sequence[int], count: int,
                 bounds: Sequence[float], maxv: float, q: float) -> float:
    """Linear-interpolated quantile over a bucket-count vector (0.0 when
    empty).  Shared by the lifetime and windowed views — caller holds the
    lock (or owns a private copy)."""
    if count == 0:
        return 0.0
    target = q * count
    seen = 0.0
    for i, c in enumerate(counts):
        if seen + c >= target and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else maxv
            frac = (target - seen) / c
            return min(lo + frac * (hi - lo), maxv)
        seen += c
    return maxv


class Histogram:
    """Fixed-bucket distribution (e.g. ``serving.latency.total``).

    Log-spaced upper bounds by default (:data:`DEFAULT_HISTOGRAM_BOUNDS`);
    values are dimensionless to the registry — record seconds for
    latencies, rows for batch fills.  Like every metric here the *call
    sites* are collection-gated: while ``enabled()`` is False no library
    code calls :meth:`observe`, so a disabled histogram is zero work.

    Quantiles (p50/p95/p99) are estimated by linear interpolation inside
    the target bucket — resolution is the bucket width (a factor of 2 by
    default), which is the standard Prometheus-histogram tradeoff.
    """

    __slots__ = ("name", "bounds", "counts", "count", "sum", "min", "max",
                 "_lock", "_win_interval", "_win_slots", "_win_epoch",
                 "_win_counts", "_win_n", "_win_sum", "_win_max")

    def __init__(self, name: str, lock: threading.RLock,
                 bounds: Optional[Sequence[float]] = None,
                 window: Tuple[float, int] = (WINDOW_INTERVAL_S,
                                              WINDOW_SLOTS)) -> None:
        self.name = name
        self.bounds = tuple(float(b) for b in
                            (bounds if bounds is not None
                             else DEFAULT_HISTOGRAM_BOUNDS))
        assert list(self.bounds) == sorted(self.bounds), \
            "histogram bounds must be sorted"
        self.counts = [0] * (len(self.bounds) + 1)   # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0
        self._lock = lock
        self._win_interval = float(window[0])
        self._win_slots = int(window[1])
        self._win_epoch = [-1] * self._win_slots
        self._win_counts: List[List[int]] = [
            [0] * len(self.counts) for _ in range(self._win_slots)]
        self._win_n = [0] * self._win_slots
        self._win_sum = [0.0] * self._win_slots
        self._win_max = [0.0] * self._win_slots

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        epoch = int(_now() / self._win_interval)
        widx = epoch % self._win_slots
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)
            if self._win_epoch[widx] != epoch:
                self._win_epoch[widx] = epoch
                self._win_counts[widx] = [0] * len(self.counts)
                self._win_n[widx] = 0
                self._win_sum[widx] = 0.0
                self._win_max[widx] = 0.0
            self._win_counts[widx][idx] += 1
            self._win_n[widx] += 1
            self._win_sum[widx] += value
            self._win_max[widx] = max(self._win_max[widx], value)

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1] (0.0 when empty)."""
        with self._lock:
            return _quantile_of(self.counts, self.count, self.bounds,
                                self.max, q)

    def as_dict(self) -> Dict[str, object]:
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else 0.0,
                "max": self.max,
                "p50": self.quantile(0.50),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99),
                "bounds": list(self.bounds),
                "counts": list(self.counts),
            }

    def windowed_dict(self) -> Dict[str, object]:
        """Distribution over the last ``interval * slots`` seconds only:
        count / sum / max and interpolated p50/p95/p99 (same estimator as
        the lifetime view, over the merged in-window bucket vectors)."""
        epoch = int(_now() / self._win_interval)
        lo = epoch - self._win_slots + 1
        with self._lock:
            counts = [0] * len(self.counts)
            n = 0
            total = 0.0
            mx = 0.0
            for i in range(self._win_slots):
                if lo <= self._win_epoch[i] <= epoch:
                    for j, c in enumerate(self._win_counts[i]):
                        counts[j] += c
                    n += self._win_n[i]
                    total += self._win_sum[i]
                    mx = max(mx, self._win_max[i])
            return {
                "count": n,
                "sum": total,
                "max": mx,
                "p50": _quantile_of(counts, n, self.bounds, mx, 0.50),
                "p95": _quantile_of(counts, n, self.bounds, mx, 0.95),
                "p99": _quantile_of(counts, n, self.bounds, mx, 0.99),
            }


class MetricsRegistry:
    """Named metric store with get-or-create accessors and snapshot/reset.

    ``window_interval_s`` / ``window_slots`` fix the rotating-window layout
    for every counter/histogram created by this registry (see module
    docstring); the merged recent-interval view is the ``"window"`` section
    of :meth:`snapshot`.
    """

    def __init__(self, *, window_interval_s: float = WINDOW_INTERVAL_S,
                 window_slots: int = WINDOW_SLOTS) -> None:
        self._lock = threading.RLock()
        self._window = (float(window_interval_s), int(window_slots))
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name, self._lock,
                                                   self._window)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name, self._lock)
            return m

    def timer(self, name: str) -> Timer:
        with self._lock:
            m = self._timers.get(name)
            if m is None:
                m = self._timers[name] = Timer(name, self._lock)
            return m

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        """Get-or-create; ``bounds`` applies only at creation (the first
        caller fixes the bucket layout, like a Prometheus registration)."""
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, self._lock,
                                                       bounds, self._window)
            return m

    def snapshot(self) -> Dict[str, Dict]:
        """Point-in-time copy: plain dicts, safe to mutate / serialize.

        The ``"window"`` section re-aggregates counters and histograms over
        the rotating recent interval only (``span_s`` seconds); the other
        sections remain process-lifetime, unchanged from PR 5."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "timers": {n: t.as_dict() for n, t in self._timers.items()},
                "histograms": {n: h.as_dict()
                               for n, h in self._histograms.items()},
                "window": {
                    "interval_s": self._window[0],
                    "span_s": self._window[0] * self._window[1],
                    "counters": {n: c.windowed()
                                 for n, c in self._counters.items()},
                    "histograms": {n: h.windowed_dict()
                                   for n, h in self._histograms.items()},
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._histograms.clear()


# ---------------------------------------------------------------------------
# global default registry + collection gate

_REGISTRY = MetricsRegistry()
_ENABLED = False


def registry() -> MetricsRegistry:
    """The process-global default registry."""
    return _REGISTRY


def enabled() -> bool:
    """Whether collection is on.  Instrumented call sites check this before
    doing any work; False (the default) means zero fences and zero timing."""
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True
    # installed lazily so `import raft_tpu` never registers global listeners
    from raft_tpu.observability.stage import _install_compile_listener
    _install_compile_listener()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


@contextlib.contextmanager
def collecting(reg: Optional[MetricsRegistry] = None) -> Iterator[MetricsRegistry]:
    """Enable collection for the body, restoring the previous state after.

    Yields the registry metrics are recorded into (the global one — per-call
    registries compose via snapshot diffs, see report.py)."""
    prev = _ENABLED
    enable()
    try:
        yield reg if reg is not None else _REGISTRY
    finally:
        if not prev:
            disable()


def snapshot() -> Dict[str, Dict]:
    """Snapshot of the global registry."""
    return _REGISTRY.snapshot()


def reset() -> None:
    """Reset the global registry (collection gate is unaffected)."""
    _REGISTRY.reset()
