"""Metrics registry — counters, gauges, timers.

The aggregation layer the reference lacks an exact analogue for: NVTX ranges
(core/nvtx.hpp) annotate but never aggregate, so raft's bench harness
re-derives stage costs from profiler dumps.  Here the registry *is* the
aggregate: ``stage(...)`` (see stage.py) records wall time per label, library
code bumps counters (comms bytes, kmeans iterations, XLA compiles), and the
exporters (export.py) serialize a snapshot to JSON / Prometheus text.

Collection is **off by default** and globally gated: when disabled, the
instrumentation in the library degenerates to a handful of predicate checks
(no timing, no device fences, no named scopes beyond the ones that already
existed).  Enable with :func:`enable` / the :func:`collecting` context
manager.

Thread-safety: metric mutation is guarded by a per-registry lock — stages can
close on worker threads (e.g. host callbacks, jax.monitoring listeners).
"""

from __future__ import annotations

import bisect
import contextlib
import math
import threading
from typing import Dict, Iterator, Optional, Sequence, Tuple


class Counter:
    """Monotonic counter (e.g. ``comms.allreduce.calls``, ``xla.compiles``)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock) -> None:
        self.name = name
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar (e.g. ``cagra.build.pdim``)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock) -> None:
        self.name = name
        self._value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Timer:
    """Duration accumulator: count / total / min / max / last, in seconds."""

    __slots__ = ("name", "count", "total", "min", "max", "last", "_lock")

    def __init__(self, name: str, lock: threading.RLock) -> None:
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0
        self.last = 0.0
        self._lock = lock

    def record(self, seconds: float) -> None:
        seconds = float(seconds)
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)
            self.last = seconds

    def as_dict(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "total_s": self.total,
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
            "last_s": self.last,
        }


# Default histogram buckets: log-spaced (factor 2) from 1 µs to ~67 s —
# wide enough for both per-query serving latencies and build stages.  27
# finite upper bounds + one overflow bucket; fixed at construction so
# ``observe`` is one bisect + one increment under the registry lock.
DEFAULT_HISTOGRAM_BOUNDS: Tuple[float, ...] = tuple(
    1e-6 * 2.0 ** i for i in range(27))


class Histogram:
    """Fixed-bucket distribution (e.g. ``serving.latency.total``).

    Log-spaced upper bounds by default (:data:`DEFAULT_HISTOGRAM_BOUNDS`);
    values are dimensionless to the registry — record seconds for
    latencies, rows for batch fills.  Like every metric here the *call
    sites* are collection-gated: while ``enabled()`` is False no library
    code calls :meth:`observe`, so a disabled histogram is zero work.

    Quantiles (p50/p95/p99) are estimated by linear interpolation inside
    the target bucket — resolution is the bucket width (a factor of 2 by
    default), which is the standard Prometheus-histogram tradeoff.
    """

    __slots__ = ("name", "bounds", "counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, name: str, lock: threading.RLock,
                 bounds: Optional[Sequence[float]] = None) -> None:
        self.name = name
        self.bounds = tuple(float(b) for b in
                            (bounds if bounds is not None
                             else DEFAULT_HISTOGRAM_BOUNDS))
        assert list(self.bounds) == sorted(self.bounds), \
            "histogram bounds must be sorted"
        self.counts = [0] * (len(self.bounds) + 1)   # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0
        self._lock = lock

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1] (0.0 when empty)."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = q * self.count
            seen = 0.0
            for i, c in enumerate(self.counts):
                if seen + c >= target and c > 0:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = self.bounds[i] if i < len(self.bounds) else self.max
                    frac = (target - seen) / c
                    return min(lo + frac * (hi - lo), self.max)
                seen += c
            return self.max

    def as_dict(self) -> Dict[str, object]:
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else 0.0,
                "max": self.max,
                "p50": self.quantile(0.50),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99),
                "bounds": list(self.bounds),
                "counts": list(self.counts),
            }


class MetricsRegistry:
    """Named metric store with get-or-create accessors and snapshot/reset."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name, self._lock)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name, self._lock)
            return m

    def timer(self, name: str) -> Timer:
        with self._lock:
            m = self._timers.get(name)
            if m is None:
                m = self._timers[name] = Timer(name, self._lock)
            return m

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        """Get-or-create; ``bounds`` applies only at creation (the first
        caller fixes the bucket layout, like a Prometheus registration)."""
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, self._lock,
                                                       bounds)
            return m

    def snapshot(self) -> Dict[str, Dict]:
        """Point-in-time copy: plain dicts, safe to mutate / serialize."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "timers": {n: t.as_dict() for n, t in self._timers.items()},
                "histograms": {n: h.as_dict()
                               for n, h in self._histograms.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._histograms.clear()


# ---------------------------------------------------------------------------
# global default registry + collection gate

_REGISTRY = MetricsRegistry()
_ENABLED = False


def registry() -> MetricsRegistry:
    """The process-global default registry."""
    return _REGISTRY


def enabled() -> bool:
    """Whether collection is on.  Instrumented call sites check this before
    doing any work; False (the default) means zero fences and zero timing."""
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True
    # installed lazily so `import raft_tpu` never registers global listeners
    from raft_tpu.observability.stage import _install_compile_listener
    _install_compile_listener()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


@contextlib.contextmanager
def collecting(reg: Optional[MetricsRegistry] = None) -> Iterator[MetricsRegistry]:
    """Enable collection for the body, restoring the previous state after.

    Yields the registry metrics are recorded into (the global one — per-call
    registries compose via snapshot diffs, see report.py)."""
    prev = _ENABLED
    enable()
    try:
        yield reg if reg is not None else _REGISTRY
    finally:
        if not prev:
            disable()


def snapshot() -> Dict[str, Dict]:
    """Snapshot of the global registry."""
    return _REGISTRY.snapshot()


def reset() -> None:
    """Reset the global registry (collection gate is unaffected)."""
    _REGISTRY.reset()
