"""Summary statistics (moments).

Reference: cpp/include/raft/stats/ — mean.cuh, meanvar.cuh, stddev.cuh,
minmax.cuh, cov.cuh, histogram.cuh, weighted_mean.cuh, mean_center.cuh
(SURVEY.md §2.8).  Axis convention follows the reference: statistics are
per-column over samples-in-rows unless ``rowwise``.

All of these are single XLA reductions/matmuls; the value kept is the API
names + semantics (sample vs population normalization, centered covariance).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.utils.precision import get_matmul_precision


def mean(data, *, rowwise: bool = False) -> jax.Array:
    """Column (or row) means (reference: stats/mean.cuh)."""
    data = ensure_array(data, "data")
    return jnp.mean(data, axis=1 if rowwise else 0)


def mean_center(data, mu=None, *, rowwise: bool = False) -> jax.Array:
    """Subtract the mean (reference: stats/mean_center.cuh)."""
    data = ensure_array(data, "data")
    if mu is None:
        mu = mean(data, rowwise=rowwise)
    return data - (mu[:, None] if rowwise else mu[None, :])


def mean_add(data, mu, *, rowwise: bool = False) -> jax.Array:
    """Add the mean back (reference: stats/mean_center.cuh meanAdd)."""
    data = ensure_array(data, "data")
    return data + (mu[:, None] if rowwise else mu[None, :])


def meanvar(data, *, sample: bool = True, rowwise: bool = False
            ) -> Tuple[jax.Array, jax.Array]:
    """Mean and variance in one pass (reference: stats/meanvar.cuh).

    ``sample=True`` uses the n-1 normalization, as the reference's flag.
    """
    data = ensure_array(data, "data")
    axis = 1 if rowwise else 0
    mu = jnp.mean(data, axis=axis)
    var = jnp.var(data, axis=axis, ddof=1 if sample else 0)
    return mu, var


def stddev(data, mu=None, *, sample: bool = True, rowwise: bool = False
           ) -> jax.Array:
    """Column standard deviation (reference: stats/stddev.cuh)."""
    data = ensure_array(data, "data")
    axis = 1 if rowwise else 0
    if mu is not None:
        centered = data - jnp.expand_dims(mu, axis)
        n = data.shape[axis]
        denom = n - 1 if sample else n
        return jnp.sqrt(jnp.sum(centered * centered, axis=axis) / denom)
    return jnp.std(data, axis=axis, ddof=1 if sample else 0)


def vars_(data, mu=None, *, sample: bool = True, rowwise: bool = False
          ) -> jax.Array:
    """Column variance (reference: stats/stddev.cuh ``vars``)."""
    s = stddev(data, mu, sample=sample, rowwise=rowwise)
    return s * s


def minmax(data, *, rowwise: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Per-column min and max (reference: stats/minmax.cuh)."""
    data = ensure_array(data, "data")
    axis = 1 if rowwise else 0
    return jnp.min(data, axis=axis), jnp.max(data, axis=axis)


def cov(data, mu=None, *, sample: bool = True, stable: bool = True
        ) -> jax.Array:
    """Covariance matrix (d, d) of row-sample data (n, d)
    (reference: stats/cov.cuh; ``stable`` centers explicitly first)."""
    data = ensure_array(data, "data")
    expects(data.ndim == 2, "cov: 2-D data required")
    n = data.shape[0]
    if mu is None:
        mu = jnp.mean(data, axis=0)
    centered = (data - mu[None, :]).astype(jnp.float32)
    denom = (n - 1) if sample else n
    return jax.lax.dot_general(
        centered.T, centered.T, (((1,), (1,)), ((), ())),
        precision=get_matmul_precision(),
        preferred_element_type=jnp.float32) / denom


def histogram(data, n_bins: int, *, lower: float, upper: float) -> jax.Array:
    """Per-column histogram (reference: stats/histogram.cuh).

    data (n, d) -> counts (n_bins, d); values outside [lower, upper) are
    dropped (the reference's binner clamps via bin index validity).
    """
    data = ensure_array(data, "data")
    if data.ndim == 1:
        data = data[:, None]
    width = (upper - lower) / n_bins
    bins = jnp.floor((data - lower) / width).astype(jnp.int32)
    valid = (bins >= 0) & (bins < n_bins)
    bins = jnp.clip(bins, 0, n_bins - 1)
    one_hot = jax.nn.one_hot(bins, n_bins, dtype=jnp.int32, axis=0)
    return jnp.sum(one_hot * valid[None, :, :].astype(jnp.int32), axis=1)


def weighted_mean(data, weights, *, rowwise: bool = True) -> jax.Array:
    """Weight-averaged rows or columns (reference: stats/weighted_mean.cuh:
    row_weighted_mean averages along rows)."""
    data = ensure_array(data, "data")
    weights = ensure_array(weights, "weights")
    axis = 1 if rowwise else 0
    w = jnp.expand_dims(weights, 1 - axis)
    return jnp.sum(data * w, axis=axis) / jnp.sum(weights)


def row_weighted_mean(data, weights) -> jax.Array:
    return weighted_mean(data, weights, rowwise=True)


def col_weighted_mean(data, weights) -> jax.Array:
    return weighted_mean(data, weights, rowwise=False)
