"""Clustering evaluation metrics.

Reference: cpp/include/raft/stats/ — adjusted_rand_index.cuh, rand_index.cuh,
completeness_score.cuh, homogeneity_score.cuh, v_measure.cuh,
mutual_info_score.cuh, entropy.cuh, contingency_matrix.cuh,
silhouette_score.cuh (incl. batched), dispersion.cuh (SURVEY.md §2.8).

All metrics reduce through the contingency matrix — one ``segment_sum``-style
scatter on device (the reference builds it with a custom kernel,
contingency_matrix.cuh) — after which the formulas are tiny reductions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType
from raft_tpu.core.outputs import raw


def contingency_matrix(y_true, y_pred, *, n_classes_true: int,
                       n_classes_pred: int) -> jax.Array:
    """Joint label-count matrix (reference: stats/contingency_matrix.cuh).

    Class counts must be static for XLA; the reference's
    ``getInputClassCardinality`` pre-pass maps to the caller supplying them
    (or via int(max)+1 outside jit).
    """
    y_true = ensure_array(y_true, "y_true").astype(jnp.int32)
    y_pred = ensure_array(y_pred, "y_pred").astype(jnp.int32)
    flat = y_true * n_classes_pred + y_pred
    counts = jnp.zeros(n_classes_true * n_classes_pred, jnp.int32).at[
        flat].add(1)
    return counts.reshape(n_classes_true, n_classes_pred)


def _entropy_from_counts(counts: jax.Array) -> jax.Array:
    n = jnp.sum(counts)
    p = counts / jnp.maximum(n, 1)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)), 0.0))


def entropy(labels, *, n_classes: int) -> jax.Array:
    """Shannon entropy of a labeling, in nats (reference: stats/entropy.cuh)."""
    labels = ensure_array(labels, "labels").astype(jnp.int32)
    counts = jnp.zeros(n_classes, jnp.int32).at[labels].add(1)
    return _entropy_from_counts(counts)


def mutual_info_score(y_true, y_pred, *, n_classes_true: int,
                      n_classes_pred: int) -> jax.Array:
    """Mutual information between two labelings
    (reference: stats/mutual_info_score.cuh)."""
    cm = contingency_matrix(y_true, y_pred,
                            n_classes_true=n_classes_true,
                            n_classes_pred=n_classes_pred).astype(jnp.float64
                            if jax.config.jax_enable_x64 else jnp.float32)
    n = jnp.sum(cm)
    pij = cm / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = pij / jnp.maximum(pi * pj, 1e-30)
    return jnp.sum(jnp.where(pij > 0,
                             pij * jnp.log(jnp.maximum(ratio, 1e-30)), 0.0))


def homogeneity_score(y_true, y_pred, *, n_classes_true: int,
                      n_classes_pred: int) -> jax.Array:
    """h = 1 - H(C|K)/H(C) (reference: stats/homogeneity_score.cuh)."""
    mi = mutual_info_score(y_true, y_pred, n_classes_true=n_classes_true,
                           n_classes_pred=n_classes_pred)
    h_c = entropy(y_true, n_classes=n_classes_true)
    return jnp.where(h_c > 0, mi / h_c, 1.0)


def completeness_score(y_true, y_pred, *, n_classes_true: int,
                       n_classes_pred: int) -> jax.Array:
    """c = 1 - H(K|C)/H(K) (reference: stats/completeness_score.cuh)."""
    return homogeneity_score(y_pred, y_true,
                             n_classes_true=n_classes_pred,
                             n_classes_pred=n_classes_true)


def v_measure(y_true, y_pred, *, n_classes_true: int, n_classes_pred: int,
              beta: float = 1.0) -> jax.Array:
    """Harmonic mean of homogeneity and completeness
    (reference: stats/v_measure.cuh)."""
    h = homogeneity_score(y_true, y_pred, n_classes_true=n_classes_true,
                          n_classes_pred=n_classes_pred)
    c = completeness_score(y_true, y_pred, n_classes_true=n_classes_true,
                           n_classes_pred=n_classes_pred)
    denom = beta * h + c
    return jnp.where(denom > 0, (1 + beta) * h * c / denom, 0.0)


def rand_index(y_true, y_pred) -> jax.Array:
    """Rand index via pair agreement (reference: stats/rand_index.cuh)."""
    y_true = ensure_array(y_true, "y_true")
    y_pred = ensure_array(y_pred, "y_pred")
    same_t = y_true[:, None] == y_true[None, :]
    same_p = y_pred[:, None] == y_pred[None, :]
    agree = (same_t == same_p).astype(jnp.float32)
    n = y_true.shape[0]
    total = n * (n - 1) / 2
    agree_pairs = (jnp.sum(agree) - n) / 2  # remove diagonal
    return agree_pairs / total


def adjusted_rand_index(y_true, y_pred, *, n_classes_true: int,
                        n_classes_pred: int) -> jax.Array:
    """ARI from the contingency matrix
    (reference: stats/adjusted_rand_index.cuh)."""
    cm = contingency_matrix(y_true, y_pred,
                            n_classes_true=n_classes_true,
                            n_classes_pred=n_classes_pred).astype(jnp.float32)
    n = jnp.sum(cm)

    def comb2(x):
        return x * (x - 1) / 2

    sum_ij = jnp.sum(comb2(cm))
    a = jnp.sum(comb2(jnp.sum(cm, axis=1)))
    b = jnp.sum(comb2(jnp.sum(cm, axis=0)))
    expected = a * b / jnp.maximum(comb2(n), 1.0)
    max_index = (a + b) / 2
    denom = max_index - expected
    return jnp.where(jnp.abs(denom) > 1e-12, (sum_ij - expected) / denom, 1.0)


def silhouette_score(
    X,
    labels,
    *,
    n_clusters: int,
    metric: int = DistanceType.L2Expanded,
    chunk: int = 0,
) -> jax.Array:
    """Mean silhouette coefficient (reference: stats/silhouette_score.cuh;
    the ``chunk`` parameter mirrors ``silhouette_score_batched`` — row tiles
    of the pairwise matrix are processed at a time).
    """
    X = ensure_array(X, "X")
    labels = ensure_array(labels, "labels").astype(jnp.int32)
    n = X.shape[0]
    chunk = chunk or n
    one_hot = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)  # (n, k)
    counts = jnp.sum(one_hot, axis=0)                                # (k,)

    def tile_scores(xt, lt):
        # distances of the row tile against the FULL dataset (columns are
        # never padded, so sums are exact)
        d = raw(pairwise_distance)(xt, X, metric)                # (c, n)
        sums = d @ one_hot                                  # (c, k)
        own = jnp.take_along_axis(sums, lt[:, None], axis=1)[:, 0]
        own_count = counts[lt]
        a = own / jnp.maximum(own_count - 1, 1)
        other_mean = sums / jnp.maximum(counts[None, :], 1)
        other_mean = other_mean.at[jnp.arange(xt.shape[0]), lt].set(jnp.inf)
        b = jnp.min(other_mean, axis=1)
        s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30)
        # singleton clusters have s = 0 by convention
        return jnp.where(own_count <= 1, 0.0, s)

    n_chunks = -(-n // chunk)
    scores = jnp.concatenate(
        [tile_scores(X[i * chunk:(i + 1) * chunk],
                     labels[i * chunk:(i + 1) * chunk])
         for i in range(n_chunks)])
    return jnp.mean(scores)


def dispersion(centroids, cluster_sizes, global_centroid=None
               ) -> jax.Array:
    """Between-cluster dispersion (reference: stats/dispersion.cuh):
    sqrt(sum_k n_k ||mu_k - mu||^2)."""
    centroids = ensure_array(centroids, "centroids")
    cluster_sizes = ensure_array(cluster_sizes, "cluster_sizes")
    if global_centroid is None:
        w = cluster_sizes.astype(jnp.float32)
        global_centroid = (jnp.sum(centroids * w[:, None], axis=0)
                           / jnp.sum(w))
    diff = centroids - global_centroid[None, :]
    return jnp.sqrt(jnp.sum(cluster_sizes * jnp.sum(diff * diff, axis=1)))
