"""Regression / classification / information metrics.

Reference: cpp/include/raft/stats/ — accuracy.cuh, r2_score.cuh,
regression_metrics.cuh, information_criterion.cuh, kl_divergence.cuh,
trustworthiness_score.cuh (SURVEY.md §2.8).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType
from raft_tpu.core.outputs import raw


def accuracy(predictions, ref_predictions) -> jax.Array:
    """Fraction of exact matches (reference: stats/accuracy.cuh)."""
    predictions = ensure_array(predictions, "predictions")
    ref_predictions = ensure_array(ref_predictions, "ref_predictions")
    return jnp.mean((predictions == ref_predictions).astype(jnp.float32))


def r2_score(y, y_hat) -> jax.Array:
    """Coefficient of determination (reference: stats/r2_score.cuh)."""
    y = ensure_array(y, "y").astype(jnp.float32)
    y_hat = ensure_array(y_hat, "y_hat").astype(jnp.float32)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    ss_res = jnp.sum((y - y_hat) ** 2)
    return 1.0 - ss_res / ss_tot


def regression_metrics(predictions, ref_predictions
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(mean_abs_error, mean_squared_error, median_abs_error)
    (reference: stats/regression_metrics.cuh)."""
    predictions = ensure_array(predictions, "predictions").astype(jnp.float32)
    ref_predictions = ensure_array(ref_predictions,
                                   "ref_predictions").astype(jnp.float32)
    diff = predictions - ref_predictions
    return (jnp.mean(jnp.abs(diff)),
            jnp.mean(diff * diff),
            jnp.median(jnp.abs(diff)))


class IC_Type:
    """Reference: stats/information_criterion.cuh ``IC_Type`` enum."""

    AIC = 0
    AICc = 1
    BIC = 2


def information_criterion_batched(loglike, ic_type: int, n_params: int,
                                  n_samples: int) -> jax.Array:
    """Batched AIC/AICc/BIC from log-likelihoods
    (reference: stats/information_criterion.cuh)."""
    loglike = ensure_array(loglike, "loglike").astype(jnp.float32)
    base = -2.0 * loglike
    if ic_type == IC_Type.AIC:
        penalty = 2.0 * n_params
    elif ic_type == IC_Type.AICc:
        penalty = (2.0 * n_params
                   + 2.0 * n_params * (n_params + 1)
                   / max(n_samples - n_params - 1, 1))
    elif ic_type == IC_Type.BIC:
        penalty = jnp.log(jnp.float32(n_samples)) * n_params
    else:
        raise ValueError(f"unknown IC type {ic_type}")
    return base + penalty


def kl_divergence(modeled_pdf, observed_pdf) -> jax.Array:
    """Scalar KL divergence between two densities
    (reference: stats/kl_divergence.cuh)."""
    p = ensure_array(modeled_pdf, "modeled_pdf").astype(jnp.float32)
    q = ensure_array(observed_pdf, "observed_pdf").astype(jnp.float32)
    term = jnp.where((p > 0) & (q > 0),
                     p * jnp.log(jnp.maximum(p, 1e-30)
                                 / jnp.maximum(q, 1e-30)), 0.0)
    return jnp.sum(term)


def trustworthiness_score(X, X_embedded, n_neighbors: int,
                          *, metric: int = DistanceType.L2SqrtExpanded
                          ) -> jax.Array:
    """Trustworthiness of a low-dimensional embedding
    (reference: stats/trustworthiness_score.cuh): penalizes points that are
    close in the embedding but far in the original space.
    """
    X = ensure_array(X, "X")
    X_embedded = ensure_array(X_embedded, "X_embedded")
    n = X.shape[0]
    expects(n_neighbors < n // 2,
            "trustworthiness: n_neighbors must be < n/2")

    d_orig = raw(pairwise_distance)(X, X, metric)
    d_emb = raw(pairwise_distance)(X_embedded, X_embedded, metric)
    big = jnp.max(d_orig) + 1.0
    d_orig = d_orig.at[jnp.arange(n), jnp.arange(n)].set(big)
    d_emb = d_emb.at[jnp.arange(n), jnp.arange(n)].set(big)

    # rank of each point j in i's original-space neighbor ordering
    orig_order = jnp.argsort(d_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32).at[
        jnp.arange(n)[:, None], orig_order].set(
        jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (n, n)))
    _, emb_nn = jax.lax.top_k(-d_emb, n_neighbors)
    emb_ranks = jnp.take_along_axis(ranks, emb_nn, axis=1)
    penalty = jnp.sum(jnp.maximum(emb_ranks - n_neighbors + 1, 0))
    norm = 2.0 / (n * n_neighbors * (2.0 * n - 3.0 * n_neighbors - 1.0))
    return 1.0 - norm * penalty
