"""Statistics primitives and model-evaluation metrics.

Reference: cpp/include/raft/stats/ (50 files, SURVEY.md §2.8) — moments
(mean/var/stddev/minmax/cov/histogram/weighted means), clustering metrics
(ARI, (adjusted) rand index, homogeneity/completeness/v-measure, mutual info,
entropy, silhouette, dispersion), regression/classification metrics, and
information criteria.
"""

from raft_tpu.stats.moments import (  # noqa: F401
    mean,
    mean_center,
    mean_add,
    meanvar,
    stddev,
    vars_,
    minmax,
    cov,
    histogram,
    weighted_mean,
    row_weighted_mean,
    col_weighted_mean,
)
from raft_tpu.stats.cluster_metrics import (  # noqa: F401
    contingency_matrix,
    entropy,
    mutual_info_score,
    homogeneity_score,
    completeness_score,
    v_measure,
    rand_index,
    adjusted_rand_index,
    silhouette_score,
    dispersion,
)
from raft_tpu.stats.regression_metrics import (  # noqa: F401
    IC_Type,
    accuracy,
    r2_score,
    regression_metrics,
    information_criterion_batched,
    kl_divergence,
    trustworthiness_score,
)
