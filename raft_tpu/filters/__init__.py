"""Filtered & multi-tenant search — per-query admission bitsets.

Reference: cpp/include/raft/neighbors/sample_filter{,_types}.hpp (the
``sample_filter`` hook on ivf_pq/ivf_flat search).  See docs/api.md,
"Filtered search & tenancy" for the bitset layout, the kernel admission
seam, and the selectivity cost model.
"""

from raft_tpu.filters.bitset import (  # noqa: F401
    BITS_PER_WORD,
    SampleFilter,
    as_filter,
    group_admission_words,
    n_words_for,
    pack_mask,
    query_bits,
    query_filter_words,
    unpack_words,
)
from raft_tpu.filters.tenant import TenantFilter  # noqa: F401
from raft_tpu.filters import hybrid  # noqa: F401
from raft_tpu.filters.hybrid import candidates_to_filter  # noqa: F401
