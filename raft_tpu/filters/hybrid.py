"""Hybrid dense+sparse retrieval — lexical candidates as an admission set.

The classic two-tower hybrid: a sparse (lexical/BM25-like) pass over CSR
term vectors proposes per-query candidate ids, and the dense IVF-PQ scan
re-ranks *only those* — expressed here as a bitset filter, so the fused
kernels do the intersection for free through the same admission seam as
predicate filters.  The dense scan stays at full fidelity over the
admitted set, and the result is bit-identical to brute-forcing the
admitted ids (the filtered-parity contract).
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.filters.bitset import SampleFilter, n_words_for
from raft_tpu.sparse.formats import CsrMatrix


def candidates_to_filter(sparse_ids, n_rows: int) -> SampleFilter:
    """Per-query candidate id lists -> admission bitset.

    ``sparse_ids`` is (nq, k_sparse) int; negative ids (select_k padding
    when a query matched fewer than k_sparse rows) are skipped.
    """
    ids = np.asarray(sparse_ids, np.int64)
    expects(ids.ndim == 2, "hybrid: sparse_ids must be (nq, k_sparse)")
    nq = ids.shape[0]
    words = np.zeros((nq, n_words_for(n_rows)), np.uint32)
    for q in range(nq):
        row = ids[q]
        row = row[(row >= 0) & (row < n_rows)]
        np.bitwise_or.at(words[q], row >> 5,
                         np.uint32(1) << (row & 31).astype(np.uint32))
    return SampleFilter.from_words(words.view(np.int32), n_rows)


def search(res, params, index, queries, k: int, *,
           sparse_queries: CsrMatrix, sparse_database: CsrMatrix,
           k_sparse: int, sparse_metric: int = None
           ) -> Tuple[jax.Array, jax.Array]:
    """Hybrid search: sparse lexical candidate generation fused into the
    dense IVF-PQ scan as a per-query filter.

    ``sparse_queries``/``sparse_database`` are the lexical (e.g. tf-idf)
    CSR representations of the same corpus the dense index was built
    from — database row r must be dense id r.  ``k_sparse`` is the
    candidate budget per query (the selectivity knob: recall of the
    hybrid result is bounded by sparse candidate recall).
    """
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.sparse.neighbors import brute_force_knn_sparse

    if sparse_metric is None:
        sparse_metric = DistanceType.InnerProduct
    _, cand = brute_force_knn_sparse(sparse_queries, sparse_database,
                                     k_sparse, metric=sparse_metric)
    filt = candidates_to_filter(np.asarray(cand),
                                int(sparse_database.shape[0]))
    return ivf_pq.search(res, params, index, queries, k, filter=filt)
