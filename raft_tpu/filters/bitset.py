"""Per-query admission bitsets — the ``SampleFilter`` predicate layer.

Reference: cpp/include/raft/neighbors/sample_filter_types.hpp — the
``bitset_filter`` a caller attaches to ivf_pq/ivf_flat search so every
(query, candidate) pair is admitted or rejected *inside* the scan, not by
a post-hoc pass that would starve k.  TPU translation: the filter is a
dense per-query bitset over row ids, packed 32 ids per int32 word, shape
``(nq, n_words)`` with ``n_words = ceil(n_rows / 32)``.  Packed words are
what streams through VMEM: the Pallas scan kernels gather one word per
32 candidates and unpack with a shift/mask, so admission costs ~1 bit of
HBM traffic per candidate instead of 32.

The admission seam reuses the tombstone seam (PRs 7/8/10): an
inadmissible candidate folds to the finite ``_ACC_WORST`` distance and
id -1 *before* top-k / the fused windowed merge, so filtered results are
bit-identical to a post-hoc filtered exact scan at full probe — the same
kernel computes the same distances; folding a row to worst before
selection is equivalent to removing it from the candidate set.

Filters are **data, not shape**: ``n_words`` depends only on the index's
id bound (static per generation), never on filter contents, so varying
per-query filters at a fixed serving bucket re-enter the same compiled
executable (0 steady-state recompiles — asserted by the serving tier).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects

# ids per packed word; int32 matches the repo's packed-lane idiom
# (ops/pq_code_scan_pallas.pack_code_lanes) and the 32-row list
# alignment (_LIST_ALIGN) so capacity-axis packing never straddles rows
BITS_PER_WORD = 32


def n_words_for(n_rows: int) -> int:
    """Packed word count covering ``n_rows`` ids (≥ 1 so an empty bound
    still has a well-formed (nq, 1) buffer)."""
    return max(1, -(-int(n_rows) // BITS_PER_WORD))


@dataclasses.dataclass(frozen=True)
class SampleFilter:
    """Dense per-query admission bitset over row ids.

    ``words[q, i >> 5] >> (i & 31) & 1`` is the admission bit of id ``i``
    for query ``q``.  Bits at or beyond ``n_rows`` are ignored by every
    consumer (candidates carry in-range ids or the -1/tombstone
    sentinel, which folds before the filter is consulted).
    """

    words: jax.Array        # (nq, n_words) int32 packed admission bits
    n_rows: int             # id bound the bitset covers

    @property
    def nq(self) -> int:
        return int(self.words.shape[0])

    @property
    def n_words(self) -> int:
        return int(self.words.shape[1])

    def admitted_counts(self) -> np.ndarray:
        """Per-query admitted-id count (host-side, for observability and
        the matched-budget recall gate in bench)."""
        w = np.asarray(self.words).view(np.uint32)
        bits = np.unpackbits(w.view(np.uint8), axis=-1,
                             count=self.n_words * BITS_PER_WORD,
                             bitorder="little").reshape(self.nq, -1)
        return bits[:, : self.n_rows].sum(axis=1).astype(np.int64)

    @staticmethod
    def from_words(words, n_rows: int) -> "SampleFilter":
        words = jnp.asarray(words, jnp.int32)
        expects(words.ndim == 2, "SampleFilter: words must be (nq, n_words)")
        expects(words.shape[1] >= n_words_for(n_rows),
                "SampleFilter: words too narrow for n_rows")
        return SampleFilter(words=words, n_rows=int(n_rows))

    @staticmethod
    def from_mask(mask) -> "SampleFilter":
        """Build from a dense (nq, n_rows) boolean admission mask."""
        mask = jnp.asarray(mask)
        expects(mask.ndim == 2, "SampleFilter: mask must be (nq, n_rows)")
        n_rows = int(mask.shape[1])
        return SampleFilter(words=pack_mask(mask), n_rows=n_rows)

    @staticmethod
    def from_ids(ids: Sequence, n_rows: int, nq: int = 1) -> "SampleFilter":
        """Admit exactly ``ids`` (host-side build; same set for each of
        ``nq`` queries).  The hybrid path and tests use this."""
        w = np.zeros(n_words_for(n_rows), np.uint32)
        arr = np.asarray(ids, np.int64).ravel()
        arr = arr[(arr >= 0) & (arr < n_rows)]
        np.bitwise_or.at(w, arr >> 5, np.uint32(1) << (arr & 31).astype(np.uint32))
        words = jnp.asarray(np.broadcast_to(w.view(np.int32), (nq, w.size)))
        return SampleFilter(words=words, n_rows=int(n_rows))

    @staticmethod
    def all_rows(n_rows: int, nq: int = 1) -> "SampleFilter":
        """Admit everything — the identity filter (all-ones words)."""
        words = jnp.full((nq, n_words_for(n_rows)), -1, jnp.int32)
        return SampleFilter(words=words, n_rows=int(n_rows))

    def intersect(self, other: "SampleFilter") -> "SampleFilter":
        """AND-compose two filters (e.g. tenant namespace ∧ predicate)."""
        expects(self.n_rows == other.n_rows,
                "SampleFilter: intersect over mismatched id bounds")
        return SampleFilter(words=self.words & other.words,
                            n_rows=self.n_rows)


def pack_mask(mask) -> jax.Array:
    """Pack a (nq, n) boolean mask into (nq, ceil(n/32)) int32 words,
    little-endian within each word (bit b of word w covers id 32*w+b)."""
    mask = jnp.asarray(mask, jnp.int32)
    nq, n = mask.shape
    nw = n_words_for(n)
    pad = nw * BITS_PER_WORD - n
    if pad:
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    m = mask.reshape(nq, nw, BITS_PER_WORD)
    shifts = jnp.arange(BITS_PER_WORD, dtype=jnp.int32)
    # uint32 intermediate: bit 31 must set the sign bit, not overflow
    w = jnp.sum(m.astype(jnp.uint32) << shifts[None, None, :], axis=-1)
    return w.astype(jnp.int32)


def query_bits(words: jax.Array, qids: jax.Array, ids: jax.Array
               ) -> jax.Array:
    """Gather admission bits — the XLA twin of the in-kernel unpack.

    ``words`` is (nq, n_words) int32; ``qids`` maps each row of ``ids``
    to its query (any shape broadcastable against ``ids`` minus the last
    axis); ``ids`` holds candidate ids (negative = padding/tombstone —
    reported inadmissible here, though every caller folds them first).
    Returns an int32 0/1 array shaped like ``ids``.
    """
    ids = ids.astype(jnp.int32)
    safe = jnp.maximum(ids, 0)
    rows = words[qids]                       # ids.shape[:-1] + (n_words,)
    w = jnp.take_along_axis(rows, safe >> 5, axis=-1, mode="clip")
    bit = (w >> (safe & 31)) & 1
    # ids the bitset does not cover are NOT admitted: the filter declares
    # the id space, so an out-of-range id is outside every predicate
    cov = words.shape[-1] * BITS_PER_WORD
    return jnp.where((ids >= 0) & (ids < cov), bit, 0).astype(jnp.int32)


def group_admission_words(filter_words: jax.Array, group_list: jax.Array,
                          slot_pairs: jax.Array, list_indices: jax.Array,
                          n_probes: int, P: int) -> jax.Array:
    """Admission words for the grouped scan, in **list-slot order**.

    The grouped kernels iterate candidates positionally along a list's
    capacity axis, so the per-(slot, candidate) admission bit must be
    laid out the same way: output is ``(n_groups, GROUP, Wc)`` int32
    with ``Wc = ceil(cap / 32)`` — word ``w`` of slot ``s`` in group
    ``g`` packs the bits of candidates ``32w..32w+31`` of list
    ``group_list[g]`` for the query owning ``slot_pairs[g, s]``.

    Empty slots (pair == ``P``) get query 0's bits; they never surface
    (the scatter drops them, the fused one-hot zero-masks them).
    Padding/tombstone candidates (id < 0) pack a 0 bit, composing the
    filter with the tombstone seam in one word.
    """
    n_groups = group_list.shape[0]
    cap = list_indices.shape[1]
    ids = list_indices[group_list]                     # (n_groups, cap)
    pairs = jnp.minimum(slot_pairs, P - 1) if P > 0 else slot_pairs
    qids = (pairs // max(1, n_probes)).astype(jnp.int32)   # (n_groups, GROUP)
    rows = filter_words[qids]                  # (n_groups, GROUP, n_words)
    safe = jnp.maximum(ids, 0).astype(jnp.int32)           # (n_groups, cap)
    w = jnp.take_along_axis(
        rows, jnp.broadcast_to((safe >> 5)[:, None, :], rows.shape[:2] + (cap,)),
        axis=-1, mode="clip")                     # (n_groups, GROUP, cap)
    bit = (w >> (safe & 31)[:, None, :]) & 1
    cov = filter_words.shape[-1] * BITS_PER_WORD
    bit = jnp.where(((ids >= 0) & (ids < cov))[:, None, :], bit, 0)
    return pack_mask(bit.reshape(-1, cap)).reshape(
        n_groups, slot_pairs.shape[1], -1)


def unpack_words(words: jax.Array, n: int) -> jax.Array:
    """Unpack packed words back to an int32 0/1 mask over ``n`` ids along
    the last axis — shared by the XLA twins and the kernel-side unpack
    (which runs the same shift under Pallas)."""
    shifts = jnp.arange(BITS_PER_WORD, dtype=jnp.int32)
    bits = (words[..., :, None] >> shifts) & 1
    return bits.reshape(words.shape[:-1] + (-1,))[..., :n]


def query_filter_words(f: "FilterLike", nq: int, site: str
                       ) -> Optional[jax.Array]:
    """Normalize a public ``search(filter=)`` argument to per-query packed
    words (nq, n_words) int32, or None when unfiltered.

    Accepts a :class:`SampleFilter` (single-query filters broadcast to
    the batch) or a dense (nq, n_rows) boolean admission mask.  This is
    the ONE seam every index type's search runs its filter through, so
    the accepted forms and the broadcast rule cannot drift between
    ivf_pq / ivf_flat / cagra / brute_force.
    """
    if f is None:
        return None
    if not isinstance(f, SampleFilter):
        arr = jnp.asarray(f)
        expects(arr.ndim == 2 and arr.dtype == jnp.bool_,
                f"{site}: filter must be a SampleFilter or an "
                "(nq, n_rows) bool mask")
        f = SampleFilter.from_mask(arr)
    expects(f.nq in (1, nq),
            f"{site}: filter covers {f.nq} queries, batch has {nq}")
    w = f.words
    if f.nq == 1 and nq != 1:
        w = jnp.broadcast_to(w, (nq, w.shape[1]))
    return w


FilterLike = Union[SampleFilter, jax.Array, np.ndarray, None]


def as_filter(f: FilterLike, n_rows: int) -> Optional[SampleFilter]:
    """Normalize a ``filter=`` argument: SampleFilter passes through
    (bound-checked), a raw 2-D bool/int mask is packed, None is None."""
    if f is None:
        return None
    if isinstance(f, SampleFilter):
        expects(f.n_words >= n_words_for(n_rows),
                "filter: bitset narrower than the index id bound")
        return f
    arr = jnp.asarray(f)
    expects(arr.ndim == 2, "filter: expected SampleFilter or (nq, n) mask")
    if arr.dtype == jnp.int32 and arr.shape[1] == n_words_for(n_rows) \
            and arr.shape[1] != n_rows:
        return SampleFilter.from_words(arr, n_rows)
    expects(arr.shape[1] == n_rows,
            "filter: mask width must equal the index id bound")
    return SampleFilter.from_mask(arr)
