"""Tenant namespaces — disjoint id ranges compiled into admission bitsets.

Multi-tenancy over one shared index: each tenant owns a contiguous,
disjoint id range ``[lo, hi)`` (the ingest path assigns source ids per
namespace), and a query tagged ``tenant=`` must only ever surface ids
from its own range.  ``TenantFilter`` compiles a namespace into a
:class:`~raft_tpu.filters.bitset.SampleFilter` consumed by the same
admission seam as any predicate filter, so isolation costs nothing the
generic filter path doesn't already pay — and composes with predicate
filters by word-wise AND (:meth:`SampleFilter.intersect`).

The declared namespaces are also an *integrity contract*:
``integrity.verify(index, namespaces=...)`` checks the ranges are
disjoint and every live id falls inside its declared range, raising a
typed :class:`IntegrityError` naming the violating (tenant, id).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.filters.bitset import (
    BITS_PER_WORD,
    SampleFilter,
    n_words_for,
)


@dataclasses.dataclass(frozen=True)
class TenantFilter:
    """Per-tenant id-range namespaces over one index.

    ``ranges`` maps tenant name -> half-open ``(lo, hi)`` id range.
    Ranges must be disjoint (validated at construction — overlap is a
    namespacing bug, not a runtime condition).
    """

    ranges: Mapping[str, Tuple[int, int]]
    n_rows: int

    def __post_init__(self):
        spans = []
        for t, (lo, hi) in self.ranges.items():
            expects(0 <= lo <= hi,
                    f"TenantFilter: bad range for tenant {t!r}: ({lo}, {hi})")
            spans.append((int(lo), int(hi), t))
        spans.sort()
        for (lo0, hi0, t0), (lo1, hi1, t1) in zip(spans, spans[1:]):
            expects(hi0 <= lo1,
                    f"TenantFilter: ranges of tenants {t0!r} and {t1!r} "
                    f"overlap ([{lo0},{hi0}) vs [{lo1},{hi1}))")

    @property
    def tenants(self):
        return tuple(self.ranges.keys())

    def range_of(self, tenant: str) -> Tuple[int, int]:
        expects(tenant in self.ranges,
                f"TenantFilter: unknown tenant {tenant!r}")
        lo, hi = self.ranges[tenant]
        return int(lo), int(hi)

    def words_for(self, tenant: str) -> np.ndarray:
        """One packed word row admitting exactly ``[lo, hi)`` — host-side
        numpy, cached per tenant (ranges are static per generation)."""
        key = (tenant, self.n_rows)
        cache = _WORD_CACHE
        if key not in cache:
            lo, hi = self.range_of(tenant)
            cache[key] = _range_words(lo, min(hi, self.n_rows), self.n_rows)
        return cache[key]

    def filter_for(self, tenant: str, nq: int = 1) -> SampleFilter:
        """The tenant's namespace as a per-query admission bitset."""
        w = self.words_for(tenant)
        words = jnp.asarray(np.broadcast_to(w, (nq, w.size)))
        return SampleFilter(words=words, n_rows=self.n_rows)

    def owner_of(self, i: int):
        """The tenant whose range holds id ``i``, or None (verify uses
        this to name the violating pair)."""
        for t, (lo, hi) in self.ranges.items():
            if lo <= i < hi:
                return t
        return None


# (tenant, n_rows) -> packed words; tiny (one row per tenant), lives for
# the process — namespaces are static per index generation
_WORD_CACHE: Dict[Tuple[str, int], np.ndarray] = {}


def _range_words(lo: int, hi: int, n_rows: int) -> np.ndarray:
    """Packed int32 words admitting exactly ids in ``[lo, hi)``."""
    nw = n_words_for(n_rows)
    idx = np.arange(nw * BITS_PER_WORD, dtype=np.int64)
    bits = ((idx >= lo) & (idx < hi)).astype(np.uint8)
    return np.packbits(bits, bitorder="little").view(np.int32)
