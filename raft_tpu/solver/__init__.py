"""Solvers — linear assignment (LAP).

Reference surface: ``raft::solver`` (`/root/reference/cpp/include/raft/solver/
linear_assignment.cuh`, legacy alias ``lap/lap.cuh``).
"""

from .linear_assignment import (  # noqa: F401
    LapSolution,
    LinearAssignmentProblem,
    solve,
)

__all__ = ["LapSolution", "LinearAssignmentProblem", "solve"]
