"""Linear assignment problem (LAP) solver.

API parity with the reference's ``raft::solver::LinearAssignmentProblem``
(``/root/reference/cpp/include/raft/solver/linear_assignment.cuh:53`` — class,
``:118`` — ``solve``, ``:148-187`` — dual-vector / objective getters; legacy
alias ``lap/lap.cuh``).  The reference ports Date & Nagi's GPU alternating-tree
Hungarian algorithm; a tree grown one augmenting path at a time is a poor fit
for XLA (data-dependent frontier, scalar host loop per step), so the TPU-native
design is **Bertsekas' auction algorithm with epsilon-scaling**:

- every unassigned row bids for its best column in parallel (one dense
  ``(n, n)`` value matrix + ``lax.top_k`` — MXU/VPU-friendly, no trees);
- bids resolve with a single scatter-max per round;
- the whole solve is a fixed ``lax.while_loop`` nest under ``jit`` (no
  data-dependent Python control flow), batched via ``vmap`` to mirror the
  reference's ``batchsize`` sub-problem axis.

Costs are quantized onto an integer grid scaled by ``(n + 1)`` so the final
epsilon = 1 pass is provably optimal for the quantized problem (the classic
``eps < 1/n`` termination condition); float64 holds the grid exactly.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mdarray import ensure_array
from ..core.error import expects

_EPS_FACTOR = 7.0  # epsilon divisor per scaling phase (Bertsekas suggests 4-10)


def _quant_for(n: int) -> float:
    """Integer grid resolution for an n x n problem.

    Benefits live on multiples of (n+1) up to QUANT*(n+1); encoded bids
    carry the bidder id in the low bits (enc = bid*n + rank) and bids can
    exceed the max benefit by up to eps0 = QUANT*(n+1)/2, so exact float64
    integer arithmetic needs 1.5 * QUANT * (n+1) * n < 2^53.  QUANT adapts
    downward for large n (capped at 2^30); quantization error is
    <= n / (2*QUANT) of the cost range (~1e-6 at n=2048).
    """
    import math
    lim = 2.0 ** 52 / (float(n) * (n + 1))
    return min(2.0 ** 30, 2.0 ** math.floor(math.log2(lim)))


class LapSolution(NamedTuple):
    """Solution of one (batch of) linear assignment problem(s).

    Mirrors the reference getters: ``row_assignments``/``col_assignments``
    (linear_assignment.cuh:118 ``solve`` outputs), ``row_duals``/``col_duals``
    (``getRowDualVector``/``getColDualVector`` :148,159) and the
    primal/dual objective values (:170,181).
    """

    row_assignment: jax.Array   # (..., n) column assigned to each row
    col_assignment: jax.Array   # (..., n) row assigned to each column
    row_duals: jax.Array        # (..., n) u_i with u_i + v_j <= c_ij
    col_duals: jax.Array        # (..., n) v_j
    obj_primal: jax.Array       # (...,) sum of assigned costs
    obj_dual: jax.Array         # (...,) sum(u) + sum(v)


def _num_phases(eps0: float) -> int:
    """Static epsilon-scaling phase count: eps0 down to 1."""
    import math
    return max(1, int(math.ceil(math.log(max(eps0, 2.0))
                                / math.log(_EPS_FACTOR))) + 1)


def _auction_phase(benefit, prices, eps, n):
    """One epsilon phase: auction rounds until every row is assigned.

    benefit: (n, n) integer-valued float64, prices: (n,) float64.
    Returns (assignment (n,), owner (n,), prices (n,)).
    """
    neg = jnp.int32(-1)
    init = (jnp.full((n,), neg), jnp.full((n,), neg), prices, jnp.int32(0))

    # safety cap: with integer eps >= 1 each round raises some price by >= eps,
    # so rounds are bounded; the cap only guards against numerical surprise.
    max_rounds = jnp.int32(16 * n + 64)

    def cond(state):
        assign, _, _, it = state
        return jnp.logical_and(jnp.any(assign == neg), it < max_rounds)

    def body(state):
        assign, owner, p, it = state
        unassigned = assign == neg                       # (n,) rows
        values = benefit - p[None, :]                    # (n, n)
        if n == 1:
            j1 = jnp.zeros((1,), jnp.int32)
            w2 = values[:, 0]  # no competitor: bid raises own price by eps
        else:
            top2, idx2 = jax.lax.top_k(values, 2)
            j1 = idx2[:, 0]
            w2 = top2[:, 1]
        # bid = p[j1] + w1 - w2 + eps  ==  benefit[i, j1] - w2 + eps
        bid = jnp.take_along_axis(benefit, j1[:, None], axis=1)[:, 0] \
            - w2 + eps
        # resolve: per-object max over bidders; bidder id in low bits so the
        # decode is exact and ties break toward the lowest row id.
        rank = jnp.arange(n, dtype=jnp.float64)
        enc = jnp.where(unassigned, bid * n + (n - 1 - rank), -1.0)
        win_enc = jnp.full((n,), -1.0).at[j1].max(enc, mode="drop")
        won = win_enc >= 0.0                              # (n,) objects
        bid_val = jnp.floor(win_enc / n)
        winner = (n - 1 - (win_enc - bid_val * n)).astype(jnp.int32)
        # previous owners of re-auctioned objects become unassigned
        prev = jnp.where(won & (owner >= 0), owner, n)
        assign = assign.at[prev].set(neg, mode="drop")
        obj_ids = jnp.arange(n, dtype=jnp.int32)
        assign = assign.at[jnp.where(won, winner, n)].set(obj_ids, mode="drop")
        owner = jnp.where(won, winner, owner)
        p = jnp.where(won, bid_val, p)
        return assign, owner, p, it + 1

    assign, owner, p, _ = jax.lax.while_loop(cond, body, init)
    return assign, owner, p


@functools.partial(jax.jit, static_argnames=("n",))
def _solve_one(cost, n):
    """Solve one n x n min-cost assignment. cost: (n, n) float64."""
    cmax = jnp.max(cost)
    cmin = jnp.min(cost)
    rng = jnp.maximum(cmax - cmin, 1e-30)
    quant = _quant_for(n)
    scale = quant / rng
    # integer benefit grid, scaled by (n+1) so final eps=1 is < "1/n"
    benefit = jnp.round((cmax - cost) * scale) * (n + 1)

    # epsilon schedule as scan inputs: one traced while_loop for all phases
    # (a Python unroll compiles P copies of the loop — 10x slower compiles).
    # Every eps is kept INTEGRAL: benefits/prices/bids then stay on the
    # integer grid, so the bid-winner encoding bid*n + rank decodes exactly
    # (a fractional eps corrupts the low bits — the winner decode breaks and
    # phases stop converging).
    schedule = []
    eps = quant * (n + 1) // 2
    for _ in range(_num_phases(eps)):
        schedule.append(eps)
        eps = max(1.0, eps // _EPS_FACTOR)

    def phase_step(carry, eps):
        _, _, prices = carry
        return _auction_phase(benefit, prices, eps, n), None

    init = (jnp.full((n,), -1, jnp.int32), jnp.full((n,), -1, jnp.int32),
            jnp.zeros((n,), jnp.float64))
    (assign, owner, prices), _ = jax.lax.scan(
        phase_step, init, jnp.asarray(schedule, jnp.float64))

    # duals back in cost units: pi_i = max_j benefit[i,j] - p_j (row profit)
    profit = jnp.max(benefit - prices[None, :], axis=1)
    denom = scale * (n + 1)
    row_duals = cmax - profit / denom
    col_duals = -prices / denom
    obj_primal = jnp.sum(jnp.take_along_axis(
        cost, assign[:, None], axis=1)[:, 0])
    obj_dual = jnp.sum(row_duals) + jnp.sum(col_duals)
    return LapSolution(assign, owner, row_duals, col_duals,
                       obj_primal, obj_dual)


def solve(res, cost, *, maximize: bool = False) -> LapSolution:
    """Solve (a batch of) square linear assignment problems.

    Functional analogue of ``LinearAssignmentProblem::solve``
    (linear_assignment.cuh:118).  ``cost`` is ``(n, n)`` or
    ``(batch, n, n)`` — the batch axis mirrors the reference's
    ``batchsize_`` sub-problem axis, vmapped instead of strided.
    """
    del res  # stateless; kept for the f(resources, ...) calling convention
    cost = ensure_array(cost, "cost")
    expects(cost.ndim in (2, 3), "cost must be (n, n) or (batch, n, n)")
    n = cost.shape[-1]
    expects(cost.shape[-2] == n, "cost matrix must be square")
    # the integer bid grid needs the float64 mantissa; scope x64 to this solve
    with jax.enable_x64():
        cost = cost.astype(jnp.float64)
        if maximize:
            cost = -cost
        if cost.ndim == 2:
            sol = _solve_one(cost, n)
        else:
            sol = jax.vmap(lambda c: _solve_one(c, n))(cost)
    if maximize:
        sol = sol._replace(row_duals=-sol.row_duals,
                           col_duals=-sol.col_duals,
                           obj_primal=-sol.obj_primal,
                           obj_dual=-sol.obj_dual)
    # the auction round cap (a while_loop safety bound) leaves rows at -1 if
    # ever exhausted; never return a silently-invalid assignment
    expects(bool(jnp.all(sol.row_assignment >= 0)),
            "LAP auction did not converge within the round cap — "
            "degenerate cost structure; rescale costs or report a bug")
    return sol


class LinearAssignmentProblem:
    """Class-shaped surface mirroring the reference
    ``raft::solver::LinearAssignmentProblem`` (linear_assignment.cuh:53).

    ``solve`` consumes a ``(batchsize, size, size)`` cost tensor (or
    ``(size, size)`` when ``batchsize == 1``) and stores assignments, duals
    and objectives for the getters.
    """

    def __init__(self, handle, size: int, batchsize: int = 1,
                 epsilon: float = 0.0):
        # epsilon is accepted for signature parity; the auction solver's
        # epsilon schedule is derived from the integer grid instead.
        self._handle = handle
        self.size = int(size)
        self.batchsize = int(batchsize)
        self._sol: LapSolution | None = None

    def solve(self, cost_matrix):
        cost = ensure_array(cost_matrix, "cost_matrix")
        if cost.ndim == 2:
            expects(self.batchsize == 1,
                    "2-D cost matrix but batchsize > 1")
            cost = cost[None]
        expects(cost.shape == (self.batchsize, self.size, self.size),
                f"cost must be ({self.batchsize}, {self.size}, {self.size})")
        self._sol = solve(self._handle, cost)
        return self._sol.row_assignment, self._sol.col_assignment

    def _need(self):
        expects(self._sol is not None, "call solve() first")
        return self._sol

    def row_dual_vector(self, sp_id: int = 0):
        """getRowDualVector analogue (linear_assignment.cuh:148)."""
        return self._need().row_duals[sp_id]

    def col_dual_vector(self, sp_id: int = 0):
        """getColDualVector analogue (linear_assignment.cuh:159)."""
        return self._need().col_duals[sp_id]

    def primal_objective_value(self, sp_id: int = 0):
        """getPrimalObjectiveValue analogue (linear_assignment.cuh:170)."""
        return self._need().obj_primal[sp_id]

    def dual_objective_value(self, sp_id: int = 0):
        """getDualObjectiveValue analogue (linear_assignment.cuh:181)."""
        return self._need().obj_dual[sp_id]
