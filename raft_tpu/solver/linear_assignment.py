"""Linear assignment problem (LAP) solver.

API parity with the reference's ``raft::solver::LinearAssignmentProblem``
(``/root/reference/cpp/include/raft/solver/linear_assignment.cuh:53`` — class,
``:118`` — ``solve``, ``:148-187`` — dual-vector / objective getters; legacy
alias ``lap/lap.cuh``).  The reference ports Date & Nagi's GPU alternating-tree
Hungarian algorithm; a tree grown one augmenting path at a time is a poor fit
for XLA (data-dependent frontier, scalar host loop per step), so the TPU-native
design is **Bertsekas' auction algorithm with epsilon-scaling**:

- every unassigned row bids for its best column in parallel (one dense
  ``(n, n)`` value matrix + ``lax.top_k`` — MXU/VPU-friendly, no trees);
- bids resolve with a single scatter-max per round;
- the auction itself is a fixed ``lax.while_loop`` nest under ``jit``
  (no data-dependent Python control flow), ``vmap``-ed over the
  reference's ``batchsize`` sub-problem axis; quantization and the
  dual/objective mapping run host-side, so ``solve`` is a host
  orchestration function (NOT itself jit-traceable).

Costs are quantized onto an integer grid scaled by ``(n + 1)`` so the final
epsilon = 1 pass is provably optimal for the quantized problem (the classic
``eps < 1/n`` termination condition).  The grid lives in **int64** on
device: quantization happens host-side in float64 (exact), and the auction
itself is pure integer arithmetic — TPUs have no native f64 (a f64 device
program crashes the runtime), but emulated S64 runs fine, so the same
solver is exact on CPU and TPU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import compat
from ..core.mdarray import ensure_array
from ..core.error import expects

_EPS_FACTOR = 7  # epsilon divisor per scaling phase (Bertsekas suggests 4-10)


def _quant_for(n: int) -> int:
    """Integer grid resolution for an n x n problem.

    Benefits live on multiples of (n+1) up to QUANT*(n+1); encoded bids
    carry the bidder id in the low bits (enc = bid*n + rank) and bids can
    exceed the max benefit by up to eps0 = QUANT*(n+1)/2, so exact int64
    arithmetic needs 1.5 * QUANT * (n+1) * n < 2^62.  QUANT adapts
    downward for (absurdly) large n (capped at 2^30); quantization error
    is <= n / (2*QUANT) of the cost range (~1e-6 at n=2048).
    """
    import math
    lim = 2.0 ** 61 / (float(n) * (n + 1))
    return int(min(2.0 ** 30, 2.0 ** math.floor(math.log2(lim))))


class LapSolution(NamedTuple):
    """Solution of one (batch of) linear assignment problem(s).

    Mirrors the reference getters: ``row_assignments``/``col_assignments``
    (linear_assignment.cuh:118 ``solve`` outputs), ``row_duals``/``col_duals``
    (``getRowDualVector``/``getColDualVector`` :148,159) and the
    primal/dual objective values (:170,181).
    """

    row_assignment: jax.Array   # (..., n) column assigned to each row
    col_assignment: jax.Array   # (..., n) row assigned to each column
    row_duals: jax.Array        # (..., n) u_i with u_i + v_j <= c_ij
    col_duals: jax.Array        # (..., n) v_j
    obj_primal: jax.Array       # (...,) sum of assigned costs
    obj_dual: jax.Array         # (...,) sum(u) + sum(v)


def _num_phases(eps0: float) -> int:
    """Static epsilon-scaling phase count: eps0 down to 1."""
    import math
    return max(1, int(math.ceil(math.log(max(eps0, 2.0))
                                / math.log(_EPS_FACTOR))) + 1)


def _auction_phase(benefit, prices, eps, n):
    """One epsilon phase: auction rounds until every row is assigned.

    benefit: (n, n) int64 (multiples of n+1), prices: (n,) int64.
    Returns (assignment (n,), owner (n,), prices (n,)).
    """
    neg = jnp.int32(-1)
    init = (jnp.full((n,), neg), jnp.full((n,), neg), prices, jnp.int32(0))

    # safety cap: with integer eps >= 1 each round raises some price by >= eps,
    # so rounds are bounded; the cap only guards against numerical surprise.
    max_rounds = jnp.int32(16 * n + 64)

    def cond(state):
        assign, _, _, it = state
        return jnp.logical_and(jnp.any(assign == neg), it < max_rounds)

    def body(state):
        assign, owner, p, it = state
        unassigned = assign == neg                       # (n,) rows
        values = benefit - p[None, :]                    # (n, n) int64
        if n == 1:
            j1 = jnp.zeros((1,), jnp.int32)
            w2 = values[:, 0]  # no competitor: bid raises own price by eps
        else:
            top2, idx2 = jax.lax.top_k(values, 2)
            j1 = idx2[:, 0]
            w2 = top2[:, 1]
        # bid = p[j1] + w1 - w2 + eps  ==  benefit[i, j1] - w2 + eps
        bid = jnp.take_along_axis(benefit, j1[:, None], axis=1)[:, 0] \
            - w2 + eps
        # resolve: per-object max over bidders; bidder id in low bits so the
        # decode is exact and ties break toward the lowest row id.
        rank = jnp.arange(n, dtype=jnp.int64)
        enc = jnp.where(unassigned, bid * n + (n - 1 - rank), jnp.int64(-1))
        win_enc = jnp.full((n,), -1, jnp.int64).at[j1].max(enc, mode="drop")
        won = win_enc >= 0                                # (n,) objects
        bid_val = win_enc // n
        winner = (n - 1 - (win_enc - bid_val * n)).astype(jnp.int32)
        # previous owners of re-auctioned objects become unassigned
        prev = jnp.where(won & (owner >= 0), owner, n)
        assign = assign.at[prev].set(neg, mode="drop")
        obj_ids = jnp.arange(n, dtype=jnp.int32)
        assign = assign.at[jnp.where(won, winner, n)].set(obj_ids, mode="drop")
        owner = jnp.where(won, winner, owner)
        p = jnp.where(won, bid_val, p)
        return assign, owner, p, it + 1

    assign, owner, p, _ = jax.lax.while_loop(cond, body, init)
    return assign, owner, p


@functools.partial(jax.jit, static_argnames=("n",))
def _solve_grid(benefit, schedule, n):
    """Run the epsilon-scaling auction on an int64 benefit grid.

    benefit: (n, n) int64; schedule: (phases,) int64 descending epsilons.
    Returns (assign (n,) i32, owner (n,) i32, prices (n,) i64,
    profit (n,) i64) — profit is the row dual on the integer grid.
    """

    def phase_step(carry, eps):
        _, _, prices = carry
        return _auction_phase(benefit, prices, eps, n), None

    init = (jnp.full((n,), -1, jnp.int32), jnp.full((n,), -1, jnp.int32),
            jnp.zeros((n,), jnp.int64))
    (assign, owner, prices), _ = jax.lax.scan(phase_step, init, schedule)
    # row profit: pi_i = max_j benefit[i,j] - p_j (dual on the grid)
    profit = jnp.max(benefit - prices[None, :], axis=1)
    return assign, owner, prices, profit


def solve(res, cost, *, maximize: bool = False) -> LapSolution:
    """Solve (a batch of) square linear assignment problems.

    Functional analogue of ``LinearAssignmentProblem::solve``
    (linear_assignment.cuh:118).  ``cost`` is ``(n, n)`` or
    ``(batch, n, n)`` — the batch axis mirrors the reference's
    ``batchsize_`` sub-problem axis, ``vmap``-ed through one device
    dispatch.  Quantization runs host-side in float64; the device part is
    pure int64, so the solver is exact on backends without native f64
    (TPU).  Host orchestration — not jit-traceable itself.
    """
    del res  # stateless; kept for the f(resources, ...) calling convention
    if isinstance(cost, jax.core.Tracer):
        raise TypeError(
            "lap.solve is host-orchestrating (float64 quantization + "
            "epsilon scheduling run on the host) and cannot be traced "
            "under jit/vmap — call it outside the transform, or vmap "
            "batched problems by passing a (batch, n, n) cost instead.")
    cost_np = np.asarray(ensure_array(cost, "cost"), dtype=np.float64)
    expects(cost_np.ndim in (2, 3), "cost must be (n, n) or (batch, n, n)")
    n = cost_np.shape[-1]
    expects(cost_np.shape[-2] == n, "cost matrix must be square")
    if maximize:
        cost_np = -cost_np

    batched = cost_np.ndim == 3
    probs = cost_np if batched else cost_np[None]
    # host-side exact quantization, vectorized over the batch: per-problem
    # grids (quant and the epsilon schedule depend only on n).  numpy
    # float64 round -> int64 is exact for |values| < 2^53.
    cmax = probs.max(axis=(1, 2))                       # (B,)
    rng = np.maximum(cmax - probs.min(axis=(1, 2)), 1e-30)
    quant = _quant_for(n)
    scale = quant / rng                                 # (B,)
    benefit = (np.round((cmax[:, None, None] - probs)
                        * scale[:, None, None]) * (n + 1)).astype(np.int64)

    # epsilon schedule as scan inputs: one traced while_loop for all
    # phases (a Python unroll compiles P copies of the loop — 10x slower
    # compiles).  Every eps is an exact integer: benefits/prices/bids stay
    # on the integer grid, so the bid-winner encoding bid*n + rank decodes
    # exactly.
    schedule = []
    eps = quant * (n + 1) // 2
    for _ in range(_num_phases(eps)):
        schedule.append(eps)
        eps = max(1, eps // _EPS_FACTOR)

    with compat.enable_x64():   # int64 device arrays (no f64 ever on device)
        sched = jnp.asarray(schedule, jnp.int64)
        assign, owner, prices, profit = jax.vmap(
            lambda b: _solve_grid(b, sched, n))(jnp.asarray(benefit))

    assign_np = np.asarray(assign)
    denom = (scale * (n + 1))[:, None]                  # (B, 1)
    row_duals = cmax[:, None] - np.asarray(profit, np.float64) / denom
    col_duals = -np.asarray(prices, np.float64) / denom
    obj_primal = np.take_along_axis(
        probs, assign_np[:, :, None].astype(np.int64), axis=2
    )[:, :, 0].sum(axis=1)
    obj_dual = row_duals.sum(axis=1) + col_duals.sum(axis=1)

    # duals/objectives are exact in host float64 — return them as host
    # arrays at that precision (the previous f64 API contract; a f64
    # DEVICE array would be unrepresentable on TPU backends).  assign and
    # owner come back to the host too, so LapSolution is uniformly
    # host-side numpy rather than a jax/numpy mix.
    assign = np.asarray(assign, np.int32)
    owner = np.asarray(owner, np.int32)
    row_duals = np.asarray(row_duals, np.float64)
    col_duals = np.asarray(col_duals, np.float64)
    obj_primal = np.asarray(obj_primal, np.float64)
    obj_dual = np.asarray(obj_dual, np.float64)
    if not batched:
        assign, owner = assign[0], owner[0]
        row_duals, col_duals = row_duals[0], col_duals[0]
        obj_primal, obj_dual = obj_primal[0], obj_dual[0]
    sol = LapSolution(assign, owner, row_duals, col_duals,
                      obj_primal, obj_dual)
    if maximize:
        sol = sol._replace(row_duals=-sol.row_duals,
                           col_duals=-sol.col_duals,
                           obj_primal=-sol.obj_primal,
                           obj_dual=-sol.obj_dual)
    # the auction round cap (a while_loop safety bound) leaves rows at -1 if
    # ever exhausted; never return a silently-invalid assignment
    expects(bool(jnp.all(sol.row_assignment >= 0)),
            "LAP auction did not converge within the round cap — "
            "degenerate cost structure; rescale costs or report a bug")
    return sol


class LinearAssignmentProblem:
    """Class-shaped surface mirroring the reference
    ``raft::solver::LinearAssignmentProblem`` (linear_assignment.cuh:53).

    ``solve`` consumes a ``(batchsize, size, size)`` cost tensor (or
    ``(size, size)`` when ``batchsize == 1``) and stores assignments, duals
    and objectives for the getters.
    """

    def __init__(self, handle, size: int, batchsize: int = 1,
                 epsilon: float = 0.0):
        # epsilon is accepted for signature parity; the auction solver's
        # epsilon schedule is derived from the integer grid instead.
        self._handle = handle
        self.size = int(size)
        self.batchsize = int(batchsize)
        self._sol: LapSolution | None = None

    def solve(self, cost_matrix):
        cost = ensure_array(cost_matrix, "cost_matrix")
        if cost.ndim == 2:
            expects(self.batchsize == 1,
                    "2-D cost matrix but batchsize > 1")
            cost = cost[None]
        expects(cost.shape == (self.batchsize, self.size, self.size),
                f"cost must be ({self.batchsize}, {self.size}, {self.size})")
        self._sol = solve(self._handle, cost)
        return self._sol.row_assignment, self._sol.col_assignment

    def _need(self):
        expects(self._sol is not None, "call solve() first")
        return self._sol

    def row_dual_vector(self, sp_id: int = 0):
        """getRowDualVector analogue (linear_assignment.cuh:148)."""
        return self._need().row_duals[sp_id]

    def col_dual_vector(self, sp_id: int = 0):
        """getColDualVector analogue (linear_assignment.cuh:159)."""
        return self._need().col_duals[sp_id]

    def primal_objective_value(self, sp_id: int = 0):
        """getPrimalObjectiveValue analogue (linear_assignment.cuh:170)."""
        return self._need().obj_primal[sp_id]

    def dual_objective_value(self, sp_id: int = 0):
        """getDualObjectiveValue analogue (linear_assignment.cuh:181)."""
        return self._need().obj_dual[sp_id]
