"""Spectral partitioning tests.

Pattern: compute-vs-reference on structured random graphs (reference tests:
cpp/test/cluster/, sklearn.SpectralClustering as the oracle where
available).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import spectral
from raft_tpu.sparse.formats import dense_to_coo
from raft_tpu.stats import adjusted_rand_index

K_BLOCKS = 3
BLOCK = 30
N = K_BLOCKS * BLOCK


def block_graph(seed=0, p_in=0.6, p_out=0.02):
    """Planted-partition adjacency: dense blocks, sparse across."""
    rng = np.random.default_rng(seed)
    labels = np.repeat(np.arange(K_BLOCKS), BLOCK)
    same = labels[:, None] == labels[None, :]
    p = np.where(same, p_in, p_out)
    a = (rng.random((N, N)) < p).astype(np.float32)
    a = np.triu(a, 1)
    a = a + a.T
    return a, labels


@pytest.fixture(scope="module")
def graph():
    return block_graph()


def _solvers(n_eig, n_clusters):
    es = spectral.LanczosSolver(
        spectral.EigenSolverConfig(n_eig_vecs=n_eig, max_iter=60, tol=1e-4))
    cs = spectral.KMeansSolver(
        spectral.ClusterSolverConfig(n_clusters=n_clusters, max_iter=100))
    return es, cs


class TestPartition:
    def test_recovers_planted_blocks(self, res, graph):
        a, labels = graph
        adj = dense_to_coo(jnp.asarray(a))
        es, cs = _solvers(K_BLOCKS, K_BLOCKS)
        clusters, eig_vals, eig_vecs, _ = spectral.partition(res, adj, es, cs)
        assert eig_vecs.shape == (N, K_BLOCKS)
        # Laplacian eigenvalues are >= 0, smallest ~0 (connected-ish graph)
        assert float(eig_vals[0]) < float(eig_vals[-1]) + 1e-6
        ari = adjusted_rand_index(jnp.asarray(labels), clusters,
                                  n_classes_true=K_BLOCKS,
                                  n_classes_pred=K_BLOCKS)
        assert float(ari) > 0.95

    def test_matches_sklearn(self, res, graph):
        sklearn = pytest.importorskip("sklearn.cluster")
        a, labels = graph
        ref = sklearn.SpectralClustering(
            n_clusters=K_BLOCKS, affinity="precomputed",
            random_state=0).fit_predict(a)
        adj = dense_to_coo(jnp.asarray(a))
        es, cs = _solvers(K_BLOCKS, K_BLOCKS)
        clusters, _, _, _ = spectral.partition(res, adj, es, cs)
        ari = adjusted_rand_index(jnp.asarray(ref), clusters,
                                  n_classes_true=K_BLOCKS,
                                  n_classes_pred=K_BLOCKS)
        assert float(ari) > 0.9

    def test_analyze_partition(self, res, graph):
        a, labels = graph
        adj = dense_to_coo(jnp.asarray(a))
        cut_true, cost_true = spectral.analyze_partition(
            res, adj, K_BLOCKS, jnp.asarray(labels))
        rng = np.random.default_rng(1)
        rand = rng.integers(0, K_BLOCKS, N)
        cut_rand, cost_rand = spectral.analyze_partition(
            res, adj, K_BLOCKS, jnp.asarray(rand))
        # planted partition cuts far fewer edges than a random one
        assert float(cut_true) < float(cut_rand)
        assert float(cost_true) < float(cost_rand)
        # edge_cut equals the direct count of cross-block edge weight
        cross = a * (labels[:, None] != labels[None, :])
        np.testing.assert_allclose(float(cut_true), cross.sum() / 2.0,
                                   rtol=1e-4)


class TestModularity:
    def test_modularity_maximization(self, res, graph):
        a, labels = graph
        adj = dense_to_coo(jnp.asarray(a))
        es, cs = _solvers(K_BLOCKS, K_BLOCKS)
        clusters, _, _, _ = spectral.modularity_maximization(res, adj, es, cs)
        ari = adjusted_rand_index(jnp.asarray(labels), clusters,
                                  n_classes_true=K_BLOCKS,
                                  n_classes_pred=K_BLOCKS)
        assert float(ari) > 0.9

    def test_analyze_modularity(self, res, graph):
        a, labels = graph
        adj = dense_to_coo(jnp.asarray(a))
        q_true = spectral.analyze_modularity(res, adj, K_BLOCKS,
                                             jnp.asarray(labels))
        rng = np.random.default_rng(2)
        q_rand = spectral.analyze_modularity(
            res, adj, K_BLOCKS, jnp.asarray(rng.integers(0, K_BLOCKS, N)))
        assert float(q_true) > 0.3         # strong community structure
        assert float(q_true) > float(q_rand)
        # cross-check against the direct dense formula
        d = a.sum(axis=1)
        two_m = d.sum()
        b = a - np.outer(d, d) / two_m
        onehot = np.eye(K_BLOCKS)[labels]
        q_ref = np.trace(onehot.T @ b @ onehot) / two_m
        np.testing.assert_allclose(float(q_true), q_ref, rtol=1e-3,
                                   atol=1e-5)


class TestEmbedding:
    def test_fit_embedding_shape_and_separation(self, res, graph):
        a, labels = graph
        adj = dense_to_coo(jnp.asarray(a))
        emb = spectral.fit_embedding(res, adj, 3)
        assert emb.shape == (N, 3)
        assert bool(jnp.all(jnp.isfinite(emb)))
