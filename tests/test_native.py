"""Native C++ host components: parity with the pure-Python fallbacks."""

import os
import subprocess

import numpy as np
import pytest

from raft_tpu import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="no C++ toolchain")


@requires_native
class TestBuildDendrogram:
    def test_matches_python_fallback(self):
        rng = np.random.default_rng(0)
        n, n_edges = 200, 400
        src = rng.integers(0, n, n_edges).astype(np.int32)
        dst = rng.integers(0, n, n_edges).astype(np.int32)
        w = rng.random(n_edges).astype(np.float32)

        labels_n, dendro_n, h_n = native.build_dendrogram(src, dst, w, n, 5)

        os.environ["RAFT_TPU_DISABLE_NATIVE"] = "1"
        try:
            from raft_tpu.cluster.single_linkage import (
                _host_union_find_labels)
            # force the fallback by reloading the guard
            native._lib = None
            native._tried = False
            labels_p, dendro_p, h_p = _host_union_find_labels(
                src, dst, w, n, 5)
        finally:
            del os.environ["RAFT_TPU_DISABLE_NATIVE"]
            native._lib = None
            native._tried = False

        np.testing.assert_array_equal(labels_n, labels_p)
        np.testing.assert_array_equal(dendro_n, dendro_p)
        np.testing.assert_allclose(h_n, h_p)

    def test_connected_components(self):
        # two components: a chain 0-1-2 and a pair 3-4; node 5 isolated
        src = np.asarray([0, 1, 3], np.int32)
        dst = np.asarray([1, 2, 4], np.int32)
        labels, n_comp = native.connected_components(src, dst, 6)
        assert n_comp == 3
        assert labels[0] == labels[1] == labels[2]
        assert labels[3] == labels[4]
        assert len({labels[0], labels[3], labels[5]}) == 3

    def test_sentinel_edges_skipped(self):
        src = np.asarray([0, -1, 2], np.int32)
        dst = np.asarray([1, 5, 3], np.int32)
        labels, n_comp = native.connected_components(src, dst, 6)
        assert n_comp == 4      # {0,1}, {2,3}, {4}, {5}


def test_single_linkage_end_to_end_uses_whatever_is_available(res):
    """single_linkage must give identical results whichever backend the
    union-find runs on."""
    from raft_tpu.cluster.single_linkage import single_linkage
    from raft_tpu.random import make_blobs
    X, y = make_blobs(300, 8, n_clusters=3, cluster_std=0.4, seed=11)
    out = single_linkage(res, np.asarray(X), n_clusters=3)
    assert out.n_clusters == 3
    # blobs are well separated: labels must match ground truth up to
    # permutation
    y = np.asarray(y)
    for cl in range(3):
        assert len(set(out.labels[y == cl].tolist())) == 1
