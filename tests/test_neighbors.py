"""Neighbors layer tests.

Reference test strategy (SURVEY.md §4): random inputs, compare against a naive
reference implementation (cpp/internal/raft_internal/neighbors/naive_knn.cuh);
ANN results asserted on recall with a margin
(cpp/test/neighbors/ann_utils.cuh:125-166 ``eval_neighbours``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import (
    brute_force,
    eps_neighbors_l2sq,
    knn_merge_parts,
    refine,
)


def naive_knn(db, q, k, metric="sqeuclidean"):
    """The naive_knn reference oracle (naive_knn.cuh:85), in numpy."""
    if metric == "inner_product":
        d = -(q @ db.T)
    else:
        d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def recall(found, truth):
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, truth))
    return hits / truth.size


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    db = rng.normal(size=(1000, 16)).astype(np.float32)
    q = rng.normal(size=(50, 16)).astype(np.float32)
    return db, q


class TestBruteForce:
    def test_exact_l2(self, res, data):
        db, q = data
        d, i = brute_force.knn(res, db, q, 10)
        td, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.99
        np.testing.assert_allclose(np.asarray(d), td, rtol=1e-3, atol=1e-3)

    def test_tiled_matches_untiled(self, res, data):
        db, q = data
        d1, i1 = brute_force.knn(res, db, q, 8, tile_n=128)
        d2, i2 = brute_force.knn(res, db, q, 8, tile_n=4096)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-4, atol=1e-4)
        assert recall(np.asarray(i1), np.asarray(i2)) > 0.99

    def test_inner_product(self, res, data):
        db, q = data
        d, i = brute_force.knn(res, db, q, 5,
                               metric=DistanceType.InnerProduct)
        _, ti = naive_knn(db, q, 5, metric="inner_product")
        assert recall(np.asarray(i), ti) > 0.99
        # IP results sorted descending
        dd = np.asarray(d)
        assert (np.diff(dd, axis=1) <= 1e-5).all()

    def test_global_id_offset(self, res, data):
        db, q = data
        _, i0 = brute_force.knn(res, db, q, 3)
        _, i1 = brute_force.knn(res, db, q, 3, global_id_offset=1000)
        np.testing.assert_array_equal(np.asarray(i0) + 1000, np.asarray(i1))

    def test_sqrt_metric(self, res, data):
        db, q = data
        d, _ = brute_force.knn(res, db, q, 4,
                               metric=DistanceType.L2SqrtExpanded)
        d2, _ = brute_force.knn(res, db, q, 4, metric=DistanceType.L2Expanded)
        np.testing.assert_allclose(np.asarray(d), np.sqrt(np.asarray(d2)),
                                   rtol=1e-3, atol=1e-3)


class TestMergeParts:
    def test_merge_equals_full(self, res, data):
        db, q = data
        n_parts = 4
        part = db.shape[0] // n_parts
        keys, vals = [], []
        for p in range(n_parts):
            shard = db[p * part:(p + 1) * part]
            d, i = brute_force.knn(res, shard, q, 6)
            keys.append(np.asarray(d))
            vals.append(np.asarray(i))
        md, mi = knn_merge_parts(jnp.asarray(np.stack(keys)),
                                 jnp.asarray(np.stack(vals)),
                                 n_samples=part)
        fd, fi = brute_force.knn(res, db, q, 6)
        np.testing.assert_allclose(np.asarray(md), np.asarray(fd),
                                   rtol=1e-3, atol=1e-3)
        assert recall(np.asarray(mi), np.asarray(fi)) > 0.99

    def test_translations(self, res):
        keys = jnp.asarray([[[0.1, 0.2]], [[0.05, 0.3]]])  # (2 parts, 1q, k=2)
        vals = jnp.asarray([[[0, 1]], [[0, 1]]])
        d, i = knn_merge_parts(keys, vals,
                               translations=jnp.asarray([100, 200]))
        np.testing.assert_allclose(np.asarray(d[0]), [0.05, 0.1])
        np.testing.assert_array_equal(np.asarray(i[0]), [200, 100])


class TestRefine:
    def test_refine_improves_candidates(self, res, data):
        db, q = data
        # corrupt candidates: true top-30 shuffled
        _, cand = naive_knn(db, q, 30)
        rng = np.random.default_rng(0)
        cand = np.take_along_axis(
            cand, rng.permuted(np.tile(np.arange(30), (q.shape[0], 1)),
                               axis=1), axis=1)
        d, i = refine(res, db, q, jnp.asarray(cand), 10,
                      metric=DistanceType.L2Expanded)
        td, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.99
        np.testing.assert_allclose(np.asarray(d), td, rtol=1e-3, atol=1e-3)

    def test_refine_masks_invalid(self, res, data):
        db, q = data
        _, cand = naive_knn(db, q, 10)
        cand[:, 5:] = -1  # only 5 valid candidates
        d, i = refine(res, db, q, jnp.asarray(cand), 5,
                      metric=DistanceType.L2Expanded)
        assert (np.asarray(i) >= 0).all()

    def test_refine_inner_product(self, res, data):
        db, q = data
        _, cand = naive_knn(db, q, 20, metric="inner_product")
        d, i = refine(res, db, q, jnp.asarray(cand), 5,
                      metric=DistanceType.InnerProduct)
        _, ti = naive_knn(db, q, 5, metric="inner_product")
        assert recall(np.asarray(i), ti) > 0.99


class TestEpsNeighborhood:
    def test_adjacency(self, res):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(100, 4)).astype(np.float32)
        adj, vd = eps_neighbors_l2sq(res, x, x, 1.5)
        d = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(adj), d < 1.5)
        np.testing.assert_array_equal(np.asarray(vd), (d < 1.5).sum(1))


class TestSpatialLegacyNamespace:
    """The deprecated ``raft::spatial::knn`` spelling forwards to neighbors
    (reference: spatial/knn/knn.cuh:20-24); the shim must expose the same
    callables."""

    def test_forwards(self, res):
        from raft_tpu import spatial
        from raft_tpu.matrix.select_k import select_k
        from raft_tpu.neighbors import brute_force
        assert spatial.knn.brute_force_knn is brute_force.knn
        assert spatial.knn.knn_merge_parts is brute_force.knn_merge_parts
        assert spatial.knn.select_k is select_k
        rng = np.random.default_rng(0)
        db = rng.normal(size=(128, 8)).astype(np.float32)
        d, i = spatial.knn.brute_force_knn(res, db, db[:4], 3)
        assert np.asarray(i).shape == (4, 3)
