"""raft_tpu.serving — dynamic batching, admission control, warm executors.

Covers the batcher edge cases the ISSUE names (single in-flight query
hitting max_wait, queue-full shedding, deadline expiry while queued,
per-tenant quota exhaustion, padded-row masking through the integrity
mask path), the zero-recompile steady-state contract, and the
bucket-keyed AOT executable cache (export→load→search round trip per
bucket; distinct batch sizes must not collide).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu import serving
from raft_tpu.core import aot
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.observability import flight, trace
from raft_tpu.resilience.retry import Deadline, DeadlineExceededError


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.disable()
    obs.reset()
    trace.disable_tracing()
    flight.clear()
    yield
    obs.disable()
    obs.reset()
    trace.disable_tracing()
    flight.clear()


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    # bucket warm-ups and generation swaps compile many executables;
    # release them at teardown so later modules in a full-suite run
    # don't inherit the accumulated JIT code mappings
    yield
    jax.clear_caches()


def _dataset(n=4000, dim=32, seed=0):
    rng = np.random.default_rng(seed)
    db = rng.normal(size=(n, dim)).astype(np.float32)
    q = rng.normal(size=(64, dim)).astype(np.float32)
    return jnp.asarray(db), jnp.asarray(q)


@pytest.fixture(scope="module")
def pq_setup():
    from raft_tpu import DeviceResources
    res = DeviceResources(seed=42)
    db, q = _dataset()
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=32, pq_dim=8, kmeans_n_iters=4), db)
    sp = ivf_pq.SearchParams(n_probes=8)
    return res, db, q, index, sp


def _executor(pq_setup, max_batch=16, ks=(5,), warm="aot"):
    res, _, _, index, sp = pq_setup
    return serving.Executor(res, "ivf_pq", index, ks=ks,
                            max_batch=max_batch, search_params=sp,
                            warm=warm)


# ---------------------------------------------------------------------------
# buckets


class TestBuckets:
    def test_bucket_sizes_powers_of_two(self):
        assert serving.bucket_sizes(16) == (1, 2, 4, 8, 16)
        # non-power max_batch is still included (the peak shape)
        assert serving.bucket_sizes(24) == (1, 2, 4, 8, 16, 24)
        assert serving.bucket_sizes(16, min_bucket=4) == (4, 8, 16)

    def test_bucket_for(self):
        assert serving.bucket_for(1, 16) == 1
        assert serving.bucket_for(3, 16) == 4
        assert serving.bucket_for(16, 16) == 16
        with pytest.raises(Exception):
            serving.bucket_for(17, 16)

    def test_pad_rows(self):
        x = jnp.ones((3, 4))
        p = serving.pad_rows(x, 8)
        assert p.shape == (8, 4)
        np.testing.assert_array_equal(np.asarray(p[3:]), 0.0)
        assert serving.pad_rows(x, 3) is x


# ---------------------------------------------------------------------------
# admission


class TestAdmission:
    def test_token_bucket(self):
        t = [0.0]
        tb = serving.TokenBucket(rate=10.0, burst=5.0, clock=lambda: t[0])
        assert tb.try_acquire(5)
        assert not tb.try_acquire(1)      # exhausted
        t[0] += 0.5                       # refills 5 tokens
        assert tb.try_acquire(5)
        assert not tb.try_acquire(1)

    def test_queue_full_shed(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_queue_rows=4,
                                   max_wait_us=50_000)
        q = pq_setup[2]
        srv = serving.Server(ex, cfg).start()
        try:
            # park the dispatcher so submissions stay queued
            srv.batcher.stop(drain=False)
            fut = srv.submit(q[:3], 5)
            with pytest.raises(serving.Overloaded):
                srv.submit(q[:3], 5)      # 3 + 3 > 4 -> shed
            srv.batcher.start()           # resume; queued request completes
            d, i = fut.result(timeout=30)
            assert d.shape == (3, 5)
        finally:
            srv.stop()

    def test_oversized_request_rejected(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        with serving.Server(ex, serving.ServerConfig(max_batch=16)) as srv:
            q = pq_setup[2]
            with pytest.raises(serving.Overloaded):
                srv.submit(q[:17], 5)

    def test_tenant_quota_exhaustion(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(
            max_batch=16, max_wait_us=100.0,
            tenant_quotas={"metered": (1.0, 4.0)})   # 4-row burst
        q = pq_setup[2]
        with serving.Server(ex, cfg) as srv:
            srv.search(q[:4], 5, tenant="metered")   # spends the burst
            with pytest.raises(serving.QuotaExceeded):
                srv.submit(q[:4], 5, tenant="metered")
            # other tenants are unmetered
            d, i = srv.search(q[:4], 5, tenant="other")
            assert d.shape == (4, 5)

    def test_quota_exceeded_is_overloaded(self):
        assert issubclass(serving.QuotaExceeded, serving.Overloaded)

    def test_expired_deadline_rejected_at_submit(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        q = pq_setup[2]
        with serving.Server(ex, serving.ServerConfig(max_batch=16)) as srv:
            with pytest.raises(serving.Overloaded):
                srv.submit(q[:2], 5, deadline=Deadline(0.0))


# ---------------------------------------------------------------------------
# batcher


class TestBatcher:
    def test_single_query_hits_max_wait(self, pq_setup):
        """One in-flight query must dispatch after ~max_wait_us even with
        no other traffic to fill the bucket."""
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=20_000)
        q = pq_setup[2]
        with serving.Server(ex, cfg) as srv:
            srv.search(q[:1], 5)                     # warm the live path
            t0 = time.monotonic()
            d, i = srv.submit(q[:1], 5).result(timeout=10)
            waited = time.monotonic() - t0
            assert d.shape == (1, 5)
            # dispatched by the max_wait timer: NOT immediately (the
            # bucket never fills) and well before the 10s future timeout
            assert waited < 5.0
            assert np.asarray(i).min() >= 0

    def test_full_bucket_dispatches_before_max_wait(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        # absurd max_wait: only the max_batch trigger can dispatch
        cfg = serving.ServerConfig(max_batch=8, max_wait_us=60_000_000)
        q = pq_setup[2]
        with serving.Server(ex, cfg) as srv:
            futs = [srv.submit(q[j:j + 1], 5) for j in range(8)]
            outs = [f.result(timeout=30) for f in futs]
        assert all(o[0].shape == (1, 5) for o in outs)

    def test_deadline_expiry_while_queued(self, pq_setup):
        """A request whose deadline lapses in the queue fails with
        DeadlineExceededError at dispatch, and does not poison the batch."""
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=200_000)
        q = pq_setup[2]
        t = [0.0]
        clock = lambda: t[0]                          # noqa: E731
        with serving.Server(ex, cfg) as srv:
            dead = Deadline(0.05, clock=clock)        # 50 ms budget
            doomed = srv.submit(q[:2], 5, deadline=dead)
            t[0] += 1.0                               # budget lapses queued
            ok = srv.submit(q[:3], 5)
            d, i = ok.result(timeout=10)
            assert d.shape == (3, 5)
            with pytest.raises(DeadlineExceededError):
                doomed.result(timeout=10)

    def test_batch_coalescing_matches_direct_search(self, pq_setup):
        res, _, q, index, sp = pq_setup
        ex = _executor(pq_setup, warm="aot")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=50_000)
        with serving.Server(ex, cfg) as srv:
            futs = [srv.submit(q[j * 3:(j + 1) * 3], 5) for j in range(4)]
            outs = [f.result(timeout=30) for f in futs]
        for j, (d, i) in enumerate(outs):
            dd, ii = ivf_pq.search(res, sp, index, q[j * 3:(j + 1) * 3], 5)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_allclose(np.asarray(d), np.asarray(dd),
                                       rtol=1e-5)

    def test_mixed_k_split_into_separate_batches(self, pq_setup):
        ex = _executor(pq_setup, ks=(5, 10), warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=10_000)
        q = pq_setup[2]
        with serving.Server(ex, cfg) as srv:
            f5 = srv.submit(q[:2], 5)
            f10 = srv.submit(q[:2], 10)
            assert f5.result(timeout=10)[0].shape == (2, 5)
            assert f10.result(timeout=10)[0].shape == (2, 10)

    def test_unknown_k_rejected(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        with serving.Server(ex, serving.ServerConfig(max_batch=16)) as srv:
            with pytest.raises(Exception):
                srv.submit(pq_setup[2][:2], 7)


# ---------------------------------------------------------------------------
# padded-row masking (the integrity mask path)


class TestPaddedRows:
    def test_padded_rows_masked(self, pq_setup):
        """Executor-level contract: rows past n_valid return id -1 and
        the worst distance, exactly like boundary-masked rows."""
        ex = _executor(pq_setup, warm="aot")
        ex.warmup()
        q = pq_setup[2]
        padded = ex.pad(q[:3], 8)
        d, i = ex.search_bucket(padded, 3, 5)
        d, i = np.asarray(d), np.asarray(i)
        assert (i[3:] == -1).all()
        assert np.isposinf(d[3:]).all()
        # real rows untouched
        assert (i[:3] >= 0).all()
        assert np.isfinite(d[:3]).all()

    def test_nonfinite_query_rows_masked_under_mask_policy(self, pq_setup):
        from raft_tpu import config
        ex = _executor(pq_setup, warm="jit")
        q = np.asarray(pq_setup[2][:4]).copy()
        q[1] = np.nan
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=5_000)
        with serving.Server(ex, cfg) as srv:
            with config.validation_policy("mask"):
                d, i = srv.search(q, 5)
        d, i = np.asarray(d), np.asarray(i)
        assert (i[1] == -1).all() and np.isposinf(d[1]).all()
        assert (i[[0, 2, 3]] >= 0).all()

    def test_nonfinite_rejected_under_raise_policy(self, pq_setup):
        from raft_tpu import config
        from raft_tpu.integrity import ValidationError
        ex = _executor(pq_setup, warm="jit")
        q = np.asarray(pq_setup[2][:2]).copy()
        q[0] = np.inf
        with serving.Server(ex, serving.ServerConfig(max_batch=16)) as srv:
            with config.validation_policy("raise"):
                with pytest.raises(ValidationError):
                    srv.submit(q, 5)


# ---------------------------------------------------------------------------
# warmup / zero-recompile contract


class TestWarmExecutors:
    def test_zero_recompiles_after_warmup(self, pq_setup):
        ex = _executor(pq_setup, warm="aot")
        with obs.collecting():
            srv = serving.Server(
                ex, serving.ServerConfig(max_batch=16,
                                         max_wait_us=2_000)).start()
            # clients submit host data; a device-side q[:m] would itself
            # compile one slice program per novel m and pollute the count
            q = np.asarray(pq_setup[2])
            try:
                for m in (1, 3, 8, 16, 5, 2):
                    srv.search(q[:m], 5)
                c0 = obs.registry().counter("xla.compiles").value
                for m in (2, 16, 1, 7, 4, 16, 3):
                    srv.search(q[:m], 5)
                c1 = obs.registry().counter("xla.compiles").value
            finally:
                srv.stop()
        assert c1 == c0, f"{c1 - c0} recompiles in steady state"

    def test_zero_recompiles_after_warmup_fused(self, pq_setup):
        """Round-7: scan_mode="fused" rides the same AOT bucket-warmup
        contract — its executables carry a distinct ExecutableCache key
        component and steady state stays recompile-free."""
        res, _, q, index, _ = pq_setup
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="fused",
                                 per_probe_topk=4)
        ex = serving.Executor(res, "ivf_pq", index, ks=(5,),
                              max_batch=16, search_params=sp, warm="aot")
        with obs.collecting():
            srv = serving.Server(
                ex, serving.ServerConfig(max_batch=16,
                                         max_wait_us=2_000)).start()
            q = np.asarray(q)
            try:
                for m in (1, 3, 8, 16, 5, 2):
                    srv.search(q[:m], 5)
                c0 = obs.registry().counter("xla.compiles").value
                for m in (2, 16, 1, 7, 4, 16, 3):
                    srv.search(q[:m], 5)
                c1 = obs.registry().counter("xla.compiles").value
            finally:
                srv.stop()
        assert c1 == c0, f"{c1 - c0} recompiles in steady state"

    def test_fused_prewarm_distinct_cache_key(self, pq_setup):
        """Fused-mode bucket executables must not collide with lut/codes
        entries — scan_mode is part of the ExecutableCache key."""
        res, _, q, index, _ = pq_setup
        from raft_tpu.core.aot import ExecutableCache
        cache = ExecutableCache()
        f1 = cache.get("ivf_pq", res, index, batch=8, k=5, n_probes=8,
                       scan_mode="fused")
        f2 = cache.get("ivf_pq", res, index, batch=8, k=5, n_probes=8,
                       scan_mode="lut")
        f3 = cache.get("ivf_pq", res, index, batch=8, k=5, n_probes=8,
                       scan_mode="fused")
        assert f1 is f3
        assert f1 is not f2
        d, i = f1(jnp.asarray(np.asarray(q)[:8]))
        assert d.shape == (8, 5) and i.shape == (8, 5)

    def test_serving_metrics_recorded(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        with obs.collecting():
            cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000)
            with serving.Server(ex, cfg) as srv:
                for m in (1, 3, 5):
                    srv.search(pq_setup[2][:m], 5)
            snap = obs.snapshot()
        assert snap["counters"]["serving.admitted"] == 3
        assert snap["counters"]["serving.batches"] >= 1
        assert snap["histograms"]["serving.latency.total"]["count"] == 3
        h = snap["histograms"]["serving.latency.queue"]
        assert h["p99"] >= h["p50"] >= 0.0

    def test_ivf_flat_executor(self, pq_setup):
        res, db, q, _, _ = pq_setup
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4), db)
        sp = ivf_flat.SearchParams(n_probes=8)
        ex = serving.Executor(res, "ivf_flat", index, ks=(5,), max_batch=8,
                              search_params=sp)
        with serving.Server(ex, serving.ServerConfig(max_batch=8)) as srv:
            d, i = srv.search(q[:3], 5)
        dd, ii = ivf_flat.search(res, sp, index, q[:3], 5)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))

    def test_brute_force_executor(self, pq_setup):
        res, db, q, _, _ = pq_setup
        from raft_tpu.neighbors import brute_force
        ex = serving.Executor(res, "brute_force", db, ks=(5,), max_batch=8)
        with serving.Server(ex, serving.ServerConfig(max_batch=8)) as srv:
            d, i = srv.search(q[:3], 5)
        dd, ii = brute_force.knn(res, db, q[:3], 5)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))


# ---------------------------------------------------------------------------
# the AOT executable cache (bucket keying)


class TestExecutableCache:
    def test_round_trip_per_bucket(self, pq_setup):
        """Export→load→search round trip at every bucket size: each
        bucket's executable accepts exactly its shape and reproduces the
        direct search."""
        res, _, q, index, sp = pq_setup
        cache = aot.ExecutableCache()
        for batch in (1, 2, 4, 8):
            g = cache.get("ivf_pq", res, index, batch=batch, k=5,
                          n_probes=8, scan_mode="recon")
            d, i = g(q[:batch])
            dd, ii = ivf_pq.search(res, sp, index, q[:batch], 5)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_allclose(np.asarray(d), np.asarray(dd),
                                       rtol=1e-5)
        assert len(cache) == 4

    def test_batch_sizes_do_not_collide(self, pq_setup):
        """Same index, different batch sizes -> distinct executables;
        each accepts only its own batch shape."""
        res, _, q, index, _ = pq_setup
        cache = aot.ExecutableCache()
        g2 = cache.get("ivf_pq", res, index, batch=2, k=5, n_probes=8,
                       scan_mode="recon")
        g4 = cache.get("ivf_pq", res, index, batch=4, k=5, n_probes=8,
                       scan_mode="recon")
        assert g2 is not g4
        assert g2(q[:2])[0].shape == (2, 5)
        assert g4(q[:4])[0].shape == (4, 5)
        with pytest.raises(Exception):
            jax.block_until_ready(g2(q[:4]))
        # a repeat lookup is a cache hit
        assert cache.get("ivf_pq", res, index, batch=2, k=5, n_probes=8,
                         scan_mode="recon") is g2

    def test_key_includes_k_and_nprobes(self, pq_setup):
        res, _, q, index, _ = pq_setup
        cache = aot.ExecutableCache()
        a = cache.get("ivf_pq", res, index, batch=2, k=5, n_probes=8,
                      scan_mode="recon")
        b = cache.get("ivf_pq", res, index, batch=2, k=3, n_probes=8,
                      scan_mode="recon")
        c = cache.get("ivf_pq", res, index, batch=2, k=5, n_probes=4,
                      scan_mode="recon")
        assert len({id(a), id(b), id(c)}) == 3
        assert b(q[:2])[0].shape == (2, 3)

    def test_dead_index_never_hits(self, pq_setup):
        """An id()-recycled dead index must miss, not serve stale
        executables (the weakref validation)."""
        res, db, q, _, sp = pq_setup
        cache = aot.ExecutableCache()
        index1 = ivf_pq.build(
            res, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=2), db[:2000])
        g1 = cache.get("ivf_pq", res, index1, batch=2, k=5, n_probes=4,
                       scan_mode="recon")
        key = next(iter(cache._entries))
        # simulate id reuse: a different index object under the same key
        index2 = ivf_pq.build(
            res, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=2), db[2000:])
        cache._entries[key] = (cache._entries[key][0], g1)
        g2 = cache.get("ivf_pq", res, index2, batch=2, k=5, n_probes=4,
                       scan_mode="recon")
        assert g2 is not g1


# ---------------------------------------------------------------------------
# generation swaps (mutation satellite)


class TestGenerationSwap:
    """extend/delete land on readers only through ``swap_index``: after a
    swap, every bucket executable serves the fresh generation (zero
    wrong-generation executions) and steady state stays recompile-free."""

    def _far_point(self, dim=32):
        # a row far outside the data cloud: its own nearest neighbor by a
        # huge margin, so any request still served by the OLD generation's
        # executables is caught by a single top-1 check
        return np.full((1, dim), 50.0, np.float32)

    def test_extend_then_swap_hits_fresh_index_every_bucket(self,
                                                            pq_setup):
        res, db, _, index, sp = pq_setup
        new_id = int(db.shape[0])
        ex = _executor(pq_setup, warm="aot")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000)
        probe = self._far_point()
        with serving.Server(ex, cfg) as srv:
            _, before = srv.search(probe, 5)
            assert new_id not in np.asarray(before)
            extended = ivf_pq.extend(
                res, index, jnp.asarray(probe),
                np.asarray([new_id], np.int64))
            n_fns = srv.swap_index(extended)
            assert n_fns == len(ex.buckets) * len(ex.ks)
            assert ex.index is extended
            # every bucket size must route to the new generation: pad the
            # probe into requests landing in each bucket
            for m in (1, 2, 3, 8, 16):
                q = np.repeat(probe, m, axis=0)
                _, ids = srv.search(q, 5)
                ids = np.asarray(ids)
                assert (ids[:, 0] == new_id).all(), (m, ids[:, 0])

    def test_zero_steady_state_recompiles_across_swap(self, pq_setup):
        res, db, _, index, _ = pq_setup
        ex = _executor(pq_setup, warm="aot")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000)
        q = np.asarray(pq_setup[2])
        with obs.collecting():
            with serving.Server(ex, cfg) as srv:
                for m in (1, 3, 8, 16, 5, 2):
                    srv.search(q[:m], 5)
                mutated = ivf_pq.delete(res, index, [0, 1, 2])
                srv.swap_index(mutated)   # re-warm happens HERE, not later
                c0 = obs.registry().counter("xla.compiles").value
                for m in (2, 16, 1, 7, 4, 16, 3):
                    srv.search(q[:m], 5)
                c1 = obs.registry().counter("xla.compiles").value
                swaps = obs.registry().counter(
                    "serving.generation_swaps").value
        assert c1 == c0, f"{c1 - c0} recompiles in post-swap steady state"
        assert swaps == 1

    def test_cache_keys_generations_apart(self, pq_setup):
        """Same index object, different generation stamp -> distinct
        executables (the rebalancer mutates and re-serves the same
        logical index; a stale hit would serve deleted rows)."""
        res, _, q, index, _ = pq_setup
        cache = aot.ExecutableCache()
        a = cache.get("ivf_pq", res, index, batch=2, k=5, n_probes=8,
                      scan_mode="recon")
        gen0 = getattr(index, "generation", 0)
        try:
            index.generation = gen0 + 1
            b = cache.get("ivf_pq", res, index, batch=2, k=5, n_probes=8,
                          scan_mode="recon")
            assert b is not a
            # same generation again -> cache hit
            assert cache.get("ivf_pq", res, index, batch=2, k=5,
                             n_probes=8, scan_mode="recon") is b
        finally:
            index.generation = gen0

    def test_swap_rejects_dim_mismatch(self, pq_setup):
        res, db, _, index, sp = pq_setup
        ex = _executor(pq_setup, warm="jit")
        narrow = ivf_pq.build(
            res, ivf_pq.IndexParams(n_lists=8, pq_dim=4, kmeans_n_iters=2),
            np.asarray(db)[:500, :16])
        with pytest.raises(Exception, match="dim"):
            ex.swap_index(narrow)


# ---------------------------------------------------------------------------
# per-request tracing + flight recorder on the live serving path (PR 11)


class TestServingTracing:
    def test_traced_request_records_full_span_chain(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000)
        q = np.asarray(pq_setup[2])
        with obs.collecting(), trace.tracing_scope():
            with serving.Server(ex, cfg) as srv:
                srv.search(q[:1], 5)              # warm the live path
                flight.clear()
                d, i = srv.search(q[:3], 5, tenant="t0")
        assert d.shape == (3, 5)
        traces = flight.traces()
        assert len(traces) == 1
        rt = traces[0]
        assert rt.name == "serving.request" and rt.t1 is not None
        names = [s.name for s in rt.spans]
        for expected in ("serving.admission", "serving.queue",
                        "serving.batch_cut", "serving.exec",
                        "serving.result_slice"):
            assert expected in names, (expected, names)
        assert rt.attrs["tenant"] == "t0"
        assert rt.attrs["rows"] == 3 and rt.attrs["k"] == 5
        cut = next(s for s in rt.spans if s.name == "serving.batch_cut")
        assert cut.attrs["rows"] == 3

    def test_untraced_requests_record_nothing(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000)
        q = np.asarray(pq_setup[2])
        with serving.Server(ex, cfg) as srv:      # tracing off (default)
            srv.search(q[:3], 5)
        assert flight.traces() == []

    def test_deadline_shed_at_submit_lands_flight_event(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        with serving.Server(ex, serving.ServerConfig(max_batch=16)) as srv:
            with pytest.raises(serving.Overloaded):
                srv.submit(pq_setup[2][:2], 5, deadline=Deadline(0.0))
        evs = flight.events("serving.shed.deadline")
        assert len(evs) == 1
        assert evs[0]["attrs"]["phase"] == "submit"
        assert evs[0]["attrs"]["rows"] == 2

    def test_deadline_expiry_while_queued_lands_flight_event(self,
                                                             pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=200_000)
        q = pq_setup[2]
        t = [0.0]
        with trace.tracing_scope(), serving.Server(ex, cfg) as srv:
            dead = Deadline(0.05, clock=lambda: t[0])
            doomed = srv.submit(q[:2], 5, deadline=dead)
            t[0] += 1.0                           # budget lapses queued
            srv.submit(q[:3], 5).result(timeout=10)
            with pytest.raises(DeadlineExceededError):
                doomed.result(timeout=10)
        evs = flight.events("serving.shed.deadline")
        assert [e["attrs"]["phase"] for e in evs] == ["dispatch"]
        # the shed request's trace lands in the ring too, marked shed
        shed = [r for r in flight.traces() if r.attrs.get("shed")]
        assert len(shed) == 1
        assert "serving.queue" in [s.name for s in shed[0].spans]

    def test_queue_full_shed_lands_flight_event(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_queue_rows=4,
                                   max_wait_us=50_000)
        q = pq_setup[2]
        srv = serving.Server(ex, cfg).start()
        try:
            srv.batcher.stop(drain=False)
            fut = srv.submit(q[:3], 5)
            with pytest.raises(serving.Overloaded):
                srv.submit(q[:3], 5)
            srv.batcher.start()
            fut.result(timeout=30)
        finally:
            srv.stop()
        evs = flight.events("serving.shed.queue_full")
        assert len(evs) == 1
        assert evs[0]["attrs"]["rows"] == 3
        assert evs[0]["attrs"]["queued_rows"] == 3
        assert evs[0]["attrs"]["bound"] == 4

    def test_quota_shed_lands_flight_event(self, pq_setup):
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(
            max_batch=16, max_wait_us=100.0,
            tenant_quotas={"metered": (1.0, 4.0)})
        q = pq_setup[2]
        with serving.Server(ex, cfg) as srv:
            srv.search(q[:4], 5, tenant="metered")
            with pytest.raises(serving.QuotaExceeded):
                srv.submit(q[:4], 5, tenant="metered")
        evs = flight.events("serving.shed.quota")
        assert len(evs) == 1
        assert evs[0]["attrs"]["tenant"] == "metered"

    def test_swap_index_lands_generation_swap_event(self, pq_setup):
        res, db, _, index, _ = pq_setup
        ex = _executor(pq_setup, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000)
        with serving.Server(ex, cfg) as srv:
            mutated = ivf_pq.delete(res, index, [0, 1, 2])
            srv.swap_index(mutated)
        evs = flight.events("serving.generation_swap")
        assert len(evs) == 1
        assert evs[0]["attrs"]["generation"] == \
            getattr(mutated, "generation", None)

    def test_zero_recompiles_with_tracing_enabled(self, pq_setup):
        """The PR 11 contract: tracing attaches to timestamps and lazy
        values the serving path already has — enabling it must not
        change bucket shapes or add compiles on warmed traffic."""
        ex = _executor(pq_setup, warm="aot")
        with obs.collecting():
            srv = serving.Server(
                ex, serving.ServerConfig(max_batch=16,
                                         max_wait_us=2_000)).start()
            q = np.asarray(pq_setup[2])
            try:
                for m in (1, 3, 8, 16, 5, 2):
                    srv.search(q[:m], 5)
                c0 = obs.registry().counter("xla.compiles").value
                with trace.tracing_scope():
                    for m in (2, 16, 1, 7, 4, 16, 3):
                        srv.search(q[:m], 5)
                c1 = obs.registry().counter("xla.compiles").value
            finally:
                srv.stop()
        assert c1 == c0, \
            f"{c1 - c0} recompiles on warmed traffic with tracing on"
        assert len(flight.traces()) == 7


# ---------------------------------------------------------------------------
# histogram metric (observability satellite)


class TestHistogram:
    def test_observe_and_quantiles(self):
        reg = obs.MetricsRegistry()
        h = reg.histogram("lat")
        for v in (0.001, 0.002, 0.004, 0.008, 0.1):
            h.observe(v)
        d = h.as_dict()
        assert d["count"] == 5
        assert d["min"] == pytest.approx(0.001)
        assert d["max"] == pytest.approx(0.1)
        assert 0.0 < d["p50"] <= d["p95"] <= d["p99"] <= 0.1

    def test_custom_bounds_and_overflow(self):
        reg = obs.MetricsRegistry()
        h = reg.histogram("x", bounds=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 3.0, 100.0):
            h.observe(v)
        d = h.as_dict()
        assert d["counts"] == [1, 1, 1, 1]     # last = overflow bucket
        assert d["p99"] <= d["max"] == 100.0

    def test_empty_histogram(self):
        reg = obs.MetricsRegistry()
        h = reg.histogram("empty")
        assert h.quantile(0.99) == 0.0
        assert h.as_dict()["min"] == 0.0

    def test_get_or_create_identity(self):
        reg = obs.MetricsRegistry()
        assert reg.histogram("h") is reg.histogram("h")

    def test_snapshot_and_prometheus_export(self):
        reg = obs.MetricsRegistry()
        reg.histogram("serving.latency.total").observe(0.01)
        snap = reg.snapshot()
        assert "serving.latency.total" in snap["histograms"]
        text = obs.to_prometheus(snap)
        assert "# TYPE raft_tpu_serving_latency_total histogram" in text
        assert 'raft_tpu_serving_latency_total_bucket{le="+Inf"} 1' in text
        assert "raft_tpu_serving_latency_total_p99" in text
        assert "raft_tpu_serving_latency_total_count 1" in text

    def test_json_roundtrip_with_histogram(self):
        import json
        reg = obs.MetricsRegistry()
        reg.histogram("h").observe(1.0)
        back = json.loads(obs.to_json(reg.snapshot()))
        assert back == reg.snapshot()

    def test_zero_work_while_disabled(self, pq_setup):
        """Counter contract: with collection off, serving records no
        histogram samples (and creates no histograms)."""
        ex = _executor(pq_setup, warm="jit")
        obs.disable()
        obs.reset()
        with serving.Server(ex,
                            serving.ServerConfig(max_batch=16)) as srv:
            srv.search(pq_setup[2][:2], 5)
        assert obs.snapshot()["histograms"] == {}
        assert obs.snapshot()["counters"] == {}


# ---------------------------------------------------------------------------
# concurrency smoke


class TestConcurrentClients:
    def test_many_threads_submit(self, pq_setup):
        res, _, q, index, sp = pq_setup
        ex = _executor(pq_setup, warm="aot")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=1_000,
                                   max_queue_rows=512)
        errs, results = [], []
        with serving.Server(ex, cfg) as srv:
            def client(j):
                try:
                    for _ in range(5):
                        d, i = srv.search(q[j:j + 2], 5, timeout=30)
                        results.append(np.asarray(i))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            threads = [threading.Thread(target=client, args=(j,))
                       for j in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errs, errs
        assert len(results) == 40
        for i in results:
            assert (i >= 0).all()
