"""Label utilities tests (reference: cpp/test/label/label.cu pattern —
compute-vs-reference on small arrays)."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.label import get_unique_labels, make_monotonic, merge_labels


class TestClassLabels:
    def test_unique_sorted(self):
        labels = jnp.asarray([5, 3, 3, 9, 5, 1], jnp.int32)
        uniq, count = get_unique_labels(labels)
        assert int(count) == 4
        np.testing.assert_array_equal(np.asarray(uniq)[:4], [1, 3, 5, 9])

    def test_unique_duplicate_heavy_padding(self):
        # regression: padding slots must hold the LARGEST label (keeping the
        # array sorted), not leftover ascending duplicates
        labels = jnp.asarray([1, 1, 1, 1, 1, 2, 3], jnp.int32)
        uniq, count = get_unique_labels(labels)
        u = np.asarray(uniq)
        assert int(count) == 3
        np.testing.assert_array_equal(u[:3], [1, 2, 3])
        assert (u[3:] == 3).all()
        assert (np.diff(u) >= 0).all()

    def test_make_monotonic_duplicate_heavy(self):
        labels = jnp.asarray([1, 1, 1, 1, 1, 2, 3], jnp.int32)
        out = np.asarray(make_monotonic(labels))
        np.testing.assert_array_equal(out, [0, 0, 0, 0, 0, 1, 2])

    def test_make_monotonic_matches_numpy(self):
        rng = np.random.default_rng(0)
        labels = rng.choice([7, -3, 42, 0, 19], size=50).astype(np.int32)
        out = np.asarray(make_monotonic(jnp.asarray(labels)))
        _, ref = np.unique(labels, return_inverse=True)
        np.testing.assert_array_equal(out, ref)

    def test_unique_max_labels_exceeds_n(self):
        labels = jnp.asarray([3, 1, 3, 1], jnp.int32)
        uniq, count = get_unique_labels(labels, max_labels=6)
        u = np.asarray(uniq)
        assert u.shape == (6,)
        assert int(count) == 2
        np.testing.assert_array_equal(u[:2], [1, 3])
        assert (u[2:] == 3).all()

    def test_make_monotonic_one_based(self):
        labels = jnp.asarray([10, 20, 10], jnp.int32)
        out = np.asarray(make_monotonic(labels, zero_based=False))
        np.testing.assert_array_equal(out, [1, 2, 1])


class TestMergeLabels:
    def test_merge_unions_groups(self):
        # a: {0,1} {2,3}; b: {1,2} — union connects all four
        a = jnp.asarray([0, 0, 2, 2], jnp.int32)
        b = jnp.asarray([0, 1, 1, 3], jnp.int32)
        mask = jnp.ones(4, jnp.bool_)
        out = np.asarray(merge_labels(a, b, mask))
        assert len(np.unique(out)) == 1

    def test_merge_respects_mask(self):
        a = jnp.asarray([0, 0, 2, 2], jnp.int32)
        b = jnp.asarray([0, 1, 1, 3], jnp.int32)
        mask = jnp.asarray([True, True, False, True])
        out = np.asarray(merge_labels(a, b, mask))
        # row 2 masked out: groups {0,1} and {3} stay separate
        assert out[0] == out[1]
        assert out[3] != out[0]
