"""Cluster layer tests.

Mirrors the reference's compute-vs-reference strategy (SURVEY.md §4):
inputs from raft_tpu.random.make_blobs, results checked against known cluster
structure and against a naive numpy Lloyd implementation.
Reference tests: cpp/test/cluster/kmeans.cu, kmeans_balanced.cu.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster import (
    InitMethod,
    KMeansBalancedParams,
    KMeansParams,
    kmeans,
    kmeans_balanced,
)
from raft_tpu.distance.types import DistanceType
from raft_tpu.random import make_blobs


def _blobs(res, n=600, d=8, k=5, std=0.3, seed=0):
    X, labels = make_blobs(n, d, n_clusters=k, cluster_std=std, seed=seed,
                           shuffle=True)
    return np.asarray(X), np.asarray(labels)


def _naive_lloyd(X, c0, iters=50):
    c = c0.copy()
    for _ in range(iters):
        d = ((X[:, None, :] - c[None, :, :]) ** 2).sum(-1)
        lab = d.argmin(1)
        for j in range(c.shape[0]):
            if (lab == j).any():
                c[j] = X[lab == j].mean(0)
    d = ((X[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    return c, lab, d.min(1).sum()


class TestKMeans:
    def test_fit_recovers_blobs(self, res):
        X, true_labels = _blobs(res, k=5)
        params = KMeansParams(n_clusters=5, max_iter=100, tol=1e-6, seed=3)
        centroids, inertia, n_iter = kmeans.fit(res, params, X)
        assert centroids.shape == (5, X.shape[1])
        assert int(n_iter) >= 1
        labels, _ = kmeans.predict(res, params, X, centroids)
        # same-blob points should land in the same cluster (ARI-style check)
        labels = np.asarray(labels)
        for b in range(5):
            blob = labels[true_labels == b]
            # dominant assignment covers nearly the whole blob
            frac = np.bincount(blob, minlength=5).max() / blob.size
            assert frac > 0.95

    def test_inertia_close_to_naive(self, res):
        X, _ = _blobs(res, n=400, d=4, k=3)
        params = KMeansParams(n_clusters=3, max_iter=100, tol=1e-8,
                              n_init=3, seed=0)
        _, inertia, _ = kmeans.fit(res, params, X)
        # naive Lloyd from a decent start
        rng = np.random.default_rng(0)
        best = np.inf
        for s in range(3):
            c0 = X[rng.choice(X.shape[0], 3, replace=False)]
            _, _, cost = _naive_lloyd(X, c0)
            best = min(best, cost)
        assert float(inertia) <= best * 1.05 + 1e-6

    def test_init_array(self, res):
        X, _ = _blobs(res, n=300, d=4, k=3)
        c0 = X[:3].copy()
        params = KMeansParams(n_clusters=3, init=InitMethod.Array,
                              max_iter=50)
        centroids, inertia, _ = kmeans.fit(res, params, X, centroids=c0)
        assert np.isfinite(float(inertia))

    def test_predict_and_transform_shapes(self, res):
        X, _ = _blobs(res, n=200, d=6, k=4)
        params = KMeansParams(n_clusters=4, max_iter=30)
        centroids, _, _ = kmeans.fit(res, params, X)
        labels, inertia = kmeans.predict(res, params, X, centroids)
        assert labels.shape == (200,) and labels.dtype == jnp.int32
        t = kmeans.transform(res, params, X, centroids)
        assert t.shape == (200, 4)
        # transform distances consistent with labels
        assert np.array_equal(np.asarray(t).argmin(1), np.asarray(labels))

    def test_update_centroids_empty_cluster(self, res):
        X = np.random.default_rng(0).normal(size=(50, 3)).astype(np.float32)
        labels = jnp.zeros(50, jnp.int32)  # all in cluster 0; cluster 1 empty
        old = jnp.asarray(np.ones((2, 3), np.float32) * 7)
        c, counts = kmeans.update_centroids(jnp.asarray(X), labels, 2,
                                            old_centroids=old)
        np.testing.assert_allclose(np.asarray(c[0]), X.mean(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(c[1]), 7 * np.ones(3))
        assert int(counts[1]) == 0

    def test_cluster_cost(self, res):
        X, _ = _blobs(res, n=100, d=4, k=2)
        c = jnp.asarray(X[:2])
        cost = kmeans.cluster_cost(jnp.asarray(X), c)
        d = ((X[:, None, :] - X[None, :2, :]) ** 2).sum(-1).min(1).sum()
        np.testing.assert_allclose(float(cost), d, rtol=1e-4)

    def test_find_k(self, res):
        X, _ = _blobs(res, n=400, d=4, k=4, std=0.2, seed=7)
        best_k, c, inertia = kmeans.find_k(res, X, k_max=8, k_min=2)
        assert 3 <= best_k <= 6


class TestKMeansBalanced:
    def test_fit_predict_balanced(self, res):
        X, _ = _blobs(res, n=1024, d=8, k=8, std=0.5)
        params = KMeansBalancedParams(n_iters=20)
        centroids, labels = kmeans_balanced.fit_predict(res, params, X, 16)
        assert centroids.shape == (16, 8)
        sizes = np.bincount(np.asarray(labels), minlength=16)
        # balance property: no cluster hugely overloaded, few empty
        assert sizes.max() <= X.shape[0] // 2
        assert (sizes > 0).sum() >= 12

    def test_predict_matches_nearest(self, res):
        X, _ = _blobs(res, n=200, d=4, k=4)
        params = KMeansBalancedParams(n_iters=10)
        centroids = kmeans_balanced.fit(res, params, X, 4)
        labels = np.asarray(kmeans_balanced.predict(res, params, X, centroids))
        d = ((X[:, None, :] - np.asarray(centroids)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_inner_product_metric(self, res):
        X, _ = _blobs(res, n=300, d=8, k=4)
        X = X / np.linalg.norm(X, axis=1, keepdims=True)
        params = KMeansBalancedParams(n_iters=10,
                                      metric=DistanceType.InnerProduct)
        centroids, labels = kmeans_balanced.fit_predict(res, params, X, 4)
        # centroids unit-norm (spherical k-means)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(centroids), axis=1), 1.0, atol=1e-4)

    def test_build_clusters(self, res):
        X, _ = _blobs(res, n=256, d=4, k=4)
        params = KMeansBalancedParams(n_iters=5)
        c, labels, sizes = kmeans_balanced.build_clusters(res, params, X, 8)
        assert int(jnp.sum(sizes)) == 256
        np.testing.assert_array_equal(
            np.asarray(sizes),
            np.bincount(np.asarray(labels), minlength=8))

    def test_hierarchical_matches_single_level_quality(self, res):
        """Two-level mesocluster build (the build_hierarchical analogue):
        exact center count, bounded skew, and clustering cost comparable
        to the single-level loop."""
        X, _ = _blobs(res, n=6000, d=16, k=32, std=0.6)
        params = KMeansBalancedParams(n_iters=10)
        c_h = kmeans_balanced.fit(res, params, X, 128, hierarchical=True)
        assert c_h.shape == (128, 16)
        lab = np.asarray(kmeans_balanced.predict(res, params, X, c_h))
        sizes = np.bincount(lab, minlength=128)
        assert (sizes > 0).sum() >= 100          # few empty lists
        assert sizes.max() <= X.shape[0] // 8    # no megacluster

        c_s = kmeans_balanced.fit(res, params, X, 128, hierarchical=False)
        lab_s = np.asarray(kmeans_balanced.predict(res, params, X, c_s))

        def cost(c, lab_):
            return float(((np.asarray(X)
                           - np.asarray(c)[lab_]) ** 2).sum())

        assert cost(c_h, lab) <= 1.5 * cost(c_s, lab_s)

    def test_fused_balanced_loop_matches_xla_branch(self, res):
        """The fused-kernel branch of _balanced_loop (TPU-only in
        production) must match the XLA branch — exercised here through
        the Pallas interpreter so CI covers the wiring (r4 review)."""
        import jax

        X, _ = _blobs(res, n=512, d=32, k=8, std=0.5)
        X = np.asarray(jnp.asarray(X).astype(jnp.bfloat16)
                       .astype(jnp.float32))
        c0 = jnp.asarray(X[:16])
        key = jax.random.key(0)
        c_x, lab_x = kmeans_balanced._balanced_loop(
            jnp.asarray(X), c0, key, 16, 5, DistanceType.L2Expanded)
        c_f, lab_f = kmeans_balanced._balanced_loop(
            jnp.asarray(X), c0, key, 16, 5, DistanceType.L2Expanded,
            use_fused=128, fused_interpret=True)

        def cost(c):
            d = ((X[:, None, :] - np.asarray(c)[None]) ** 2).sum(-1)
            return d.min(1).sum()

        np.testing.assert_allclose(cost(c_f), cost(c_x), rtol=2e-2)
        # same balance behavior (trajectories may diverge on re-seed
        # draws once distances differ at bf16 rounding — quality and
        # balance are the contract, not label identity)
        sizes = np.bincount(np.asarray(lab_f), minlength=16)
        assert (sizes > 0).sum() >= 12
        assert sizes.max() <= X.shape[0] // 2

    def test_meso_partition_sample_covers_members(self, res):
        """Sampled indices must belong to the right mesocluster segment
        (cycling when a mesocluster has fewer than `per` members)."""
        import jax

        labels = jnp.asarray(np.repeat([0, 1, 2, 3], [5, 100, 30, 2]))
        idx = kmeans_balanced._meso_partition_sample(
            labels, jax.random.key(0), 4, 16)
        got = np.asarray(labels)[np.asarray(idx)]
        np.testing.assert_array_equal(got,
                                      np.repeat([0, 1, 2, 3], 16
                                                ).reshape(4, 16))
