"""Integrity subsystem tests: verify() negative tests per index type,
boundary-validation policies, recall canaries (build/serialize/load/extend,
regression detection), and a seeded degenerate-input fuzz suite.

Reference intent: RAFT itself ships no index verifier — these tests pin the
invariants raft_tpu.integrity adds on top (ISSUE PR 4, robustness archetype).
"""

import dataclasses
import io
import os
import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import DeviceResources, config, integrity, observability as obs
from raft_tpu.cluster import kmeans
from raft_tpu.core.error import RaftError
from raft_tpu.distance.types import DistanceType
from raft_tpu.integrity import IntegrityError, ValidationError
from raft_tpu.integrity import canary as _canary
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

# pinned for reproducibility; CI's fuzz job sets it explicitly so local
# reruns of a CI failure replay the identical degenerate inputs
SEED = int(os.environ.get("RAFT_TPU_FUZZ_SEED", "20260805"))


def _data(n, d, seed=0):
    rng = np.random.default_rng(SEED + seed)
    return jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))


def _counter(name):
    return obs.registry().snapshot()["counters"].get(name, 0)


@pytest.fixture(scope="module")
def ires():
    return DeviceResources(seed=7)


@pytest.fixture
def collecting():
    # integrity.* counters honor the observability zero-overhead
    # contract: they record only while collection is enabled
    obs.enable()
    try:
        yield
    finally:
        obs.disable()


@pytest.fixture(scope="module")
def flat_index(ires):
    params = ivf_flat.IndexParams(n_lists=8, canary_queries=16, canary_k=5,
                                  canary_floor=0.3)
    return ivf_flat.build(ires, params, _data(400, 16))


@pytest.fixture(scope="module")
def pq_index(ires):
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=4, canary_queries=16,
                                canary_k=5, canary_floor=0.2)
    return ivf_pq.build(ires, params, _data(400, 16, seed=1))


@pytest.fixture(scope="module")
def cagra_index(ires):
    params = cagra.IndexParams(graph_degree=16, intermediate_graph_degree=32,
                               canary_queries=16, canary_k=5,
                               canary_floor=0.3)
    return cagra.build(ires, params, _data(300, 16, seed=2))


def _fullest(index):
    """(list, size) of the most populated IVF list."""
    sizes = np.asarray(index.list_sizes)
    li = int(np.argmax(sizes))
    return li, int(sizes[li])


# ---------------------------------------------------------------------------
# verify(): healthy indexes pass every level
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("level", ["structural", "statistical", "full"])
def test_verify_healthy_flat(ires, flat_index, level):
    integrity.verify(flat_index, level=level, res=ires)


@pytest.mark.parametrize("level", ["structural", "statistical", "full"])
def test_verify_healthy_pq(ires, pq_index, level):
    integrity.verify(pq_index, level=level, res=ires)


@pytest.mark.parametrize("level", ["structural", "statistical", "full"])
def test_verify_healthy_cagra(ires, cagra_index, level):
    integrity.verify(cagra_index, level=level, res=ires)


def test_verify_bad_level(flat_index):
    with pytest.raises(ValueError):
        integrity.verify(flat_index, level="paranoid")


def test_verify_full_needs_res(flat_index):
    with pytest.raises(ValueError):
        integrity.verify(flat_index, level="full")


def test_verify_full_without_canaries(ires):
    index = ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=4),
                           _data(64, 8, seed=3))
    with pytest.raises(IntegrityError) as ei:
        integrity.verify(index, level="full", res=ires)
    assert ei.value.invariant == "canary.missing"


def test_verify_counts_calls(flat_index, collecting):
    before = _counter("integrity.verify.calls")
    integrity.verify(flat_index, level="structural")
    assert _counter("integrity.verify.calls") == before + 1


# ---------------------------------------------------------------------------
# verify(): negative tests — each corruption names its invariant
# ---------------------------------------------------------------------------

def _expect_invariant(index, invariant, level="structural", **kw):
    before = _counter("integrity.verify.failures")
    with pytest.raises(IntegrityError) as ei:
        integrity.verify(index, level=level, **kw)
    assert ei.value.invariant == invariant, ei.value
    if obs.enabled():
        assert _counter("integrity.verify.failures") == before + 1
    return ei.value


def test_verify_failure_counter(flat_index, collecting):
    sizes = flat_index.list_sizes.at[0].set(-1)
    bad = dataclasses.replace(flat_index, list_sizes=sizes)
    before = _counter("integrity.verify.failures")
    _expect_invariant(bad, "ivf_flat.list_sizes.range")
    assert _counter("integrity.verify.failures") == before + 1


def test_flat_corrupt_list_size_range(flat_index):
    sizes = flat_index.list_sizes.at[0].set(flat_index.capacity + 5)
    bad = dataclasses.replace(flat_index, list_sizes=sizes)
    err = _expect_invariant(bad, "ivf_flat.list_sizes.range")
    assert err.coord == (0,)


def test_flat_corrupt_list_size_slots(flat_index):
    li, sz = _fullest(flat_index)
    assert sz >= 2
    sizes = flat_index.list_sizes.at[li].set(sz - 1)
    bad = dataclasses.replace(flat_index, list_sizes=sizes)
    _expect_invariant(bad, "ivf_flat.list_sizes.slots")


def test_flat_oob_id(flat_index):
    li, _ = _fullest(flat_index)
    total = int(np.asarray(flat_index.list_sizes).sum())
    lidx = flat_index.list_indices.at[li, 0].set(total + 100)
    bad = dataclasses.replace(flat_index, list_indices=lidx)
    _expect_invariant(bad, "ivf_flat.ids.range")


def test_flat_duplicate_id(flat_index):
    li, sz = _fullest(flat_index)
    assert sz >= 2
    dup = flat_index.list_indices[li, 1]
    lidx = flat_index.list_indices.at[li, 0].set(dup)
    bad = dataclasses.replace(flat_index, list_indices=lidx)
    _expect_invariant(bad, "ivf_flat.ids.unique")


def test_flat_stale_norm_cache(flat_index):
    li, _ = _fullest(flat_index)
    good_sq = jnp.sum(flat_index.list_data.astype(jnp.float32) ** 2, axis=-1)
    bad = dataclasses.replace(flat_index,
                              list_data_sq=good_sq.at[li, 0].add(7.0))
    _expect_invariant(bad, "ivf_flat.list_data_sq.stale")
    # the un-perturbed recomputation passes
    integrity.verify(dataclasses.replace(flat_index, list_data_sq=good_sq))


def test_flat_nonfinite_center(flat_index):
    centers = flat_index.centers.at[2, 3].set(jnp.nan)
    bad = dataclasses.replace(flat_index, centers=centers)
    # structural does not look at values...
    integrity.verify(bad, level="structural")
    # ...statistical does
    err = _expect_invariant(bad, "ivf_flat.centers.finite",
                            level="statistical")
    assert err.coord == (2, 3)


def test_pq_corrupt_list_size_range(pq_index):
    sizes = pq_index.list_sizes.at[1].set(-3)
    bad = dataclasses.replace(pq_index, list_sizes=sizes)
    _expect_invariant(bad, "ivf_pq.list_sizes.range")


def test_pq_oob_id(pq_index):
    li, _ = _fullest(pq_index)
    total = int(np.asarray(pq_index.list_sizes).sum())
    lidx = pq_index.list_indices.at[li, 0].set(total + 9)
    bad = dataclasses.replace(pq_index, list_indices=lidx)
    _expect_invariant(bad, "ivf_pq.ids.range")


def test_pq_stale_recon_cache(pq_index):
    assert pq_index.list_recon is not None
    li, _ = _fullest(pq_index)
    recon = pq_index.list_recon.at[li, 0, :].add(1.0)
    bad = dataclasses.replace(pq_index, list_recon=recon)
    _expect_invariant(bad, "ivf_pq.list_recon.stale")


def test_pq_stale_recon_norms(pq_index):
    assert pq_index.list_recon_sq is not None
    li, _ = _fullest(pq_index)
    rsq = pq_index.list_recon_sq.at[li, 0].add(50.0)
    bad = dataclasses.replace(pq_index, list_recon_sq=rsq)
    _expect_invariant(bad, "ivf_pq.list_recon_sq.stale")


def test_pq_rotation_not_orthonormal(pq_index):
    bad = dataclasses.replace(pq_index, rotation=pq_index.rotation * 2.0)
    integrity.verify(bad, level="structural")
    _expect_invariant(bad, "ivf_pq.rotation.orthonormal",
                      level="statistical")


def test_cagra_oob_edge(cagra_index):
    graph = cagra_index.graph.at[0, 0].set(cagra_index.size + 5)
    bad = dataclasses.replace(cagra_index, graph=graph)
    err = _expect_invariant(bad, "cagra.graph.range")
    assert err.coord == (0, 0)


def test_cagra_self_loop(cagra_index):
    graph = cagra_index.graph.at[3, 1].set(3)
    bad = dataclasses.replace(cagra_index, graph=graph)
    err = _expect_invariant(bad, "cagra.graph.self_loop")
    assert err.coord == (3, 1)


def test_cagra_bad_degree(cagra_index):
    # wider graph than the node count allows (degree must be <= n-1)
    n = cagra_index.size
    wide = jnp.tile(cagra_index.graph, (1, (n // 16) + 1))
    bad = dataclasses.replace(cagra_index, graph=wide)
    _expect_invariant(bad, "cagra.graph.degree")


def test_cagra_nonfinite_dataset(cagra_index):
    ds = cagra_index.dataset.at[5, 0].set(jnp.inf)
    bad = dataclasses.replace(cagra_index, dataset=ds)
    integrity.verify(bad, level="structural")
    _expect_invariant(bad, "cagra.dataset.finite", level="statistical")


# ---------------------------------------------------------------------------
# canaries: build, serialize round-trip, regression detection
# ---------------------------------------------------------------------------

def test_canaries_recorded_at_build(flat_index, pq_index, cagra_index):
    for index in (flat_index, pq_index, cagra_index):
        cs = index.canaries
        assert cs is not None
        assert cs.queries.shape[0] == 16
        assert cs.gt_ids.shape == (16, 5)
        assert cs.build_recall >= cs.floor


def test_canaries_survive_serialize_roundtrip(ires, flat_index, pq_index,
                                              cagra_index):
    for mod, index in ((ivf_flat, flat_index), (ivf_pq, pq_index),
                       (cagra, cagra_index)):
        buf = io.BytesIO()
        mod.serialize(ires, buf, index)
        buf.seek(0)
        out = mod.deserialize(ires, buf)
        assert out.canaries is not None
        np.testing.assert_array_equal(np.asarray(out.canaries.gt_ids),
                                      np.asarray(index.canaries.gt_ids))
        assert out.canaries.floor == index.canaries.floor
        assert out.canaries.build_recall == pytest.approx(
            index.canaries.build_recall)


def test_no_canary_roundtrip(ires):
    index = ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=4),
                           _data(64, 8, seed=4))
    buf = io.BytesIO()
    ivf_flat.serialize(ires, buf, index)
    buf.seek(0)
    assert ivf_flat.deserialize(ires, buf).canaries is None


def test_health_check_passes_on_healthy(ires, flat_index):
    report = _canary.health_check(ires, flat_index)
    assert report.ok
    assert report.recall >= flat_index.canaries.floor


def test_health_check_detects_regression_after_load(ires, flat_index,
                                                    tmp_path, collecting):
    path = str(tmp_path / "flat.idx")
    ivf_flat.save(ires, path, flat_index)
    loaded = ivf_flat.load(ires, path)          # auto health check passes
    assert loaded.canaries is not None
    # inject a recall regression: the stored vectors are zeroed, so the
    # canary queries no longer find their true neighbors
    bad = dataclasses.replace(loaded,
                              list_data=jnp.zeros_like(loaded.list_data),
                              list_data_sq=None)
    assert bad.canaries is not None             # dataclasses.replace carries
    before = _counter("integrity.canary.failures")
    with pytest.raises(IntegrityError) as ei:
        _canary.health_check(ires, bad)
    assert ei.value.invariant == "canary.recall_floor"
    assert _counter("integrity.canary.failures") == before + 1
    report = _canary.health_check(ires, bad, raise_on_fail=False)
    assert not report.ok


def test_load_auto_check_raises_on_corrupt_file(ires, flat_index, tmp_path,
                                                collecting):
    bad = dataclasses.replace(flat_index,
                              list_data=jnp.zeros_like(flat_index.list_data),
                              list_data_sq=None)
    path = str(tmp_path / "corrupt.idx")
    ivf_flat.save(ires, path, bad)
    before = _counter("integrity.canary.auto.load")
    with pytest.raises(IntegrityError) as ei:
        ivf_flat.load(ires, path)
    assert ei.value.invariant == "canary.recall_floor"
    assert _counter("integrity.canary.auto.load") == before + 1


def test_extend_carries_and_checks_canaries(ires, flat_index):
    new = _data(40, 16, seed=5)
    out = ivf_flat.extend(ires, flat_index, new,
                          jnp.arange(400, 440, dtype=jnp.int32))
    assert out.canaries is not None
    assert _canary.health_check(ires, out).ok


def test_verify_full_uses_canaries(ires, flat_index):
    bad = dataclasses.replace(flat_index,
                              list_data=jnp.zeros_like(flat_index.list_data),
                              list_data_sq=None)
    with pytest.raises(IntegrityError) as ei:
        integrity.verify(bad, level="full", res=ires)
    assert ei.value.invariant == "canary.recall_floor"


# ---------------------------------------------------------------------------
# boundary validation: policies raise | mask | off
# ---------------------------------------------------------------------------

def _nan_queries(n=6, d=16, bad_rows=(1, 4)):
    q = np.asarray(_data(n, d, seed=6))
    q = q.copy()
    q[bad_rows[0], 0] = np.nan
    q[bad_rows[1], 2] = np.inf
    return jnp.asarray(q)


def test_policy_raise_nonfinite(ires, flat_index, collecting):
    before = _counter("integrity.boundary.raised")
    with pytest.raises(ValidationError) as ei:
        ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8), flat_index,
                        _nan_queries(), k=5)
    assert ei.value.invariant == "boundary.nonfinite"
    assert ei.value.coord == (1,)               # first bad row
    assert _counter("integrity.boundary.raised") == before + 1


def test_validation_error_is_value_error(ires, flat_index):
    # callers with pre-existing `except ValueError` handlers keep working
    with pytest.raises(ValueError):
        ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8), flat_index,
                        _nan_queries(), k=5)


@pytest.mark.parametrize("kind", ["flat", "pq", "cagra"])
def test_policy_mask_flags_bad_rows(ires, flat_index, pq_index, cagra_index,
                                    kind):
    index = {"flat": flat_index, "pq": pq_index, "cagra": cagra_index}[kind]
    mod = {"flat": ivf_flat, "pq": ivf_pq, "cagra": cagra}[kind]
    q = _nan_queries(d=index.dim)
    params = (mod.SearchParams() if kind == "cagra"
              else mod.SearchParams(n_probes=8))
    with config.validation_policy("mask"):
        d, i = mod.search(ires, params, index, q, k=5)
    d, i = np.asarray(d), np.asarray(i)
    for row in (1, 4):                          # masked rows are flagged
        assert (i[row] == -1).all()
        assert (d[row] == np.inf).all()
    for row in (0, 2, 3, 5):                    # clean rows still answered
        assert (i[row] >= 0).all() and (i[row] < index.size).all()
        assert np.isfinite(d[row]).all()


def test_policy_mask_counts_rows(ires, flat_index):
    obs.enable()
    try:
        with config.validation_policy("mask"):
            before = _counter("integrity.boundary.masked_rows")
            ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8),
                            flat_index, _nan_queries(), k=5)
            assert (_counter("integrity.boundary.masked_rows")
                    == before + 2)
    finally:
        obs.disable()


def test_policy_off_no_raise(ires, flat_index):
    with config.validation_policy("off"):
        d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8),
                               flat_index, _nan_queries(), k=5)
    assert i.shape == (6, 5)                    # no crash; contents undefined


def test_policy_off_checks_counter_flat(ires, flat_index, collecting):
    # "off" must add zero validation work — not even a counter bump from
    # the guard itself (collection enabled so "raise" WOULD record)
    q = _data(4, 16, seed=7)
    before = _counter("integrity.boundary.checks")
    ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8), flat_index,
                    q, k=5)
    assert _counter("integrity.boundary.checks") == before + 1
    with config.validation_policy("off"):
        ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8), flat_index,
                        q, k=5)
    assert _counter("integrity.boundary.checks") == before + 1


def test_boundary_rank_and_dim_errors(ires, flat_index):
    with pytest.raises(ValidationError) as ei:
        ivf_flat.search(ires, ivf_flat.SearchParams(), flat_index,
                        jnp.ones((16,), jnp.float32), k=5)
    assert ei.value.invariant == "boundary.rank"
    with pytest.raises(ValidationError) as ei:
        ivf_flat.search(ires, ivf_flat.SearchParams(), flat_index,
                        jnp.ones((2, 7), jnp.float32), k=5)
    assert ei.value.invariant == "boundary.dim"


def test_boundary_empty_error(ires):
    with pytest.raises(ValidationError) as ei:
        kmeans.fit(ires, kmeans.KMeansParams(n_clusters=2),
                   jnp.zeros((0, 4), jnp.float32))
    assert ei.value.invariant == "boundary.empty"


def test_kmeans_guards_nonfinite(ires):
    X = np.asarray(_data(64, 8, seed=8)).copy()
    X[3, 3] = np.nan
    with pytest.raises(ValidationError):
        kmeans.fit(ires, kmeans.KMeansParams(n_clusters=4), jnp.asarray(X))


def test_brute_force_mask_policy(ires):
    db = _data(50, 16, seed=9)
    with config.validation_policy("mask"):
        d, i = brute_force.knn(ires, db, _nan_queries(), k=3)
    i = np.asarray(i)
    assert (i[1] == -1).all() and (i[4] == -1).all()
    assert (i[0] >= 0).all()


# ---------------------------------------------------------------------------
# seeded degenerate-input fuzz suite
# ---------------------------------------------------------------------------

def test_fuzz_k_exceeds_rows(ires):
    # brute force rejects k > n cleanly; IVF search pads with sentinels
    db = _data(5, 8, seed=10)
    q = _data(3, 8, seed=11)
    with pytest.raises(RaftError):
        brute_force.knn(ires, db, q, k=16)
    index = ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=2), db)
    d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=2), index,
                           q, k=16)
    i = np.asarray(i)
    assert ((i >= -1) & (i < 5)).all()
    assert (np.sort(i[i >= 0].reshape(3, -1), axis=1)
            == np.arange(5)).all()              # all real rows found once
    pq = ivf_pq.build(ires, ivf_pq.IndexParams(n_lists=2, pq_dim=2), db)
    d, i = ivf_pq.search(ires, ivf_pq.SearchParams(n_probes=2), pq, q, k=16)
    assert ((np.asarray(i) >= -1) & (np.asarray(i) < 5)).all()


def test_fuzz_single_row(ires):
    db = _data(1, 8, seed=12)
    d, i = brute_force.knn(ires, db, _data(2, 8, seed=13), k=1)
    assert (np.asarray(i) == 0).all()
    assert np.isfinite(np.asarray(d)).all()


def test_fuzz_empty_dataset_rejected(ires):
    empty = jnp.zeros((0, 8), jnp.float32)
    for build in (
            lambda: ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=2),
                                   empty),
            lambda: ivf_pq.build(ires, ivf_pq.IndexParams(n_lists=2,
                                                          pq_dim=2), empty),
            lambda: cagra.build(ires, cagra.IndexParams(
                graph_degree=4, intermediate_graph_degree=8), empty)):
        with pytest.raises((RaftError, ValueError)):
            build()


def test_fuzz_more_lists_than_rows(ires):
    with pytest.raises((RaftError, ValueError)):
        ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=64),
                       _data(8, 8, seed=14))


def test_fuzz_constant_dataset(ires):
    const = jnp.ones((64, 8), jnp.float32)
    q = jnp.ones((4, 8), jnp.float32)
    index = ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=4), const)
    integrity.verify(index, level="statistical")
    d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=4), index,
                           q, k=4)
    assert ((np.asarray(i) >= 0) & (np.asarray(i) < 64)).all()
    assert np.allclose(np.asarray(d), 0.0, atol=1e-4)
    pq = ivf_pq.build(ires, ivf_pq.IndexParams(n_lists=4, pq_dim=2), const)
    integrity.verify(pq, level="statistical")
    graph = cagra.build(ires, cagra.IndexParams(graph_degree=8,
                                                intermediate_graph_degree=16),
                        const)
    integrity.verify(graph, level="statistical")
    cents, _, _ = kmeans.fit(ires, kmeans.KMeansParams(n_clusters=4), const)
    assert np.isfinite(np.asarray(cents)).all()


def test_fuzz_duplicate_rows(ires):
    base = np.asarray(_data(32, 8, seed=15))
    dup = jnp.asarray(np.concatenate([base, base], axis=0))
    index = ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=4), dup)
    integrity.verify(index, level="statistical")
    d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=4), index,
                           dup[:4], k=2)
    assert np.allclose(np.asarray(d), 0.0, atol=1e-4)  # both copies at 0


def test_fuzz_empty_ivf_lists(ires):
    # force genuinely empty lists, then verify + search must stay sane
    index = ivf_flat.build(ires, ivf_flat.IndexParams(n_lists=8),
                           _data(128, 8, seed=16))
    li, _ = _fullest(index)
    victim = (li + 1) % index.n_lists
    emptied = dataclasses.replace(
        index,
        list_sizes=index.list_sizes.at[victim].set(0),
        list_indices=index.list_indices.at[victim].set(-1),
        list_data=index.list_data.at[victim].set(0.0),
        list_data_sq=None)
    # emptying a list leaves a sparse id space; pass the true universe
    integrity.verify(emptied, level="statistical", n_rows=128)
    d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8), emptied,
                           _data(4, 8, seed=17), k=4)
    i = np.asarray(i)
    remaining = set(np.asarray(emptied.list_indices)[
        np.asarray(emptied.list_indices) >= 0].tolist())
    assert all(x in remaining for x in i.ravel().tolist())


@pytest.mark.parametrize("policy", ["raise", "mask", "off"])
def test_fuzz_nonfinite_under_each_policy(ires, flat_index, policy):
    q = _nan_queries()
    with config.validation_policy(policy):
        if policy == "raise":
            with pytest.raises(ValidationError):
                ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8),
                                flat_index, q, k=5)
        else:
            d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=8),
                                   flat_index, q, k=5)
            assert i.shape == (6, 5)
            if policy == "mask":
                assert (np.asarray(i)[1] == -1).all()


def test_boundary_guard_lint(tmp_path):
    # the CI entry-point lint: clean tree passes, unguarded entry fails
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_boundary_guard",
        str(pathlib.Path(__file__).resolve().parent.parent / "scripts" /
            "check_boundary_guard.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0                      # current tree is clean

    bad = tmp_path / "bad.py"
    bad.write_text("def search(res, params, index, queries, k):\n"
                   "    return queries\n")
    assert len(mod.check_file(bad)) == 1
    good = tmp_path / "good.py"
    good.write_text(
        "from raft_tpu.integrity import boundary as _boundary\n"
        "def search(res, params, index, queries, k):\n"
        "    queries, ok = _boundary.check_matrix(queries, 'q', site='s')\n"
        "    return queries\n")
    assert mod.check_file(good) == []
    delegating = tmp_path / "delegating.py"
    delegating.write_text(
        "from raft_tpu.integrity.boundary import check_matrix\n"
        "def fit(res, X):\n"
        "    X, _ = check_matrix(X, 'X', site='s')\n"
        "    return X\n"
        "def fit_predict(res, X):\n"
        "    return fit(res, X)\n")
    assert mod.check_file(delegating) == []


def test_fuzz_inner_product_mask_sentinel(ires):
    # masked rows must take the WORST distance for the metric: -inf-like
    # (lowest) for similarities, +max for distances
    db = _data(50, 16, seed=18)
    index = ivf_flat.build(
        ires, ivf_flat.IndexParams(n_lists=4,
                                   metric=DistanceType.InnerProduct), db)
    with config.validation_policy("mask"):
        d, i = ivf_flat.search(ires, ivf_flat.SearchParams(n_probes=4),
                               index, _nan_queries(), k=3)
    assert (np.asarray(i)[1] == -1).all()
    assert (np.asarray(d)[1] == -np.inf).all()
