"""Crash-safe background rebalancer tests (ISSUE PR 7 tentpole): staged
checkpointed passes, the verify + canary gate in front of every swap-in,
and the kill-at-every-boundary recovery matrix the CI crash-recovery job
replays under a pinned ``RAFT_TPU_FAULT_SEED``.

The invariant under test everywhere: no reader ever observes a partially
applied generation.  A pass that dies at ANY fault site leaves the served
index exactly where it was; ``resume()`` lands on a verify-clean,
canary-passing index — the finished candidate when the checkpoints allow
it, the checkpointed base otherwise.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import DeviceResources, integrity, serving
from raft_tpu import observability as obs
from raft_tpu.integrity import canary as _canary
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors import mutate
from raft_tpu.random import make_blobs
from raft_tpu.resilience import FaultInjected, FaultPlan
from raft_tpu.serving import RebalanceConfig, Rebalancer

# the CI crash-recovery job pins this so a red matrix cell replays the
# identical kill schedule locally
SEED = int(os.environ.get("RAFT_TPU_FAULT_SEED", "20260805"))


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    # rebalance passes compile fresh shapes every time capacity shrinks;
    # release the executables at teardown so later modules in a
    # full-suite run don't inherit the accumulated JIT code mappings
    yield
    jax.clear_caches()

# every boundary a pass can die at: the rebalancer's own stage sites plus
# the checkpoint manager's save/load (see rebalancer module docstring)
KILL_SITES = (
    "rebalance.plan",
    "rebalance.recluster",
    "rebalance.compact",
    "rebalance.verify",
    "rebalance.swap",
    "checkpoint.save",
    "checkpoint.load",
)


@pytest.fixture(scope="module")
def res():
    return DeviceResources(seed=42)


@pytest.fixture(scope="module")
def dataset():
    X, _ = make_blobs(900, 16, n_clusters=8, cluster_std=1.0, seed=21)
    return np.asarray(X[:860]), np.asarray(X[860:876])


def _fresh_index(res, dataset, *, canaries=True):
    db, _ = dataset
    kw = dict(canary_queries=12, canary_k=5, canary_floor=0.3) \
        if canaries else {}
    params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5, **kw)
    return ivf_flat.build(res, params, db)


def _with_dead_rows(res, index, n=250):
    """An index with enough tombstones to trip the default compaction
    threshold — every rebalance pass on it has real work to do."""
    return ivf_flat.delete(res, index, list(range(0, n)))


def _assert_gated(res, index, n_rows):
    """What 'safe to serve' means: verify-clean at the rebalancer's own
    level and bound, canary floor holding."""
    integrity.verify(index, level="statistical", res=res, n_rows=n_rows)
    if getattr(index, "canaries", None) is not None:
        assert _canary.health_check(res, index, raise_on_fail=True).ok


class TestHappyPath:
    def test_compaction_pass(self, res, dataset, tmp_path):
        db, _ = dataset
        idx = _with_dead_rows(res, _fresh_index(res, dataset))
        assert mutate.dead_fraction(idx) > 0.2
        rb = Rebalancer(res, idx, checkpoint=str(tmp_path / "ck"))
        out = rb.run_once()
        st = rb.stats()
        assert st["swaps"] == 1 and st["compactions"] == 1
        assert st["dead_fraction"] == 0.0
        assert mutate.generation(out) > mutate.generation(idx)
        assert mutate.live_count(out) == mutate.live_count(idx)
        _assert_gated(res, out, db.shape[0])
        # an accepted pass clears its checkpoints
        assert not rb.checkpoint.completed

    def test_noop_pass(self, res, dataset):
        idx = _fresh_index(res, dataset, canaries=False)
        rb = Rebalancer(res, idx)
        out = rb.run_once()
        assert out is idx
        assert rb.stats()["noops"] == 1

    def test_recluster_redistributes_overfull_list(self, res, dataset):
        db, _ = dataset
        idx = _fresh_index(res, dataset, canaries=False)
        # cram extra rows into one list's neighborhood: extend near the
        # fullest list's center so that list becomes overfull
        li = int(np.argmax(np.asarray(mutate.live_sizes(idx.list_indices))))
        center = np.asarray(idx.centers[li])
        rng = np.random.default_rng(5)
        extra = (center[None, :]
                 + 0.1 * rng.normal(size=(300, db.shape[1]))
                 ).astype(np.float32)
        n = db.shape[0]
        idx = ivf_flat.extend(res, idx, extra,
                              np.arange(n, n + 300, dtype=np.int64))
        rb = Rebalancer(res, idx,
                        config=RebalanceConfig(overfull_factor=1.5))
        out = rb.run_once()
        st = rb.stats()
        assert st["reclustered_rows"] > 0 and st["swaps"] == 1
        assert mutate.live_count(out) == mutate.live_count(idx)
        _assert_gated(res, out, n + 300)

    def test_pq_pass(self, res):
        X, _ = make_blobs(1000, 32, n_clusters=16, cluster_std=1.0, seed=9)
        db = np.asarray(X)
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=4)
        idx = ivf_pq.build(res, params, db)
        idx = ivf_pq.delete(res, idx, list(range(0, 300)))
        rb = Rebalancer(res, idx)
        out = rb.run_once()
        assert rb.stats()["swaps"] == 1
        assert mutate.dead_fraction(out) == 0.0
        integrity.verify(out, level="statistical", res=res,
                         n_rows=db.shape[0])

    def test_rejects_unsupported_index(self, res, dataset):
        from raft_tpu.core.error import RaftError
        from raft_tpu.neighbors import cagra
        db, _ = dataset
        # The gate is an isinstance check, so a hand-assembled CAGRA index
        # exercises it without paying for a real graph build.
        g = cagra.Index(dataset=jnp.asarray(db[:32]),
                        graph=jnp.zeros((32, 8), jnp.int32))
        with pytest.raises(RaftError, match="rebalancer"):
            Rebalancer(res, g)


def _crash_and_resume(rb, site):
    """Kill one pass at ``site``, then recover.  ``checkpoint.load`` only
    fires on the resume path (run_once never loads), so that cell crashes
    the pass at the swap boundary and injects the load fault into resume
    itself — the recovery must survive its own I/O failing."""
    crash_site = "rebalance.swap" if site == "checkpoint.load" else site
    with FaultPlan(seed=SEED).at(crash_site, times=1).active():
        with pytest.raises(FaultInjected):
            rb.run_once()
    if site == "checkpoint.load":
        with FaultPlan(seed=SEED).at(site, times=1).active():
            return rb.resume()
    return rb.resume()


class TestKillMatrix:
    """Satellite 5's core: die at every checkpoint/stage boundary, then
    resume — the result must always be gated, never partial."""

    @pytest.mark.parametrize("site", KILL_SITES)
    def test_kill_then_resume_lands_gated(self, res, dataset, tmp_path,
                                          site):
        db, q = dataset
        idx = _with_dead_rows(res, _fresh_index(res, dataset))
        rb = Rebalancer(res, idx, checkpoint=str(tmp_path / "ck"))
        base_gen = mutate.generation(idx)
        out = _crash_and_resume(rb, site)
        # the served index was never a partial candidate
        assert rb.last_good is out
        st = rb.stats()
        # resume lands on the finished candidate (furthest checkpoint
        # made it through the gate) or rolls back to base — never between
        assert (mutate.generation(out) == base_gen
                or mutate.dead_fraction(out) == 0.0), st
        _assert_gated(res, out, db.shape[0])
        # checkpoints are consumed either way; the next pass starts clean
        assert not rb.checkpoint.completed
        # and the recovered index still answers searches
        _, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=8),
                               out, q, 5)
        assert (np.asarray(i) >= 0).all() or mutate.live_count(out) == 0

    @pytest.mark.parametrize("site", KILL_SITES)
    def test_kill_resume_is_idempotent(self, res, dataset, tmp_path, site):
        idx = _with_dead_rows(res, _fresh_index(res, dataset,
                                                canaries=False))
        rb = Rebalancer(res, idx, checkpoint=str(tmp_path / "ck"))
        first = _crash_and_resume(rb, site)
        # a second resume with consumed checkpoints changes nothing
        assert rb.resume() is first

    def test_corrupt_progress_checkpoints_roll_back(self, res, dataset,
                                                    tmp_path):
        db, _ = dataset
        idx = _with_dead_rows(res, _fresh_index(res, dataset,
                                                canaries=False))
        rb = Rebalancer(res, idx, checkpoint=str(tmp_path / "ck"))
        plan = FaultPlan(seed=SEED).at("rebalance.swap", times=1)
        with plan.active():
            with pytest.raises(FaultInjected):
                rb.run_once()
        # flip bytes inside the progress checkpoints; the CRC envelope
        # must reject them and resume must fall back to base
        for name in ("recluster", "compact"):
            p = tmp_path / "ck" / f"{name}.ckpt"
            with open(p, "r+b") as f:
                f.seek(10)
                f.write(b"\xff\xff\xff\xff")
        out = rb.resume()
        st = rb.stats()
        assert st["rollbacks"] == 1 and st["errors"] >= 1
        assert mutate.generation(out) == mutate.generation(idx)
        integrity.verify(out, level="statistical", res=res,
                         n_rows=db.shape[0])

    def test_resume_without_checkpoints_is_noop(self, res, dataset):
        idx = _fresh_index(res, dataset, canaries=False)
        rb = Rebalancer(res, idx)
        assert rb.resume() is idx


class TestServingIntegration:
    def test_accepted_pass_swaps_serving_index(self, res, dataset):
        db, q = dataset
        idx = _with_dead_rows(res, _fresh_index(res, dataset,
                                                canaries=False))
        sp = ivf_flat.SearchParams(n_probes=8)
        ex = serving.Executor(res, "ivf_flat", idx, ks=(5,), max_batch=8,
                              search_params=sp, warm="jit")
        with serving.Server(ex, serving.ServerConfig(max_batch=8)) as srv:
            rb = Rebalancer(res, idx, server=srv)
            out = rb.run_once()
            assert ex.index is out
            assert mutate.generation(out) > mutate.generation(idx)
            d, i = srv.search(np.asarray(q[:3], np.float32), k=5)
            assert (np.asarray(i) >= 0).all()

    def test_failed_gate_keeps_serving_old_generation(self, res, dataset):
        idx = _with_dead_rows(res, _fresh_index(res, dataset,
                                                canaries=False))
        sp = ivf_flat.SearchParams(n_probes=8)
        ex = serving.Executor(res, "ivf_flat", idx, ks=(5,), max_batch=8,
                              search_params=sp, warm="jit")
        with serving.Server(ex, serving.ServerConfig(max_batch=8)) as srv:
            rb = Rebalancer(res, idx, server=srv)
            plan = FaultPlan(seed=SEED).at("rebalance.verify", times=1)
            with plan.active():
                with pytest.raises(FaultInjected):
                    rb.run_once()
            assert ex.index is idx  # reader-visible index never moved

    def test_background_thread_start_stop(self, res, dataset):
        idx = _with_dead_rows(res, _fresh_index(res, dataset,
                                                canaries=False))
        cfg = RebalanceConfig(interval_s=0.01)
        with Rebalancer(res, idx, config=cfg) as rb:
            deadline = 200
            while rb.stats()["passes"] < 1 and deadline:
                rb._stop.wait(0.05)
                deadline -= 1
        st = rb.stats()
        assert st["passes"] >= 1 and st["swaps"] >= 1
        assert st["dead_fraction"] == 0.0
        # stopped: no further passes accumulate
        frozen = rb.stats()["passes"]
        rb._stop.wait(0.05)
        assert rb.stats()["passes"] == frozen

    def test_swap_counter(self, res, dataset):
        idx = _with_dead_rows(res, _fresh_index(res, dataset,
                                                canaries=False))
        obs.enable()
        try:
            with obs.collecting():
                rb = Rebalancer(res, idx)
                rb.run_once()
                swaps = obs.registry().counter("rebalance.swaps").value
            assert swaps == 1
        finally:
            obs.disable()


class TestRoutedRebalance:
    """PR 8: per-shard compaction passes + the global placement
    generation barrier over a ``placement="by_list"`` index."""

    @pytest.fixture(scope="class")
    def rhandle(self):
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        from raft_tpu.comms import CommsSession
        mesh = jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
        s = CommsSession(mesh=mesh, axis_name="data").init()
        yield s.worker_handle(seed=0)
        s.destroy()

    @pytest.fixture(scope="class")
    def routed(self, rhandle):
        from raft_tpu.distributed import ann
        rng = np.random.default_rng(31)
        db = rng.normal(size=(2048, 32)).astype(np.float32)
        q = rng.normal(size=(16, 32)).astype(np.float32)
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        base = ivf_pq.build(rhandle, params, db)
        return ann.shard_by_list(rhandle, base), q

    def test_noop_on_clean_index(self, rhandle, routed):
        from raft_tpu.serving.rebalancer import rebalance_routed
        idx, _ = routed
        assert rebalance_routed(rhandle, idx) is idx

    def test_compaction_pass_preserves_results(self, rhandle, routed):
        from raft_tpu.distributed import ann
        from raft_tpu.serving.rebalancer import rebalance_routed
        idx, q = routed
        deleted = ann.delete(rhandle, idx, list(range(0, 700)))
        sp = ivf_pq.SearchParams(n_probes=32)
        d1, i1 = ann.search(rhandle, sp, deleted, q, 10)
        out = rebalance_routed(rhandle, deleted)
        assert out is not deleted
        assert mutate.generation(out) == mutate.generation(deleted) + 1
        assert out.placement.generation == \
            deleted.placement.generation + 1
        d2, i2 = ann.search(rhandle, sp, out, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        # tombstone debt actually repaired on the eligible shards
        assert int(jnp.sum(out.list_indices <= -2)) < \
            int(jnp.sum(deleted.list_indices <= -2))

    def test_swap_publishes_through_server(self, rhandle, routed):
        from raft_tpu.distributed import ann
        from raft_tpu.serving.executor import DistributedExecutor
        from raft_tpu.serving.rebalancer import rebalance_routed
        idx, q = routed
        deleted = ann.delete(rhandle, idx, list(range(0, 700)))
        ex = DistributedExecutor(
            rhandle, deleted, ks=(10,), max_batch=16,
            search_params=ivf_pq.SearchParams(n_probes=8))
        ex.warmup()
        out = rebalance_routed(rhandle, deleted, server=ex)
        assert ex.index is out
        d, i = ex.search_bucket(jnp.asarray(q), q.shape[0], 10)
        assert not (set(np.asarray(i).ravel().tolist())
                    & set(range(0, 700)))

    def test_rejects_data_parallel_index(self, rhandle):
        from raft_tpu.serving.rebalancer import rebalance_routed
        with pytest.raises(Exception):
            rebalance_routed(rhandle, object())
