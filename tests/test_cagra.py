"""CAGRA tests — recall-based (reference: cpp/test/neighbors/ann_cagra.cuh),
covering graph build, prune degree/validity, search recall and serialization.
"""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import cagra
from raft_tpu.random import make_blobs


def naive_knn(db, q, k):
    d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def recall(found, truth):
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, truth))
    return hits / truth.size


@pytest.fixture(scope="module")
def dataset():
    X, _ = make_blobs(2100, 16, n_clusters=30, cluster_std=1.0, seed=11)
    return np.asarray(X[:2000]), np.asarray(X[2000:2040])


@pytest.fixture(scope="module")
def index(dataset):
    from raft_tpu import DeviceResources
    db, _ = dataset
    res = DeviceResources(seed=42)
    params = cagra.IndexParams(intermediate_graph_degree=32, graph_degree=16)
    return cagra.build(res, params, db)


class TestCagra:
    def test_graph_shape_and_validity(self, dataset, index):
        db, _ = dataset
        assert index.graph.shape == (db.shape[0], 16)
        g = np.asarray(index.graph)
        assert (g >= 0).all() and (g < db.shape[0]).all()
        # no self-edges in the forward half
        self_frac = (g == np.arange(db.shape[0])[:, None]).mean()
        assert self_frac < 0.01

    def test_knn_graph_quality(self, res, dataset):
        db, _ = dataset
        knn = cagra.build_knn_graph(res, db, 16)
        _, ti = naive_knn(db, db, 17)
        # graph neighbors should substantially overlap true neighbors
        # (exclude self column from truth)
        r = recall(np.asarray(knn)[:200], ti[:200, 1:])
        assert r > 0.8

    def test_search_recall(self, res, dataset, index):
        db, q = dataset
        params = cagra.SearchParams(itopk_size=32, search_width=2)
        d, i = cagra.search(res, params, index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.85

    def test_search_sorted_and_valid(self, res, dataset, index):
        db, q = dataset
        params = cagra.SearchParams(itopk_size=32)
        d, i = cagra.search(res, params, index, q, 5)
        dd = np.asarray(d)
        assert (np.diff(dd, axis=1) >= -1e-5).all()
        assert (np.asarray(i) >= 0).all()

    def test_serialize_roundtrip(self, res, dataset, index):
        db, q = dataset
        buf = io.BytesIO()
        cagra.serialize(res, buf, index)
        buf.seek(0)
        index2 = cagra.deserialize(res, buf)
        np.testing.assert_array_equal(np.asarray(index.graph),
                                      np.asarray(index2.graph))
        d1, i1 = cagra.search(res, cagra.SearchParams(), index, q, 5)
        d2, i2 = cagra.search(res, cagra.SearchParams(), index2, q, 5)
        # same index contents -> same search behavior modulo random seeds
        assert d1.shape == d2.shape

    def test_default_params_on_flat_spectrum_data(self, res):
        """Regression (r4 review): isotropic gaussian data has no small
        PCA subspace — the auto walk_pdim must widen (or fall back to
        the exact walk) instead of silently collapsing recall."""
        rng = np.random.default_rng(0)
        db = rng.normal(size=(3000, 64)).astype(np.float32)
        q = rng.normal(size=(50, 64)).astype(np.float32)
        params = cagra.IndexParams(intermediate_graph_degree=64,
                                   graph_degree=32)
        index = cagra.build(res, params, db)
        assert cagra._auto_pdim(index) >= 48   # flat spectrum -> wide
        d, i = cagra.search(res, cagra.SearchParams(), index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.85

    def test_walk_table_cached_per_pdim(self, res, dataset, index):
        """Two entry-set sizes must share ONE neighborhood table
        (r4 review: the multi-GB table was keyed on (pdim, entries))."""
        db, q = dataset
        cagra.search(res, cagra.SearchParams(entry_points=256), index,
                     q, 5)
        n_tables = len(index._walk_tables)
        n_entries = len(index._walk_entries)
        cagra.search(res, cagra.SearchParams(entry_points=512), index,
                     q, 5)
        assert len(index._walk_tables) == n_tables     # table reused
        assert len(index._walk_entries) == n_entries + 1

    def test_walk_table_int16_container_roundtrip(self, res, dataset,
                                                  index):
        """Regression (r4): the packed table container must be an
        INTEGER dtype — bf16 lanes flushed denormal bit patterns (low
        int32 id halves) in XLA relayout copies at 1M scale, silently
        corrupting neighbor ids.  Decode must be bit-exact."""
        import jax

        db, q = dataset
        cagra.search(res, cagra.SearchParams(), index, q, 5)
        (pdim, quant), = list(index._walk_tables)[:1]
        assert not quant          # small index: bf16 format selected
        table, proj, _ = index._walk_tables[(pdim, quant)]
        assert jnp.issubdtype(table.dtype, jnp.integer)
        unit = pdim + 4
        deg = index.graph_degree
        rows = table[:16, :deg * unit].reshape(16, deg, unit)
        ids = jax.lax.bitcast_convert_type(rows[..., pdim + 2:pdim + 4],
                                           jnp.int32)
        np.testing.assert_array_equal(np.asarray(ids),
                                      np.asarray(index.graph[:16]))
        sq = jax.lax.bitcast_convert_type(rows[..., pdim:pdim + 2],
                                          jnp.float32)
        true_sq = np.sum(np.asarray(db, np.float32)[
            np.asarray(index.graph[:16])] ** 2, axis=-1)
        np.testing.assert_allclose(np.asarray(sq), true_sq, rtol=1e-5)

    @pytest.mark.parametrize("A,B", [(64, 64), (24, 64), (32, 128),
                                     (96, 64)])
    def test_bitonic_merge_matches_full_sort(self, A, B):
        """The log-depth merge must equal a full sort of the
        concatenation for any sorted inputs (incl. non-pow2 widths and
        +inf padding)."""
        rng = np.random.default_rng(A * 100 + B)
        q = 13
        a_k = np.sort(rng.normal(size=(q, A)).astype(np.float32), axis=1)
        b_k = np.sort(rng.normal(size=(q, B)).astype(np.float32), axis=1)
        a_i = rng.integers(0, 10000, (q, A)).astype(np.int32)
        b_i = rng.integers(0, 10000, (q, B)).astype(np.int32)
        a_v = rng.random((q, A)) < 0.5
        k, i, v = cagra._bitonic_merge(
            jnp.asarray(a_k), jnp.asarray(a_i), jnp.asarray(a_v),
            jnp.asarray(b_k), jnp.asarray(b_i), A)
        cat_k = np.concatenate([a_k, b_k], axis=1)
        order = np.argsort(cat_k, axis=1)[:, :A]
        np.testing.assert_allclose(np.asarray(k),
                                   np.take_along_axis(cat_k, order, 1))
        # carried payloads follow their keys (keys here are distinct
        # with probability 1, so the id/visited rows are determined)
        cat_i = np.concatenate([a_i, b_i], axis=1)
        cat_v = np.concatenate([a_v, np.zeros((q, B), bool)], axis=1)
        np.testing.assert_array_equal(np.asarray(i),
                                      np.take_along_axis(cat_i, order, 1))
        np.testing.assert_array_equal(np.asarray(v),
                                      np.take_along_axis(cat_v, order, 1))

    def test_prune_reverse_edges(self, res, dataset):
        db, _ = dataset
        knn = cagra.build_knn_graph(res, db, 16)
        pruned = cagra.prune(res, knn, 8)
        assert pruned.shape == (db.shape[0], 8)
        g = np.asarray(pruned)
        assert (g >= 0).all()


@pytest.mark.slow
class TestMillionScale:
    @pytest.mark.skipif(
        __import__("jax").default_backend() == "cpu",
        reason="1M build is an accelerator workload (hours on the CPU "
               "test backend); validated on a v5e chip each round "
               "(PERFORMANCE.md round-4 CAGRA section)")
    def test_recall_at_1m(self, res):
        """CAGRA at the reference's headline scale (1M x 128, the
        sift-128-euclidean.json regime): packed-neighborhood walk must
        clear recall 0.95 @ k=10."""
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        n, nq, dim, latent = 1_000_000, 2_000, 128, 16
        Z = rng.normal(size=(n + nq, latent)).astype(np.float32)
        A = (rng.normal(size=(latent, dim)).astype(np.float32)
             / np.sqrt(latent))
        X = (Z @ A + 0.05 * rng.normal(size=(n + nq, dim))).astype(
            np.float32)
        X = jnp.asarray(X)
        db, q = X[:n], X[n:]
        from raft_tpu.neighbors import brute_force
        _, gt = brute_force.knn(res, db, q, 10)
        index = cagra.build(res, cagra.IndexParams(graph_degree=32), db)
        _, i = cagra.search(res, cagra.SearchParams(itopk_size=64,
                                                    search_width=2),
                            index, q, 10)
        rec = recall(np.asarray(i), np.asarray(gt))
        assert rec >= 0.95


@pytest.mark.slow
class TestManifoldScale:
    def test_recall_on_low_intrinsic_dim_data(self, res):
        """SIFT-like data: low intrinsic dimensionality embedded in high-d.

        Uniform random high-d data concentrates distances and clustered
        blobs disconnect the kNN graph — both adversarial to every
        graph-ANN method; realistic descriptors are manifold-like.
        (Validated on a v5e chip at 100k x 128: recall@10 = 0.99 at
        itopk=64; this is the CPU-sized version.)
        """
        rng = np.random.default_rng(0)
        n, dim, latent = 8000, 64, 8
        Z = rng.normal(size=(n + 100, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = (Z @ A + 0.05 * rng.normal(size=(n + 100, dim))).astype(np.float32)
        Q = X[n:]; X = X[:n]
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16, build_n_probes=32)
        index = cagra.build(res, params, X)
        d, i = cagra.search(res, cagra.SearchParams(itopk_size=64), index,
                            Q, 10)
        from raft_tpu.neighbors import brute_force
        _, gt = brute_force.knn(res, X, Q, 10)
        i, gt = np.asarray(i), np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(i, gt)) / gt.size
        assert rec >= 0.9


class TestClusteredBuild:
    def test_clustered_knn_graph_recall(self, res):
        """The list-major clustered build pass (n > _BRUTE_BUILD_MAX):
        the projected candidate scan + fused exact refine must produce a
        near-exact kNN graph on manifold data (reference analogue:
        cagra_build.cuh's IVF-PQ + refine pipeline)."""
        rng = np.random.default_rng(3)
        n, dim, latent = 40_000, 32, 8
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = (Z @ A + 0.05 * rng.normal(size=(n, dim))).astype(np.float32)
        assert n > cagra._BRUTE_BUILD_MAX
        deg = 16
        knn = np.asarray(cagra.build_knn_graph(res, X, deg))
        assert knn.shape == (n, deg)
        # no self edges, all ids valid
        sample = np.arange(0, n, 97)
        assert not np.any(knn[sample] == sample[:, None])
        assert knn.min() >= 0 and knn.max() < n
        # graph recall vs exact ground truth on a query sample
        from raft_tpu.neighbors import brute_force
        _, gt = brute_force.knn(res, X, X[sample], deg + 1)
        gt = np.asarray(gt)[:, 1:]          # drop self column
        rec = sum(len(set(a) & set(b))
                  for a, b in zip(knn[sample], gt)) / gt.size
        assert rec >= 0.9


class TestQuantWalkTable:
    """int8/uint16 packed-row format (the 10M-rows-per-chip regime)."""

    def test_decode_roundtrip(self, res, dataset):
        db, _ = dataset
        db = jnp.asarray(db)
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16)
        index = cagra.build(res, params, db)
        pdim = 8
        table, proj, scales = cagra._build_walk_table_q(
            db, index.graph, pdim)
        deg = index.graph_degree
        unit = cagra._quant_unit(pdim)
        rows = table[:64, None, :deg * unit].reshape(64, 1, deg, unit)
        nb_p, nb_sq, nb_id = cagra._decode_neighborhood(
            rows, pdim, deg, True, scales)
        # ids decode exactly
        np.testing.assert_array_equal(
            np.asarray(nb_id[:, 0]), np.asarray(index.graph[:64]))
        # norms decode to within the uint16 quantization step
        x_sq = np.sum(np.asarray(db).astype(np.float64) ** 2, axis=1)
        want = x_sq[np.asarray(index.graph[:64])]
        got = np.asarray(nb_sq[:, 0])
        step = float(scales[2])
        assert np.max(np.abs(got - want)) <= step * 1.01 + 1e-3
        # projected lanes: in-range values decode to within one int8
        # step; only the ~0.1% beyond the 99.9th-percentile clip scale
        # may exceed it
        xp = np.asarray(db, dtype=np.float64) @ np.asarray(proj)
        want_p = xp[np.asarray(index.graph[:64])]
        got_p = np.asarray(nb_p[:, 0].astype(jnp.float32)) \
            * float(scales[0]) / 127.0
        err = np.abs(got_p - want_p)
        step = float(scales[0]) / 127.0
        assert np.quantile(err, 0.99) <= step
        clipped = np.abs(want_p) > float(scales[0])
        assert np.all(err[~clipped] <= step)

    def test_quant_walk_recall_matches_bf16(self, res):
        rng = np.random.default_rng(7)
        n, dim, latent = 6000, 32, 6
        Z = rng.normal(size=(n + 64, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = jnp.asarray((Z @ A).astype(np.float32))
        db, q = X[:n], X[n:]
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16)
        index = cagra.build(res, params, db)
        pdim = cagra._auto_pdim(index) or 16
        k, itopk = 10, 48
        outs = {}
        for quant in (False, True):
            cache = cagra._walk_cache(res, index, pdim, 256, quant=quant)
            d, i = cagra._search_impl_walk(
                index.dataset, cache.table, cache.entry_proj,
                cache.entry_sq, cache.entry_ids, cache.proj, q, k,
                itopk, 1, 60, index.metric, 32, index.graph_degree,
                quant=cache.quant, scales=cache.scales)
            outs[quant] = np.asarray(i)
        from raft_tpu.neighbors import brute_force
        _, gt = brute_force.knn(res, db, q, k)
        gt = np.asarray(gt)
        for quant, ii in outs.items():
            rec = sum(len(set(a) & set(b))
                      for a, b in zip(ii, gt)) / gt.size
            assert rec >= 0.85, (quant, rec)


class TestDeepScalePath:
    def test_deep_regime_matches_default(self, res, monkeypatch):
        """The deep-scale memory regime (in-place fused rounds, host
        reverse/prune tails) must produce graphs of the same quality as
        the default path — exercised here by lowering the row
        threshold."""
        rng = np.random.default_rng(11)
        n, dim, latent = 40_000, 32, 8
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = (Z @ A + 0.05 * rng.normal(size=(n, dim))).astype(np.float32)
        deg = 16
        knn_default = np.asarray(cagra.build_knn_graph(res, X, deg))
        monkeypatch.setattr(cagra, "_DEEP_SCALE_ROWS", 10_000)
        monkeypatch.setattr(cagra, "_REV_HOST_EDGES", 100_000)
        knn_deep = np.asarray(cagra.build_knn_graph(res, X, deg))
        pruned = np.asarray(cagra.prune(res, jnp.asarray(knn_deep), 8))
        assert pruned.shape == (n, 8)
        from raft_tpu.neighbors import brute_force
        sample = np.arange(0, n, 97)
        _, gt = brute_force.knn(res, X, X[sample], deg + 1)
        gt = np.asarray(gt)[:, 1:]

        def rec(knn):
            return sum(len(set(a) & set(b))
                       for a, b in zip(knn[sample], gt)) / gt.size

        r_def, r_deep = rec(knn_default), rec(knn_deep)
        assert r_deep >= 0.9, r_deep
        assert r_deep >= r_def - 0.05, (r_def, r_deep)


class TestSearchTableFormat:
    def test_format_ladder(self, res, monkeypatch):
        """bf16 when it fits, quantized when only that fits, None when
        nothing does — the ONE gate shared by search and the AOT
        exporter.  Manifold data: the quant rung is fidelity-gated and
        tight blobs legitimately fail it."""
        rng = np.random.default_rng(13)
        n, dim, latent = 6000, 32, 6
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = jnp.asarray((Z @ A).astype(np.float32))
        index = cagra.build(
            res, cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16), X)
        pdim = cagra._auto_pdim(index) or 16
        assert cagra._search_table_format(index, pdim) == (pdim, False)
        bf16_bytes = cagra._table_bytes(index.size, index.graph_degree,
                                        pdim, False)
        q_bytes = cagra._table_bytes(index.size, index.graph_degree,
                                     max(pdim - pdim % 2, 8), True)
        assert q_bytes < bf16_bytes
        monkeypatch.setattr(cagra, "_WALK_TABLE_MAX_BYTES", q_bytes)
        fmt = cagra._search_table_format(index, pdim)
        assert fmt is not None and fmt[1] is True
        monkeypatch.setattr(cagra, "_WALK_TABLE_MAX_BYTES", 1)
        assert cagra._search_table_format(index, pdim) is None


class TestMergeRefineDebugChecks:
    """_merge_refine_chunked fast-path precondition (first sorted by key
    and dup-free) — validated host-side when the debug flag is on."""

    def _inputs(self):
        rng = np.random.default_rng(5)
        n, dim, kg = 32, 8, 4
        xf = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
        first = jnp.tile(jnp.arange(kg, dtype=jnp.int32), (n, 1))
        first_d = jnp.tile(jnp.arange(kg, dtype=jnp.float32), (n, 1))
        second = jnp.asarray(
            rng.integers(0, n, size=(n, kg)).astype(np.int32))
        return xf, first, first_d, second, kg

    def test_valid_inputs_pass(self, monkeypatch):
        monkeypatch.setattr(cagra, "_DEBUG_CHECKS", True)
        xf, first, first_d, second, kg = self._inputs()
        out, _ = cagra._merge_refine_chunked(
            xf, first, second, kg, False, chunk=32, first_d=first_d,
            with_d=True)
        assert out.shape == (32, kg)

    def test_unsorted_first_d_raises(self, monkeypatch):
        from raft_tpu import RaftError
        monkeypatch.setattr(cagra, "_DEBUG_CHECKS", True)
        xf, first, first_d, second, kg = self._inputs()
        bad = first_d.at[3, 0].set(99.0)       # row 3 now decreasing
        with pytest.raises(RaftError, match="non-decreasing"):
            cagra._merge_refine_chunked(xf, first, second, kg, False,
                                        chunk=32, first_d=bad,
                                        with_d=True)

    def test_duplicate_first_raises(self, monkeypatch):
        from raft_tpu import RaftError
        monkeypatch.setattr(cagra, "_DEBUG_CHECKS", True)
        xf, first, first_d, second, kg = self._inputs()
        bad = first.at[0, 1].set(0)            # id 0 twice in row 0
        with pytest.raises(RaftError, match="duplicate-free"):
            cagra._merge_refine_chunked(xf, bad, second, kg, False,
                                        chunk=32, first_d=first_d,
                                        with_d=True)

    def test_checks_off_by_default(self):
        assert not cagra._DEBUG_CHECKS
        xf, first, first_d, second, kg = self._inputs()
        bad = first_d.at[3, 0].set(99.0)
        # with the flag off a violating input is not validated (the
        # jitted fast path runs unchecked, as in production)
        out, _ = cagra._merge_refine_chunked(xf, first, second, kg,
                                             False, chunk=32,
                                             first_d=bad, with_d=True)
        assert out.shape == (32, kg)


class TestFusedHop:
    """Round-7 low-batch fused hop kernel (ops/cagra_hop_pallas):
    score + dedupe + merge in one VMEM-resident pass, parity with the
    XLA _merge_candidates/_bitonic_merge pair."""

    def _hop_inputs(self, seed=0, nq=5, itopk=16, wd=24, pdim=16,
                    id_hi=40):
        rng = np.random.default_rng(seed)
        qp = rng.normal(size=(nq, pdim)).astype(np.float32)
        qsq = (rng.random(nq) * 3).astype(np.float32)
        nbp = rng.normal(size=(nq, wd, pdim)).astype(np.float32)
        nbsq = (rng.random((nq, wd)) * 3).astype(np.float32)
        nbid = rng.integers(0, id_hi, size=(nq, wd)).astype(np.int32)
        nbid[0, :4] = -1                       # masked parent slots
        if nq > 1 and wd > 6:
            nbid[1, 5] = nbid[1, 6]            # self-dup
        # walk invariant: every copy of an id decodes the SAME table
        # row, so dup slots must carry identical (proj, sq) payloads
        for r in range(nq):
            first = {}
            for j in range(wd):
                cid = int(nbid[r, j])
                if cid < 0:
                    continue
                if cid in first:
                    nbp[r, j] = nbp[r, first[cid]]
                    nbsq[r, j] = nbsq[r, first[cid]]
                else:
                    first[cid] = j
        d_c = (qsq[:, None] + nbsq
               - 2.0 * np.einsum("qp,qwp->qw", qp, nbp)).astype(np.float32)
        # sorted buffer, inf tail, ids disjoint from candidates (100+)
        # except dups carrying the candidate's exact key (same formula
        # on both sides in the real walk)
        bufd = np.sort(rng.random((nq, itopk)).astype(np.float32) * 2,
                       axis=1)
        bufd[:, itopk - 3:] = np.inf
        bufi = np.zeros((nq, itopk), np.int32)
        for r in range(nq):
            bufi[r] = np.random.default_rng(r).permutation(
                10 * itopk)[:itopk] + 10 * id_hi
            for slot, j in ((2, 1), (5, min(7, wd - 1))):
                if nbid[r, j] >= 0:
                    bufi[r, slot] = nbid[r, j]
                    bufd[r, slot] = d_c[r, j]
        order = np.argsort(bufd, axis=1)
        bufd = np.take_along_axis(bufd, order, axis=1)
        bufi = np.take_along_axis(bufi, order, axis=1)
        bufi[bufd == np.inf] = -1
        vis = np.asarray(np.random.default_rng(9)
                         .random((nq, itopk)) < 0.3)
        vis[bufd == np.inf] = False
        return qp, qsq, nbp, nbsq, nbid, d_c, bufd, bufi, vis, itopk

    def _assert_hop_parity(self, data, merge_window=1):
        from raft_tpu.ops.cagra_hop_pallas import fused_hop
        qp, qsq, nbp, nbsq, nbid, d_c, bufd, bufi, vis, itopk = data
        fd, fi, fv = fused_hop(
            jnp.asarray(qp), jnp.asarray(qsq), jnp.asarray(nbp),
            jnp.asarray(nbsq), jnp.asarray(nbid), jnp.asarray(bufd),
            jnp.asarray(bufi), jnp.asarray(vis), itopk=itopk,
            ip_metric=False, interpret=True, merge_window=merge_window)
        d_ref = jnp.where(jnp.asarray(nbid) >= 0, jnp.asarray(d_c),
                          jnp.inf)
        rd, ri, rv = cagra._merge_candidates(
            jnp.asarray(bufd), jnp.asarray(bufi), jnp.asarray(vis),
            d_ref, jnp.asarray(nbid), itopk)
        fd, fi, fv = map(np.asarray, (fd, fi, fv))
        rd, ri, rv = map(np.asarray, (rd, ri, rv))
        for r in range(fd.shape[0]):
            finite = np.isfinite(rd[r])
            np.testing.assert_array_equal(np.isfinite(fd[r]), finite)
            np.testing.assert_allclose(fd[r][finite], rd[r][finite],
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_array_equal(fi[r][finite], ri[r][finite])
            np.testing.assert_array_equal(fv[r][finite], rv[r][finite])
            assert (fi[r][~finite] == -1).all()

    def test_merge_parity_with_reference(self):
        self._assert_hop_parity(self._hop_inputs())

    @pytest.mark.parametrize("seed,nq,itopk,wd,pdim,mw", [
        (0, 5, 16, 24, 16, 2),    # staged forced at a legacy shape
        (1, 7, 64, 64, 32, 0),    # auto -> staged: the itopk-64 lift
        (2, 16, 64, 96, 64, 2),   # wd > itopk (stage truncation)
        (3, 3, 48, 32, 64, 2),    # wd < itopk, non-pow2 itopk
        (4, 1, 64, 48, 16, 2),    # single query
    ])
    def test_staged_merge_parity(self, seed, nq, itopk, wd, pdim, mw):
        """Round-14 staged hop merge (merge_window=2): buffer-membership
        dedupe + staged extraction + in-kernel bitonic merge must match
        _merge_candidates exactly, including at itopk 64 — the shape the
        legacy kernel's VMEM budget rejects.  The planted buffer dups
        (slots 2/5 carry a candidate's exact key) exercise
        dedupe-across-window: the kill happens at score time, before
        the staging buffer ever sees the candidate."""
        self._assert_hop_parity(
            self._hop_inputs(seed=seed, nq=nq, itopk=itopk, wd=wd,
                             pdim=pdim, id_hi=200),
            merge_window=mw)

    def test_fused_walk_matches_reference_walk(self, res, dataset, index):
        db, q = dataset
        q = q[:8]
        pdim = cagra._auto_pdim(index)
        pdim, quant = cagra._search_table_format(index, pdim)
        cache = cagra._walk_cache(res, index, pdim, 64, quant=quant)
        k, itopk, sw = 5, 16, 1
        args = (index.dataset, cache.table, cache.entry_proj,
                cache.entry_sq, cache.entry_ids, cache.proj,
                jnp.asarray(q), k, itopk, sw, 24, index.metric, 10,
                index.graph_degree)
        d0, i0 = cagra._search_impl_walk(*args, quant=cache.quant,
                                         scales=cache.scales)
        d1, i1 = cagra._search_impl_walk(*args, quant=cache.quant,
                                         scales=cache.scales,
                                         fused_hop=True,
                                         pallas_interpret=True)
        d0, i0, d1, i1 = map(np.asarray, (d0, i0, d1, i1))
        ov = np.mean([len(set(i0[r]) & set(i1[r])) / k
                      for r in range(len(q))])
        assert ov >= 0.9
        same = i0 == i1
        np.testing.assert_allclose(d0[same], d1[same], rtol=1e-5,
                                   atol=1e-5)

    def test_fused_walk_single_query(self, res, dataset, index):
        db, q = dataset
        q = q[:1]
        pdim = cagra._auto_pdim(index)
        pdim, quant = cagra._search_table_format(index, pdim)
        cache = cagra._walk_cache(res, index, pdim, 64, quant=quant)
        d, i = cagra._search_impl_walk(
            index.dataset, cache.table, cache.entry_proj, cache.entry_sq,
            cache.entry_ids, cache.proj, jnp.asarray(q), 5, 16, 1, 24,
            index.metric, 10, index.graph_degree, quant=cache.quant,
            scales=cache.scales, fused_hop=True, pallas_interpret=True)
        d, i = np.asarray(d), np.asarray(i)
        assert d.shape == (1, 5) and i.shape == (1, 5)
        assert (np.diff(d, axis=1) >= -1e-5).all()
        assert (i >= 0).all() and len(set(i[0])) == 5

    def test_supported_hop_gate(self):
        from raft_tpu.ops.cagra_hop_pallas import (hop_merge_window,
                                                   supported_hop)
        # serving buckets of 1-64 at low itopk pass
        assert supported_hop(1, 16, 32, 32)
        assert supported_hop(64, 32, 64, 64)
        # round-14: the staged merge lifts the itopk ceiling to 64 ...
        assert supported_hop(64, 64, 64, 64)
        assert hop_merge_window(64, 64, 64, 64) == 2
        # ... but forcing the legacy per-hop merge keeps the old gate
        assert not supported_hop(64, 64, 64, 64, merge_window=1)
        # throughput shapes and itopk past the staged ceiling do not
        assert not supported_hop(5000, 32, 64, 64)
        assert not supported_hop(64, 128, 64, 64)
        assert not supported_hop(64, 16, 256, 64)
