"""Live quality observability (PR 16): Wilson math, the windowed recall
estimator, the operating-point log (RTIE-sealed rotation, torn-tail
tolerance, calibrator-table shape), calibrated-vs-measured drift
detection with injected staleness, and the shadow-replay monitor
end-to-end — live recall estimate with a confidence interval, degraded
verdicts arming the generation watchdog, ground-truth derivation across
generation swaps, and the disabled-cost contract."""

import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import DeviceResources, serving
from raft_tpu import observability as obs
from raft_tpu.core.serialize import CorruptIndexError
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.observability import flight, quality
from raft_tpu.serving.shadow import ShadowSample


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    flight.clear()
    yield
    obs.disable()
    obs.reset()
    flight.clear()


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    yield
    jax.clear_caches()


@pytest.fixture
def clock(monkeypatch):
    t = {"now": 0.0}
    monkeypatch.setattr(quality, "_now", lambda: t["now"])
    return t


DIM = 32


@pytest.fixture(scope="module")
def res():
    return DeviceResources(seed=42)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    db = rng.normal(size=(4000, DIM)).astype(np.float32)
    q = rng.normal(size=(256, DIM)).astype(np.float32)
    return jnp.asarray(db), q


@pytest.fixture(scope="module")
def pq_index(res, dataset):
    db, _ = dataset
    return ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=32, pq_dim=8, kmeans_n_iters=4),
        db)


# ---------------------------------------------------------------------------
# Wilson interval


class TestWilson:
    def test_known_value(self):
        # 50/100 at z=1.96: the textbook Wilson bound
        lo, hi = quality.wilson_interval(50, 100)
        assert lo == pytest.approx(0.4038, abs=1e-3)
        assert hi == pytest.approx(0.5962, abs=1e-3)

    def test_perfect_and_zero_proportions_stay_in_bounds(self):
        lo, hi = quality.wilson_interval(20, 20)
        assert 0.0 < lo < 1.0 and hi == 1.0
        lo, hi = quality.wilson_interval(0, 20)
        assert lo == 0.0 and 0.0 < hi < 1.0

    def test_empty_window_is_vacuous(self):
        assert quality.wilson_interval(0, 0) == (0.0, 1.0)

    def test_more_samples_narrow_the_interval(self):
        lo1, hi1 = quality.wilson_interval(9, 10)
        lo2, hi2 = quality.wilson_interval(900, 1000)
        assert (hi2 - lo2) < (hi1 - lo1)

    def test_interval_brackets_the_proportion(self):
        for hits, total in ((1, 7), (5, 9), (77, 80)):
            lo, hi = quality.wilson_interval(hits, total)
            assert lo <= hits / total <= hi


# ---------------------------------------------------------------------------
# the windowed estimator


class TestRecallEstimator:
    def test_pools_hits_not_averages(self, clock):
        est = quality.RecallEstimator(window_s=60.0)
        # a 1-row window at 0/5 and a 9-row window at 45/45: pooled
        # recall is 45/50, not the 0.5 a window-mean would report
        est.record("a", 10, 0, 5, rows=1)
        est.record("a", 10, 45, 45, rows=9)
        e = est.estimate()
        assert e.recall == pytest.approx(0.9)
        assert e.hits == 45 and e.total == 50 and e.rows == 10
        assert e.lo <= e.recall <= e.hi

    def test_keyed_and_filtered_views(self, clock):
        est = quality.RecallEstimator(window_s=60.0)
        est.record("a", 10, 9, 10)
        est.record("b", 10, 5, 10)
        est.record("a", 100, 80, 100)
        per = est.estimates()
        assert set(per) == {("a", 10), ("b", 10), ("a", 100)}
        assert per[("b", 10)].recall == pytest.approx(0.5)
        assert est.estimate(tenant="a").total == 110
        assert est.estimate(k=10).total == 20
        assert est.estimate(tenant="b", k=100) is None

    def test_samples_age_out(self, clock):
        est = quality.RecallEstimator(window_s=10.0)
        est.record("a", 10, 1, 10)
        clock["now"] = 8.0
        est.record("a", 10, 9, 10)
        assert est.estimate().total == 20
        clock["now"] = 12.0            # first sample beyond the horizon
        assert est.estimate().total == 10
        assert est.estimate().recall == pytest.approx(0.9)
        clock["now"] = 100.0
        assert est.estimate() is None

    def test_reset(self, clock):
        est = quality.RecallEstimator()
        est.record("a", 10, 1, 1)
        est.reset()
        assert est.estimate() is None


# ---------------------------------------------------------------------------
# the operating-point log


def _point(j, knobs=None, **measured):
    measured = {"recall": 0.9, "hits": 9 * (j + 1), "total": 10 * (j + 1),
                **measured}
    return quality.OpPoint(
        t=float(j), generation=j,
        knobs=knobs or {"kind": "ivf_pq", "n_probes": 8, "k": 10},
        measured=measured, tenant="t0")


class TestOperatingPointLog:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "op.jsonl")
        with quality.OperatingPointLog(path) as log:
            for j in range(5):
                log.append(_point(j, p99=0.001 * j))
        pts = quality.read_operating_points(path)
        assert len(pts) == 5
        assert [p.generation for p in pts] == list(range(5))
        assert pts[3].knobs == {"kind": "ivf_pq", "n_probes": 8, "k": 10}
        assert pts[3].measured["p99"] == pytest.approx(0.003)
        assert pts[3].tenant == "t0"

    def test_rotation_seals_segments_and_prunes(self, tmp_path):
        path = str(tmp_path / "op.jsonl")
        with quality.OperatingPointLog(path, max_bytes=256,
                                       keep=2) as log:
            for j in range(40):
                log.append(_point(j))
        segs = quality._segment_paths(path)
        assert len(segs) == 2          # pruned down to keep
        assert all(s.endswith(".rtie") for s in segs)
        pts = quality.read_operating_points(path)
        # oldest segments were pruned, so the tail of the sequence
        # survives contiguously and in order
        gens = [p.generation for p in pts]
        assert gens == sorted(gens)
        assert gens[-1] == 39
        assert 0 < len(gens) < 40

    def test_torn_final_line_tolerated(self, tmp_path):
        path = str(tmp_path / "op.jsonl")
        with quality.OperatingPointLog(path) as log:
            log.append(_point(0))
            log.append(_point(1))
        with open(path, "a") as f:
            f.write('{"t": 2.0, "generation": 2, "kno')   # crash mid-line
        pts = quality.read_operating_points(path)
        assert [p.generation for p in pts] == [0, 1]

    def test_garbage_mid_file_raises(self, tmp_path):
        path = str(tmp_path / "op.jsonl")
        with quality.OperatingPointLog(path) as log:
            log.append(_point(0))
        with open(path, "a") as f:
            f.write("not json at all\n")
            f.write(json.dumps(_point(1).as_dict()) + "\n")
        with pytest.raises(CorruptIndexError, match="line 2"):
            quality.read_operating_points(path)

    def test_corrupt_sealed_segment_rejected(self, tmp_path):
        path = str(tmp_path / "op.jsonl")
        with quality.OperatingPointLog(path, max_bytes=128,
                                       keep=8) as log:
            for j in range(10):
                log.append(_point(j))
        seg = quality._segment_paths(path)[0]
        raw = bytearray(open(seg, "rb").read())
        raw[-3] ^= 0xFF
        open(seg, "wb").write(bytes(raw))
        with pytest.raises(CorruptIndexError):
            quality.read_operating_points(path)

    def test_calibrator_table_pools_by_knobs(self):
        pts = [_point(0, p99=0.002), _point(1, p99=0.004),
               _point(2, knobs={"kind": "ivf_pq", "n_probes": 16,
                                "k": 10})]
        table = quality.calibrator_table(pts)
        assert len(table) == 2
        key8 = tuple(sorted({"kind": "ivf_pq", "n_probes": 8,
                             "k": 10}.items()))
        row = table[key8]
        # hits/total re-pooled across windows, not averaged
        assert row["hits"] == 9 + 18 and row["total"] == 10 + 20
        assert row["recall"] == pytest.approx(27 / 30)
        assert row["recall_lo"] <= row["recall"] <= row["recall_hi"]
        assert row["p99"] == pytest.approx(0.003)
        assert len(row["points"]) == 2


# ---------------------------------------------------------------------------
# drift detection


class _FakeIndex:
    def __init__(self, group_est=0.0):
        self.group_est = group_est


class _FakeMemtable:
    def __init__(self, live, dead):
        self.live_rows = live
        self.n_tombstones = dead


class TestDriftDetector:
    def test_group_est_staleness_flagged(self):
        det = quality.DriftDetector()
        stats = {"touched_fraction": 0.5, "touched_lists": 16.0,
                 "n_probes": 4.0, "n_lists": 32.0}
        # calibrated at 0.1, measured 0.5 > 0.1 * 1.25 -> stale
        fs = det.check(index=_FakeIndex(group_est=0.1), probe_stats=stats)
        assert [f.kind for f in fs] == ["group_est"]
        assert fs[0].measured == pytest.approx(0.5)
        evs = flight.events("serving.quality.drift")
        assert len(evs) == 1 and evs[0]["attrs"]["kind"] == "group_est"

    def test_group_est_within_margin_quiet(self):
        det = quality.DriftDetector()
        stats = {"touched_fraction": 0.5, "touched_lists": 16.0,
                 "n_probes": 4.0, "n_lists": 32.0}
        assert det.check(index=_FakeIndex(group_est=0.45),
                         probe_stats=stats) == []
        # uncalibrated (group_est == 0) must never invent drift
        assert det.check(index=_FakeIndex(), probe_stats=stats) == []

    def test_scan_skew_flagged(self):
        det = quality.DriftDetector()
        stats = {"touched_fraction": 0.2, "touched_lists": 8.0,
                 "n_probes": 4.0, "n_lists": 32.0,
                 "live_rows": 3200.0, "probed_rows_per_query": 900.0}
        # uniform model: 3200 * 4 / 32 = 400; measured 900 > 2x
        fs = det.check(index=_FakeIndex(), probe_stats=stats)
        assert [f.kind for f in fs] == ["scan_skew"]
        assert fs[0].calibrated == pytest.approx(400.0)

    def test_fused_fallback_window_with_reasons(self):
        det = quality.DriftDetector()
        with obs.collecting():
            obs.registry().counter("ivf_pq.search.fused_fallback").inc(3)
            obs.registry().counter(
                "ivf_pq.search.fused_fallback.reason.kt_zero").inc(3)
            fs = det.check()
            assert [f.kind for f in fs] == ["fused_fallback"]
            assert fs[0].measured == 3.0
            assert fs[0].detail["reasons"] == {"kt_zero": 3}
            snap = obs.snapshot()["counters"]
            assert snap["serving.quality.drift"] == 1
            assert snap["serving.quality.drift.fused_fallback"] == 1

    def test_memtable_dead_fraction_flagged(self):
        det = quality.DriftDetector()
        # delete-heavy churn: 8 tombstones over 4 live rows (67% dead)
        fs = det.check(memtable=_FakeMemtable(live=4, dead=8))
        assert [f.kind for f in fs] == ["memtable_dead"]
        assert fs[0].measured == pytest.approx(8 / 12)
        assert det.check(memtable=_FakeMemtable(live=10, dead=1)) == []
        assert det.check(memtable=_FakeMemtable(live=0, dead=0)) == []

    def test_no_signals_no_findings(self):
        assert quality.DriftDetector().check() == []

    def test_measure_probe_stats_on_real_index(self, pq_index, dataset):
        _, q = dataset
        stats = quality.measure_probe_stats(pq_index, q[:16], n_probes=4)
        assert 0.0 < stats["touched_fraction"] <= 1.0
        assert stats["n_lists"] == 32.0 and stats["n_probes"] == 4.0
        assert stats["probed_rows_per_query"] > 0
        assert stats["live_rows"] == 4000.0
        # no coarse structure -> no measurement, never an exception
        assert quality.measure_probe_stats(object(), q[:4], 4) is None

    def test_injected_staleness_on_real_index(self, pq_index, dataset):
        _, q = dataset
        det = quality.DriftDetector()
        stale = dataclasses.replace(pq_index)
        # inject: calibration claims almost no lists are touched
        stale.group_est = 0.01
        fs = det.check(index=stale, queries=q[:16], n_probes=8)
        assert "group_est" in [f.kind for f in fs]


# ---------------------------------------------------------------------------
# ground-truth derivation + operating knobs


class TestGroundTruthParams:
    def test_ivf_pq_full_probe(self, pq_index):
        sp = serving.ground_truth_search_params(
            "ivf_pq", pq_index,
            ivf_pq.SearchParams(n_probes=4, per_probe_topk=4,
                                scan_mode="fused"))
        assert sp.n_probes == pq_index.n_lists
        assert sp.exact_coarse is True
        assert sp.per_probe_topk == 0
        assert sp.use_reconstruction is None
        assert sp.scan_mode in ("lut", "recon")

    def test_ivf_flat_full_probe(self, res, dataset):
        db, _ = dataset
        idx = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2), db)
        sp = serving.ground_truth_search_params("ivf_flat", idx)
        assert sp.n_probes == 16

    def test_brute_force_already_exact(self):
        assert serving.ground_truth_search_params("brute_force",
                                                  object()) is None

    def test_underivable_kind_refused(self):
        with pytest.raises(ValueError, match="ground_truth_params"):
            serving.ground_truth_search_params("cagra", object())


class TestOperatingKnobs:
    def test_executor_reports_closed_shape_coordinates(self, res, pq_index):
        ex = serving.Executor(
            res, "ivf_pq", pq_index, ks=(5,), max_batch=16,
            search_params=ivf_pq.SearchParams(n_probes=8,
                                              scan_mode="fused",
                                              per_probe_topk=4),
            warm="jit")
        knobs = ex.operating_knobs(0)
        assert knobs["kind"] == "ivf_pq"
        assert knobs["bucket"] == 16
        assert knobs["rung"] == 0
        assert knobs["n_probes"] == 8
        assert knobs["scan_mode"] == "fused"
        assert knobs["kt"] == 4
        assert json.dumps(knobs)       # op-log serializable as-is


# ---------------------------------------------------------------------------
# the shadow monitor end-to-end


def _shadow_server(res, pq_index, config, n_probes=8):
    sp = ivf_pq.SearchParams(n_probes=n_probes)
    ex = serving.Executor(res, "ivf_pq", pq_index, ks=(5,), max_batch=16,
                          search_params=sp, warm="jit")
    srv = serving.Server(ex, serving.ServerConfig(max_batch=16,
                                                  max_wait_us=500))
    monitor = serving.ShadowMonitor(config)
    srv.attach_shadow(monitor)
    return srv, monitor


def _drain(monitor, timeout=15.0):
    deadline = time.monotonic() + timeout
    while monitor.stats()["backlog"] and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)                    # let an in-flight replay land


class TestShadowMonitor:
    # The three full-loop tests (server + replay thread + ground-truth
    # executor warm) dominate this module's runtime; they run in the CI
    # quality job (which runs this file unfiltered) and stay out of the
    # fast tier.
    @pytest.mark.slow
    def test_live_estimate_with_interval_and_oplog(self, res, pq_index,
                                                   dataset, tmp_path):
        _, q = dataset
        cfg = serving.ShadowConfig(sample_rows_per_s=1e6, burst_rows=1e6,
                                   window_s=3600.0,
                                   op_log_path=str(tmp_path / "op.jsonl"))
        srv, monitor = _shadow_server(res, pq_index, cfg)
        with obs.collecting():
            srv.start()
            try:
                for j in range(6):
                    srv.search(q[j * 8:(j + 1) * 8], 5)
                _drain(monitor)
                records = monitor.flush()
            finally:
                srv.stop()
            snap = obs.snapshot()
        assert snap["counters"]["serving.shadow.replayed"] >= 8
        est = monitor.estimator.estimate()
        assert est is not None
        assert 0.0 <= est.lo <= est.recall <= est.hi <= 1.0
        assert est.rows >= 8
        assert records and records[0]["k"] == 5
        assert snap["gauges"]["serving.quality.recall"] == pytest.approx(
            est.recall)
        # op-point log round-trips into the calibrator shape
        pts = quality.read_operating_points(str(tmp_path / "op.jsonl"))
        assert pts
        assert pts[0].knobs["kind"] == "ivf_pq"
        assert pts[0].knobs["k"] == 5
        assert pts[0].measured["total"] >= 1
        assert quality.calibrator_table(pts)

    @pytest.mark.slow
    def test_degraded_window_arms_watchdog(self, res, pq_index, dataset):
        _, q = dataset
        # injected recall regression: serve at n_probes=1 against the
        # full-probe ground truth, with a floor the estimate can't meet
        cfg = serving.ShadowConfig(sample_rows_per_s=1e6, burst_rows=1e6,
                                   window_s=3600.0, recall_floor=0.99,
                                   arm_watchdog=True)
        srv, monitor = _shadow_server(res, pq_index, cfg, n_probes=1)
        strikes = []
        srv.note_integrity_strike = lambda reason: (strikes.append(reason)
                                                    or True)
        with obs.collecting():
            srv.start()
            try:
                for j in range(6):
                    srv.search(q[j * 8:(j + 1) * 8], 5)
                _drain(monitor)
                records = monitor.flush()
            finally:
                srv.stop()
            snap = obs.snapshot()
        assert any(r["degraded"] for r in records)
        evs = flight.events("serving.quality.degraded")
        assert evs and evs[0]["attrs"]["floor"] == pytest.approx(0.99)
        assert evs[0]["attrs"]["lo"] < 0.99
        assert strikes and "floor" in strikes[0]
        assert snap["counters"]["serving.quality.degraded"] >= 1

    @pytest.mark.slow
    def test_swap_rederives_ground_truth_point(self, res, dataset):
        db, _ = dataset
        a = ivf_pq.build(res, ivf_pq.IndexParams(n_lists=32, pq_dim=8,
                                                 kmeans_n_iters=2), db)
        b = ivf_pq.build(res, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                                 kmeans_n_iters=2), db)
        cfg = serving.ShadowConfig(window_s=3600.0)
        srv, monitor = _shadow_server(res, a, cfg)
        srv.start()
        try:
            assert monitor.executor.params.n_probes == 32
            srv.swap_index(b)
            assert monitor.executor.index is b
            assert monitor.executor.params.n_probes == 16
            assert monitor.executor.params.exact_coarse is True
        finally:
            srv.stop()

    def test_stale_generation_sample_dropped(self, res, pq_index, dataset):
        _, q = dataset
        cfg = serving.ShadowConfig(window_s=3600.0)
        srv, monitor = _shadow_server(res, pq_index, cfg)
        with obs.collecting():
            srv.start()
            try:
                stale = ShadowSample(
                    queries=q[:4].copy(),
                    served_ids=np.zeros((4, 5), np.int64), k=5,
                    tenant="default", rung=0, index=object(), t=0.0)
                monitor._replay(stale)
            finally:
                srv.stop()
            snap = obs.snapshot()
        assert snap["counters"]["serving.shadow.dropped.generation"] == 1
        assert monitor.estimator.estimate() is None

    def test_budget_zero_skips_sampling(self, res, pq_index, dataset):
        _, q = dataset
        cfg = serving.ShadowConfig(sample_rows_per_s=1e-9, burst_rows=0.0,
                                   window_s=3600.0)
        srv, monitor = _shadow_server(res, pq_index, cfg)
        with obs.collecting():
            srv.start()
            try:
                for j in range(3):
                    srv.search(q[j * 8:(j + 1) * 8], 5)
                _drain(monitor)
            finally:
                srv.stop()
            snap = obs.snapshot()
        assert snap["counters"].get("serving.shadow.sampled", 0) == 0
        assert snap["counters"]["serving.shadow.skipped.budget"] >= 24

    def test_disabled_offer_is_one_flag_check(self, res, pq_index):
        cfg = serving.ShadowConfig(window_s=3600.0)
        srv, monitor = _shadow_server(res, pq_index, cfg)
        monitor.disable()

        class _Forbidden:
            def __getattr__(self, name):
                raise AssertionError(
                    f"disabled offer() touched {name!r}")

        # with sampling disabled, offer() may read nothing but the flag
        monitor._budget = _Forbidden()
        monitor._tenant_budgets = _Forbidden()
        monitor._cond = _Forbidden()
        monitor._samples = _Forbidden()
        monitor.offer([(object(), None, None)], 5, pq_index)
        monitor.enable()
        monitor._budget = serving.TokenBucket(1.0, 1.0)
        monitor._tenant_budgets = {}

    def test_attach_after_start_refused(self, res, pq_index):
        sp = ivf_pq.SearchParams(n_probes=8)
        ex = serving.Executor(res, "ivf_pq", pq_index, ks=(5,),
                              max_batch=16, search_params=sp, warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=16,
                                                      max_wait_us=500))
        srv.start()
        try:
            with pytest.raises(Exception, match="start"):
                srv.attach_shadow(serving.ShadowMonitor())
        finally:
            srv.stop()
