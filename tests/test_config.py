"""Output-type config / auto-convert tests.

Mirrors the reference's
python/pylibraft/pylibraft/test/test_config.py:46 ``test_auto_convert_output``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import raft_tpu.config
from raft_tpu import auto_convert_output


@auto_convert_output
def gen_arrays(m, n, t=None):
    a = jnp.zeros((m, n), jnp.float32)
    if t is None:
        return a
    if t == tuple:
        return a, jnp.ones((m, n), jnp.float32)
    if t == list:
        return [a, jnp.ones((m, n), jnp.float32)]


@pytest.fixture(autouse=True)
def _restore_config():
    yield
    raft_tpu.config.set_output_as("jax")


@pytest.mark.parametrize(
    "out_type",
    [
        ("jax", jax.Array),
        ("numpy", np.ndarray),
        ("torch", torch.Tensor),
        (lambda arr: np.asarray(arr), np.ndarray),
    ],
    ids=["jax", "numpy", "torch", "callable"],
)
@pytest.mark.parametrize("gen_t", [None, tuple, list])
def test_auto_convert_output(out_type, gen_t):
    conf, t = out_type
    raft_tpu.config.set_output_as(conf)
    output = gen_arrays(1, 5, gen_t)
    if not isinstance(output, (list, tuple)):
        assert isinstance(output, t)
    else:
        for o in output:
            assert isinstance(o, t)


def test_invalid_option_rejected():
    with pytest.raises(ValueError):
        raft_tpu.config.set_output_as("cupy")


def test_namedtuple_preserved(res):
    """raft_tpu index/search APIs return NamedTuples; the container type and
    field names must survive conversion."""
    from raft_tpu.distance import fused_l2_nn
    raft_tpu.config.set_output_as("numpy")
    x = np.random.default_rng(0).random((16, 8)).astype(np.float32)
    y = np.random.default_rng(1).random((8, 8)).astype(np.float32)
    out = fused_l2_nn(x, y)
    leaves = out if isinstance(out, (list, tuple)) else [out]
    for leaf in leaves:
        assert isinstance(leaf, np.ndarray)


@pytest.mark.parametrize(
    "conf, t",
    [
        ("numpy", np.ndarray),
        ("torch", torch.Tensor),
        (lambda arr: np.asarray(arr), np.ndarray),
    ],
    ids=["numpy", "torch", "callable"],
)
def test_composite_jit_functions_with_non_jax_output(res, conf, t):
    """Regression: decorated primitives (select_k, pairwise_distance,
    fused_l2_nn) are called both inside jitted compositions (tracers must
    pass through) and *eagerly* from other library code (kmeans.predict,
    cagra.build via ivf_pq.search) — internal eager call sites must use
    ``raw()`` so a torch/callable output type never leaks jax-incompatible
    values mid-pipeline."""
    from raft_tpu.cluster import kmeans
    from raft_tpu.neighbors import brute_force
    rng = np.random.default_rng(0)
    X = rng.random((64, 8)).astype(np.float32)
    raft_tpu.config.set_output_as(conf)
    d, i = brute_force.knn(res, X, X[:8], 4)
    assert isinstance(d, t) and isinstance(i, t)
    params = kmeans.KMeansParams(n_clusters=4, max_iter=5)
    centroids, inertia, n_iter = kmeans.fit(res, params, X)
    assert isinstance(centroids, t)
    labels, _ = kmeans.predict(res, params, X, np.asarray(centroids))
    assert isinstance(labels, t)
    out = kmeans.fit_predict(res, params, X)
    assert isinstance(out[0], t)
    cost = kmeans.cluster_cost(jnp.asarray(X),
                               jnp.asarray(np.asarray(centroids)))
    assert float(cost) >= 0


@pytest.mark.parametrize("conf, t", [("torch", torch.Tensor)], ids=["torch"])
def test_cagra_build_with_non_jax_output(res, conf, t):
    """cagra.build composes ivf_pq.search + refine eagerly; it must work
    (and return the configured type) under any output config."""
    from raft_tpu.neighbors import cagra
    rng = np.random.default_rng(1)
    X = rng.random((256, 16)).astype(np.float32)
    raft_tpu.config.set_output_as(conf)
    index = cagra.build(res, cagra.IndexParams(
        graph_degree=8, intermediate_graph_degree=16), X)
    d, i = cagra.search(res, cagra.SearchParams(itopk_size=16), index,
                        X[:8], 4)
    assert isinstance(i, t)


def test_end_to_end_pairwise(res):
    """pylibraft round-trip: numpy in -> configured type out, values equal."""
    from raft_tpu.distance import pairwise_distance
    rng = np.random.default_rng(2)
    x = rng.random((10, 4)).astype(np.float32)

    raft_tpu.config.set_output_as("torch")
    d_torch = pairwise_distance(x, x, metric="euclidean")
    assert isinstance(d_torch, torch.Tensor)

    raft_tpu.config.set_output_as("jax")
    d_jax = pairwise_distance(x, x, metric="euclidean")
    assert isinstance(d_jax, jax.Array)
    np.testing.assert_allclose(np.asarray(d_jax), d_torch.numpy(), rtol=1e-5)
