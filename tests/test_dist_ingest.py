"""Replicated durable ingest over the routed index (round 19): owner-
routed writes through the shared coarse quantizer, per-shard WALs with
quorum acks, the two-LSN broadcast-tombstone upsert scheme, typed
Unavailable refusal, the write-path kill matrix at every
``ingest.dist.*`` boundary (zero acked-row loss + bit-identical
post-recovery search at r=2 + zero steady-state recompiles), the
catch-up WAL delta phase, per-shard torn-tail repair at every record
boundary, and the fold under ONE placement-generation bump."""

import os

import jax
import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu.comms import CommsSession
from raft_tpu.core import serialize as ser
from raft_tpu.neighbors import delta as _delta
from raft_tpu.neighbors import ivf_pq, mutate
from raft_tpu.observability import flight
from raft_tpu.resilience import FaultInjected, FaultPlan
from raft_tpu.serving.dist_ingest import (
    DistIngestConfig,
    RoutedIngest,
    Unavailable,
)
from raft_tpu.serving.ingest import scan_wal

# the CI chaos job pins this so a red matrix cell replays the identical
# kill schedule locally
SEED = int(os.environ.get("RAFT_TPU_FAULT_SEED", "20260805"))

DIST_KILL_SITES = ("ingest.dist.route", "ingest.dist.append",
                   "ingest.dist.ack", "ingest.dist.replicate",
                   "ingest.dist.fold", "ingest.dist.catch_up")

N, DIM, NL, NQ, K = 2048, 32, 32, 16, 10

NEW_IDS = np.arange(N, N + 32)
MOVED_IDS = np.arange(N, N + 8)
DEL_BASE = np.arange(40, 45)


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.disable()
    obs.reset()
    flight.clear()
    yield
    obs.disable()
    obs.reset()
    flight.clear()


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def rhandle():
    devs = jax.devices()
    if len(devs) < 8:
        devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
    s = CommsSession(mesh=mesh, axis_name="data").init()
    yield s.worker_handle(seed=0)
    s.destroy()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    db = rng.normal(size=(N, DIM)).astype(np.float32)
    q = rng.normal(size=(NQ, DIM)).astype(np.float32)
    new_rows = rng.normal(size=(32, DIM)).astype(np.float32)
    moved = rng.normal(size=(8, DIM)).astype(np.float32)
    return db, q, new_rows, moved


@pytest.fixture(scope="module")
def built(rhandle, data):
    from raft_tpu.distributed import ann
    db, _, _, _ = data
    params = ivf_pq.IndexParams(n_lists=NL, pq_dim=8, kmeans_n_iters=3,
                                cache_reconstructions=True)
    base = ivf_pq.build(rhandle, params, db)
    return base, ann.shard_by_list(rhandle, base, replication_factor=2)


def _fresh_tracker():
    from raft_tpu.distributed import health
    return health.HealthTracker(8, health.HealthConfig(
        suspect_after=1, fail_after=1, ok_to_clear=1, dwell_s=0.0))


def _mk(rhandle, built, wal_dir, *, tracker=None, policy=None, **cfg):
    base, routed = built
    ing = RoutedIngest(rhandle, routed, base,
                       config=DistIngestConfig(wal_dir=str(wal_dir),
                                               **cfg),
                       tracker=tracker, policy=policy)
    ing.recover()
    return ing


def _write_stream(ing, data):
    """The shared write sequence every matrix cell replays: two upsert
    batches, a delete touching base ids, and a re-upsert whose vectors
    moved (the two-LSN list-move case)."""
    _, _, new_rows, moved = data
    acked = []
    acked.append(ing.write(NEW_IDS[:16], new_rows[:16]))
    acked.append(ing.write(NEW_IDS[16:], new_rows[16:]))
    acked.append(ing.write(DEL_BASE, op="delete"))
    acked.append(ing.write(MOVED_IDS, moved))
    return acked


def _record_offsets(blob):
    """Byte offset of every framed record in a WAL blob."""
    head = ser._ENVELOPE_HEADER
    offsets = []
    off = 0
    while off < len(blob):
        offsets.append(off)
        _m, _v, length, _crc = head.unpack_from(blob, off)
        off += head.size + length
    assert off == len(blob)
    return offsets


class TestRoutedWritePath:
    def test_upsert_replicates_to_every_owner(self, rhandle, built,
                                              data, tmp_path):
        from raft_tpu.distributed import ann
        _, routed = built
        _, _, new_rows, _ = data
        ing = _mk(rhandle, built, tmp_path / "w")
        lsn = ing.write(NEW_IDS[:16], new_rows[:16])
        assert lsn == 2          # two-LSN scheme: tombstone + upsert
        homes = ann.route_vectors(routed, new_rows[:16])
        owners, _slots = routed.placement.rank_tables()
        for j, i in enumerate(NEW_IDS[:16]):
            g = int(homes[j])
            for rank in range(owners.shape[0]):
                s = int(owners[rank, g])
                assert int(i) in ing.memtables[s]._slot_of, (i, s)
        # the broadcast tombstone lands on EVERY shard (on the owners
        # it doubles as the main-index mask for the upserted id)
        for s in range(8):
            for i in NEW_IDS[:16]:
                assert int(i) in ing.memtables[s]._tombs
        ing.close()

    def test_moved_upsert_leaves_no_stale_copy(self, rhandle, built,
                                               data, tmp_path):
        _, _, new_rows, moved = data
        ing = _mk(rhandle, built, tmp_path / "w")
        ing.write(MOVED_IDS, new_rows[:8])
        ing.write(MOVED_IDS, moved)     # vectors moved: maybe new lists
        # exactly r live copies of each id across ALL memtables — the
        # broadcast tombstone killed every stale copy on old owners
        r = built[1].placement.replication_factor
        for i in MOVED_IDS:
            copies = sum(1 for m in ing.memtables
                         if int(i) in m._slot_of)
            assert copies == r, (i, copies)
        # and the live copies hold the NEW vector
        sp = ivf_pq.SearchParams(n_probes=NL)
        _, ids = ing.search(sp, moved, K)
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], MOVED_IDS)
        ing.close()

    def test_delete_broadcasts_and_masks_main(self, rhandle, built,
                                              data, tmp_path):
        _, q, _, _ = data
        ing = _mk(rhandle, built, tmp_path / "w")
        ing.write(DEL_BASE, op="delete")
        for s in range(8):
            for i in DEL_BASE:
                assert int(i) in ing.memtables[s]._tombs
        sp = ivf_pq.SearchParams(n_probes=NL)
        _, ids = ing.search(sp, q, K)
        assert not np.isin(np.asarray(ids), DEL_BASE).any()
        ing.close()

    def test_unavailable_when_every_replica_down(self, rhandle, built,
                                                 data, tmp_path):
        from raft_tpu.distributed import ann
        _, routed = built
        _, _, new_rows, _ = data
        tr = _fresh_tracker()
        ing = _mk(rhandle, built, tmp_path / "w", tracker=tr)
        vec = new_rows[:1]
        g = int(ann.route_vectors(routed, vec)[0])
        owners, _ = routed.placement.rank_tables()
        for rank in range(owners.shape[0]):
            s = int(owners[rank, g])
            tr.note_timeout(s)
            tr.note_timeout(s)      # suspect -> failed
        sizes_before = [os.path.getsize(ing.wal_path(s))
                        for s in range(8)]
        with obs.collecting():
            with pytest.raises(Unavailable):
                ing.write(np.asarray([N]), vec)
            assert obs.registry().counter(
                "serving.ingest.dist.unavailable").value == 1
        # refused BEFORE any WAL byte anywhere
        assert sizes_before == [os.path.getsize(ing.wal_path(s))
                                for s in range(8)]
        ev = flight.events("serving.ingest.dist.unavailable")
        assert ev and g in ev[-1]["attrs"]["lists"]
        ing.close()

    def test_quorum_one_acks_with_a_replica_down(self, rhandle, built,
                                                 data, tmp_path):
        from raft_tpu.distributed import ann
        _, routed = built
        _, _, new_rows, _ = data
        tr = _fresh_tracker()
        ing = _mk(rhandle, built, tmp_path / "w", tracker=tr,
                  write_quorum=1)
        vec = new_rows[:1]
        g = int(ann.route_vectors(routed, vec)[0])
        owners, _ = routed.placement.rank_tables()
        dead = int(owners[0, g])
        tr.note_timeout(dead)
        tr.note_timeout(dead)
        lsn = ing.write(np.asarray([N]), vec)
        assert lsn > 0
        # the row is readable from the surviving replica (masked view
        # for the dead shard; id<0 seam + k-bounded merge)
        sp = ivf_pq.SearchParams(n_probes=NL)
        _, ids = ing.search(sp, vec, K)
        assert int(np.asarray(ids)[0, 0]) == N
        ing.close()

    def test_leader_append_failure_fails_ack_under_full_quorum(
            self, rhandle, built, data, tmp_path):
        _, _, new_rows, _ = data
        tr = _fresh_tracker()
        ing = _mk(rhandle, built, tmp_path / "w", tracker=tr)
        with FaultPlan(seed=SEED).at("ingest.dist.append",
                                     times=1).active():
            with pytest.raises(FaultInjected):
                ing.write(np.asarray([N]), new_rows[:1])
        # the leader took a write-error strike (hard evidence)
        assert any(st in ("SUSPECT", "FAILED") for st in tr.states())
        assert flight.events("serving.ingest.dist.write_error")
        # idempotent retry acks once the fault clears
        assert ing.write(np.asarray([N]), new_rows[:1]) > 0
        ing.close()

    def test_all_fsyncs_failing_fails_ack(self, rhandle, built, data,
                                          tmp_path):
        """Satellite: the per-shard WALs inherit the ``ingest.fsync``
        failure path — a sync that raises fails the ack for every row
        riding that shard's group commit."""
        _, _, new_rows, _ = data
        ing = _mk(rhandle, built, tmp_path / "w")
        with FaultPlan(seed=SEED).at("ingest.fsync", times=8).active():
            with pytest.raises(FaultInjected):
                ing.write(NEW_IDS[:4], new_rows[:4])
        assert ing.write(NEW_IDS[:4], new_rows[:4]) > 0
        ing.close()


class TestKillMatrix:
    """The acceptance matrix: a seed-pinned single-shard kill at every
    ``ingest.dist.*`` boundary, r=2 — every acked row survives, the
    recovered full-probe search is bit-identical to the never-killed
    control, and the fail -> catch-up -> readmit arc triggers zero
    steady-state recompiles."""

    KILL_SHARD = 2

    @pytest.fixture(scope="class")
    def control(self, rhandle, built, data, tmp_path_factory):
        _, q, _, moved = data
        ing = _mk(rhandle, built,
                  tmp_path_factory.mktemp("ctl") / "w")
        acked = _write_stream(ing, data)
        assert all(a > 0 for a in acked)
        sp = ivf_pq.SearchParams(n_probes=NL)
        d1, i1 = ing.search(sp, q, K)
        d2, i2 = ing.search(sp, moved, K)
        np.testing.assert_array_equal(np.asarray(i2)[:, 0], MOVED_IDS)
        assert not np.isin(np.asarray(i1), DEL_BASE).any()
        ing.close()
        return (np.asarray(d1), np.asarray(i1), np.asarray(d2),
                np.asarray(i2))

    def _drop_shard_state(self, ing, s):
        """Simulate the killed shard's process loss: its WAL bytes and
        memtable are gone."""
        if ing._wals[s] is not None:
            ing._wals[s].close()
            ing._wals[s] = None
        os.unlink(ing.wal_path(s))
        ing.memtables[s].reset()

    @pytest.mark.parametrize("site", DIST_KILL_SITES)
    def test_kill_matrix_zero_acked_loss_bit_identical(
            self, rhandle, built, data, control, tmp_path, site):
        from raft_tpu.distributed import health
        _, q, _, moved = data
        s = self.KILL_SHARD
        tr = _fresh_tracker()
        ing = _mk(rhandle, built, tmp_path / "w", tracker=tr)
        sp = ivf_pq.SearchParams(n_probes=NL)
        plan = FaultPlan(seed=SEED).kill_shard_at(site, s)
        if site == "ingest.dist.catch_up":
            # this site only fires inside the delta phase below
            acked = _write_stream(ing, data)
            tr.note_timeout(s)
            tr.note_timeout(s)
        else:
            with plan.active():
                # kill_shard_at is a membership change, not an
                # exception: every write still acks (the quorum
                # re-plans onto survivors once the kill is observed)
                acked = _write_stream(ing, data)
                if site == "ingest.dist.fold":
                    assert ing.fold() is not None
                tr.note_timeout(s)
                tr.note_timeout(s)   # the decision loop declares FAILED
        assert all(a > 0 for a in acked)
        assert s in tr.failed_shards()
        self._drop_shard_state(ing, s)
        # acked rows remain visible while the shard is down (replicas
        # hold every acked row; the dead shard joins as a masked view)
        _, ids_down = ing.search(sp, moved, K)
        np.testing.assert_array_equal(np.asarray(ids_down)[:, 0],
                                      MOVED_IDS)
        # catch-up delta phase + canary-gated readmission
        if site == "ingest.dist.catch_up":
            with plan.active():
                caught = health.catch_up(rhandle, ing.index, s,
                                         tracker=tr, ingest=ing)
        else:
            caught = health.catch_up(rhandle, ing.index, s, tracker=tr,
                                     ingest=ing)
        assert health.readmit(rhandle, ing, caught, s, tracker=tr)
        assert s not in tr.failed_shards()
        assert flight.events("serving.ingest.dist.catch_up")
        d1, i1 = ing.search(sp, q, K)
        d2, i2 = ing.search(sp, moved, K)
        if site == "ingest.dist.fold":
            # the fold drained the delta tier into the index: the same
            # rows answer, now from the folded main
            np.testing.assert_array_equal(np.asarray(i2)[:, 0],
                                          MOVED_IDS)
            assert not np.isin(np.asarray(i1), DEL_BASE).any()
        else:
            cd1, ci1, cd2, ci2 = control
            np.testing.assert_array_equal(np.asarray(i1), ci1)
            np.testing.assert_allclose(np.asarray(d1), cd1,
                                       rtol=0, atol=0)
            np.testing.assert_array_equal(np.asarray(i2), ci2)
            np.testing.assert_allclose(np.asarray(d2), cd2,
                                       rtol=0, atol=0)
        # the kill really fired at the scripted site
        assert sum(spec.fired for spec in plan.specs) == 1
        ing.close()

    def test_failover_write_read_zero_recompiles(self, rhandle, built,
                                                 data, tmp_path):
        """Routing tables and memtable views are data, not shape: the
        fail -> re-plan -> read arc reuses every warmed executable."""
        _, q, _, moved = data
        tr = _fresh_tracker()
        ing = _mk(rhandle, built, tmp_path / "w", tracker=tr)
        sp = ivf_pq.SearchParams(n_probes=NL)
        _write_stream(ing, data)
        assert ing.prewarm([1, 8, 16]) > 0
        ing.search(sp, q, K)                 # warm healthy read
        ing.search(sp, moved, K)
        s = self.KILL_SHARD
        tr.note_timeout(s)
        tr.note_timeout(s)
        ing.search(sp, q, K)                 # warm the masked-view read
        ing.search(sp, moved, K)
        with obs.collecting():
            c0 = obs.registry().counter("xla.compiles").value
            ing.write(NEW_IDS[:16] + 100, data[2][:16])   # re-routed
            _, _i = ing.search(sp, q, K)
            _, i_moved = ing.search(sp, moved, K)
            c1 = obs.registry().counter("xla.compiles").value
        assert c1 == c0, f"{c1 - c0} recompiles across write failover"
        np.testing.assert_array_equal(np.asarray(i_moved)[:, 0],
                                      MOVED_IDS)
        ing.close()


class TestTornTail:
    def test_torn_tail_repair_at_every_record_boundary(
            self, rhandle, built, data, tmp_path):
        """Per-shard WALs inherit the PR 13 torn-tail taxonomy: cut one
        shard's log mid-record at EVERY record boundary — recover()
        repairs the tail, replays the intact prefix, and the memtable
        matches an independent replay of the same prefix."""
        ing = _mk(rhandle, built, tmp_path / "w")
        _write_stream(ing, data)
        s = 0
        path = ing.wal_path(s)
        ing.close()
        with open(path, "rb") as f:
            blob = f.read()
        records, good_end = scan_wal(blob)
        assert good_end == len(blob) and records
        offsets = _record_offsets(blob)
        assert len(offsets) == len(records)
        bounds = offsets + [len(blob)]
        for j, start in enumerate(offsets):
            # tear record j roughly mid-frame: records[:j] stay intact
            cut = start + max(1, (bounds[j + 1] - start) // 2)
            with open(path, "wb") as f:
                f.write(blob[:cut])
            ing2 = _mk(rhandle, built, tmp_path / "w")
            ref = _delta.Memtable(DIM, capacity=1024,
                                  tomb_capacity=1024,
                                  metric=ing2.metric)
            for rec in records[:j]:
                ref.apply(rec)
            assert ing2.memtables[s].digest() == ref.digest(), j
            # the repaired log is clean: exactly the intact prefix
            with open(path, "rb") as f:
                repaired = f.read()
            recs2, end2 = scan_wal(repaired)
            assert end2 == len(repaired) and len(recs2) == j
            ing2.close()
        with open(path, "wb") as f:
            f.write(blob)       # restore the intact log


class TestFoldAndRecover:
    def test_fold_one_placement_generation_bump(self, rhandle, built,
                                                data, tmp_path):
        _, q, _, moved = data
        ing = _mk(rhandle, built, tmp_path / "w")
        _write_stream(ing, data)
        g_idx = mutate.generation(ing.index)
        g_pl = ing.index.placement.generation
        with obs.collecting():
            out = ing.fold()
            assert obs.registry().counter(
                "serving.ingest.dist.folds").value == 1
        assert out is not None
        assert ing.index.placement.generation == g_pl + 1
        assert mutate.generation(ing.index) == g_idx + 1
        # every shard WAL truncated, every memtable drained
        assert ing.stats()["wal_bytes"] == [0] * 8
        assert all(m.live_rows == 0 and m.n_tombstones == 0
                   for m in ing.memtables)
        sp = ivf_pq.SearchParams(n_probes=NL)
        _, ids = ing.search(sp, moved, K)
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], MOVED_IDS)
        _, ids_q = ing.search(sp, q, K)
        assert not np.isin(np.asarray(ids_q), DEL_BASE).any()
        ev = flight.events("serving.ingest.dist.fold")
        assert ev and ev[-1]["attrs"]["placement_generation"] == g_pl + 1
        ing.close()

    def test_recover_rolls_forward_after_commit_marker(
            self, rhandle, built, data, tmp_path):
        """A kill between the commit marker and the truncations rolls
        FORWARD: the checkpointed candidate serves, truncations
        finish."""
        _, q, _, moved = data
        ing = _mk(rhandle, built, tmp_path / "w")
        _write_stream(ing, data)
        # the fold dies on the FIRST per-shard truncation — after the
        # commit marker and the publish
        with FaultPlan(seed=SEED).at("ingest.truncate",
                                     times=1).active():
            with pytest.raises(FaultInjected):
                ing.fold()
        ing.close()
        ing2 = _mk(rhandle, built, tmp_path / "w")
        ev = flight.events("serving.ingest.dist.replay")
        assert ev and ev[-1]["attrs"]["rolled_forward"] is True
        assert ing2.stats()["wal_bytes"] == [0] * 8
        sp = ivf_pq.SearchParams(n_probes=NL)
        _, ids = ing2.search(sp, moved, K)
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], MOVED_IDS)
        _, ids_q = ing2.search(sp, q, K)
        assert not np.isin(np.asarray(ids_q), DEL_BASE).any()
        ing2.close()

    def test_recover_rolls_back_before_commit_marker(
            self, rhandle, built, data, tmp_path):
        """A kill at the fold boundary (before the marker) rolls BACK:
        the base index is untouched and the per-shard replay reproduces
        every logged record bit-identically."""
        _, _, _, moved = data
        ing = _mk(rhandle, built, tmp_path / "w")
        _write_stream(ing, data)
        digests = [m.digest() for m in ing.memtables]
        last = ing.stats()["last_lsn"]
        with FaultPlan(seed=SEED).at("ingest.dist.fold",
                                     times=1).active():
            with pytest.raises(FaultInjected):
                ing.fold()
        ing.close()
        ing2 = _mk(rhandle, built, tmp_path / "w")
        assert [m.digest() for m in ing2.memtables] == digests
        assert ing2.stats()["last_lsn"] == last
        sp = ivf_pq.SearchParams(n_probes=NL)
        _, ids = ing2.search(sp, moved, K)
        np.testing.assert_array_equal(np.asarray(ids)[:, 0], MOVED_IDS)
        ing2.close()

    def test_catch_up_filters_to_owned_lists(self, rhandle, built,
                                             data, tmp_path):
        from raft_tpu.distributed import ann
        _, _, new_rows, _ = data
        ing = _mk(rhandle, built, tmp_path / "w")
        ing.write(NEW_IDS[:16], new_rows[:16])
        s = 1
        before = ing.memtables[s].digest()
        kept = ing.catch_up_shard(s)
        assert kept > 0
        # a catch-up of an up-to-date shard is a no-op on its state:
        # the rebuilt WAL + memtable reproduce what it already held
        assert ing.memtables[s].digest() == before
        homes = ann.route_vectors(ing.index, new_rows[:16])
        owned = set(int(g) for g in
                    ing.index.placement.shard_lists(s))
        for j, i in enumerate(NEW_IDS[:16]):
            should = int(homes[j]) in owned
            assert (int(i) in ing.memtables[s]._slot_of) == should
        ing.close()
