"""Ball cover tests.

Mirrors the reference's recall-based ANN strategy (SURVEY.md §4;
cpp/test/neighbors/ball_cover.cu compares RBC against brute force with
a min-recall assertion).  RBC with post-filtering is exact, so the bar
here is equality-up-to-ties with brute force, plus the VERDICT contract:
recall >= 0.95 on 10k haversine points.
"""

import numpy as np
import pytest

from raft_tpu.distance import DistanceType
from raft_tpu.neighbors import ball_cover, brute_force


def _recall(found, gt):
    found = np.asarray(found)
    gt = np.asarray(gt)
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, gt))
    return hits / gt.size


def _haversine_np(x, y):
    dlat = 0.5 * (x[:, None, 0] - y[None, :, 0])
    dlon = 0.5 * (x[:, None, 1] - y[None, :, 1])
    a = (np.sin(dlat) ** 2
         + np.cos(x[:, None, 0]) * np.cos(y[None, :, 0]) * np.sin(dlon) ** 2)
    return 2.0 * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


def _random_latlon(rng, n):
    lat = rng.uniform(-np.pi / 2, np.pi / 2, n)
    lon = rng.uniform(-np.pi, np.pi, n)
    return np.stack([lat, lon], axis=1).astype(np.float32)


class TestHaversine:
    @pytest.mark.slow
    def test_all_knn_query_10k(self, res):
        rng = np.random.default_rng(0)
        X = _random_latlon(rng, 10_000)
        k = 11
        index = ball_cover.BallCoverIndex(res, X,
                                          metric=DistanceType.Haversine)
        d, i = ball_cover.all_knn_query(res, index, k)
        gt = np.argsort(_haversine_np(X, X), axis=1)[:, :k]
        assert _recall(i, gt) >= 0.95   # exact up to ties, VERDICT bar 0.95
        # distances must be the true haversine values, sorted
        d = np.asarray(d)
        assert np.all(np.diff(d, axis=1) >= -1e-6)
        np.testing.assert_allclose(d[:, 0], 0.0, atol=1e-5)  # self-match

    def test_knn_query_out_of_index(self, res):
        rng = np.random.default_rng(1)
        X = _random_latlon(rng, 2000)
        Q = _random_latlon(rng, 100)
        k = 5
        index = ball_cover.BallCoverIndex(res, X,
                                          metric=DistanceType.Haversine)
        ball_cover.build_index(res, index)
        d, i = ball_cover.knn_query(res, index, Q, k)
        gt = np.argsort(_haversine_np(Q, X), axis=1)[:, :k]
        assert _recall(i, gt) >= 0.99


class TestEuclidean:
    @pytest.mark.parametrize("metric", [DistanceType.L2SqrtExpanded,
                                        DistanceType.L2Unexpanded])
    def test_matches_brute_force(self, res, metric):
        rng = np.random.default_rng(2)
        X = rng.random((4000, 8)).astype(np.float32)
        Q = rng.random((200, 8)).astype(np.float32)
        k = 10
        index = ball_cover.BallCoverIndex(res, X, metric=metric)
        ball_cover.build_index(res, index)
        d, i = ball_cover.knn_query(res, index, Q, k)
        bf_d, bf_i = brute_force.knn(res, X, Q, k, metric=metric)
        assert _recall(i, bf_i) >= 0.99
        np.testing.assert_allclose(np.sort(np.asarray(d), axis=1),
                                   np.sort(np.asarray(bf_d), axis=1),
                                   rtol=1e-4, atol=1e-5)

    def test_squared_metric_pruning_is_exact(self, res):
        """Regression: pruning must use real units — in squared units
        ``d² - r²`` over-prunes (a ball at distance 3.5 with radius 2.5
        holds a point at distance 1, but 3.5² - 2.5² = 6 > 1²)."""
        rng = np.random.default_rng(0)
        near = rng.normal(0.0, 0.3, (50, 2)).astype(np.float32)
        far = (np.array([3.5, 0.0]) +
               rng.normal(0.0, 1.2, (50, 2))).astype(np.float32)
        X = np.concatenate([near, far, [[1.0, 0.0]]]).astype(np.float32)
        Q = np.zeros((1, 2), np.float32)
        for seed in range(5):
            r = type(res)(seed=seed)
            index = ball_cover.BallCoverIndex(
                r, X, metric=DistanceType.L2Expanded, n_landmarks=3)
            ball_cover.build_index(r, index)
            d, i = ball_cover.knn_query(r, index, Q, 3)
            gt_d = np.sum((X - Q) ** 2, axis=1)
            gt = np.argsort(gt_d)[:3]
            np.testing.assert_allclose(np.asarray(d)[0],
                                       np.sort(gt_d)[:3], rtol=1e-4,
                                       atol=1e-6)
            assert set(np.asarray(i)[0]) == set(gt)

    def test_weight_below_one_approximate(self, res):
        """weight < 1 prunes more balls — recall may drop but stays decent
        (reference ball_cover.cuh:102-110 semantics)."""
        rng = np.random.default_rng(3)
        X = rng.random((3000, 4)).astype(np.float32)
        index = ball_cover.BallCoverIndex(res, X)
        d, i = ball_cover.all_knn_query(res, index, 10, weight=0.5)
        _, gt = brute_force.knn(res, X, X, 10)
        assert _recall(i, gt) >= 0.8

    def test_no_post_filtering_first_pass_only(self, res):
        rng = np.random.default_rng(4)
        X = rng.random((2000, 4)).astype(np.float32)
        index = ball_cover.BallCoverIndex(res, X)
        d, i = ball_cover.all_knn_query(res, index, 8,
                                        perform_post_filtering=False)
        _, gt = brute_force.knn(res, X, X, 8)
        assert _recall(i, gt) >= 0.5   # approximate by construction

    def test_unsupported_metric_rejected(self, res):
        with pytest.raises(Exception):
            ball_cover.BallCoverIndex(
                res, np.zeros((10, 2), np.float32),
                metric=DistanceType.CosineExpanded)

    def test_haversine_dim_check(self, res):
        with pytest.raises(Exception):
            ball_cover.BallCoverIndex(
                res, np.zeros((10, 3), np.float32),
                metric=DistanceType.Haversine)
