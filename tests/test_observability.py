"""raft_tpu.observability — registry, stages, exporters, build reports.

Marker-free (tier-1): everything here runs on tiny inputs.  The key
contract under test: collection is OFF by default and the instrumented
hot paths add NO fences (``block_until_ready``) while it is off.
"""

import importlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import observability as obs

# the package re-exports a `stage` FUNCTION that shadows the submodule
# attribute — import the module itself for monkeypatching
stage_mod = importlib.import_module("raft_tpu.observability.stage")


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


class TestRegistry:
    def test_counter_gauge_timer(self):
        reg = obs.MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(4)
        reg.gauge("g").set(2.5)
        reg.timer("t").record(0.5)
        reg.timer("t").record(1.5)
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 5
        assert snap["gauges"]["g"] == 2.5
        t = snap["timers"]["t"]
        assert t["count"] == 2
        assert t["total_s"] == pytest.approx(2.0)
        assert t["min_s"] == pytest.approx(0.5)
        assert t["max_s"] == pytest.approx(1.5)
        assert t["last_s"] == pytest.approx(1.5)

    def test_reset(self):
        reg = obs.MetricsRegistry()
        reg.counter("c").inc()
        reg.reset()
        snap = reg.snapshot()
        assert snap["counters"] == {} and snap["gauges"] == {}
        assert snap["timers"] == {} and snap["histograms"] == {}
        assert snap["window"]["counters"] == {}
        assert snap["window"]["histograms"] == {}

    def test_get_or_create_identity(self):
        reg = obs.MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.timer("y") is reg.timer("y")


class TestExport:
    def _populated(self):
        reg = obs.MetricsRegistry()
        reg.counter("comms.allreduce.calls").inc(3)
        reg.gauge("cap").set(7.0)
        reg.timer("cagra.build.scan").record(0.25)
        return reg

    def test_json_roundtrip(self):
        snap = self._populated().snapshot()
        back = json.loads(obs.to_json(snap))
        assert back == snap

    def test_prometheus_text(self):
        # registry -> JSON -> Prometheus round-trip: the Prometheus
        # text must be derivable from the JSON-serialized snapshot
        snap = json.loads(obs.to_json(self._populated().snapshot()))
        text = obs.to_prometheus(snap)
        assert "raft_tpu_comms_allreduce_calls_total 3" in text
        assert "raft_tpu_cap 7.0" in text
        assert "raft_tpu_cagra_build_scan_seconds_count 1" in text
        assert "raft_tpu_cagra_build_scan_seconds_total 0.25" in text
        # names sanitized: no dots survive
        for line in text.splitlines():
            if not line.startswith("#"):
                assert "." not in line.split(" ")[0]

    def test_prometheus_global_default(self):
        with obs.collecting():
            obs.registry().counter("k").inc()
        assert "raft_tpu_k_total 1" in obs.to_prometheus()


class TestStage:
    def test_disabled_is_noop(self):
        assert not obs.enabled()
        with obs.stage("nothing") as st:
            st.fence(jnp.zeros(3))
        assert obs.snapshot()["timers"] == {}

    def test_disabled_shares_singleton(self):
        with obs.stage("a") as h1:
            pass
        with obs.stage("b") as h2:
            pass
        assert h1 is h2                      # shared no-op handle

    def test_enabled_records(self):
        with obs.collecting():
            with obs.stage("work") as st:
                x = jnp.arange(8) * 2
                st.fence(x)
        t = obs.snapshot()["timers"]["work"]
        assert t["count"] == 1
        assert t["total_s"] > 0

    def test_fence_skips_tracers(self):
        @jax.jit
        def f(x):
            obs.fence(x)                     # tracer: must not block
            return x + 1
        with obs.collecting():
            np.testing.assert_array_equal(np.asarray(f(jnp.ones(2))),
                                          [2.0, 2.0])

    def test_collecting_restores_state(self):
        assert not obs.enabled()
        with obs.collecting():
            assert obs.enabled()
        assert not obs.enabled()


class TestNoFencesWhenDisabled:
    """Acceptance criterion: with collection disabled (the default), an
    instrumented CAGRA search performs NO block_until_ready fences."""

    def _index(self, res):
        from raft_tpu.neighbors import cagra
        rng = np.random.default_rng(0)
        db = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
        return cagra, cagra.build(
            res, cagra.IndexParams(graph_degree=8,
                                   intermediate_graph_degree=16), db)

    def test_search_fence_free_when_disabled(self, res, monkeypatch):
        cagra, index = self._index(res)
        q = jnp.asarray(np.random.default_rng(1).normal(
            size=(4, 16)).astype(np.float32))
        sp = cagra.SearchParams(itopk_size=16)
        cagra.search(res, sp, index, q, 4)   # warm (walk-cache attach)
        calls = []
        monkeypatch.setattr(stage_mod, "_block_until_ready",
                            lambda x: calls.append(x) or x)
        assert not obs.enabled()
        cagra.search(res, sp, index, q, 4)
        assert calls == []
        with obs.collecting():
            cagra.search(res, sp, index, q, 4)
        assert len(calls) > 0

    def test_build_report_attached(self, res):
        with obs.collecting():
            cagra, index = self._index(res)
        rep = obs.build_report(index)
        assert rep is not None
        assert rep["name"] == "cagra.build"
        assert rep["total_s"] > 0
        assert "cagra.build.prune" in rep["stages"]
        assert "cagra.build.knn_exact" in rep["stages"]  # n=256 exact path
        assert rep["stages"]["cagra.build.prune"]["count"] == 1

    def test_build_report_absent_when_disabled(self, res):
        _, index = self._index(res)
        assert obs.build_report(index) is None


class TestCompileEvents:
    def test_compile_counter(self):
        # the persistent compile cache can serve the executable without
        # a backend_compile event — force real compiles for this test
        prev = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        try:
            with obs.collecting():
                @jax.jit
                def f(x):
                    return (x * 3 + 1).sum()
                f(jnp.arange(13.0)).block_until_ready()
            snap = obs.snapshot()
            assert snap["counters"].get("xla.compiles", 0) >= 1
            assert any(n.startswith("xla.") for n in snap["timers"])
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)


class TestInstrumentedModules:
    def test_kmeans_stage_and_counters(self, res):
        from raft_tpu.cluster import kmeans
        from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
        X = jnp.asarray(np.random.default_rng(2).normal(
            size=(512, 8)).astype(np.float32))
        p = KMeansParams(n_clusters=8, max_iter=5, n_init=1,
                         init=InitMethod.Random, tol=0.0)
        with obs.collecting():
            kmeans.fit(res, p, X)
        snap = obs.snapshot()
        assert snap["timers"]["kmeans.fit"]["count"] == 1
        assert snap["counters"]["kmeans.iterations"] >= 1

    def test_comms_record_helper(self):
        comms_mod = importlib.import_module("raft_tpu.comms.comms")
        comms_mod._record_collective("allreduce", jnp.ones(4, jnp.float32))
        assert obs.snapshot()["counters"] == {}      # disabled: no-op
        with obs.collecting():
            comms_mod._record_collective("allreduce",
                                         jnp.ones(4, jnp.float32))
        snap = obs.snapshot()
        assert snap["counters"]["comms.allreduce.calls"] == 1
        assert snap["counters"]["comms.allreduce.bytes"] == 16

    def test_ivf_stages(self, res):
        from raft_tpu.neighbors import ivf_flat
        rng = np.random.default_rng(3)
        db = jnp.asarray(rng.normal(size=(512, 16)).astype(np.float32))
        q = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
        with obs.collecting():
            index = ivf_flat.build(
                res, ivf_flat.IndexParams(n_lists=8), db)
            ivf_flat.search(res, ivf_flat.SearchParams(n_probes=4),
                            index, q, 4)
        snap = obs.snapshot()
        assert snap["timers"]["ivf_flat.build.kmeans"]["count"] == 1
        assert snap["timers"]["ivf_flat.search.coarse"]["count"] == 1
        assert snap["timers"]["ivf_flat.search.scan"]["count"] == 1
        rep = obs.build_report(index)
        assert rep is not None and rep["name"] == "ivf_flat.build"
