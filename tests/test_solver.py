"""LAP solver tests.

Mirrors the reference's Hungarian-vs-known-optimum strategy
(cpp/test/linalg/... has no LAP test; the contract here is VERDICT-driven:
match ``scipy.optimize.linear_sum_assignment`` costs on random matrices).
"""

import jax
import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from raft_tpu import solver


def _assert_valid_assignment(row, col, n):
    row = np.asarray(row)
    col = np.asarray(col)
    assert sorted(row.tolist()) == list(range(n))   # a permutation
    # col_assignment is the inverse permutation
    assert np.array_equal(col[row], np.arange(n))


class TestSolve:
    @pytest.mark.parametrize("n", [1, 2, 16, 100])
    def test_matches_scipy_small(self, res, n):
        rng = np.random.default_rng(n)
        cost = rng.random((n, n)).astype(np.float32)
        sol = solver.solve(res, cost)
        _assert_valid_assignment(sol.row_assignment, sol.col_assignment, n)
        ri, ci = linear_sum_assignment(cost)
        expected = cost[ri, ci].sum()
        got = cost[np.arange(n), np.asarray(sol.row_assignment)].sum()
        np.testing.assert_allclose(got, expected, rtol=1e-5)

    def test_matches_scipy_200(self, res):
        rng = np.random.default_rng(7)
        cost = rng.random((200, 200)).astype(np.float32)
        sol = solver.solve(res, cost)
        _assert_valid_assignment(sol.row_assignment, sol.col_assignment, 200)
        ri, ci = linear_sum_assignment(cost)
        np.testing.assert_allclose(
            float(sol.obj_primal), cost[ri, ci].sum(), rtol=1e-5)

    @pytest.mark.slow
    def test_matches_scipy_500(self, res):
        rng = np.random.default_rng(7)
        cost = rng.random((500, 500)).astype(np.float32)
        sol = solver.solve(res, cost)
        _assert_valid_assignment(sol.row_assignment, sol.col_assignment, 500)
        ri, ci = linear_sum_assignment(cost)
        np.testing.assert_allclose(
            float(sol.obj_primal), cost[ri, ci].sum(), rtol=1e-5)

    @pytest.mark.slow
    @pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="auction rounds are (n,n) top-2 passes — minutes on an "
               "accelerator, hours on the CPU test backend at n=2048; "
               "validated on a real chip (see PERFORMANCE.md)")
    def test_matches_scipy_2048(self, res):
        rng = np.random.default_rng(11)
        cost = rng.random((2048, 2048)).astype(np.float32)
        sol = solver.solve(res, cost)
        _assert_valid_assignment(sol.row_assignment, sol.col_assignment, 2048)
        ri, ci = linear_sum_assignment(cost)
        np.testing.assert_allclose(
            float(sol.obj_primal), cost[ri, ci].sum(), rtol=1e-5)

    def test_integer_costs_exact(self, res):
        rng = np.random.default_rng(3)
        cost = rng.integers(0, 1000, size=(64, 64)).astype(np.float32)
        sol = solver.solve(res, cost)
        ri, ci = linear_sum_assignment(cost)
        assert float(sol.obj_primal) == pytest.approx(cost[ri, ci].sum())

    def test_maximize(self, res):
        rng = np.random.default_rng(5)
        cost = rng.random((32, 32)).astype(np.float32)
        sol = solver.solve(res, cost, maximize=True)
        ri, ci = linear_sum_assignment(cost, maximize=True)
        np.testing.assert_allclose(
            float(sol.obj_primal), cost[ri, ci].sum(), rtol=1e-5)

    def test_batched(self, res):
        rng = np.random.default_rng(9)
        cost = rng.random((4, 48, 48)).astype(np.float32)
        sol = solver.solve(res, cost)
        for b in range(4):
            ri, ci = linear_sum_assignment(cost[b])
            np.testing.assert_allclose(
                float(sol.obj_primal[b]), cost[b][ri, ci].sum(), rtol=1e-5)

    def test_duals_feasible_and_tight(self, res):
        """u_i + v_j <= c_ij (feasible) and dual ~ primal (strong duality)."""
        rng = np.random.default_rng(13)
        cost = rng.random((64, 64)).astype(np.float32)
        sol = solver.solve(res, cost)
        u = np.asarray(sol.row_duals)[:, None]
        v = np.asarray(sol.col_duals)[None, :]
        assert np.all(u + v <= cost + 1e-5)
        np.testing.assert_allclose(
            float(sol.obj_dual), float(sol.obj_primal), rtol=1e-4)


class TestClassSurface:
    def test_class_solve_and_getters(self, res):
        rng = np.random.default_rng(21)
        cost = rng.random((2, 32, 32)).astype(np.float32)
        lap = solver.LinearAssignmentProblem(res, size=32, batchsize=2)
        row, col = lap.solve(cost)
        for b in range(2):
            _assert_valid_assignment(row[b], col[b], 32)
            ri, ci = linear_sum_assignment(cost[b])
            np.testing.assert_allclose(
                float(lap.primal_objective_value(b)),
                cost[b][ri, ci].sum(), rtol=1e-5)
            assert lap.row_dual_vector(b).shape == (32,)
            assert lap.col_dual_vector(b).shape == (32,)
            np.testing.assert_allclose(float(lap.dual_objective_value(b)),
                                       float(lap.primal_objective_value(b)),
                                       rtol=1e-4)

    def test_shape_validation(self, res):
        lap = solver.LinearAssignmentProblem(res, size=8, batchsize=1)
        with pytest.raises(Exception):
            lap.solve(np.zeros((4, 4), np.float32))
