"""Core layer tests (reference test analogue: cpp/test/core/)."""

import io
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import DeviceResources, Resources
from raft_tpu.core import (
    LogicError,
    check_matrix,
    check_vector,
    deserialize_mdspan,
    deserialize_scalar,
    expects,
    interruptible,
    InterruptedException,
    make_device_matrix,
    resource_type,
    serialize_mdspan,
    serialize_scalar,
)
from raft_tpu.core import logger as rlog


class TestResources:
    def test_lazy_factory(self):
        r = Resources()
        calls = []
        r.add_resource_factory("thing", lambda: calls.append(1) or "made")
        assert not calls
        assert r.get_resource("thing") == "made"
        assert r.get_resource("thing") == "made"
        assert len(calls) == 1

    def test_missing_resource_raises(self):
        with pytest.raises(LogicError):
            Resources().get_resource("nope")

    def test_copy_shares_factories_not_instances(self):
        r = Resources()
        r.add_resource_factory("x", lambda: object())
        a = r.get_resource("x")
        r2 = Resources(r)
        assert r2.get_resource("x") is not a

    def test_device_resources_defaults(self):
        res = DeviceResources(seed=7)
        assert res.device in jax.devices()
        assert res.mesh.axis_names == ("data",)
        assert res.workspace_bytes > 0

    def test_prng_chain_deterministic(self):
        a = DeviceResources(seed=3)
        b = DeviceResources(seed=3)
        k1, k2 = a.next_key(), a.next_key()
        assert not jnp.array_equal(jax.random.key_data(k1),
                                   jax.random.key_data(k2))
        assert jnp.array_equal(jax.random.key_data(b.next_key()),
                               jax.random.key_data(k1))

    def test_comms_slot(self):
        res = DeviceResources()
        assert not res.comms_initialized()
        res.set_comms("comm")
        assert res.get_comms() == "comm"


class TestContracts:
    def test_check_matrix(self):
        x = jnp.zeros((3, 4))
        assert check_matrix(x, rows=3, cols=4) is x
        with pytest.raises(LogicError):
            check_matrix(jnp.zeros(3))
        with pytest.raises(LogicError):
            check_matrix(x, dtype=jnp.int32)

    def test_check_vector_ingests_numpy(self):
        v = check_vector(np.arange(5.0), size=5)
        assert isinstance(v, jax.Array)

    def test_make_device_matrix(self):
        res = DeviceResources()
        m = make_device_matrix(res, 2, 3)
        assert m.shape == (2, 3)


class TestSerialize:
    def test_mdspan_roundtrip(self):
        buf = io.BytesIO()
        arr = np.random.default_rng(0).normal(size=(5, 7)).astype(np.float32)
        serialize_mdspan(None, buf, jnp.asarray(arr))
        buf.seek(0)
        out = deserialize_mdspan(None, buf)
        np.testing.assert_array_equal(out, arr)

    def test_scalar_roundtrip(self):
        buf = io.BytesIO()
        serialize_scalar(None, buf, np.int64(42))
        serialize_scalar(None, buf, np.float32(1.5))
        buf.seek(0)
        assert deserialize_scalar(None, buf) == 42
        assert deserialize_scalar(None, buf) == np.float32(1.5)


class TestLogger:
    def test_callback_sink(self):
        records = []
        lg = rlog.Logger.get()
        lg.set_callback(lambda lvl, msg: records.append((lvl, msg)))
        try:
            rlog.log_info("hello %d", 5)
        finally:
            lg.set_callback(None)
        assert any("hello 5" in m for _, m in records)

    def test_level_filtering(self):
        lg = rlog.Logger.get()
        old = lg.get_level()
        lg.set_level(rlog.ERROR)
        try:
            assert not lg.should_log_for(rlog.INFO)
            assert lg.should_log_for(rlog.ERROR)
        finally:
            lg.set_level(old)


class TestInterruptible:
    def test_cancel_from_other_thread(self):
        tok = interruptible.get_token()
        t = threading.Thread(target=tok.cancel)
        t.start()
        t.join()
        with pytest.raises(InterruptedException):
            interruptible.synchronize()
        # token cleared after raise
        interruptible.synchronize()


class TestAot:
    """AOT export (core/aot.py) — the instantiation-layer analogue
    (reference: cpp/src precompiled template units; SURVEY §1)."""

    def test_export_roundtrip(self):
        from raft_tpu.core import aot

        def fn(a, b):
            return a @ b + 1.0

        x = jnp.ones((8, 16), jnp.float32)
        y = jnp.ones((16, 4), jnp.float32)
        blob = aot.export_fn(fn, (x, y))
        assert isinstance(blob, bytes) and len(blob) > 0
        g = aot.load_fn(blob)
        np.testing.assert_allclose(np.asarray(g(x, y)),
                                   np.asarray(fn(x, y)), rtol=1e-6)

    def test_ivf_pq_search_artifact(self, res):
        """Flagship deployment artifact: export at fixed shapes, reload
        in a fresh callable, identical results to the live search."""
        from raft_tpu.core import aot
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(0)
        db = jnp.asarray(rng.normal(size=(2048, 32)).astype(np.float32))
        q = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        index = ivf_pq.build(
            res, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=4), db)
        buf = aot.export_ivf_pq_search(res, index, n_probes=8, k=5,
                                       batch=16)
        g = aot.load_search_fn(buf)
        d1, i1 = g(q)
        d2, i2 = ivf_pq._search_impl_recon(
            index.centers, index.list_recon, index.list_indices,
            index.rotation, q, k=5, n_probes=8, metric=index.metric)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)

    def test_ivf_pq_codes_artifact(self, res):
        """Compact-code deployment artifact: scan_mode="codes" bakes
        only the packed PQ codes (+codebooks) and round-trips against
        the live code-domain search."""
        from raft_tpu.core import aot
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(2)
        db = jnp.asarray(rng.normal(size=(2048, 32)).astype(np.float32))
        q = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        index = ivf_pq.build(
            res, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=4), db)
        buf = aot.export_ivf_pq_search(res, index, n_probes=8, k=5,
                                       batch=16, scan_mode="codes")
        g = aot.load_search_fn(buf)
        d1, i1 = g(q)
        d2, i2 = ivf_pq._search_impl(
            index.centers, index.codebooks, index.list_codes,
            index.list_indices, index.rotation, q, k=5, n_probes=8,
            metric=index.metric, codebook_kind=index.codebook_kind,
            lut_dtype=jnp.float32, pq_bits=index.pq_bits)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)
        # the codes artifact must be materially smaller than the recon
        # one: it carries 1 byte/subspace/row instead of 2 bytes/dim/row
        recon_buf = aot.export_ivf_pq_search(res, index, n_probes=8,
                                             k=5, batch=16)
        assert len(buf.getvalue()) < len(recon_buf.getvalue())

    def test_ivf_flat_search_artifact(self, res):
        from raft_tpu.core import aot
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(3)
        db = jnp.asarray(rng.normal(size=(2048, 32)).astype(np.float32))
        q = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4), db)
        buf = aot.export_ivf_flat_search(res, index, n_probes=8, k=5,
                                         batch=16)
        g = aot.load_search_fn(buf)
        d1, i1 = g(q)
        d2, i2 = ivf_flat._search_impl(
            index.centers, index.list_data, index.list_indices, q, k=5,
            n_probes=8, metric=index.metric)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)

    def test_brute_force_knn_artifact(self, res):
        from raft_tpu.core import aot
        from raft_tpu.neighbors import brute_force

        rng = np.random.default_rng(4)
        db = jnp.asarray(rng.normal(size=(1024, 32)).astype(np.float32))
        q = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        buf = aot.export_brute_force_knn(res, db, k=7, batch=16)
        g = aot.load_search_fn(buf)
        d1, i1 = g(q)
        d2, i2 = brute_force.knn(res, db, q, 7)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)

    def test_cagra_search_artifact(self, res):
        """CAGRA walk deployment artifact: the walk table + entry set +
        exported walk program reload into a callable that matches the
        live packed-walk search exactly."""
        from raft_tpu.core import aot
        from raft_tpu.neighbors import cagra

        rng = np.random.default_rng(1)
        lat = rng.normal(size=(2048 + 16, 8)).astype(np.float32)
        A = rng.normal(size=(8, 32)).astype(np.float32)
        X = jnp.asarray(lat @ A)
        db, q = X[:2048], X[2048:]
        index = cagra.build(
            res, cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16), db)
        buf = aot.export_cagra_search(res, index, k=5, batch=16,
                                      itopk=32)
        g = aot.load_search_fn(buf)
        d1, i1 = g(q)
        assert np.asarray(i1).shape == (16, 5)
        # live search at the same operating point agrees
        d2, i2 = cagra.search(
            res, cagra.SearchParams(itopk_size=32, search_width=1),
            index, q, 5)
        same = np.mean(np.asarray(i1) == np.asarray(i2))
        assert same == 1.0, same
