"""Per-request tracing, the flight recorder, and windowed telemetry.

Pins the PR 11 observability contracts: retroactive span recording from
timestamps the serving path already takes, the per-thread ambient
recorder stack, ``stage()`` timers mirroring onto the ambient trace,
the lock-free flight ring (always-on anomaly events, Chrome-trace
dumps, env-gated auto-dump), rotating-window counter/histogram views,
and the disabled-path cost contract (no lock, no fence, no allocation
when collection is off).
"""

import importlib
import json
import os
import threading

import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu.observability import flight, trace

# the package __init__ rebinds the ``registry`` attribute to the accessor
# function, so the module itself must come through importlib
registry_mod = importlib.import_module("raft_tpu.observability.registry")
stage_mod = importlib.import_module("raft_tpu.observability.stage")


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    trace.disable_tracing()
    flight.clear()
    yield
    obs.disable()
    obs.reset()
    trace.disable_tracing()
    flight.clear()


# ---------------------------------------------------------------------------
# span / recorder model


class TestTraceModel:
    def test_retroactive_spans_from_timestamps(self):
        rec = trace.SpanRecorder("serving.request", t0=1.0)
        s = rec.span("serving.exec", 2.0, 2.5, rows=4)
        rec.close(3.0)
        assert s.duration == pytest.approx(0.5)
        assert s.attrs == {"rows": 4}
        assert rec.duration == pytest.approx(2.0)
        assert [x.name for x in rec.spans] == ["serving.exec"]

    def test_trace_ids_are_unique_and_increasing(self):
        a = trace.start_request()
        b = trace.start_request()
        assert a.name == "serving.request"
        assert b.trace_id > a.trace_id

    def test_adopt_shares_spans_and_merges_attrs(self):
        batch = trace.SpanRecorder("serving.batch")
        shared = batch.span("serving.exec", 0.0, 1.0)
        batch.annotate("bucket", 16)
        rt = trace.start_request()
        rt.annotate("tenant", "t0")
        rt.adopt(batch)
        assert shared in rt.spans          # shared, not copied
        assert rt.attrs == {"tenant": "t0", "bucket": 16}

    def test_gate_and_ambient_stack(self):
        rec = trace.SpanRecorder("serving.request")
        # tracing off: current() is None even with a pushed recorder
        trace.push_active(rec)
        assert trace.current() is None
        trace.pop_active()
        trace.enable_tracing()
        assert trace.current() is None
        with trace.activating(rec):
            assert trace.current() is rec
            trace.annotate_current("k", 5)
        assert trace.current() is None
        assert rec.attrs == {"k": 5}

    def test_ambient_stack_is_per_thread(self):
        trace.enable_tracing()
        rec = trace.SpanRecorder("serving.request")
        seen = []
        with trace.activating(rec):
            t = threading.Thread(target=lambda: seen.append(trace.current()))
            t.start()
            t.join()
        assert seen == [None]

    def test_stage_hook_mirrors_stage_timers_as_spans(self):
        rec = trace.SpanRecorder("serving.request")
        with obs.collecting(), trace.tracing_scope(), trace.activating(rec):
            with obs.stage("tracetest.phase"):
                pass
        assert [s.name for s in rec.spans] == ["tracetest.phase"]
        assert rec.spans[0].duration >= 0.0

    def test_tracing_scope_restores_previous_state(self):
        assert not trace.tracing()
        with trace.tracing_scope():
            assert trace.tracing()
            with trace.tracing_scope():
                assert trace.tracing()
            assert trace.tracing()        # outer scope still active
        assert not trace.tracing()


# ---------------------------------------------------------------------------
# flight recorder


class TestFlightRecorder:
    def test_events_always_on(self):
        # neither metrics collection nor tracing is enabled here
        flight.record_event("serving.shed.deadline", tenant="t0", rows=4)
        evs = flight.events("serving.shed.deadline")
        assert len(evs) == 1
        assert evs[0]["attrs"] == {"tenant": "t0", "rows": 4}
        assert evs[0]["trace_id"] is None

    def test_ring_keeps_last_capacity_records(self):
        fr = flight.FlightRecorder(capacity=4)
        for j in range(10):
            fr.record_event("serving.shed.quota", j=j)
        evs = fr.events()
        assert [e["attrs"]["j"] for e in evs] == [6, 7, 8, 9]

    def test_trace_records_and_event_filter(self):
        rec = trace.start_request()
        rec.span("serving.exec", 0.0, 1.0)
        flight.record_trace(rec.close())
        flight.record_event("serving.generation_swap", generation=2)
        flight.record_event("serving.shed.quota", tenant="t")
        assert [t.trace_id for t in flight.traces()] == [rec.trace_id]
        assert len(flight.events()) == 2
        assert len(flight.events("serving.generation_swap")) == 1

    def test_clear(self):
        flight.record_event("serving.shed.quota")
        flight.clear()
        assert flight.events() == [] and flight.traces() == []

    def test_dump_chrome_trace_format(self, tmp_path):
        rec = trace.start_request()
        rec.span("serving.exec", rec.t0, rec.t0 + 0.25)
        # lazy array attribute: only dump() may materialize it
        rec.annotate("distributed.shard_status", np.asarray([1, 1, 0]))
        flight.record_trace(rec.close())
        flight.record_event("distributed.degraded_search",
                            trace_id=rec.trace_id, failed=[2])
        path = tmp_path / "flight.json"
        doc = json.loads(flight.dump(str(path), reason="unit"))
        assert path.exists()
        assert doc["otherData"]["reason"] == "unit"
        complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        instant = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        names = {e["name"] for e in complete}
        assert {"serving.request", "serving.exec"} <= names
        root = next(e for e in complete if e["name"] == "serving.request")
        assert root["tid"] == rec.trace_id
        assert root["args"]["distributed.shard_status"] == [1, 1, 0]
        exec_ev = next(e for e in complete if e["name"] == "serving.exec")
        assert exec_ev["dur"] == pytest.approx(0.25 * 1e6)
        assert instant[0]["name"] == "distributed.degraded_search"
        assert instant[0]["args"] == {"failed": [2]}

    def test_maybe_auto_dump_env_gated(self, tmp_path, monkeypatch):
        monkeypatch.delenv(flight.DUMP_ENV, raising=False)
        assert flight.maybe_auto_dump("x") is None
        out = tmp_path / "auto.json"
        monkeypatch.setenv(flight.DUMP_ENV, str(out))
        flight.record_event("serving.batch_error", error="boom")
        assert flight.maybe_auto_dump("unit-test") == str(out)
        doc = json.loads(out.read_text())
        assert doc["otherData"]["reason"] == "unit-test"
        # an unwritable path must not raise (the recorder never masks
        # the original serving error)
        monkeypatch.setenv(flight.DUMP_ENV,
                           str(tmp_path / "no" / "such" / "dir" / "f.json"))
        assert flight.maybe_auto_dump("x") is None

    def test_maybe_auto_dump_directory_rotates(self, tmp_path, monkeypatch):
        d = tmp_path / "dumps"
        # trailing separator selects directory mode before the dir exists
        monkeypatch.setenv(flight.DUMP_ENV, str(d) + os.sep)
        monkeypatch.setenv(flight.DUMP_KEEP_ENV, "3")
        flight.record_event("serving.batch_error", error="boom")
        paths = [flight.maybe_auto_dump(f"r{j}") for j in range(5)]
        assert paths[0].endswith("flight-000000.json")
        # only the newest RAFT_TPU_FLIGHT_DUMP_KEEP dumps survive
        assert sorted(os.listdir(d)) == [
            "flight-000002.json", "flight-000003.json", "flight-000004.json"]
        doc = json.loads((d / "flight-000004.json").read_text())
        assert doc["otherData"]["reason"] == "r4"
        # an existing directory without the trailing separator also rotates
        monkeypatch.setenv(flight.DUMP_ENV, str(d))
        p = flight.maybe_auto_dump("r5")
        assert p.endswith("flight-000005.json")
        assert sorted(os.listdir(d)) == [
            "flight-000003.json", "flight-000004.json", "flight-000005.json"]
        # an unparseable keep bound falls back to the default, not a raise
        monkeypatch.setenv(flight.DUMP_KEEP_ENV, "bananas")
        assert flight.maybe_auto_dump("r6").endswith("flight-000006.json")
        assert len(os.listdir(d)) == 4     # 4 <= DEFAULT_DUMP_KEEP: no prune


# ---------------------------------------------------------------------------
# windowed telemetry


@pytest.fixture
def clock(monkeypatch):
    t = {"now": 0.0}
    monkeypatch.setattr(registry_mod, "_now", lambda: t["now"])
    return t


class TestWindowedTelemetry:
    def test_counter_window_ages_out(self, clock):
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=4)
        c = reg.counter("w.c")
        clock["now"] = 0.5
        c.inc(3)
        clock["now"] = 1.5
        c.inc(2)
        assert c.windowed() == 5
        clock["now"] = 4.2          # window covers epochs 1..4: drops the 3
        assert c.windowed() == 2
        clock["now"] = 9.0
        assert c.windowed() == 0
        assert c.value == 5         # lifetime total persists

    def test_counter_slot_reuse_zeroes_stale_epoch(self, clock):
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=2)
        c = reg.counter("w.c")
        c.inc(7)                    # epoch 0, slot 0
        clock["now"] = 2.1          # epoch 2 reuses slot 0
        c.inc(1)
        assert c.windowed() == 1    # the stale 7 must not leak in

    def test_histogram_window_quantiles(self, clock):
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=4)
        h = reg.histogram("w.h")
        for v in (0.001, 0.002, 0.004):
            h.observe(v)
        clock["now"] = 1.5
        h.observe(0.064)
        w = h.windowed_dict()
        assert w["count"] == 4
        assert w["sum"] == pytest.approx(0.071)
        assert w["max"] == pytest.approx(0.064)
        assert 0.001 <= w["p50"] <= 0.004 < w["p99"] <= 0.064
        clock["now"] = 4.8          # window is epochs 1..4: drops epoch 0
        w = h.windowed_dict()
        assert w["count"] == 1
        assert w["p50"] == pytest.approx(0.064, rel=0.5)
        assert h.count == 4         # lifetime view unchanged

    def test_snapshot_window_section(self, clock):
        reg = registry_mod.MetricsRegistry(window_interval_s=2.0,
                                           window_slots=3)
        reg.counter("w.c").inc(4)
        reg.histogram("w.h").observe(0.01)
        snap = reg.snapshot()
        assert snap["window"]["interval_s"] == 2.0
        assert snap["window"]["span_s"] == 6.0
        assert snap["window"]["counters"] == {"w.c": 4}
        assert snap["window"]["histograms"]["w.h"]["count"] == 1

    def test_counter_backwards_clock_drops_future_slots(self, clock):
        # a clock that steps backwards (suspend/resume, test clocks) must
        # never raise, and slots stamped with a now-future epoch are
        # excluded from the sum rather than double-counted
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=4)
        c = reg.counter("w.c")
        clock["now"] = 10.0
        c.inc(3)
        clock["now"] = 1.0
        assert c.windowed() == 0        # the epoch-10 slot is in the future
        c.inc(1)                        # lands in the earlier epoch cleanly
        assert c.windowed() == 1
        clock["now"] = 10.0             # forward again: future slot intact,
        assert c.windowed() == 3        # the old epoch-1 slot aged out
        assert c.value == 4             # lifetime total saw everything

    def test_counter_jump_beyond_span_empties_window(self, clock):
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=4)
        c = reg.counter("w.c")
        c.inc(5)
        clock["now"] = 1e9              # jump far past the window span
        assert c.windowed() == 0
        assert c.value == 5

    def test_histogram_clock_jumps(self, clock):
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=4)
        h = reg.histogram("w.h")
        clock["now"] = 10.0
        h.observe(0.01)
        clock["now"] = 1.0
        assert h.windowed_dict()["count"] == 0    # future slot excluded
        h.observe(0.02)
        w = h.windowed_dict()
        assert w["count"] == 1
        assert w["max"] == pytest.approx(0.02)
        clock["now"] = 1e9
        assert h.windowed_dict()["count"] == 0
        assert h.count == 2             # lifetime view unaffected

    def test_empty_window_shape(self, clock):
        # windowed views on a never-observed metric: zeros, not NaN/None
        reg = registry_mod.MetricsRegistry(window_interval_s=1.0,
                                           window_slots=4)
        assert reg.counter("w.c").windowed() == 0
        w = reg.histogram("w.h").windowed_dict()
        assert w == {"count": 0, "sum": 0.0, "max": 0.0,
                     "p50": 0.0, "p95": 0.0, "p99": 0.0}

    def test_prometheus_exports_window_series(self):
        with obs.collecting() as reg:
            reg.counter("w.c").inc(2)
            reg.histogram("w.h").observe(0.5)
            text = obs.to_prometheus(reg.snapshot())
        assert "raft_tpu_w_c_window 2" in text
        assert "raft_tpu_w_h_window_count 1" in text
        assert "raft_tpu_w_h_window_p99" in text


# ---------------------------------------------------------------------------
# disabled-path cost (the contract the registry docstrings pin)


class _ForbiddenLock:
    """Stand-in lock that fails the test on any acquisition."""

    def __enter__(self):
        raise AssertionError("metric lock acquired while collection is off")

    __exit__ = None

    def acquire(self, *a, **k):
        raise AssertionError("metric lock acquired while collection is off")

    release = acquire


class TestDisabledPathCost:
    def test_stage_yields_shared_noop_and_never_fences(self, monkeypatch):
        def _no_fence(x):
            raise AssertionError("fence on the disabled path")

        monkeypatch.setattr(stage_mod, "_block_until_ready", _no_fence)
        with obs.stage("serving.cut") as a, obs.stage("serving.cut2") as b:
            a.fence(object())
            assert a is b is stage_mod._NOOP   # singleton: no allocation

    def test_disabled_serving_path_never_touches_metric_locks(self,
                                                              monkeypatch):
        """The gate contract: with collection off, the hot path performs
        no lock acquisition and no metric mutation — pinned by swapping
        every metric's lock for one that raises on acquire."""
        reg = registry_mod.MetricsRegistry()
        c = reg.counter("serving.admitted")
        h = reg.histogram("serving.latency.total")
        monkeypatch.setattr(c, "_lock", _ForbiddenLock())
        monkeypatch.setattr(h, "_lock", _ForbiddenLock())

        # the library's gated call-site idiom, off-path
        for _ in range(3):
            if obs.enabled():
                c.inc()
                h.observe(0.001)
            with obs.stage("serving.cut"):
                pass
        assert c.value == 0 and h.count == 0

    def test_stage_hook_is_one_flag_check_when_tracing_off(self,
                                                           monkeypatch):
        # tracing off: stage_hook must not touch thread-local state
        def _no_tls():
            raise AssertionError("ambient stack touched with tracing off")

        monkeypatch.setattr(trace, "_stack", _no_tls)
        trace.stage_hook("serving.cut", 0.001)
        assert trace.current() is None
