"""Pallas kernel tests (interpreter mode — CPU-runnable; on-chip parity is
exercised by the same assertions when a TPU backend is present)."""

import numpy as np
import pytest

from raft_tpu.ops import fused_l2_nn_pallas


class TestFusedL2NNPallas:
    @pytest.mark.parametrize("m,n,k", [(300, 700, 64), (256, 512, 128),
                                       (10, 5, 32), (1000, 33, 16)])
    def test_matches_naive(self, m, n, k):
        rng = np.random.default_rng(m + n + k)
        x = rng.random((m, k)).astype(np.float32)
        y = rng.random((n, k)).astype(np.float32)
        d, i = fused_l2_nn_pallas(x, y, interpret=True)
        D = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(np.asarray(d), D.min(1),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(i), D.argmin(1))

    def test_sqrt_form(self):
        rng = np.random.default_rng(0)
        x = rng.random((64, 8)).astype(np.float32)
        y = rng.random((96, 8)).astype(np.float32)
        d, i = fused_l2_nn_pallas(x, y, sqrt=True, interpret=True)
        D = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(np.asarray(d), D.min(1),
                                   rtol=1e-4, atol=1e-4)

    def test_dispatch_via_fused_l2_nn(self):
        """fused_l2_nn(use_pallas=True) must agree with the XLA path —
        off-TPU the dispatch auto-selects the Pallas interpreter, on a TPU
        backend these same assertions check the compiled kernel."""
        from raft_tpu.distance import fused_l2_nn
        rng = np.random.default_rng(1)
        x = rng.random((128, 32)).astype(np.float32)
        y = rng.random((256, 32)).astype(np.float32)
        d_x, i_x = fused_l2_nn(x, y)
        d_p, i_p = fused_l2_nn(x, y, use_pallas=True)
        np.testing.assert_allclose(np.asarray(d_x), np.asarray(d_p),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(i_x), np.asarray(i_p))

    def test_precision_policy_not_stale(self):
        """Regression: the precision policy keys the jit cache — a call
        under a changed matmul_precision() must not reuse a stale trace."""
        import jax
        from raft_tpu.utils.precision import matmul_precision
        rng = np.random.default_rng(2)
        x = rng.random((64, 16)).astype(np.float32)
        y = rng.random((32, 16)).astype(np.float32)
        d1, _ = fused_l2_nn_pallas(x, y, interpret=True)
        with matmul_precision("default"):
            d2, _ = fused_l2_nn_pallas(x, y, interpret=True)
        with matmul_precision("highest"):
            d3, _ = fused_l2_nn_pallas(x, y, interpret=True)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d3))
