"""Pallas kernel tests (interpreter mode — CPU-runnable; on-chip parity is
exercised by the same assertions when a TPU backend is present)."""

import numpy as np
import pytest

from raft_tpu.ops import fused_l2_nn_pallas


class TestFusedL2NNPallas:
    @pytest.mark.parametrize("m,n,k", [(300, 700, 64), (256, 512, 128),
                                       (10, 5, 32), (1000, 33, 16)])
    def test_matches_naive(self, m, n, k):
        rng = np.random.default_rng(m + n + k)
        x = rng.random((m, k)).astype(np.float32)
        y = rng.random((n, k)).astype(np.float32)
        d, i = fused_l2_nn_pallas(x, y, interpret=True)
        D = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(np.asarray(d), D.min(1),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(i), D.argmin(1))

    def test_sqrt_form(self):
        rng = np.random.default_rng(0)
        x = rng.random((64, 8)).astype(np.float32)
        y = rng.random((96, 8)).astype(np.float32)
        d, i = fused_l2_nn_pallas(x, y, sqrt=True, interpret=True)
        D = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(np.asarray(d), D.min(1),
                                   rtol=1e-4, atol=1e-4)

    def test_dispatch_via_fused_l2_nn(self):
        """fused_l2_nn(use_pallas=True) must agree with the XLA path —
        off-TPU the dispatch auto-selects the Pallas interpreter, on a TPU
        backend these same assertions check the compiled kernel."""
        from raft_tpu.distance import fused_l2_nn
        rng = np.random.default_rng(1)
        x = rng.random((128, 32)).astype(np.float32)
        y = rng.random((256, 32)).astype(np.float32)
        d_x, i_x = fused_l2_nn(x, y)
        d_p, i_p = fused_l2_nn(x, y, use_pallas=True)
        np.testing.assert_allclose(np.asarray(d_x), np.asarray(d_p),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(i_x), np.asarray(i_p))

    def test_kmeans_fused_assign_update_matches_reference(self):
        """Fused assignment+update pass (interpret mode) vs the plain
        argmin + segment-sum formulation, including row/cluster/dim
        padding and zero-weight rows."""
        import jax.numpy as jnp

        from raft_tpu.ops.kmeans_update_pallas import fused_assign_update

        rng = np.random.default_rng(7)
        n, dim, k = 300, 50, 37
        x = rng.normal(size=(n, dim)).astype(np.float32)
        w = rng.random(n).astype(np.float32)
        w[::11] = 0.0
        c = rng.normal(size=(k, dim)).astype(np.float32)

        sums, counts, dmin = fused_assign_update(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(c), tile=128,
            interpret=True)

        d = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
        labels = d.argmin(1)
        ref_sums = np.zeros((k, dim), np.float32)
        ref_counts = np.zeros(k, np.float32)
        np.add.at(ref_sums, labels, x * w[:, None])
        np.add.at(ref_counts, labels, w)
        # bf16 MXU passes: ~1e-3 relative on sums
        np.testing.assert_allclose(np.asarray(sums), ref_sums,
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(np.asarray(counts), ref_counts,
                                   rtol=1e-5, atol=1e-5)
        # dmin + ||x||^2 must equal the true min squared distance
        np.testing.assert_allclose(
            np.asarray(dmin) + (x * x).sum(-1), d.min(1),
            rtol=2e-2, atol=2e-2)

    def test_kmeans_fused_lloyd_matches_xla_lloyd(self):
        """Fused Lloyd vs the XLA path: bit-equal first step on
        bf16-representable inputs, and equal clustering quality
        (inertia) after a full run — trajectories may legitimately
        diverge on boundary points once centroids stop being
        bf16-representable (means), so element-wise centroid equality
        at iteration 20 is NOT the contract."""
        import jax.numpy as jnp

        from raft_tpu.cluster.kmeans import _lloyd
        from raft_tpu.ops.kmeans_update_pallas import fused_assign_update

        rng = np.random.default_rng(3)
        n, dim, k = 512, 32, 8
        centers = rng.normal(size=(k, dim)).astype(np.float32) * 8
        x = (centers[rng.integers(0, k, n)]
             + rng.normal(size=(n, dim)).astype(np.float32))
        # bf16-representable inputs: the kernel's bf16 rounding of x and
        # c0 is then the identity, so step 1 must agree exactly
        x = np.asarray(jnp.asarray(x).astype(jnp.bfloat16).astype(
            jnp.float32))
        c0 = x[:k].copy()
        w = np.ones(n, np.float32)

        args = (jnp.asarray(x), jnp.asarray(c0), jnp.asarray(w),
                jnp.float32(1e-6), k, 20, 1)        # L2Expanded
        c_ref, _, _, _ = _lloyd(*args, use_fused=False)

        c_cur = jnp.asarray(c0)
        for it in range(20):
            sums, counts, _ = fused_assign_update(
                jnp.asarray(x), jnp.asarray(w), c_cur, tile=128,
                interpret=True)
            means = sums / jnp.maximum(counts, 1.0)[:, None]
            c_cur = jnp.where((counts > 0)[:, None], means, c_cur)
            if it == 0:
                from raft_tpu.cluster.kmeans import (
                    min_cluster_and_distance, update_centroids)
                lab, _ = min_cluster_and_distance(jnp.asarray(x),
                                                  jnp.asarray(c0), metric=1)
                c1, _ = update_centroids(jnp.asarray(x), lab, k,
                                         sample_weight=jnp.asarray(w),
                                         old_centroids=jnp.asarray(c0))
                np.testing.assert_allclose(np.asarray(c_cur),
                                           np.asarray(c1),
                                           rtol=1e-5, atol=1e-5)

        # clustering quality must match: same inertia within bf16 noise
        d_ref = ((x[:, None, :] - np.asarray(c_ref)[None]) ** 2).sum(-1)
        d_fus = ((x[:, None, :] - np.asarray(c_cur)[None]) ** 2).sum(-1)
        np.testing.assert_allclose(d_fus.min(1).sum(), d_ref.min(1).sum(),
                                   rtol=1e-2)

    def test_precision_policy_not_stale(self):
        """Regression: the precision policy keys the jit cache — a call
        under a changed matmul_precision() must not reuse a stale trace."""
        import jax
        from raft_tpu.utils.precision import matmul_precision
        rng = np.random.default_rng(2)
        x = rng.random((64, 16)).astype(np.float32)
        y = rng.random((32, 16)).astype(np.float32)
        d1, _ = fused_l2_nn_pallas(x, y, interpret=True)
        with matmul_precision("default"):
            d2, _ = fused_l2_nn_pallas(x, y, interpret=True)
        with matmul_precision("highest"):
            d3, _ = fused_l2_nn_pallas(x, y, interpret=True)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d3))
