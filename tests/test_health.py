"""Shard health lifecycle state machine (PR 17) — pure-Python unit
tests: strike weights, hysteresis, dwell pinning, flap absorption, the
readmission guards and the paired flight-event + counter signal on every
transition.  The integration half (tracker-driven failover, catch-up,
canary-gated readmit on a live mesh) lives in
``tests/test_distributed.py::TestReplicatedRouted``.
"""

import pytest

from raft_tpu import observability as obs
from raft_tpu.core.error import RaftError
from raft_tpu.distributed import health
from raft_tpu.distributed.health import (
    CATCHING_UP,
    FAILED,
    HEALTHY,
    SUSPECT,
    HealthConfig,
    HealthTracker,
)
from raft_tpu.observability import flight
from raft_tpu.resilience import FaultPlan, faults


class _Clock:
    """Injected monotonic clock — tests drive dwell synthetically."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _tracker(n=4, **kw):
    clock = _Clock()
    return HealthTracker(n, HealthConfig(**kw), clock=clock), clock


class TestConfig:
    def test_defaults_validate(self):
        cfg = HealthConfig()
        assert cfg.validate() is cfg

    @pytest.mark.parametrize("kw", [dict(suspect_after=0),
                                    dict(fail_after=0),
                                    dict(ok_to_clear=0),
                                    dict(dwell_s=-1.0)])
    def test_rejects_bad_knobs(self, kw):
        with pytest.raises(RaftError):
            HealthConfig(**kw).validate()

    def test_tracker_rejects_empty(self):
        with pytest.raises(RaftError):
            HealthTracker(0)


class TestStrikes:
    def test_initial_state_all_healthy(self):
        tr, _ = _tracker()
        assert tr.states() == (HEALTHY,) * 4
        assert tr.failed_shards() == ()
        assert tr.suspect_shards() == ()

    def test_straggles_are_soft_evidence(self):
        """One straggle strike is not enough at suspect_after=2; the
        second suspects.  The strike run resets on SUSPECT entry, so
        escalation to FAILED counts fresh strikes."""
        tr, _ = _tracker(suspect_after=2, fail_after=3)
        tr.note_straggle(1)
        assert tr.state(1) == HEALTHY
        tr.note_straggle(1)
        assert tr.state(1) == SUSPECT
        assert tr.suspect_shards() == (1,)
        tr.note_straggle(1)
        tr.note_straggle(1)
        assert tr.state(1) == SUSPECT  # 2 < fail_after=3
        tr.note_straggle(1)
        assert tr.state(1) == FAILED
        assert tr.failed_shards() == (1,)

    def test_timeout_is_hard_evidence(self):
        """A deadline overrun carries suspect_after weight — a healthy
        shard is SUSPECT after ONE timeout regardless of the knob."""
        tr, _ = _tracker(suspect_after=3, fail_after=3)
        tr.note_timeout(2)
        assert tr.state(2) == SUSPECT
        tr.note_timeout(2)
        assert tr.state(2) == FAILED

    def test_ok_resets_a_partial_strike_run(self):
        tr, _ = _tracker(suspect_after=2)
        tr.note_straggle(0)
        tr.note_ok(0)
        tr.note_straggle(0)
        assert tr.state(0) == HEALTHY  # run was reset, 1 < 2

    def test_flapping_evidence_is_absorbed(self):
        """The hysteresis story: alternating straggle/OK forever never
        escalates — each OK clears the run before it reaches the
        threshold.  Zero transitions recorded."""
        tr, _ = _tracker(suspect_after=2, fail_after=3)
        for _ in range(20):
            tr.note_straggle(3)
            tr.note_ok(3)
        assert tr.state(3) == HEALTHY
        assert tr.stats()["transitions"] == {}

    def test_failed_shard_absorbs_further_strikes(self):
        tr, _ = _tracker(suspect_after=1, fail_after=1)
        tr.note_timeout(0)
        tr.note_timeout(0)
        assert tr.state(0) == FAILED
        flight.clear()
        tr.note_timeout(0)
        tr.note_straggle(0)
        assert tr.state(0) == FAILED
        assert not flight.events("distributed.health.failed")


class TestClearing:
    def test_consecutive_oks_clear_suspect(self):
        tr, _ = _tracker(suspect_after=1, ok_to_clear=2)
        tr.note_timeout(1)
        assert tr.state(1) == SUSPECT
        tr.note_ok(1)
        assert tr.state(1) == SUSPECT  # 1 < ok_to_clear
        tr.note_ok(1)
        assert tr.state(1) == HEALTHY
        assert tr.stats()["transitions"] == {
            "distributed.health.suspect": 1,
            "distributed.health.recovered": 1,
        }

    def test_a_strike_resets_the_ok_run(self):
        """OKs must be CONSECUTIVE: a straggle in the middle restarts
        the count — the other half of the hysteresis."""
        tr, _ = _tracker(suspect_after=1, fail_after=5, ok_to_clear=2)
        tr.note_timeout(1)
        tr.note_ok(1)
        tr.note_straggle(1)  # resets the OK run
        tr.note_ok(1)
        assert tr.state(1) == SUSPECT
        tr.note_ok(1)
        assert tr.state(1) == HEALTHY


class TestDwell:
    def test_dwell_pins_escalation(self):
        """Strikes accrue during dwell but the transition waits for
        residency — a burst right after suspecting cannot fail the
        shard until dwell_s elapses."""
        tr, clock = _tracker(suspect_after=1, fail_after=2, dwell_s=10.0)
        tr.note_timeout(0)
        assert tr.state(0) == HEALTHY  # dwell pins HEALTHY at t=0
        clock.t = 11.0
        tr.note_timeout(0)
        assert tr.state(0) == SUSPECT  # dwell elapsed, strikes >= 1
        tr.note_timeout(0)
        tr.note_timeout(0)
        assert tr.state(0) == SUSPECT  # dwell re-pins after transition
        clock.t = 22.0
        tr.note_timeout(0)
        assert tr.state(0) == FAILED

    def test_dwell_pins_clearing(self):
        tr, clock = _tracker(suspect_after=1, ok_to_clear=1, dwell_s=5.0)
        clock.t = 10.0
        tr.note_timeout(2)
        assert tr.state(2) == SUSPECT
        clock.t = 12.0
        tr.note_ok(2)
        assert tr.state(2) == SUSPECT  # 2s residency < 5s dwell
        clock.t = 16.0
        tr.note_ok(2)
        assert tr.state(2) == HEALTHY

    def test_flap_shard_churn_is_absorbed_by_dwell(self):
        """The fault plan's flap schedule (failed / healthy every poll)
        feeding the tracker as timeout / OK evidence cannot drag a
        SUSPECT shard through fail->readmit churn: dwell pins SUSPECT
        across the whole flap window."""
        plan = FaultPlan(seed=9).flap_shard(1, period=1)
        tr, clock = _tracker(n=4, suspect_after=1, fail_after=1,
                             ok_to_clear=1, dwell_s=60.0)
        clock.t = 100.0
        tr.note_timeout(1)
        assert tr.state(1) == SUSPECT
        with plan.active():
            for step in range(10):
                clock.t = 100.0 + step  # well inside dwell
                if 1 in faults.failed_shards(4):
                    tr.note_timeout(1)
                else:
                    tr.note_ok(1)
        assert tr.state(1) == SUSPECT
        assert tr.stats()["transitions"] == {
            "distributed.health.suspect": 1}


class TestReadmissionGuards:
    def _failed(self):
        tr, clock = _tracker(suspect_after=2, fail_after=1)
        tr.note_timeout(0)  # weight = suspect_after -> SUSPECT at once
        tr.note_timeout(0)
        assert tr.state(0) == FAILED
        return tr, clock

    def test_catch_up_only_from_failed(self):
        tr, _ = self._failed()
        with pytest.raises(RaftError):
            tr.begin_catch_up(1)  # shard 1 is HEALTHY
        tr.begin_catch_up(0, generation_delta=3)
        assert tr.state(0) == CATCHING_UP
        # a catching-up shard stays OUT of the routing
        assert tr.failed_shards() == (0,)
        with pytest.raises(RaftError):
            tr.begin_catch_up(0)  # already catching up

    def test_readmit_only_from_catching_up(self):
        tr, _ = self._failed()
        with pytest.raises(RaftError):
            tr.readmit(0)  # FAILED, not CATCHING_UP
        tr.begin_catch_up(0)
        tr.readmit(0)
        assert tr.state(0) == HEALTHY
        assert tr.failed_shards() == ()
        # strike slate is clean after readmission
        tr.note_straggle(0)
        assert tr.state(0) == HEALTHY

    def test_block_readmit_returns_to_failed(self):
        tr, _ = self._failed()
        tr.begin_catch_up(0)
        tr.block_readmit(0, reason="canary")
        assert tr.state(0) == FAILED
        with pytest.raises(RaftError):
            tr.block_readmit(0)  # no longer CATCHING_UP
        # the shard can retry catch-up
        tr.begin_catch_up(0)
        tr.readmit(0)
        assert tr.state(0) == HEALTHY


class TestOverload:
    """PR 18: load evidence from the routing policy — a continuous
    score demotion that caps at SUSPECT and never enters the failed
    set (overload is not failure)."""

    def test_penalty_accrues_and_caps_at_suspect(self):
        tr, _ = _tracker(suspect_after=2)
        for _ in range(10):
            tr.note_overload(1, 4.0)
        assert tr.state(1) == SUSPECT          # never FAILED from load
        assert tr.failed_shards() == ()
        assert tr.suspect_shards() == (1,)
        assert tr.load_penalties()[1] > 0.0
        assert tr.load_penalties()[0] == 0.0

    def test_penalty_is_an_ewma_of_the_excess(self):
        tr, _ = _tracker()
        tr.note_overload(0, 3.0)
        assert tr.load_penalties()[0] == pytest.approx(0.3 * 2.0)
        tr.note_overload(0, 3.0)
        assert tr.load_penalties()[0] == pytest.approx(
            0.7 * 0.6 + 0.3 * 2.0)
        # sub-mean load clamps at zero instead of going negative
        for _ in range(20):
            tr.note_overload(0, 0.1)
        assert tr.load_penalties()[0] == 0.0

    def test_ok_decays_the_penalty(self):
        tr, _ = _tracker(suspect_after=100)
        tr.note_overload(2, 5.0)
        before = tr.load_penalties()[2]
        tr.note_ok(2)
        assert tr.load_penalties()[2] == pytest.approx(0.7 * before)

    def test_failed_shard_ignores_overload(self):
        tr, _ = _tracker(suspect_after=2, fail_after=2)
        tr.note_timeout(3)
        tr.note_timeout(3)
        assert tr.state(3) == FAILED
        tr.note_overload(3, 9.0)
        assert tr.load_penalties()[3] == 0.0   # already out of routing
        assert tr.state(3) == FAILED

    def test_suspect_event_fires_with_load_cause(self):
        flight.clear()
        tr, _ = _tracker(suspect_after=2)
        tr.note_overload(1, 4.0)
        tr.note_overload(1, 4.0)
        evs = flight.events("distributed.health.suspect")
        assert evs and evs[0]["attrs"]["cause"] == "load"

    def test_dwell_pins_load_escalation(self):
        tr, clock = _tracker(suspect_after=1, dwell_s=5.0)
        tr.note_overload(0, 4.0)
        assert tr.state(0) == HEALTHY          # dwell not elapsed
        assert tr.load_penalties()[0] > 0.0    # but the demotion lands
        clock.t = 6.0
        tr.note_overload(0, 4.0)
        assert tr.state(0) == SUSPECT

    def test_stats_expose_penalties(self):
        tr, _ = _tracker()
        tr.note_overload(1, 2.0)
        assert tr.stats()["load_penalties"][1] > 0.0


class TestPairedSignals:
    """Every transition = one flight event + the same-named counter —
    the contract graftlint's health-transition rule enforces statically
    and the chaos job's flight-trail gate reads at runtime."""

    def test_full_lifecycle_flight_trail(self):
        flight.clear()
        with obs.collecting():
            tr, _ = _tracker(suspect_after=1, fail_after=1, ok_to_clear=1)
            tr.note_timeout(2)
            tr.note_timeout(2)
            tr.begin_catch_up(2, generation_delta=1)
            tr.block_readmit(2, reason="canary")
            tr.begin_catch_up(2)
            tr.readmit(2)
            for name in ("distributed.health.suspect",
                         "distributed.health.failed",
                         "distributed.health.catch_up",
                         "distributed.health.readmit_blocked",
                         "distributed.health.readmitted"):
                evs = flight.events(name)
                assert len(evs) >= 1, name
                assert evs[0]["attrs"]["shard"] == 2
                assert obs.registry().counter(name).value >= 1, name
        # the second catch_up appears twice
        assert len(flight.events("distributed.health.catch_up")) == 2
        assert tr.stats()["transitions"]["distributed.health.catch_up"] == 2

    def test_suspect_event_carries_cause_and_strikes(self):
        flight.clear()
        tr, _ = _tracker(suspect_after=2)
        tr.note_straggle(1)
        tr.note_straggle(1)
        evs = flight.events("distributed.health.suspect")
        assert evs[0]["attrs"] == {"shard": 1, "cause": "straggle",
                                   "strikes": 2}

    def test_canary_failure_ticks_integrity_counter_with_shard(self):
        """The satellite: per-shard canary verdicts finally tick
        ``integrity.canary_failure`` with the shard id attached."""
        flight.clear()
        with obs.collecting():
            tr, _ = _tracker(suspect_after=1)
            tr.note_canary_failure(3)
            evs = flight.events("integrity.canary_failure")
            assert evs and evs[0]["attrs"]["shard"] == 3
            assert obs.registry().counter(
                "integrity.canary_failure").value == 1
        assert tr.state(3) == SUSPECT  # hard evidence

    def test_recovered_event_on_ok_clear(self):
        flight.clear()
        tr, _ = _tracker(suspect_after=1, ok_to_clear=1)
        tr.note_timeout(0)
        tr.note_ok(0)
        evs = flight.events("distributed.health.recovered")
        assert evs and evs[0]["attrs"]["shard"] == 0


class TestFaultPlanShardKills:
    """The fault-plan half of the kill matrix: lifecycle-boundary kills
    and flapping membership, without a mesh."""

    def test_kill_shard_at_fires_once_at_site(self):
        plan = FaultPlan(seed=1).kill_shard_at("distributed.scan", 5)
        with plan.active():
            assert faults.failed_shards(8) == ()
            faults.maybe_fail("distributed.route")  # wrong site: no-op
            assert faults.failed_shards(8) == ()
            faults.maybe_fail("distributed.scan")
            assert faults.failed_shards(8) == (5,)
            faults.maybe_fail("distributed.scan")  # times=1: no re-fire
            assert faults.failed_shards(8) == (5,)

    def test_kill_shard_at_after_skips_passes(self):
        plan = FaultPlan(seed=1).kill_shard_at("distributed.gather", 2,
                                               after=2)
        with plan.active():
            faults.maybe_fail("distributed.gather")
            faults.maybe_fail("distributed.gather")
            assert faults.failed_shards(8) == ()
            faults.maybe_fail("distributed.gather")
            assert faults.failed_shards(8) == (2,)

    def test_kill_does_not_raise(self):
        """A shard kill is a membership change, not an exception — the
        site keeps executing (the search finishes on pre-kill routing)."""
        plan = FaultPlan(seed=1).kill_shard_at("distributed.swap", 1)
        with plan.active():
            faults.maybe_fail("distributed.swap")  # must not raise
            assert faults.failed_shards(4) == (1,)

    def test_flap_shard_alternates_membership(self):
        plan = FaultPlan(seed=1).flap_shard(2, period=2)
        with plan.active():
            seen = [2 in faults.failed_shards(8) for _ in range(8)]
        # period=2: two polls down, two up, ... starting down
        assert seen == [True, True, False, False,
                        True, True, False, False]

    def test_flap_rejects_bad_period(self):
        with pytest.raises(ValueError):
            FaultPlan(seed=1).flap_shard(0, period=0)
