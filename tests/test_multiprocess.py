"""Multi-process ``jax.distributed`` bootstrap test.

Reference: python/raft-dask/raft_dask/test/test_comms.py:45 proves the
NCCL rendezvous with a LocalCUDACluster; here two OS processes (2
virtual CPU devices each) rendezvous via ``jax.distributed.initialize``
and run collectives + one MNMG k-means over the 4-device global mesh
(tests/distributed_worker.py).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_and_mnmg_kmeans():
    port = _free_port()
    env = dict(os.environ)
    # the workers set their own JAX env; drop any inherited backend pins
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    # make raft_tpu importable in the workers regardless of install state
    # (the worker also self-inserts the repo root, belt and braces)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (root, env.get("PYTHONPATH")) if p)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    # capability gate: a jax build without multi-controller CPU
    # collectives reports UNSUPPORTED from inside the worker — skip with
    # the worker's reason instead of hard-failing the suite
    for out in outs:
        if "MULTIPROC_UNSUPPORTED" in out:
            line = next(ln for ln in out.splitlines()
                        if "MULTIPROC_UNSUPPORTED" in ln)
            pytest.skip(f"multi-process collectives unavailable: {line}")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {rank} failed (rc={p.returncode}):\n{out[-4000:]}")
        assert "MULTIPROC_OK" in out, out[-4000:]
