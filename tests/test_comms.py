"""Comms tests — mirrors python/raft-dask/raft_dask/test/test_comms.py:
spin up the multi-device session (virtual 8-CPU mesh = the LocalCUDACluster
analogue, SURVEY.md §4) and run the C++-side-style collective self-checks
(perform_test_comms_*), which assert results inside the workers.
"""

import jax
import numpy as np
import pytest

from raft_tpu.comms import CommsSession, local_handle, self_test


@pytest.fixture
def session(mesh8):
    s = CommsSession(mesh=mesh8, axis_name="data").init()
    yield s
    s.destroy()


class TestCommsInit:
    def test_comms_init(self, session):
        # reference: test_comms.py:45 test_comms_init_no_p2p
        assert session.nccl_initialized
        assert session.comms().get_size() == 8

    def test_local_handle(self, session):
        # reference: comms.py:245 local_handle retrieval pattern
        handle = local_handle(session.session_id)
        assert handle.comms_initialized()
        assert handle.get_comms().axis_name == "data"

    def test_local_handle_unknown_session(self):
        from raft_tpu.core.error import RaftError
        with pytest.raises(RaftError):
            local_handle("nonexistent")

    def test_destroy(self, mesh8):
        s = CommsSession(mesh=mesh8).init()
        sid = s.session_id
        s.destroy()
        from raft_tpu.core.error import RaftError
        with pytest.raises(RaftError):
            local_handle(sid)


@pytest.mark.parametrize("func", [
    # reference: test_comms.py:199 parametrization over the same set
    self_test.perform_test_comms_allreduce,
    self_test.perform_test_comms_bcast,
    self_test.perform_test_comms_reduce,
    self_test.perform_test_comms_allgather,
    self_test.perform_test_comms_gatherv,
    self_test.perform_test_comms_reducescatter,
])
def test_collectives(session, func):
    assert func(session)


def test_p2p_sendrecv(session):
    # reference: test_comms.py:248 (ucx-marked p2p tests)
    assert self_test.perform_test_comms_device_sendrecv(session)


def test_comm_split(session):
    # reference: test.hpp test_commsplit / sub_comms pattern
    assert self_test.perform_test_comm_split(session)


def test_bcast_nonzero_root(session):
    # reference: test_comms.py:162 root placement variants
    assert self_test.perform_test_comms_bcast(session, root=3)


def test_tagged_isend_irecv(session):
    # reference: comms.hpp:146-168 isend/irecv/waitall (UCX tags) —
    # absolute-rank ring + involution swap under two tags, one waitall
    assert self_test.perform_test_comms_isend_irecv(session)


def test_isend_rejects_non_permutation(session):
    from raft_tpu.core.error import RaftError
    comms = session.comms()
    with pytest.raises(RaftError):
        comms.isend(np.zeros(1), dst=[0] * comms.get_size())


class Test2DGrid:
    """2D (row, col) grid session — the sub_comms/comm_split contract on a
    real 2D mesh (VERDICT weak #9)."""

    def test_make_2d_session_and_split(self):
        from raft_tpu.comms import make_2d_session
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        s = make_2d_session(4, 2, devices=devs).init()
        try:
            assert s.mesh.shape == {"row": 4, "col": 2}
            assert self_test.perform_test_comm_split(s)
        finally:
            s.destroy()

    def test_collectives_on_2d_axes(self):
        from raft_tpu.comms import Comms, make_2d_session
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        s = make_2d_session(2, 4, devices=devs).init()
        try:
            def body():
                row = Comms("row")      # 2 ranks per column
                col = Comms("col")      # 4 ranks per row
                a = row.allreduce(jnp.ones((), jnp.float32))   # = 2
                b = col.allreduce(jnp.ones((), jnp.float32))   # = 4
                g = col.allgather(jax.lax.axis_index("col")
                                  .astype(jnp.float32))
                return (a * 10 + b + jnp.sum(g) * 0)[None]

            shard = jax.shard_map(body, mesh=s.mesh, in_specs=P(),
                                  out_specs=P(("row", "col")),
                                  check_vma=False)
            res = np.asarray(jax.jit(shard)())
            assert (res == 24.0).all()
        finally:
            s.destroy()
