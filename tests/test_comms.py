"""Comms tests — mirrors python/raft-dask/raft_dask/test/test_comms.py:
spin up the multi-device session (virtual 8-CPU mesh = the LocalCUDACluster
analogue, SURVEY.md §4) and run the C++-side-style collective self-checks
(perform_test_comms_*), which assert results inside the workers.
"""

import jax
from raft_tpu.core.compat import shard_map
import numpy as np
import pytest

from raft_tpu.comms import CommsSession, local_handle, self_test


@pytest.fixture
def session(mesh8):
    s = CommsSession(mesh=mesh8, axis_name="data").init()
    yield s
    s.destroy()


class TestCommsInit:
    def test_comms_init(self, session):
        # reference: test_comms.py:45 test_comms_init_no_p2p
        assert session.nccl_initialized
        assert session.comms().get_size() == 8

    def test_local_handle(self, session):
        # reference: comms.py:245 local_handle retrieval pattern
        handle = local_handle(session.session_id)
        assert handle.comms_initialized()
        assert handle.get_comms().axis_name == "data"

    def test_local_handle_unknown_session(self):
        from raft_tpu.core.error import RaftError
        with pytest.raises(RaftError):
            local_handle("nonexistent")

    def test_destroy(self, mesh8):
        s = CommsSession(mesh=mesh8).init()
        sid = s.session_id
        s.destroy()
        from raft_tpu.core.error import RaftError
        with pytest.raises(RaftError):
            local_handle(sid)


@pytest.mark.parametrize("func", [
    # reference: test_comms.py:199 parametrization over the same set
    self_test.perform_test_comms_allreduce,
    self_test.perform_test_comms_bcast,
    self_test.perform_test_comms_reduce,
    self_test.perform_test_comms_allgather,
    self_test.perform_test_comms_gatherv,
    self_test.perform_test_comms_reducescatter,
])
def test_collectives(session, func):
    assert func(session)


def test_p2p_sendrecv(session):
    # reference: test_comms.py:248 (ucx-marked p2p tests)
    assert self_test.perform_test_comms_device_sendrecv(session)


def test_comm_split(session):
    # reference: test.hpp test_commsplit / sub_comms pattern
    assert self_test.perform_test_comm_split(session)


def test_bcast_nonzero_root(session):
    # reference: test_comms.py:162 root placement variants
    assert self_test.perform_test_comms_bcast(session, root=3)


def test_tagged_isend_irecv(session):
    # reference: comms.hpp:146-168 isend/irecv/waitall (UCX tags) —
    # absolute-rank ring + involution swap under two tags, one waitall
    assert self_test.perform_test_comms_isend_irecv(session)


def test_isend_many_to_one_fallback(session):
    """Non-permutation (fan-in) p2p patterns complete via the gather
    fallback: even ranks send to their odd neighbor; even ranks receive
    nothing (src=-1 -> zeros).  The UCX-style many-to-one shape the
    permutation-only ppermute path used to hard-reject (VERDICT r3
    weak #6)."""
    import jax.numpy as jnp

    comms = session.comms()
    n = comms.get_size()
    P = jax.sharding.PartitionSpec
    dst = [r + 1 if r % 2 == 0 else -1 for r in range(n)]  # evens -> odds
    src = [r - 1 if r % 2 == 1 else -1 for r in range(n)]

    def body():
        mine = jax.lax.axis_index(session.axis_name).astype(jnp.float32)
        reqs = [comms.isend(mine, dst, tag=0), comms.irecv(src, tag=0)]
        (got,) = comms.waitall(reqs)
        return got[None]

    shard = shard_map(body, mesh=session.mesh, in_specs=P(),
                          out_specs=P(session.axis_name), check_vma=False)
    res = np.asarray(jax.jit(shard)())
    expected = np.asarray([r - 1 if r % 2 == 1 else 0.0
                           for r in range(n)], np.float32)
    np.testing.assert_array_equal(res.ravel(), expected)


class Test2DGrid:
    """2D (row, col) grid session — the sub_comms/comm_split contract on a
    real 2D mesh (VERDICT weak #9)."""

    def test_make_2d_session_and_split(self):
        from raft_tpu.comms import make_2d_session
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        s = make_2d_session(4, 2, devices=devs).init()
        try:
            assert s.mesh.shape == {"row": 4, "col": 2}
            assert self_test.perform_test_comm_split(s)
        finally:
            s.destroy()

    def test_collectives_on_2d_axes(self):
        from raft_tpu.comms import Comms, make_2d_session
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        s = make_2d_session(2, 4, devices=devs).init()
        try:
            def body():
                row = Comms("row")      # 2 ranks per column
                col = Comms("col")      # 4 ranks per row
                a = row.allreduce(jnp.ones((), jnp.float32))   # = 2
                b = col.allreduce(jnp.ones((), jnp.float32))   # = 4
                g = col.allgather(jax.lax.axis_index("col")
                                  .astype(jnp.float32))
                return (a * 10 + b + jnp.sum(g) * 0)[None]

            shard = shard_map(body, mesh=s.mesh, in_specs=P(),
                                  out_specs=P(("row", "col")),
                                  check_vma=False)
            res = np.asarray(jax.jit(shard)())
            assert (res == 24.0).all()
        finally:
            s.destroy()


def test_collective_counters(session):
    # observability wiring: collectives record call/byte counters at
    # trace time (the self-test retraces per call: fresh closures)
    from raft_tpu import observability as obs
    obs.reset()
    with obs.collecting():
        assert self_test.perform_test_comms_allreduce(session)
    snap = obs.snapshot()
    obs.reset()
    assert snap["counters"].get("comms.allreduce.calls", 0) >= 1
    assert snap["counters"].get("comms.allreduce.bytes", 0) >= 4


def test_reduce_gather_record_own_counters(session):
    # reduce/gather share lowering with allreduce/allgather but must be
    # attributed under their OWN names (recorded before dispatch) —
    # PROD included
    import jax.numpy as jnp
    from raft_tpu import observability as obs
    from raft_tpu.comms import Comms
    from raft_tpu.comms.comms import op_t
    P = jax.sharding.PartitionSpec

    def body():
        c = Comms(session.axis_name)
        r = c.reduce(jnp.ones((), jnp.float32), op=op_t.PROD)
        g = c.gather(jax.lax.axis_index(session.axis_name)
                     .astype(jnp.float32))
        return (r + jnp.sum(g))[None]

    obs.reset()
    with obs.collecting():
        fn = shard_map(body, mesh=session.mesh, in_specs=(),
                       out_specs=P(session.axis_name), check_vma=False)
        np.asarray(jax.jit(fn)())
    snap = obs.snapshot()["counters"]
    obs.reset()
    assert snap.get("comms.reduce.calls", 0) == 1
    assert snap.get("comms.gather.calls", 0) == 1
    assert "comms.allreduce.calls" not in snap
    assert "comms.allgather.calls" not in snap


def test_comms_fault_site_fires_at_trace(session):
    # resilience: a scripted comms.allreduce fault raises at trace time
    from raft_tpu.resilience import TransientFault, inject
    import jax.numpy as jnp
    from raft_tpu.comms import Comms
    P = jax.sharding.PartitionSpec

    def body():
        return Comms(session.axis_name).allreduce(
            jnp.ones((), jnp.float32))[None]

    with inject("comms.allreduce", times=1):
        with pytest.raises(TransientFault):
            fn = shard_map(body, mesh=session.mesh, in_specs=(),
                           out_specs=P(session.axis_name),
                           check_vma=False)
            jax.jit(fn)()
