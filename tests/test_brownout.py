"""raft_tpu.serving.brownout — adaptive degradation + auto-rollback.

Covers the PR 12 robustness surface: ladder/config validation, the
rung-extended executor (every (bucket, k, rung) warmed, rung part of the
AOT cache key, zero recompiles across transitions), the controller's
step_down/step_up decisions under injected clocks (hysteresis + dwell
pin oscillation), exactly-one-shed-counter deadline accounting at every
brownout level, the generation watchdog's strike/rollback matrix, and
the flight recorder's configurable capacity.
"""

import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu import serving
from raft_tpu.core import aot
from raft_tpu.integrity import IntegrityError
from raft_tpu.neighbors import ivf_pq
from raft_tpu.observability import flight, trace
from raft_tpu.resilience.retry import Deadline, DeadlineExceededError


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.disable()
    obs.reset()
    trace.disable_tracing()
    flight.clear()
    yield
    obs.disable()
    obs.reset()
    trace.disable_tracing()
    flight.clear()


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    # rung warm-ups and rollback swaps compile many executables; release
    # them at teardown so later modules don't inherit the JIT mappings
    yield
    jax.clear_caches()


def _dataset(n=4000, dim=32, seed=0):
    rng = np.random.default_rng(seed)
    db = rng.normal(size=(n, dim)).astype(np.float32)
    q = rng.normal(size=(64, dim)).astype(np.float32)
    return jnp.asarray(db), jnp.asarray(q)


@pytest.fixture(scope="module")
def pq_setup():
    from raft_tpu import DeviceResources
    res = DeviceResources(seed=42)
    db, q = _dataset()
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=32, pq_dim=8, kmeans_n_iters=4), db)
    sp = ivf_pq.SearchParams(n_probes=8)
    return res, db, q, index, sp


@pytest.fixture(scope="module")
def canary_setup(pq_setup):
    res, db, q, _, sp = pq_setup
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=32, pq_dim=8, kmeans_n_iters=4,
                                canary_queries=16, canary_k=5,
                                canary_floor=0.2), db)
    return res, db, q, index, sp


def _executor(pq_setup, max_batch=16, ks=(5,), warm="jit"):
    res, _, _, index, sp = pq_setup
    return serving.Executor(res, "ivf_pq", index, ks=ks,
                            max_batch=max_batch, search_params=sp,
                            warm=warm)


def _ladder():
    """full quality -> reduced n_probes -> best-effort shed (shed-only
    top rung inherits the degraded executables)."""
    return [
        serving.Rung("full"),
        serving.Rung("probes/4", params=ivf_pq.SearchParams(n_probes=4)),
        serving.Rung("shed-best-effort", shed_best_effort=True),
    ]


def _bcfg(**kw):
    kw.setdefault("step_down_p99_s", 0.5)
    kw.setdefault("step_up_p99_s", 0.1)
    kw.setdefault("dwell_s", 1.0)
    return serving.BrownoutConfig(**kw)


def _mk(pq_setup, *, t=None, tenants=(), bcfg=None, cfg=None, warm="jit"):
    """Server + controller pair (controller BEFORE start, per contract);
    ``t`` injects the controller clock as a one-element list."""
    ex = _executor(pq_setup, warm=warm)
    srv = serving.Server(ex, cfg or serving.ServerConfig(
        max_batch=16, max_wait_us=5_000, max_queue_rows=8))
    clock = (lambda: t[0]) if t is not None else time.monotonic
    ctl = serving.BrownoutController(srv, _ladder(), bcfg or _bcfg(),
                                     best_effort_tenants=tenants,
                                     clock=clock)
    return srv, ctl


# ---------------------------------------------------------------------------
# ladder + config validation


class TestLadderValidation:
    def test_hysteresis_gap_enforced(self):
        with pytest.raises(Exception, match="hysteresis"):
            _bcfg(step_up_p99_s=0.5, step_down_p99_s=0.5).validate()
        with pytest.raises(Exception, match="queue_low"):
            _bcfg(queue_low_fraction=0.5, queue_high_fraction=0.5).validate()
        with pytest.raises(Exception, match="dwell"):
            _bcfg(dwell_s=-1.0).validate()
        with pytest.raises(Exception, match="interval"):
            _bcfg(interval_s=0.0).validate()
        with pytest.raises(Exception, match="shed_step_down"):
            _bcfg(shed_step_down=0).validate()

    def test_ladder_needs_two_rungs(self, pq_setup):
        srv = serving.Server(_executor(pq_setup),
                             serving.ServerConfig(max_batch=16))
        with pytest.raises(Exception, match="at least"):
            serving.BrownoutController(srv, [serving.Rung("full")])

    def test_rung_zero_must_be_undegraded(self, pq_setup):
        srv = serving.Server(_executor(pq_setup),
                             serving.ServerConfig(max_batch=16))
        bad = [serving.Rung("half", params=ivf_pq.SearchParams(n_probes=4)),
               serving.Rung("quarter",
                            params=ivf_pq.SearchParams(n_probes=2))]
        with pytest.raises(Exception, match="rung 0"):
            serving.BrownoutController(srv, bad)
        with pytest.raises(Exception, match="rung 0"):
            serving.BrownoutController(
                srv, [serving.Rung("full", shed_best_effort=True),
                      serving.Rung("half",
                                   params=ivf_pq.SearchParams(n_probes=4))])

    def test_set_ladder_after_warmup_rejected(self, pq_setup):
        ex = _executor(pq_setup)
        ex.warmup()
        with pytest.raises(Exception, match="zero-recompile"):
            ex.set_ladder([ivf_pq.SearchParams(n_probes=4)])

    def test_shed_only_rung_inherits_executables(self, pq_setup):
        srv, ctl = _mk(pq_setup)
        # ladder level 2 is shed-only (params=None) -> same executor rung
        # as level 1: no extra warmup, no extra cache entries
        assert ctl._exec_rung == [0, 1, 1]
        assert srv.executor.n_rungs == 2

    def test_brownedout_is_overloaded(self):
        assert issubclass(serving.BrownedOut, serving.Overloaded)


# ---------------------------------------------------------------------------
# the rung-extended executor


class TestRungExecutor:
    def test_warmup_covers_every_rung(self, pq_setup):
        res, _, _, index, sp = pq_setup
        ex = serving.Executor(
            res, "ivf_pq", index, ks=(5,), max_batch=16, search_params=sp,
            ladder=(ivf_pq.SearchParams(n_probes=2),), warm="jit")
        n = ex.warmup()
        assert n == len(ex.buckets) * len(ex.ks) * 2
        assert {r for (_, _, r) in ex._fns} == {0, 1}

    def test_degraded_rung_uses_its_params(self, pq_setup):
        res, _, q, index, _ = pq_setup
        sp2 = ivf_pq.SearchParams(n_probes=2)
        ex = serving.Executor(
            res, "ivf_pq", index, ks=(5,), max_batch=16,
            search_params=ivf_pq.SearchParams(n_probes=8),
            ladder=(sp2,), warm="jit")
        d, i = ex.search_bucket(q[:8], 8, 5, rung=1)
        dd, ii = ivf_pq.search(res, sp2, index, q[:8], 5)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
        np.testing.assert_allclose(np.asarray(d), np.asarray(dd), rtol=1e-5)

    def test_rung_outside_ladder_rejected(self, pq_setup):
        ex = _executor(pq_setup)
        with pytest.raises(Exception, match="rung"):
            ex.search_bucket(jnp.zeros((4, 32), np.float32), 4, 5, rung=1)

    def test_aot_cache_key_includes_rung(self, pq_setup):
        res, _, q, index, _ = pq_setup
        cache = aot.ExecutableCache()
        a = cache.get("ivf_pq", res, index, batch=4, k=5, n_probes=8,
                      scan_mode="recon", rung=0)
        b = cache.get("ivf_pq", res, index, batch=4, k=5, n_probes=8,
                      scan_mode="recon", rung=1)
        assert a is not b
        assert cache.get("ivf_pq", res, index, batch=4, k=5, n_probes=8,
                         scan_mode="recon", rung=0) is a
        d, i = b(q[:4])
        assert d.shape == (4, 5) and i.shape == (4, 5)

    def test_zero_recompiles_across_rung_transitions(self, pq_setup):
        """The tentpole contract: every rung pre-warmed at start, so a
        brownout transition (one int store) never compiles — asserted
        with the same xla.compiles tripwire as the bucket contract."""
        srv, ctl = _mk(pq_setup, warm="aot",
                       cfg=serving.ServerConfig(max_batch=16,
                                                max_wait_us=2_000))
        q = np.asarray(pq_setup[2])
        with obs.collecting():
            srv.start()
            try:
                for lvl in (0, 1, 2, 0):
                    srv.brownout.rung = ctl._exec_rung[lvl]
                    srv.brownout.level = lvl
                    for m in (1, 3, 16):
                        srv.search(q[:m], 5)
                c0 = obs.registry().counter("xla.compiles").value
                for lvl in (2, 1, 0, 1, 2):
                    srv.brownout.rung = ctl._exec_rung[lvl]
                    srv.brownout.level = lvl
                    for m in (2, 16, 5):
                        srv.search(q[:m], 5)
                c1 = obs.registry().counter("xla.compiles").value
            finally:
                srv.stop()
        assert c1 == c0, f"{c1 - c0} recompiles across rung transitions"


# ---------------------------------------------------------------------------
# the controller's decisions (synchronous evaluate, injected clock)


class TestController:
    def test_latency_steps_down_to_the_floor(self, pq_setup):
        t = [0.0]
        srv, ctl = _mk(pq_setup, t=t)
        with obs.collecting():
            h = obs.registry().histogram("serving.latency.total")
            for _ in range(10):
                h.observe(1.0)                    # p99 well above 0.5
            assert ctl.evaluate() is None         # dwell since construction
            t[0] += 1.5
            assert ctl.evaluate() == "step_down"
            assert srv.brownout.level == 1 and srv.brownout.rung == 1
            assert obs.registry().gauge("serving.brownout.level").value == 1
            assert ctl.evaluate() is None         # dwell pins the next step
            t[0] += 1.5
            assert ctl.evaluate() == "step_down"  # still hot -> level 2
            assert srv.brownout.level == 2
            assert srv.brownout.shed_best_effort
            t[0] += 1.5
            assert ctl.evaluate() is None         # already at the floor
        evs = flight.events("serving.brownout.step_down")
        assert [(e["attrs"]["from_level"], e["attrs"]["to_level"])
                for e in evs] == [(0, 1), (1, 2)]
        assert evs[0]["attrs"]["rung"] == "probes/4"
        assert evs[0]["attrs"]["p99_s"] >= 0.5

    def test_hysteresis_pins_midband_and_calm_steps_up(self, pq_setup,
                                                       monkeypatch):
        import importlib
        # the package's registry() accessor shadows the submodule attr
        _registry = importlib.import_module(
            "raft_tpu.observability.registry")
        T = [1000.0]
        monkeypatch.setattr(_registry, "_now", lambda: T[0])
        t = [0.0]
        srv, ctl = _mk(pq_setup, t=t)
        with obs.collecting():
            h = obs.registry().histogram("serving.latency.total")
            h.observe(1.0)
            t[0] += 1.5
            assert ctl.evaluate() == "step_down"
            # the hot sample ages out of the window; mid-band latency
            # (between step_up 0.1 and step_down 0.5) arrives instead
            T[0] += 300.0
            for _ in range(5):
                h.observe(0.3)
            for _ in range(5):
                t[0] += 1.5
                assert ctl.evaluate() is None      # hysteresis: no flap
            assert srv.brownout.level == 1
            # a calm (empty) window recovers one level
            T[0] += 300.0
            t[0] += 1.5
            assert ctl.evaluate() == "step_up"
            assert srv.brownout.level == 0 and srv.brownout.rung == 0
            assert obs.registry().gauge("serving.brownout.level").value == 0
        evs = flight.events("serving.brownout.step_up")
        assert len(evs) == 1
        assert evs[0]["attrs"]["from_level"] == 1
        assert evs[0]["attrs"]["to_level"] == 0

    def test_pressure_sheds_step_down_quota_excluded(self, pq_setup):
        t = [0.0]
        srv, ctl = _mk(pq_setup, t=t)
        with obs.collecting():
            reg = obs.registry()
            reg.counter("serving.shed.quota").inc(5)   # policy, not pressure
            t[0] += 1.5
            assert ctl.evaluate() is None
            reg.counter("serving.shed.deadline").inc()
            t[0] += 1.5
            assert ctl.evaluate() == "step_down"
            assert flight.events("serving.brownout.step_down")[0][
                "attrs"]["window_sheds"] == 1

    def test_queue_pressure_steps_down(self, pq_setup):
        t = [0.0]
        srv, ctl = _mk(pq_setup, t=t)          # max_queue_rows=8, high=0.5
        q = pq_setup[2]
        srv.start()
        try:
            srv.batcher.stop(drain=False)      # park: submissions stay queued
            fut = srv.submit(q[:4], 5)         # 4 rows >= 0.5 * 8
            t[0] += 1.5
            assert ctl.evaluate() == "step_down"
            assert srv.brownout.level == 1
            srv.batcher.start()                # drain at the degraded rung
            d, i = fut.result(timeout=30)
            assert d.shape == (4, 5)
        finally:
            srv.stop()

    def test_best_effort_tenant_shed_exactly_once(self, pq_setup):
        srv, ctl = _mk(pq_setup, tenants={"batch"})
        q = pq_setup[2]
        with obs.collecting():
            srv.start()
            try:
                # the state the controller would publish at the top rung
                srv.brownout.rung = ctl._exec_rung[2]
                srv.brownout.shed_best_effort = True
                srv.brownout.level = 2
                with pytest.raises(serving.BrownedOut):
                    srv.submit(q[:2], 5, tenant="batch")
                # paying tenants still served at the degraded rung
                d, i = srv.search(q[:3], 5, tenant="paying")
                assert d.shape == (3, 5)
            finally:
                srv.stop()
            reg = obs.registry()
            assert reg.counter("serving.shed.brownout").value == 1
            for other in ("serving.shed.deadline", "serving.shed.queue_full",
                          "serving.shed.quota"):
                assert reg.counter(other).value == 0, other
        evs = flight.events("serving.shed.brownout")
        assert len(evs) == 1
        assert evs[0]["attrs"]["tenant"] == "batch"
        assert evs[0]["attrs"]["level"] == 2

    def test_stats_track_residency(self, pq_setup):
        t = [0.0]
        srv, ctl = _mk(pq_setup, t=t)
        with obs.collecting():
            obs.registry().histogram("serving.latency.total").observe(1.0)
            t[0] += 2.0
            assert ctl.evaluate() == "step_down"
            t[0] += 3.0
            s = ctl.stats()
        assert s["level"] == 1 and s["rung"] == "probes/4"
        assert s["transitions"] == 1
        assert s["residency_s"]["full"] == pytest.approx(2.0)
        assert s["residency_s"]["probes/4"] == pytest.approx(3.0)

    def test_disabled_collection_is_calm(self, pq_setup):
        # no registry signal at all: the controller must idle at level 0,
        # not oscillate on missing telemetry
        t = [10.0]
        srv, ctl = _mk(pq_setup, t=t)
        t[0] += 5.0
        assert ctl.evaluate() is None
        assert srv.brownout.level == 0

    def test_background_loop_lifecycle(self, pq_setup):
        srv, ctl = _mk(pq_setup, bcfg=_bcfg(dwell_s=0.0, interval_s=0.01))
        with ctl:
            time.sleep(0.05)
        assert ctl._thread is None
        assert srv.brownout.level == 0

    def test_brownout_level_annotated_on_traces(self, pq_setup):
        srv, ctl = _mk(pq_setup, cfg=serving.ServerConfig(
            max_batch=16, max_wait_us=2_000))
        q = np.asarray(pq_setup[2])
        with trace.tracing_scope():
            srv.start()
            try:
                srv.brownout.rung = ctl._exec_rung[1]
                srv.brownout.level = 1
                srv.search(q[:2], 5)
            finally:
                srv.stop()
        recs = [r for r in flight.traces() if r.name == "serving.request"]
        assert recs and recs[-1].attrs["brownout_level"] == 1


# ---------------------------------------------------------------------------
# deadline accounting at every brownout level (exactly one shed counter)


class TestDeadlineAtEveryLevel:
    @pytest.mark.parametrize("level", [0, 1, 2])
    def test_queue_expiry_ticks_one_counter(self, pq_setup, level):
        srv, ctl = _mk(pq_setup, cfg=serving.ServerConfig(
            max_batch=16, max_wait_us=200_000))
        q = pq_setup[2]
        t = [0.0]
        with obs.collecting():
            srv.start()
            try:
                srv.brownout.rung = ctl._exec_rung[level]
                srv.brownout.level = level
                dead = Deadline(0.05, clock=lambda: t[0])
                doomed = srv.submit(q[:2], 5, deadline=dead)
                t[0] += 1.0                       # budget lapses queued
                ok = srv.submit(q[:3], 5)
                assert ok.result(timeout=10)[0].shape == (3, 5)
                with pytest.raises(DeadlineExceededError):
                    doomed.result(timeout=10)
            finally:
                srv.stop()
            # exactly ONE shed counter for the shed request, at any level
            assert obs.registry().counter(
                "serving.shed.deadline").value == 1
            assert obs.registry().counter(
                "serving.shed.brownout").value == 0
        evs = flight.events("serving.shed.deadline")
        assert [e["attrs"]["phase"] for e in evs] == ["dispatch"]
        assert evs[0]["attrs"]["level"] == level

    def test_submit_expiry_ticks_one_counter(self, pq_setup):
        srv, ctl = _mk(pq_setup)
        with obs.collecting():
            srv.start()
            try:
                srv.brownout.rung = ctl._exec_rung[1]
                srv.brownout.level = 1
                with pytest.raises(serving.Overloaded):
                    srv.submit(pq_setup[2][:2], 5, deadline=Deadline(0.0))
            finally:
                srv.stop()
            assert obs.registry().counter(
                "serving.shed.deadline").value == 1
        evs = flight.events("serving.shed.deadline")
        assert [e["attrs"]["phase"] for e in evs] == ["submit"]
        assert evs[0]["attrs"]["level"] == 1


# ---------------------------------------------------------------------------
# the generation watchdog (auto-rollback)


class TestWatchdog:
    def test_disabled_by_default(self, pq_setup):
        srv = serving.Server(_executor(pq_setup),
                             serving.ServerConfig(max_batch=16))
        assert srv.note_integrity_strike("test") is False
        assert flight.events("serving.auto_rollback") == []

    def test_below_threshold_no_rollback(self, pq_setup):
        res, _, _, index, _ = pq_setup
        srv = serving.Server(
            _executor(pq_setup),
            serving.ServerConfig(max_batch=16, rollback_strikes=3))
        mutated = ivf_pq.delete(res, index, [0, 1, 2])
        srv.swap_index(mutated)
        assert srv.note_integrity_strike("one") is False
        assert srv.note_integrity_strike("two") is False
        assert srv.executor.index is mutated
        assert flight.events("serving.auto_rollback") == []

    def test_rollback_restores_last_good_and_passes_canary(self,
                                                           canary_setup):
        from raft_tpu.integrity import canary as _canary
        res, _, q, index, sp = canary_setup
        ex = serving.Executor(res, "ivf_pq", index, ks=(5,), max_batch=16,
                              search_params=sp, warm="jit")
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000,
                                   rollback_strikes=2)
        q = np.asarray(q)
        with obs.collecting():
            with serving.Server(ex, cfg) as srv:
                srv.search(q[:3], 5)
                mutated = ivf_pq.delete(res, index, [0, 1, 2])
                srv.swap_index(mutated)       # retains `index` as last-good
                assert srv.note_integrity_strike("canary floor") is False
                assert srv.note_integrity_strike("canary floor") is True
                assert srv.executor.index is index
                # the restored generation passes its own canary check
                assert _canary.health_check(res, srv.executor.index).ok
                # and keeps serving recompile-free (the rollback swap
                # re-warmed the table before publishing it)
                c0 = obs.registry().counter("xla.compiles").value
                for m in (1, 3, 8):
                    srv.search(q[:m], 5)
                c1 = obs.registry().counter("xla.compiles").value
                assert c1 == c0, f"{c1 - c0} recompiles after rollback"
            reg = obs.registry()
            assert reg.counter("serving.auto_rollbacks").value == 1
            assert reg.counter("serving.integrity_strikes").value == 2
        evs = flight.events("serving.auto_rollback")
        assert len(evs) == 1
        at = evs[0]["attrs"]
        assert at["strikes"] == 2
        assert at["restored_generation"] == getattr(index, "generation",
                                                    None)
        assert "canary floor" in at["reason"]

    def test_window_prunes_old_strikes(self, pq_setup, monkeypatch):
        import raft_tpu.serving.server as server_mod
        res, _, _, index, _ = pq_setup
        srv = serving.Server(
            _executor(pq_setup),
            serving.ServerConfig(max_batch=16, rollback_strikes=2,
                                 rollback_window_s=1.0))
        mutated = ivf_pq.delete(res, index, [0, 1, 2])
        srv.swap_index(mutated)
        t = [0.0]
        monkeypatch.setattr(server_mod, "time",
                            types.SimpleNamespace(monotonic=lambda: t[0]))
        assert srv.note_integrity_strike("a") is False
        t[0] = 5.0                              # first strike ages out
        assert srv.note_integrity_strike("b") is False
        t[0] = 5.5                              # two strikes inside 1.0s
        assert srv.note_integrity_strike("c") is True
        assert srv.executor.index is index

    def test_batch_integrity_error_strikes_and_rolls_back(self, pq_setup,
                                                          monkeypatch):
        res, _, q, index, _ = pq_setup
        ex = _executor(pq_setup)
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000,
                                   rollback_strikes=1)
        q = np.asarray(q)
        with serving.Server(ex, cfg) as srv:
            mutated = ivf_pq.delete(res, index, [0, 1, 2])
            srv.swap_index(mutated)
            orig = ex.search_bucket
            trip = [True]

            def poisoned(queries, n_valid, k, rung=0):
                if trip[0]:
                    trip[0] = False
                    raise IntegrityError("post-swap corruption",
                                         invariant="test.trip")
                return orig(queries, n_valid, k, rung)

            monkeypatch.setattr(ex, "search_bucket", poisoned)
            with pytest.raises(IntegrityError):
                srv.search(q[:2], 5, timeout=30)
            # the rollback runs on the dispatcher thread after the futures
            # fail; wait for the swap to land
            deadline = time.monotonic() + 30
            while (srv.executor.index is not index
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.executor.index is index
            d, i = srv.search(q[:3], 5, timeout=30)
            assert d.shape == (3, 5)
        evs = flight.events("serving.auto_rollback")
        assert len(evs) == 1
        assert "batch_error" in evs[0]["attrs"]["reason"]

    def test_non_integrity_batch_errors_do_not_strike(self, pq_setup,
                                                      monkeypatch):
        res, _, q, index, _ = pq_setup
        ex = _executor(pq_setup)
        cfg = serving.ServerConfig(max_batch=16, max_wait_us=2_000,
                                   rollback_strikes=1)
        q = np.asarray(q)
        with serving.Server(ex, cfg) as srv:
            mutated = ivf_pq.delete(res, index, [0, 1, 2])
            srv.swap_index(mutated)
            orig = ex.search_bucket
            trip = [True]

            def flaky(queries, n_valid, k, rung=0):
                if trip[0]:
                    trip[0] = False
                    raise RuntimeError("transient executor hiccup")
                return orig(queries, n_valid, k, rung)

            monkeypatch.setattr(ex, "search_bucket", flaky)
            with pytest.raises(RuntimeError):
                srv.search(q[:2], 5, timeout=30)
            d, i = srv.search(q[:3], 5, timeout=30)
            assert d.shape == (3, 5)
            assert srv.executor.index is mutated    # no rollback
        assert flight.events("serving.auto_rollback") == []

    def test_check_canary_failure_strikes(self, canary_setup, monkeypatch):
        from raft_tpu.integrity import canary as _canary
        res, _, _, index, sp = canary_setup
        ex = serving.Executor(res, "ivf_pq", index, ks=(5,), max_batch=16,
                              search_params=sp, warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=16,
                                                      rollback_strikes=1))
        mutated = ivf_pq.delete(res, index, [0, 1, 2])
        srv.swap_index(mutated)
        bad = _canary.CanaryReport(recall=0.05, floor=0.5, n_queries=4, k=5)
        monkeypatch.setattr(_canary, "health_check", lambda *a, **k: bad)
        assert srv.check_canary(res) is False
        # single-strike config: the canary strike rolled back synchronously
        assert srv.executor.index is index
        evs = flight.events("serving.auto_rollback")
        assert len(evs) == 1
        assert "canary" in evs[0]["attrs"]["reason"]

    def test_check_canary_passing_and_canaryless(self, pq_setup,
                                                 canary_setup):
        res = pq_setup[0]
        # canary-less index: health_check returns None -> healthy
        srv = serving.Server(_executor(pq_setup),
                             serving.ServerConfig(max_batch=16,
                                                  rollback_strikes=1))
        assert srv.check_canary(res) is True
        # canary-carrying healthy index: report.ok -> no strike
        _, _, _, cindex, sp = canary_setup
        ex = serving.Executor(res, "ivf_pq", cindex, ks=(5,), max_batch=16,
                              search_params=sp, warm="jit")
        srv2 = serving.Server(ex, serving.ServerConfig(max_batch=16,
                                                       rollback_strikes=1))
        assert srv2.check_canary(res) is True
        assert flight.events("serving.auto_rollback") == []

    def test_no_second_rollback_without_new_good(self, pq_setup):
        res, _, _, index, _ = pq_setup
        srv = serving.Server(
            _executor(pq_setup),
            serving.ServerConfig(max_batch=16, rollback_strikes=1))
        mutated = ivf_pq.delete(res, index, [0, 1, 2])
        srv.swap_index(mutated)
        assert srv.note_integrity_strike("first") is True
        # last-good was consumed: a still-failing environment must not
        # ping-pong back onto the generation it just indicted
        assert srv.note_integrity_strike("second") is False
        assert srv.executor.index is index
        assert len(flight.events("serving.auto_rollback")) == 1


# ---------------------------------------------------------------------------
# flight recorder capacity (satellite)


class TestFlightCapacity:
    def test_ring_wraps_at_capacity(self):
        fr = flight.FlightRecorder(capacity=4)
        for j in range(6):
            fr.record_event("ringtest.evt", j=j)
        evs = fr.events("ringtest.evt")
        assert len(evs) == 4
        assert [e["attrs"]["j"] for e in evs] == [2, 3, 4, 5]

    def test_capacity_bounds_checked(self):
        for bad in (0, -3, flight.MAX_CAPACITY + 1):
            with pytest.raises(ValueError):
                flight.FlightRecorder(capacity=bad)
        assert flight.FlightRecorder(capacity=1).capacity == 1
        assert flight.FlightRecorder(
            capacity=flight.MAX_CAPACITY).capacity == flight.MAX_CAPACITY

    def test_env_capacity_valid(self, monkeypatch):
        monkeypatch.setenv(flight.CAPACITY_ENV, "64")
        assert flight._env_capacity() == 64

    def test_env_capacity_unset_uses_default(self, monkeypatch):
        monkeypatch.delenv(flight.CAPACITY_ENV, raising=False)
        assert flight._env_capacity() == flight.DEFAULT_CAPACITY

    @pytest.mark.parametrize("bad", ["notanint", "0", "-5",
                                     str(flight.MAX_CAPACITY + 1)])
    def test_env_capacity_invalid_warns_and_falls_back(self, monkeypatch,
                                                       bad):
        monkeypatch.setenv(flight.CAPACITY_ENV, bad)
        with pytest.warns(RuntimeWarning):
            assert flight._env_capacity() == flight.DEFAULT_CAPACITY
