"""Durable streaming ingest: WAL framing/corruption taxonomy, memtable
semantics (incl. the same-id churn regression), the fsync-before-ack
write path, the kill-at-every-boundary recovery matrix (bit-identical
replay, no acked write lost), write-path backpressure/quota/brownout
shedding, the checkpointed fold lifecycle, and the zero-steady-state-
recompile contract with the delta tier attached."""

import io
import os
import struct
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import DeviceResources, serving
from raft_tpu import observability as obs
from raft_tpu.core.error import RaftError
from raft_tpu.core.serialize import CorruptIndexError
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import delta, ivf_flat, mutate
from raft_tpu.observability import flight, trace
from raft_tpu.resilience import FaultInjected, FaultPlan
from raft_tpu.serving import ingest
from raft_tpu.serving.brownout import BrownoutState

# the CI chaos job pins this so a red matrix cell replays the identical
# kill schedule locally
SEED = int(os.environ.get("RAFT_TPU_FAULT_SEED", "20260805"))

KILL_SITES = ("ingest.append", "ingest.apply", "ingest.fsync",
              "ingest.fold", "ingest.truncate")


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.disable()
    obs.reset()
    flight.clear()
    yield
    obs.disable()
    obs.reset()
    flight.clear()


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def res():
    return DeviceResources(seed=42)


DIM = 16


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(3)
    db = rng.normal(size=(2000, DIM)).astype(np.float32)
    q = rng.normal(size=(8, DIM)).astype(np.float32)
    return db, q


@pytest.fixture(scope="module")
def flat_index(res, dataset):
    db, _ = dataset
    return ivf_flat.build(
        res, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4),
        jnp.asarray(db))


def _ingest(tmp_path, res=None, **cfg):
    cfg.setdefault("wal_dir", str(tmp_path / "wal"))
    cfg.setdefault("memtable_capacity", 32)
    cfg.setdefault("tomb_capacity", 32)
    srv = ingest.IngestServer(res, ingest.IngestConfig(**cfg), dim=DIM)
    return srv


def _rows(rng, n):
    return rng.normal(size=(n, DIM)).astype(np.float32)


# ---------------------------------------------------------------------------
# WAL framing + the corruption taxonomy


class TestWalFraming:
    def test_encode_scan_round_trip(self):
        rng = np.random.default_rng(0)
        recs = [
            ingest.encode_record(1, delta.OP_UPSERT, np.array([4, 7]),
                                 _rows(rng, 2)),
            ingest.encode_record(2, delta.OP_DELETE, np.array([4]), None),
            ingest.encode_record(3, delta.OP_UPSERT, np.array([9]),
                                 _rows(rng, 1)),
        ]
        out, end = ingest.scan_wal(b"".join(recs))
        assert [r.lsn for r in out] == [1, 2, 3]
        assert [r.op for r in out] == [delta.OP_UPSERT, delta.OP_DELETE,
                                       delta.OP_UPSERT]
        assert end == sum(len(r) for r in recs)
        np.testing.assert_array_equal(out[0].ids, [4, 7])
        assert out[1].vectors is None
        assert out[2].vectors.shape == (1, DIM)

    def test_torn_tail_truncated_not_raised(self):
        rng = np.random.default_rng(1)
        good = ingest.encode_record(1, delta.OP_UPSERT, np.array([1]),
                                    _rows(rng, 1))
        torn = ingest.encode_record(2, delta.OP_UPSERT, np.array([2]),
                                    _rows(rng, 1))[:-7]
        out, end = ingest.scan_wal(good + torn)
        assert [r.lsn for r in out] == [1]
        assert end == len(good)

    def test_short_header_at_eof_is_torn(self):
        rng = np.random.default_rng(1)
        good = ingest.encode_record(1, delta.OP_UPSERT, np.array([1]),
                                    _rows(rng, 1))
        out, end = ingest.scan_wal(good + b"RT")
        assert len(out) == 1 and end == len(good)

    def test_garbage_tail_without_magic_is_torn(self):
        rng = np.random.default_rng(1)
        good = ingest.encode_record(1, delta.OP_UPSERT, np.array([1]),
                                    _rows(rng, 1))
        out, end = ingest.scan_wal(good + b"\x00" * 40)
        assert len(out) == 1 and end == len(good)

    def test_crc_flip_on_final_record_is_torn(self):
        rng = np.random.default_rng(2)
        a = ingest.encode_record(1, delta.OP_UPSERT, np.array([1]),
                                 _rows(rng, 1))
        b = bytearray(ingest.encode_record(2, delta.OP_UPSERT,
                                           np.array([2]), _rows(rng, 1)))
        b[-1] ^= 0xFF              # payload bit flip -> CRC mismatch
        out, end = ingest.scan_wal(a + bytes(b))
        assert [r.lsn for r in out] == [1]
        assert end == len(a)

    def test_crc_flip_mid_log_raises_with_offset(self):
        rng = np.random.default_rng(2)
        a = ingest.encode_record(1, delta.OP_UPSERT, np.array([1]),
                                 _rows(rng, 1))
        b = bytearray(ingest.encode_record(2, delta.OP_UPSERT,
                                           np.array([2]), _rows(rng, 1)))
        b[-1] ^= 0xFF
        c = ingest.encode_record(3, delta.OP_DELETE, np.array([9]), None)
        with pytest.raises(CorruptIndexError, match=f"offset {len(a)}"):
            ingest.scan_wal(a + bytes(b) + c)

    def test_frame_garbage_mid_log_raises(self):
        rng = np.random.default_rng(2)
        a = ingest.encode_record(1, delta.OP_UPSERT, np.array([1]),
                                 _rows(rng, 1))
        c = ingest.encode_record(2, delta.OP_DELETE, np.array([9]), None)
        # junk between two otherwise-intact records: real corruption
        with pytest.raises(CorruptIndexError, match=f"offset {len(a)}"):
            ingest.scan_wal(a + b"\xde\xad\xbe\xef" * 4 + c)

    def test_valid_crc_bad_op_raises(self):
        from raft_tpu.core import serialize as ser
        payload = struct.pack("<QBII", 1, 99, 1, 0) + np.int64([4]).tobytes()
        buf = io.BytesIO()
        ser.write_envelope(buf, payload)
        with pytest.raises(CorruptIndexError, match="unknown op"):
            ingest.scan_wal(buf.getvalue())

    def test_repair_tail_truncates_file(self, tmp_path):
        rng = np.random.default_rng(4)
        srv = _ingest(tmp_path)
        srv.recover()
        srv.write(np.array([1]), _rows(rng, 1))
        srv.write(np.array([2]), _rows(rng, 1))
        srv.close()
        path = srv.wal_path
        size = os.path.getsize(path)
        with open(path, "ab") as f:
            f.write(b"RTIE\x01\x00partialgarbage")
        srv2 = _ingest(tmp_path)
        srv2.recover()
        assert os.path.getsize(path) == size
        assert srv2.memtable.live_rows == 2
        evs = flight.events("serving.ingest.replay")
        assert evs and evs[0]["attrs"]["truncated_bytes"] > 0
        srv2.close()


# ---------------------------------------------------------------------------
# memtable semantics


class TestMemtable:
    def test_upsert_search_delete(self):
        mt = delta.Memtable(DIM, capacity=8, tomb_capacity=8)
        v = np.full((1, DIM), 2.0, np.float32)
        mt.apply(delta.Record(lsn=1, op=delta.OP_UPSERT,
                              ids=np.array([11]), vectors=v))
        d, i = mt.search(v, 3)
        assert int(np.asarray(i)[0, 0]) == 11
        assert float(np.asarray(d)[0, 0]) == pytest.approx(0.0, abs=1e-5)
        mt.apply(delta.Record(lsn=2, op=delta.OP_DELETE,
                              ids=np.array([11])))
        _, i2 = mt.search(v, 3)
        assert (np.asarray(i2) == -1).all()
        assert mt.live_rows == 0 and mt.n_tombstones == 1

    def test_duplicate_lsn_is_noop(self):
        mt = delta.Memtable(DIM, capacity=8, tomb_capacity=8)
        rec = delta.Record(lsn=1, op=delta.OP_UPSERT, ids=np.array([1]),
                           vectors=np.ones((1, DIM), np.float32))
        assert mt.apply(rec) is True
        d0 = mt.digest()
        assert mt.apply(rec) is False
        assert mt.digest() == d0

    def test_regrow_preserves_rows_and_bumps_generation(self):
        rng = np.random.default_rng(5)
        mt = delta.Memtable(DIM, capacity=2, tomb_capacity=64)
        g0 = mt.generation
        rows = _rows(rng, 5)
        for j in range(5):
            mt.apply(delta.Record(lsn=j + 1, op=delta.OP_UPSERT,
                                  ids=np.array([j]), vectors=rows[j:j + 1]))
        assert mt.capacity == 8 and mt.generation > g0
        assert mt.live_rows == 5
        d, i = mt.search(rows[3:4], 1)
        assert int(np.asarray(i)[0, 0]) == 3

    def test_same_id_churn_one_slot_one_tombstone(self):
        """The upsert double-work regression: N overwrites of one id
        must cost ONE memtable slot and ONE main-index tombstone."""
        rng = np.random.default_rng(6)
        mt = delta.Memtable(DIM, capacity=4, tomb_capacity=4)
        last = None
        for j in range(50):
            last = _rows(rng, 1)
            mt.apply(delta.Record(lsn=j + 1, op=delta.OP_UPSERT,
                                  ids=np.array([7]), vectors=last))
        assert mt.live_rows == 1
        assert mt.n_tombstones == 1
        assert mt.capacity == 4          # no regrow: one slot reused
        d, _ = mt.search(last, 1)
        assert float(np.asarray(d)[0, 0]) == pytest.approx(0.0, abs=1e-5)
        live_ids, live_rows, tomb_ids = mt.fold_payload()
        np.testing.assert_array_equal(live_ids, [7])
        np.testing.assert_array_equal(tomb_ids, [7])
        np.testing.assert_allclose(live_rows, last, rtol=1e-6)

    def test_delete_then_reinsert_keeps_single_tombstone(self):
        rng = np.random.default_rng(7)
        mt = delta.Memtable(DIM, capacity=8, tomb_capacity=8)
        v = _rows(rng, 1)
        mt.apply(delta.Record(lsn=1, op=delta.OP_UPSERT,
                              ids=np.array([3]), vectors=v))
        mt.apply(delta.Record(lsn=2, op=delta.OP_DELETE, ids=np.array([3])))
        v2 = _rows(rng, 1)
        mt.apply(delta.Record(lsn=3, op=delta.OP_UPSERT,
                              ids=np.array([3]), vectors=v2))
        assert mt.live_rows == 1 and mt.n_tombstones == 1
        d, i = mt.search(v2, 1)
        assert int(np.asarray(i)[0, 0]) == 3

    def test_search_parity_vs_numpy_l2(self):
        rng = np.random.default_rng(8)
        mt = delta.Memtable(DIM, capacity=32, tomb_capacity=8)
        rows = _rows(rng, 20)
        for j in range(20):
            mt.apply(delta.Record(lsn=j + 1, op=delta.OP_UPSERT,
                                  ids=np.array([100 + j]),
                                  vectors=rows[j:j + 1]))
        q = _rows(rng, 4)
        d, i = mt.search(q, 5)
        ref = np.linalg.norm(q[:, None, :] - rows[None], axis=-1) ** 2
        order = np.argsort(ref, axis=1)[:, :5] + 100
        np.testing.assert_array_equal(np.asarray(i), order)

    def test_inner_product_metric(self):
        rng = np.random.default_rng(9)
        mt = delta.Memtable(DIM, capacity=8, tomb_capacity=8,
                            metric=DistanceType.InnerProduct)
        rows = _rows(rng, 4)
        for j in range(4):
            mt.apply(delta.Record(lsn=j + 1, op=delta.OP_UPSERT,
                                  ids=np.array([j]), vectors=rows[j:j + 1]))
        q = _rows(rng, 2)
        _, i = mt.search(q, 2)
        ref = np.argsort(-(q @ rows.T), axis=1)[:, :2]
        np.testing.assert_array_equal(np.asarray(i), ref)
        assert mt.select_min is False

    def test_reset_keeps_shapes(self):
        rng = np.random.default_rng(10)
        mt = delta.Memtable(DIM, capacity=8, tomb_capacity=8)
        mt.apply(delta.Record(lsn=1, op=delta.OP_UPSERT,
                              ids=np.array([1]), vectors=_rows(rng, 1)))
        cap = mt.capacity
        mt.reset()
        assert mt.live_rows == 0 and mt.n_tombstones == 0
        assert mt.capacity == cap and mt.applied_lsn == 0
        data, ids, tombs = mt.device_view()
        assert data.shape == (cap, DIM)
        assert (np.asarray(ids) == -1).all()


# ---------------------------------------------------------------------------
# the write path: ack semantics + observability


class TestWritePath:
    def test_lsn_monotonic_and_counters(self, tmp_path):
        rng = np.random.default_rng(11)
        with obs.collecting():
            srv = _ingest(tmp_path)
            srv.recover()
            lsns = [srv.write(np.array([j]), _rows(rng, 1))
                    for j in range(3)]
            assert lsns == [1, 2, 3]
            srv.write(np.array([0]), op="delete")
            snap = obs.snapshot()["counters"]
            assert snap["serving.ingest.appended"] == 4
            assert snap["serving.ingest.acked"] == 4
            h = obs.registry().histogram(
                "serving.ingest.visibility").windowed_dict()
            assert h["count"] == 4
            srv.close()

    def test_write_before_recover_refused(self, tmp_path):
        srv = _ingest(tmp_path)
        with pytest.raises(RaftError, match="recover"):
            srv.write(np.array([1]), np.ones((1, DIM), np.float32))
        srv.close()

    def test_bad_args_refused(self, tmp_path):
        rng = np.random.default_rng(12)
        srv = _ingest(tmp_path)
        srv.recover()
        with pytest.raises(RaftError, match="op"):
            srv.write(np.array([1]), _rows(rng, 1), op="replace")
        with pytest.raises(RaftError, match="no vectors"):
            srv.write(np.array([1]), _rows(rng, 1), op="delete")
        with pytest.raises(RaftError, match=">= 0"):
            srv.write(np.array([-4]), _rows(rng, 1))
        with pytest.raises(RaftError):
            srv.write(np.array([1]), _rows(rng, 1)[:, :4])
        assert srv.stats()["last_lsn"] == 0
        srv.close()

    def test_concurrent_writers_all_acked_and_replayable(self, tmp_path):
        srv = _ingest(tmp_path, max_memtable_rows=4096,
                      memtable_capacity=256)
        srv.recover()
        errs = []

        def worker(base):
            rng = np.random.default_rng(base)
            try:
                for j in range(20):
                    srv.write(np.array([base * 1000 + j]), _rows(rng, 1))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        assert srv.memtable.live_rows == 80
        assert srv.stats()["last_lsn"] == 80
        dig = srv.memtable.digest()
        srv.close()
        srv2 = _ingest(tmp_path, max_memtable_rows=4096,
                       memtable_capacity=256)
        srv2.recover()
        # lock-ordered apply: replay reproduces the interleaving exactly
        assert srv2.memtable.digest() == dig
        srv2.close()


# ---------------------------------------------------------------------------
# crash recovery: the kill matrix


def _acked_writes(srv, rng, n=4, start=0):
    """n acked single-row upserts; returns {id: row} for loss checks."""
    acked = {}
    for j in range(start, start + n):
        row = _rows(rng, 1)
        srv.write(np.array([j]), row)
        acked[j] = row[0]
    return acked


class TestKillMatrix:
    @pytest.mark.parametrize("site", KILL_SITES)
    def test_kill_then_recover_no_acked_loss(self, tmp_path, res,
                                             flat_index, site):
        rng = np.random.default_rng(SEED % 2**31)
        srv = _ingest(tmp_path, res=res)
        srv.recover(base_index=flat_index)
        acked = _acked_writes(srv, rng, n=4)
        with FaultPlan(seed=SEED).at(site, times=1).active():
            with pytest.raises(FaultInjected):
                if site in ("ingest.fold", "ingest.truncate"):
                    srv.fold()
                else:
                    srv.write(np.array([99]), _rows(rng, 1))
        srv.close()

        r1 = _ingest(tmp_path, res=res)
        idx1 = r1.recover(base_index=flat_index)

        # no acknowledged write is lost: every acked id is live in the
        # memtable, or (post-fold roll-forward) folded into the index
        if mutate.generation(idx1) > mutate.generation(flat_index):
            # the commit marker landed before the kill: recovery rolls
            # the fold FORWARD (candidate index, fresh memtable) and
            # consumes the marker — the fold finished, nothing replays
            sp = ivf_flat.SearchParams(n_probes=16)
            for i, row in acked.items():
                _, got = ivf_flat.search(res, sp, idx1, row[None, :], 1)
                assert int(np.asarray(got)[0, 0]) == i, site
            assert r1.memtable.live_rows == 0
            r1.close()
        else:
            # replay path: two INDEPENDENT recoveries of the same WAL
            # must agree bit for bit
            d1 = r1.memtable.digest()
            r1.close()
            r2 = _ingest(tmp_path, res=res)
            idx2 = r2.recover(base_index=flat_index)
            assert r2.memtable.digest() == d1
            assert mutate.generation(idx1) == mutate.generation(idx2)
            for i, row in acked.items():
                d, got = r2.memtable.search(row[None, :], 1)
                assert int(np.asarray(got)[0, 0]) == i, site
                assert float(np.asarray(d)[0, 0]) == pytest.approx(
                    0.0, abs=1e-5)
            r2.close()

    def test_truncate_kill_rolls_forward(self, tmp_path, res, flat_index):
        """A kill between the durable commit marker and the WAL
        truncation must finish the fold on recover, not replay it."""
        rng = np.random.default_rng(13)
        srv = _ingest(tmp_path, res=res)
        srv.recover(base_index=flat_index)
        acked = _acked_writes(srv, rng, n=3, start=5000)
        with FaultPlan(seed=SEED).at("ingest.truncate", times=1).active():
            with pytest.raises(FaultInjected):
                srv.fold()
        srv.close()

        r = _ingest(tmp_path, res=res)
        idx = r.recover(base_index=flat_index)
        assert mutate.generation(idx) == mutate.generation(flat_index) + 1
        assert r.memtable.live_rows == 0          # folded, not replayed
        assert r.stats()["wal_bytes"] == 0        # truncation completed
        sp = ivf_flat.SearchParams(n_probes=16)
        for i, row in acked.items():
            _, got = ivf_flat.search(res, sp, idx, row[None, :], 1)
            assert int(np.asarray(got)[0, 0]) == i
        r.close()

    def test_fold_kill_rolls_back_to_full_replay(self, tmp_path, res,
                                                 flat_index):
        rng = np.random.default_rng(14)
        srv = _ingest(tmp_path, res=res)
        srv.recover(base_index=flat_index)
        _acked_writes(srv, rng, n=3)
        pre = srv.memtable.digest()
        with FaultPlan(seed=SEED).at("ingest.fold", times=1).active():
            with pytest.raises(FaultInjected):
                srv.fold()
        srv.close()
        r = _ingest(tmp_path, res=res)
        idx = r.recover(base_index=flat_index)
        assert idx is flat_index
        assert r.memtable.digest() == pre
        r.close()

    def test_duplicate_replay_idempotent(self, tmp_path):
        rng = np.random.default_rng(15)
        srv = _ingest(tmp_path)
        srv.recover()
        _acked_writes(srv, rng, n=5)
        dig = srv.memtable.digest()
        srv.close()
        # recover, write nothing, recover again: same WAL replayed twice
        # into fresh memtables lands on the identical digest every time
        for _ in range(2):
            r = _ingest(tmp_path)
            r.recover()
            assert r.memtable.digest() == dig
            r.close()

    def test_recover_continues_lsn_sequence(self, tmp_path):
        rng = np.random.default_rng(16)
        srv = _ingest(tmp_path)
        srv.recover()
        _acked_writes(srv, rng, n=3)
        srv.close()
        r = _ingest(tmp_path)
        r.recover()
        assert r.write(np.array([50]), _rows(rng, 1)) == 4
        r.close()

    def test_replay_under_injected_fsync_failure(self, tmp_path):
        """A torn tail forces a truncation fsync during replay; an
        injected fsync failure there must propagate (never a silent
        half-repair) and the NEXT recover must succeed."""
        rng = np.random.default_rng(17)
        srv = _ingest(tmp_path)
        srv.recover()
        _acked_writes(srv, rng, n=3)
        dig = srv.memtable.digest()
        srv.close()
        with open(srv.wal_path, "ab") as f:
            f.write(b"tornrecordtail")
        with FaultPlan(seed=SEED).at("ingest.fsync", times=1).active():
            r = _ingest(tmp_path)
            with pytest.raises(FaultInjected):
                r.recover()
            r.close()
        r2 = _ingest(tmp_path)
        r2.recover()
        assert r2.memtable.digest() == dig
        r2.close()

    def test_midlog_corruption_refuses_recovery(self, tmp_path):
        rng = np.random.default_rng(18)
        srv = _ingest(tmp_path)
        srv.recover()
        _acked_writes(srv, rng, n=3)
        srv.close()
        # flip a byte INSIDE the first record's payload (not the tail)
        with open(srv.wal_path, "r+b") as f:
            f.seek(20)
            b = f.read(1)
            f.seek(20)
            f.write(bytes([b[0] ^ 0xFF]))
        r = _ingest(tmp_path)
        with pytest.raises(CorruptIndexError, match="offset 0"):
            r.recover()
        r.close()

    def test_delay_at_injects_write_latency(self, tmp_path):
        import time
        rng = np.random.default_rng(19)
        srv = _ingest(tmp_path)
        srv.recover()
        plan = FaultPlan(seed=SEED).delay_at("ingest.fsync", delay=0.05)
        with plan.active():
            t0 = time.monotonic()
            srv.write(np.array([1]), _rows(rng, 1))
            assert time.monotonic() - t0 >= 0.05
        srv.close()


# ---------------------------------------------------------------------------
# write-path admission: backpressure, quotas, brownout


class TestBackpressure:
    def test_memtable_rows_bound_sheds(self, tmp_path):
        rng = np.random.default_rng(20)
        with obs.collecting():
            srv = _ingest(tmp_path, max_memtable_rows=3)
            srv.recover()
            _acked_writes(srv, rng, n=3)
            with pytest.raises(serving.Overloaded, match="backpressure"):
                srv.write(np.array([99]), _rows(rng, 1))
            snap = obs.snapshot()["counters"]
            assert snap["serving.ingest.shed.backpressure"] == 1
            evs = flight.events("serving.ingest.backpressure")
            assert evs and evs[0]["attrs"]["state"] == "enter"
            assert srv.stats()["backpressured"] is True
            # a delete drains a row; the next write records the exit
            srv.write(np.array([0]), op="delete")
            srv.write(np.array([99]), _rows(rng, 1))
            states = [e["attrs"]["state"]
                      for e in flight.events("serving.ingest.backpressure")]
            assert states == ["enter", "exit"]
            srv.close()

    def test_wal_bytes_bound_sheds(self, tmp_path):
        rng = np.random.default_rng(21)
        srv = _ingest(tmp_path, max_wal_bytes=64)
        srv.recover()
        srv.write(np.array([1]), _rows(rng, 1))   # pushes past 64 bytes
        with pytest.raises(serving.Overloaded, match="WAL"):
            srv.write(np.array([2]), _rows(rng, 1))
        srv.close()

    def test_tenant_write_quota(self, tmp_path):
        rng = np.random.default_rng(22)
        clock = [0.0]
        srv = ingest.IngestServer(
            None,
            ingest.IngestConfig(wal_dir=str(tmp_path / "wal"),
                                write_quotas={"batch": (10.0, 2.0)}),
            dim=DIM, clock=lambda: clock[0])
        srv.recover()
        with obs.collecting():
            srv.write(np.array([1]), _rows(rng, 1), tenant="batch")
            srv.write(np.array([2]), _rows(rng, 1), tenant="batch")
            with pytest.raises(serving.QuotaExceeded):
                srv.write(np.array([3]), _rows(rng, 1), tenant="batch")
            # unquota'd tenants are unaffected
            srv.write(np.array([4]), _rows(rng, 1))
            assert obs.snapshot()["counters"][
                "serving.ingest.shed.quota"] == 1
        clock[0] += 1.0          # refill
        srv.write(np.array([5]), _rows(rng, 1), tenant="batch")
        srv.close()

    def test_brownout_write_shed(self, tmp_path):
        rng = np.random.default_rng(23)
        srv = _ingest(tmp_path)
        srv.recover()
        bo = BrownoutState(best_effort_tenants={"batch"})
        bo.shed_best_effort_writes = True
        bo.level = 2
        srv._brownout = bo
        with obs.collecting():
            with pytest.raises(serving.BrownedOut):
                srv.write(np.array([1]), _rows(rng, 1), tenant="batch")
            assert obs.snapshot()["counters"][
                "serving.ingest.shed.brownout"] == 1
        # interactive tenants write through; clearing the rung re-admits
        srv.write(np.array([2]), _rows(rng, 1))
        bo.shed_best_effort_writes = False
        srv.write(np.array([3]), _rows(rng, 1), tenant="batch")
        srv.close()

    def test_rung_flag_propagates_through_controller(self, res,
                                                     flat_index):
        ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                              max_batch=4,
                              search_params=ivf_flat.SearchParams(
                                  n_probes=4), warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=4))
        ladder = [serving.Rung("full"),
                  serving.Rung("shed-writes",
                               shed_best_effort_writes=True)]
        ctl = serving.BrownoutController(
            srv, ladder, best_effort_tenants={"batch"})
        now = ctl._clock()
        with ctl._lock:
            ctl._apply(1, "step_down", now, p99=None, queue_rows=0,
                       sheds=0)
        assert srv.brownout.shed_best_effort_writes is True
        with ctl._lock:
            ctl._apply(0, "step_up", now, p99=None, queue_rows=0, sheds=0)
        assert srv.brownout.shed_best_effort_writes is False

    def test_rung0_must_not_shed_writes(self, res, flat_index):
        ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                              max_batch=4,
                              search_params=ivf_flat.SearchParams(
                                  n_probes=4), warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=4))
        bad = [serving.Rung("full", shed_best_effort_writes=True),
               serving.Rung("degraded")]
        with pytest.raises(RaftError, match="rung 0"):
            serving.BrownoutController(srv, bad)


# ---------------------------------------------------------------------------
# the fold lifecycle


class TestFold:
    def test_empty_fold_is_noop(self, tmp_path, res, flat_index):
        srv = _ingest(tmp_path, res=res)
        srv.recover(base_index=flat_index)
        assert srv.fold() is None

    def test_fold_publishes_and_truncates(self, tmp_path, res,
                                          flat_index, dataset):
        db, _ = dataset
        rng = np.random.default_rng(24)
        with obs.collecting():
            srv = _ingest(tmp_path, res=res)
            srv.recover(base_index=flat_index)
            acked = _acked_writes(srv, rng, n=3, start=7000)
            srv.write(np.array([0]), op="delete")     # tombstone a db row
            cand = srv.fold()
            assert mutate.generation(cand) == mutate.generation(
                flat_index) + 1
            assert srv.stats()["wal_bytes"] == 0
            assert srv.memtable.live_rows == 0
            snap = obs.snapshot()["counters"]
            assert snap["serving.ingest.folds"] == 1
            assert snap["serving.ingest.truncations"] == 1
            evs = flight.events("serving.ingest.fold")
            assert evs and evs[0]["attrs"]["rows"] == 3
            assert evs[0]["attrs"]["tombstones"] == 4
        sp = ivf_flat.SearchParams(n_probes=16)
        for i, row in acked.items():
            _, got = ivf_flat.search(res, sp, cand, row[None, :], 1)
            assert int(np.asarray(got)[0, 0]) == i
        _, got = ivf_flat.search(res, sp, cand, db[0][None, :], 2)
        assert 0 not in np.asarray(got)[0]
        srv.close()

    def test_maybe_fold_thresholds(self, tmp_path, res, flat_index):
        rng = np.random.default_rng(25)
        srv = _ingest(tmp_path, res=res, fold_rows=2)
        srv.recover(base_index=flat_index)
        srv.write(np.array([8000]), _rows(rng, 1))
        assert srv.maybe_fold() is None
        srv.write(np.array([8001]), _rows(rng, 1))
        assert srv.maybe_fold() is not None
        srv.close()

    def test_rebalancer_fold_hook(self, tmp_path, res, flat_index):
        rng = np.random.default_rng(26)
        srv = _ingest(tmp_path, res=res, fold_rows=1)
        srv.recover(base_index=flat_index)
        rb = serving.Rebalancer(res, flat_index, ingest=srv)
        assert rb.maybe_fold_ingest() is None        # nothing buffered
        srv.write(np.array([8100]), _rows(rng, 1))
        folded = rb.maybe_fold_ingest()
        assert folded is not None
        assert rb.last_good is folded                # base moved forward
        srv.close()


# ---------------------------------------------------------------------------
# serving integration: merged visibility + zero-recompile steady state


@pytest.fixture()
def served(tmp_path, res, flat_index):
    ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                          max_batch=4,
                          search_params=ivf_flat.SearchParams(n_probes=16),
                          warm="jit")
    srv = serving.Server(ex, serving.ServerConfig(max_batch=4,
                                                  max_wait_us=500))
    ig = _ingest(tmp_path, res=res, memtable_capacity=64)
    ig.recover(base_index=flat_index)
    srv.attach_ingest(ig)
    srv.start()
    yield srv, ig
    srv.stop()
    ig.close()


class TestServingIntegration:
    def test_write_visible_before_fold(self, served):
        srv, _ = served
        v = np.full((1, DIM), 7.0, np.float32)
        srv.write(np.array([9000]), v)
        _, i = srv.search(v, k=5)
        assert int(np.asarray(i)[0, 0]) == 9000

    def test_delete_masks_main_index(self, served, dataset):
        srv, _ = served
        db, _ = dataset
        q = db[5][None, :]
        _, i0 = srv.search(q, k=5)
        victim = int(np.asarray(i0)[0, 0])
        srv.write(np.array([victim]), op="delete")
        _, i1 = srv.search(q, k=5)
        assert victim not in np.asarray(i1)[0]

    def test_overwrite_wins_over_main_copy(self, served, dataset):
        srv, _ = served
        db, _ = dataset
        new_row = np.full((1, DIM), -6.0, np.float32)
        srv.write(np.array([5]), new_row)          # id 5 exists in main
        _, i = srv.search(new_row, k=5)
        assert int(np.asarray(i)[0, 0]) == 5
        d0, i0 = srv.search(db[5][None, :], k=5)
        # the main-index row for id 5 is tombstoned: if id 5 surfaces,
        # it is the NEW row's (far) distance, not the old exact match
        row0 = np.asarray(i0)[0]
        if 5 in row0:
            at = float(np.asarray(d0)[0][list(row0).index(5)])
            assert at > 1.0

    def test_fold_then_search_consistent(self, served):
        srv, ig = served
        v = np.full((1, DIM), 7.5, np.float32)
        srv.write(np.array([9100]), v)
        ig.fold()
        _, i = srv.search(v, k=5)
        assert int(np.asarray(i)[0, 0]) == 9100

    def test_server_write_requires_ingest(self, res, flat_index):
        ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                              max_batch=4,
                              search_params=ivf_flat.SearchParams(
                                  n_probes=4), warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=4))
        with pytest.raises(RaftError, match="attach_ingest"):
            srv.write(np.array([1]), np.ones((1, DIM), np.float32))

    def test_attach_after_start_refused(self, tmp_path, res, flat_index):
        ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                              max_batch=4,
                              search_params=ivf_flat.SearchParams(
                                  n_probes=4), warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=4)).start()
        ig = _ingest(tmp_path, res=res)
        ig.recover(base_index=flat_index)
        try:
            with pytest.raises(RaftError, match="attach"):
                srv.attach_ingest(ig)
        finally:
            srv.stop()
            ig.close()

    def test_zero_steady_state_recompiles_write_search_fold_search(
            self, tmp_path, res, flat_index):
        """The acceptance bar: with the delta tier attached, steady
        state — writes, searches, a fold, more searches — compiles
        nothing outside the fold's own swap warm (which happens before
        the new generation is published, off the request path)."""
        ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                              max_batch=4,
                              search_params=ivf_flat.SearchParams(
                                  n_probes=16), warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=4,
                                                      max_wait_us=500))
        ig = _ingest(tmp_path, res=res, memtable_capacity=64)
        ig.recover(base_index=flat_index)
        srv.attach_ingest(ig)
        rng = np.random.default_rng(27)
        with obs.collecting():
            srv.start()
            try:
                # absorb warmup + one shape round
                for m in (1, 2, 4, 3):
                    srv.search(_rows(rng, m), k=5)
                reg = obs.registry()
                c0 = reg.counter("xla.compiles").value
                # steady state: write -> search (memtable dirty -> fresh
                # device view, same shapes)
                for j in range(4):
                    srv.write(np.array([9500 + j]), _rows(rng, 1))
                    for m in (1, 3, 4):
                        srv.search(_rows(rng, m), k=5)
                srv.write(np.array([3]), op="delete")
                srv.search(_rows(rng, 2), k=5)
                c1 = reg.counter("xla.compiles").value
                assert c1 == c0, f"{c1 - c0} recompiles on the write path"
                ig.fold()            # swap warm may compile — off path
                c2 = reg.counter("xla.compiles").value
                for m in (1, 2, 4, 3):
                    srv.search(_rows(rng, m), k=5)
                srv.write(np.array([9600]), _rows(rng, 1))
                srv.search(_rows(rng, 1), k=5)
                c3 = reg.counter("xla.compiles").value
                assert c3 == c2, f"{c3 - c2} recompiles after the fold"
            finally:
                srv.stop()
        ig.close()

    def test_memtable_regrow_is_one_generation_bump(self, tmp_path, res,
                                                    flat_index):
        """Filling past capacity regrows once (one new compiled shape),
        then steady state is flat again."""
        ex = serving.Executor(res, "ivf_flat", flat_index, ks=(5,),
                              max_batch=4,
                              search_params=ivf_flat.SearchParams(
                                  n_probes=16), warm="jit")
        srv = serving.Server(ex, serving.ServerConfig(max_batch=4,
                                                      max_wait_us=500))
        ig = _ingest(tmp_path, res=res, memtable_capacity=4,
                     max_memtable_rows=64)
        ig.recover(base_index=flat_index)
        srv.attach_ingest(ig)
        rng = np.random.default_rng(28)
        srv.start()
        try:
            g0 = ig.memtable.generation
            for j in range(6):                  # 4 -> regrow -> 8
                srv.write(np.array([9700 + j]), _rows(rng, 1))
            assert ig.memtable.capacity == 8
            assert ig.memtable.generation == g0 + 1
            v = np.full((1, DIM), 3.3, np.float32)
            srv.write(np.array([9750]), v)
            _, i = srv.search(v, k=5)
            assert int(np.asarray(i)[0, 0]) == 9750
        finally:
            srv.stop()
        ig.close()


# ---------------------------------------------------------------------------
# write-path tracing (PR 16): serving.ingest.* spans on the durable path


class TestIngestTracing:
    def test_write_mints_trace_with_spans(self, tmp_path):
        rng = np.random.default_rng(31)
        srv = _ingest(tmp_path)
        srv.recover()
        with obs.collecting(), trace.tracing_scope():
            srv.write(np.arange(4, dtype=np.int64), _rows(rng, 4))
        mine = [r for r in flight.traces()
                if r.name == "serving.ingest.request"]
        assert len(mine) == 1
        rt = mine[0]
        assert [s.name for s in rt.spans] == [
            "serving.ingest.append", "serving.ingest.apply",
            "serving.ingest.fsync"]
        assert all(s.duration >= 0.0 for s in rt.spans)
        assert rt.attrs["op"] == "upsert"
        assert rt.attrs["rows"] == 4
        assert rt.attrs["lsn"] == 1
        srv.close()

    def test_write_adopts_ambient_trace(self, tmp_path):
        rng = np.random.default_rng(32)
        srv = _ingest(tmp_path)
        srv.recover()
        rec = trace.SpanRecorder("serving.request")
        with obs.collecting(), trace.tracing_scope(), trace.activating(rec):
            srv.write(np.arange(4, dtype=np.int64), _rows(rng, 4))
        # adopted the caller's recorder: nothing minted into the ring
        assert flight.traces() == []
        assert "serving.ingest.fsync" in [s.name for s in rec.spans]
        assert rec.attrs["op"] == "upsert"
        srv.close()

    def test_write_without_tracing_records_nothing(self, tmp_path):
        rng = np.random.default_rng(33)
        srv = _ingest(tmp_path)
        srv.recover()
        with obs.collecting():
            srv.write(np.arange(4, dtype=np.int64), _rows(rng, 4))
        assert flight.traces() == []
        srv.close()

    def test_fold_trace_lands_with_stage_span(self, tmp_path, res,
                                              flat_index):
        rng = np.random.default_rng(34)
        srv = _ingest(tmp_path, res=res)
        srv.recover(base_index=flat_index)
        srv.write(np.arange(2000, 2008, dtype=np.int64), _rows(rng, 8))
        with obs.collecting(), trace.tracing_scope():
            assert srv.fold() is not None
        folds = [r for r in flight.traces()
                 if r.attrs.get("op") == "fold"]
        assert len(folds) == 1
        frt = folds[0]
        # the stage hook mirrors the fold timer onto the minted trace
        assert "serving.ingest.fold" in [s.name for s in frt.spans]
        assert frt.attrs["rows"] == 8
        assert "generation" in frt.attrs
        srv.close()


# ---------------------------------------------------------------------------
# round 19 satellites: the group-commit failure fence, the WAL-lag /
# visibility fold triggers, and replay racing live readers


class TestRound19Satellites:
    def test_fsync_failure_fails_whole_group_commit(self, tmp_path,
                                                    monkeypatch):
        """A failed group fsync fails the ack for EVERY rider of that
        group — the performer raises, and a waiter whose record was
        covered re-raises the same exception through the epoch fence —
        and the tail stays repairable: the records were appended, so
        the next good fsync (or a recover) makes them durable."""
        rng = np.random.default_rng(40)
        srv = _ingest(tmp_path, memtable_capacity=64)
        srv.recover()
        in_sync = threading.Event()
        release = threading.Event()
        calls = []
        orig = ingest.WriteAheadLog.sync

        def patched(wal):
            if not calls:
                calls.append(1)
                in_sync.set()
                assert release.wait(10.0)
                raise OSError("injected fsync failure")
            return orig(wal)

        monkeypatch.setattr(ingest.WriteAheadLog, "sync", patched)
        errs = {}

        def writer(name, i):
            try:
                srv.write(np.array([i]), _rows(rng, 1))
            except BaseException as e:  # noqa: BLE001
                errs[name] = e

        t1 = threading.Thread(target=writer, args=("performer", 9001))
        t1.start()
        assert in_sync.wait(10.0)        # performer is inside fsync
        t2 = threading.Thread(target=writer, args=("rider", 9002))
        t2.start()
        # the rider appends its record, then parks on the busy group
        deadline = 50
        while srv.stats()["last_lsn"] < 2 and deadline:
            threading.Event().wait(0.1)
            deadline -= 1
        threading.Event().wait(0.3)      # let the rider reach the fence
        release.set()
        t1.join(10.0)
        t2.join(10.0)
        assert isinstance(errs.get("performer"), OSError)
        assert isinstance(errs.get("rider"), OSError)
        assert errs["rider"] is errs["performer"]   # the fence re-raises
        # both records were appended; the NEXT write's good fsync (and
        # any recover) sees them — no acked state was lost, only acks
        assert srv.write(np.array([9003]), _rows(rng, 1)) == 3
        dig = srv.memtable.digest()
        srv.close()
        srv2 = _ingest(tmp_path, memtable_capacity=64)
        srv2.recover()
        assert srv2.memtable.digest() == dig
        assert srv2.stats()["last_lsn"] == 3
        srv2.close()

    def test_fold_trigger_replay_debt_rows(self, tmp_path, res,
                                           flat_index):
        rng = np.random.default_rng(41)
        srv = _ingest(tmp_path, res=res, fold_replay_debt_rows=3)
        srv.recover(base_index=flat_index)
        with obs.collecting():
            srv.write(np.array([8200, 8201]), _rows(rng, 2))
            assert srv.maybe_fold() is None          # debt 2 < 3
            assert srv.stats()["replay_debt_rows"] == 2
            srv.write(np.array([8202]), _rows(rng, 1))
            assert srv.maybe_fold() is not None      # debt 3 fires
            snap = obs.snapshot()["counters"]
            assert snap["serving.ingest.fold_trigger.rows"] == 1
            assert "serving.ingest.fold_trigger.lag" not in snap
        assert srv.stats()["replay_debt_rows"] == 0  # fold clears debt
        srv.close()

    def test_fold_trigger_visibility_lag(self, tmp_path, res,
                                         flat_index):
        rng = np.random.default_rng(42)
        t = [100.0]
        srv = ingest.IngestServer(
            res, ingest.IngestConfig(wal_dir=str(tmp_path / "wal"),
                                     memtable_capacity=32,
                                     tomb_capacity=32,
                                     fold_visibility_lag_s=5.0),
            dim=DIM, clock=lambda: t[0])
        srv.recover(base_index=flat_index)
        srv.write(np.array([8300]), _rows(rng, 1))
        with obs.collecting():
            assert srv.maybe_fold() is None          # age 0 < 5s
            t[0] += 10.0                             # oldest row ages out
            assert srv.maybe_fold() is not None
            snap = obs.snapshot()["counters"]
            assert snap["serving.ingest.fold_trigger.lag"] == 1
        # a fresh write restarts the visibility clock
        srv.write(np.array([8301]), _rows(rng, 1))
        assert srv.maybe_fold() is None
        srv.close()

    def test_recover_replay_races_concurrent_reads(self, tmp_path):
        """recover() replays under the append lock while a closed-loop
        reader hammers the memtable search path — no exception, no torn
        view, and the final state is the full bit-identical replay."""
        rng = np.random.default_rng(43)
        srv = _ingest(tmp_path, memtable_capacity=256)
        srv.recover()
        for j in range(40):
            srv.write(np.array([j]), _rows(rng, 1))
        dig = srv.memtable.digest()
        srv.close()
        srv2 = _ingest(tmp_path, memtable_capacity=256)
        stop = threading.Event()
        errs = []
        seen = []

        def reader():
            q = np.zeros((1, DIM), np.float32)
            while not stop.is_set():
                try:
                    _, i = srv2.memtable.search(q, 5)
                    seen.append(int((np.asarray(i) >= 0).sum()))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    return

        rt = threading.Thread(target=reader)
        rt.start()
        srv2.recover()
        stop.set()
        rt.join(10.0)
        assert not errs
        assert seen                                   # the loop really ran
        assert srv2.memtable.digest() == dig
        srv2.close()
